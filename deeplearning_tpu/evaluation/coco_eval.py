"""COCO-style detection mAP — self-contained (no pycocotools dependency).

Re-implements the COCOeval semantics the reference consumes
(detection/fasterRcnn/utils/coco_eval.py CocoEvaluator wrapping
pycocotools; YOLOX fast_coco_eval_api.py:19 COCOeval_opt): greedy
score-ordered matching per (image, category) at 10 IoU thresholds,
crowd/ignore handling, area ranges, maxDets, 101-point interpolated
precision, and the standard 12-metric summary. The greedy matching inner
loops dispatch to the native C++ module (native/cocoeval.cpp coco_match
via ctypes) when a compiler is available — the TPU-era analog of YOLOX's
`yolox._C` fast path — and fall back to numpy; the precision-envelope
accumulation is vectorized numpy either way.

Design note: unlike pycocotools there is no COCO-json object model here;
the evaluator consumes plain arrays (the detector's fixed-shape outputs
feed straight in after host gather), which is the natural TPU interface.
"""

from __future__ import annotations

import dataclasses
from collections import defaultdict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

IOU_THRS = np.linspace(0.5, 0.95, 10)
RECALL_THRS = np.linspace(0.0, 1.0, 101)
AREA_RANGES = {
    "all": (0.0, 1e10),
    "small": (0.0, 32.0 ** 2),
    "medium": (32.0 ** 2, 96.0 ** 2),
    "large": (96.0 ** 2, 1e10),
}
MAX_DETS = (1, 10, 100)


def box_iou_np(det: np.ndarray, gt: np.ndarray,
               iscrowd: Optional[np.ndarray] = None) -> np.ndarray:
    """(D, 4) × (G, 4) xyxy → (D, G); crowd gt uses IoA (COCO semantics)."""
    if len(det) == 0 or len(gt) == 0:
        return np.zeros((len(det), len(gt)))
    lt = np.maximum(det[:, None, :2], gt[None, :, :2])
    rb = np.minimum(det[:, None, 2:], gt[None, :, 2:])
    wh = np.clip(rb - lt, 0, None)
    inter = wh[..., 0] * wh[..., 1]
    area_d = np.prod(np.clip(det[:, 2:] - det[:, :2], 0, None), axis=1)
    area_g = np.prod(np.clip(gt[:, 2:] - gt[:, :2], 0, None), axis=1)
    union = area_d[:, None] + area_g[None, :] - inter
    if iscrowd is not None and iscrowd.any():
        union = np.where(iscrowd[None, :], area_d[:, None], union)
    return inter / np.maximum(union, 1e-9)


@dataclasses.dataclass
class _ImgEval:
    dt_scores: np.ndarray          # (D,)
    dt_matched: np.ndarray         # (T, D) matched gt id or -1
    dt_ignore: np.ndarray          # (T, D)
    gt_ignore: np.ndarray          # (G,)


class CocoEvaluator:
    """Streaming evaluator: add per-image ground truth + detections, then
    ``summarize()``."""

    def __init__(self, num_classes: int, use_cpp: bool = True):
        self.num_classes = num_classes
        self._gts: Dict[int, Dict] = {}
        self._dts: Dict[int, Dict] = {}
        self.use_cpp = use_cpp

    def add_image(self, image_id: int, *, gt_boxes: np.ndarray,
                  gt_labels: np.ndarray, det_boxes: np.ndarray,
                  det_scores: np.ndarray, det_labels: np.ndarray,
                  gt_crowd: Optional[np.ndarray] = None) -> None:
        """Boxes xyxy in image coords; arrays may be empty."""
        gt_boxes = np.asarray(gt_boxes, np.float64).reshape(-1, 4)
        if gt_crowd is None:
            gt_crowd = np.zeros(len(gt_boxes), bool)
        self._gts[image_id] = {
            "boxes": gt_boxes,
            "labels": np.asarray(gt_labels, np.int64).reshape(-1),
            "crowd": np.asarray(gt_crowd, bool).reshape(-1),
        }
        self._dts[image_id] = {
            "boxes": np.asarray(det_boxes, np.float64).reshape(-1, 4),
            "scores": np.asarray(det_scores, np.float64).reshape(-1),
            "labels": np.asarray(det_labels, np.int64).reshape(-1),
        }

    def add_batch(self, image_ids, det: Dict, gt: Dict,
                  image_valid=None) -> None:
        """Consume one eval step's *batched* padded outputs — the shape
        the jitted batched postprocess emits — with exactly one host
        conversion per array (each ``np.asarray`` below is the single
        D2H materialization for the whole batch; no per-image device
        slicing, no per-image retraces).

        det: {'boxes' (B,D,4), 'scores' (B,D), 'labels' (B,D),
        'valid' (B,D)}; gt: {'boxes' (B,G,4), 'labels' (B,G),
        'valid' (B,G), optional 'crowd' (B,G)}; ``image_valid`` (B,)
        masks wrap-around padding images. Padded detection slots are
        dropped by the valid mask AND by label < 0 (the
        ``gather_nms_outputs`` fill), so a padded slot can never alias a
        real class-0 / score-0 detection."""
        det_boxes = np.asarray(det["boxes"], np.float64)
        det_scores = np.asarray(det["scores"], np.float64)
        det_labels = np.asarray(det["labels"], np.int64)
        det_valid = np.asarray(det["valid"], bool) & (det_labels >= 0)
        gt_boxes = np.asarray(gt["boxes"], np.float64)
        gt_labels = np.asarray(gt["labels"], np.int64)
        gt_valid = np.asarray(gt["valid"], bool)
        gt_crowd = np.asarray(gt["crowd"], bool) if "crowd" in gt else None
        image_ids = np.asarray(image_ids, np.int64)
        if image_valid is not None:
            image_valid = np.asarray(image_valid, bool)
        for j, img_id in enumerate(image_ids):
            if image_valid is not None and not image_valid[j]:
                continue
            dv = det_valid[j]
            gv = gt_valid[j]
            self.add_image(
                int(img_id),
                gt_boxes=gt_boxes[j][gv],
                gt_labels=gt_labels[j][gv],
                det_boxes=det_boxes[j][dv],
                det_scores=det_scores[j][dv],
                det_labels=det_labels[j][dv],
                gt_crowd=gt_crowd[j][gv] if gt_crowd is not None else None)

    # ------------------------------------------------------------- match
    def _evaluate_img(self, img_id: int, cat: int,
                      area_rng: Tuple[float, float], max_det: int
                      ) -> Optional[_ImgEval]:
        gt = self._gts[img_id]
        dt = self._dts[img_id]
        g_sel = gt["labels"] == cat
        d_sel = dt["labels"] == cat
        g_boxes = gt["boxes"][g_sel]
        g_crowd = gt["crowd"][g_sel]
        d_order = np.argsort(-dt["scores"][d_sel], kind="mergesort")[:max_det]
        d_boxes = dt["boxes"][d_sel][d_order]
        d_scores = dt["scores"][d_sel][d_order]
        if len(g_boxes) == 0 and len(d_boxes) == 0:
            return None

        g_area = np.prod(np.clip(g_boxes[:, 2:] - g_boxes[:, :2], 0, None),
                         axis=1) if len(g_boxes) else np.zeros(0)
        g_ignore = g_crowd | (g_area < area_rng[0]) | (g_area > area_rng[1])
        # sort gt: non-ignored first (COCO matching preference)
        g_order = np.argsort(g_ignore, kind="mergesort")
        g_boxes = g_boxes[g_order]
        g_ignore_sorted = g_ignore[g_order]
        g_crowd_sorted = g_crowd[g_order]

        iou = box_iou_np(d_boxes, g_boxes, g_crowd_sorted)
        t_count = len(IOU_THRS)
        d_count = len(d_boxes)
        g_count = len(g_boxes)
        dt_matched = -np.ones((t_count, d_count), np.int64)
        gt_matched = -np.ones((t_count, g_count), np.int64)
        dt_ignore = np.zeros((t_count, d_count), bool)
        for ti, thr in enumerate(IOU_THRS):
            for di in range(d_count):
                best_iou = min(thr, 1 - 1e-10)
                best_g = -1
                for gi in range(g_count):
                    if gt_matched[ti, gi] >= 0 and not g_crowd_sorted[gi]:
                        continue
                    # prefer non-ignored gt; once we have a real match,
                    # don't switch to an ignored one
                    if best_g >= 0 and not g_ignore_sorted[best_g] \
                            and g_ignore_sorted[gi]:
                        break
                    if iou[di, gi] < best_iou:
                        continue
                    best_iou = iou[di, gi]
                    best_g = gi
                if best_g >= 0:
                    dt_matched[ti, di] = best_g
                    gt_matched[ti, best_g] = di
                    dt_ignore[ti, di] = g_ignore_sorted[best_g]
        # unmatched dets outside area range are ignored
        d_area = np.prod(np.clip(d_boxes[:, 2:] - d_boxes[:, :2], 0, None),
                         axis=1)
        out_of_range = (d_area < area_rng[0]) | (d_area > area_rng[1])
        dt_ignore |= (dt_matched == -1) & out_of_range[None, :]
        return _ImgEval(d_scores, dt_matched, dt_ignore, g_ignore_sorted)

    # ------------------------------------------------- C++ fast matching
    def _evaluate_cpp(self, cat: int, area_rng: Tuple[float, float],
                      max_det: int) -> List[_ImgEval]:
        """Packed all-image matching via native/cocoeval.cpp coco_match —
        identical results to _evaluate_img, C++ inner loops."""
        import ctypes

        from ..native.build import load
        lib = load("cocoeval")
        if lib is None:
            return None
        d_boxes_l, d_scores_l, g_boxes_l = [], [], []
        g_crowd_l, g_ignore_l = [], []
        d_off, g_off = [0], [0]
        per_img_meta = []
        for img_id in self._gts:
            gt, dt = self._gts[img_id], self._dts[img_id]
            g_sel = gt["labels"] == cat
            d_sel = dt["labels"] == cat
            g_boxes = gt["boxes"][g_sel]
            g_crowd = gt["crowd"][g_sel]
            order = np.argsort(-dt["scores"][d_sel],
                               kind="mergesort")[:max_det]
            d_boxes = dt["boxes"][d_sel][order]
            d_scores = dt["scores"][d_sel][order]
            if len(g_boxes) == 0 and len(d_boxes) == 0:
                per_img_meta.append(None)
                continue
            g_area = np.prod(np.clip(g_boxes[:, 2:] - g_boxes[:, :2], 0,
                                     None), axis=1) if len(g_boxes) else \
                np.zeros(0)
            g_ignore = g_crowd | (g_area < area_rng[0]) | \
                (g_area > area_rng[1])
            g_order = np.argsort(g_ignore, kind="mergesort")
            d_boxes_l.append(d_boxes)
            d_scores_l.append(d_scores)
            g_boxes_l.append(g_boxes[g_order])
            g_crowd_l.append(g_crowd[g_order])
            g_ignore_l.append(g_ignore[g_order])
            d_off.append(d_off[-1] + len(d_boxes))
            g_off.append(g_off[-1] + len(g_boxes))
            per_img_meta.append((len(d_boxes), len(g_boxes)))

        n_img = len(d_off) - 1
        total_d = d_off[-1]
        t_n = len(IOU_THRS)
        cat_ = np.concatenate
        db = cat_(d_boxes_l).astype(np.float64) if d_boxes_l else \
            np.zeros((0, 4))
        gb = cat_(g_boxes_l).astype(np.float64) if g_boxes_l else \
            np.zeros((0, 4))
        gc = cat_(g_crowd_l).astype(np.uint8) if g_crowd_l else \
            np.zeros(0, np.uint8)
        gi = cat_(g_ignore_l).astype(np.uint8) if g_ignore_l else \
            np.zeros(0, np.uint8)
        dt_matched = np.empty((t_n, total_d), np.int64)
        dt_ignore = np.empty((t_n, total_d), np.uint8)
        if n_img:
            c = lambda a, t: a.ctypes.data_as(ctypes.POINTER(t))
            d_off_a = np.asarray(d_off, np.int64)
            g_off_a = np.asarray(g_off, np.int64)
            thrs = np.ascontiguousarray(IOU_THRS, np.float64)
            lib.coco_match(
                ctypes.c_int(n_img), c(d_off_a, ctypes.c_int64),
                c(g_off_a, ctypes.c_int64), c(np.ascontiguousarray(db),
                                              ctypes.c_double),
                c(np.ascontiguousarray(gb), ctypes.c_double),
                c(gc, ctypes.c_uint8), c(gi, ctypes.c_uint8),
                c(thrs, ctypes.c_double), ctypes.c_int(t_n),
                ctypes.c_double(area_rng[0]), ctypes.c_double(area_rng[1]),
                ctypes.c_int64(total_d), c(dt_matched, ctypes.c_int64),
                c(dt_ignore, ctypes.c_uint8))
        evals = []
        k = 0
        for meta in per_img_meta:
            if meta is None:
                continue
            dn, gn = meta
            d0, d1 = d_off[k], d_off[k + 1]
            g0, g1 = g_off[k], g_off[k + 1]
            evals.append(_ImgEval(
                d_scores_l[k], dt_matched[:, d0:d1],
                dt_ignore[:, d0:d1].astype(bool), g_ignore_l[k]))
            k += 1
        return evals

    # -------------------------------------------------------- accumulate
    def accumulate(self) -> Dict[str, np.ndarray]:
        cats = range(self.num_classes)
        t_n = len(IOU_THRS)
        precision = -np.ones((t_n, len(RECALL_THRS), self.num_classes,
                              len(AREA_RANGES), len(MAX_DETS)))
        recall = -np.ones((t_n, self.num_classes, len(AREA_RANGES),
                           len(MAX_DETS)))
        for ki, cat in enumerate(cats):
            for ai, (aname, arng) in enumerate(AREA_RANGES.items()):
                # match ONCE at the largest maxDet; smaller maxDets are
                # score-ordered prefixes of the same greedy matching
                # (pycocotools does the same slicing)
                full = (self._evaluate_cpp(cat, arng, max(MAX_DETS))
                        if self.use_cpp else None)
                if full is None:
                    full = [self._evaluate_img(i, cat, arng, max(MAX_DETS))
                            for i in self._gts]
                    full = [e for e in full if e is not None]
                for mi, max_det in enumerate(MAX_DETS):
                    evals = [
                        _ImgEval(e.dt_scores[:max_det],
                                 e.dt_matched[:, :max_det],
                                 e.dt_ignore[:, :max_det], e.gt_ignore)
                        for e in full]
                    if not evals:
                        continue
                    scores = np.concatenate([e.dt_scores for e in evals])
                    order = np.argsort(-scores, kind="mergesort")
                    matched = np.concatenate(
                        [e.dt_matched for e in evals], axis=1)[:, order]
                    ignored = np.concatenate(
                        [e.dt_ignore for e in evals], axis=1)[:, order]
                    num_gt = sum(int((~e.gt_ignore).sum()) for e in evals)
                    if num_gt == 0:
                        continue
                    tp = (matched >= 0) & ~ignored
                    fp = (matched < 0) & ~ignored
                    tp_cum = np.cumsum(tp, axis=1).astype(np.float64)
                    fp_cum = np.cumsum(fp, axis=1).astype(np.float64)
                    for ti in range(t_n):
                        rc = tp_cum[ti] / num_gt
                        pr = tp_cum[ti] / np.maximum(
                            tp_cum[ti] + fp_cum[ti], 1e-9)
                        recall[ti, ki, ai, mi] = rc[-1] if len(rc) else 0
                        from .metrics import interp_precision_at_recall
                        precision[ti, :, ki, ai, mi] = \
                            interp_precision_at_recall(pr, rc, RECALL_THRS)
        return {"precision": precision, "recall": recall}

    # --------------------------------------------------------- summarize
    def summarize(self, acc: Optional[Dict] = None) -> Dict[str, float]:
        acc = acc or self.accumulate()
        p, r = acc["precision"], acc["recall"]

        def ap(iou_thr=None, area="all", max_det=100):
            ai = list(AREA_RANGES).index(area)
            mi = MAX_DETS.index(max_det)
            s = p[:, :, :, ai, mi]
            if iou_thr is not None:
                s = s[[np.argmin(np.abs(IOU_THRS - iou_thr))]]
            s = s[s > -1]
            return float(np.mean(s)) if s.size else -1.0

        def ar(area="all", max_det=100):
            ai = list(AREA_RANGES).index(area)
            mi = MAX_DETS.index(max_det)
            s = r[:, :, ai, mi]
            s = s[s > -1]
            return float(np.mean(s)) if s.size else -1.0

        return {
            "AP": ap(), "AP50": ap(0.5), "AP75": ap(0.75),
            "AP_small": ap(area="small"), "AP_medium": ap(area="medium"),
            "AP_large": ap(area="large"),
            "AR1": ar(max_det=1), "AR10": ar(max_det=10),
            "AR100": ar(max_det=100),
            "AR_small": ar(area="small"), "AR_medium": ar(area="medium"),
            "AR_large": ar(area="large"),
        }
