from . import coco_eval, metrics, voc  # noqa: F401
