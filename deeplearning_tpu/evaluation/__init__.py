from . import coco_eval, keypoints, metrics, retrieval, voc  # noqa: F401
