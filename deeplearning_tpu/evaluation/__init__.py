from . import coco_eval, distributed, keypoints, metrics, retrieval, voc  # noqa: F401
