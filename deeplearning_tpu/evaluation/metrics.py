"""Evaluation metrics: top-k accuracy, confusion matrix / mIoU, dice.

Rebuilds the reference's metric helpers as device-side, jit-able reducers:
top-k accuracy (swin utils/torch_utils.py:325), ConfusionMatrix with mIoU +
cross-process reduction (Image_segmentation/FCN/utils/distributed_utils.py:
73-104), dice coefficient (U-Net loss/dice_score.py). Cross-replica
reduction is free under GSPMD: metrics are SUMS over the global batch, so
jit over the sharded batch already yields globally-reduced counts (the
reduce_from_all_processes analog).
"""

from __future__ import annotations

from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def topk_correct(logits: jax.Array, labels: jax.Array,
                 ks: Sequence[int] = (1, 5)) -> Dict[str, jax.Array]:
    """Counts (not rates) of top-k correct predictions; divide by the
    number of examples host-side."""
    out = {}
    maxk = min(max(ks), logits.shape[-1])
    _, pred = jax.lax.top_k(logits, maxk)
    correct = pred == labels[:, None]
    for k in ks:
        k_eff = min(k, maxk)
        out[f"top{k}"] = jnp.sum(jnp.any(correct[:, :k_eff], axis=-1))
    out["count"] = jnp.asarray(labels.shape[0], jnp.int32)
    return out


def confusion_matrix(preds: jax.Array, labels: jax.Array,
                     num_classes: int) -> jax.Array:
    """(C, C) count matrix, rows=truth, cols=pred; labels<0 or >=C ignored
    (FCN ConfusionMatrix.update surface)."""
    valid = (labels >= 0) & (labels < num_classes)
    idx = labels.astype(jnp.int32) * num_classes + preds.astype(jnp.int32)
    idx = jnp.where(valid.reshape(idx.shape), idx, num_classes * num_classes)
    counts = jnp.bincount(idx.reshape(-1),
                          length=num_classes * num_classes + 1)
    return counts[:-1].reshape(num_classes, num_classes)


def miou_from_confusion(mat: np.ndarray) -> Dict[str, np.ndarray]:
    """Global accuracy, per-class accuracy and IoU, mean IoU
    (FCN distributed_utils.py:85-103 compute surface)."""
    mat = np.asarray(mat, np.float64)
    diag = np.diag(mat)
    global_acc = diag.sum() / np.maximum(mat.sum(), 1)
    class_acc = diag / np.maximum(mat.sum(1), 1)
    union = mat.sum(1) + mat.sum(0) - diag
    iou = diag / np.maximum(union, 1)
    return {"global_acc": global_acc, "class_acc": class_acc,
            "iou": iou, "miou": iou.mean()}


def dice_counts(probs: jax.Array, onehot: jax.Array
                ) -> Tuple[jax.Array, jax.Array]:
    """Numerator/denominator sums for a dataset-level dice score."""
    inter = jnp.sum(probs * onehot)
    denom = jnp.sum(probs) + jnp.sum(onehot)
    return 2 * inter, denom


def interp_precision_at_recall(precision: np.ndarray, recall: np.ndarray,
                               rec_points: np.ndarray) -> np.ndarray:
    """COCO-convention interpolated precision: envelope (monotone
    non-increasing right-to-left) then left-searchsorted sampling at
    ``rec_points``. Single source of truth shared by coco_eval.py
    accumulate() and precision_recall_curve()."""
    pr = np.asarray(precision, np.float64)
    envelope = np.maximum.accumulate(pr[::-1])[::-1]
    idx = np.searchsorted(recall, rec_points, side="left")
    out = np.zeros(len(rec_points))
    valid = idx < len(envelope)
    out[valid] = envelope[idx[valid]]
    return out


def precision_recall_curve(scores: np.ndarray, is_tp: np.ndarray,
                           n_gt: int) -> Dict[str, np.ndarray]:
    """Single-class PR curve + AP from scored detections (yolov5
    utils/metrics.py ap_per_class surface, host-side).

    scores: (N,) detection confidences; is_tp: (N,) bool, whether each
    detection matched an unmatched gt at the working IoU; n_gt: number of
    ground-truth instances. Returns precision/recall arrays sorted by
    descending confidence plus 101-point-interpolated AP (the COCO
    convention, same interpolation as evaluation/coco_eval.py)."""
    order = np.argsort(-np.asarray(scores, np.float64))
    tp = np.asarray(is_tp, np.float64)[order]
    fp = 1.0 - tp
    tp_cum, fp_cum = np.cumsum(tp), np.cumsum(fp)
    recall = tp_cum / max(n_gt, 1)
    precision = tp_cum / np.maximum(tp_cum + fp_cum, 1e-12)
    rec_points = np.linspace(0.0, 1.0, 101)
    ap = float(np.mean(interp_precision_at_recall(
        precision, recall, rec_points))) if len(tp) else 0.0
    return {"precision": precision, "recall": recall,
            "scores": np.asarray(scores, np.float64)[order], "ap": ap}
