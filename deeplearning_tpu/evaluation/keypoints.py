"""Keypoint evaluation: heatmap decoding + OKS-based AP.

Surface of pose_estimation/Insulator utils/kp_eval.py + utils/coco_eval.py
(OKS keypoint metric): decode argmax+offset keypoints from heatmaps, score
predictions against gt with object keypoint similarity, PCK and OKS-AP
summaries.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# COCO person sigmas; custom datasets pass their own
COCO_SIGMAS = np.asarray([
    .026, .025, .025, .035, .035, .079, .079, .072, .072, .062, .062,
    .107, .107, .087, .087, .089, .089])


def decode_heatmaps(heatmaps: jax.Array, stride: int = 4
                    ) -> Tuple[jax.Array, jax.Array]:
    """(B, H, W, K) → keypoints (B, K, 2) xy in input coords + scores
    (B, K). Quarter-pixel offset toward the second-highest neighbor
    (standard HRNet decoding)."""
    b, h, w, k = heatmaps.shape
    flat = heatmaps.reshape(b, h * w, k)
    idx = jnp.argmax(flat, axis=1)                     # (B, K)
    scores = jnp.max(flat, axis=1)
    ys = (idx // w).astype(jnp.float32)
    xs = (idx % w).astype(jnp.float32)

    def neighbor(dy, dx):
        yy = jnp.clip(ys + dy, 0, h - 1).astype(jnp.int32)
        xx = jnp.clip(xs + dx, 0, w - 1).astype(jnp.int32)
        flat_idx = yy * w + xx
        return jnp.take_along_axis(flat, flat_idx[:, None, :],
                                   axis=1)[:, 0, :]
    right = neighbor(0, 1)
    left = neighbor(0, -1)
    down = neighbor(1, 0)
    up = neighbor(-1, 0)
    # quarter-pixel refinement only for strictly interior peaks (standard
    # HRNet decoding) — at borders a clipped neighbor would bias the shift
    x_interior = (xs > 0) & (xs < w - 1)
    y_interior = (ys > 0) & (ys < h - 1)
    xs = xs + jnp.where(x_interior, 0.25 * jnp.sign(right - left), 0.0)
    ys = ys + jnp.where(y_interior, 0.25 * jnp.sign(down - up), 0.0)
    kp = jnp.stack([xs, ys], axis=-1) * stride
    return kp, scores


def oks(pred: np.ndarray, gt: np.ndarray, visible: np.ndarray,
        area: float, sigmas: Optional[np.ndarray] = None) -> float:
    """Object keypoint similarity between one predicted and one gt pose.
    pred/gt (K, 2); visible (K,) >0 counts."""
    k = len(gt)
    sigmas = COCO_SIGMAS[:k] if sigmas is None else np.asarray(sigmas)[:k]
    vars_ = (2 * sigmas) ** 2
    v = visible > 0
    if not v.any():
        return 0.0
    d2 = np.sum((np.asarray(pred) - np.asarray(gt)) ** 2, axis=1)
    e = d2 / (vars_ * 2 * max(area, 1e-9))
    return float(np.mean(np.exp(-e[v])))


def pck(pred: np.ndarray, gt: np.ndarray, visible: np.ndarray,
        threshold_px: float) -> float:
    """Percentage of correct keypoints within a pixel threshold."""
    v = visible > 0
    if not v.any():
        return 0.0
    d = np.linalg.norm(np.asarray(pred) - np.asarray(gt), axis=1)
    return float(np.mean(d[v] <= threshold_px))


def oks_ap(predictions: Sequence[Dict], groundtruths: Sequence[Dict],
           thresholds: np.ndarray = np.linspace(0.5, 0.95, 10)
           ) -> Dict[str, float]:
    """Single-pose-per-image OKS AP (the Insulator dataset setting):
    predictions [{keypoints (K,2), score}], groundtruths
    [{keypoints (K,2), visible (K,), area}]."""
    oks_vals = np.asarray([
        oks(p["keypoints"], g["keypoints"], g["visible"], g["area"])
        for p, g in zip(predictions, groundtruths)])
    scores = np.asarray([p.get("score", 1.0) for p in predictions])
    order = np.argsort(-scores)
    oks_sorted = oks_vals[order]
    out = {}
    aps = []
    for t in thresholds:
        tp = np.cumsum(oks_sorted >= t)
        fp = np.cumsum(oks_sorted < t)
        recall = tp / max(len(groundtruths), 1)
        precision = tp / np.maximum(tp + fp, 1e-9)
        for i in range(len(precision) - 1, 0, -1):
            precision[i - 1] = max(precision[i - 1], precision[i])
        ap = 0.0
        for r in np.linspace(0, 1, 101):
            idx = np.searchsorted(recall, r, side="left")
            ap += (precision[idx] if idx < len(precision) else 0.0) / 101
        aps.append(ap)
    out["AP"] = float(np.mean(aps))
    out["AP50"] = float(aps[0])
    out["AP75"] = float(aps[5])
    out["mean_oks"] = float(np.mean(oks_vals)) if len(oks_vals) else 0.0
    return out


def make_heatmap_targets(keypoints: np.ndarray, visible: np.ndarray,
                         out_hw: Tuple[int, int], stride: int = 4,
                         sigma: float = 2.0) -> np.ndarray:
    """Gaussian heatmap targets (Insulator coco_transforms heatmap gen).
    keypoints (K, 2) in input coords → (H, W, K)."""
    h, w = out_hw
    k = len(keypoints)
    yy, xx = np.mgrid[0:h, 0:w]
    heat = np.zeros((h, w, k), np.float32)
    for i, ((x, y), v) in enumerate(zip(keypoints, visible)):
        if v <= 0:
            continue
        cx, cy = x / stride, y / stride
        heat[:, :, i] = np.exp(-((xx - cx) ** 2 + (yy - cy) ** 2)
                               / (2 * sigma ** 2))
    return heat
