"""PASCAL VOC detection AP (07 11-point and all-points metrics).

Surface of detection/YOLOX/yolox/evaluators/voc_eval.py (the classic
voc_eval port) used by the VOC-trained detectors (RetinaNet/fasterRcnn
train on VOC in the reference). Array-based: no XML parsing — converters
in data/label_convert.py produce the arrays.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

from .coco_eval import box_iou_np


def voc_ap(recall: np.ndarray, precision: np.ndarray,
           use_07_metric: bool = False) -> float:
    if use_07_metric:
        ap = 0.0
        for t in np.arange(0.0, 1.1, 0.1):
            p = np.max(precision[recall >= t]) if (recall >= t).any() else 0.0
            ap += p / 11.0
        return float(ap)
    mrec = np.concatenate([[0.0], recall, [1.0]])
    mpre = np.concatenate([[0.0], precision, [0.0]])
    for i in range(len(mpre) - 1, 0, -1):
        mpre[i - 1] = max(mpre[i - 1], mpre[i])
    idx = np.where(mrec[1:] != mrec[:-1])[0]
    return float(np.sum((mrec[idx + 1] - mrec[idx]) * mpre[idx + 1]))


def voc_eval_class(gt_per_image: Dict[int, Dict], detections: np.ndarray,
                   iou_thresh: float = 0.5,
                   use_07_metric: bool = False) -> Dict[str, float]:
    """One class. gt_per_image: {img_id: {'boxes': (G,4),
    'difficult': (G,) bool}}. detections: (D, 6) rows
    [img_id, score, x1, y1, x2, y2]."""
    npos = sum(int((~g["difficult"]).sum()) for g in gt_per_image.values())
    matched = {i: np.zeros(len(g["boxes"]), bool)
               for i, g in gt_per_image.items()}
    if len(detections) == 0:
        return {"ap": 0.0, "precision": np.zeros(0), "recall": np.zeros(0)}
    order = np.argsort(-detections[:, 1], kind="mergesort")
    detections = detections[order]
    tp = np.zeros(len(detections))
    fp = np.zeros(len(detections))
    for di, row in enumerate(detections):
        img_id = int(row[0])
        box = row[2:6]
        gt = gt_per_image.get(img_id)
        if gt is None or len(gt["boxes"]) == 0:
            fp[di] = 1
            continue
        iou = box_iou_np(box[None], gt["boxes"])[0]
        best = int(np.argmax(iou))
        if iou[best] >= iou_thresh:
            if gt["difficult"][best]:
                continue                      # neither tp nor fp
            if not matched[img_id][best]:
                matched[img_id][best] = True
                tp[di] = 1
            else:
                fp[di] = 1
        else:
            fp[di] = 1
    tp_cum = np.cumsum(tp)
    fp_cum = np.cumsum(fp)
    recall = tp_cum / max(npos, 1)
    precision = tp_cum / np.maximum(tp_cum + fp_cum, 1e-9)
    return {"ap": voc_ap(recall, precision, use_07_metric),
            "precision": precision, "recall": recall}


def voc_map(gt: Dict[int, Dict[int, Dict]], dets: Dict[int, np.ndarray],
            num_classes: int, iou_thresh: float = 0.5,
            use_07_metric: bool = False) -> Dict[str, float]:
    """gt: {class: {img: {'boxes','difficult'}}}; dets: {class: (D,6)}."""
    aps = []
    per_class = {}
    for c in range(num_classes):
        res = voc_eval_class(gt.get(c, {}),
                             dets.get(c, np.zeros((0, 6))),
                             iou_thresh, use_07_metric)
        per_class[c] = res["ap"]
        aps.append(res["ap"])
    return {"mAP": float(np.mean(aps)) if aps else 0.0, "per_class": per_class}
