"""Distributed detection evaluation: per-host shards → global metrics.

The YOLOX pattern (detection/YOLOX/yolox/evaluators/coco_evaluator.py:
each rank runs inference on its DistributedSampler shard, the per-image
detection lists are all_gather'd as pickled objects over a gloo CPU
group (yolox/utils/dist.py:186,128), and rank 0 runs COCOeval) mapped
to TPU multi-host: detections come out of the jitted postprocess as
FIXED-SHAPE padded arrays (boxes/scores/labels + valid mask), so the
object-pickle gather becomes a plain array gather —
``parallel.collectives.host_allgather`` (jax.experimental
multihost_utils.process_allgather) — and every host can then fill the
evaluator identically (no rank-0 special case needed; summarize is
deterministic).

Shard protocol: every process evaluates an equal-length slice of the
image list (pad the last slice and mark padding with image_valid=False
— the analog of DistributedSampler's wrap-around padding, deduplicated
here by dropping invalid rows).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import numpy as np

from ..parallel.collectives import host_allgather
from .coco_eval import CocoEvaluator


def pack_shard(image_ids, det: Dict, gt: Dict,
               image_valid: Optional[np.ndarray] = None) -> Dict:
    """Bundle one process's padded per-image arrays for the gather.

    det: {'boxes' (B,D,4), 'scores' (B,D), 'labels' (B,D), 'valid' (B,D)}
    gt:  {'boxes' (B,G,4), 'labels' (B,G), 'valid' (B,G)}
    image_valid: (B,) False for wrap-around padding images.
    """
    b = len(image_ids)
    if image_valid is None:
        image_valid = np.ones((b,), bool)
    return {
        "image_ids": np.asarray(image_ids, np.int64),
        "image_valid": np.asarray(image_valid, bool),
        "det_boxes": np.asarray(det["boxes"], np.float32),
        "det_scores": np.asarray(det["scores"], np.float32),
        "det_labels": np.asarray(det["labels"], np.int64),
        "det_valid": np.asarray(det["valid"], bool),
        "gt_boxes": np.asarray(gt["boxes"], np.float32),
        "gt_labels": np.asarray(gt["labels"], np.int64),
        "gt_valid": np.asarray(gt["valid"], bool),
    }


def gather_and_evaluate(shard: Dict, num_classes: int,
                        allgather: Callable = host_allgather,
                        use_cpp: bool = True) -> Dict[str, float]:
    """All-gather every process's shard and run the COCO metrics over
    the union. Returns the 12-metric summary dict; identical on every
    host. ``allgather`` is injectable so the multi-process path is
    testable single-process (tests stack shards to fake a world)."""
    gathered = {k: np.asarray(v) for k, v in allgather(shard).items()}
    ev = CocoEvaluator(num_classes=num_classes, use_cpp=use_cpp)
    seen = set()
    n_proc = gathered["image_ids"].shape[0]
    for p in range(n_proc):
        ids = gathered["image_ids"][p]
        # wrap-around duplicate safety folded into the image mask, then
        # one batched fill per process row (arrays are already on host
        # post-gather; add_batch keeps the per-image work to cheap slices)
        valid = gathered["image_valid"][p].copy()
        for i in range(ids.shape[0]):
            if not valid[i]:
                continue
            img_id = int(ids[i])
            if img_id in seen:
                valid[i] = False
            else:
                seen.add(img_id)
        ev.add_batch(
            ids,
            det={"boxes": gathered["det_boxes"][p],
                 "scores": gathered["det_scores"][p],
                 "labels": gathered["det_labels"][p],
                 "valid": gathered["det_valid"][p]},
            gt={"boxes": gathered["gt_boxes"][p],
                "labels": gathered["gt_labels"][p],
                "valid": gathered["gt_valid"][p]},
            image_valid=valid)
    return ev.summarize()
