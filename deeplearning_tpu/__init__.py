"""deeplearning_tpu — a TPU-native deep-learning framework.

A ground-up JAX/XLA/Pallas rebuild of the capabilities of the
KKKSQJ/DeepLearning paper-reimplementation zoo (reference mounted at
/root/reference). Where the reference copy-pastes per-project CUDA/DDP
harnesses, this package provides ONE shared TPU-first core:

- ``core``      config tree (dataclass + YAML + CLI), registry, logging,
                Orbax checkpointing, RNG, precision policy.
- ``parallel``  device mesh construction, GSPMD shardings, collectives,
                ring attention (sequence parallelism).
- ``ops``       Pallas kernels + XLA-friendly fixed-shape ops (window
                attention, NMS, RoIAlign, focal loss, box coders).
- ``models``    the model zoo (classification / detection / segmentation /
                self-supervised / metric learning / pose / stereo).
- ``data``      input pipelines (per-host sharded loading, mixup/mosaic).
- ``train``     TrainState, hook-based Trainer, optimizers, LR schedules.
- ``evaluation``  metrics: top-k, confusion-matrix mIoU, dice, COCO/VOC
                mAP (with a native C++ fast path), CMC/mAP retrieval.
- ``export``    StableHLO / TF SavedModel export paths.
- ``analysis``  dltpu-check: AST policy linter with a ratchet baseline,
                jaxpr structural auditor, runtime strict mode.
"""

__version__ = "0.1.0"

# Importing the subpackages populates the registries (models, optimizers,
# schedules, ...), so `deeplearning_tpu.core.MODELS.build(name)` works after
# a bare `import deeplearning_tpu`.
from . import core, ops, parallel, data, train, models, evaluation  # noqa: E402,F401
from . import analysis  # noqa: E402,F401  (lint is stdlib-only; jaxpr/strict lazy)
