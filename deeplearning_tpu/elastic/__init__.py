"""Elastic runs: survive preemption, resume anywhere, restart yourself.

Production TPU time is preemptible, and five straight bench rounds
(BENCH_r01–r05) died to wedged device tunnels — a long run that cannot
be killed and resumed is a run that eventually loses everything. This
package is the machinery that makes any Trainer run survivable:

- ``signals``    — chained signal subscriptions (flight recorder AND
  preemption guard share SIGTERM; neither clobbers the other).
- ``preempt``    — SIGTERM/SIGINT → flush in-flight checkpoint + flight
  ring → :class:`Preempted` at the next step boundary → exit
  :data:`EXIT_PREEMPTED` (75), the supervisor's requeue signal.
- ``heartbeat``  — step/activity watermark file the Trainer feeds and
  the supervisor reads.
- ``faults``     — ``DLTPU_FAULTS`` injection (sigterm / crash / wedge)
  so the whole loop is CPU-testable in tier-1.
- ``supervisor`` — launch, watch, classify slow-vs-wedged, kill,
  requeue with bounded exponential backoff.
- ``topology`` / ``resume`` — checkpoint topology sidecars and
  restore-onto-a-different-mesh (import these two explicitly:
  ``from deeplearning_tpu.elastic import resume`` — they import jax,
  the rest of the package stays importable without touching a backend).

README "Elastic run policy" documents the exit-code and backoff
contract; ``tools/supervise.py`` is the CLI.
"""

from . import faults, heartbeat, preempt, signals, supervisor
from .preempt import EXIT_PREEMPTED, Preempted, PreemptionGuard
from .supervisor import Supervisor, SupervisorConfig, WedgeDetector

__all__ = ["signals", "preempt", "heartbeat", "faults", "supervisor",
           "EXIT_PREEMPTED", "Preempted", "PreemptionGuard",
           "Supervisor", "SupervisorConfig", "WedgeDetector"]
