"""Preemption-safe shutdown: SIGTERM/SIGINT → flush → checkpoint → 75.

Production TPU time is preemptible: the scheduler sends SIGTERM and the
process has seconds to land its state. The guard turns that signal into
a three-phase graceful exit:

1. **In the handler** (async-signal time, main thread): record a flight
   event, run the registered ``flush`` callbacks — the checkpoint
   manager's ``flush()`` barrier lands any in-flight async write, the
   flight recorder dumps its ring — and set a flag. Nothing here starts
   new device work.
2. **At the next step boundary** the Trainer sees the flag and raises
   :class:`Preempted`, then saves a fresh checkpoint at the exact
   interrupted step and flushes it.
3. **The entrypoint** converts :class:`Preempted` into
   :data:`EXIT_PREEMPTED` (75, sysexits' EX_TEMPFAIL) so the supervisor
   requeues the run instead of counting a crash.

Signals subscribe through :mod:`.signals`, so the guard coexists with
the flight recorder's own SIGTERM hook — neither replaces the other.
"""

from __future__ import annotations

import signal
import threading
from typing import Callable, Iterable, List, Optional

from . import signals

__all__ = ["EXIT_PREEMPTED", "Preempted", "PreemptionGuard",
           "agree_preempt_step"]

# sysexits EX_TEMPFAIL: "transient failure, retry" — the supervisor's
# contract for "requeue me, this was a preemption, not a bug".
EXIT_PREEMPTED = 75


class Preempted(Exception):
    """Raised at a step boundary after a preemption signal. By the time
    the Trainer re-raises this, the final checkpoint is saved+flushed."""

    def __init__(self, message: str, *, signum: Optional[int] = None,
                 step: Optional[int] = None):
        super().__init__(message)
        self.signum = signum
        self.step = step


class PreemptionGuard:
    """Graceful-shutdown flag fed by chained SIGTERM/SIGINT handlers.

    ``install()`` subscribes (graceful — the process does NOT die in the
    handler); the hot loop polls ``requested()`` (one ``Event.is_set``)
    and raises :class:`Preempted` at the next boundary. ``add_flush``
    callbacks run inside the handler itself so an in-flight async
    checkpoint write commits even if the loop never reaches another
    boundary (e.g. preempted mid-eval)."""

    def __init__(self, signums: Iterable[int] = (signal.SIGTERM,
                                                 signal.SIGINT)):
        self.signums = tuple(signums)
        self.signum: Optional[int] = None
        self._event = threading.Event()
        self._flush: List[Callable[[], None]] = []
        self._installed: List[int] = []

    def add_flush(self, fn: Callable[[], None]) -> "PreemptionGuard":
        self._flush.append(fn)
        return self

    def install(self) -> bool:
        """Subscribe on every signal; True if at least one took (False
        off the main thread — callers just lose signal-driven preemption,
        ``request()`` still works)."""
        for signum in self.signums:
            if signals.subscribe(signum, self._on_signal, graceful=True):
                self._installed.append(signum)
        return bool(self._installed)

    def uninstall(self) -> None:
        for signum in self._installed:
            signals.unsubscribe(signum, self._on_signal)
        self._installed = []

    def _on_signal(self, signum: int, frame) -> None:
        if self._event.is_set():
            return                       # double-delivery: already landing
        self.signum = signum
        from ..obs import flight       # lazy: keep this module jax-free
        flight.record("preempt_signal", signum=int(signum))
        for fn in self._flush:
            try:
                fn()
            except Exception:  # noqa: BLE001 - a failed flush must not
                pass           # stop the remaining landing steps
        self._event.set()

    def request(self) -> None:
        """Programmatic preemption (tests, managed-runtime callbacks)."""
        self._event.set()

    def requested(self) -> bool:
        return self._event.is_set()


def agree_preempt_step(step: int) -> int:
    """Multi-host preemption agreement: process 0 broadcasts ITS step so
    every host lands the same checkpoint step (a pod-wide SIGTERM
    reaches hosts at slightly different step boundaries — without
    agreement each host would save a different step and the restore
    would mix them). One tiny all-reduce; a no-op on single-host, and a
    best-effort identity if the collective itself fails (a dying pod
    should still land SOME checkpoint)."""
    import jax                       # lazy: keep this module jax-free
    if jax.process_count() == 1:
        return int(step)
    try:
        import numpy as np
        from jax.experimental import multihost_utils
        agreed = multihost_utils.broadcast_one_to_all(
            np.asarray(int(step), np.int64))
        return int(agreed)
    except Exception:  # noqa: BLE001 - never block the landing on it
        return int(step)
