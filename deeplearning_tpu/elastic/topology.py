"""Topology fingerprints: what hardware a checkpoint was written on.

A checkpoint that will be resumed on *whatever capacity the scheduler
gives back* must record what it was sharded over, so the resume path can
(a) decide whether this is a same-topology fast path or a cross-topology
reshard, and (b) leave an auditable flight event saying which. The
fingerprint is a small JSON dict — mesh axis sizes, device/process
counts, platform, and the shard-layout summary of the saved state — that
``CheckpointManager.save(..., topology=...)`` drops next to each step.

This module imports jax; keep it out of ``elastic/__init__`` so the
supervisor process can import the package without touching a backend.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
from jax.sharding import Mesh

from ..parallel.mesh import mesh_shape_str
from ..parallel.sharding import shard_layout_summary

__all__ = ["current_topology", "topology_changed", "topology_str"]


def _mesh_from_state(state: Any) -> Optional[Mesh]:
    """The mesh a placed pytree lives on, read off its first
    NamedSharding leaf (the Trainer holds a state, not a mesh)."""
    try:
        for leaf in jax.tree.leaves(state):
            mesh = getattr(getattr(leaf, "sharding", None), "mesh", None)
            if mesh is not None and hasattr(mesh, "shape"):
                return mesh
    except Exception:  # noqa: BLE001 - inference is best-effort
        pass
    return None


def _infer_weight_update(state: Any) -> Optional[str]:
    """'zero1' when the state's optimizer moments are sharded while the
    params are replicated (the ZeRO-1 signature), 'replicated' for a
    fully-replicated opt state; None when the state has no opt_state or
    the layout is something else (TP/FSDP shards params too — then the
    weight-update mode is not inferable from layout alone)."""
    params = getattr(state, "params", None)
    opt = getattr(state, "opt_state", None)
    if params is None or opt is None:
        return None
    p = shard_layout_summary(params)
    o = shard_layout_summary(opt)
    if p["sharded"] == 0 and o["sharded"] > 0:
        return "zero1"
    if p["sharded"] == 0 and o["sharded"] == 0:
        return "replicated"
    return None


def current_topology(mesh: Optional[Mesh] = None,
                     state: Optional[Any] = None,
                     weight_update: Optional[str] = None) -> Dict[str, Any]:
    """Fingerprint the running process: device/process counts, platform,
    the mesh axis sizes (given a mesh, or inferred from ``state``'s
    shardings), and the state's shard layout (when given). The
    weight-update mode rides along in the sidecar — passed explicitly by
    the Trainer, else inferred from the state's moment/param layouts —
    so a resume knows the checkpoint's opt state is ZeRO-1-sharded
    before it rebuilds the target layout."""
    devices = jax.devices()
    doc: Dict[str, Any] = {
        "device_count": len(devices),
        "process_count": jax.process_count(),
        "platform": devices[0].platform if devices else "none",
    }
    if mesh is None and state is not None:
        mesh = _mesh_from_state(state)
    if mesh is not None:
        doc["mesh_shape"] = {str(k): int(v) for k, v in mesh.shape.items()}
        doc["mesh_str"] = mesh_shape_str(mesh)
    if state is not None:
        try:
            doc["shard_layout"] = shard_layout_summary(state)
        except Exception:  # noqa: BLE001 - a summary failure must not
            pass           # block the checkpoint that embeds it
    if weight_update is None and state is not None:
        try:
            weight_update = _infer_weight_update(state)
        # dltpu: allow(DLT104) best-effort inference must not block the save
        except Exception:  # noqa: BLE001
            pass
    if weight_update is not None:
        doc["weight_update"] = weight_update
    return doc


def topology_changed(saved: Optional[Dict[str, Any]],
                     current: Dict[str, Any]) -> bool:
    """True when resume-time hardware differs from save-time in any way
    that forces a reshard: device count, process count, or mesh axis
    sizes. Unknown saved topology (old checkpoint, missing sidecar)
    counts as changed — the reshard path is always safe, the fast
    assumption is not."""
    if not saved:
        return True
    for key in ("device_count", "process_count"):
        if saved.get(key) != current.get(key):
            return True
    a, b = saved.get("mesh_shape"), current.get("mesh_shape")
    if a is not None and b is not None and dict(a) != dict(b):
        return True
    return False


def topology_str(doc: Optional[Dict[str, Any]]) -> str:
    if not doc:
        return "unknown"
    mesh = doc.get("mesh_str") or "?"
    return (f"{mesh} ({doc.get('device_count', '?')} devices, "
            f"{doc.get('process_count', '?')} processes, "
            f"{doc.get('platform', '?')})")
