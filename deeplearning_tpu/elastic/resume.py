"""Cross-topology resume: restore a checkpoint onto whatever mesh exists.

``test_checkpoint_cross_topology.py`` proved the mechanism (Orbax's
restore-into-sharded-target reshards automatically); this module makes
it a supported path instead of test folklore. ``elastic_restore`` shards
a freshly initialized template state onto the CURRENT mesh under the
CURRENT rules, restores the newest (or a chosen) checkpoint into that
target — values from disk, layout from today's hardware — and records a
flight ``resume`` event that says whether the topology changed and from
what, using the sidecar written by ``CheckpointManager.save(...,
topology=...)``.

Optimizer state rides along for free: ``shard_state`` mirrors param
shardings onto param-shaped optimizer moments (adam mu/nu), so the
restored moments are bitwise the saved values, just resharded.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

from jax.sharding import Mesh

from ..core.checkpoint import CheckpointManager
from ..obs import flight
from ..parallel.sharding import Rules
from ..train.steps import shard_state
from . import topology as topo

__all__ = ["elastic_restore"]


def elastic_restore(ckpt: CheckpointManager, state: Any, mesh: Mesh,
                    rules: Optional[Rules] = None,
                    step: Optional[int] = None,
                    zero1: bool = False) -> Tuple[Any, int]:
    """Restore the newest checkpoint onto ``mesh`` — re-sharding as
    needed — and return ``(state, step)``.

    ``state`` is a template (freshly initialized, correct structure);
    its values are discarded when a checkpoint exists. With no
    checkpoint, returns the template sharded onto the mesh at step 0 —
    i.e. calling this unconditionally at startup is the whole resume
    policy.

    ``zero1=True`` builds the target with data-sharded optimizer moments
    (``shard_state(..., zero1=True)``): a ZeRO-1 checkpoint saved on one
    data-parallel extent restores onto another with the moments bitwise
    the saved values, just re-split — and a replicated checkpoint can be
    adopted INTO zero1 the same way (the sidecar's ``weight_update``
    field says which it was)."""
    target = shard_state(state, mesh, rules, zero1=zero1)
    # integrity-checked restore: a corrupt newest step is quarantined and
    # the next intact one restored instead (core.checkpoint hardening)
    restored, got = ckpt.restore_verified(target, step)
    if restored is None:
        return target, 0
    step = got
    saved_topo = ckpt.topology(step)
    current = topo.current_topology(mesh)
    cross = topo.topology_changed(saved_topo, current)
    flight.record(
        "resume", step=int(step), cross_topology=bool(cross),
        saved_topology=topo.topology_str(saved_topo),
        current_topology=topo.topology_str(current))
    return restored, int(step)
