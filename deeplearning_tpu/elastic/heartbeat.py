"""Heartbeat file: the supervisor's window into a training process.

The Trainer touches a tiny in-memory :class:`Heartbeat` from the same
instrumentation points that emit obs spans (data_wait/dispatch/eval/
checkpoint) — a step watermark plus a monotonically increasing activity
counter. A daemon :class:`HeartbeatWriter` serializes it to a JSON file
on an interval with atomic replace, and the supervisor reads that file
to distinguish *slow* (activity advancing, steps not) from *wedged*
(neither advancing: the host thread is stuck inside a device transfer).

The writer thread keeps writing wall time even while the main thread is
wedged — deliberately. File freshness proves the *process* is alive;
only ``step``/``activity`` prove the *training loop* is. A supervisor
keying on mtime alone would never catch a wedged tunnel.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Optional

from ..obs import threads as obs_threads

__all__ = ["Heartbeat", "HeartbeatWriter", "read_heartbeat", "ENV_VAR",
           "RUN_ID_VAR", "REPLICA_VAR"]

# the supervisor hands its child the heartbeat path through this env var
ENV_VAR = "DLTPU_HEARTBEAT"

# fleet identity (tools/supervise.py exports these; obs/metrics.py uses
# the same names) — stamped into every heartbeat doc so supervisor
# heartbeats and fleet /metrics scrapes join on the same key
RUN_ID_VAR = "DLTPU_RUN_ID"
REPLICA_VAR = "DLTPU_REPLICA"


def _identity() -> Dict[str, str]:
    out: Dict[str, str] = {}
    run_id = os.environ.get(RUN_ID_VAR)
    replica = os.environ.get(REPLICA_VAR)
    if run_id:
        out["run_id"] = run_id
    if replica is not None and replica != "":
        out["replica"] = replica
    return out


class Heartbeat:
    """Shared mutable beat state. ``touch()`` is one int bump + two
    attribute stores — cheap enough for the hot loop, GIL-atomic enough
    to need no lock (the writer only ever reads)."""

    __slots__ = ("step", "activity", "phase")

    def __init__(self, step: int = 0):
        self.step = int(step)
        self.activity = 0
        self.phase = ""

    def touch(self, phase: Optional[str] = None,
              step: Optional[int] = None) -> None:
        if step is not None:
            self.step = int(step)
        if phase is not None:
            self.phase = phase
        self.activity += 1


class HeartbeatWriter:
    """Daemon thread ("elastic-heartbeat") serializing a Heartbeat to
    ``path`` every ``interval_s``. Writes are tmp + ``os.replace`` so a
    reader never sees a torn file; an immediate first write on start
    gives the supervisor a pid to key on before the first step lands."""

    def __init__(self, path: str, beat: Heartbeat,
                 interval_s: float = 0.5):
        self.path = os.path.abspath(path)
        self.beat = beat
        self.interval_s = max(float(interval_s), 0.05)
        self.writes = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _write(self) -> None:
        doc = {"time": time.time(), "pid": os.getpid(),
               "step": self.beat.step, "activity": self.beat.activity,
               "phase": self.beat.phase, **_identity()}
        tmp = f"{self.path}.tmp.{os.getpid()}"
        try:
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, self.path)
            self.writes += 1
        except OSError:
            pass                       # a missed beat is not a crash

    def _run(self) -> None:
        self._write()
        while not self._stop.wait(self.interval_s):
            self._write()

    def start(self) -> "HeartbeatWriter":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = obs_threads.spawn(
                self._run, name="elastic-heartbeat", daemon=True)
        return self

    def stop(self, timeout: float = 2.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
        self._write()                  # final beat: the exit watermark


def read_heartbeat(path: str) -> Optional[Dict[str, Any]]:
    """Parse a heartbeat file; None when absent/torn (the writer's
    atomic replace makes torn reads rare but a crash can leave any
    garbage behind)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc, dict) else None
