"""Chained signal subscriptions: one process, many SIGTERM subscribers.

Two subsystems want the same signals — the obs flight recorder dumps its
ring on SIGTERM, and the elastic preemption guard turns SIGTERM/SIGINT
into a graceful checkpoint-and-requeue. Python gives a process exactly
one handler per signal, so whoever installs second silently disconnects
whoever installed first. This registry owns the real handler and fans
the signal out to every subscriber, then falls through to whatever
handler was installed *before* the registry took the signal over — the
chain is never silently broken.

A subscriber registered with ``graceful=True`` declares that it owns
shutdown (the preemption guard: "I set a flag; the train loop will
checkpoint and exit at the next step boundary"). When any graceful
subscriber is present the dispatcher does NOT terminate the process;
without one, the pre-registry handler (or the OS default) runs, so a
process with only the flight-recorder subscriber still dies on SIGTERM
exactly as before.

Everything here is stdlib-only — the supervisor process imports it
without touching jax.
"""

from __future__ import annotations

import os
import signal
import threading
from typing import Callable, Dict, List, Tuple

__all__ = ["subscribe", "unsubscribe", "subscribers", "installed"]

Handler = Callable[[int, object], None]

_LOCK = threading.Lock()
_SUBSCRIBERS: Dict[int, List[Tuple[Handler, bool]]] = {}
_PREVIOUS: Dict[int, object] = {}      # handler the registry replaced


def subscribe(signum: int, fn: Handler, *, graceful: bool = False) -> bool:
    """Register ``fn(signum, frame)`` to run when ``signum`` arrives.

    Installs the registry's dispatcher on first use for that signal
    (main thread only — returns False elsewhere, signal.signal's rule).
    ``graceful=True`` marks ``fn`` as owning shutdown: while it is
    subscribed, the dispatcher returns after the fan-out instead of
    chaining into the terminating default."""
    with _LOCK:
        if signum not in _PREVIOUS:
            if threading.current_thread() is not threading.main_thread():
                return False
            try:
                previous = signal.getsignal(signum)
                signal.signal(signum, _dispatch)
            except (ValueError, OSError):   # exotic runtime / bad signum
                return False
            _PREVIOUS[signum] = previous
        _SUBSCRIBERS.setdefault(signum, []).append((fn, graceful))
    return True


def unsubscribe(signum: int, fn: Handler) -> None:
    """Remove every subscription of ``fn``. The dispatcher stays
    installed (removing it races with delivery); with zero subscribers
    it degenerates to the pre-registry behavior."""
    with _LOCK:
        subs = _SUBSCRIBERS.get(signum, [])
        # equality, not identity: ``obj.method`` builds a fresh bound
        # method on every access, so an identity check would never match
        _SUBSCRIBERS[signum] = [(f, g) for f, g in subs if f != fn]


def subscribers(signum: int) -> List[Tuple[Handler, bool]]:
    with _LOCK:
        return list(_SUBSCRIBERS.get(signum, []))


def installed(signum: int) -> bool:
    with _LOCK:
        return signum in _PREVIOUS


def _dispatch(signum: int, frame) -> None:
    """The one real handler: run every subscriber (a failing subscriber
    never starves the rest), then either yield to a graceful owner or
    chain the pre-registry handler / OS default."""
    with _LOCK:
        subs = list(_SUBSCRIBERS.get(signum, []))
        previous = _PREVIOUS.get(signum)
    graceful = False
    for fn, g in subs:
        try:
            fn(signum, frame)
        except Exception:  # noqa: BLE001 - handlers must not cascade
            pass  # dltpu: allow(DLT104) a failing subscriber must not starve the rest
        graceful = graceful or g
    if graceful:
        return                        # the owner exits at a safe boundary
    if previous in (signal.SIG_IGN, None):
        return
    if callable(previous):            # e.g. pytest/KeyboardInterrupt hook
        previous(signum, frame)
        return
    # SIG_DFL: re-deliver with the default disposition (terminates)
    signal.signal(signum, signal.SIG_DFL)
    os.kill(os.getpid(), signum)
