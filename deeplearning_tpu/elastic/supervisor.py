"""Run supervisor: launch, watch the heartbeat, classify, requeue.

The supervisor owns the outer loop that our bench history (BENCH_r01–r05,
five rounds of wedged-tunnel deaths) proves every long run needs:

    launch child → watch heartbeat → classify the ending → maybe requeue

Classification of an ended (or killed) attempt:

- exit 0                 → ``completed``: done, stop.
- exit :data:`EXIT_PREEMPTED` (75) → ``preempted``: the child landed its
  checkpoint before dying; requeue immediately-ish (backoff still
  applies — preemption storms exist).
- wedge (heartbeat ``step`` AND ``activity`` both frozen past
  ``wedge_deadline_s``) → ``wedged``: SIGTERM, grace, SIGKILL, requeue.
  A *slow* child (activity advancing, step not — long compile, big eval)
  is never killed.
- any other exit         → ``crashed``: requeue under the same budget.

Requeue waits ``min(base·factor^(n-1), max)·(1+jitter·U)`` and burns one
unit of a bounded restart budget; when the budget is gone the supervisor
gives up with the child's last exit code. Every decision is recorded to
the supervisor's *own* flight recorder (the child has its own) and
dumped to ``<workdir>/flightrec_supervisor.json`` — ``tools/obs_report``
renders the restarts section from exactly this file.

The supervisor never touches the device: its flight dumps skip the HBM
snapshot (``include_hbm=False``) because a supervisor that initializes
the jax backend can wedge in the same device init it polices.
"""

from __future__ import annotations

import os
import random
import signal
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from . import faults, heartbeat
from ..obs import threads as obs_threads
from .preempt import EXIT_PREEMPTED

__all__ = ["SupervisorConfig", "Supervisor", "WedgeDetector",
           "backoff_delay", "backoff_schedule",
           "worst_outcome", "exit_for_outcome",
           "OUTCOME_SEVERITY", "EXIT_WEDGED"]

# fleet exit classification: a crash outranks a wedge outranks a
# preemption outranks a clean/deliberate stop — numeric exit codes
# don't sort this way (75 > 1), so fleet mode classifies instead of
# max()ing raw return codes
OUTCOME_SEVERITY = {"completed": 0, "stopped": 0,
                    "preempted": 1, "wedged": 2, "crashed": 3}
EXIT_WEDGED = 70          # EX_SOFTWARE: killed-wedged, distinct from 1/75


def worst_outcome(outcomes: Sequence[str]) -> str:
    """The most severe outcome of a fleet (crash > wedge > preempted >
    clean); unknown labels rank as crashes."""
    worst = "completed"
    for o in outcomes:
        if OUTCOME_SEVERITY.get(o, 3) > OUTCOME_SEVERITY.get(worst, 3):
            worst = o
    return worst


def exit_for_outcome(outcome: str) -> int:
    """Representative process exit code for a classified outcome."""
    return {"completed": 0, "stopped": 0,
            "preempted": EXIT_PREEMPTED,
            "wedged": EXIT_WEDGED}.get(outcome, 1)


class SupervisorConfig:
    """Knobs for one supervised run. Defaults suit real runs; tests dial
    the deadlines down to tenths of seconds."""

    def __init__(self, argv: Sequence[str], *,
                 workdir: str = "runs/supervised",
                 heartbeat_path: Optional[str] = None,
                 max_restarts: int = 5,
                 backoff_base_s: float = 1.0,
                 backoff_factor: float = 2.0,
                 backoff_max_s: float = 60.0,
                 backoff_jitter: float = 0.25,
                 wedge_deadline_s: float = 120.0,
                 startup_deadline_s: float = 600.0,
                 poll_s: float = 0.25,
                 kill_grace_s: float = 10.0,
                 env: Optional[Dict[str, str]] = None,
                 seed: Optional[int] = None,
                 run_id: Optional[str] = None,
                 replica: Optional[int] = None):
        self.argv = list(argv)
        self.workdir = os.path.abspath(workdir)
        self.heartbeat_path = os.path.abspath(
            heartbeat_path or os.path.join(self.workdir, "heartbeat.json"))
        # fleet identity: handed to the child via env so its heartbeat,
        # /metrics exposition, and trace dump all join on the same key
        self.run_id = run_id
        self.replica = replica
        self.max_restarts = int(max_restarts)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_factor = float(backoff_factor)
        self.backoff_max_s = float(backoff_max_s)
        self.backoff_jitter = float(backoff_jitter)
        self.wedge_deadline_s = float(wedge_deadline_s)
        self.startup_deadline_s = float(startup_deadline_s)
        self.poll_s = float(poll_s)
        self.kill_grace_s = float(kill_grace_s)
        self.env = dict(env or {})
        self.seed = seed


def backoff_schedule(attempt: int, *, base_s: float, factor: float,
                     max_s: float, jitter: float,
                     rng: Optional[random.Random] = None) -> float:
    """Capped-exponential-plus-jitter delay before retry ``attempt``
    (1-based) — the one backoff curve in the codebase. The supervisor's
    requeue waits and the checkpoint manager's save retries both go
    through here, so a preemption storm (or an NFS brownout) never
    restarts/rewrites a whole fleet in lockstep."""
    base = min(base_s * (factor ** max(attempt - 1, 0)), max_s)
    u = (rng or random).random()
    return base * (1.0 + jitter * u)


def backoff_delay(attempt: int, cfg: SupervisorConfig,
                  rng: Optional[random.Random] = None) -> float:
    """Delay before restart number ``attempt`` under ``cfg``'s knobs."""
    return backoff_schedule(attempt, base_s=cfg.backoff_base_s,
                            factor=cfg.backoff_factor,
                            max_s=cfg.backoff_max_s,
                            jitter=cfg.backoff_jitter, rng=rng)


class WedgeDetector:
    """Slow-vs-wedged classifier over (step, activity) watermarks.

    ``observe(step, activity)`` returns ``"ok"`` when either watermark
    moved, ``"slow"`` when activity moves but step doesn't, ``"wedged"``
    once NEITHER has moved for ``deadline_s``. The distinction is the
    whole point: a 10-minute compile is slow (spans still tick); a dead
    device tunnel is wedged (the host thread never comes back).
    """

    def __init__(self, deadline_s: float):
        self.deadline_s = float(deadline_s)
        self._step: Optional[int] = None
        self._activity: Optional[int] = None
        self._step_at = time.monotonic()
        self._moved_at = time.monotonic()

    def reset(self) -> None:
        self._step = None
        self._activity = None
        self._step_at = time.monotonic()
        self._moved_at = time.monotonic()

    def observe(self, step: Optional[int], activity: Optional[int],
                now: Optional[float] = None) -> str:
        now = time.monotonic() if now is None else now
        moved = False
        if step is not None and step != self._step:
            self._step, self._step_at, moved = step, now, True
        if activity is not None and activity != self._activity:
            self._activity, moved = activity, True
        if moved:
            self._moved_at = now
            return "ok" if self._step_at == now else "slow"
        if now - self._moved_at >= self.deadline_s:
            return "wedged"
        return "slow" if now - self._step_at > now - self._moved_at else "ok"

    def stalled_for(self, now: Optional[float] = None) -> float:
        now = time.monotonic() if now is None else now
        return now - self._moved_at

    # ------------------------------------------------- in-process watch
    def watch(self, activity_fn: Callable[[], int],
              on_wedge: Callable[[float], None], *,
              poll_s: float = 1.0,
              stop: Optional[threading.Event] = None,
              name: str = "wedge-watch") -> threading.Thread:
        """Background thread flavor for in-process use (bench.py health
        probes): poll ``activity_fn()`` and call ``on_wedge(stalled_s)``
        once when it freezes past the deadline. ``stop.set()`` ends the
        watch — the happy path never fires the callback."""
        stop = stop or threading.Event()
        self.reset()

        def _run() -> None:
            while not stop.wait(min(poll_s, self.deadline_s / 2)):
                try:
                    verdict = self.observe(None, int(activity_fn()))
                except Exception:  # noqa: BLE001 - probe itself died
                    verdict = "wedged"
                if verdict == "wedged":
                    try:
                        on_wedge(self.stalled_for())
                    except Exception:  # noqa: BLE001
                        pass
                    return

        thread = obs_threads.spawn(_run, name=name, daemon=True,
                                   start=False)
        thread.stop = stop  # type: ignore[attr-defined]
        thread.start()
        return thread


class Supervisor:
    """The requeue loop. ``run()`` blocks until the child completes,
    the restart budget is exhausted, or the run is unsupervisable."""

    def __init__(self, cfg: SupervisorConfig):
        from ..obs.flight import FlightRecorder   # own ring, not global
        self.cfg = cfg
        self.rng = random.Random(cfg.seed)
        self.flight = FlightRecorder()
        self.flight.configure(
            os.path.join(cfg.workdir, "flightrec_supervisor.json"),
            config={"argv": cfg.argv, "max_restarts": cfg.max_restarts,
                    "wedge_deadline_s": cfg.wedge_deadline_s,
                    "backoff_base_s": cfg.backoff_base_s,
                    "backoff_factor": cfg.backoff_factor,
                    "backoff_max_s": cfg.backoff_max_s})
        self.launches = 0
        self.outcomes: List[str] = []
        self.final_outcome: Optional[str] = None
        self.backoff_total_s = 0.0
        self._log = print
        # runtime lifecycle verbs (fleet controller surface): a pending
        # directive is honored at the next watch poll / backoff wake —
        # "stop" ends the run cleanly, "restart" requeues the child NOW
        # without burning the restart budget (a capacity op, not a
        # failure). on_outcome, when set, sees every natural ending and
        # may return "requeue_now" (skip backoff + budget) or "stop"
        # (shed the replica) to override the default policy.
        self._directive_lock = threading.Lock()
        self._directive: Optional[tuple] = None
        self._wake = threading.Event()
        self.on_outcome: Optional[Callable[..., Optional[str]]] = None

    # ------------------------------------------------------- directives
    def request_stop(self, reason: str = "requested") -> None:
        """Ask the run loop to kill the child (if any) and return 0."""
        with self._directive_lock:
            self._directive = ("stop", reason)
        self._wake.set()

    def request_restart(self, reason: str = "requested") -> None:
        """Ask the run loop to kill + relaunch the child immediately —
        no backoff, no restart-budget burn. The relaunch still gets a
        fresh attempt number (``DLTPU_RESTART_ATTEMPT``), so
        attempt-gated fault specs don't re-fire in the replacement."""
        with self._directive_lock:
            if self._directive is None:       # stop always wins
                self._directive = ("restart", reason)
        self._wake.set()

    def _take_directive(self) -> Optional[tuple]:
        with self._directive_lock:
            d, self._directive = self._directive, None
            self._wake.clear()        # inside the lock: a set() after
        return d                      # this re-raises the flag

    # ----------------------------------------------------------- pieces
    def _child_env(self, attempt: int) -> Dict[str, str]:
        env = dict(os.environ)
        env.update(self.cfg.env)
        env[heartbeat.ENV_VAR] = self.cfg.heartbeat_path
        env[faults.ATTEMPT_VAR] = str(attempt)
        if self.cfg.run_id:
            env[heartbeat.RUN_ID_VAR] = self.cfg.run_id
        if self.cfg.replica is not None:
            env[heartbeat.REPLICA_VAR] = str(self.cfg.replica)
            # where the child advertises its scrape URL (fleet discovery)
            env["DLTPU_ENDPOINT_FILE"] = os.path.join(
                self.cfg.workdir, "endpoint.json")
        return env

    def _launch(self, attempt: int) -> subprocess.Popen:
        os.makedirs(self.cfg.workdir, exist_ok=True)
        try:                              # a stale beat from a previous
            os.remove(self.cfg.heartbeat_path)   # attempt must not count
        except OSError:
            pass
        self.launches += 1
        self.flight.record("launch", attempt=attempt, argv=self.cfg.argv)
        self._log(f"[supervise] attempt {attempt}: "
                  f"exec {' '.join(self.cfg.argv)}", file=sys.stderr)
        return subprocess.Popen(self.cfg.argv, env=self._child_env(attempt))

    def _watch(self, child: subprocess.Popen) -> str:
        """Block until the child exits, wedges, or a lifecycle directive
        arrives. Returns ``"exit"``, ``"wedged"``, or ``"directive"``
        (for the latter two the child may still be running — caller must
        kill). The directive check comes FIRST so a controller's verdict
        beats the child's own exit classification: a wedged serving
        child killed by us exits 0 through its graceful SIGTERM drain,
        and that must still count as a requeue, not a completion."""
        detector = WedgeDetector(self.cfg.wedge_deadline_s)
        started = time.monotonic()
        seen_beat = False
        while True:
            if self._directive is not None:
                return "directive"
            if child.poll() is not None:
                return "exit"
            beat = heartbeat.read_heartbeat(self.cfg.heartbeat_path)
            if beat is not None and beat.get("pid") == child.pid:
                seen_beat = True
                detector.observe(beat.get("step"), beat.get("activity"))
                if detector.stalled_for() >= self.cfg.wedge_deadline_s:
                    return "wedged"
            elif not seen_beat and (time.monotonic() - started
                                    >= self.cfg.startup_deadline_s):
                return "wedged"           # never even produced a beat
            self._wake.wait(self.cfg.poll_s)

    def _kill(self, child: subprocess.Popen) -> None:
        """SIGTERM → grace → SIGKILL. The grace window lets the child's
        preemption guard flush its checkpoint; a truly wedged main
        thread won't take the hint and eats the SIGKILL."""
        try:
            child.send_signal(signal.SIGTERM)
        except OSError:
            return
        try:
            child.wait(self.cfg.kill_grace_s)
        except subprocess.TimeoutExpired:
            child.kill()
            child.wait()

    # -------------------------------------------------------------- run
    def _finish(self, outcome: str, rc: int, reason: str) -> int:
        self.final_outcome = outcome
        self.flight.record(outcome if outcome in ("completed", "stopped")
                           else "gave_up", returncode=rc, reason=reason)
        self.flight.dump(reason, include_hbm=False)
        return rc

    def run(self) -> int:
        attempt, last_rc, budget_used = 0, 1, 0
        while True:
            child = self._launch(attempt)
            verdict = self._watch(child)
            if verdict == "directive":
                kind, reason = self._take_directive() or ("stop", "race")
                self._kill(child)
                if kind == "stop":
                    self.outcomes.append("stopped")
                    self._log(f"[supervise] attempt {attempt}: stopped "
                              f"({reason})", file=sys.stderr)
                    return self._finish("stopped", 0, reason)
                # restart directive: a capacity op — requeue NOW, no
                # backoff, no budget burn; attempt still advances so the
                # replacement's env (DLTPU_RESTART_ATTEMPT) moves past
                # attempt-gated fault specs
                self.outcomes.append("requeued")
                self.flight.record("requeue", attempt=attempt,
                                   reason=reason)
                self._log(f"[supervise] attempt {attempt}: requeued "
                          f"({reason})", file=sys.stderr)
                attempt += 1
                continue
            if verdict == "wedged":
                self.flight.record("wedge_kill", attempt=attempt,
                                   pid=child.pid,
                                   deadline_s=self.cfg.wedge_deadline_s)
                self._log(f"[supervise] attempt {attempt}: wedged "
                          f"(no progress for {self.cfg.wedge_deadline_s}s)"
                          f" — killing pid {child.pid}", file=sys.stderr)
                self._kill(child)
                outcome, last_rc = "wedged", child.returncode or 1
            else:
                rc = child.returncode
                last_rc = rc
                if rc == 0:
                    outcome = "completed"
                elif rc == EXIT_PREEMPTED:
                    outcome = "preempted"
                else:
                    outcome = "crashed"
                self.flight.record("child_exit", attempt=attempt,
                                   returncode=rc, outcome=outcome)
            self.outcomes.append(outcome)
            hint = None
            if self.on_outcome is not None:
                try:
                    hint = self.on_outcome(self, outcome, attempt, last_rc)
                except Exception:  # noqa: BLE001 - policy must not kill us
                    hint = None
            if hint == "stop":
                # the controller chose to shed this replica (e.g. a
                # preemption while over capacity): a deliberate, clean end
                self._log(f"[supervise] attempt {attempt} {outcome}; "
                          f"shed by controller", file=sys.stderr)
                return self._finish("stopped", 0, f"shed_after_{outcome}")
            if outcome == "completed":
                self.flight.record("completed", attempt=attempt)
                self.final_outcome = "completed"
                self.flight.dump("completed", include_hbm=False)
                return 0
            attempt += 1
            if hint == "requeue_now":
                self.flight.record("requeue", attempt=attempt - 1,
                                   reason=f"controller_{outcome}")
                self._log(f"[supervise] attempt {attempt - 1} {outcome}; "
                          f"controller requeue now", file=sys.stderr)
                continue
            budget_used += 1
            if budget_used > self.cfg.max_restarts:
                self.final_outcome = outcome
                self.flight.record("gave_up", attempts=attempt,
                                   last_outcome=outcome, returncode=last_rc)
                self.flight.dump("gave_up", include_hbm=False)
                self._log(f"[supervise] restart budget exhausted after "
                          f"{attempt} attempts; giving up (rc={last_rc})",
                          file=sys.stderr)
                return last_rc if last_rc else 1
            delay = backoff_delay(budget_used, self.cfg, self.rng)
            self.backoff_total_s += delay
            self.flight.record("backoff", attempt=attempt,
                               outcome=outcome, delay_s=round(delay, 3))
            self._log(f"[supervise] attempt {attempt - 1} {outcome}; "
                      f"requeue {attempt}/{self.cfg.max_restarts} in "
                      f"{delay:.2f}s", file=sys.stderr)
            if self._wake.wait(delay):
                d = self._take_directive()
                if d is not None and d[0] == "stop":
                    self.outcomes.append("stopped")
                    return self._finish("stopped", 0, d[1])
                # restart directive mid-backoff: just relaunch now
