"""Fault injection: make preemption, crashes, and wedges CPU-testable.

The elastic loop only earns trust if tier-1 can kill it on purpose. The
Trainer calls :func:`maybe_fire` at two sites — every step boundary
(``site="step"``) and just before each checkpoint write
(``site="checkpoint"``) — and this module decides, from the
``DLTPU_FAULTS`` env var, whether to deliver a fault there.

Grammar (``;``-separated specs, each ``@``-separated fields)::

    DLTPU_FAULTS="sigterm@step:5@attempt:0;crash@checkpoint;wedge@step:3"

    kind      := sigterm | sigint | crash | wedge
               | nan | bad_sample | ckpt_corrupt
    site      := step[:N] | checkpoint[:N]   (N = fire at host step >= N;
                                              omitted = first visit)
    attempt:K := only fire on restart attempt K (DLTPU_RESTART_ATTEMPT,
                 set by the supervisor; defaults to 0 when unset)

Each spec fires at most once per process. Actions:

- ``sigterm``/``sigint``: ``os.kill(os.getpid(), SIG*)`` — exercises the
  real handler chain, not a shortcut into the guard.
- ``crash``: raise :class:`InjectedCrash` (a non-Preempted exception →
  non-75 exit → the supervisor counts a crash).
- ``wedge``: block in ``time.sleep`` while the heartbeat writer thread
  keeps the file fresh — exactly the wedged-device-tunnel signature
  (process alive, loop stuck) the supervisor must classify and kill.

The self-healing kinds (``nan``, ``bad_sample``, ``ckpt_corrupt``) are
*consumed*, not fired: :func:`maybe_fire` never delivers them — the
subsystem that owns the effect polls :func:`consume` and applies it
through its REAL code path, so the recovery machinery is exercised end
to end instead of shortcut into:

- ``nan@step:N``: the Trainer poisons its params with NaN at host step
  N, so the next dispatched step's jitted ``bad_step`` flag fires and
  divergence recovery (rollback or abort) runs for real.
- ``bad_sample@step:N``: the DataLoader's per-sample fetch raises
  :class:`InjectedBadSample` at fetch ordinal N — the quarantine path's
  test handle (``step`` here counts SAMPLE fetches, not train steps).
- ``ckpt_corrupt@step:N``: after the checkpoint write at step >= N
  commits, the Trainer garbles the step dir on disk
  (:func:`corrupt_checkpoint`), so restore-time verification must fall
  back to the previous intact step.

The fleet-choreography kinds target ONE replica of a supervised fleet
(``DLTPU_REPLICA``, exported per child by ``tools/supervise.py``) so a
single ``DLTPU_FAULTS`` value shared by every replica still wedges or
preempts exactly one of them:

- ``wedge_replica:<i>@step:N``: consumed by the serving
  ``MicroBatcher``'s dispatch loop on replica ``i`` once ``dispatched``
  reaches N — the loop blocks (heartbeat thread stays alive, queue
  keeps filling) so ``DispatchWatch``/the controller must classify the
  frozen stream and requeue the replica.
- ``preempt_replica:<i>@step:N``: consumed on replica ``i`` at the same
  site; the serving CLI reacts exactly as a real SIGTERM-with-grace
  preemption would — drain, then exit 75 — so the controller's
  preemption-as-capacity path runs for real.

The resilience-layer kinds extend the consumed family to the serving
data plane (all polled by the ``MicroBatcher`` against its
``dispatched`` counter):

- ``e503@submit:N``: the serve CLI answers one request with an injected
  503 once ``dispatched`` reaches N — exercises router failover and the
  per-replica circuit breaker without any replica actually failing.
- ``latency:<ms>@step:N``: the dispatch loop sleeps ``ms`` before one
  batch — injected tail latency, the stimulus the router's hedging
  policy exists to absorb.
- ``crash_replica:<i>@step:N``: replica ``i`` hard-exits (non-75,
  non-0) mid-serve, so the supervisor classifies a crash and in-flight
  requests surface as connection errors to the router.

``DLTPU_CHAOS=<seed>:<spec>`` compiles a *deterministic* schedule of
the above through :func:`chaos_schedule` (same seed → byte-identical
schedule), e.g. ``DLTPU_CHAOS="7:e503*20@5-40;latency:50*10@5-40;
wedge:1*1@10-30"`` — each token is ``kind[:target]*count@lo-hi`` and
expands to ``count`` specs in the regular grammar with step ordinals
drawn from ``[lo, hi]``. :func:`active_faults` merges the compiled
schedule with any explicit ``DLTPU_FAULTS`` specs.
"""

from __future__ import annotations

import os
import random
import signal
import time
from typing import List, Optional

__all__ = ["ENV_VAR", "ATTEMPT_VAR", "REPLICA_VAR", "CHAOS_VAR",
           "FaultSpec", "InjectedCrash", "InjectedBadSample",
           "parse_faults", "chaos_schedule", "active_faults",
           "maybe_fire", "consume", "consume_arg",
           "corrupt_checkpoint", "reset"]

ENV_VAR = "DLTPU_FAULTS"
ATTEMPT_VAR = "DLTPU_RESTART_ATTEMPT"
CHAOS_VAR = "DLTPU_CHAOS"

_KINDS = ("sigterm", "sigint", "crash", "wedge",
          "nan", "bad_sample", "ckpt_corrupt",
          "wedge_replica", "preempt_replica",
          "e503", "latency", "crash_replica")
# kinds applied by their owning subsystem via consume(); maybe_fire
# skips them so the generic step/checkpoint hooks can't double-deliver
_CONSUMED_KINDS = ("nan", "bad_sample", "ckpt_corrupt",
                   "wedge_replica", "preempt_replica",
                   "e503", "latency", "crash_replica")
# kinds whose token carries a target replica index (kind:<i>) matched
# against DLTPU_REPLICA — one shared fault var, one afflicted replica
_REPLICA_KINDS = ("wedge_replica", "preempt_replica", "crash_replica")
# kinds whose token carries a numeric argument (kind:<value>)
_ARG_KINDS = ("latency",)
_SITES = ("step", "checkpoint", "submit")
REPLICA_VAR = "DLTPU_REPLICA"

# chaos token kind → the regular-grammar kind/site it expands to
_CHAOS_KINDS = {"e503": ("e503", "submit"),
                "latency": ("latency", "step"),
                "wedge": ("wedge_replica", "step"),
                "preempt": ("preempt_replica", "step"),
                "crash": ("crash_replica", "step")}

# long enough that only the supervisor's wedge kill ends it, short
# enough that an escaped sleep can't outlive a test suite timeout
WEDGE_SLEEP_S = 600.0


class InjectedCrash(RuntimeError):
    """The ``crash`` fault: an ordinary hard failure, exit code != 75."""


class InjectedBadSample(ValueError):
    """The ``bad_sample`` fault: a per-sample decode failure, raised
    inside the loader's fetch so the quarantine path catches it exactly
    where a real corrupt JPEG would surface."""


class FaultSpec:
    __slots__ = ("kind", "site", "at_step", "attempt", "replica", "arg",
                 "fired")

    def __init__(self, kind: str, site: str, at_step: Optional[int],
                 attempt: Optional[int], replica: Optional[int] = None,
                 arg: Optional[float] = None):
        self.kind = kind
        self.site = site
        self.at_step = at_step
        self.attempt = attempt
        self.replica = replica
        self.arg = arg
        self.fired = False

    def __repr__(self) -> str:  # shows up in flight events / test output
        kind = self.kind
        if self.replica is not None:
            kind = f"{kind}:{self.replica}"
        elif self.arg is not None:
            kind = f"{kind}:{self.arg:g}"
        parts = [kind, self.site if self.at_step is None
                 else f"{self.site}:{self.at_step}"]
        if self.attempt is not None:
            parts.append(f"attempt:{self.attempt}")
        return "@".join(parts)

    def matches(self, site: str, step: int, attempt: int) -> bool:
        if self.fired or self.site != site:
            return False
        if self.attempt is not None and self.attempt != attempt:
            return False
        if self.at_step is not None and step < self.at_step:
            return False
        if self.replica is not None and self.replica != _current_replica():
            return False
        return True


def parse_faults(text: str) -> List[FaultSpec]:
    """Parse the grammar; malformed specs are skipped (a typo in a fault
    var should never take down a real run)."""
    specs: List[FaultSpec] = []
    for raw in text.split(";"):
        raw = raw.strip()
        if not raw:
            continue
        fields = [f.strip() for f in raw.split("@")]
        kind, _, target = fields[0].lower().partition(":")
        if kind not in _KINDS:
            continue
        replica, arg = None, None
        if kind in _REPLICA_KINDS:
            try:
                replica = int(target)
            except ValueError:
                continue               # replica kinds require a target
        elif kind in _ARG_KINDS:
            try:
                arg = float(target)
            except ValueError:
                continue               # arg kinds require a value
        elif target:
            continue                   # "sigterm:3" is not grammar
        site, at_step, attempt = "step", None, None
        ok = True
        for field in fields[1:]:
            name, _, value = field.partition(":")
            name = name.lower()
            if name in _SITES:
                site = name
                if value:
                    try:
                        at_step = int(value)
                    except ValueError:
                        ok = False
            elif name == "attempt":
                try:
                    attempt = int(value)
                except ValueError:
                    ok = False
            else:
                ok = False
        if ok:
            specs.append(FaultSpec(kind, site, at_step, attempt, replica,
                                   arg))
    return specs


def chaos_schedule(text: str) -> str:
    """Compile ``DLTPU_CHAOS="<seed>:<token>;<token>..."`` into a
    regular-grammar fault string. Each token is
    ``kind[:target]*count@lo-hi`` (``count`` defaults to 1, range to
    ``0-0``); kinds: ``e503``, ``latency:<ms>``, ``wedge:<i>``,
    ``preempt:<i>``, ``crash:<i>``. Pure and deterministic — one
    ``random.Random(seed)`` consumed in token order, so the same seed
    yields a byte-identical schedule on every run (replayable chaos).
    Malformed input compiles to ``""``, never raises."""
    seed_s, sep, body = text.partition(":")
    if not sep:
        return ""
    try:
        rng = random.Random(int(seed_s))
    except ValueError:
        return ""
    out: List[str] = []
    for token in body.split(";"):
        token = token.strip()
        if not token:
            continue
        head, _, rng_s = token.partition("@")
        name, _, count_s = head.partition("*")
        kind, _, target = name.strip().lower().partition(":")
        if kind not in _CHAOS_KINDS:
            continue
        real_kind, site = _CHAOS_KINDS[kind]
        if real_kind in _REPLICA_KINDS or real_kind in _ARG_KINDS:
            if not target:
                continue               # wedge/preempt/crash/latency need one
            real_kind = f"{real_kind}:{target}"
        elif target:
            continue
        try:
            count = int(count_s) if count_s else 1
            lo_s, _, hi_s = (rng_s or "0-0").partition("-")
            lo, hi = int(lo_s), int(hi_s or lo_s)
        except ValueError:
            continue
        if count < 1 or hi < lo:
            continue
        steps = sorted(rng.randint(lo, hi) for _ in range(count))
        out.extend(f"{real_kind}@{site}:{s}" for s in steps)
    return ";".join(out)


_SPECS: Optional[List[FaultSpec]] = None


def active_faults() -> List[FaultSpec]:
    global _SPECS
    if _SPECS is None:
        specs = parse_faults(os.environ.get(ENV_VAR, ""))
        chaos = os.environ.get(CHAOS_VAR, "")
        if chaos:
            specs.extend(parse_faults(chaos_schedule(chaos)))
        _SPECS = specs
    return _SPECS


def reset() -> None:
    """Forget parsed state so tests can re-point DLTPU_FAULTS."""
    global _SPECS
    _SPECS = None


def current_attempt() -> int:
    try:
        return int(os.environ.get(ATTEMPT_VAR, "0"))
    except ValueError:
        return 0


def _current_replica() -> int:
    try:
        return int(os.environ.get(REPLICA_VAR, "0"))
    except ValueError:
        return 0


def maybe_fire(site: str, step: int = 0) -> None:
    """Fire the first matching un-fired fault for this site, if any."""
    specs = active_faults()
    if not specs:
        return
    attempt = current_attempt()
    for spec in specs:
        if spec.kind in _CONSUMED_KINDS:
            continue
        if not spec.matches(site, step, attempt):
            continue
        spec.fired = True
        _fire(spec, step)
        return


def _consume_spec(kind: str, site: str, step: int) -> Optional[FaultSpec]:
    specs = active_faults()
    if not specs:
        return None
    attempt = current_attempt()
    for spec in specs:
        if spec.kind != kind or not spec.matches(site, step, attempt):
            continue
        spec.fired = True
        from ..obs import flight
        flight.record("fault_injected", fault=repr(spec), step=int(step))
        return spec
    return None


def consume(kind: str, site: str, step: int = 0) -> bool:
    """Poll-style faults: True once when a matching un-fired spec of
    ``kind`` exists — the CALLER owns the effect (poison params, raise a
    decode error, garble a step dir), so the fault flows through the
    same code path a real failure would."""
    return _consume_spec(kind, site, step) is not None


def consume_arg(kind: str, site: str, step: int = 0) -> Optional[float]:
    """Like :func:`consume` for arg-carrying kinds (``latency:<ms>``):
    returns the spec's numeric argument once, ``None`` when nothing
    matches."""
    spec = _consume_spec(kind, site, step)
    if spec is None:
        return None
    return spec.arg if spec.arg is not None else 0.0


def corrupt_checkpoint(directory: str, step: int,
                       n_files: int = 1) -> List[str]:
    """Garble the largest file(s) of a COMMITTED checkpoint step dir
    (bit-flip a chunk in the middle) — the ``ckpt_corrupt`` fault's
    effect, applied after the write lands so Orbax's atomic-rename
    commit sees nothing. Returns the paths touched."""
    root = os.path.join(directory, str(step))
    candidates = []
    for dirpath, _, names in os.walk(root):
        for name in names:
            path = os.path.join(dirpath, name)
            try:
                size = os.path.getsize(path)
            except OSError:
                continue
            if size > 0:
                candidates.append((size, path))
    candidates.sort(reverse=True)
    hit = []
    for size, path in candidates[:max(int(n_files), 1)]:
        with open(path, "r+b") as f:
            f.seek(size // 2)
            chunk = f.read(min(64, size - size // 2)) or b"\x00"
            f.seek(size // 2)
            f.write(bytes(b ^ 0xFF for b in chunk))
        hit.append(path)
    return hit


def _fire(spec: FaultSpec, step: int) -> None:
    from ..obs import flight
    flight.record("fault_injected", fault=repr(spec), step=int(step))
    if spec.kind in ("sigterm", "sigint"):
        signum = signal.SIGTERM if spec.kind == "sigterm" else signal.SIGINT
        # deliver through the kernel: the registry's dispatcher, the
        # flight hook, and the preemption guard all run for real
        os.kill(os.getpid(), signum)
        return
    if spec.kind == "crash":
        raise InjectedCrash(f"injected fault {spec!r} at step {step}")
    if spec.kind == "wedge":
        # simulate a blocked device transfer: the main thread stalls,
        # daemon threads (heartbeat writer) stay alive — the supervisor
        # must notice the frozen step/activity watermarks and kill us.
        deadline = time.monotonic() + WEDGE_SLEEP_S
        while time.monotonic() < deadline:
            time.sleep(0.5)
