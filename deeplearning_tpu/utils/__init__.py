from . import normalization, profiling, visualize  # noqa: F401
