"""Torch checkpoint → flax variables converter.

The reference ships weight converters in both directions
(classification/efficientNet/trans_weights_to_pytorch.py,
deep_stereo/.../trans_weight_to_pytorch.py) plus a partial/renamed
state-dict loading tour (others/load_weights_test/load_weights.py). This
module is the TPU-era analog: it turns a torch ``state_dict`` (dotted
names, OIHW conv kernels, (out,in) linear weights) into a flax variables
tree ({"params": ..., "batch_stats": ...}) with the layout transposes the
two frameworks disagree on, so reference-zoo ``.pth`` files can seed our
models via ``core.checkpoint.surgical_load``.

Layout rules applied per tensor:
- conv ``weight`` (O,I,kH,kW)  -> ``kernel`` (kH,kW,I,O)
- linear ``weight`` (out,in)   -> ``kernel`` (in,out)
- norm ``weight``              -> ``scale``  (unchanged shape)
- ``running_mean``/``running_var`` -> batch_stats ``mean``/``var``
- ``num_batches_tracked``      -> dropped
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Mapping, Optional, Tuple

import numpy as np

__all__ = ["torch_to_flax", "load_torch_checkpoint"]

_BN_STATS = {"running_mean": "mean", "running_var": "var"}
_NORM_HINTS = ("bn", "norm", "downsample.1")


def _is_norm_weight(torch_key: str, arr: np.ndarray,
                    state: Mapping[str, Any]) -> bool:
    """A 1-D ``weight`` is a norm scale iff the module also has running
    stats, or its name says so (LayerNorm has no running stats)."""
    if arr.ndim != 1:
        return False
    stem = torch_key.rsplit(".", 1)[0]
    if f"{stem}.running_mean" in state:
        return True
    return any(h in stem.lower() for h in _NORM_HINTS)


def _convert(torch_key: str, arr: np.ndarray,
             state: Mapping[str, Any]) -> Tuple[str, np.ndarray, str]:
    """-> (flax_leaf_name, converted_array, collection)."""
    leaf = torch_key.rsplit(".", 1)[-1]
    if leaf in _BN_STATS:
        return _BN_STATS[leaf], arr, "batch_stats"
    if leaf == "weight":
        if arr.ndim == 4:                       # conv OIHW -> HWIO
            return "kernel", arr.transpose(2, 3, 1, 0), "params"
        if arr.ndim == 3:                       # conv1d OIW -> WIO
            return "kernel", arr.transpose(2, 1, 0), "params"
        if arr.ndim == 2:
            # nn.Embedding stays (V, C) and flax calls it "embedding";
            # detected by module name since torch stores both as "weight"
            stem_last = torch_key.rsplit(".", 2)[-2] if "." in torch_key \
                else ""
            if "embed" in stem_last.lower():
                return "embedding", arr, "params"
            return "kernel", arr.transpose(1, 0), "params"  # linear
        if _is_norm_weight(torch_key, arr, state):
            return "scale", arr, "params"
        return "kernel", arr, "params"
    return leaf, arr, "params"


def torch_to_flax(
    state_dict: Mapping[str, Any],
    rename: Optional[Callable[[str], Optional[str]]] = None,
) -> Dict[str, Dict]:
    """Convert a torch ``state_dict`` to a flax variables tree.

    ``rename`` maps each torch module path (dots already split off the
    leaf) to the flax module path ("a/b/c"); return None to drop the
    entry. Default: dots become path separators unchanged, so converted
    trees line up with models whose submodule names mirror the torch
    implementation (the surgical_load name-mapping hook covers the rest).
    """
    out: Dict[str, Dict] = {"params": {}, "batch_stats": {}}
    for key, value in state_dict.items():
        if key.endswith("num_batches_tracked"):
            continue
        arr = np.asarray(
            value.detach().cpu().numpy() if hasattr(value, "detach")
            else value)
        stem = key.rsplit(".", 1)[0] if "." in key else ""
        if rename is not None:
            stem = rename(stem)
            if stem is None:
                continue
        leaf, arr, col = _convert(key, arr, state_dict)
        node = out[col]
        for part in (p for p in stem.split(".") if p):
            node = node.setdefault(part, {})
        node[leaf] = arr
    return {k: v for k, v in out.items() if v}


def load_torch_checkpoint(path: str, **kw) -> Dict[str, Dict]:
    """Read a ``.pth``/``.pt`` file (CPU map) and convert. Accepts either a
    bare state_dict or the common {"model"|"state_dict": ...} wrappers."""
    import torch

    obj = torch.load(path, map_location="cpu", weights_only=False)
    for wrapper in ("model", "state_dict", "model_state_dict"):
        if isinstance(obj, dict) and wrapper in obj and isinstance(
                obj[wrapper], dict):
            obj = obj[wrapper]
            break
    if hasattr(obj, "state_dict"):
        obj = obj.state_dict()
    return torch_to_flax(obj, **kw)
