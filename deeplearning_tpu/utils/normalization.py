"""Normalization layers from scratch — the teaching module.

Surface of others/normalization (batch_normalization.py,
layer_normalization.py, instance_normalization.py,
group_normalization.py): each norm written out as explicit mean/var math
over its reduction axes, for study and as golden references against the
flax implementations (tests compare them).

Axes cheat-sheet for NHWC:
  BatchNorm:    reduce (N, H, W)  per channel
  LayerNorm:    reduce (C,) [or (H, W, C)] per sample position
  InstanceNorm: reduce (H, W)     per sample per channel
  GroupNorm:    reduce (H, W, C/G) per sample per group
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def batch_norm(x, gamma, beta, eps: float = 1e-5):
    mean = jnp.mean(x, axis=(0, 1, 2), keepdims=True)
    var = jnp.var(x, axis=(0, 1, 2), keepdims=True)
    return gamma * (x - mean) / jnp.sqrt(var + eps) + beta


def layer_norm(x, gamma, beta, eps: float = 1e-5):
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return gamma * (x - mean) / jnp.sqrt(var + eps) + beta


def instance_norm(x, gamma, beta, eps: float = 1e-5):
    mean = jnp.mean(x, axis=(1, 2), keepdims=True)
    var = jnp.var(x, axis=(1, 2), keepdims=True)
    return gamma * (x - mean) / jnp.sqrt(var + eps) + beta


def group_norm(x, gamma, beta, groups: int, eps: float = 1e-5):
    b, h, w, c = x.shape
    g = x.reshape(b, h, w, groups, c // groups)
    mean = jnp.mean(g, axis=(1, 2, 4), keepdims=True)
    var = jnp.var(g, axis=(1, 2, 4), keepdims=True)
    g = (g - mean) / jnp.sqrt(var + eps)
    return gamma * g.reshape(b, h, w, c) + beta


def sync_batch_norm_stats(x, axis_name: str):
    """Cross-replica BN statistics via pmean — what SyncBatchNorm does
    (others/train_with_DDP/train.py:192). Inside pjit/GSPMD this is
    automatic; this explicit version is for shard_map code."""
    mean = jax.lax.pmean(jnp.mean(x, axis=(0, 1, 2)), axis_name)
    mean2 = jax.lax.pmean(jnp.mean(jnp.square(x), axis=(0, 1, 2)),
                          axis_name)
    return mean, mean2 - jnp.square(mean)
