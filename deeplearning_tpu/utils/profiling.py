"""Profiling: step timing, XLA-FLOPs MFU meter, jax.profiler traces.

The reference's ad-hoc timing stack (SURVEY.md §5: cuda-synchronized
time_sync, thop-based layer profilers, swin throughput mode) becomes:
- ``StepTimer``: wall-clock per-step timing synced by scalar D2H fetch
  (block_until_ready is unreliable on remote-tunnel backends).
- ``mfu``: measured step time vs compiled-graph FLOPs vs chip peak — the
  BASELINE.md headline metric.
- ``trace``: context manager around jax.profiler for TensorBoard's
  profile plugin.
"""

from __future__ import annotations

import contextlib
import time
import warnings
from typing import Callable, Dict, Optional

import jax

PEAK_BF16_FLOPS = {
    "v6": 918e12, "v5p": 459e12, "v5": 197e12, "v4": 275e12,
    "v3": 123e12, "v2": 45e12,
}


def device_peak_flops(device: Optional[jax.Device] = None) -> float:
    device = device or jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    for key, val in PEAK_BF16_FLOPS.items():
        if key in kind:
            return val
    return 197e12


class StepTimer:
    """Accumulates step wall times; caller syncs via the returned scalar."""

    def __init__(self):
        self.times = []
        self._t0 = None

    def start(self):
        self._t0 = time.perf_counter()

    def stop(self):
        # stop() without a matching start() (callback fired before the
        # loop primed the timer) records nothing instead of raising a
        # TypeError on the None arithmetic
        if self._t0 is None:
            return
        self.times.append(time.perf_counter() - self._t0)
        self._t0 = None

    @property
    def mean(self) -> float:
        return sum(self.times) / max(len(self.times), 1)


class RetraceGuard:
    """Warn when a wrapped (jitted) step function sees a NEW abstract
    argument signature after its first call — under ``jax.jit`` every new
    shape/dtype/treedef signature forces a full XLA retrace, and a
    retrace mid-epoch (shape churn from a sloppy loader, a dtype flip, a
    non-dropped last batch) is the silent MFU killer: minutes of compile
    amortized over zero extra steps.

    Signatures are computed host-side from leaf shapes/dtypes (python
    scalars hash by type, matching jit's weak-typed cache key), so the
    guard costs a tree-flatten per call and never touches the device.
    Deliberate shape buckets (multiscale training) warn once per new
    bucket and then stay quiet.
    """

    def __init__(self, fn: Callable, name: str = "step",
                 logger=None, max_warnings: int = 8,
                 on_retrace: Optional[Callable[[Dict], None]] = None):
        self.fn = fn
        self.name = name
        self.logger = logger
        self.max_warnings = max_warnings
        # observability hook: called with {name, retraces, n_signatures}
        # on every retrace (the Trainer routes it into the flight
        # recorder ring) — fires even past the max_warnings cap
        self.on_retrace = on_retrace
        self._sigs: set = set()
        self.retraces = 0          # new signatures seen after the first

    @property
    def n_signatures(self) -> int:
        return len(self._sigs)

    @staticmethod
    def _leaf_sig(x):
        shape = getattr(x, "shape", None)
        dtype = getattr(x, "dtype", None)
        if shape is None and dtype is None:
            return type(x).__name__
        return (tuple(shape) if shape is not None else None, str(dtype))

    def _signature(self, args, kwargs):
        leaves, treedef = jax.tree.flatten((args, kwargs))
        return (str(treedef), tuple(self._leaf_sig(l) for l in leaves))

    def __call__(self, *args, **kwargs):
        sig = self._signature(args, kwargs)
        if sig not in self._sigs:
            self._sigs.add(sig)
            if len(self._sigs) > 1:
                self.retraces += 1
                if self.on_retrace is not None:
                    self.on_retrace({"name": self.name,
                                     "retraces": self.retraces,
                                     "n_signatures": len(self._sigs)})
                if self.retraces <= self.max_warnings:
                    msg = (f"{self.name}: argument signature changed "
                           f"({len(self._sigs)} distinct signatures seen) "
                           "— each new shape/dtype forces an XLA retrace; "
                           "pad or bucket inputs to fixed shapes")
                    warnings.warn(msg, RuntimeWarning, stacklevel=2)
                    if self.logger is not None:
                        self.logger.warning(msg)
        return self.fn(*args, **kwargs)


def cost_analysis_dict(compiled) -> Dict[str, float]:
    """``Compiled.cost_analysis()`` returns a dict in recent JAX and a
    one-element list of dicts in older releases; normalize to a dict."""
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return cost or {}


def compiled_flops(fn: Callable, *args) -> float:
    from ..obs.xla import tracked_compile   # lazy: obs imports this module
    compiled = tracked_compile(jax.jit(fn).lower(*args),
                               getattr(fn, "__name__", "flops_probe"))
    return float(cost_analysis_dict(compiled).get("flops", 0.0))


def measure_mfu(step_fn: Callable, args: tuple, n_steps: int = 10,
                sync_fetch: Callable = None) -> Dict[str, float]:
    """Run ``step_fn(*args)`` n times, sync by fetching a scalar from the
    output (sync_fetch(output) -> float), report step time + MFU."""
    flops = compiled_flops(step_fn, *args)
    out = step_fn(*args)
    if sync_fetch:
        sync_fetch(out)
    t0 = time.perf_counter()
    for _ in range(n_steps):
        out = step_fn(*args)
    if sync_fetch:
        sync_fetch(out)
    dt = (time.perf_counter() - t0) / n_steps
    peak = device_peak_flops()
    return {"step_time_s": dt, "flops_per_step": flops,
            "mfu": flops / dt / peak if flops else 0.0,
            "peak_flops": peak}


@contextlib.contextmanager
def trace(logdir: str):
    """jax.profiler trace for TensorBoard's profile plugin."""
    import os
    os.makedirs(logdir, exist_ok=True)   # fresh run dirs must not fail
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def model_info(model, *example_args, train: bool = False,
               tabulate: bool = False, **example_kw) -> Dict[str, float]:
    """Params / FLOPs / activation summary for a flax model — the
    get_model_info / model_info surface (yolov5 utils/torch_utils.py:236,
    YOLOX yolox/utils/model_utils.py, vision_transformer/flops.py).

    FLOPs come from XLA's compiled cost analysis of the forward (so
    fusion is reflected, like thop/fvcore count the traced graph). Set
    ``tabulate=True`` to also return flax's per-layer table string."""
    import jax.numpy as jnp
    import numpy as np

    variables = model.init(jax.random.key(0), *example_args,
                           train=train, **example_kw)
    n_params = sum(int(np.prod(np.shape(l)))
                   for l in jax.tree.leaves(variables["params"]))
    flops = compiled_flops(
        lambda v, *a: model.apply(v, *a, train=train, **example_kw),
        variables, *example_args)
    info: Dict[str, float] = {
        "params_m": n_params / 1e6,
        "gflops": flops / 1e9,
    }
    if tabulate:
        import flax.linen as nn
        info["table"] = nn.tabulate(
            model, jax.random.key(0),
            compute_flops=False, compute_vjp_flops=False)(
            *example_args, train=train, **example_kw)
    return info
