"""Feature-map / kernel / prediction visualization.

Surface of others/visual_weight_feature_map_test
(visual_feature_map.py:66 truncated-model per-channel plots,
visual_kernel_weight.py:23 conv-kernel grids), tensorboard_test's figure
helpers, and the detection demo drawing (yolov5 utils/plots.py). Pure
numpy → (H, W, 3) uint8 images that feed TensorBoardWriter.add_image or
PIL. Capturing intermediates uses flax's capture_intermediates — no
forward hooks needed.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy as np


def _to_grid(tiles: np.ndarray, pad: int = 1) -> np.ndarray:
    """(N, H, W) → one (rows·H, cols·W) grid image, normalized per tile."""
    n, h, w = tiles.shape
    cols = int(np.ceil(np.sqrt(n)))
    rows = int(np.ceil(n / cols))
    canvas = np.zeros((rows * (h + pad), cols * (w + pad)), np.float32)
    for i in range(n):
        t = tiles[i]
        lo, hi = t.min(), t.max()
        t = (t - lo) / (hi - lo + 1e-9)
        r, c = divmod(i, cols)
        canvas[r * (h + pad):r * (h + pad) + h,
               c * (w + pad):c * (w + pad) + w] = t
    return canvas


def feature_map_grid(features: np.ndarray, max_channels: int = 64
                     ) -> np.ndarray:
    """(H, W, C) activation → uint8 grid of the first C channels."""
    f = np.asarray(features, np.float32)
    f = np.moveaxis(f, -1, 0)[:max_channels]
    return (255 * _to_grid(f)).astype(np.uint8)


def kernel_grid(kernel: np.ndarray, max_kernels: int = 64) -> np.ndarray:
    """(kh, kw, cin, cout) conv kernel → uint8 grid (input-channel mean)."""
    k = np.asarray(kernel, np.float32).mean(axis=2)     # (kh, kw, cout)
    k = np.moveaxis(k, -1, 0)[:max_kernels]
    return (255 * _to_grid(k, pad=1)).astype(np.uint8)


def capture_feature_maps(model, variables, x, filter_fn=None
                         ) -> Dict[str, np.ndarray]:
    """Run the model capturing every module's output (the truncated-model
    forward of visual_feature_map.py, but via capture_intermediates)."""
    _, mods = model.apply(variables, x, train=False,
                          capture_intermediates=filter_fn or True)
    flat = {}

    def walk(tree, prefix=""):
        for k, v in tree.items():
            path = f"{prefix}/{k}" if prefix else k
            if isinstance(v, dict):
                walk(v, path)
            else:
                arr = v[0] if isinstance(v, tuple) else v
                flat[path] = np.asarray(arr)
    walk(mods["intermediates"])
    return flat


def draw_boxes(image: np.ndarray, boxes: np.ndarray,
               labels: Optional[Sequence] = None,
               scores: Optional[np.ndarray] = None,
               color: Tuple[int, int, int] = (0, 255, 0),
               thickness: int = 2) -> np.ndarray:
    """Draw xyxy boxes on a uint8 image (detection demo rendering)."""
    img = np.ascontiguousarray(np.asarray(image, np.uint8).copy())
    for i, box in enumerate(np.asarray(boxes)):
        x1, y1, x2, y2 = (int(round(float(v))) for v in box)
        x1, x2 = np.clip([x1, x2], 0, img.shape[1] - 1)
        y1, y2 = np.clip([y1, y2], 0, img.shape[0] - 1)
        img[y1:y1 + thickness, x1:x2] = color
        img[max(y2 - thickness, 0):y2, x1:x2] = color
        img[y1:y2, x1:x1 + thickness] = color
        img[y1:y2, max(x2 - thickness, 0):x2] = color
    return img


def confusion_matrix_figure(matrix: np.ndarray,
                            class_names: Sequence[str]):
    """matplotlib figure of a confusion matrix (tensorboard_test
    add_figure tour); returns None when matplotlib is missing."""
    try:
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except ImportError:
        return None
    fig, ax = plt.subplots(figsize=(6, 6))
    ax.imshow(matrix, cmap="Blues")
    ax.set_xticks(range(len(class_names)), class_names, rotation=45)
    ax.set_yticks(range(len(class_names)), class_names)
    ax.set_xlabel("predicted")
    ax.set_ylabel("true")
    for i in range(len(class_names)):
        for j in range(len(class_names)):
            ax.text(j, i, f"{matrix[i, j]:.0f}", ha="center", va="center")
    fig.tight_layout()
    return fig


def pr_curve_figure(curves):
    """Overlay per-class PR curves (yolov5 utils/metrics.py plot_pr_curve
    surface). ``curves``: {name: {"precision", "recall", "ap"}} as
    produced by evaluation.metrics.precision_recall_curve. Returns a
    matplotlib figure or None."""
    try:
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except ImportError:
        return None
    fig, ax = plt.subplots(figsize=(6, 6))
    for name, c in curves.items():
        ax.plot(c["recall"], c["precision"],
                label=f"{name} AP={c['ap']:.3f}")
    ax.set_xlabel("recall")
    ax.set_ylabel("precision")
    ax.set_xlim(0, 1)
    ax.set_ylim(0, 1.05)
    ax.legend(loc="lower left", fontsize=8)
    fig.tight_layout()
    return fig


def embedding_projection_figure(embeddings: np.ndarray,
                                labels: Sequence[int],
                                method: str = "pca"):
    """2-D scatter of embeddings colored by label — the SupCon t-SNE.py
    visualization surface. method: "pca" (no extra deps) or "tsne"
    (sklearn, falling back to PCA if unavailable). Returns a matplotlib
    figure or None."""
    try:
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except ImportError:
        return None
    x = np.asarray(embeddings, np.float64)
    proj = None
    if method == "tsne" and len(x) >= 5:
        try:
            from sklearn.manifold import TSNE
            proj = TSNE(n_components=2, init="pca",
                        perplexity=min(30.0, max(2.0, len(x) / 4 - 1))
                        ).fit_transform(x)
        except (ImportError, ValueError):   # no sklearn / tiny n_samples
            proj = None
    if method == "tsne" and proj is None:
        method = "pca"
    if proj is None:
        x = x - x.mean(0)
        _, _, vt = np.linalg.svd(x, full_matrices=False)
        proj = x @ vt[:2].T
    fig, ax = plt.subplots(figsize=(6, 6))
    sc = ax.scatter(proj[:, 0], proj[:, 1], c=np.asarray(labels),
                    cmap="tab10", s=12)
    fig.colorbar(sc, ax=ax, label="class")
    ax.set_title(f"embedding projection ({method.upper()})")
    fig.tight_layout()
    return fig
