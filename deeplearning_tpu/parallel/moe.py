"""Mixture-of-Experts MLP with expert parallelism over the mesh.

Surface of classification/swin_transformer/models/swin_transformer_moe.py
(:36 MoEMlp → tutel moe_layer with top-k cosine router, capacity factor
:273, aux load-balance loss; :705 global experts = local × world_size).
TPU-native design: the tutel all-to-all dispatch becomes einsum dispatch/
combine tensors under GSPMD — expert parameters carry a leading E axis
sharded over the ``expert`` mesh axis, tokens are sharded over ``data``,
and XLA inserts the all-to-alls from the shardings. Capacity-limited
top-k routing with dropped-token passthrough, fully static shapes.
"""

from __future__ import annotations

import math
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from .mesh import EXPERT_AXIS
from .sharding import Rules
from jax.sharding import PartitionSpec as P

# sharding rules for MoE params: expert-major leading axis
MOE_RULES: Rules = (
    (r"experts/(fc1|fc2)_kernel$", P(EXPERT_AXIS, None, None)),
    (r"experts/(fc1|fc2)_bias$", P(EXPERT_AXIS, None)),
)


def load_balance_loss(router_probs: jax.Array, expert_mask: jax.Array
                      ) -> jax.Array:
    """Switch-style aux loss: E · dot(mean prob per expert, fraction of
    tokens per expert)."""
    e = router_probs.shape[-1]
    density = jnp.mean(expert_mask, axis=0)          # tokens fraction
    density_proxy = jnp.mean(router_probs, axis=0)   # prob mass
    return e * jnp.sum(density * density_proxy)


class ExpertMlp(nn.Module):
    """E parallel MLPs as batched params (leading E axis → shardable)."""
    num_experts: int
    hidden: int
    out_dim: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):            # x: (E, C, D)
        d = x.shape[-1]
        k1 = self.param("fc1_kernel", nn.initializers.lecun_normal(),
                        (self.num_experts, d, self.hidden), jnp.float32)
        b1 = self.param("fc1_bias", nn.initializers.zeros,
                        (self.num_experts, self.hidden), jnp.float32)
        k2 = self.param("fc2_kernel", nn.initializers.lecun_normal(),
                        (self.num_experts, self.hidden, self.out_dim),
                        jnp.float32)
        b2 = self.param("fc2_bias", nn.initializers.zeros,
                        (self.num_experts, self.out_dim), jnp.float32)
        y = jnp.einsum("ecd,edh->ech", x, k1.astype(x.dtype)) \
            + b1[:, None].astype(x.dtype)
        y = nn.gelu(y, approximate=True)
        y = jnp.einsum("ech,eho->eco", y, k2.astype(x.dtype)) \
            + b2[:, None].astype(x.dtype)
        return y


class MoEMlp(nn.Module):
    """Drop-in MLP replacement with top-k capacity-limited routing.

    Returns (output, aux_loss). Dropped tokens pass through as zeros plus
    the residual connection outside handles them (swin-moe behavior).
    """
    num_experts: int = 8
    top_k: int = 1
    capacity_factor: float = 1.25
    hidden_ratio: float = 4.0
    aux_weight: float = 0.01
    drop: float = 0.0
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array, deterministic: bool = True
                 ) -> Tuple[jax.Array, jax.Array]:
        b, n, d = x.shape
        t = b * n
        tokens = x.reshape(t, d)
        e = self.num_experts
        capacity = max(int(t / e * self.capacity_factor * self.top_k), 1)

        logits = nn.Dense(e, dtype=jnp.float32, name="router")(
            tokens.astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1)

        # Scatter/gather dispatch — O(T·d + E·C·d) memory. The previous
        # dense (T, E, C) combine/dispatch tensors are O(T²·d) because
        # C ∝ T/E: at 56px·batch-64 (T=50k) that is terabytes — the
        # round-4 swin_moe_cls_hard56 rc=-9 OOM. Routing semantics are
        # unchanged: top-k argmax rounds, token-order capacity ranks,
        # later rounds offset by earlier slot usage.
        aux = jnp.zeros((), jnp.float32)
        remaining = probs
        used = jnp.zeros((e,), jnp.float32)   # slots taken in prior rounds
        gate_sum = jnp.zeros((t,), jnp.float32)  # selected in-capacity mass
        rounds = []                           # (choice, pos_idx, gate, keep)
        n_assigned = jnp.zeros((), jnp.float32)
        per_expert = jnp.zeros((e,), jnp.float32)
        for k in range(self.top_k):
            choice = jnp.argmax(remaining, axis=-1)              # (T,)
            gate = jnp.take_along_axis(remaining, choice[:, None],
                                       axis=-1)[:, 0]
            mask = jax.nn.one_hot(choice, e)                     # (T, E)
            if k == 0:
                aux = load_balance_loss(probs, mask)
            # position within expert (capacity rank), in token order,
            # OFFSET by slots consumed in earlier top-k rounds so first-
            # and second-choice tokens never collide on a slot
            pos = jnp.sum((jnp.cumsum(mask, axis=0) - 1.0 + used[None, :])
                          * mask, axis=-1)                       # (T,)
            keep = pos < capacity                                # (T,)
            pos_idx = jnp.clip(pos.astype(jnp.int32), 0, capacity - 1)
            rounds.append((choice, pos_idx, gate, keep))
            gate_sum = gate_sum + gate * keep
            n_assigned = n_assigned + jnp.sum(keep, dtype=jnp.float32)
            per_expert = per_expert + jnp.sum(
                mask * keep[:, None], axis=0, dtype=jnp.float32)
            used = used + jnp.sum(mask, axis=0)
            remaining = remaining * (1.0 - mask)

        # observability: the quantities that actually go wrong in MoE
        # training (swin_transformer_moe.py:273 tunes capacity_factor
        # against exactly these) — sown per layer, harvested by the
        # trainer into step metrics
        self.sow("moe_metrics", "drop_rate",
                 1.0 - n_assigned / (t * self.top_k))
        self.sow("moe_metrics", "capacity_util",
                 n_assigned / (e * capacity))
        self.sow("moe_metrics", "max_expert_load",
                 jnp.max(per_expert) / jnp.maximum(
                     jnp.mean(per_expert), 1.0))

        # build the (E, C) slot→token table by scatter (dropped tokens
        # write to a dummy expert row e), then gather tokens into
        # (E, C, d) expert inputs; empty slots stay zero like the dense
        # dispatch einsum produced
        slot_token = jnp.zeros((e + 1, capacity), jnp.int32)
        slot_filled = jnp.zeros((e + 1, capacity), tokens.dtype)
        for choice, pos_idx, gate, keep in rounds:
            safe_e = jnp.where(keep, choice, e)
            slot_token = slot_token.at[safe_e, pos_idx].set(
                jnp.arange(t, dtype=jnp.int32))
            slot_filled = slot_filled.at[safe_e, pos_idx].set(1.0)
        expert_in = tokens[slot_token[:e]] * slot_filled[:e, :, None]
        expert_out = ExpertMlp(e, int(d * self.hidden_ratio), d,
                               self.dtype, name="experts")(expert_in)

        # combine: each token gathers its slot's expert output, weighted
        # by its gate (normalized over the selected in-capacity mass for
        # top-k > 1, the tutel/swin-moe convention)
        out = jnp.zeros((t, d), expert_out.dtype)
        for choice, pos_idx, gate, keep in rounds:
            w = gate * keep
            if self.top_k > 1:
                w = w / jnp.maximum(gate_sum, 1e-9)
            out = out + expert_out[choice, pos_idx] \
                * w[:, None].astype(expert_out.dtype)
        out = nn.Dropout(self.drop, deterministic=deterministic)(out)
        return out.reshape(b, n, d), self.aux_weight * aux
