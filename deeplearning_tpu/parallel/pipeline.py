"""Pipeline parallelism: GPipe-style microbatch pipeline over a mesh axis.

A capability beyond the reference (SURVEY.md §2.9: PP absent). Design:
stage parameters carry a leading S (stage) axis sharded over the ``pipe``
mesh axis; the schedule runs inside shard_map — each device applies its
stage to its current microbatch then ppermutes activations to the next
device. With M microbatches and S stages the loop runs S+M-1 ticks
(bubble fraction (S-1)/(S+M-1)), all under one jit.

The stage function must be shape-preserving (same activation shape in and
out, the usual transformer-block setting), which keeps the rotating
buffer static-shaped.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ._compat import SHARD_MAP_NATIVE, shard_map

PIPE_AXIS = "model"     # reuse the model axis for stages by default


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,           # pytree with leading S axis on leaves
    x: jax.Array,                # (M, micro_batch, ...) microbatches
    mesh: Mesh,
    axis_name: str = PIPE_AXIS,
) -> jax.Array:
    """Run x through S pipelined stages; returns (M, micro_batch, ...).

    stage_fn(params_slice, activation) -> activation, applied by every
    device to the microbatch currently resident on it.
    """
    return _pipeline_schedule(stage_fn, stage_params, x, mesh, axis_name)


def _pipeline_schedule(
    apply_stage: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,           # pytree with leading S axis on leaves
    x: jax.Array,                # (M, micro_batch, ...) microbatches
    mesh: Mesh,
    axis_name: str,
) -> jax.Array:
    """The shared GPipe fill-drain schedule: apply_stage runs on each
    device with its de-stacked param slice and the resident microbatch,
    then activations ppermute one stage forward."""
    s = mesh.shape[axis_name]
    m = x.shape[0]
    if m % s != 0:
        raise ValueError(
            f"microbatches ({m}) must be divisible by pipeline stages "
            f"({s}): the (M,...) input is sharded P({axis_name!r}) for "
            "storage, so a non-multiple silently truncates outputs")

    # On 0.4.x JAX, a traced-intermediate operand whose in_spec shards one
    # axis of a multi-axis mesh while leaving another unmentioned reaches
    # the shard_map body summed over the unmentioned axis (partitioner
    # bug at the jit->manual boundary, observed on 0.4.37; fully
    # replicated P() operands arrive intact). So on legacy JAX every
    # operand enters replicated and the body slices out its own stage;
    # on modern JAX params/microbatches enter sharded as designed.
    if SHARD_MAP_NATIVE:
        param_specs = jax.tree.map(lambda _: P(axis_name), stage_params)
        x_spec = P(axis_name)
    else:
        param_specs = jax.tree.map(lambda _: P(), stage_params)
        x_spec = P()

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(param_specs, x_spec),
        out_specs=P(axis_name), check_vma=SHARD_MAP_NATIVE)
    def run(params, xs):
        idx = jax.lax.axis_index(axis_name)
        if SHARD_MAP_NATIVE:
            # params: leaves (1, ...) — this device's stage; xs
            # (ceil(M/S), ...) microbatches sharded over the axis for
            # storage; gather to a local queue (M is small; activations
            # are microbatch-sized)
            params = jax.tree.map(lambda p: p[0], params)
            all_x = jax.lax.all_gather(xs, axis_name, tiled=True)
        else:
            # legacy path: everything arrived replicated; slice this
            # device's stage (S x params resident per device — the
            # workaround's cost)
            params = jax.tree.map(
                lambda p: jax.lax.dynamic_index_in_dim(
                    p, idx, 0, keepdims=False), params)
            all_x = xs
        n_ticks = s + m - 1
        perm = [(i, (i + 1) % s) for i in range(s)]

        def tick(carry, t):
            buf, outputs = carry
            # stage 0 ingests microbatch t (if any) — other stages use buf
            feed = jnp.where(t < m, t, 0)
            incoming = jnp.where(idx == 0, 1.0, 0.0)
            inject = all_x[feed] * incoming + buf * (1 - incoming)
            y = apply_stage(params, inject)
            # device s-1's output at tick t is microbatch t-(s-1)
            out_slot = t - (s - 1)
            is_last = idx == s - 1
            valid = (out_slot >= 0) & (out_slot < m) & is_last
            # select, not lax.cond: both arms always run (the update is
            # microbatch-sized, so this costs nothing) and the replication
            # checker tracks plain selects on every JAX release, whereas
            # 0.4.x's pre-vma checker rejects device-varying cond here
            updated = jax.lax.dynamic_update_index_in_dim(
                outputs, y, jnp.maximum(out_slot, 0), 0)
            outputs = jnp.where(valid, updated, outputs)
            # rotate activations forward one stage
            buf = jax.lax.ppermute(y, axis_name, perm)
            return (buf, outputs), None

        buf0 = jnp.zeros_like(all_x[0])
        outputs0 = jnp.zeros_like(all_x)
        # scan, not fori_loop: the trip count is static and scan is
        # reverse-mode differentiable, so the SAME schedule serves the
        # training step (grads flow back through ppermute/psum)
        (_, outputs), _ = jax.lax.scan(
            tick, (buf0, outputs0), jnp.arange(n_ticks))
        # outputs live on the last stage; share them back to all devices
        outputs = jax.lax.psum(outputs, axis_name)
        # return this device's storage shard
        per_dev = m // s
        return jax.lax.dynamic_slice_in_dim(outputs, idx * per_dev,
                                            per_dev, 0)

    return run(stage_params, x)


def stack_stage_params(params_list) -> Any:
    """[stage0_params, stage1_params, ...] (same structure) → stacked
    pytree with leading S axis, ready for P('model') sharding."""
    return jax.tree.map(lambda *ps: jnp.stack(ps), *params_list)


# -------------------------------------------------- heterogeneous stages

def pack_stages(params_list) -> Tuple[jax.Array, list]:
    """Pack per-stage param pytrees of DIFFERENT structures into one
    (S, L) f32 array (rows zero-padded to the longest stage) plus
    per-stage unpack closures. This is what lets a pipeline span e.g.
    ResNet stages whose block structures differ: the packed rows all
    have the same shape, so they shard over the pipe axis like any
    stacked pytree, and each device reconstitutes its own stage's
    structure locally."""
    import numpy as np

    flats, unpackers = [], []
    for p in params_list:
        leaves, treedef = jax.tree.flatten(p)
        shapes = [l.shape for l in leaves]
        dtypes = [l.dtype for l in leaves]
        for d in dtypes:
            # the packed row is f32; wider/integer leaves would silently
            # lose bits on the round trip
            if not (jnp.issubdtype(d, jnp.floating)
                    and jnp.dtype(d).itemsize <= 4):
                raise TypeError(
                    f"pack_stages supports float leaves of <=32 bits, got "
                    f"{d}; keep non-float state out of the packed stage "
                    f"params")
        sizes = [int(np.prod(s)) if s else 1 for s in shapes]
        offs = np.concatenate([[0], np.cumsum(sizes)]).astype(int)
        flat = (jnp.concatenate([jnp.ravel(l).astype(jnp.float32)
                                 for l in leaves])
                if leaves else jnp.zeros((0,), jnp.float32))
        flats.append(flat)

        def make_unpack(treedef=treedef, shapes=shapes, dtypes=dtypes,
                        offs=offs):
            def unpack(vec: jax.Array):
                ls = [vec[offs[i]:offs[i + 1]].reshape(shapes[i])
                      .astype(dtypes[i]) for i in range(len(shapes))]
                return jax.tree.unflatten(treedef, ls)
            return unpack
        unpackers.append(make_unpack())
    length = max((f.shape[0] for f in flats), default=1)
    packed = jnp.stack([jnp.pad(f, (0, length - f.shape[0]))
                        for f in flats])
    return packed, unpackers


def pipeline_apply_heterogeneous(
    stage_fns,                   # [fn_i(params_i, act) -> act] per stage
    params_list,                 # per-stage pytrees, any structures
    x: jax.Array,                # (M, micro_batch, ...) microbatches
    mesh: Mesh,
    axis_name: str = PIPE_AXIS,
) -> jax.Array:
    """GPipe schedule over stages with different parameter structures.

    Stage params are packed (pack_stages) so every device's shard has
    the same shape; each device dispatches to ITS stage's function via
    ``lax.switch`` on its mesh coordinate (every branch is compiled
    once, the device executes only its own — the SPMD analog of
    per-rank module code in torch pipelines). Activations must still be
    shape-uniform across stage boundaries (the ppermute buffer is
    static); insert adapter layers at stage edges if a model changes
    activation shape.
    """
    s = mesh.shape[axis_name]
    if len(stage_fns) != s or len(params_list) != s:
        raise ValueError(f"need exactly {s} stages for axis "
                         f"{axis_name!r}, got {len(stage_fns)}")
    packed, unpackers = pack_stages(params_list)
    branches = [
        (lambda row, act, f=fn, u=unpack: f(u(row), act))
        for fn, unpack in zip(stage_fns, unpackers)]

    def dispatch(row, act):
        idx = jax.lax.axis_index(axis_name)
        return jax.lax.switch(idx, branches, row, act)

    return _pipeline_schedule(dispatch, packed, x, mesh, axis_name)
