"""User-facing pipeline-parallel TRAINING for the ViT family.

Makes GPipe pipelining (parallel/pipeline.py) a first-class training
option — ``tools/train.py train.pipeline_stages=S`` — the way YOLOX's
launch() makes its parallelism reachable from the CLI
(detection/YOLOX/yolox/core/launch.py:39). The reference has no pipeline
parallelism at all (SURVEY §2.9: PP absent); this is a capability row
beyond it, now with gradients end to end:

- ViT params are split into ``outer`` (patch embed, cls/pos, final norm,
  head — replicated) and ``stages`` (the D transformer blocks stacked
  into S shape-uniform stages, sharded P('model') on the leading axis);
- the forward runs embed → GPipe schedule over microbatches → head; the
  schedule is a lax.scan of ppermute ticks, so jax.grad flows back
  through the whole pipeline (reverse of a ring rotation is a ring
  rotation);
- one optimizer step updates outer + all stages together.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .pipeline import pipeline_apply, stack_stage_params

PIPE_AXIS = "model"


def split_vit_params(params: Dict[str, Any], num_stages: int
                     ) -> Tuple[Dict[str, Any], Any, int]:
    """ViT param tree → (outer_params, stacked stage params, blocks/stage).

    Stage j holds blocks [j*K, (j+1)*K); leaves carry a leading S axis
    ready for P('model') sharding."""
    block_keys = sorted((k for k in params if k.startswith("blocks_")),
                        key=lambda k: int(k.split("_")[1]))
    depth = len(block_keys)
    if depth == 0:
        raise ValueError("pipeline_stages needs a ViT-style model with "
                         "blocks_<i> params")
    if depth % num_stages:
        raise ValueError(f"depth {depth} not divisible by "
                         f"pipeline_stages={num_stages}")
    k_per = depth // num_stages
    per_stage = [
        {f"sub{k}": params[f"blocks_{j * k_per + k}"]
         for k in range(k_per)}
        for j in range(num_stages)]
    outer = {k: v for k, v in params.items() if not k.startswith("blocks_")}
    return outer, stack_stage_params(per_stage), k_per


def _embed(model, outer: Dict[str, Any], images: jax.Array) -> jax.Array:
    """patch embed + cls token + pos embed (VisionTransformer.__call__
    pre-block section), applied with the ORIGINAL param subtrees."""
    from ..models.classification.vit import PatchEmbed

    x = PatchEmbed(model.patch_size, model.embed_dim, model.dtype).apply(
        {"params": outer["patch_embed"]}, images)
    b, n, c = x.shape
    cls = jnp.broadcast_to(outer["cls_token"].astype(x.dtype), (b, 1, c))
    x = jnp.concatenate([cls, x], axis=1)
    return x + outer["pos_embed"].astype(x.dtype)


def _head(model, outer: Dict[str, Any], x: jax.Array) -> jax.Array:
    import flax.linen as nn

    x = nn.LayerNorm(dtype=model.dtype).apply(
        {"params": outer["norm"]}, x)
    x = x[:, 0]
    if "pre_logits" in outer:
        x = nn.tanh(nn.Dense(model.representation_size,
                             dtype=model.dtype).apply(
            {"params": outer["pre_logits"]}, x))
    x = nn.Dense(model.num_classes, dtype=model.dtype).apply(
        {"params": outer["head"]}, x)
    return x.astype(jnp.float32)


def make_vit_pipeline_forward(model, mesh: Mesh, num_stages: int,
                              k_per_stage: int, microbatches: int,
                              axis_name: str = PIPE_AXIS) -> Callable:
    """(params={'outer','stages'}, images) -> logits, pipelined."""
    from ..models.classification.vit import Block

    # stochastic regularizers would need rng plumbing through the
    # shard_map schedule (and per-block drop-path rates per stage slice);
    # refuse loudly rather than silently train without them
    if (model.drop_rate or model.attn_drop_rate or model.drop_path_rate):
        raise ValueError(
            "pipeline_stages currently requires drop_rate = "
            "attn_drop_rate = drop_path_rate = 0 on the model; the "
            "schedule runs deterministically")
    block = Block(model.num_heads, model.mlp_ratio, model.qkv_bias,
                  dtype=model.dtype, attn_fn=model.attn_fn)

    def stage_fn(stage_params, act):
        for k in range(k_per_stage):
            act = block.apply({"params": stage_params[f"sub{k}"]}, act)
        return act

    def forward(params, images):
        x = _embed(model, params["outer"], images)
        b = x.shape[0]
        if b % microbatches:
            raise ValueError(f"batch {b} not divisible by "
                             f"microbatches={microbatches}")
        acts = x.reshape(microbatches, b // microbatches, *x.shape[1:])
        acts = pipeline_apply(stage_fn, params["stages"], acts, mesh,
                              axis_name)
        return _head(model, params["outer"], acts.reshape(b, *x.shape[1:]))

    return forward


def make_pipeline_train_step(model, mesh: Mesh, tx,
                             num_stages: int, k_per_stage: int,
                             microbatches: int,
                             label_smoothing: float = 0.0,
                             axis_name: str = PIPE_AXIS):
    """(train_step, eval_step) over a TrainState whose params are
    {'outer': replicated, 'stages': P('model')-sharded stack}."""
    forward = make_vit_pipeline_forward(model, mesh, num_stages,
                                        k_per_stage, microbatches,
                                        axis_name)

    def loss_fn(params, batch):
        logits = forward(params, batch["image"])
        labels = batch["label"]
        if label_smoothing > 0:
            n = logits.shape[-1]
            soft = optax.smooth_labels(jax.nn.one_hot(labels, n),
                                       label_smoothing)
            loss = optax.softmax_cross_entropy(logits, soft).mean()
        else:
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, labels).mean()
        acc = (logits.argmax(-1) == labels).mean()
        return loss, acc

    def train_step(state, batch, rng):
        del rng
        (loss, acc), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params, batch)
        state = state.apply_gradients(grads)
        return state, {"loss": loss, "accuracy": acc}

    def eval_step(state, batch):
        logits = forward(state.params, batch["image"])
        # count-style metrics: the Trainer divides by "count" at the end,
        # turning this into top-1 accuracy — named "top1" so the Trainer's
        # default best_metric tracks pipeline runs too
        from ..evaluation.metrics import topk_correct
        return topk_correct(logits, batch["label"], ks=(1,))

    return (jax.jit(train_step, donate_argnums=(0,)), jax.jit(eval_step))


def shard_pipeline_state(state, mesh: Mesh, axis_name: str = PIPE_AXIS):
    """Place 'stages' leaves P(axis_name) on their leading axis, replicate
    everything else (opt_state mirrors params via tree prefix match)."""
    def spec_for(path_has_stages: bool):
        return NamedSharding(mesh, P(axis_name)) if path_has_stages \
            else NamedSharding(mesh, P())

    def place(tree):
        def go(path, leaf):
            has_stages = any(getattr(p, "key", None) == "stages"
                             for p in path)
            return jax.device_put(leaf, spec_for(has_stages))
        return jax.tree_util.tree_map_with_path(go, tree)

    return state.replace(params=place(state.params),
                         opt_state=place(state.opt_state),
                         ema_params=(place(state.ema_params)
                                     if state.ema_params is not None
                                     else None))
