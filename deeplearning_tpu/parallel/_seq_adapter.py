"""Shared plumbing for sequence-parallel model ``attn_fn`` adapters.

Both SP flavors (ring, Ulysses) expose the zoo's (B, N, H, D) attention
signature through the same adapter: transpose to (B, H, N, D), zero-pad
the token dim to a multiple of the ``seq`` axis, run the shard_mapped
attention, slice and transpose back. One copy here so the contract
(dropout guard, flash divisibility rule, padding policy, batch-dim
sharding) cannot diverge between the two.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def batch_axes(mesh: Mesh) -> Optional[Tuple[str, ...]]:
    """The mesh axes the batch dim shards over inside the adapters'
    shard_maps — the SAME set sharding.batch_spec uses (('data',
    'fsdp') when present). Without them the activations would be
    replicated across those axes and every layer would all-gather the
    global batch."""
    axes = tuple(a for a in ("data", "fsdp") if a in mesh.axis_names)
    return axes or None


def batch_extent(mesh: Mesh, axes: Optional[Tuple[str, ...]]) -> int:
    ext = 1
    for a in axes or ():
        ext *= mesh.shape[a]
    return ext


def seq_attn_adapter(mesh: Mesh, axis_size: int, axis_name: str,
                     flavor: str, use_flash: bool,
                     sharded_call: Callable) -> Callable:
    """Wrap ``sharded_call(qt, kt, vt, n_valid, sharded) ->
    (B, H, Npad, D)`` into the models' attn_fn signature. ``axis_size`` is the seq-axis
    extent. The batch dim shards over the mesh's batch axes when it
    divides them (training batches do); otherwise it stays replicated —
    the ``sharded`` flag passed to ``sharded_call`` says which, so the
    flavor's shard_map spec always matches the boundary pin.

    The adapter PINS its boundary sharding to batch-axes-only (sequence
    replicated outside the shard_map): letting the N-over-seq sharding
    propagate into the surrounding graph reaches the patch-embed
    convolution through token reshapes, and GSPMD's spatially
    partitioned conv path miscompiles on the virtual-CPU backend
    (observed: patch_embed off by O(1) with identical inputs/params).
    The O(N²) attention itself still splits over ``seq`` inside the
    shard_map — that is the part sequence parallelism exists for; the
    elementwise inter-layer stream stays batch-sharded."""
    b_spec = NamedSharding(
        mesh, P(batch_axes(mesh), None, None, None))
    b_ext = batch_extent(mesh, batch_axes(mesh))

    def shardable(b):
        return b_ext > 1 and b % b_ext == 0

    def pin(x):
        if shardable(x.shape[0]):
            return jax.lax.with_sharding_constraint(x, b_spec)
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P(None, None, None, None)))

    def attn_fn(q, k, v, dropout_rate=0.0, deterministic=True, rng=None):
        if dropout_rate and not deterministic:
            raise NotImplementedError(
                f"{flavor} attn_fn does not support attention dropout")
        n = q.shape[1]
        n_pad = -n % axis_size
        if n_pad and use_flash:
            raise ValueError(
                f"the {axis_name} axis size ({axis_size}) must divide "
                f"N={n} for the flash {flavor} path (masking needs the "
                "lax path)")
        t = lambda x: x.transpose(0, 2, 1, 3)     # -> (B, H, N, D)
        pad = [(0, 0), (0, 0), (0, n_pad), (0, 0)]
        out = sharded_call(*(pin(jnp.pad(t(x), pad))
                             for x in (q, k, v)), n,
                           shardable(q.shape[0]))
        return t(pin(out)[:, :, :n, :])

    return attn_fn
