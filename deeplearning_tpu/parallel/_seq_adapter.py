"""Shared plumbing for sequence-parallel model ``attn_fn`` adapters.

Both SP flavors (ring, Ulysses) expose the zoo's (B, N, H, D) attention
signature through the same adapter: transpose to (B, H, N, D), zero-pad
the token dim to a multiple of the ``seq`` axis, run the shard_mapped
attention, slice and transpose back. One copy here so the contract
(dropout guard, flash divisibility rule, padding policy, batch-dim
sharding) cannot diverge between the two.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def batch_axes(mesh: Mesh) -> Optional[Tuple[str, ...]]:
    """The mesh axes the batch dim shards over inside the adapters'
    shard_maps — the SAME set sharding.batch_spec uses (('data',
    'fsdp') when present). Without them the activations would be
    replicated across those axes and every layer would all-gather the
    global batch."""
    axes = tuple(a for a in ("data", "fsdp") if a in mesh.axis_names)
    return axes or None


def batch_extent(mesh: Mesh, axes: Optional[Tuple[str, ...]]) -> int:
    ext = 1
    for a in axes or ():
        ext *= mesh.shape[a]
    return ext


def seq_attn_adapter(axis_size: int, axis_name: str, flavor: str,
                     use_flash: bool, sharded_call: Callable) -> Callable:
    """Wrap ``sharded_call(qt, kt, vt, n_valid) -> (B, H, Npad, D)``
    into the models' attn_fn signature. ``axis_size`` is the seq-axis
    extent; the batch dim must divide the mesh's batch axes (training
    batches do; build an inference mesh with data=1 otherwise)."""

    def attn_fn(q, k, v, dropout_rate=0.0, deterministic=True, rng=None):
        if dropout_rate and not deterministic:
            raise NotImplementedError(
                f"{flavor} attn_fn does not support attention dropout")
        n = q.shape[1]
        n_pad = -n % axis_size
        if n_pad and use_flash:
            raise ValueError(
                f"the {axis_name} axis size ({axis_size}) must divide "
                f"N={n} for the flash {flavor} path (masking needs the "
                "lax path)")
        t = lambda x: x.transpose(0, 2, 1, 3)     # -> (B, H, N, D)
        pad = [(0, 0), (0, 0), (0, n_pad), (0, 0)]
        out = sharded_call(*(jnp.pad(t(x), pad) for x in (q, k, v)), n)
        return t(out[:, :, :n, :])

    return attn_fn
