"""Shared plumbing for sequence-parallel model ``attn_fn`` adapters.

Both SP flavors (ring, Ulysses) expose the zoo's (B, N, H, D) attention
signature through the same adapter: transpose to (B, H, N, D), zero-pad
the token dim to a multiple of the ``seq`` axis, run the shard_mapped
attention, slice and transpose back. One copy here so the contract
(dropout guard, flash divisibility rule, padding policy, batch-dim
sharding) cannot diverge between the two.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def batch_axis(mesh: Mesh) -> Optional[str]:
    """The mesh axis the batch dim shards over inside the adapters'
    shard_maps — without it the activations would be replicated across
    ``data`` and every layer would all-gather the global batch."""
    return "data" if "data" in mesh.axis_names else None


def seq_attn_adapter(axis_size: int, flavor: str, use_flash: bool,
                     sharded_call: Callable) -> Callable:
    """Wrap ``sharded_call(qt, kt, vt, n_valid) -> (B, H, Npad, D)``
    into the models' attn_fn signature. ``axis_size`` is the seq-axis
    extent; the batch dim must divide the mesh's data axis (training
    batches do; build an inference mesh with data=1 otherwise)."""

    def attn_fn(q, k, v, dropout_rate=0.0, deterministic=True, rng=None):
        if dropout_rate and not deterministic:
            raise NotImplementedError(
                f"{flavor} attn_fn does not support attention dropout")
        n = q.shape[1]
        n_pad = -n % axis_size
        if n_pad and use_flash:
            raise ValueError(
                f"N={n} must divide the seq axis ({axis_size}) for the "
                f"flash {flavor} path (masking needs the lax path)")
        t = lambda x: x.transpose(0, 2, 1, 3)     # -> (B, H, N, D)
        pad = [(0, 0), (0, 0), (0, n_pad), (0, 0)]
        out = sharded_call(*(jnp.pad(t(x), pad) for x in (q, k, v)), n)
        return t(out[:, :, :n, :])

    return attn_fn
