"""Ulysses sequence parallelism: all-to-all head redistribution.

The second SP flavor next to ring attention (the task's "ring attention
or all-to-all sequence/context parallelism"; neither exists in the
reference — SURVEY.md §2.9 lists SP/CP as absent). Where ring attention
rotates KV chunks P times around the ``seq`` axis, Ulysses does ONE
``lax.all_to_all`` that trades the sharded sequence dimension for a
sharded head dimension: each device then holds the FULL sequence for
H/P heads, runs any off-the-shelf attention (including the Pallas flash
kernel — and unlike the ring+flash path this stays differentiable,
since all_to_all has a transpose and the inner attention is a normal
trainable op), and a second all_to_all restores sequence sharding.

Communication: 2 all-to-alls of the activations per call (O(B·N·D·H/P)
bytes each over ICI) vs ring's P ppermutes of K/V — Ulysses wins when
heads divide the axis and N is large; ring wins when H < P or ICI
bandwidth must overlap per-chunk compute.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ._compat import axis_size as _axis_size
from .mesh import SEQ_AXIS


def _default_attention(q, k, v, sm_scale, valid_len=None):
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * sm_scale
    if valid_len is not None and valid_len < k.shape[2]:
        col = jnp.arange(k.shape[2])
        s = jnp.where(col[None, None, None, :] < valid_len, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      axis_name: str = SEQ_AXIS,
                      sm_scale: Optional[float] = None,
                      attn_fn: Optional[Callable] = None,
                      valid_len: Optional[int] = None) -> jax.Array:
    """Must run inside shard_map with ``axis_name`` bound; q/k/v are the
    device-local sequence chunks (B, H, N/P, D) with H divisible by the
    axis size. ``attn_fn`` sees (B, H/P, N, D) full-sequence blocks
    (default: softmax attention; pass the Pallas flash kernel for fused
    long-context blocks). If it accepts an ``sm_scale`` keyword the
    scale is forwarded; plain ``attn_fn(q, k, v)`` callables are allowed
    only with the default scale."""
    p_size = _axis_size(axis_name)
    b, h, nl, d = q.shape
    if h % p_size:
        raise ValueError(f"heads={h} must divide over axis size {p_size}")
    if sm_scale is None:
        sm_scale = d ** -0.5

    # seq-sharded -> head-sharded: split heads, gather sequence
    def scatter_heads(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1,
                                  concat_axis=2, tiled=True)

    def gather_heads(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=2,
                                  concat_axis=1, tiled=True)

    if attn_fn is not None and valid_len is not None \
            and valid_len < nl * p_size:
        raise ValueError(
            "valid_len masking is only implemented for the default inner "
            "attention — a custom attn_fn would silently attend padded "
            "keys. Pad N to a multiple of the axis instead.")
    qh, kh, vh = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    if attn_fn is None:
        # the gathered sequence carries any zero-padding at its global
        # tail, so a STATIC valid_len bound masks it exactly
        out = _default_attention(qh, kh, vh, sm_scale, valid_len=valid_len)
    else:
        # forward sm_scale when the fn accepts it (flash_attention does)
        # so an explicit scale is never silently dropped; plain
        # attn_fn(q, k, v) callables still work with the default scale
        import inspect
        try:
            takes_scale = "sm_scale" in inspect.signature(
                attn_fn).parameters
        except (TypeError, ValueError):
            takes_scale = False
        if not takes_scale and sm_scale != q.shape[-1] ** -0.5:
            raise ValueError(
                "explicit sm_scale given but attn_fn does not accept an "
                "sm_scale keyword — it would be silently ignored")
        out = (attn_fn(qh, kh, vh, sm_scale=sm_scale) if takes_scale
               else attn_fn(qh, kh, vh))
    return gather_heads(out.astype(q.dtype))


def make_ulysses_attention(mesh: Mesh, axis_name: str = SEQ_AXIS,
                           attn_fn: Optional[Callable] = None,
                           check_vma: bool = True):
    """shard_map-wrapped Ulysses attention: takes globally sharded
    (B, H, N, D) arrays (sequence sharded over ``axis_name``) and returns
    the same sharding. Set check_vma=False when attn_fn is a pallas_call
    (its out_shapes carry no varying-mesh-axes info)."""
    from ._compat import shard_map

    spec = P(None, None, axis_name, None)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(spec, spec, spec), out_specs=spec, check_vma=check_vma)
    def fn(q, k, v):
        return ulysses_attention(q, k, v, axis_name, attn_fn=attn_fn)

    return fn


def make_ulysses_attn_fn(mesh: Mesh, axis_name: str = SEQ_AXIS,
                         use_flash: bool = False):
    """Ulysses as a model ``attn_fn`` — the (B, N, H, D) signature every
    transformer in the zoo accepts (same drop-in contract as
    ring_attention.make_ring_attn_fn). Token counts that don't divide
    the ``seq`` axis are zero-padded; padding lands at the gathered
    sequence's tail, so the inner attention masks it with a static
    bound. ``use_flash=True`` runs each head block through the Pallas
    flash kernel and requires N to divide the axis exactly."""
    from ._compat import shard_map

    from ._seq_adapter import batch_axes, seq_attn_adapter

    axis_size = mesh.shape[axis_name]
    b_axes = batch_axes(mesh)

    inner = None
    if use_flash:
        from ..ops.pallas.flash_attention import flash_attention
        inner = flash_attention

    # one shard_map per (token count, batch-sharded?) — shared by every
    # layer of a model; Ulysses' valid_len is static per shape
    _fns = {}

    def call(qt, kt, vt, n, sharded):
        key = (n, sharded)
        if key not in _fns:
            spec = P(b_axes if sharded else None, None, axis_name, None)

            @functools.partial(
                shard_map, mesh=mesh, in_specs=(spec, spec, spec),
                out_specs=spec, check_vma=not use_flash)
            def fn(q, k, v):
                return ulysses_attention(q, k, v, axis_name,
                                         attn_fn=inner, valid_len=n)
            _fns[key] = fn
        return _fns[key](qt, kt, vt)

    return seq_attn_adapter(mesh, axis_size, axis_name, "ulysses",
                            use_flash, call)
