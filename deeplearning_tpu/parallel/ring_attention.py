"""Ring attention: sequence-parallel exact attention over the ``seq`` axis.

A capability the reference does NOT have (SURVEY.md §2.9: SP/CP absent —
its only long-sequence tool is Swin's window locality). TPU-native design:
shard the sequence over the ``seq`` mesh axis; each device holds its Q/K/V
chunk; K/V chunks rotate around the ring via ``lax.ppermute`` (ICI
neighbor exchange) while each device accumulates its queries' attention
over every chunk with the same online-softmax update the flash kernel
uses. Peak memory per device is O(N/P · N/P) per block — exact attention
over sequences P× longer than one device could hold, with communication
hidden behind the per-chunk compute.

Composable: the per-chunk inner attention uses the Pallas flash kernel on
TPU (lax fallback elsewhere), so blockwise HBM savings and ring scaling
stack.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ._compat import axis_size as _axis_size
from .mesh import SEQ_AXIS


NEG_INF = -1e30


def _chunk_attention_stats(q, k, v, sm_scale, kv_mask=None):
    """Un-normalized attention over one KV chunk: returns (numerator,
    max, sumexp) for online combining. q,k,v: (B, H, Nq, D)/(B, H, Nk, D).
    ``kv_mask`` (Nk,) bool marks valid key tokens — padded tokens (ring
    chunks must divide the global N, so wrappers zero-pad the tail) are
    excluded from the softmax."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * sm_scale
    if kv_mask is not None:
        s = jnp.where(kv_mask[None, None, None, :], s, NEG_INF)
    m = jnp.max(s, axis=-1)                                  # (B,H,Nq)
    p = jnp.exp(s - m[..., None])
    if kv_mask is not None:
        p = p * kv_mask[None, None, None, :].astype(p.dtype)
    l = jnp.sum(p, axis=-1)
    num = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return num, m, l


def _combine(carry, update):
    """Online-softmax merge of (num, m, l) accumulators."""
    num1, m1, l1 = carry
    num2, m2, l2 = update
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    return (num1 * a1[..., None] + num2 * a2[..., None],
            m, l1 * a1 + l2 * a2)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str = SEQ_AXIS,
                   sm_scale: Optional[float] = None,
                   use_flash: bool = False,
                   kv_mask: Optional[jax.Array] = None) -> jax.Array:
    """Exact attention with K/V ring-rotated over ``axis_name``.

    Must run inside shard_map with ``axis_name`` bound; q/k/v are the
    device-local sequence chunks (B, H, Nlocal, D). Non-causal (the zoo's
    encoders are bidirectional).

    ``use_flash`` runs each chunk through the Pallas flash kernel
    (flash_attention_with_lse): a chunk's (out, lse) is an equivalent
    online-softmax accumulator (num=out, m=lse, l=1), so the ring merge
    is exact and never materializes a (Nlocal, Nlocal) score matrix in
    HBM. TRAINABLE: a custom VJP runs a second ring in the backward pass
    where each device computes per-chunk (dq, dk, dv) with the flash
    backward kernels against the GLOBAL logsumexp, rotating the dK/dV
    accumulators with the KV chunks (Liu & Abbeel ring attention bwd).
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if use_flash:
        if kv_mask is not None:
            raise NotImplementedError(
                "kv_mask needs the lax path (the flash kernel masks by "
                "static kv_len only) — pad to a seq-axis multiple "
                "instead, or set use_flash=False")
        return _ring_flash(axis_name, sm_scale, q, k, v)
    out, _ = _ring_forward(q, k, v, axis_name, sm_scale, use_flash=False,
                           kv_mask=kv_mask)
    return out


def _zero_like_varying(x, fill=0.0, drop_last=False):
    """A fill-valued f32 array DERIVED from ``x`` so it carries exactly
    x's varying-mesh-axes type — fori_loop requires carry init and body
    output types to match, and the body's accumulators inherit the
    inputs' axes (seq, and data when the batch dim is sharded)."""
    z = x.astype(jnp.float32)
    if drop_last:
        z = z[..., 0]
    return z * 0.0 + fill


def _ring_forward(q, k, v, axis_name, sm_scale, use_flash,
                  kv_mask=None):
    """Ring forward; returns (out, global_lse). ``kv_mask`` (Nlocal,)
    bool rotates around the ring with its KV chunk (lax path only)."""
    axis_size = _axis_size(axis_name)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def chunk_stats(q, kk, vv, mm):
        if use_flash:
            from ..ops.pallas.flash_attention import flash_attention_with_lse
            o, lse = flash_attention_with_lse(q, kk, vv, sm_scale=sm_scale)
            return (o.astype(jnp.float32), lse, jnp.ones_like(lse))
        return _chunk_attention_stats(q, kk, vv, sm_scale, kv_mask=mm)

    def body(i, state):
        carry, kk, vv, mm = state
        update = chunk_stats(q, kk, vv, mm)
        carry = _combine(carry, update)
        # rotate KV to the next device; last iteration's rotate is wasted
        # but keeps the loop body uniform (XLA overlaps it with compute).
        kk = jax.lax.ppermute(kk, axis_name, perm)
        vv = jax.lax.ppermute(vv, axis_name, perm)
        if mm is not None:
            mm = jax.lax.ppermute(mm, axis_name, perm)
        return carry, kk, vv, mm

    init = (_zero_like_varying(q),
            _zero_like_varying(q, fill=-jnp.inf, drop_last=True),
            _zero_like_varying(q, drop_last=True))
    (num, m, l), _, _, _ = jax.lax.fori_loop(
        0, axis_size, body, (init, k, v, kv_mask))
    l_safe = jnp.maximum(l, 1e-30)
    out = (num / l_safe[..., None]).astype(q.dtype)
    return out, m + jnp.log(l_safe)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _ring_flash(axis_name, sm_scale, q, k, v):
    out, _ = _ring_flash_fwd(axis_name, sm_scale, q, k, v)
    return out


def _ring_flash_fwd(axis_name, sm_scale, q, k, v):
    out, lse = _ring_forward(q, k, v, axis_name, sm_scale, use_flash=True)
    return out, (q, k, v, out, lse)


def _ring_flash_bwd(axis_name, sm_scale, res, dout):
    """Backward ring: per-chunk flash gradients against the global LSE
    sum to the exact full-sequence gradient (flash_chunk_grads
    docstring), so dQ accumulates locally while (KV, dK, dV) rotate
    together — after a full circle the dK/dV accumulators are home with
    every device's contribution."""
    from ..ops.pallas.flash_attention import flash_chunk_grads

    q, k, v, out, lse = res
    axis_size = _axis_size(axis_name)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    delta = jnp.sum(dout.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)

    def body(i, state):
        dq, kk, vv, dkk, dvv = state
        dq_c, dk_c, dv_c = flash_chunk_grads(q, kk, vv, dout, lse, delta,
                                             sm_scale=sm_scale)
        dq = dq + dq_c      # chunk grads are f32 (flash_chunk_grads)
        dkk = dkk + dk_c
        dvv = dvv + dv_c
        kk, vv, dkk, dvv = (jax.lax.ppermute(t, axis_name, perm)
                            for t in (kk, vv, dkk, dvv))
        return dq, kk, vv, dkk, dvv

    dq, _, _, dk, dv = jax.lax.fori_loop(
        0, axis_size, body,
        (_zero_like_varying(q), k, v,
         _zero_like_varying(k), _zero_like_varying(v)))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def make_ring_attention(mesh: Mesh, axis_name: str = SEQ_AXIS,
                        use_flash: bool = False):
    """shard_map-wrapped ring attention over a live mesh: takes globally
    sharded (B, H, N, D) arrays (sequence dim sharded over ``axis_name``)
    and returns the same sharding."""
    from ._compat import shard_map

    spec = P(None, None, axis_name, None)

    # pallas_call out_shapes carry no varying-mesh-axes info, so the
    # flash-backed path needs shard_map's vma check off
    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec, check_vma=not use_flash)
    def fn(q, k, v):
        return ring_attention(q, k, v, axis_name, use_flash=use_flash)

    return fn


def make_ring_attn_fn(mesh: Mesh, axis_name: str = SEQ_AXIS,
                      use_flash: bool = False):
    """Ring attention as a model ``attn_fn``: the (B, N, H, D) signature
    every transformer in the zoo accepts (vit.py Attention, transfg,
    mae). This is how sequence parallelism drops INTO a model instead of
    living beside it: build any ViT with
    ``attn_fn=make_ring_attn_fn(mesh)`` and its attention shards over
    the ``seq`` axis while the rest of the model stays GSPMD-sharded
    (batch over ``data``, sequence over ``seq``).

    Token counts rarely divide the seq axis (ViT-B/16 has 197 = 196+cls),
    so inputs are zero-padded to a multiple and a KV validity mask rides
    the ring with its chunk (lax path). ``use_flash=True`` requires the
    unpadded length to divide the axis exactly."""
    from ._compat import shard_map

    from ._seq_adapter import batch_axes, seq_attn_adapter

    axis_size = mesh.shape[axis_name]
    b_axes = batch_axes(mesh)

    rings = {}

    def _ring_for(shard_batch):
        if shard_batch not in rings:
            spec = P(b_axes if shard_batch else None, None, axis_name,
                     None)

            @functools.partial(
                shard_map, mesh=mesh,
                in_specs=(spec, spec, spec, P(axis_name)),
                out_specs=spec, check_vma=not use_flash)
            def ring(q, k, v, mask):
                return ring_attention(
                    q, k, v, axis_name, use_flash=use_flash,
                    kv_mask=None if use_flash else mask)
            rings[shard_batch] = ring
        return rings[shard_batch]

    def call(qt, kt, vt, n, sharded):
        mask = jnp.arange(qt.shape[2]) < n
        return _ring_for(sharded)(qt, kt, vt, mask)

    return seq_attn_adapter(mesh, axis_size, axis_name, "ring",
                            use_flash, call)
