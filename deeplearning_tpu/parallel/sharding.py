"""GSPMD sharding rules: batch sharding, parameter sharding, host data split.

This is the TPU-native replacement for DDP + DistributedSampler
(others/train_with_DDP/train.py:140-195): the batch is sharded over the
('data','fsdp') mesh axes, parameters are replicated (pure DP) or sharded
by rule (TP / FSDP), and pjit/GSPMD inserts gradient all-reduces over ICI —
the analog of DDP's bucketed NCCL all-reduce, but fused by the compiler.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import DATA_AXIS, FSDP_AXIS, MODEL_AXIS


def batch_spec() -> P:
    """Shard the leading (batch) dim over data×fsdp; replicate the rest."""
    return P((DATA_AXIS, FSDP_AXIS))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, batch_spec())


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


# ---------------------------------------------------------------------------
# Parameter sharding by regex rules (the GSPMD way to express TP/FSDP).
# A rule maps a '/'-joined param path regex -> PartitionSpec. First match
# wins; default is replicated.
# ---------------------------------------------------------------------------

Rules = Sequence[Tuple[str, P]]


def logical_to_sharding(mesh: Mesh, rules: Optional[Rules]
                        ) -> Callable[[str, Any], NamedSharding]:
    compiled = [(re.compile(pat), spec) for pat, spec in (rules or [])]

    def lookup(path: str, leaf: Any) -> NamedSharding:
        for pat, spec in compiled:
            if pat.search(path):
                if len(spec) <= np.ndim(leaf):
                    return NamedSharding(mesh, spec)
        return NamedSharding(mesh, P())
    return lookup


def tree_paths(tree: Any) -> Any:
    """Pytree of '/'-joined string paths mirroring ``tree``."""
    paths, treedef = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for keypath, _ in paths:
        parts = []
        for k in keypath:
            if hasattr(k, "key"):
                parts.append(str(k.key))
            elif hasattr(k, "idx"):
                parts.append(str(k.idx))
            elif hasattr(k, "name"):
                parts.append(str(k.name))
            else:
                parts.append(str(k))
        out.append("/".join(parts))
    return jax.tree_util.tree_unflatten(treedef, out)


def shard_params_tree(params: Any, mesh: Mesh,
                      rules: Optional[Rules] = None) -> Any:
    """Pytree of NamedShardings for ``params`` under ``rules``."""
    lookup = logical_to_sharding(mesh, rules)
    paths = tree_paths(params)
    return jax.tree.map(lambda p, x: lookup(p, x), paths, params)


# Standard TP rules for transformer blocks (Megatron layout expressed as
# GSPMD specs — SURVEY.md §2.9 "TP: provide via GSPMD param sharding"):
# attention qkv + mlp-in column-parallel, proj + mlp-out row-parallel.
TRANSFORMER_TP_RULES: Rules = (
    (r"(qkv|query|key|value|mlp/fc1|Dense_0)/kernel$", P(None, MODEL_AXIS)),
    (r"(proj|out|mlp/fc2|Dense_1)/kernel$", P(MODEL_AXIS, None)),
    (r"(qkv|query|key|value|mlp/fc1|Dense_0)/bias$", P(MODEL_AXIS)),
)

# FSDP rules: shard matmul kernels over fsdp. Row-parallel kernels
# (attention proj, mlp/fc2 — the second matmul of each pair) shard their
# INPUT dim, everything else the output dim: with all kernels
# output-sharded, the backward kernel-grad dots need the batch-sharded
# activation cotangent resharded to feature sharding, which the SPMD
# partitioner can only do by full rematerialization ("Involuntary full
# rematerialization" warnings, MULTICHIP r3); the alternating layout
# keeps every grad contraction layout-compatible (and shards the WIDE
# dim of fc2, which is bigger anyway).
FSDP_RULES: Rules = (
    # anchored to the transformer paths (blocks_*/attn/proj,
    # stage*_block*/attn/proj, */mlp/fc2) so 4-D conv kernels that happen
    # to be NAMED proj (ViT patch_embed/proj and friends) fall through to
    # the conv rule below instead of input-dim sharding.
    (r"(attn/proj|mlp/fc2)/kernel$", P(FSDP_AXIS, None)),
    # 4-D HWIO conv kernels: shard the OUTPUT-feature dim. Listed before
    # the 2-D fallback because lookup skips any rule whose spec rank
    # exceeds the leaf rank, so dense kernels fall through to the next
    # rule while convs stop here (a bare P(None, fsdp) on a 4-D leaf
    # would shard dim 1 — the tiny spatial kw dim).
    (r"kernel$", P(None, None, None, FSDP_AXIS)),
    (r"kernel$", P(None, FSDP_AXIS)),
)


# ---------------------------------------------------------------------------
# ZeRO-1 weight-update sharding (PAPERS.md "Automatic Cross-Replica
# Sharding of Weight Update in Data-Parallel Training"): optimizer-moment
# leaves shard over the data axes instead of replicating, and the train
# step's matching sharding constraints let XLA lower the DDP all-reduce
# into reduce-scatter -> per-shard update -> all-gather.
# ---------------------------------------------------------------------------


def zero1_partition_spec(shape: Tuple[int, ...], dp: int) -> P:
    """Spec sharding the FIRST dim of ``shape`` divisible by the
    data-parallel extent ``dp`` over ('data','fsdp'); ``P()`` when no dim
    divides — the small-leaf tail (biases of odd width, scalars) stays
    replicated rather than padded, and ``shard_layout_summary`` shows it.
    First-divisible-dim (not largest) keeps the choice predictable and
    lets the quantized grad path reduce-scatter along dim 0."""
    if dp <= 1:
        return P()
    for d, size in enumerate(shape):
        if size >= dp and size % dp == 0:
            spec = [None] * len(shape)
            spec[d] = (DATA_AXIS, FSDP_AXIS)
            return P(*spec)
    return P()


def zero1_shardings(params: Any, mesh: Mesh,
                    rules: Optional[Rules] = None) -> Any:
    """NamedSharding pytree for param-SHAPED trees (adam mu/nu, grads
    mid-update) under ZeRO-1: leaves a TP/FSDP rule already shards keep
    their rule layout; rule-replicated leaves shard over the full
    data-parallel extent when a dim divides, else stay replicated."""
    dp = mesh.shape[DATA_AXIS] * mesh.shape[FSDP_AXIS]
    base = shard_params_tree(params, mesh, rules)

    def pick(leaf: Any, sh: NamedSharding) -> NamedSharding:
        if not sh.is_fully_replicated:
            return sh
        return NamedSharding(mesh, zero1_partition_spec(
            tuple(np.shape(leaf)), dp))
    return jax.tree.map(pick, params, base)


def opt_state_shardings(opt_state: Any, params_treedef: Any,
                        param_sh: Any, rep: NamedSharding,
                        on_fallback: Optional[Callable[[Any, Exception],
                                                       None]] = None) -> Any:
    """Sharding pytree mirroring an optax state: param-structured
    subtrees (ScaleByAdam mu/nu and friends) get ``param_sh``, everything
    else (step counters, un-flattenable fields) ``rep``. Shared by
    ``shard_state`` (placement) and the zero1 train step (the matching
    in-step constraints), so the two can never disagree."""
    def go(opt: Any) -> Any:
        if hasattr(opt, "_fields"):
            return type(opt)(*(go(f) for f in opt))
        if isinstance(opt, (tuple, list)):
            return type(opt)(go(o) for o in opt)
        try:
            if jax.tree.structure(opt) == params_treedef:
                return param_sh
        except (TypeError, ValueError) as e:
            if on_fallback is not None:
                on_fallback(opt, e)
        return jax.tree.map(lambda x: rep, opt)
    return go(opt_state)


def tree_bytes_per_device(tree: Any) -> int:
    """Bytes ONE device holds for a placed pytree — shard sizes, not
    global sizes. This is the per-device HBM cost ZeRO-1 exists to cut:
    replicated vs zero1 opt states differ by ~the data-parallel extent."""
    total = 0
    for leaf in jax.tree.leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        sharding = getattr(leaf, "sharding", None)
        if sharding is not None and hasattr(sharding, "shard_shape"):
            shape = sharding.shard_shape(tuple(shape))
        total += int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
    return total


def shard_layout_summary(tree: Any) -> Dict[str, Any]:
    """Compact JSON-able description of how a pytree is laid out: the
    PartitionSpec of every NON-replicated jax.Array leaf (keyed by
    '/'-joined path) plus leaf counts. This is what checkpoint topology
    sidecars embed so a resume can report what layout it came from —
    listing only the sharded leaves keeps a pure-DP summary tiny."""
    paths = tree_paths(tree)
    specs: Dict[str, str] = {}
    counts = {"leaves": 0, "replicated": 0, "sharded": 0}

    def visit(path: str, leaf: Any) -> None:
        counts["leaves"] += 1
        sharding = getattr(leaf, "sharding", None)
        spec = getattr(sharding, "spec", None)
        if sharding is None or spec is None or sharding.is_fully_replicated:
            counts["replicated"] += 1
            return
        counts["sharded"] += 1
        specs[path] = str(tuple(spec))

    jax.tree.map(visit, paths, tree)
    return {"specs": specs, **counts}


def host_local_slice(global_batch: int) -> Tuple[int, int]:
    """[start, end) of this host's slice of a global batch — the
    DistributedSampler successor for per-host data loading."""
    per_host = global_batch // jax.process_count()
    start = jax.process_index() * per_host
    return start, start + per_host


def make_global_array(local_batch: np.ndarray, mesh: Mesh,
                      spec: Optional[P] = None) -> jax.Array:
    """Assemble per-host local batches into one global sharded jax.Array
    (multi-host form-up; the reference has no analog because DDP keeps
    arrays process-local)."""
    spec = batch_spec() if spec is None else spec
    sharding = NamedSharding(mesh, spec)
    global_shape = (local_batch.shape[0] * jax.process_count(),
                    *local_batch.shape[1:])
    return jax.make_array_from_process_local_data(
        sharding, local_batch, global_shape)
