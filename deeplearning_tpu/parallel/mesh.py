"""Device discovery and mesh construction.

Replaces the reference's L0 device layer — per-project ``select_device`` /
``.cuda()`` calls (others/train_with_DDP/utils/torch_utils.py:32) — and its
L2 process-group bootstrap: env-var rank discovery + ``init_process_group
(nccl|gloo)`` (others/train_with_DDP/train.py:32-111, YOLOX
yolox/core/launch.py:39-147). In JAX a single ``Mesh`` over all devices plus
GSPMD subsumes DP/DDP/TP/EP: shard batch over the ``data`` axis (DDP),
shard params over ``model`` (TP), experts over ``expert`` (EP), sequences
over ``seq`` (SP/ring attention). XLA inserts the NCCL-equivalent
collectives over ICI automatically.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

# Canonical axis names, in mesh order. Keeping data outermost puts replicas
# on the slowest-varying (DCN/ICI-outer) dimension, matching the scaling-book
# recipe: DP over the outer ring, TP over the densest ICI links (innermost).
DATA_AXIS = "data"
FSDP_AXIS = "fsdp"
SEQ_AXIS = "seq"
MODEL_AXIS = "model"
EXPERT_AXIS = "expert"


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """-1 on the data axis means "absorb all remaining devices"."""
    data: int = -1
    fsdp: int = 1
    seq: int = 1
    model: int = 1
    expert: int = 1


def initialize_distributed(coordinator: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """Multi-host bootstrap (the init_process_group analog). On single-host
    runs this is a no-op; on pods jax.distributed wires the hosts together
    so jax.devices() is global."""
    if num_processes is not None and num_processes > 1:
        jax.distributed.initialize(coordinator, num_processes, process_id)
    elif os.environ.get("JAX_COORDINATOR_ADDRESS"):
        jax.distributed.initialize()


def build_mesh(cfg: MeshConfig = MeshConfig(),
               devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    sizes = {DATA_AXIS: cfg.data, FSDP_AXIS: cfg.fsdp, SEQ_AXIS: cfg.seq,
             MODEL_AXIS: cfg.model, EXPERT_AXIS: cfg.expert}
    fixed = int(np.prod([s for s in sizes.values() if s > 0]))
    n_infer = sum(1 for s in sizes.values() if s == -1)
    if n_infer > 1:
        raise ValueError("At most one mesh axis may be -1")
    if n_infer == 1:
        if n % fixed:
            raise ValueError(f"{n} devices not divisible by fixed axes {fixed}")
        sizes = {k: (n // fixed if s == -1 else s) for k, s in sizes.items()}
    elif fixed != n:
        raise ValueError(f"Mesh {sizes} needs {fixed} devices, have {n}")
    shape = tuple(sizes.values())
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, tuple(sizes.keys()))


def data_parallel_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """The DDP successor: every device a data replica."""
    return build_mesh(MeshConfig(), devices)


def mesh_shape_str(mesh: Mesh) -> str:
    return "×".join(f"{k}={v}" for k, v in mesh.shape.items() if v > 1) or "1"


def local_device_count() -> int:
    return jax.local_device_count()


def global_batch_from_per_device(per_device: int,
                                 mesh: Optional[Mesh] = None) -> int:
    """lr/batch scaling helper — the reference scales lr by WORLD_SIZE
    (others/train_with_DDP/train.py:198); here batch scales by the number
    of data-parallel shards."""
    if mesh is None:
        return per_device * jax.device_count()
    dp = mesh.shape.get(DATA_AXIS, 1) * mesh.shape.get(FSDP_AXIS, 1)
    return per_device * dp
