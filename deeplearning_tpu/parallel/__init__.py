from . import moe, pipeline  # noqa: F401
from .mesh import (MeshConfig, build_mesh, data_parallel_mesh,  # noqa: F401
                   initialize_distributed, DATA_AXIS, FSDP_AXIS, SEQ_AXIS,
                   MODEL_AXIS, EXPERT_AXIS)
from .sharding import (batch_spec, batch_sharding, replicated,  # noqa: F401
                       shard_params_tree, make_global_array,
                       TRANSFORMER_TP_RULES, FSDP_RULES)
