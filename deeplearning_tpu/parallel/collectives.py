"""Collective helpers over the device mesh.

Maps the reference's torch.distributed usage (SURVEY.md §2.9) onto XLA
collectives: ``reduce_value`` all-reduce mean
(others/train_with_DDP/utils/distributed_utils.py:71) → ``pmean``;
metric ``reduce_dict`` (fasterRcnn utils/distributed_utils.py:116) →
tree-pmean; SyncBatchNorm (train.py:192) → batch-stat pmean inside the norm
(see ops/norm.py); object all_gather (YOLOX yolox/utils/dist.py:186) →
``process_allgather`` on host. Inside pjit-compiled code most collectives
are implicit — GSPMD inserts them from sharding constraints — so these
helpers are for shard_map code and for host-side gathers.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import DATA_AXIS, FSDP_AXIS


def pmean_tree(tree: Any, axis_name: str | tuple = (DATA_AXIS, FSDP_AXIS)) -> Any:
    """Mean a pytree across replicas — DDP's gradient/metric all-reduce.
    Only valid inside shard_map/pmap with the axis bound."""
    return jax.tree.map(lambda x: jax.lax.pmean(x, axis_name), tree)


def psum_tree(tree: Any, axis_name: str | tuple = (DATA_AXIS, FSDP_AXIS)) -> Any:
    return jax.tree.map(lambda x: jax.lax.psum(x, axis_name), tree)


# ---------------------------------------------------------------------------
# Quantized collectives (PAPERS.md "EQuARX: Efficient Quantized AllReduce
# in XLA"): block-scaled int8 payloads cut gradient all-reduce bytes ~4x.
# Each block of ``block`` consecutive elements shares one fp32 scale; the
# scale is rounded UP to a power of two so quantization is an exact
# binary shift whenever values (and their cross-replica sums) are small
# integers — that is what makes the parity test bitwise, and bounds the
# general-case error at s/2 <= max|x|/127 per element per stage.
# Two stages (quantize -> reduce-scatter -> requantize -> all-gather)
# mirror a ring all-reduce, so worst-case relative error is ~2/127 of the
# block max — fine for gradients, wrong for loss scalars; callers psum
# metrics in fp32.
# ---------------------------------------------------------------------------

_QMAX = 127.0
_TINY = 1e-30  # floor before log2 so all-zero blocks get scale 2^-~100


def _quantize_blocks(xb: jax.Array) -> tuple[jax.Array, jax.Array]:
    """(..., block) fp32 -> int8 payload + per-block power-of-two scale."""
    maxabs = jnp.max(jnp.abs(xb), axis=-1, keepdims=True)
    s = jnp.exp2(jnp.ceil(jnp.log2(jnp.maximum(maxabs, _TINY) / _QMAX)))
    q = jnp.clip(jnp.round(xb / s), -_QMAX, _QMAX).astype(jnp.int8)
    return q, s.astype(jnp.float32)


def _dequantize_blocks(q: jax.Array, s: jax.Array) -> jax.Array:
    return q.astype(jnp.float32) * s


def _pad_to(x: jax.Array, multiple: int) -> tuple[jax.Array, int]:
    pad = (-x.shape[-1]) % multiple
    if pad:
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, pad)])
    return x, pad


def _quantized_rs_stage(flat: jax.Array, axis_name: Any, n: int,
                        block: int) -> jax.Array:
    """Stage 1 of the quantized all-reduce, inside shard_map: every
    replica holds the SAME flat fp32 vector (length divisible by
    n*block); returns this replica's 1/n chunk of the cross-replica SUM.
    The wire carries int8 payloads + fp32 block scales via all_to_all
    (each replica ships peer-destined chunks), then the sum is done in
    fp32 after rescale — the EQuARX reduce-scatter stage."""
    chunks = flat.reshape(n, flat.shape[-1] // n)
    q, s = _quantize_blocks(chunks.reshape(n, -1, block))
    q = jax.lax.all_to_all(q, axis_name, 0, 0)
    s = jax.lax.all_to_all(s, axis_name, 0, 0)
    return jnp.sum(_dequantize_blocks(q, s), axis=0).reshape(-1)


def quantized_psum(x: jax.Array, axis_name: Any = (DATA_AXIS, FSDP_AXIS),
                   block: int = 256) -> jax.Array:
    """int8 block-scaled all-reduce SUM of ``x`` across ``axis_name``.
    Only valid inside shard_map with the axes bound; every replica must
    pass the same-shaped local array and gets the full summed array back
    (like ``jax.lax.psum``). Exact when per-replica values and their sums
    are integers within [-127, 127]; otherwise relative error is bounded
    by ~2/127 per block (two quantization stages)."""
    from ._compat import axis_size
    n = axis_size(axis_name)
    flat = x.astype(jnp.float32).reshape(-1)
    size = flat.shape[0]
    flat, _ = _pad_to(flat, n * block)
    part = _quantized_rs_stage(flat, axis_name, n, block)
    q2, s2 = _quantize_blocks(part.reshape(-1, block))
    q2 = jax.lax.all_gather(q2.reshape(-1), axis_name, axis=0, tiled=True)
    s2 = jax.lax.all_gather(s2.reshape(-1), axis_name, axis=0, tiled=True)
    out = _dequantize_blocks(q2.reshape(-1, block),
                             s2.reshape(-1, 1)).reshape(-1)
    return out[:size].reshape(x.shape).astype(x.dtype)


def quantized_psum_tree(tree: Any,
                        axis_name: Any = (DATA_AXIS, FSDP_AXIS),
                        block: int = 256) -> Any:
    """``psum_tree`` with int8 block-scaled payloads (EQuARX-style)."""
    return jax.tree.map(
        lambda x: quantized_psum(x, axis_name, block=block), tree)


def quantized_reduce_scatter(x: jax.Array,
                             axis_name: Any = (DATA_AXIS, FSDP_AXIS),
                             block: int = 256) -> jax.Array:
    """int8 reduce-scatter: every replica passes the same-shaped local
    array; returns this replica's ``x.shape[0]//n`` leading-dim slice of
    the cross-replica SUM (like ``jax.lax.psum_scatter(..., tiled=True)``).
    Requires ``x.shape[0] % n == 0`` — the ZeRO-1 grad path only routes
    leaves here when their zero1 spec shards dim 0. Skips the second
    quantization stage entirely (the scattered shard never rides the
    wire again), so only one stage of error applies."""
    from ._compat import axis_size
    n = axis_size(axis_name)
    if x.shape[0] % n != 0:
        raise ValueError(
            f"quantized_reduce_scatter needs dim0 % {n} == 0, "
            f"got shape {x.shape}")
    rows = x.shape[0] // n
    flat = x.astype(jnp.float32).reshape(n, -1)
    flat, pad = _pad_to(flat, block)
    part = _quantized_rs_stage(flat.reshape(-1), axis_name, n,
                               block)
    if pad:
        part = part[:-pad]
    return part.reshape((rows,) + x.shape[1:]).astype(x.dtype)


def host_allgather(tree: Any) -> Any:
    """Gather host-local (numpy-backed) pytrees from every process onto all
    hosts — the analog of torch.distributed all_gather of pickled objects
    (YOLOX dist.py:186, used for distributed COCO evaluation)."""
    from jax.experimental import multihost_utils
    if jax.process_count() == 1:
        return jax.tree.map(lambda x: jnp.asarray(x)[None], tree)
    return multihost_utils.process_allgather(tree)


def broadcast_from_host0(tree: Any) -> Any:
    """Rank-0 weight broadcast successor (others/train_with_DDP/
    train.py:163-177 did this with a tmp file + barrier)."""
    from jax.experimental import multihost_utils
    if jax.process_count() == 1:
        return tree
    return multihost_utils.broadcast_one_to_all(tree)


def sync_barrier(name: str = "barrier") -> None:
    from jax.experimental import multihost_utils
    if jax.process_count() > 1:
        multihost_utils.sync_global_devices(name)


def with_data_sharding_constraint(x: jax.Array, mesh: Optional[Mesh] = None
                                  ) -> jax.Array:
    """Pin the leading dim of an intermediate to the data axes inside jit —
    the steering wheel for GSPMD when propagation is ambiguous."""
    return jax.lax.with_sharding_constraint(
        x, jax.sharding.NamedSharding(
            mesh or _current_mesh(), P((DATA_AXIS, FSDP_AXIS))))


def _current_mesh() -> Mesh:
    env = jax.sharding.get_abstract_mesh()
    if env is None:
        raise RuntimeError("No mesh in scope; pass mesh= explicitly")
    return env
