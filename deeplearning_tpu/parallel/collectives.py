"""Collective helpers over the device mesh.

Maps the reference's torch.distributed usage (SURVEY.md §2.9) onto XLA
collectives: ``reduce_value`` all-reduce mean
(others/train_with_DDP/utils/distributed_utils.py:71) → ``pmean``;
metric ``reduce_dict`` (fasterRcnn utils/distributed_utils.py:116) →
tree-pmean; SyncBatchNorm (train.py:192) → batch-stat pmean inside the norm
(see ops/norm.py); object all_gather (YOLOX yolox/utils/dist.py:186) →
``process_allgather`` on host. Inside pjit-compiled code most collectives
are implicit — GSPMD inserts them from sharding constraints — so these
helpers are for shard_map code and for host-side gathers.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import DATA_AXIS, FSDP_AXIS


def pmean_tree(tree: Any, axis_name: str | tuple = (DATA_AXIS, FSDP_AXIS)) -> Any:
    """Mean a pytree across replicas — DDP's gradient/metric all-reduce.
    Only valid inside shard_map/pmap with the axis bound."""
    return jax.tree.map(lambda x: jax.lax.pmean(x, axis_name), tree)


def psum_tree(tree: Any, axis_name: str | tuple = (DATA_AXIS, FSDP_AXIS)) -> Any:
    return jax.tree.map(lambda x: jax.lax.psum(x, axis_name), tree)


def host_allgather(tree: Any) -> Any:
    """Gather host-local (numpy-backed) pytrees from every process onto all
    hosts — the analog of torch.distributed all_gather of pickled objects
    (YOLOX dist.py:186, used for distributed COCO evaluation)."""
    from jax.experimental import multihost_utils
    if jax.process_count() == 1:
        return jax.tree.map(lambda x: jnp.asarray(x)[None], tree)
    return multihost_utils.process_allgather(tree)


def broadcast_from_host0(tree: Any) -> Any:
    """Rank-0 weight broadcast successor (others/train_with_DDP/
    train.py:163-177 did this with a tmp file + barrier)."""
    from jax.experimental import multihost_utils
    if jax.process_count() == 1:
        return tree
    return multihost_utils.broadcast_one_to_all(tree)


def sync_barrier(name: str = "barrier") -> None:
    from jax.experimental import multihost_utils
    if jax.process_count() > 1:
        multihost_utils.sync_global_devices(name)


def with_data_sharding_constraint(x: jax.Array, mesh: Optional[Mesh] = None
                                  ) -> jax.Array:
    """Pin the leading dim of an intermediate to the data axes inside jit —
    the steering wheel for GSPMD when propagation is ambiguous."""
    return jax.lax.with_sharding_constraint(
        x, jax.sharding.NamedSharding(
            mesh or _current_mesh(), P((DATA_AXIS, FSDP_AXIS))))


def _current_mesh() -> Mesh:
    env = jax.sharding.get_abstract_mesh()
    if env is None:
        raise RuntimeError("No mesh in scope; pass mesh= explicitly")
    return env
