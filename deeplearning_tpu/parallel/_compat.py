"""JAX version compatibility for the parallel package.

``shard_map`` graduated from ``jax.experimental.shard_map`` to
``jax.shard_map`` (and its replication check was renamed
``check_rep`` -> ``check_vma``) after 0.4.x. The rest of this package
writes against the modern surface — ``shard_map(f, mesh=..., in_specs=...,
out_specs=..., check_vma=...)`` — and this module backfills it on older
releases by translating the kwarg onto the experimental entry point.
"""

from __future__ import annotations

import jax

try:  # modern surface (jax >= 0.5): top-level, check_vma kwarg
    from jax import shard_map as _shard_map

    SHARD_MAP_NATIVE = True

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True, **kw):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=check_vma, **kw)

except ImportError:  # 0.4.x: experimental module, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map

    SHARD_MAP_NATIVE = False

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True, **kw):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_vma, **kw)


if hasattr(jax.lax, "axis_size"):
    axis_size = jax.lax.axis_size
else:
    def axis_size(axis_name):
        """Static size of a bound mesh axis. ``jax.lax.axis_size`` is
        post-0.4.x; ``psum(1, axis)`` constant-folds to a python int for
        named axes on every release."""
        return jax.lax.psum(1, axis_name)


__all__ = ["SHARD_MAP_NATIVE", "axis_size", "shard_map"]
