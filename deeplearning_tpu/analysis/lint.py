"""dltpu-check: a TPU-policy AST linter for the repo's hot-path invariants.

The repo's hard-won invariants — the sync-free hot loop, batch-buffer
donation, retrace discipline, signal-handler safety — each live in one
bespoke test and otherwise in README prose, while 190 sync-capable call
sites sit across 47 modules. This linter turns them into named, machine-
checkable rules over the Python AST (stdlib ``ast`` only — this module
must import neither jax nor anything else heavy, so ``tools/check.py
--ci`` and ``tools/obs_report.py`` can load it standalone in well under
a second):

  DLT100  host-sync call (``jax.device_get`` / ``.block_until_ready()``
          / ``np.asarray``) inside a hot-path module (``train/``,
          ``data/device_prefetch.py``, ``serve/batcher.py``,
          ``serve/engine.py``). One stray sync between log points undoes
          the PR 1 pipelining.
  DLT101  use-after-donate: a variable passed at a ``donate_argnums``
          position of a jitted call and read afterwards — XLA has
          already recycled that buffer.
  DLT102  retrace hazard: ``jax.jit`` over a closure on a Python scalar
          derived from ``.shape``/``len()``/``int()`` without
          ``static_argnums``, or a ``jax.jit`` call constructed inside a
          ``for``/``while`` body (a fresh cache per iteration).
  DLT103  non-async-signal-safe call (print/open/logging/sleep/
          subprocess) inside a handler registered via
          ``elastic.signals.subscribe`` or ``signal.signal``.
  DLT104  silent exception swallowing: a bare/broad ``except`` whose
          entire body is ``pass`` — the bug class that hid worker
          errors until PR 7.
  DLT105  blocking I/O or ``time.*`` inside a traced (jitted) function —
          it runs at trace time, not step time, and poisons the cache.

Suppression: append ``# dltpu: allow(DLT100)`` (comma-separate several,
or ``allow(*)``) to the offending line or the line above it.

Ratchet: ``baseline.json`` (checked in next to this file) records the
per-file per-rule finding counts at adoption time. ``new_findings``
flags only counts ABOVE the baseline, so the existing debt doesn't
block CI but no new violation can land. ``tools/check.py
--update-baseline`` re-records (tightening is always safe; loosening
shows up in the diff).
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "RULES", "HOT_PATH_MODULES", "Finding", "lint_source", "lint_file",
    "lint_tree", "counts", "load_baseline", "write_baseline",
    "new_findings", "ratchet_status", "DEFAULT_BASELINE", "DEFAULT_SCAN",
]

RULES: Dict[str, str] = {
    "DLT100": "host-sync call in a hot-path module",
    "DLT101": "use-after-donate: donated buffer read after the call",
    "DLT102": "retrace hazard: jit over python-scalar closure or in loop",
    "DLT103": "non-async-signal-safe call in a signal handler",
    "DLT104": "silent exception swallowing (broad except: pass)",
    "DLT105": "blocking I/O or time.* inside a traced function",
}

# modules where DLT100 applies — the proven sync-free surfaces
HOT_PATH_MODULES: Tuple[str, ...] = (
    "deeplearning_tpu/train/",
    "deeplearning_tpu/data/device_prefetch.py",
    "deeplearning_tpu/serve/batcher.py",
    "deeplearning_tpu/serve/engine.py",
    # multi-tenant residency manager: the warm-path request() is a dict
    # lookup on the submit thread, so it carries the same no-sync bar
    "deeplearning_tpu/serve/zoo.py",
    # fleet telemetry plane: instrumented hot paths call into these, so
    # they must be provably sync-free too (stdlib-only by construction)
    "deeplearning_tpu/obs/metrics.py",
    "deeplearning_tpu/obs/fleet.py",
    "deeplearning_tpu/fleet/",
)

# scan roots for lint_tree, relative to the repo root (tests/ is out by
# design: test code syncs on purpose, and seeded-violation fixtures for
# the unit tests live in tmp dirs)
DEFAULT_SCAN: Tuple[str, ...] = (
    "deeplearning_tpu", "tools", "bench.py", "__graft_entry__.py",
)

DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")

_PRAGMA = re.compile(r"#\s*dltpu:\s*allow\(([^)]*)\)")

_LOGGING_METHODS = {"info", "warning", "error", "debug", "exception",
                    "critical", "log"}
_SIGNAL_UNSAFE_NAMES = {"print", "open", "input"}


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    col: int
    msg: str

    def __str__(self) -> str:  # "path:line:col: DLTnnn message"
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.msg}"


# --------------------------------------------------------------- helpers
def _qualname(node: ast.AST) -> Optional[str]:
    """Dotted name for Name/Attribute chains, else None."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _qualname(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


class _Index:
    """One breadth-first walk of the module, shared by every rule pass.

    Each rule used to re-run ``ast.walk`` over the full tree (nine walks
    per file between the passes, alias scan, and parent map); on the
    190-file tree that dominated ``tools/check.py --ci`` wall time. The
    index walks once and buckets the node kinds the rules filter on."""

    def __init__(self, tree: ast.Module):
        self.tree = tree
        self.nodes: List[ast.AST] = []
        self.calls: List[ast.Call] = []
        self.func_defs: List[ast.AST] = []
        self.except_handlers: List[ast.ExceptHandler] = []
        self.parents: Dict[ast.AST, ast.AST] = {}
        todo: deque = deque([tree])
        while todo:
            node = todo.popleft()
            self.nodes.append(node)
            if isinstance(node, ast.Call):
                self.calls.append(node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.func_defs.append(node)
            elif isinstance(node, ast.ExceptHandler):
                self.except_handlers.append(node)
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
                todo.append(child)


class _Aliases:
    """Import aliases the rules need to resolve (np, jax, time, ...)."""

    def __init__(self, nodes: Iterable[ast.AST]):
        self.numpy: set = set()
        self.jax: set = set()
        self.time: set = set()
        self.subprocess: set = set()
        self.partial: set = set()      # functools.partial names
        self.functools: set = set()
        self.jax_names: set = set()    # from jax import jit, device_get
        for node in nodes:
            if isinstance(node, ast.Import):
                for a in node.names:
                    name = a.asname or a.name
                    if a.name == "numpy":
                        self.numpy.add(name)
                    elif a.name == "jax":
                        self.jax.add(name)
                    elif a.name == "time":
                        self.time.add(name)
                    elif a.name == "subprocess":
                        self.subprocess.add(name)
                    elif a.name == "functools":
                        self.functools.add(name)
            elif isinstance(node, ast.ImportFrom):
                if node.module == "functools":
                    for a in node.names:
                        if a.name == "partial":
                            self.partial.add(a.asname or "partial")
                elif node.module == "jax":
                    for a in node.names:
                        self.jax_names.add(a.asname or a.name)


def _is_jit_ref(node: ast.AST, al: _Aliases) -> bool:
    """Does this expression refer to jax.jit / pjit?"""
    q = _qualname(node)
    if q is None:
        return False
    if q in al.jax_names and q in ("jit", "pjit", "pmap"):
        return True
    head, _, tail = q.partition(".")
    return head in al.jax and tail in ("jit", "pjit", "pmap")


def _is_jit_call(node: ast.AST, al: _Aliases) -> bool:
    """Call whose result is a jitted callable: ``jax.jit(...)`` or
    ``partial(jax.jit, ...)(...)``-style partials over jit."""
    if not isinstance(node, ast.Call):
        return False
    if _is_jit_ref(node.func, al):
        return True
    # partial(jax.jit, static_argnums=...) — decorator idiom
    fq = _qualname(node.func)
    if fq and (fq in al.partial
               or any(fq == f"{m}.partial" for m in al.functools)):
        return bool(node.args) and _is_jit_ref(node.args[0], al)
    return False


def _call_kw(node: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in node.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _int_tuple(node: Optional[ast.expr]) -> Optional[Tuple[int, ...]]:
    """Literal int / tuple-of-ints, else None (can't reason about it)."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.append(e.value)
            else:
                return None
        return tuple(out)
    return None


def _scope_walk(body: Sequence[ast.stmt]) -> Iterable[ast.AST]:
    """Walk a function body without descending into nested function /
    class scopes (their loads/stores execute at a different time)."""
    stack: deque = deque(body)
    while stack:
        node = stack.popleft()
        yield node
        for child in ast.iter_child_nodes(node):
            if not isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.Lambda, ast.ClassDef)):
                stack.append(child)


def _scopes(idx: _Index) -> Iterable[Sequence[ast.stmt]]:
    """Module body + every function body (the units DLT101/102 reason
    over)."""
    yield idx.tree.body
    for node in idx.func_defs:
        yield node.body


def _free_loads(fn: ast.AST) -> set:
    """Names a lambda/def loads but neither binds as a param nor stores
    locally — i.e. its closure reads."""
    if isinstance(fn, ast.Lambda):
        params = {a.arg for a in fn.args.args + fn.args.kwonlyargs}
        body: List[ast.AST] = [fn.body]
    elif isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        params = {a.arg for a in fn.args.args + fn.args.kwonlyargs}
        if fn.args.vararg:
            params.add(fn.args.vararg.arg)
        if fn.args.kwarg:
            params.add(fn.args.kwarg.arg)
        body = list(fn.body)
    else:
        return set()
    loads, stores = set(), set(params)
    for node in body:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name):
                if isinstance(sub.ctx, ast.Load):
                    loads.add(sub.id)
                else:
                    stores.add(sub.id)
    return loads - stores


# ------------------------------------------------------------ rule passes
def _rule_dlt100(idx, al, path, add) -> None:
    if not any(h in path for h in HOT_PATH_MODULES):
        return
    for node in idx.calls:
        q = _qualname(node.func)
        if q is None:
            continue
        head, _, tail = q.partition(".")
        if tail == "device_get" and head in al.jax:
            add("DLT100", node, "jax.device_get syncs the dispatch queue")
        elif q == "device_get" and "device_get" in al.jax_names:
            add("DLT100", node, "device_get syncs the dispatch queue")
        elif isinstance(node.func, ast.Attribute) and \
                node.func.attr == "block_until_ready":
            add("DLT100", node, ".block_until_ready() stalls the host")
        elif head in al.numpy and tail in ("asarray", "array"):
            add("DLT100", node,
                f"{q}() on a device value forces a D2H transfer")


def _rule_dlt101(idx, al, path, add) -> None:
    for body in _scopes(idx):
        donating: Dict[str, Tuple[int, ...]] = {}
        donations: List[Tuple[str, int]] = []   # (var, line)
        stores: List[Tuple[str, int]] = []
        loads: List[Tuple[str, int, ast.Name]] = []

        def donate_positions(call: ast.Call) -> Optional[Tuple[int, ...]]:
            """Positions donated by this call, when it IS a donating
            call (directly jitted-with-donate or a name bound to one)."""
            if isinstance(call.func, ast.Name) and \
                    call.func.id in donating:
                return donating[call.func.id]
            if _is_jit_call(call.func, al):     # jax.jit(f, ...)(args)
                pos = _int_tuple(_call_kw(call.func, "donate_argnums"))
                return pos
            return None

        for node in _scope_walk(body):
            if isinstance(node, ast.Assign) and \
                    _is_jit_call(node.value, al):
                pos = _int_tuple(_call_kw(node.value, "donate_argnums"))
                if pos:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            donating[t.id] = pos
            if isinstance(node, ast.Call):
                pos = donate_positions(node)
                if pos:
                    for p in pos:
                        if p < len(node.args) and \
                                isinstance(node.args[p], ast.Name):
                            donations.append((node.args[p].id,
                                              node.lineno))
            if isinstance(node, ast.Name):
                if isinstance(node.ctx, ast.Load):
                    loads.append((node.id, node.lineno, node))
                else:
                    stores.append((node.id, node.lineno))

        for var, dline in donations:
            for name, lline, lnode in loads:
                if name != var or lline <= dline:
                    continue
                # a rebinding between donation and load clears it —
                # including `state, m = step(state, ...)` same-line
                if any(s == var and dline <= sline <= lline
                       for s, sline in stores):
                    continue
                add("DLT101", lnode,
                    f"'{var}' was donated at line {dline}; its buffer "
                    "is already recycled")
                break          # one finding per donation is enough


def _rule_dlt102(idx, al, path, add) -> None:
    # (a) jit over a closure on scalar-derived locals, no static_argnums
    local_defs: Dict[str, ast.AST] = {}
    for node in idx.func_defs:
        local_defs[node.name] = node

    def scalar_derived_names(body) -> set:
        out = set()
        for node in _scope_walk(body):
            if not isinstance(node, ast.Assign):
                continue
            v = node.value
            is_scalar = (
                (isinstance(v, ast.Subscript) and
                 isinstance(v.value, ast.Attribute) and
                 v.value.attr == "shape") or
                (isinstance(v, ast.Call) and
                 isinstance(v.func, ast.Name) and
                 v.func.id in ("len", "int")))
            if is_scalar:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
        return out

    for body in _scopes(idx):
        scalars = scalar_derived_names(body)
        if not scalars:
            continue
        for node in _scope_walk(body):
            if not (isinstance(node, ast.Call) and
                    _is_jit_ref(node.func, al) and node.args):
                continue
            if _call_kw(node, "static_argnums") is not None or \
                    _call_kw(node, "static_argnames") is not None:
                continue
            target = node.args[0]
            if isinstance(target, ast.Name):
                target = local_defs.get(target.id)
            if target is None or not isinstance(
                    target, (ast.Lambda, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
                continue
            hazard = _free_loads(target) & scalars
            if hazard:
                add("DLT102", node,
                    f"jit closes over python scalar(s) "
                    f"{sorted(hazard)} without static_argnums — every "
                    "new value retraces")

    # (b) jit construction inside a loop body (fresh cache/trace per
    # iteration); the nearest enclosing scope boundary wins
    parents = idx.parents
    for node in idx.calls:
        if not _is_jit_ref(node.func, al):
            continue
        up = parents.get(node)
        while up is not None:
            if isinstance(up, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Lambda, ast.Module)):
                break
            if isinstance(up, (ast.For, ast.While)):
                add("DLT102", node,
                    "jax.jit constructed inside a loop: a fresh jit "
                    "cache (and trace) per iteration")
                break
            up = parents.get(up)


def _rule_dlt103(idx, al, path, add) -> None:
    defs_by_name: Dict[str, ast.AST] = {}
    for node in idx.func_defs:
        defs_by_name[node.name] = node

    handlers: List[ast.AST] = []
    for node in idx.calls:
        q = _qualname(node.func) or ""
        is_subscribe = q == "subscribe" or q.endswith(".subscribe")
        is_signal = q == "signal.signal" or q.endswith("signal.signal")
        if not (is_subscribe or is_signal):
            continue
        fn_arg = node.args[1] if len(node.args) > 1 else \
            _call_kw(node, "fn")
        if fn_arg is None:
            continue
        if isinstance(fn_arg, ast.Name) and fn_arg.id in defs_by_name:
            handlers.append(defs_by_name[fn_arg.id])
        elif isinstance(fn_arg, ast.Attribute) and \
                fn_arg.attr in defs_by_name:
            handlers.append(defs_by_name[fn_arg.attr])
        elif isinstance(fn_arg, ast.Lambda):
            handlers.append(fn_arg)

    # one level of callee resolution: a handler that merely delegates
    # (``def _on_term(...): _do_dump()``) used to hide its I/O from
    # this rule — any same-module function/method the handler body
    # calls is scanned with it
    for h in list(handlers):
        body = h.body if isinstance(h.body, list) else [h.body]
        for node in body:
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                callee = None
                if isinstance(sub.func, ast.Name):
                    callee = defs_by_name.get(sub.func.id)
                elif isinstance(sub.func, ast.Attribute):
                    callee = defs_by_name.get(sub.func.attr)
                if callee is not None:
                    handlers.append(callee)

    seen = set()
    for h in handlers:
        if id(h) in seen:
            continue
        seen.add(id(h))
        body = h.body if isinstance(h.body, list) else [h.body]
        for node in body:
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                q = _qualname(sub.func) or ""
                head, _, tail = q.partition(".")
                unsafe = (
                    q in _SIGNAL_UNSAFE_NAMES
                    or (head in al.time and tail == "sleep")
                    or q in ("os.system",)
                    or head in al.subprocess
                    or (isinstance(sub.func, ast.Attribute) and
                        sub.func.attr in _LOGGING_METHODS and
                        "log" in (_qualname(sub.func.value) or "").lower())
                )
                if unsafe:
                    add("DLT103", sub,
                        f"'{q or sub.func.attr}' is not async-signal-"
                        "safe inside a registered signal handler")


def _rule_dlt104(idx, al, path, add) -> None:
    broad = {"Exception", "BaseException"}
    for node in idx.except_handlers:
        if not (len(node.body) == 1 and isinstance(node.body[0], ast.Pass)):
            continue
        t = node.type
        is_broad = (
            t is None
            or (isinstance(t, ast.Name) and t.id in broad)
            or (isinstance(t, ast.Tuple) and any(
                isinstance(e, ast.Name) and e.id in broad
                for e in t.elts)))
        if is_broad:
            add("DLT104", node,
                "broad except whose body is only 'pass' swallows real "
                "failures silently")


def _rule_dlt105(idx, al, path, add) -> None:
    local_defs: Dict[str, ast.AST] = {}
    for node in idx.func_defs:
        local_defs[node.name] = node

    traced: List[ast.AST] = []
    for node in idx.func_defs:
        for dec in node.decorator_list:
            if _is_jit_ref(dec, al) or _is_jit_call(dec, al):
                traced.append(node)
                break
    for node in idx.calls:
        if _is_jit_ref(node.func, al) and node.args:
            target = node.args[0]
            if isinstance(target, ast.Name):
                target = local_defs.get(target.id)
            if isinstance(target, (ast.Lambda, ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                traced.append(target)

    seen = set()
    for fn in traced:
        if id(fn) in seen:
            continue
        seen.add(id(fn))
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for node in body:
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                q = _qualname(sub.func) or ""
                head, _, tail = q.partition(".")
                if q in ("open", "print") or head in al.time:
                    add("DLT105", sub,
                        f"'{q}' inside a traced function runs at TRACE "
                        "time only (and blocks it)")


_PASSES = (_rule_dlt100, _rule_dlt101, _rule_dlt102, _rule_dlt103,
           _rule_dlt104, _rule_dlt105)


# ------------------------------------------------------------- public API
def lint_source(src: str, path: str = "<string>") -> List[Finding]:
    """Lint one module's source. ``path`` decides hot-path scoping and
    is echoed into findings (repo-relative, forward slashes)."""
    path = path.replace(os.sep, "/")
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding("DLT000", path, e.lineno or 0, 0,
                        f"syntax error: {e.msg}")]
    idx = _Index(tree)
    al = _Aliases(idx.nodes)
    lines = src.splitlines()

    def allowed(rule: str, line: int) -> bool:
        for ln in (line, line - 1):
            if 1 <= ln <= len(lines):
                m = _PRAGMA.search(lines[ln - 1])
                if m:
                    allow = {t.strip() for t in m.group(1).split(",")}
                    if "*" in allow or rule in allow:
                        return True
        return False

    findings: List[Finding] = []
    dedup = set()

    def add(rule: str, node: ast.AST, msg: str) -> None:
        line = getattr(node, "lineno", 0)
        col = getattr(node, "col_offset", 0)
        key = (rule, line, col)
        if key in dedup or allowed(rule, line):
            return
        dedup.add(key)
        findings.append(Finding(rule, path, line, col, msg))

    for rule_pass in _PASSES:
        rule_pass(idx, al, path, add)
    findings.sort(key=lambda f: (f.line, f.col, f.rule))
    return findings


def lint_file(abspath: str, root: Optional[str] = None) -> List[Finding]:
    rel = os.path.relpath(abspath, root) if root else abspath
    with open(abspath, encoding="utf-8") as f:
        return lint_source(f.read(), rel)


def repo_root() -> str:
    """The checkout root (two levels above this file's package dir)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def iter_python_files(root: str,
                      scan: Sequence[str] = DEFAULT_SCAN
                      ) -> Iterable[str]:
    for entry in scan:
        full = os.path.join(root, entry)
        if os.path.isfile(full):
            yield full
        elif os.path.isdir(full):
            for dirpath, dirnames, filenames in os.walk(full):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git",
                                            "runs", ".jax_cache")]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)


def lint_tree(root: Optional[str] = None,
              scan: Sequence[str] = DEFAULT_SCAN
              ) -> Tuple[List[Finding], int]:
    """Lint the whole tree. Returns (findings, files_scanned)."""
    root = root or repo_root()
    findings: List[Finding] = []
    n_files = 0
    for path in iter_python_files(root, scan):
        n_files += 1
        findings.extend(lint_file(path, root))
    return findings, n_files


# ---------------------------------------------------------------- ratchet
def counts(findings: Iterable[Finding]) -> Dict[str, Dict[str, int]]:
    out: Dict[str, Dict[str, int]] = {}
    for f in findings:
        out.setdefault(f.path, {})
        out[f.path][f.rule] = out[f.path].get(f.rule, 0) + 1
    return {p: dict(sorted(r.items())) for p, r in sorted(out.items())}


def load_baseline(path: str = DEFAULT_BASELINE) -> Dict[str, Any]:
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return {"version": 1, "counts": {}}
    data.setdefault("counts", {})
    return data


def write_baseline(findings: Iterable[Finding],
                   path: str = DEFAULT_BASELINE) -> Dict[str, Any]:
    data = {"version": 1, "rules": sorted(RULES),
            "counts": counts(findings)}
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return data


def new_findings(findings: Sequence[Finding],
                 baseline: Optional[Dict[str, Any]] = None
                 ) -> List[Dict[str, Any]]:
    """Groups whose count exceeds the baseline budget. Each entry names
    the file, rule, budget, count, and every finding in the group (line
    numbers move, so the RATCHET is per-(file, rule) count — any finding
    in an over-budget group might be the new one)."""
    if baseline is None:
        baseline = load_baseline()
    budget = baseline.get("counts", {})
    groups: Dict[Tuple[str, str], List[Finding]] = {}
    for f in findings:
        groups.setdefault((f.path, f.rule), []).append(f)
    out = []
    for (path, rule), fs in sorted(groups.items()):
        allowed = int(budget.get(path, {}).get(rule, 0))
        if len(fs) > allowed:
            out.append({"path": path, "rule": rule, "count": len(fs),
                        "budget": allowed,
                        "findings": [str(f) for f in fs]})
    return out


def ratchet_status(root: Optional[str] = None,
                   baseline_path: str = DEFAULT_BASELINE
                   ) -> Dict[str, Any]:
    """One-call summary for bench.py / obs_report.py: scan + compare."""
    findings, n_files = lint_tree(root)
    baseline = load_baseline(baseline_path)
    new = new_findings(findings, baseline)
    b_counts = baseline.get("counts", {})
    return {
        "rules": len(RULES),
        "files_scanned": n_files,
        "findings": len(findings),
        "baseline_findings": sum(sum(r.values())
                                 for r in b_counts.values()),
        "baseline_files": len(b_counts),
        "new_groups": len(new),
        "new": new,
        "clean": not new,
    }
