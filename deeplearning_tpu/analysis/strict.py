"""Runtime strict mode: JAX's own sanitizers scoped to our hot loops.

The static linter (``analysis/lint.py``) and the jaxpr auditor
(``analysis/jaxpr.py``) reason about code; this module arms the runtime.
``Trainer(strict="transfers")`` (or ``DLTPU_STRICT=1`` in the
environment) wraps every hot-loop step region in
``jax.transfer_guard_device_to_host("disallow")``, turning the "≤1 sync
per log window" claim from a counter-based test into a hard runtime
error at the exact offending line. ``strict="nans"`` arms
``jax_debug_nans`` for the whole run (composes with the
``train/recovery.py`` fault injection: the injected NaN is caught at the
emitting primitive instead of steps later in the metrics ring).
``strict="threads"`` (``DLTPU_STRICT=threads``) arms the runtime thread
sanitizer (``analysis/threadsan.py``): instrumented Lock/RLock in the
serving/elastic fleet modules, lock-order cycle detection seeded from
the static graph, flightrec-style autopsy on violation.

Caveat the tests rely on: the CPU backend shares one address space with
the host, so device→host "transfers" are zero-copy views and the d2h
guard NEVER fires there — it has teeth on TPU/GPU only. Host→device
transfers DO copy on CPU and the h2d guard raises even there.
``guard_enforced(kind)`` probes the running backend so tests can skip
negative cases the backend cannot enforce.
"""

from __future__ import annotations

import contextlib
import os
from typing import FrozenSet, Iterator, Optional, Union

import jax

__all__ = [
    "MODES", "resolve", "no_host_transfers", "no_transfers",
    "debug_nans", "strict_section", "guard_enforced", "StrictError",
    "maybe_enable_threads",
]

MODES = ("transfers", "nans", "threads")

# what a bare opt-in ("1", "true", "on", "all") arms
_DEFAULT_MODES = frozenset({"transfers"})

StrictError = jax.errors.JaxRuntimeError


def resolve(value: Union[str, bool, None] = None,
            env: str = "DLTPU_STRICT") -> FrozenSet[str]:
    """Normalize a strict spec into the set of armed modes.

    ``value`` wins when given (``True``/``"1"`` → transfers;
    ``"transfers,nans"``/``"all"`` → both; ``False``/``""``/``"0"`` →
    none); otherwise the ``DLTPU_STRICT`` env var is consulted so any
    entry point gains strict mode without a code change.
    """
    if value is None:
        value = os.environ.get(env, "")
    if isinstance(value, bool):
        return _DEFAULT_MODES if value else frozenset()
    value = str(value).strip().lower()
    if value in ("", "0", "false", "off", "none"):
        return frozenset()
    if value in ("1", "true", "on"):
        return _DEFAULT_MODES
    if value == "all":
        return frozenset(MODES)
    modes = frozenset(m.strip() for m in value.split(",") if m.strip())
    unknown = modes - frozenset(MODES)
    if unknown:
        raise ValueError(
            f"unknown strict mode(s) {sorted(unknown)}; "
            f"valid: {MODES}, '1'/'all', or ''")
    return modes


def maybe_enable_threads(modes: FrozenSet[str]) -> bool:
    """Arm the runtime thread sanitizer when ``"threads"`` is in the
    resolved mode set. Called once per entry point (Trainer._obs_start,
    tools/serve.py) BEFORE the fleet objects construct their locks —
    enable() instruments module ``threading`` attributes, so locks
    created earlier stay raw."""
    if "threads" not in modes:
        return False
    from . import threadsan
    threadsan.enable()
    return True


@contextlib.contextmanager
def no_transfers(kind: str = "device_to_host") -> Iterator[None]:
    """Disallow implicit ``kind`` transfers inside the block.
    ``kind`` ∈ {"device_to_host", "host_to_device", "all"}."""
    if kind == "device_to_host":
        ctx = jax.transfer_guard_device_to_host("disallow")
    elif kind == "host_to_device":
        ctx = jax.transfer_guard_host_to_device("disallow")
    elif kind == "all":
        ctx = jax.transfer_guard("disallow")
    else:
        raise ValueError(f"unknown transfer kind {kind!r}")
    with ctx:
        yield


def no_host_transfers() -> "contextlib.AbstractContextManager[None]":
    """The hot-loop guard: any device→host materialization inside the
    block (``.item()``, ``np.asarray``, float(), implicit printing)
    raises instead of silently stalling the dispatch pipeline."""
    return no_transfers("device_to_host")


@contextlib.contextmanager
def debug_nans(enable: bool = True) -> Iterator[None]:
    """Arm ``jax_debug_nans`` inside the block (restores the previous
    setting on exit). Under this flag XLA re-runs any computation that
    produced a NaN in op-by-op mode and raises at the emitting
    primitive — expensive, so opt-in via ``strict='nans'`` only."""
    prev = jax.config.jax_debug_nans
    try:
        jax.config.update("jax_debug_nans", bool(enable))
        yield
    finally:
        jax.config.update("jax_debug_nans", prev)


@contextlib.contextmanager
def strict_section(modes: FrozenSet[str]) -> Iterator[None]:
    """The per-step guard region the Trainer wraps around its hot loop.
    Only the transfer guard applies per-section (debug_nans is armed
    run-wide by the Trainer because it changes compiled artifacts)."""
    if "transfers" in modes:
        with no_host_transfers():
            yield
    else:
        yield


def guard_enforced(kind: str = "device_to_host",
                   backend: Optional[str] = None) -> bool:
    """Does the running backend actually raise on a disallowed ``kind``
    transfer?  CPU's zero-copy D2H path makes the d2h guard inert there;
    tests use this probe to skip negative assertions the backend cannot
    produce."""
    import jax.numpy as jnp
    try:
        if kind == "device_to_host":
            x = jnp.arange(4)
            jax.block_until_ready(x)
            with no_transfers(kind):
                float(x[0])  # must attempt a real D2H materialization
        elif kind == "host_to_device":
            import numpy as np
            with no_transfers(kind):
                # must be an IMPLICIT transfer: explicit jax.device_put
                # is always allowed under "disallow"
                jnp.add(np.ones(2), 1.0)
        else:
            raise ValueError(f"unknown transfer kind {kind!r}")
        return False
    except Exception:  # noqa: BLE001 - any raise means the guard works
        return True
