"""dltpu-check: static TPU-policy linter, jaxpr structural auditor, and
runtime strict mode.

``lint`` is stdlib-only and imported eagerly (it must stay usable from
processes that never import jax — ``tools/check.py --ci`` loads it
standalone for exactly that reason). ``jaxpr`` and ``strict`` import
jax, so they resolve lazily on first attribute access.
"""

from __future__ import annotations

import importlib

from . import lint  # noqa: F401  (stdlib-only, safe eager)

_LAZY = ("jaxpr", "strict")

__all__ = ["lint", "jaxpr", "strict"]


def __getattr__(name):
    if name in _LAZY:
        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
