"""Runtime thread sanitizer: instrumented locks for the serving fleet.

``DLTPU_STRICT=threads`` (via ``analysis/strict.py``) swaps the
``threading.Lock`` / ``RLock`` constructors seen by the instrumented
modules for wrappers that:

- record every acquire/release into a bounded ring (thread name, lock
  site, wall time, caller), the flightrec idiom applied to locks;
- maintain the per-thread held stack and the process-wide runtime
  lock-order graph, seeded from the STATIC graph that
  ``analysis/concurrency.py::lock_order_graph()`` computes — both key
  locks by the file:line of their creation site, so a ``with a: with
  b`` order proven in source and the reverse order observed live join
  into one cycle check;
- assert consistency at the two spots where the information exists:
  acquire time (does this edge close a cycle in runtime ∪ static
  edges?) and release time (LIFO discipline; releasing a lock this
  thread does not hold).

On violation the sanitizer dumps an autopsy — the ring, every thread's
held stack, the offending edge and the cycle it closes — to stderr
(and to the flight recorder when that module is loaded) and raises
:class:`LockOrderError`. A single-threaded interleaving is enough to
trip the order check (acquire A→B now, B→A later), which is what makes
the seeded-cycle test deterministic instead of a timing lottery.

Stdlib-only and importable without jax — tests and ``tools/serve.py``
arm it directly; training runs get it through
``strict.maybe_enable_threads``. Instrument BEFORE constructing the
objects whose locks you care about: ``enable()`` patches each module's
``threading`` attribute, so locks created earlier stay raw.
"""

from __future__ import annotations

import collections
import os
import sys
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Set, Tuple

__all__ = [
    "LockOrderError", "InstrumentedLock", "enable", "disable",
    "enabled", "seed_static_edges", "status", "autopsy", "reset",
    "DEFAULT_MODULES", "RING_SIZE",
]

# modules whose locks the fleet actually contends on; enable() only
# touches the ones already imported (never imports — some pull jax)
DEFAULT_MODULES: Tuple[str, ...] = (
    "deeplearning_tpu.serve.zoo",
    "deeplearning_tpu.serve.batcher",
    "deeplearning_tpu.serve.engine",
    "deeplearning_tpu.obs.flight",
    "deeplearning_tpu.obs.metrics",
    "deeplearning_tpu.obs.fleet",
    "deeplearning_tpu.obs.xla",
    "deeplearning_tpu.obs.threads",
    "deeplearning_tpu.elastic.signals",
    "deeplearning_tpu.elastic.heartbeat",
    "deeplearning_tpu.elastic.supervisor",
    "deeplearning_tpu.data.device_prefetch",
)

RING_SIZE = 512

# originals, captured before any proxying can occur
_ORIG_LOCK = threading.Lock
_ORIG_RLOCK = threading.RLock

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_THIS_FILE = os.path.abspath(__file__)


class LockOrderError(RuntimeError):
    """A lock-discipline violation caught live; carries the autopsy."""

    def __init__(self, msg: str, report: Dict[str, Any]):
        super().__init__(msg)
        self.report = report


class _State:
    """Process-wide sanitizer state, guarded by a RAW lock."""

    def __init__(self) -> None:
        self.mu = _ORIG_LOCK()
        self.enabled = False
        self.edges: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self.static_edges: Set[Tuple[str, str]] = set()
        self.ring: "collections.deque" = collections.deque(
            maxlen=RING_SIZE)
        self.locks: Dict[str, "InstrumentedLock"] = {}
        self.violations = 0
        # fuse: after a violation the sanitizer is record-only until
        # reset() — the raise unwinds through __exit__ calls that would
        # otherwise cascade secondary violations masking the first
        self.tripped = False
        self.patched: List[Tuple[Any, Any]] = []   # (module, old attr)


_S = _State()
_TLS = threading.local()


def _held() -> List["InstrumentedLock"]:
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
    return stack


def _creation_site() -> str:
    """file:line of the frame that called Lock()/RLock(), skipping the
    sanitizer's own frames — the same key the static graph uses."""
    frame = sys._getframe(1)
    while frame is not None:
        fn = frame.f_code.co_filename
        if os.path.abspath(fn) != _THIS_FILE:
            rel = os.path.relpath(fn, _REPO_ROOT).replace(os.sep, "/")
            return f"{rel}:{frame.f_lineno}"
        frame = frame.f_back
    return "<unknown>:0"


def _record(action: str, site: str) -> None:
    _S.ring.append({
        "t": time.time(),
        "thread": threading.current_thread().name,
        "action": action,
        "lock": site,
        "held": [lk.site for lk in _held()],
    })


def _adjacency() -> Dict[str, Set[str]]:
    adj: Dict[str, Set[str]] = {}
    for (a, b) in _S.edges:
        adj.setdefault(a, set()).add(b)
    for (a, b) in _S.static_edges:
        adj.setdefault(a, set()).add(b)
    return adj


def _path_between(adj: Dict[str, Set[str]], src: str, dst: str
                  ) -> Optional[List[str]]:
    """A src→dst path in the edge set, if one exists (BFS)."""
    prev: Dict[str, str] = {}
    todo = collections.deque([src])
    seen = {src}
    while todo:
        node = todo.popleft()
        if node == dst:
            out = [node]
            while node != src:
                node = prev[node]
                out.append(node)
            return out[::-1]
        for nxt in adj.get(node, ()):
            if nxt not in seen:
                seen.add(nxt)
                prev[nxt] = node
                todo.append(nxt)
    return None


def _violate(kind: str, msg: str, **extra: Any) -> None:
    _S.violations += 1
    _S.tripped = True
    report = autopsy()
    report["violation"] = {"kind": kind, "msg": msg, **extra}
    try:
        sys.stderr.write(f"[threadsan] {kind}: {msg}\n")
        for ev in list(_S.ring)[-16:]:
            sys.stderr.write(
                f"[threadsan]   {ev['thread']} {ev['action']} "
                f"{ev['lock']} held={ev['held']}\n")
    # dltpu: allow(DLT104) stderr reporting must never mask the raise below
    except Exception:  # noqa: BLE001
        pass
    try:  # flightrec autopsy ride-along, when obs.flight is loaded
        flight = sys.modules.get("deeplearning_tpu.obs.flight")
        if flight is not None:
            flight.record("threadsan_violation", kind=kind, msg=msg)
    # dltpu: allow(DLT104) ride-along telemetry; the raise below still fires
    except Exception:  # noqa: BLE001
        pass
    raise LockOrderError(f"{kind}: {msg}", report)


def _on_acquired(lock: "InstrumentedLock") -> None:
    held = _held()
    with _S.mu:
        _record("acquire", lock.site)
        for h in held:
            if h.site == lock.site:
                continue           # RLock re-entry: no fresh edge
            edge = (h.site, lock.site)
            if edge in _S.edges:
                _S.edges[edge]["count"] += 1
                continue
            adj = _adjacency()
            back = _path_between(adj, lock.site, h.site)
            _S.edges[edge] = {
                "count": 1,
                "thread": threading.current_thread().name,
            }
            if back is not None and not _S.tripped:
                cycle = back + [lock.site]
                held_sites = [lk.site for lk in held]
                # raising inside the with below would hold mu; record
                # first, raise after
                _S.ring.append({
                    "t": time.time(),
                    "thread": threading.current_thread().name,
                    "action": "cycle", "lock": lock.site,
                    "held": held_sites,
                })
                kind = "lock-order-inversion"
                msg = (f"acquiring {lock.site} while holding "
                       f"{held_sites} closes the cycle "
                       f"{' -> '.join(cycle)}")
                break
        else:
            held.append(lock)
            return
    held.append(lock)              # the acquire DID succeed
    _violate(kind, msg, cycle=cycle)


def _on_release(lock: "InstrumentedLock") -> None:
    held = _held()
    with _S.mu:
        _record("release", lock.site)
    for i in range(len(held) - 1, -1, -1):
        if held[i] is lock:
            if (i != len(held) - 1 and not lock.reentrant
                    and not _S.tripped):
                _violate(
                    "non-lifo-release",
                    f"releasing {lock.site} while "
                    f"{[lk.site for lk in held[i + 1:]]} acquired "
                    "after it are still held")
            del held[i]
            return
    if _S.tripped:
        return
    _violate("release-unheld",
             f"thread {threading.current_thread().name} releases "
             f"{lock.site} it does not hold")


class InstrumentedLock:
    """Drop-in Lock/RLock wrapper feeding the sanitizer state."""

    def __init__(self, reentrant: bool = False):
        self._inner = _ORIG_RLOCK() if reentrant else _ORIG_LOCK()
        self.reentrant = reentrant
        self.site = _creation_site()
        with _S.mu:
            _S.locks[self.site] = self

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            _on_acquired(self)
        return ok

    def release(self) -> None:
        _on_release(self)
        self._inner.release()

    def locked(self) -> bool:
        try:
            return self._inner.locked()
        except AttributeError:     # RLock pre-3.12 has no .locked()
            return any(lk is self for lk in _held())

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def __repr__(self) -> str:
        kind = "RLock" if self.reentrant else "Lock"
        return f"<InstrumentedLock {kind} {self.site}>"


class _ThreadingProxy:
    """Per-module stand-in for the ``threading`` module: Lock/RLock
    construct instrumented wrappers, everything else forwards. Swapping
    a module's ``threading`` attribute (not the global module) keeps
    the blast radius to the instrumented fleet."""

    def __init__(self) -> None:
        self.__dict__["_real"] = threading

    def Lock(self) -> InstrumentedLock:  # noqa: N802 - stand-in name
        return InstrumentedLock(reentrant=False)

    def RLock(self) -> InstrumentedLock:  # noqa: N802
        return InstrumentedLock(reentrant=True)

    def __getattr__(self, name: str) -> Any:
        return getattr(self.__dict__["_real"], name)


def seed_static_edges(graph: Optional[Dict[str, Any]] = None) -> int:
    """Load ``concurrency.lock_order_graph()`` edges (or a precomputed
    graph dict) into the runtime check. Returns edges seeded."""
    if graph is None:
        from . import concurrency
        graph = concurrency.lock_order_graph()
    locks = graph.get("locks", {})

    def site(lock_id: str) -> Optional[str]:
        meta = locks.get(lock_id)
        if meta is None:
            return None
        return f"{meta['path']}:{meta['line']}"

    n = 0
    with _S.mu:
        for e in graph.get("edges", ()):
            a, b = site(e["src"]), site(e["dst"])
            if a and b and a != b:
                _S.static_edges.add((a, b))
                n += 1
    return n


def enable(modules: Optional[Iterable[Any]] = None,
           seed_static: bool = True) -> List[str]:
    """Arm the sanitizer: patch each module's ``threading`` attribute.

    ``modules`` may be module objects or dotted names; default is every
    :data:`DEFAULT_MODULES` entry already imported. Idempotent.
    Returns the names actually patched this call."""
    targets: List[Any] = []
    if modules is None:
        for name in DEFAULT_MODULES:
            mod = sys.modules.get(name)
            if mod is not None:
                targets.append(mod)
    else:
        for m in modules:
            mod = sys.modules.get(m) if isinstance(m, str) else m
            if mod is not None:
                targets.append(mod)
    patched: List[str] = []
    with _S.mu:
        already = {id(mod) for mod, _old in _S.patched}
        for mod in targets:
            if id(mod) in already:
                continue
            old = getattr(mod, "threading", None)
            if old is None or isinstance(old, _ThreadingProxy):
                continue
            mod.threading = _ThreadingProxy()
            _S.patched.append((mod, old))
            patched.append(getattr(mod, "__name__", repr(mod)))
        _S.enabled = True
    if seed_static:
        try:
            seed_static_edges()
        # the runtime check still works from runtime-observed edges
        # dltpu: allow(DLT104) static seed is best-effort
        except Exception:  # noqa: BLE001
            pass
    return patched


def disable() -> None:
    """Restore every patched module and stop recording. Existing
    InstrumentedLock instances keep working (they only log)."""
    with _S.mu:
        for mod, old in _S.patched:
            mod.threading = old
        _S.patched.clear()
        _S.enabled = False


def enabled() -> bool:
    return _S.enabled


def reset() -> None:
    """Drop recorded state (edges/ring/locks) but keep patches — test
    isolation between cases sharing one process."""
    with _S.mu:
        _S.edges.clear()
        _S.static_edges.clear()
        _S.ring.clear()
        _S.locks.clear()
        _S.violations = 0
        _S.tripped = False
    # this thread's held stack may reference pre-reset locks (a raise
    # mid-__enter__ leaves them); other threads' stacks live in their
    # own TLS and drain as those threads unwind
    _TLS.stack = []


def status() -> Dict[str, Any]:
    with _S.mu:
        return {
            "enabled": _S.enabled,
            "locks_instrumented": len(_S.locks),
            "runtime_edges": len(_S.edges),
            "static_edges": len(_S.static_edges),
            "ring_events": len(_S.ring),
            "violations": _S.violations,
            "tripped": _S.tripped,
            "modules_patched": len(_S.patched),
        }


def autopsy() -> Dict[str, Any]:
    """Flightrec-style snapshot: the ring, the graphs, the held stacks
    (this thread's; other threads' stacks live in their TLS and show up
    through the ring's ``held`` field)."""
    with _S.mu:
        return {
            "ring": list(_S.ring),
            "edges": {f"{a} -> {b}": dict(meta)
                      for (a, b), meta in _S.edges.items()},
            "static_edges": sorted(f"{a} -> {b}"
                                   for a, b in _S.static_edges),
            "locks": sorted(_S.locks),
            "violations": _S.violations,
            "held_here": [lk.site for lk in _held()],
        }
