"""dltpu-check v2: concurrency auditor for the serving/elastic thread fleet.

The repo now runs a real thread fleet — zoo loader threads, the batcher
dispatch loop, heartbeat/metrics/fleet-scrape daemons, prefetch workers,
supervisor watchers — and every one of the lock-discipline rules that
keeps them honest lived only in code review. This module is the third
analysis layer (after ``lint.py``'s DLT1xx policy rules and
``jaxpr_audit.py``'s structural audits): six concurrency rules over the
stdlib ``ast``, sharing ``lint.py``'s Finding/pragma/ratchet machinery
so ``tools/check.py --ci`` gates them identically:

  DLT200  shared mutable ``self.X`` written from a thread-entry function
          (any ``Thread(target=...)`` / ``obs_threads.spawn(...)``
          callee, resolved transitively one level) AND written from a
          public method without holding the class's lock.
  DLT201  lock acquired in inconsistent order across functions: the
          static lock-order graph (``with``-nesting plus ``acquire()``
          sequencing per scope) contains a cycle — a potential deadlock.
  DLT202  indefinite blocking call (``queue.get()`` / ``.join()`` /
          ``.acquire()`` / ``.wait()`` without timeout) while holding a
          lock.
  DLT203  non-daemon thread with no ``join()`` in its spawn scope (and
          no pragma naming the stop-flag protocol that retires it).
  DLT204  ``threading.Thread`` constructed outside the
          ``obs/threads.py`` spawn registry — unregistered threads are
          invisible to the inventory and the sanitizer.
  DLT205  time-of-check/time-of-use: ``if k in self.d`` and the
          ``self.d[k]`` use sit in different lock regions, so the state
          can change between them.

Suppression and ratchet are byte-compatible with DLT1xx: append
``# dltpu: allow(DLT200)`` to the line (or the line above), and
``analysis/baseline.json`` budgets both rule families per (file, rule).

Lock identity — the static/runtime join: every lock this module tracks
is keyed by the file:line of its ``threading.Lock()`` / ``RLock()``
creation site. ``lock_order_graph()`` exports nodes and edges under
that key, and ``analysis/threadsan.py``'s instrumented locks record the
same creator file:line at runtime, so the sanitizer can seed its
order-consistency check from the static graph.

Standalone-loadable: imports nothing heavy. When loaded by path (the
``tools/check.py`` / ``tools/obs_report.py`` pattern) it resolves
``lint.py`` from ``sys.modules`` or loads the adjacent file directly.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import Any, Dict, Iterable, List, Optional, Sequence, Set, Tuple


def _lint_mod():
    """The DLT1xx module, however this one was loaded.

    In-package: a plain relative import. Standalone (loaded by file
    path, no package parent): reuse whichever alias check.py or
    obs_report.py already registered, else load the adjacent lint.py.
    """
    try:
        from . import lint as _lint  # type: ignore[no-redef]
        return _lint
    except ImportError:
        pass
    for name in ("deeplearning_tpu.analysis.lint", "_dltpu_lint",
                 "_dltpu_lint_report"):
        mod = sys.modules.get(name)
        if mod is not None:
            return mod
    import importlib.util
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "lint.py")
    spec = importlib.util.spec_from_file_location("_dltpu_lint", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


_lint = _lint_mod()
Finding = _lint.Finding
_qualname = _lint._qualname
_call_kw = _lint._call_kw
_Index = _lint._Index
_scope_walk = _lint._scope_walk
_PRAGMA = _lint._PRAGMA

__all__ = [
    "RULES", "Finding", "lint_source", "lint_file", "lint_tree",
    "lock_order_graph", "ratchet_status", "DEFAULT_SCAN",
    "DEFAULT_BASELINE", "THREAD_REGISTRY",
]

RULES: Dict[str, str] = {
    "DLT200": "shared attribute written from thread and from public "
              "method without the class's lock",
    "DLT201": "inconsistent lock acquisition order (potential deadlock "
              "cycle)",
    "DLT202": "indefinite blocking call while holding a lock",
    "DLT203": "non-daemon thread with no join() in its spawn scope",
    "DLT204": "threading.Thread created outside the obs/threads.py "
              "spawn registry",
    "DLT205": "check-then-use on shared dict/list across lock regions",
}

# the one file allowed to call threading.Thread directly (DLT204)
THREAD_REGISTRY = "deeplearning_tpu/obs/threads.py"

DEFAULT_SCAN = _lint.DEFAULT_SCAN
DEFAULT_BASELINE = _lint.DEFAULT_BASELINE

# cheap substring gate: a file with no thread/lock vocabulary cannot
# trip any DLT2xx rule, so the tree scan parses only the fleet files
# and the combined --ci run stays inside its 3s budget
_PREFILTER = ("threading", "Thread(", ".spawn(", "Lock(", "_lock")


def _relevant(src: str) -> bool:
    return any(tok in src for tok in _PREFILTER)


# ---------------------------------------------------------- lock model
class _ThreadingAliases:
    """Names that resolve to the threading module / its Lock ctors."""

    def __init__(self, nodes: Iterable[ast.AST]):
        self.modules: Set[str] = set()      # import threading [as t]
        self.lock_ctors: Set[str] = set()   # from threading import Lock
        for node in nodes:
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "threading":
                        self.modules.add(a.asname or a.name)
            elif isinstance(node, ast.ImportFrom):
                if node.module == "threading":
                    for a in node.names:
                        if a.name in ("Lock", "RLock"):
                            self.lock_ctors.add(a.asname or a.name)

    def is_lock_ctor(self, node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        q = _qualname(node.func)
        if q is None:
            return False
        if q in self.lock_ctors:
            return True
        head, _, tail = q.partition(".")
        return head in self.modules and tail in ("Lock", "RLock")


class _Locks:
    """Every lock declared in the file, keyed for the runtime join.

    - ``attrs[class_name][attr]`` = creation line of
      ``self.<attr> = threading.Lock()`` inside that class.
    - ``globals_[name]`` = creation line of a module-level
      ``NAME = threading.Lock()``.
    Lock ids are ``"<path>::<Class>.<attr>"`` / ``"<path>::<name>"``;
    ``line_of`` maps an id back to its creation line.
    """

    def __init__(self, idx: _Index, al: _ThreadingAliases, path: str):
        self.path = path
        self.attrs: Dict[str, Dict[str, int]] = {}
        self.globals_: Dict[str, int] = {}
        self.line_of: Dict[str, int] = {}
        class_of: Dict[ast.AST, str] = {}
        for node in idx.nodes:
            if isinstance(node, ast.ClassDef):
                for sub in ast.walk(node):
                    class_of[sub] = node.name
        for node in idx.nodes:
            if not isinstance(node, ast.Assign):
                continue
            if not al.is_lock_ctor(node.value):
                continue
            line = node.value.lineno
            for t in node.targets:
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self" and node in class_of:
                    cls = class_of[node]
                    self.attrs.setdefault(cls, {})[t.attr] = line
                    self.line_of[f"{path}::{cls}.{t.attr}"] = line
                elif isinstance(t, ast.Name) and node not in class_of:
                    self.globals_[t.id] = line
                    self.line_of[f"{path}::{t.id}"] = line

    def ref(self, expr: ast.AST, class_name: Optional[str]
            ) -> Optional[str]:
        """Lock id for an expression naming a declared lock, else None."""
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self" and class_name:
            if expr.attr in self.attrs.get(class_name, {}):
                return f"{self.path}::{class_name}.{expr.attr}"
            return None
        if isinstance(expr, ast.Name) and expr.id in self.globals_:
            return f"{self.path}::{expr.id}"
        return None


# ------------------------------------------------------- file analysis
class _Analysis:
    """Shared per-file context for every DLT2xx pass."""

    def __init__(self, tree: ast.Module, path: str):
        self.path = path
        self.idx = _Index(tree)
        self.al = _ThreadingAliases(self.idx.nodes)
        self.locks = _Locks(self.idx, self.al, path)
        self.classes: Dict[str, ast.ClassDef] = {}
        self.methods: Dict[str, Dict[str, ast.AST]] = {}
        self.class_of: Dict[ast.AST, str] = {}
        for node in self.idx.nodes:
            if isinstance(node, ast.ClassDef):
                self.classes[node.name] = node
                meths: Dict[str, ast.AST] = {}
                for st in node.body:
                    if isinstance(st, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                        meths[st.name] = st
                self.methods[node.name] = meths
                for sub in ast.walk(node):
                    self.class_of.setdefault(sub, node.name)
        # edges discovered by the DLT201 pass: (src, dst, line, func)
        self.edges: List[Tuple[str, str, int, str]] = []

    def enclosing_func(self, node: ast.AST) -> Optional[ast.AST]:
        up = self.idx.parents.get(node)
        while up is not None:
            if isinstance(up, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return up
            up = self.idx.parents.get(up)
        return None

    def enclosing_class(self, node: ast.AST) -> Optional[str]:
        return self.class_of.get(node)

    # ------------------------------------------------- spawn targets
    def thread_calls(self) -> List[Tuple[ast.Call, str]]:
        """Every Thread(...) / spawn(...) call: (call, kind)."""
        out = []
        for call in self.idx.calls:
            q = _qualname(call.func) or ""
            last = q.rsplit(".", 1)[-1]
            if last == "Thread" and (
                    q in ("Thread", "threading.Thread")
                    or any(q == f"{m}.Thread"
                           for m in self.al.modules)):
                out.append((call, "Thread"))
            elif last == "spawn":
                out.append((call, "spawn"))
        return out

    def thread_entry_methods(self) -> Dict[str, Set[str]]:
        """{class_name: method names reachable from a thread entry},
        resolved transitively one level (an entry's direct self.*
        callees count too). Module-level targets land under ''."""
        entries: Dict[str, Set[str]] = {}

        def record(target: ast.AST, call: ast.Call) -> None:
            if isinstance(target, ast.Attribute) and \
                    isinstance(target.value, ast.Name) and \
                    target.value.id == "self":
                cls = self.enclosing_class(call)
                if cls and target.attr in self.methods.get(cls, {}):
                    entries.setdefault(cls, set()).add(target.attr)
            elif isinstance(target, ast.Name):
                entries.setdefault("", set()).add(target.id)

        for call, kind in self.thread_calls():
            target = _call_kw(call, "target")
            if target is None and kind == "spawn" and call.args:
                target = call.args[0]
            if target is not None:
                record(target, call)

        # one level of transitive closure: self.foo() inside an entry
        for cls, names in list(entries.items()):
            if not cls:
                continue
            meths = self.methods.get(cls, {})
            for name in list(names):
                fn = meths.get(name)
                if fn is None:
                    continue
                for sub in _scope_walk(fn.body):
                    if isinstance(sub, ast.Call) and \
                            isinstance(sub.func, ast.Attribute) and \
                            isinstance(sub.func.value, ast.Name) and \
                            sub.func.value.id == "self" and \
                            sub.func.attr in meths:
                        entries[cls].add(sub.func.attr)
        return entries

    # --------------------------------------------------- guardedness
    def write_guarded(self, node: ast.AST, func: ast.AST,
                      class_name: str) -> bool:
        """Is this write lexically under ``with self._lock`` (any class
        lock), or after a ``self._lock.acquire()`` in the same scope?"""
        up = self.idx.parents.get(node)
        while up is not None and up is not func:
            if isinstance(up, ast.With):
                for item in up.items:
                    if self.locks.ref(item.context_expr, class_name):
                        return True
            up = self.idx.parents.get(up)
        for sub in _scope_walk(func.body):
            if isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr == "acquire" and \
                    sub.lineno <= getattr(node, "lineno", 0) and \
                    self.locks.ref(sub.func.value, class_name):
                return True
        return False

    def self_writes(self, func: ast.AST) -> List[Tuple[str, ast.AST]]:
        """(attr, node) for every ``self.X = ...`` / ``self.X[...] =``
        / ``self.X += ...`` / ``del self.X[...]`` in the function."""
        out: List[Tuple[str, ast.AST]] = []

        def attr_of(t: ast.AST) -> Optional[str]:
            if isinstance(t, ast.Attribute) and \
                    isinstance(t.value, ast.Name) and t.value.id == "self":
                return t.attr
            if isinstance(t, ast.Subscript):
                return attr_of(t.value)
            return None

        for node in _scope_walk(func.body):
            targets: List[ast.AST] = []
            if isinstance(node, ast.Assign):
                targets = list(node.targets)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            elif isinstance(node, ast.Delete):
                targets = list(node.targets)
            for t in targets:
                attr = attr_of(t)
                if attr is not None:
                    out.append((attr, node))
        return out


# ------------------------------------------------------------- passes
def _rule_dlt200(an: _Analysis, add) -> None:
    entries = an.thread_entry_methods()
    for cls, entry_names in entries.items():
        if not cls:
            continue
        lock_attrs = an.locks.attrs.get(cls, {})
        if not lock_attrs:
            continue               # no lock to hold — not this rule's bug
        meths = an.methods.get(cls, {})
        thread_writes: Set[str] = set()
        for name in entry_names:
            fn = meths.get(name)
            if fn is None:
                continue
            for attr, _node in an.self_writes(fn):
                if attr not in lock_attrs:
                    thread_writes.add(attr)
        if not thread_writes:
            continue
        for name, fn in meths.items():
            if name.startswith("_") or name in entry_names:
                continue           # public, non-thread methods only
            for attr, node in an.self_writes(fn):
                if attr not in thread_writes:
                    continue
                if an.write_guarded(node, fn, cls):
                    continue
                add("DLT200", node,
                    f"'{cls}.{attr}' is written by thread entry "
                    f"{sorted(n for n in entry_names if n in meths)} "
                    f"and here in public '{name}()' without holding "
                    f"the class's lock")


def _lock_edges(an: _Analysis) -> None:
    """Populate an.edges: lock-order pairs from with-nesting and
    acquire()/release() sequencing, per function scope."""

    def visit_block(stmts: Sequence[ast.stmt], held: List[str],
                    cls: Optional[str], fname: str) -> None:
        held = list(held)
        for st in stmts:
            held = visit_stmt(st, held, cls, fname)

    def visit_stmt(st: ast.stmt, held: List[str],
                   cls: Optional[str], fname: str) -> List[str]:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return held
        if isinstance(st, ast.With):
            acquired = []
            for item in st.items:
                lk = an.locks.ref(item.context_expr, cls)
                if lk:
                    for h in held:
                        if h != lk:
                            an.edges.append((h, lk, st.lineno, fname))
                    acquired.append(lk)
            visit_block(st.body, held + acquired, cls, fname)
            return held
        if isinstance(st, ast.Expr) and isinstance(st.value, ast.Call):
            call = st.value
            if isinstance(call.func, ast.Attribute):
                lk = an.locks.ref(call.func.value, cls)
                if lk is not None:
                    if call.func.attr == "acquire":
                        for h in held:
                            if h != lk:
                                an.edges.append((h, lk, st.lineno,
                                                 fname))
                        return held + [lk]
                    if call.func.attr == "release":
                        return [h for h in held if h != lk]
        for _field, value in ast.iter_fields(st):
            if isinstance(value, list) and value and \
                    isinstance(value[0], ast.stmt):
                visit_block(value, held, cls, fname)
        return held

    visit_block(an.idx.tree.body, [], None, "<module>")
    for fn in an.idx.func_defs:
        cls = an.enclosing_class(fn)
        visit_block(fn.body, [], cls, fn.name)


def _find_cycles(edges: Iterable[Tuple[str, str]]) -> List[List[str]]:
    """Simple cycles in the lock-order graph, each reported once."""
    adj: Dict[str, Set[str]] = {}
    for src, dst in edges:
        adj.setdefault(src, set()).add(dst)
    cycles: List[List[str]] = []
    seen_cycles: Set[Tuple[str, ...]] = set()

    def dfs(node: str, path: List[str], on_path: Set[str],
            done: Set[str]) -> None:
        on_path.add(node)
        path.append(node)
        for nxt in sorted(adj.get(node, ())):
            if nxt in on_path:
                cyc = path[path.index(nxt):]
                # canonical rotation so each cycle dedups
                k = min(range(len(cyc)), key=lambda i: cyc[i])
                canon = tuple(cyc[k:] + cyc[:k])
                if canon not in seen_cycles:
                    seen_cycles.add(canon)
                    cycles.append(list(canon))
            elif nxt not in done:
                dfs(nxt, path, on_path, done)
        on_path.discard(node)
        path.pop()
        done.add(node)

    done: Set[str] = set()
    for node in sorted(adj):
        if node not in done:
            dfs(node, [], set(), done)
    return cycles


def _rule_dlt201(an: _Analysis, add) -> None:
    _lock_edges(an)
    cycles = _find_cycles((s, d) for s, d, _l, _f in an.edges)
    for cyc in cycles:
        # anchor the finding on the latest edge participating in the
        # cycle — by construction that edge closed it
        pairs = {(cyc[i], cyc[(i + 1) % len(cyc)])
                 for i in range(len(cyc))}
        where = max((e for e in an.edges if (e[0], e[1]) in pairs),
                    key=lambda e: e[2])
        display = " -> ".join(c.split("::", 1)[-1] for c in cyc)
        node = ast.stmt()
        node.lineno, node.col_offset = where[2], 0
        add("DLT201", node,
            f"lock order cycle {display} (edge taken in "
            f"'{where[3]}') — two threads interleaving these orders "
            "deadlock")


_BLOCKING_EXEMPT_KW = ("timeout",)


def _is_blocking_call(call: ast.Call) -> Optional[str]:
    """Name of the indefinitely-blocking method, else None."""
    if not isinstance(call.func, ast.Attribute):
        return None
    attr = call.func.attr
    if _call_kw(call, "timeout") is not None:
        return None
    if attr == "get":
        # dict.get(k[, default]) always has args; queue.get() has none
        blk = _call_kw(call, "block")
        if not call.args and (blk is None or not (
                isinstance(blk, ast.Constant) and blk.value is False)):
            return "get"
        return None
    if attr == "join" and not call.args:
        return "join"
    if attr == "acquire":
        blk = _call_kw(call, "blocking")
        if isinstance(blk, ast.Constant) and blk.value is False:
            return None
        if call.args and isinstance(call.args[0], ast.Constant) and \
                call.args[0].value is False:
            return None
        return "acquire"
    if attr in ("wait", "wait_for") and not call.args:
        return attr
    return None


def _rule_dlt202(an: _Analysis, add) -> None:
    for node in an.idx.nodes:
        if not isinstance(node, ast.With):
            continue
        cls = an.enclosing_class(node)
        held = [item.context_expr for item in node.items
                if an.locks.ref(item.context_expr, cls)]
        if not held:
            continue
        held_q = {_qualname(h) for h in held}
        for sub in _scope_walk(node.body):
            if not isinstance(sub, ast.Call):
                continue
            blocked = _is_blocking_call(sub)
            if blocked is None:
                continue
            recv = _qualname(sub.func.value) \
                if isinstance(sub.func, ast.Attribute) else None
            if blocked in ("wait", "wait_for") and recv in held_q:
                continue       # Condition.wait releases the held lock
            add("DLT202", sub,
                f"'.{blocked}()' with no timeout while holding "
                f"{sorted(q for q in held_q if q)} — a stuck peer "
                "wedges every waiter on this lock")


def _rule_dlt203(an: _Analysis, add) -> None:
    for call, kind in an.thread_calls():
        daemon = _call_kw(call, "daemon")
        if kind == "spawn":
            # registry default is daemon=True
            nondaemon = isinstance(daemon, ast.Constant) and \
                daemon.value is False
        else:
            # threading.Thread default is daemon=False
            nondaemon = daemon is None or (
                isinstance(daemon, ast.Constant) and
                daemon.value is False)
        if not nondaemon:
            continue
        func = an.enclosing_func(call)
        body = func.body if func is not None else an.idx.tree.body
        joined = any(
            isinstance(sub, ast.Call) and
            isinstance(sub.func, ast.Attribute) and
            sub.func.attr == "join"
            for sub in _scope_walk(body))
        if not joined:
            add("DLT203", call,
                "non-daemon thread is never join()ed in this scope — "
                "it outlives shutdown invisibly (join it, or pragma "
                "with the stop-flag that retires it)")


def _rule_dlt204(an: _Analysis, add) -> None:
    if an.path.endswith(THREAD_REGISTRY):
        return
    for call, kind in an.thread_calls():
        if kind != "Thread":
            continue
        add("DLT204", call,
            "threading.Thread outside obs/threads.py — route it "
            "through obs_threads.spawn() so the fleet inventory and "
            "thread sanitizer can see it")


def _rule_dlt205(an: _Analysis, add) -> None:
    def key_repr(node: ast.AST) -> Optional[str]:
        q = _qualname(node)
        if q is not None:
            return q
        if isinstance(node, ast.Constant):
            return repr(node.value)
        return None

    def region_of(node: ast.AST, func: ast.AST,
                  cls: Optional[str]) -> Optional[int]:
        up = an.idx.parents.get(node)
        while up is not None and up is not func:
            if isinstance(up, ast.With):
                for item in up.items:
                    if an.locks.ref(item.context_expr, cls):
                        return id(up)
            up = an.idx.parents.get(up)
        return None

    for fn in an.idx.func_defs:
        if fn.name == "__init__":
            continue
        cls = an.enclosing_class(fn)
        checks: List[Tuple[str, str, int, Optional[int]]] = []
        uses: List[Tuple[str, str, ast.AST, Optional[int]]] = []
        for node in _scope_walk(fn.body):
            if isinstance(node, ast.Compare) and len(node.ops) == 1 \
                    and isinstance(node.ops[0], (ast.In, ast.NotIn)):
                cont = _qualname(node.comparators[0])
                key = key_repr(node.left)
                if cont and cont.startswith("self.") and key:
                    checks.append((cont, key, node.lineno,
                                   region_of(node, fn, cls)))
            elif isinstance(node, ast.Subscript):
                cont = _qualname(node.value)
                key = key_repr(node.slice)
                if cont and cont.startswith("self.") and key:
                    uses.append((cont, key, node,
                                 region_of(node, fn, cls)))
        for cont, key, node, ureg in uses:
            line = node.lineno
            same = [c for c in checks
                    if c[0] == cont and c[1] == key and c[2] <= line]
            if not same:
                continue
            if any(c[3] == ureg and c[3] is not None for c in same):
                continue       # re-checked inside the use's own region
            stale = [c for c in same if c[3] != ureg]
            if stale:
                c = max(stale, key=lambda c: c[2])
                add("DLT205", node,
                    f"'{key} in {cont}' checked at line {c[2]} but "
                    f"'{cont}[{key}]' used here in a different lock "
                    "region — the entry can vanish in between")


_PASSES = (_rule_dlt200, _rule_dlt201, _rule_dlt202, _rule_dlt203,
           _rule_dlt204, _rule_dlt205)


# --------------------------------------------------------- public API
def lint_source(src: str, path: str = "<string>") -> List[Finding]:
    """Concurrency-audit one module's source (pragma-aware)."""
    path = path.replace(os.sep, "/")
    if not _relevant(src):
        return []
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding("DLT000", path, e.lineno or 0, 0,
                        f"syntax error: {e.msg}")]
    an = _Analysis(tree, path)
    lines = src.splitlines()

    def allowed(rule: str, line: int) -> bool:
        for ln in (line, line - 1):
            if 1 <= ln <= len(lines):
                m = _PRAGMA.search(lines[ln - 1])
                if m:
                    allow = {t.strip() for t in m.group(1).split(",")}
                    if "*" in allow or rule in allow:
                        return True
        return False

    findings: List[Finding] = []
    dedup = set()

    def add(rule: str, node: ast.AST, msg: str) -> None:
        line = getattr(node, "lineno", 0)
        col = getattr(node, "col_offset", 0)
        key = (rule, line, col)
        if key in dedup or allowed(rule, line):
            return
        dedup.add(key)
        findings.append(Finding(rule, path, line, col, msg))

    for rule_pass in _PASSES:
        rule_pass(an, add)
    findings.sort(key=lambda f: (f.line, f.col, f.rule))
    return findings


def lint_file(abspath: str, root: Optional[str] = None) -> List[Finding]:
    rel = os.path.relpath(abspath, root) if root else abspath
    with open(abspath, encoding="utf-8") as f:
        return lint_source(f.read(), rel)


def lint_tree(root: Optional[str] = None,
              scan: Sequence[str] = DEFAULT_SCAN
              ) -> Tuple[List[Finding], int]:
    """Audit the whole tree. Returns (findings, files_scanned) — the
    substring prefilter means only fleet files are actually parsed."""
    root = root or _lint.repo_root()
    findings: List[Finding] = []
    n_files = 0
    for path in _lint.iter_python_files(root, scan):
        n_files += 1
        findings.extend(lint_file(path, root))
    return findings, n_files


def lock_order_graph(root: Optional[str] = None,
                     scan: Sequence[str] = DEFAULT_SCAN
                     ) -> Dict[str, Any]:
    """The repo-wide static lock-order graph, keyed for the runtime
    join: every node carries the creation file:line that
    ``threadsan.InstrumentedLock`` also records, so the sanitizer can
    seed its order check from these edges."""
    root = root or _lint.repo_root()
    locks: Dict[str, Dict[str, Any]] = {}
    edges: List[Dict[str, Any]] = []
    spawns: List[Dict[str, Any]] = []
    edge_seen: Set[Tuple[str, str]] = set()
    for abspath in _lint.iter_python_files(root, scan):
        with open(abspath, encoding="utf-8") as f:
            src = f.read()
        if not _relevant(src):
            continue
        rel = os.path.relpath(abspath, root).replace(os.sep, "/")
        try:
            tree = ast.parse(src)
        except SyntaxError:
            continue
        an = _Analysis(tree, rel)
        for lock_id, line in an.locks.line_of.items():
            locks[lock_id] = {"path": rel, "line": line,
                              "name": lock_id.split("::", 1)[-1]}
        for call, kind in an.thread_calls():
            spawns.append({"path": rel, "line": call.lineno,
                           "kind": kind})
        _lock_edges(an)
        for src_id, dst_id, line, func in an.edges:
            if (src_id, dst_id) in edge_seen:
                continue
            edge_seen.add((src_id, dst_id))
            edges.append({"src": src_id, "dst": dst_id,
                          "path": rel, "line": line, "func": func})
    cycles = _find_cycles((e["src"], e["dst"]) for e in edges)
    return {"locks": locks, "edges": edges, "cycles": cycles,
            "spawn_sites": spawns}


def ratchet_status(root: Optional[str] = None,
                   baseline_path: str = DEFAULT_BASELINE
                   ) -> Dict[str, Any]:
    """Concurrency counterpart of ``lint.ratchet_status`` — DLT2xx
    findings vs the shared baseline. Feeds ``bench.py``'s
    ``concurrency_clean`` and the obs_report posture line."""
    findings, n_files = lint_tree(root)
    baseline = _lint.load_baseline(baseline_path)
    new = _lint.new_findings(findings, baseline)
    b_counts = baseline.get("counts", {})
    b_total = sum(n for rules in b_counts.values()
                  for rule, n in rules.items()
                  if rule.startswith("DLT2"))
    return {
        "rules": len(RULES),
        "files_scanned": n_files,
        "findings": len(findings),
        "baseline_findings": b_total,
        "new_groups": len(new),
        "new": new,
        "clean": not new,
    }
