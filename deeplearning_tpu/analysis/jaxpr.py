"""Structural jaxpr auditor: reusable invariant checks over traced fns.

``tests/test_blocked_nms.py`` proved the no-N×N-memory claim by walking
the jaxpr inline; that walk is the general tool for every structural
invariant this repo cares about — peak intermediate size (does the
postprocess really stay O(N·B)?), transfer counts (does the train step
really dispatch zero ``device_put``s?), and collective counts (what does
a sharded step actually all-reduce? — the accounting PAPERS.md
"Automatic Cross-Replica Sharding of Weight Update" and "EQuARX"
optimizations start from). This module is that walk, shared: usable from
any test and from ``tools/check.py --jaxpr`` over the registered
step/postprocess functions.

Everything reasons over ``jax.make_jaxpr`` output — tracing only, no
compile, no device execution — so audits are cheap even on the 1-core
build box.
"""

from __future__ import annotations

import math
import re
from functools import partial
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

import jax

__all__ = [
    "iter_eqns", "iter_avals", "peak_intermediate",
    "assert_peak_intermediate_below", "count_primitive",
    "count_transfers", "count_collectives", "collective_bytes",
    "hlo_collectives", "count_hlo_collectives", "hlo_collective_bytes",
    "Audit", "builtin_audits", "run_audits",
]

# primitives that move bytes between host and device (or between
# devices) when they appear inside a traced computation
TRANSFER_PRIMITIVES = ("device_put", "copy")

# cross-replica communication primitives (jax.lax collectives + the
# names GSPMD lowers shard_map bodies to)
COLLECTIVE_PRIMITIVES = (
    "psum", "pmax", "pmin", "pmean", "all_gather", "all_to_all",
    "ppermute", "pshuffle", "reduce_scatter", "psum_scatter",
    "pbroadcast", "allreduce",
)


def _as_jaxpr(obj):
    """Accept Jaxpr, ClosedJaxpr, or anything with a ``.jaxpr``."""
    if hasattr(obj, "eqns"):
        return obj
    if hasattr(obj, "jaxpr"):
        return obj.jaxpr
    raise TypeError(f"not a jaxpr: {type(obj)!r}")


def iter_eqns(jaxpr) -> Iterable[Any]:
    """Every equation in ``jaxpr`` and all nested sub-jaxprs (pjit
    bodies, scan/while/cond branches, custom_* calls)."""
    jaxpr = _as_jaxpr(jaxpr)
    for eqn in jaxpr.eqns:
        yield eqn
        for p in eqn.params.values():
            if hasattr(p, "eqns") or hasattr(p, "jaxpr"):
                yield from iter_eqns(p)
            elif isinstance(p, (tuple, list)):
                for q in p:
                    if hasattr(q, "eqns") or hasattr(q, "jaxpr"):
                        yield from iter_eqns(q)


def iter_avals(jaxpr) -> Iterable[Any]:
    """Abstract values of every equation OUTPUT, nested jaxprs included
    — the exact set the original inline walk in test_blocked_nms.py
    measured (inputs/consts excluded), kept identical so ported bounds
    stay bitwise the same."""
    for eqn in iter_eqns(jaxpr):
        for v in eqn.outvars:
            yield v.aval


def _trace(fn: Callable, *args, **kwargs):
    return jax.make_jaxpr(fn)(*args, **kwargs)


def peak_intermediate(fn: Callable, *args, **kwargs) -> int:
    """Largest intermediate (in ELEMENTS, not bytes) any equation in the
    traced ``fn(*args)`` produces; 0 when there are no shaped outputs.
    Scalars count as 1 element (``prod(()) == 1``)."""
    closed = _trace(fn, *args, **kwargs)
    return max((int(math.prod(a.shape)) for a in iter_avals(closed.jaxpr)
                if getattr(a, "shape", None) is not None), default=0)


def assert_peak_intermediate_below(fn: Callable, args: Tuple,
                                   max_elements: int,
                                   msg: str = "") -> int:
    """Assert the traced ``fn(*args)`` never materializes an
    intermediate above ``max_elements`` elements. Returns the measured
    peak so callers can report/log it."""
    peak = peak_intermediate(fn, *args)
    assert peak <= max_elements, (
        f"peak intermediate {peak} elements exceeds budget "
        f"{max_elements}" + (f" ({msg})" if msg else ""))
    return peak


def count_primitive(fn: Callable, name, *args, **kwargs) -> int:
    """Occurrences of primitive(s) ``name`` (a str or tuple of strs) in
    the traced ``fn(*args)``, nested jaxprs included."""
    names = (name,) if isinstance(name, str) else tuple(name)
    closed = _trace(fn, *args, **kwargs)
    return sum(1 for eqn in iter_eqns(closed.jaxpr)
               if eqn.primitive.name in names)


def count_transfers(fn: Callable, *args, **kwargs) -> int:
    """Host/device transfer primitives inside the traced computation.
    The sync-free hot-loop contract says this is 0 for the train step:
    batches arrive placed (DevicePrefetcher) and metrics leave lazily
    (DeferredMetrics), so nothing inside the step moves bytes itself."""
    return count_primitive(fn, TRANSFER_PRIMITIVES, *args, **kwargs)


def count_collectives(fn: Callable, *args,
                      axis_env: Optional[List[Tuple[str, int]]] = None,
                      **kwargs) -> Dict[str, int]:
    """Per-primitive counts of cross-replica collectives in the traced
    ``fn(*args)`` — ``{"psum": 2, "all_gather": 1}``-shaped; empty when
    the computation is collective-free. ``axis_env`` names mapped axes
    for functions that psum over an axis outside pmap/shard_map (same
    contract as ``jax.make_jaxpr``'s)."""
    mk = jax.make_jaxpr(fn, axis_env=axis_env) if axis_env else \
        jax.make_jaxpr(fn)
    closed = mk(*args, **kwargs)
    out: Dict[str, int] = {}
    for eqn in iter_eqns(closed.jaxpr):
        nm = eqn.primitive.name
        if nm in COLLECTIVE_PRIMITIVES:
            out[nm] = out.get(nm, 0) + 1
    return out


def collective_bytes(fn: Callable, *args,
                     axis_env: Optional[List[Tuple[str, int]]] = None,
                     **kwargs) -> Dict[str, int]:
    """Per-primitive OPERAND bytes of cross-replica collectives in the
    traced ``fn(*args)`` — what each collective puts on the wire (before
    any topology-aware lowering), summed per primitive name. The byte
    companion to ``count_collectives``; same axis_env contract."""
    mk = jax.make_jaxpr(fn, axis_env=axis_env) if axis_env else \
        jax.make_jaxpr(fn)
    closed = mk(*args, **kwargs)
    out: Dict[str, int] = {}
    for eqn in iter_eqns(closed.jaxpr):
        nm = eqn.primitive.name
        if nm not in COLLECTIVE_PRIMITIVES:
            continue
        nbytes = 0
        for v in eqn.invars:
            aval = getattr(v, "aval", None)
            shape = getattr(aval, "shape", None)
            dtype = getattr(aval, "dtype", None)
            if shape is not None and dtype is not None:
                nbytes += int(math.prod(shape)) * dtype.itemsize
        out[nm] = out.get(nm, 0) + nbytes
    return out


# ---------------------------------------------------------------------------
# Compiled-HLO collective accounting. GSPMD inserts collectives during
# SPMD partitioning, AFTER tracing — a jitted step's jaxpr shows none of
# them, so proving "zero1 lowers the gradient all-reduce to
# reduce-scatter + all-gather" requires reading the post-optimization
# HLO. One platform wart is handled here: XLA's ReduceScatterCreator
# combiner runs on TPU/GPU only, so on CPU the reduce-scatter appears as
# all-reduce followed by a partition dynamic-slice (full result, then
# each replica keeps its 1/n). ``hlo_collectives`` reclassifies that
# pair as ``reduce_scatter`` — it IS the reduce-scatter this program
# lowers to on TPU — which keeps the audit meaningful on the CPU CI box.
# ---------------------------------------------------------------------------

_HLO_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
}

_HLO_OP_RE = re.compile(
    r"=\s*(?P<dt>[a-z]+\d*)\[(?P<shape>[\d,]*)\](?:\{[^}]*\})?\s*"
    r"(?P<op>all-reduce|reduce-scatter|all-gather|all-to-all|"
    r"collective-permute|dynamic-slice)(?P<suffix>-start|-done)?"
    r"\(\s*(?:(?P<odt>[a-z]+\d*)\[(?P<oshape>[\d,]*)\])?")


def _shape_elems(shape_str: str) -> int:
    return int(math.prod(int(d) for d in shape_str.split(",") if d))


def _hlo_module_text(obj: Any, *args, **kwargs) -> str:
    """Post-optimization HLO text from a str, a compiled executable
    (``jit(f).lower(...).compile()``), or a callable + example args
    (jitted or not — plain callables are wrapped in ``jax.jit``)."""
    if isinstance(obj, str):
        return obj
    if hasattr(obj, "lower"):                      # jitted function
        return obj.lower(*args, **kwargs).compile().as_text()
    if hasattr(obj, "compile"):                    # Lowered
        return obj.compile().as_text()
    if hasattr(obj, "as_text"):                    # Compiled executable
        return obj.as_text()
    return jax.jit(obj).lower(*args, **kwargs).compile().as_text()


def hlo_collectives(obj: Any, *args, reclassify_scatter: bool = True,
                    **kwargs) -> Dict[str, Dict[str, int]]:
    """Collectives in compiled HLO: ``{op: {count, bytes, max_bytes}}``
    with underscore op names (``all_reduce``, ``reduce_scatter``, ...);
    bytes are the op's OUTPUT buffer (sum / max over occurrences).
    Async ``-start``/``-done`` pairs count once. With
    ``reclassify_scatter`` (default), an all-reduce whose full result
    feeds a dynamic-slice producing exactly 1/num_partitions of it is
    reported as ``reduce_scatter`` (see module comment: XLA:CPU lacks
    the reduce-scatter combiner pass)."""
    text = _hlo_module_text(obj, *args, **kwargs)
    m = re.search(r"num_partitions=(\d+)", text)
    n_part = int(m.group(1)) if m else 1

    colls: List[Tuple[str, str, str]] = []   # (op, dtype, shape)
    slices: List[Tuple[str, str]] = []       # (out_shape, operand_shape)
    for mo in _HLO_OP_RE.finditer(text):
        if mo.group("suffix") == "-done":
            continue
        op = mo.group("op")
        if op == "dynamic-slice":
            if mo.group("oshape") is not None:
                slices.append((mo.group("shape"), mo.group("oshape")))
            continue
        colls.append((op, mo.group("dt"), mo.group("shape")))

    def is_scattered(shape: str) -> bool:
        if n_part <= 1:
            return False
        elems = _shape_elems(shape)
        return any(osh == shape and _shape_elems(sh) * n_part == elems
                   for sh, osh in slices)

    out: Dict[str, Dict[str, int]] = {}
    for op, dt, shape in colls:
        if (reclassify_scatter and op == "all-reduce"
                and is_scattered(shape)):
            op = "reduce-scatter"
        name = op.replace("-", "_")
        nbytes = _shape_elems(shape) * _HLO_DTYPE_BYTES.get(dt, 4)
        row = out.setdefault(name, {"count": 0, "bytes": 0, "max_bytes": 0})
        row["count"] += 1
        row["bytes"] += nbytes
        row["max_bytes"] = max(row["max_bytes"], nbytes)
    return out


def count_hlo_collectives(obj: Any, *args, **kwargs) -> Dict[str, int]:
    """``{op: count}`` view of ``hlo_collectives``."""
    return {op: row["count"]
            for op, row in hlo_collectives(obj, *args, **kwargs).items()}


def hlo_collective_bytes(obj: Any, *args, **kwargs) -> Dict[str, int]:
    """``{op: total_bytes}`` view of ``hlo_collectives``."""
    return {op: row["bytes"]
            for op, row in hlo_collectives(obj, *args, **kwargs).items()}


# --------------------------------------------------------------- audits
class Audit:
    """One registered structural check for ``tools/check.py --jaxpr``:
    trace ``fn(*args)``, measure peak/transfers/collectives, compare to
    the declared budgets. ``max_elements=None`` means unbounded (the
    reference rows exist to show the auditor SEES the blow-up)."""

    def __init__(self, name: str, fn: Callable, args: Tuple, *,
                 max_elements: Optional[int] = None,
                 max_transfers: Optional[int] = 0,
                 min_elements: Optional[int] = None,
                 extra: Optional[Callable[[], Tuple[bool, Dict]]] = None,
                 note: str = ""):
        self.name = name
        self.fn = fn
        self.args = args
        self.max_elements = max_elements
        self.max_transfers = max_transfers
        self.min_elements = min_elements
        self.extra = extra
        self.note = note

    def run(self) -> Dict[str, Any]:
        row: Dict[str, Any] = {"name": self.name, "note": self.note}
        try:
            row["peak_elements"] = peak_intermediate(self.fn, *self.args)
            row["transfers"] = count_transfers(self.fn, *self.args)
            row["collectives"] = count_collectives(self.fn, *self.args)
            ok = True
            if self.max_elements is not None:
                row["budget_elements"] = self.max_elements
                ok &= row["peak_elements"] <= self.max_elements
            if self.min_elements is not None:
                ok &= row["peak_elements"] >= self.min_elements
            if self.max_transfers is not None:
                ok &= row["transfers"] <= self.max_transfers
            if self.extra is not None:
                # audit-specific measurement (e.g. compiled-HLO
                # collective checks); its dict merges into the row
                extra_ok, extra_row = self.extra()
                row.update(extra_row)
                ok &= extra_ok
            row["ok"] = bool(ok)
        except Exception as e:  # noqa: BLE001 - a broken audit must report
            row["ok"] = False
            row["error"] = repr(e)
        return row


def builtin_audits() -> List[Audit]:
    """The registered step/postprocess functions with their structural
    budgets — the tentpole invariants, re-checkable on demand:

    - blocked NMS stays O(N·B) (the test_blocked_nms bound, N=4096);
    - the reference NMS row PROVES the auditor sees an N×N blow-up;
    - one-pass RoIAlign does <=8 gathers (one sampling pass);
    - the mnist train step traces with zero transfer primitives (the
      PR 1 sync-free contract, structural form);
    - interleaved two-tenant zoo dispatch leaves every engine's
      trace/compile counters exactly where warmup put them (the
      per-model zero-recompiles-after-warmup contract of serve/zoo.py);
    - (>= 2 devices only) the zero1 train step compiles to
      reduce-scatter + all-gather with no param-sized all-reduce, with
      the replicated step as the control row that DOES show the
      all-reduce zero1 replaced.
    """
    import jax.numpy as jnp

    from ..ops import nms as nms_ops
    from ..ops import roi_align as roi_ops

    audits: List[Audit] = []
    n, block = 4096, 256
    boxes = jnp.zeros((n, 4))
    scores = jnp.zeros((n,))
    audits.append(Audit(
        f"nms_blocked_n{n}",
        partial(nms_ops.nms_blocked, iou_threshold=0.5, max_out=100,
                block_size=block),
        (boxes, scores),
        max_elements=4 * n * block,
        note=f"O(N*B) budget, B={block}"))
    audits.append(Audit(
        f"nms_reference_n{n}",
        partial(nms_ops.nms_reference, iou_threshold=0.5, max_out=100),
        (boxes, scores),
        min_elements=n * n,
        note="control: auditor must SEE the N^2 buffer"))

    pyr = {f"p{lv}": jnp.zeros((64 >> (lv - 2), 64 >> (lv - 2), 8))
           for lv in (2, 3, 4, 5)}
    rois = jnp.zeros((16, 4))
    audits.append(Audit(
        "roi_align_onepass",
        partial(roi_ops.multiscale_roi_align),
        (pyr, rois),
        note="one-pass multiscale gather"))

    def train_step_audit() -> Audit:
        from ..core.registry import MODELS
        from ..train import TrainState, make_train_step
        from ..train.classification import make_loss_fn
        from ..train.optim import build_optimizer
        from ..train.schedules import build_schedule

        model = MODELS.build("mnist_fcn", num_classes=4,
                             dtype=jnp.float32)
        params = model.init(jax.random.key(0),
                            jnp.zeros((1, 16, 16, 1)))["params"]
        tx = build_optimizer("sgd", build_schedule("constant",
                                                   base_lr=1e-2),
                             params=params)
        state = TrainState.create(apply_fn=model.apply, params=params,
                                  tx=tx)
        batch = {"image": jnp.zeros((8, 16, 16, 1)),
                 "label": jnp.zeros((8,), jnp.int32)}
        step = make_train_step(make_loss_fn(), donate=False)
        rng = jax.random.key(0)
        return Audit("train_step_mnist", step, (state, batch, rng),
                     max_transfers=0,
                     note="hot-loop step: zero transfer primitives")

    audits.append(train_step_audit())

    def zoo_multimodel_audit() -> Audit:
        import numpy as np

        from ..serve import MicroBatcher, ModelZoo

        def extra():
            zoo = ModelZoo()
            for alias in ("a", "b"):
                zoo.register(alias, "mnist_fcn", num_classes=4,
                             image_size=16, batch_buckets=(1, 2))
                zoo.load(alias, wait=True)
            warm = {a: (zoo.engine(a).trace_count,
                        zoo.engine(a).compile_count) for a in ("a", "b")}
            img = np.zeros((16, 16, 3), np.float32)
            with MicroBatcher(zoo=zoo, max_wait_ms=1.0) as mb:
                handles = [mb.submit(img, model=("a", "b")[i % 2])
                           for i in range(8)]
                for h in handles:
                    h.result(timeout=120.0)
            ok, row = True, {}
            for a in ("a", "b"):
                eng = zoo.engine(a)
                row[f"{a}_trace_count"] = eng.trace_count
                row[f"{a}_compile_count"] = eng.compile_count
                ok &= (eng.trace_count, eng.compile_count) == warm[a]
            return ok, row

        # the traced fn is a placeholder; the audit's substance is the
        # extra() pass driving interleaved dispatch through two warm
        # engines and asserting their counters never move
        return Audit("zoo_multimodel", lambda x: x + 1,
                     (jnp.zeros((1,)),), extra=extra,
                     note="interleaved 2-tenant dispatch: zero "
                          "retraces after warmup")

    audits.append(zoo_multimodel_audit())

    def zero1_audits() -> List[Audit]:
        from ..core.registry import MODELS
        from ..parallel.mesh import MeshConfig, build_mesh
        from ..train import TrainState, make_train_step
        from ..train.classification import make_loss_fn
        from ..train.optim import build_optimizer
        from ..train.schedules import build_schedule
        from ..train.steps import shard_state

        mesh = build_mesh(MeshConfig(data=-1))
        n_dev = mesh.shape["data"] * mesh.shape["fsdp"]

        def fresh(zero1: bool) -> TrainState:
            model = MODELS.build("mnist_fcn", num_classes=4,
                                 dtype=jnp.float32)
            params = model.init(jax.random.key(0),
                                jnp.zeros((1, 16, 16, 1)))["params"]
            tx = build_optimizer(
                "adamw", build_schedule("constant", base_lr=1e-3),
                params=params)
            state = TrainState.create(apply_fn=model.apply,
                                      params=params, tx=tx)
            return shard_state(state, mesh, zero1=zero1)

        batch = {"image": jnp.zeros((8 * n_dev, 16, 16, 1)),
                 "label": jnp.zeros((8 * n_dev,), jnp.int32)}
        rng = jax.random.key(0)
        out: List[Audit] = []

        for mode in ("zero1", "replicated"):
            state = fresh(zero1=(mode == "zero1"))
            step = make_train_step(make_loss_fn(), mesh=mesh,
                                   donate=False, weight_update=mode)
            # the biggest param leaf is the threshold for "param-sized":
            # any all-reduce at or above it means the gradient
            # all-reduce survived; smaller ones are the non-divisible
            # tail and scalar metric reductions
            param_bytes = max(
                int(math.prod(p.shape)) * p.dtype.itemsize
                for p in jax.tree.leaves(state.params))

            def extra(step=step, state=state, mode=mode,
                      param_bytes=param_bytes):
                hlo = hlo_collectives(step, state, batch, rng)
                row = {"hlo_collectives":
                       {op: r["count"] for op, r in hlo.items()},
                       "collective_bytes":
                       {op: r["bytes"] for op, r in hlo.items()}}
                ar_max = hlo.get("all_reduce", {}).get("max_bytes", 0)
                if mode == "zero1":
                    ok = (hlo.get("reduce_scatter", {}).get("count", 0) >= 1
                          and hlo.get("all_gather", {}).get("count", 0) >= 1
                          and ar_max < param_bytes)
                else:
                    ok = ar_max >= param_bytes
                return ok, row

            out.append(Audit(
                f"train_step_{mode}_dp{n_dev}", step,
                (fresh(zero1=(mode == "zero1")), batch, rng),
                max_transfers=0, extra=extra,
                note=("grad AR lowered to reduce-scatter + all-gather"
                      if mode == "zero1" else
                      "control: full-gradient all-reduce present")))
        return out

    if len(jax.devices()) >= 2:
        audits.extend(zero1_audits())
    return audits


def run_audits(audits: Optional[List[Audit]] = None
               ) -> List[Dict[str, Any]]:
    if audits is None:
        audits = builtin_audits()
    return [a.run() for a in audits]
