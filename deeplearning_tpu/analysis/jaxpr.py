"""Structural jaxpr auditor: reusable invariant checks over traced fns.

``tests/test_blocked_nms.py`` proved the no-N×N-memory claim by walking
the jaxpr inline; that walk is the general tool for every structural
invariant this repo cares about — peak intermediate size (does the
postprocess really stay O(N·B)?), transfer counts (does the train step
really dispatch zero ``device_put``s?), and collective counts (what does
a sharded step actually all-reduce? — the accounting PAPERS.md
"Automatic Cross-Replica Sharding of Weight Update" and "EQuARX"
optimizations start from). This module is that walk, shared: usable from
any test and from ``tools/check.py --jaxpr`` over the registered
step/postprocess functions.

Everything reasons over ``jax.make_jaxpr`` output — tracing only, no
compile, no device execution — so audits are cheap even on the 1-core
build box.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

import jax

__all__ = [
    "iter_eqns", "iter_avals", "peak_intermediate",
    "assert_peak_intermediate_below", "count_primitive",
    "count_transfers", "count_collectives", "Audit", "builtin_audits",
    "run_audits",
]

# primitives that move bytes between host and device (or between
# devices) when they appear inside a traced computation
TRANSFER_PRIMITIVES = ("device_put", "copy")

# cross-replica communication primitives (jax.lax collectives + the
# names GSPMD lowers shard_map bodies to)
COLLECTIVE_PRIMITIVES = (
    "psum", "pmax", "pmin", "pmean", "all_gather", "all_to_all",
    "ppermute", "pshuffle", "reduce_scatter", "psum_scatter",
    "pbroadcast", "allreduce",
)


def _as_jaxpr(obj):
    """Accept Jaxpr, ClosedJaxpr, or anything with a ``.jaxpr``."""
    if hasattr(obj, "eqns"):
        return obj
    if hasattr(obj, "jaxpr"):
        return obj.jaxpr
    raise TypeError(f"not a jaxpr: {type(obj)!r}")


def iter_eqns(jaxpr) -> Iterable[Any]:
    """Every equation in ``jaxpr`` and all nested sub-jaxprs (pjit
    bodies, scan/while/cond branches, custom_* calls)."""
    jaxpr = _as_jaxpr(jaxpr)
    for eqn in jaxpr.eqns:
        yield eqn
        for p in eqn.params.values():
            if hasattr(p, "eqns") or hasattr(p, "jaxpr"):
                yield from iter_eqns(p)
            elif isinstance(p, (tuple, list)):
                for q in p:
                    if hasattr(q, "eqns") or hasattr(q, "jaxpr"):
                        yield from iter_eqns(q)


def iter_avals(jaxpr) -> Iterable[Any]:
    """Abstract values of every equation OUTPUT, nested jaxprs included
    — the exact set the original inline walk in test_blocked_nms.py
    measured (inputs/consts excluded), kept identical so ported bounds
    stay bitwise the same."""
    for eqn in iter_eqns(jaxpr):
        for v in eqn.outvars:
            yield v.aval


def _trace(fn: Callable, *args, **kwargs):
    return jax.make_jaxpr(fn)(*args, **kwargs)


def peak_intermediate(fn: Callable, *args, **kwargs) -> int:
    """Largest intermediate (in ELEMENTS, not bytes) any equation in the
    traced ``fn(*args)`` produces; 0 when there are no shaped outputs.
    Scalars count as 1 element (``prod(()) == 1``)."""
    closed = _trace(fn, *args, **kwargs)
    return max((int(math.prod(a.shape)) for a in iter_avals(closed.jaxpr)
                if getattr(a, "shape", None) is not None), default=0)


def assert_peak_intermediate_below(fn: Callable, args: Tuple,
                                   max_elements: int,
                                   msg: str = "") -> int:
    """Assert the traced ``fn(*args)`` never materializes an
    intermediate above ``max_elements`` elements. Returns the measured
    peak so callers can report/log it."""
    peak = peak_intermediate(fn, *args)
    assert peak <= max_elements, (
        f"peak intermediate {peak} elements exceeds budget "
        f"{max_elements}" + (f" ({msg})" if msg else ""))
    return peak


def count_primitive(fn: Callable, name, *args, **kwargs) -> int:
    """Occurrences of primitive(s) ``name`` (a str or tuple of strs) in
    the traced ``fn(*args)``, nested jaxprs included."""
    names = (name,) if isinstance(name, str) else tuple(name)
    closed = _trace(fn, *args, **kwargs)
    return sum(1 for eqn in iter_eqns(closed.jaxpr)
               if eqn.primitive.name in names)


def count_transfers(fn: Callable, *args, **kwargs) -> int:
    """Host/device transfer primitives inside the traced computation.
    The sync-free hot-loop contract says this is 0 for the train step:
    batches arrive placed (DevicePrefetcher) and metrics leave lazily
    (DeferredMetrics), so nothing inside the step moves bytes itself."""
    return count_primitive(fn, TRANSFER_PRIMITIVES, *args, **kwargs)


def count_collectives(fn: Callable, *args,
                      axis_env: Optional[List[Tuple[str, int]]] = None,
                      **kwargs) -> Dict[str, int]:
    """Per-primitive counts of cross-replica collectives in the traced
    ``fn(*args)`` — ``{"psum": 2, "all_gather": 1}``-shaped; empty when
    the computation is collective-free. ``axis_env`` names mapped axes
    for functions that psum over an axis outside pmap/shard_map (same
    contract as ``jax.make_jaxpr``'s)."""
    mk = jax.make_jaxpr(fn, axis_env=axis_env) if axis_env else \
        jax.make_jaxpr(fn)
    closed = mk(*args, **kwargs)
    out: Dict[str, int] = {}
    for eqn in iter_eqns(closed.jaxpr):
        nm = eqn.primitive.name
        if nm in COLLECTIVE_PRIMITIVES:
            out[nm] = out.get(nm, 0) + 1
    return out


# --------------------------------------------------------------- audits
class Audit:
    """One registered structural check for ``tools/check.py --jaxpr``:
    trace ``fn(*args)``, measure peak/transfers/collectives, compare to
    the declared budgets. ``max_elements=None`` means unbounded (the
    reference rows exist to show the auditor SEES the blow-up)."""

    def __init__(self, name: str, fn: Callable, args: Tuple, *,
                 max_elements: Optional[int] = None,
                 max_transfers: Optional[int] = 0,
                 min_elements: Optional[int] = None,
                 note: str = ""):
        self.name = name
        self.fn = fn
        self.args = args
        self.max_elements = max_elements
        self.max_transfers = max_transfers
        self.min_elements = min_elements
        self.note = note

    def run(self) -> Dict[str, Any]:
        row: Dict[str, Any] = {"name": self.name, "note": self.note}
        try:
            row["peak_elements"] = peak_intermediate(self.fn, *self.args)
            row["transfers"] = count_transfers(self.fn, *self.args)
            row["collectives"] = count_collectives(self.fn, *self.args)
            ok = True
            if self.max_elements is not None:
                row["budget_elements"] = self.max_elements
                ok &= row["peak_elements"] <= self.max_elements
            if self.min_elements is not None:
                ok &= row["peak_elements"] >= self.min_elements
            if self.max_transfers is not None:
                ok &= row["transfers"] <= self.max_transfers
            row["ok"] = bool(ok)
        except Exception as e:  # noqa: BLE001 - a broken audit must report
            row["ok"] = False
            row["error"] = repr(e)
        return row


def builtin_audits() -> List[Audit]:
    """The registered step/postprocess functions with their structural
    budgets — the tentpole invariants, re-checkable on demand:

    - blocked NMS stays O(N·B) (the test_blocked_nms bound, N=4096);
    - the reference NMS row PROVES the auditor sees an N×N blow-up;
    - one-pass RoIAlign does <=8 gathers (one sampling pass);
    - the mnist train step traces with zero transfer primitives (the
      PR 1 sync-free contract, structural form).
    """
    import jax.numpy as jnp

    from ..ops import nms as nms_ops
    from ..ops import roi_align as roi_ops

    audits: List[Audit] = []
    n, block = 4096, 256
    boxes = jnp.zeros((n, 4))
    scores = jnp.zeros((n,))
    audits.append(Audit(
        f"nms_blocked_n{n}",
        partial(nms_ops.nms_blocked, iou_threshold=0.5, max_out=100,
                block_size=block),
        (boxes, scores),
        max_elements=4 * n * block,
        note=f"O(N*B) budget, B={block}"))
    audits.append(Audit(
        f"nms_reference_n{n}",
        partial(nms_ops.nms_reference, iou_threshold=0.5, max_out=100),
        (boxes, scores),
        min_elements=n * n,
        note="control: auditor must SEE the N^2 buffer"))

    pyr = {f"p{lv}": jnp.zeros((64 >> (lv - 2), 64 >> (lv - 2), 8))
           for lv in (2, 3, 4, 5)}
    rois = jnp.zeros((16, 4))
    audits.append(Audit(
        "roi_align_onepass",
        partial(roi_ops.multiscale_roi_align),
        (pyr, rois),
        note="one-pass multiscale gather"))

    def train_step_audit() -> Audit:
        from ..core.registry import MODELS
        from ..train import TrainState, make_train_step
        from ..train.classification import make_loss_fn
        from ..train.optim import build_optimizer
        from ..train.schedules import build_schedule

        model = MODELS.build("mnist_fcn", num_classes=4,
                             dtype=jnp.float32)
        params = model.init(jax.random.key(0),
                            jnp.zeros((1, 16, 16, 1)))["params"]
        tx = build_optimizer("sgd", build_schedule("constant",
                                                   base_lr=1e-2),
                             params=params)
        state = TrainState.create(apply_fn=model.apply, params=params,
                                  tx=tx)
        batch = {"image": jnp.zeros((8, 16, 16, 1)),
                 "label": jnp.zeros((8,), jnp.int32)}
        step = make_train_step(make_loss_fn(), donate=False)
        rng = jax.random.key(0)
        return Audit("train_step_mnist", step, (state, batch, rng),
                     max_transfers=0,
                     note="hot-loop step: zero transfer primitives")

    audits.append(train_step_audit())
    return audits


def run_audits(audits: Optional[List[Audit]] = None
               ) -> List[Dict[str, Any]]:
    if audits is None:
        audits = builtin_audits()
    return [a.run() for a in audits]
