#!/usr/bin/env python
"""Export CLI — the per-project export.py successor (yolov5 export.py
surface: one flag per backend).

  python tools/export.py --model vit_base_patch16_224 --num-classes 1000 \\
      --size 224 --format stablehlo --out model.shlo
  python tools/export.py --model resnet50 --format savedmodel --out sm/
  python tools/export.py --model mnist_cnn --channels 1 --size 28 \\
      --format onnx --out model.onnx
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax

if os.environ.get("DLTPU_PLATFORM"):
    jax.config.update("jax_platforms", os.environ["DLTPU_PLATFORM"])

import jax.numpy as jnp
import numpy as np


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", required=True)
    ap.add_argument("--num-classes", type=int, default=10)
    ap.add_argument("--ckpt", default=None)
    ap.add_argument("--size", type=int, default=224)
    ap.add_argument("--channels", type=int, default=3)
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument("--format",
                    choices=("stablehlo", "savedmodel", "onnx"),
                    default="stablehlo")
    ap.add_argument("--decode", action="store_true",
                    help="detectors: include the box decode in the graph "
                         "(pre-NMS raw detections, the yolov5 "
                         "export.py:29-159 export_detect / YOLOX "
                         "tools/export_onnx.py --decode analog)")
    ap.add_argument("--out", required=True)
    args = ap.parse_args(argv)

    from deeplearning_tpu.core.checkpoint import load_pytree
    from deeplearning_tpu.core.registry import MODELS
    from deeplearning_tpu.export.serialize import (export_savedmodel,
                                                   export_stablehlo,
                                                   flops_estimate)

    build_kw = {}
    if args.format == "onnx":
        build_kw["dtype"] = jnp.float32   # portable f32 ONNX artifact
    model = MODELS.build(args.model, num_classes=args.num_classes,
                         **build_kw)
    example = jnp.zeros((args.batch, args.size, args.size, args.channels))
    variables = model.init(jax.random.key(0), example, train=False)
    if args.ckpt:
        restored = load_pytree(args.ckpt)
        params = restored.get("params", restored) \
            if isinstance(restored, dict) else restored
        variables = {**variables, "params": params}

    def fn(x):
        return model.apply(variables, x, train=False)

    if args.decode:
        hw = (args.size, args.size)
        if args.model.startswith("yolox"):
            from deeplearning_tpu.models.detection.yolox import (
                decode_outputs, yolox_grid)
            centers, strides = (jnp.asarray(a) for a in yolox_grid(hw))

            def fn(x):
                raw = model.apply(variables, x, train=False)
                return decode_outputs(raw, centers, strides)
        elif args.model.startswith("yolov5"):
            from deeplearning_tpu.models.detection.yolov5 import (
                decode_yolov5, yolov5_grid)
            grid = {k: jnp.asarray(v) for k, v in yolov5_grid(hw).items()}

            def fn(x):
                raw = model.apply(variables, x, train=False)
                return decode_yolov5(raw, grid)
        else:
            raise SystemExit(f"--decode not supported for {args.model!r} "
                             "(yolox*/yolov5* only)")

    print(f"model FLOPs (fwd, batch {args.batch}): "
          f"{flops_estimate(fn, example) / 1e9:.2f} G")
    if args.format == "onnx":
        from deeplearning_tpu.export.onnx import (export_onnx, load_onnx,
                                                  run_onnx)
        blob = export_onnx(fn, [example], args.out)
        # load-back numeric self-check, the export.py --simplify/check
        # analog (yolov5 export.py:43 onnx.checker + simplifier). A random
        # probe, not zeros: conv(0)=0 would mask a mis-serialized stem.
        probe = jnp.asarray(np.random.default_rng(0).normal(
            size=example.shape), jnp.float32)
        got = run_onnx(load_onnx(blob), np.asarray(probe))[0]
        want = np.asarray(fn(probe))
        err = float(np.abs(got - want).max())
        print(f"wrote {len(blob)} bytes of ONNX to {args.out}; "
              f"load-back max|diff|={err:.2e}")
        if err > 1e-3:
            print("ONNX self-check FAILED"); return 1
    elif args.format == "stablehlo":
        blob = export_stablehlo(fn, [example], args.out)
        print(f"wrote {len(blob)} bytes of StableHLO to {args.out}")
    else:
        ok = export_savedmodel(fn, [example], args.out)
        print(f"SavedModel written to {args.out}" if ok
              else "tensorflow unavailable")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
