#!/usr/bin/env python
"""MFU push experiments: ViT-B/16 train-step variants, one per invocation.

Each run measures ONE variant to completion and appends a JSON line to
tools/mfu_results.jsonl. Variants are selected by CLI flags so that
XLA-flag experiments (which must be set before backend init) get a fresh
interpreter. Run variants SEQUENTIALLY — this box has one CPU core and
the axon TPU tunnel wedges if processes are killed mid-compile, so no
kill-capable timeouts here; the bench watchdog in bench.py is the only
place that self-reports a timeout.

Usage:
  python tools/mfu_push.py --attn naive
  python tools/mfu_push.py --attn flash_hb --head-block 4
  XLA_FLAGS="--xla_tpu_enable_latency_hiding_scheduler=true" \
      python tools/mfu_push.py --attn naive --tag lhs
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

import bench_util  # noqa: F401  (side effect: persistent compile cache)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--attn", default="naive",
                    choices=["naive", "flash", "flash_hb", "sdpa"])
    ap.add_argument("--head-block", type=int, default=4)
    ap.add_argument("--block-q", type=int, default=128)
    ap.add_argument("--block-k", type=int, default=128)
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--remat", action="store_true")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--tag", default="")
    args = ap.parse_args()

    from deeplearning_tpu.core.registry import MODELS
    from deeplearning_tpu.train import TrainState, make_train_step
    from deeplearning_tpu.train.classification import make_loss_fn
    from deeplearning_tpu.train.optim import build_optimizer
    from deeplearning_tpu.train.schedules import build_schedule

    attn_fn = None
    if args.attn == "sdpa":
        from deeplearning_tpu.ops.attention import sdpa_adapter
        attn_fn = sdpa_adapter
    elif args.attn == "flash":
        from deeplearning_tpu.ops.attention import flash_attn_adapter
        attn_fn = flash_attn_adapter
    elif args.attn == "flash_hb":
        from deeplearning_tpu.ops.pallas.flash_attention import (
            flash_attention_hb)

        def attn_fn(q, k, v, dropout_rate=0.0, deterministic=True, rng=None):
            t = lambda x: x.transpose(0, 2, 1, 3)
            return t(flash_attention_hb(
                t(q), t(k), t(v), head_block=args.head_block,
                block_q=args.block_q, block_k=args.block_k))

    model = MODELS.build("vit_base_patch16_224", num_classes=1000,
                         remat=args.remat, attn_fn=attn_fn)
    rng = jax.random.key(0)
    params = model.init(rng, jnp.zeros((1, 224, 224, 3)),
                        train=False)["params"]
    sched = build_schedule("warmup_cosine", base_lr=1e-3, total_steps=10_000,
                           warmup_steps=100)
    tx = build_optimizer("adamw", sched, weight_decay=0.05, params=params)
    state = TrainState.create(apply_fn=model.apply, params=params, tx=tx)

    batch = args.batch
    images = jnp.asarray(np.random.default_rng(0).normal(
        size=(batch, 224, 224, 3)), jnp.float32)
    labels = jnp.asarray(np.random.default_rng(1).integers(0, 1000, batch),
                         jnp.int32)
    data = {"image": images, "label": labels}

    step = make_train_step(make_loss_fn(label_smoothing=0.1), donate=True)
    t_c0 = time.perf_counter()
    compiled = jax.jit(lambda s, b, r: step(s, b, r),
                       donate_argnums=(0,)).lower(state, data, rng).compile()
    compile_s = time.perf_counter() - t_c0
    cost = compiled.cost_analysis()
    step_flops = float(cost.get("flops", 0.0)) if cost else 0.0

    # drive the compiled executable directly — step() has its own jit
    # cache and would pay a second identical compile
    state, metrics = compiled(state, data, rng)
    loss0 = float(metrics["loss"])  # D2H sync; also a sanity check
    t0 = time.perf_counter()
    for _ in range(args.steps):
        state, metrics = compiled(state, data, rng)
    loss1 = float(metrics["loss"])
    dt = (time.perf_counter() - t0) / args.steps

    peak = 197e12  # v5e bf16
    # Pallas custom calls are opaque to XLA cost analysis, so for non-naive
    # attention `step_flops` undercounts. mfu_ref uses the naive-path
    # compiled FLOPs for THIS model/image config, scaled by batch, so
    # variants compare on the same semantic workload. The per-image value
    # is measured by the naive non-remat run and cached in a sidecar keyed
    # by config, so it can't silently go stale when the config changes.
    # batch is part of the key: XLA's compiled FLOPs per image differ by
    # ~11% between batch 128 and 512 (fusion decisions), so a batch-free
    # key would let the last naive run poison other batches' mfu_ref_pct
    ref_key = f"vit_base_patch16_224/img224/b{batch}"
    ref_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "mfu_ref_flops.json")
    ref_cache = {}
    if os.path.exists(ref_path):
        with open(ref_path) as f:
            ref_cache = json.load(f)
    if args.attn == "naive" and not args.remat and step_flops > 0:
        ref_cache[ref_key] = step_flops / batch
        with open(ref_path, "w") as f:
            json.dump(ref_cache, f)
    if ref_key in ref_cache:
        ref_flops = ref_cache[ref_key] * batch
    else:  # no naive run measured yet on this machine
        ref_flops = 1.3543e13 * batch / 128.0  # batch-128 measurement, r2
    from bench_util import append_result
    extra = {
        "attn": args.attn,
        "remat": args.remat,
        "head_block": args.head_block if args.attn == "flash_hb" else None,
        "mfu_ref_pct": round(ref_flops / dt / peak * 100.0, 2),
        "compile_s": round(compile_s, 1),
        "flops_per_step": step_flops,
        "loss0": round(loss0, 4), "loss1": round(loss1, 4),
        "xla_flags": os.environ.get("XLA_FLAGS", ""),
    }
    rec = append_result(
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "mfu_results.jsonl"),
        args.tag or args.attn, batch=batch, step_ms=dt * 1e3,
        img_per_s=batch / dt,
        mfu_pct=step_flops / dt / peak * 100.0, **extra)
    print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
