#!/usr/bin/env python
"""Inference server CLI over the serving engine.

  # stdin mode: one image path per line, one JSON answer per line
  echo img.png | python tools/serve.py --model mnist_cnn \\
      --num-classes 10 --size 28 [--ckpt DIR]

  # optional HTTP mode (stdlib-only): POST /predict with an .npy body
  python tools/serve.py --model yolox_tiny --num-classes 80 \\
      --size 416 --http 8000

Every request path — stdin lines, HTTP posts, .npz batches — goes
through the same ``MicroBatcher.submit()`` front door, so concurrent
clients batch together, admission control applies (full queue answers
"rejected" with a retry-after hint instead of queueing unboundedly),
and the model only ever executes its warmed bucket shapes. ``GET
/stats`` (HTTP) or EOF (stdin) reports the telemetry snapshot.

Fleet plane: HTTP mode always exposes ``GET /metrics`` (Prometheus
text format, the uniform schema ``obs/fleet.py`` scrapes) and
``GET /metrics.json``; when a supervisor hands down
``DLTPU_ENDPOINT_FILE`` the replica advertises its URL there, and
``DLTPU_TRACE=1`` enables the span tracer with a ``trace.json`` dump on
graceful shutdown (SIGTERM drains the server instead of killing it).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax

if os.environ.get("DLTPU_PLATFORM"):
    jax.config.update("jax_platforms", os.environ["DLTPU_PLATFORM"])

import numpy as np


def load_request_images(path: str, size: int, task: str) -> np.ndarray:
    """One request's model-ready (n, size, size, 3) frames.

    Conventions shared with predict.py/demo.py: ``.npz`` batches are
    model-ready (tools/train.py feeds npz raw — normalizing again would
    double-normalize); image files go through the classification eval
    transform, or plain resize+/255 for detection (demo.py's frame)."""
    from deeplearning_tpu.data.datasets import load_image
    if path.endswith(".npz"):
        imgs = np.load(path)["images"]
    else:
        raw = np.asarray(load_image(path), np.float32)
        if task == "detect":
            if not path.lower().endswith(".npy"):
                raw = raw / 255.0      # .npy is model-ready by convention
            import jax.numpy as jnp
            imgs = np.asarray(jax.image.resize(
                jnp.asarray(raw), (size, size, 3), "bilinear"))[None]
            return imgs.astype(np.float32)
        else:
            from deeplearning_tpu.data.transforms import (
                classification_eval_transform)
            fn = classification_eval_transform((size, size))
            imgs = fn({"image": raw[None]})["image"]
    imgs = np.asarray(imgs, np.float32)
    if imgs.ndim == 3:
        imgs = imgs[None]
    if imgs.shape[1:3] != (size, size):
        import jax.numpy as jnp
        imgs = np.asarray(jax.image.resize(
            jnp.asarray(imgs), (imgs.shape[0], size, size, 3),
            "bilinear"))
    return imgs


def format_answer(task: str, row, names, topk: int) -> dict:
    """One request's JSON answer. Detection answers carry only the
    VALID rows — the fixed-shape class −1 padding slots never leave the
    server."""
    if task == "classify":
        order = np.argsort(-row)[:topk]
        return {"top": [[names.get(int(i), int(i)), round(float(row[i]), 4)]
                        for i in order]}
    keep = np.asarray(row["valid"], bool)
    return {"detections": [
        {"box": [round(float(x), 1) for x in b],
         "score": round(float(s), 4),
         "label": names.get(int(c), int(c))}
        for b, s, c in zip(np.asarray(row["boxes"])[keep],
                           np.asarray(row["scores"])[keep],
                           np.asarray(row["labels"])[keep])]}


def serve_stdin(batcher, task: str, size: int, names, topk: int,
                timeout_s: float, stream_in=None, stream_out=None) -> int:
    """Line protocol: path in, JSON out (one line per image; an .npz
    submits every row concurrently so they micro-batch together)."""
    from deeplearning_tpu.serve import DeadlineExceeded, Rejected
    stream_in = stream_in or sys.stdin
    stream_out = stream_out or sys.stdout
    for line in stream_in:
        path = line.strip()
        if not path:
            continue
        try:
            images = load_request_images(path, size, task)
            handles = [batcher.submit(img) for img in images]
        except Rejected as r:
            print(json.dumps({"error": "rejected", "path": path,
                              "retry_after_s": round(r.retry_after_s, 3)}),
                  file=stream_out, flush=True)
            continue
        except Exception as e:  # noqa: BLE001 - per-line protocol
            print(json.dumps({"error": repr(e), "path": path}),
                  file=stream_out, flush=True)
            continue
        for i, h in enumerate(handles):
            try:
                row = h.result(timeout=timeout_s)
                ans = format_answer(task, row, names, topk)
            except DeadlineExceeded:
                ans = {"error": "deadline_exceeded"}
            ans.update({"path": path, "image": i})
            print(json.dumps(ans), file=stream_out, flush=True)
    print(json.dumps(batcher.telemetry.snapshot()), file=sys.stderr,
          flush=True)
    return 0


_SERVE_COUNTER_NAMES = {
    "submitted": "dltpu_serve_requests_total",
    "completed": "dltpu_serve_completed_total",
    "rejected": "dltpu_serve_rejected_total",
    "timed_out": "dltpu_serve_timed_out_total",
    "batches": "dltpu_serve_batches_total",
    "shed_batches": "dltpu_serve_shed_batches_total",
}
_SERVE_GAUGE_KEYS = (
    "requests_per_s", "rejects_per_s", "completions_per_s", "window_s",
    "batch_occupancy", "queue_depth_mean", "e2e_ms_p50", "e2e_ms_p90",
    "e2e_ms_p99", "dispatch_ms_p50", "dispatch_ms_p90",
    "dispatch_ms_p99")


def _mirror_telemetry(reg, snap, labels=None):
    for key, name in _SERVE_COUNTER_NAMES.items():
        reg.counter(name, f"serve telemetry {key}",
                    labels=labels).set_total(snap.get(key, 0.0))
    for key in _SERVE_GAUGE_KEYS:
        if key in snap:
            reg.gauge(f"dltpu_serve_{key}", f"serve telemetry {key}",
                      labels=labels).set(snap[key])


def make_metrics_collector(batcher):
    """Scrape-time pull adapter: mirror ``ServeTelemetry.snapshot()``
    (rates, percentiles, cumulative counts) and ``engine.stats()`` into
    the registry under the ``dltpu_serve_*`` names ``obs/fleet.py``
    rolls up. Counters use ``set_total`` (monotonic mirror); xla-side
    compile/HBM metrics are PUSHED by obs.xla and deliberately not
    mirrored here — one writer per metric, never two.

    Zoo mode additionally mirrors every tenant lane under the SAME
    metric names with a ``model`` label (the per-tenant series
    ``fleet.compute_rollup`` folds into its ``models`` section) plus
    per-model queue/warm gauges and the zoo residency counters."""

    def _collect(reg):
        snap = batcher.telemetry.snapshot()
        _mirror_telemetry(reg, snap)
        reg.gauge("dltpu_serve_queue_depth",
                  "live micro-batch queue depth").set(
            float(batcher.queue_depth))
        reg.gauge("dltpu_serve_standby",
                  "1 while a warm spare out of rotation").set(
            1.0 if batcher.standby else 0.0)
        if batcher.zoo is None:
            for key, val in batcher.engine.stats().items():
                if isinstance(val, (int, float)) \
                        and not isinstance(val, bool):
                    safe = "".join(c if c.isalnum() else "_"
                                   for c in key)
                    reg.gauge(f"dltpu_engine_{safe}",
                              f"engine stats {key}").set(float(val))
            return
        zs = batcher.zoo.stats()
        for key in ("registered", "resident", "loads", "evictions",
                    "rejected_loads"):
            reg.gauge(f"dltpu_zoo_{key}",
                      f"zoo {key}").set(float(zs[key]))
        for alias, row in zs["models"].items():
            labels = {"model": alias}
            lane_tel = batcher.lane_telemetry(alias)
            if lane_tel is not None:
                _mirror_telemetry(reg, lane_tel.snapshot(), labels)
            reg.gauge("dltpu_serve_queue_depth",
                      "live micro-batch queue depth",
                      labels=labels).set(
                float(batcher.lane_depth(alias)))
            reg.gauge("dltpu_zoo_model_warm", "1 while servable",
                      labels=labels).set(1.0 if row["warm"] else 0.0)
            reg.gauge("dltpu_serve_brownout_step",
                      "tenant degrade-ladder step (0 = full service)",
                      labels=labels).set(
                float(batcher.brownout_step(alias)))
            reg.gauge("dltpu_zoo_model_bytes", "resident weight bytes",
                      labels=labels).set(float(row["bytes"]))
            if "trace_count" in row:
                reg.gauge("dltpu_zoo_model_trace_count",
                          "engine trace count", labels=labels).set(
                    float(row["trace_count"]))
    return _collect


def serve_http(batcher, task: str, size: int, names, topk: int,
               timeout_s: float, port: int,
               wedge_deadline_s: float = 30.0):
    """Minimal stdlib HTTP front: POST /predict (.npy body, one image or
    a batch) → JSON; GET /stats → telemetry; GET /healthz → the health
    verdict, including the DispatchWatch wedge check (requests queued
    while the dispatch counter is frozen past ``wedge_deadline_s`` →
    503 with ``"wedged": true``, so a balancer drains a stuck replica
    the process itself cannot notice); GET /metrics + /metrics.json →
    the fleet scrape surface. ThreadingHTTPServer gives each request
    its own thread, so concurrent posts micro-batch.

    Zoo mode (``batcher.zoo`` set) adds the multi-tenant surface:
    ``POST /predict/<model>`` routes to that tenant's lane (a cold
    tenant hot-loads in the background; HBM-pressure refusals answer
    429 with the model and reason in the body), ``GET /models`` dumps
    the per-tenant state table, and ``POST /admin/load/<model>`` /
    ``POST /admin/evict/<model>`` drive residency by hand."""
    import io
    from concurrent.futures import TimeoutError as FutureTimeout
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from deeplearning_tpu.obs import metrics as obs_metrics
    from deeplearning_tpu.obs import xla as obs_xla
    from deeplearning_tpu.serve import DeadlineExceeded, Rejected
    from deeplearning_tpu.serve.health import DispatchWatch
    from deeplearning_tpu.serve.health import health as health_check
    from deeplearning_tpu.serve.health import zoo_health

    zoo = batcher.zoo
    watch = DispatchWatch(batcher, wedge_deadline_s)
    registry = obs_metrics.enable()
    registry.register_collector(make_metrics_collector(batcher))

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):   # quiet: telemetry is the log
            pass

        def _json(self, code: int, payload: dict):
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _rejected(self, r):
            # admission backpressure answers 429 ("slow down, retry
            # here"); a standby or chaos-injected refusal answers 503
            # ("wrong replica / failed attempt") so the router's
            # breaker classification sees the difference
            code = 503 if r.reason in ("standby", "injected") else 429
            body = json.dumps({
                "error": "rejected", "reason": r.reason,
                "model": r.model, "depth": r.depth,
                "retry_after_s": round(r.retry_after_s, 3)}).encode()
            self.send_response(code)
            self.send_header("Retry-After", f"{r.retry_after_s:.3f}")
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            route = self.path.rstrip("/")
            if route == "/stats":
                payload = batcher.telemetry.snapshot()
                if zoo is None:
                    payload["engine"] = batcher.engine.stats()
                else:
                    payload["zoo"] = zoo.stats()
                payload["compile"] = obs_xla.compile_stats()
                payload["hbm"] = obs_xla.hbm_snapshot()
                return self._json(200, payload)
            if route == "/models" and zoo is not None:
                return self._json(200, zoo.stats())
            if route == "/healthz":
                if zoo is None:
                    code, payload = health_check(batcher.engine, batcher,
                                                 wedge=watch)
                else:
                    code, payload = zoo_health(zoo, batcher, wedge=watch)
                payload.update(obs_metrics.replica_identity())
                return self._json(code, payload)
            if route == "/metrics":
                body = registry.prometheus_text().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4; "
                                 "charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return None
            if route == "/metrics.json":
                return self._json(200, registry.snapshot())
            return self._json(404, {"error": "GET /stats, /healthz, "
                                             "/metrics or /metrics.json"})

        def _predict(self, alias):
            n = int(self.headers.get("Content-Length", 0))
            # end-to-end deadline: a router stamping X-Deadline-Ms is
            # spending ONE budget across retries/hedges — map it onto
            # the admission deadline so queue time counts against it
            req_timeout = timeout_s
            hdr = self.headers.get("X-Deadline-Ms")
            if hdr:
                try:
                    req_timeout = min(timeout_s,
                                      max(int(hdr), 1) / 1e3)
                except ValueError:
                    pass
            try:
                arr = np.load(io.BytesIO(self.rfile.read(n)),
                              allow_pickle=False)
                images = np.asarray(arr, np.float32)
                if images.ndim == 3:
                    images = images[None]
                handles = [batcher.submit(img, timeout_s=req_timeout,
                                          model=alias)
                           for img in images]
                rows = [h.result(timeout=req_timeout) for h in handles]
            except Rejected as r:
                return self._rejected(r)
            except (DeadlineExceeded, FutureTimeout):
                return self._json(504, {"error": "deadline_exceeded"})
            except KeyError as e:
                return self._json(404, {"error": repr(e)})
            except Exception as e:  # noqa: BLE001 - request-scoped
                return self._json(400, {"error": repr(e)})
            if zoo is None:
                row_task = task
            else:
                # the engine served the batch, so it was warm a moment
                # ago; a racing evict just means we format as classify
                eng = zoo.engine(alias or zoo.models()[0])
                row_task = eng.task if eng is not None else "classify"
            return self._json(200, {"results": [
                format_answer(row_task, row, names, topk)
                for row in rows]})

        def do_POST(self):
            parts = [p for p in self.path.split("/") if p]
            if parts and parts[0] == "predict":
                if len(parts) == 1:
                    return self._predict(None)
                if len(parts) == 2 and zoo is not None:
                    return self._predict(parts[1])
            elif parts == ["admin", "drain"]:
                # fleet controller verb: stop accepting, finish lanes.
                # healthz flips to 503 "draining" so routers reroute;
                # the controller polls "drained" before the requeue
                batcher.drain()
                return self._json(200, {"draining": True,
                                        "drained": bool(batcher.drained),
                                        "queue_depth":
                                            batcher.queue_depth})
            elif parts == ["admin", "promote"]:
                # fleet controller verb: warm standby -> rotation. The
                # engine AOT'd at startup, so this is a flag flip —
                # healthz answers "ready" on the very next probe
                return self._json(200, {"promoted": batcher.promote(),
                                        "standby": batcher.standby})
            elif (len(parts) == 4 and parts[0] == "admin"
                    and parts[1] == "brownout"):
                # fleet controller verb: one tenant's degrade-ladder
                # step (0 restores). Step 2+ additionally demotes the
                # tenant to int8 residency when a zoo owns the weights
                alias, step_s = parts[2], parts[3]
                try:
                    step = int(step_s)
                except ValueError:
                    return self._json(400,
                                      {"error": "step must be an int"})
                applied = batcher.set_brownout(alias, step)
                out = {"model": alias, "step": applied}
                if zoo is not None and applied >= 2:
                    out["demoted"] = zoo.demote_residency(alias)
                return self._json(200, out)
            elif (zoo is not None and len(parts) == 3
                    and parts[0] == "admin"
                    and parts[1] in ("load", "evict")):
                verb, alias = parts[1], parts[2]
                try:
                    if verb == "load":
                        state = zoo.load(alias, wait=False)
                    else:
                        evicted = zoo.evict(alias)
                        state = zoo.state(alias)
                except Rejected as r:
                    return self._rejected(r)
                except KeyError as e:
                    return self._json(404, {"error": repr(e)})
                out = {"model": alias, "state": state}
                if verb == "evict":
                    out["evicted"] = evicted
                return self._json(200, out)
            return self._json(404, {
                "error": "POST /predict[/<model>], /admin/drain, "
                         "/admin/promote, "
                         "/admin/brownout/<model>/<step> or "
                         "/admin/{load,evict}/<model>"})

    server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    url = f"http://127.0.0.1:{server.server_port}"
    # advertise the scrape endpoint when a supervisor asked for it
    obs_metrics.write_endpoint(url, role="serve")
    endpoints = ["/predict", "/stats", "/healthz", "/metrics",
                 "/metrics.json", "/admin/drain", "/admin/promote",
                 "/admin/brownout/<model>/<step>"]
    if zoo is not None:
        endpoints[:1] = ["/predict/<model>", "/models",
                         "/admin/load/<model>", "/admin/evict/<model>"]
    print(json.dumps({"serving": url, "endpoints": endpoints}),
          flush=True)
    return server


def parse_zoo_spec(raw: str) -> dict:
    """``--zoo`` value: inline JSON or ``@file.json`` mapping alias →
    tenant spec. Per-tenant keys: ``model`` (architecture name,
    defaults to the alias), policy keys (``weight_quant``,
    ``max_queue``, ``shed_threshold``, ``timeout_s``, ``est_bytes``,
    ``preload``), ``buckets`` (list), and everything else passes
    through as engine kwargs (``num_classes``, ``image_size``,
    ``ckpt``, ...)."""
    if raw.startswith("@"):
        with open(raw[1:]) as f:
            spec = json.load(f)
    else:
        spec = json.loads(raw)
    if not isinstance(spec, dict) or not spec:
        raise ValueError("--zoo must map alias -> tenant spec")
    return spec


def build_zoo(spec: dict, args):
    """ModelZoo from a parsed ``--zoo`` spec + CLI defaults."""
    from deeplearning_tpu.serve import ModelZoo
    zoo = ModelZoo(alert_frac=args.hbm_alert_frac,
                   max_resident=args.max_resident)
    preload = []
    for alias, row in spec.items():
        row = dict(row)
        model_name = row.pop("model", alias)
        if row.pop("preload", False):
            preload.append(alias)
        buckets = row.pop("buckets", None)
        if buckets is not None:
            row["batch_buckets"] = tuple(int(b) for b in buckets)
        row.setdefault("batch_buckets", tuple(
            int(b) for b in args.buckets.split(",")))
        zoo.register(
            alias, model_name,
            weight_quant=row.pop("weight_quant", "fp32"),
            max_queue=int(row.pop("max_queue", args.max_queue)),
            shed_threshold=row.pop("shed_threshold", None),
            default_timeout_s=row.pop("timeout_s", args.timeout_s),
            est_bytes=row.pop("est_bytes", None),
            **row)
    for alias in preload:
        zoo.load(alias, wait=True)
    return zoo


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", default=None,
                    help="single-model mode: architecture to serve")
    ap.add_argument("--zoo", default=None,
                    help="multi-tenant mode: JSON (or @file.json) "
                         "mapping alias -> tenant spec; see "
                         "parse_zoo_spec")
    ap.add_argument("--max-resident", type=int, default=None,
                    help="zoo: cap on simultaneously-warm models")
    ap.add_argument("--hbm-alert-frac", type=float, default=None,
                    help="zoo: evict when a load projects past this "
                         "HBM fraction (default DLTPU_HBM_ALERT_FRAC "
                         "or 0.9)")
    ap.add_argument("--num-classes", type=int, default=10)
    ap.add_argument("--ckpt", default=None)
    ap.add_argument("--size", type=int, default=224)
    ap.add_argument("--buckets", default="1,8,32",
                    help="comma-separated batch buckets")
    ap.add_argument("--max-wait-ms", type=float, default=5.0)
    ap.add_argument("--max-queue", type=int, default=256)
    ap.add_argument("--timeout-s", type=float, default=30.0,
                    help="per-request deadline")
    ap.add_argument("--topk", type=int, default=5)
    ap.add_argument("--score", type=float, default=0.3,
                    help="detection score threshold")
    ap.add_argument("--max-det", type=int, default=100)
    ap.add_argument("--nms-impl", default="auto")
    ap.add_argument("--tta", action="store_true",
                    help="classification flip-TTA inside the executable")
    ap.add_argument("--classes", default=None,
                    help="json mapping class index -> name")
    ap.add_argument("--http", type=int, default=None,
                    help="serve HTTP on this port instead of stdin "
                         "(0 = ephemeral)")
    ap.add_argument("--wedge-deadline-s", type=float, default=30.0,
                    help="healthz reports wedged after this many seconds "
                         "of queued-but-frozen dispatch")
    args = ap.parse_args(argv)
    if (args.model is None) == (args.zoo is None):
        ap.error("pass exactly one of --model or --zoo")
    if args.zoo is not None and args.http is None:
        ap.error("--zoo requires --http (stdin mode is single-model)")

    from deeplearning_tpu.analysis import strict as strict_mod
    from deeplearning_tpu.elastic import heartbeat as hb
    from deeplearning_tpu.obs import spans
    from deeplearning_tpu.serve import InferenceEngine, MicroBatcher

    # DLTPU_STRICT=threads: instrument the fleet's locks BEFORE the
    # zoo/batcher/heartbeat objects below create them
    strict_mod.maybe_enable_threads(strict_mod.resolve())

    # DLTPU_TRACE=1: record the span timeline and dump trace.json on
    # graceful exit (next to the endpoint file when supervised, so
    # tools/trace_merge.py finds one trace per replica workdir)
    trace_path = None
    if os.environ.get("DLTPU_TRACE"):
        spans.enable()
        ep = os.environ.get("DLTPU_ENDPOINT_FILE")
        trace_path = os.environ.get("DLTPU_TRACE_FILE") or os.path.join(
            os.path.dirname(ep) if ep else ".", "trace.json")

    engine = zoo = None
    if args.zoo is not None:
        zoo = build_zoo(parse_zoo_spec(args.zoo), args)
        print(json.dumps({"ready": zoo.stats()}), file=sys.stderr,
              flush=True)
        task, size = "classify", 0     # resolved per model per request
    else:
        engine = InferenceEngine(
            args.model, num_classes=args.num_classes, ckpt=args.ckpt,
            image_size=args.size,
            batch_buckets=tuple(int(b) for b in args.buckets.split(",")),
            tta=args.tta, score_thresh=args.score, max_det=args.max_det,
            nms_impl=args.nms_impl)
        print(json.dumps({"ready": engine.stats()}), file=sys.stderr,
              flush=True)
        task, size = engine.task, args.size
    names = {}
    if args.classes:
        with open(args.classes) as f:
            names = {int(k): v for k, v in json.load(f).items()}

    # supervised serving: when DLTPU_HEARTBEAT names a file (the
    # supervisor's contract with its children), the batcher's dispatch
    # loop advances the activity watermark — a wedged replica gets the
    # same SIGTERM/requeue treatment as a wedged training run
    beat = writer = None
    beat_path = os.environ.get(hb.ENV_VAR)
    if beat_path:
        beat = hb.Heartbeat()
        writer = hb.HeartbeatWriter(beat_path, beat).start()
    try:
        with MicroBatcher(engine, zoo=zoo,
                          max_wait_ms=args.max_wait_ms,
                          max_queue=args.max_queue,
                          default_timeout_s=args.timeout_s,
                          heartbeat=beat,
                          standby=os.environ.get("DLTPU_STANDBY")
                          == "1") as batcher:
            if args.http is not None:
                server = serve_http(batcher, task, size,
                                    names, args.topk, args.timeout_s,
                                    args.http, args.wedge_deadline_s)

                # SIGTERM (the supervisor's drain signal) shuts the
                # server down from a helper thread — serve_forever
                # returns, the trace dumps, the heartbeat finalizes —
                # instead of the default die-mid-request
                import signal

                from deeplearning_tpu.elastic.preempt import \
                    EXIT_PREEMPTED
                from deeplearning_tpu.obs import flight as obs_flight
                from deeplearning_tpu.obs import threads as obs_threads

                rc_holder = {"rc": 0}

                def _drain(signum, frame):
                    obs_threads.spawn(server.shutdown,
                                      name="serve-drain",
                                      daemon=True)
                try:
                    signal.signal(signal.SIGTERM, _drain)
                except ValueError:
                    pass           # non-main thread (embedded use)

                # preemption (injected via preempt_replica:<i>, or a
                # platform eviction the batcher surfaces): drain, shut
                # down gracefully, and exit 75 so the supervisor
                # classifies capacity-loss — not a crash, not a clean
                # completion
                def _preempted():
                    rc_holder["rc"] = EXIT_PREEMPTED
                    obs_flight.record("serve_preempted",
                                      dispatched=batcher.dispatched)
                    batcher.drain()
                    obs_threads.spawn(server.shutdown,
                                      name="serve-preempt-drain",
                                      daemon=True)
                batcher.on_preempt = _preempted

                # chaos crash (crash_replica:<i>): a hard, instant
                # death — no drain, no cleanup; the supervisor must
                # classify a crash and in-flight clients see the
                # connection drop, exactly like a segfaulted replica
                def _crashed():
                    obs_flight.record("serve_crash",
                                      dispatched=batcher.dispatched)
                    os._exit(1)
                batcher.on_crash = _crashed
                try:
                    server.serve_forever()
                except KeyboardInterrupt:
                    pass
                finally:
                    server.server_close()
                return rc_holder["rc"]
            return serve_stdin(batcher, task, size, names,
                               args.topk, args.timeout_s)
    finally:
        if trace_path is not None:
            tracer = spans.get_tracer()
            if tracer is not None:
                tracer.dump(trace_path)
        if writer is not None:
            writer.stop()


if __name__ == "__main__":
    raise SystemExit(main())
