#!/usr/bin/env python
"""dltpu-check: the repo's policy gate.

  python tools/check.py                    # lint, human-readable findings
  python tools/check.py --ci               # ratchet gate: exit 1 on NEW findings
  python tools/check.py --update-baseline  # re-record analysis/baseline.json
  python tools/check.py --rules            # rule table
  python tools/check.py --jaxpr            # structural audits (imports jax)

The default/``--ci``/``--update-baseline``/``--rules`` paths never
import jax (``analysis/lint.py`` is loaded standalone by file path, not
through the ``deeplearning_tpu`` package whose ``__init__`` pulls the
whole stack) — the lint gate stays a sub-10s pure-CPython pass that CI
can run before any accelerator is even visible. ``--jaxpr`` traces the
registered step/postprocess functions and checks their structural
budgets (peak intermediate elements, transfer primitives), so it does
import jax; run it with ``JAX_PLATFORMS=cpu`` off-device.

Exit codes: 0 clean, 1 policy findings, 2 usage/internal error.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_lint():
    """Import analysis/lint.py WITHOUT importing the package (which
    would drag jax in). sys.modules registration is required: lint.py
    uses ``from __future__ import annotations`` + dataclasses, and
    dataclass field resolution looks the module up by name."""
    path = os.path.join(_REPO, "deeplearning_tpu", "analysis", "lint.py")
    spec = importlib.util.spec_from_file_location("_dltpu_lint", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def cmd_rules(lint) -> int:
    width = max(len(r) for r in lint.RULES)
    for rule, desc in sorted(lint.RULES.items()):
        print(f"{rule:<{width}}  {desc}")
    print(f"\nsuppress one site:   # dltpu: allow({min(lint.RULES)})")
    print("suppress all rules:  # dltpu: allow(*)")
    return 0


def cmd_lint(lint, root: str, baseline_path: str, ci: bool,
             as_json: bool) -> int:
    t0 = time.monotonic()
    findings, n_files = lint.lint_tree(root)
    baseline = lint.load_baseline(baseline_path)
    new = lint.new_findings(findings, baseline)
    dt = time.monotonic() - t0
    n_baselined = sum(sum(r.values())
                      for r in baseline.get("counts", {}).values())
    n_new = sum(g["count"] - g["budget"] for g in new)
    clean = not new

    if as_json:
        print(json.dumps({
            "clean": clean, "files_scanned": n_files,
            "findings": [str(f) for f in findings],
            "baseline_findings": n_baselined,
            "new_groups": new, "new": n_new,
            "seconds": round(dt, 3),
        }, indent=2, sort_keys=True))
        return 0 if clean else 1

    if ci:
        # the ratchet gate: only findings NOT covered by the baseline fail
        for grp in new:
            for f in grp["findings"]:
                print(f)
            print(f"  ^ {grp['path']} has {grp['count']}x {grp['rule']} "
                  f"(baseline allows {grp['budget']}) — fix it, pragma it "
                  f"with '# dltpu: allow({grp['rule']})', or (for "
                  f"pre-existing debt only) rerun --update-baseline")
        verdict = "clean" if clean else f"{n_new} NEW finding(s)"
        print(f"dltpu-check: {verdict} — {len(findings)} total, "
              f"{n_baselined} baselined, {n_files} files, {dt:.2f}s")
        return 0 if clean else 1

    # plain lint: print everything, baselined or not
    for f in findings:
        print(f)
    print(f"dltpu-check: {len(findings)} finding(s) in {n_files} files, "
          f"{dt:.2f}s ({n_baselined} covered by baseline)")
    return 0 if clean else 1


def cmd_update_baseline(lint, root: str, baseline_path: str) -> int:
    findings, n_files = lint.lint_tree(root)
    lint.write_baseline(findings, baseline_path)
    by_rule = {}
    for f in findings:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    detail = ", ".join(f"{r}:{n}" for r, n in sorted(by_rule.items()))
    print(f"wrote {os.path.relpath(baseline_path, root)}: "
          f"{len(findings)} finding(s) across {n_files} files"
          + (f" ({detail})" if detail else ""))
    return 0


def cmd_jaxpr(as_json: bool) -> int:
    # jax from here on — keep every other path import-free
    sys.path.insert(0, _REPO)
    from deeplearning_tpu.analysis import jaxpr as jx

    rows = jx.run_audits()
    if as_json:
        print(json.dumps(rows, indent=2, sort_keys=True))
    else:
        for r in rows:
            mark = "ok " if r["ok"] else "FAIL"
            extra = f" budget={r['budget_elements']}" \
                if "budget_elements" in r else ""
            if "error" in r:
                print(f"[{mark}] {r['name']}: {r['error']}")
                continue
            col = ",".join(f"{k}x{v}" for k, v in
                           sorted(r["collectives"].items())) or "-"
            print(f"[{mark}] {r['name']}: peak={r['peak_elements']}"
                  f"{extra} transfers={r['transfers']} collectives={col}"
                  f"  ({r['note']})")
    bad = [r for r in rows if not r["ok"]]
    print(f"dltpu-check --jaxpr: {len(rows) - len(bad)}/{len(rows)} "
          f"audits within budget")
    return 0 if not bad else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="check.py", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--ci", action="store_true",
                    help="ratchet gate: fail only on non-baseline findings")
    ap.add_argument("--update-baseline", action="store_true",
                    help="re-record analysis/baseline.json from the tree")
    ap.add_argument("--rules", action="store_true",
                    help="print the DLT rule table and pragma syntax")
    ap.add_argument("--jaxpr", action="store_true",
                    help="run the structural jaxpr audits (imports jax)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ap.add_argument("--root", default=_REPO,
                    help="tree to scan (default: repo root)")
    ap.add_argument("--baseline", default=None,
                    help="baseline path (default: analysis/baseline.json)")
    args = ap.parse_args(argv)

    if args.jaxpr:
        return cmd_jaxpr(args.json)

    lint = _load_lint()
    baseline = args.baseline or lint.DEFAULT_BASELINE
    if args.rules:
        return cmd_rules(lint)
    if args.update_baseline:
        return cmd_update_baseline(lint, args.root, baseline)
    return cmd_lint(lint, args.root, baseline, ci=args.ci,
                    as_json=args.json)


if __name__ == "__main__":
    sys.exit(main())
