#!/usr/bin/env python
"""dltpu-check: the repo's policy gate.

  python tools/check.py                    # lint + concurrency audit, all findings
  python tools/check.py --ci               # ratchet gate: exit 1 on NEW findings
  python tools/check.py --update-baseline  # re-record analysis/baseline.json
  python tools/check.py --rules            # rule table (DLT1xx / DLT2xx groups)
  python tools/check.py --jaxpr            # structural audits (imports jax)

Two static layers run in one pass: the TPU-policy linter
(``analysis/lint.py``, DLT100-105) and the concurrency auditor
(``analysis/concurrency.py``, DLT200-205 — lock discipline, lock-order
deadlock cycles, thread-registry enforcement). They share one pragma
syntax and one ratchet baseline, so the CI contract stays a single
exit code.

The default/``--ci``/``--update-baseline``/``--rules`` paths never
import jax (both analysis modules are loaded standalone by file path,
not through the ``deeplearning_tpu`` package whose ``__init__`` pulls
the whole stack) — the gate stays a sub-3s pure-CPython pass that CI
can run before any accelerator is even visible. ``--jaxpr`` traces the
registered step/postprocess functions and checks their structural
budgets (peak intermediate elements, transfer primitives), so it does
import jax; run it with ``JAX_PLATFORMS=cpu`` off-device.

``--json`` additionally emits the static lock-order graph edges
(``lock_order_edges``) — the same edges ``analysis/threadsan.py``
seeds its runtime check from.

Exit codes: 0 clean, 1 policy findings, 2 usage/internal error.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_by_path(alias: str, filename: str):
    """Import an analysis module WITHOUT importing the package (which
    would drag jax in). sys.modules registration is required: lint.py
    uses ``from __future__ import annotations`` + dataclasses, and
    dataclass field resolution looks the module up by name."""
    path = os.path.join(_REPO, "deeplearning_tpu", "analysis", filename)
    spec = importlib.util.spec_from_file_location(alias, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def _load_lint():
    return _load_by_path("_dltpu_lint", "lint.py")


def _load_conc():
    # loaded after lint: concurrency.py reuses the registered alias
    return _load_by_path("_dltpu_concurrency", "concurrency.py")


def _stale_baseline(baseline: dict, root: str) -> list:
    """Baseline entries whose file no longer exists under ``root`` —
    dead weight that silently shrinks the ratchet's reach."""
    return sorted(p for p in baseline.get("counts", {})
                  if not os.path.exists(os.path.join(root, p)))


def cmd_rules(lint, conc) -> int:
    groups = (("TPU policy (DLT1xx) — analysis/lint.py", lint.RULES),
              ("concurrency (DLT2xx) — analysis/concurrency.py",
               conc.RULES))
    width = max(len(r) for _t, rules in groups for r in rules)
    for title, rules in groups:
        print(f"{title}:")
        for rule, desc in sorted(rules.items()):
            print(f"  {rule:<{width}}  {desc}")
        print()
    print(f"suppress one site:   # dltpu: allow({min(lint.RULES)})")
    print("suppress all rules:  # dltpu: allow(*)")
    return 0


def cmd_lint(lint, conc, root: str, baseline_path: str, ci: bool,
             as_json: bool) -> int:
    t0 = time.monotonic()
    findings, n_files = lint.lint_tree(root)
    conc_findings, _n2 = conc.lint_tree(root)
    findings = sorted(findings + conc_findings,
                      key=lambda f: (f.path, f.line, f.col, f.rule))
    baseline = lint.load_baseline(baseline_path)
    new = lint.new_findings(findings, baseline)
    stale = _stale_baseline(baseline, root)
    dt = time.monotonic() - t0
    n_baselined = sum(sum(r.values())
                      for r in baseline.get("counts", {}).values())
    n_new = sum(g["count"] - g["budget"] for g in new)
    clean = not new

    if as_json:
        graph = conc.lock_order_graph(root)
        print(json.dumps({
            "clean": clean, "files_scanned": n_files,
            "findings": [str(f) for f in findings],
            "baseline_findings": n_baselined,
            "new_groups": new, "new": n_new,
            "stale_baseline": stale,
            "lock_order_edges": graph["edges"],
            "lock_order_cycles": graph["cycles"],
            "seconds": round(dt, 3),
        }, indent=2, sort_keys=True))
        return 0 if clean else 1

    if ci:
        # the ratchet gate: only findings NOT covered by the baseline fail
        for grp in new:
            for f in grp["findings"]:
                print(f)
            print(f"  ^ {grp['path']} has {grp['count']}x {grp['rule']} "
                  f"(baseline allows {grp['budget']}) — fix it, pragma it "
                  f"with '# dltpu: allow({grp['rule']})', or (for "
                  f"pre-existing debt only) rerun --update-baseline")
        for p in stale:
            print(f"warning: baseline entry for missing file {p} — "
                  "run --update-baseline to prune it")
        verdict = "clean" if clean else f"{n_new} NEW finding(s)"
        print(f"dltpu-check: {verdict} — {len(findings)} total, "
              f"{n_baselined} baselined, {n_files} files, {dt:.2f}s")
        return 0 if clean else 1

    # plain lint: print everything, baselined or not
    for f in findings:
        print(f)
    print(f"dltpu-check: {len(findings)} finding(s) in {n_files} files, "
          f"{dt:.2f}s ({n_baselined} covered by baseline)")
    return 0 if clean else 1


def cmd_update_baseline(lint, conc, root: str, baseline_path: str) -> int:
    old = lint.load_baseline(baseline_path)
    pruned = _stale_baseline(old, root)
    findings, n_files = lint.lint_tree(root)
    conc_findings, _n2 = conc.lint_tree(root)
    findings = findings + conc_findings
    lint.write_baseline(findings, baseline_path)
    by_rule = {}
    for f in findings:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    detail = ", ".join(f"{r}:{n}" for r, n in sorted(by_rule.items()))
    print(f"wrote {os.path.relpath(baseline_path, root)}: "
          f"{len(findings)} finding(s) across {n_files} files"
          + (f" ({detail})" if detail else ""))
    if pruned:
        print(f"pruned {len(pruned)} stale entr"
              f"{'y' if len(pruned) == 1 else 'ies'} for missing "
              f"file(s): {', '.join(pruned)}")
    return 0


def cmd_jaxpr(as_json: bool) -> int:
    # jax from here on — keep every other path import-free
    sys.path.insert(0, _REPO)
    from deeplearning_tpu.analysis import jaxpr as jx

    rows = jx.run_audits()
    if as_json:
        print(json.dumps(rows, indent=2, sort_keys=True))
    else:
        for r in rows:
            mark = "ok " if r["ok"] else "FAIL"
            extra = f" budget={r['budget_elements']}" \
                if "budget_elements" in r else ""
            if "error" in r:
                print(f"[{mark}] {r['name']}: {r['error']}")
                continue
            col = ",".join(f"{k}x{v}" for k, v in
                           sorted(r["collectives"].items())) or "-"
            print(f"[{mark}] {r['name']}: peak={r['peak_elements']}"
                  f"{extra} transfers={r['transfers']} collectives={col}"
                  f"  ({r['note']})")
    bad = [r for r in rows if not r["ok"]]
    print(f"dltpu-check --jaxpr: {len(rows) - len(bad)}/{len(rows)} "
          f"audits within budget")
    return 0 if not bad else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="check.py", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--ci", action="store_true",
                    help="ratchet gate: fail only on non-baseline findings")
    ap.add_argument("--update-baseline", action="store_true",
                    help="re-record analysis/baseline.json from the tree")
    ap.add_argument("--rules", action="store_true",
                    help="print the DLT rule table and pragma syntax")
    ap.add_argument("--jaxpr", action="store_true",
                    help="run the structural jaxpr audits (imports jax)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ap.add_argument("--root", default=_REPO,
                    help="tree to scan (default: repo root)")
    ap.add_argument("--baseline", default=None,
                    help="baseline path (default: analysis/baseline.json)")
    args = ap.parse_args(argv)

    if args.jaxpr:
        return cmd_jaxpr(args.json)

    lint = _load_lint()
    conc = _load_conc()
    baseline = args.baseline or lint.DEFAULT_BASELINE
    if args.rules:
        return cmd_rules(lint, conc)
    if args.update_baseline:
        return cmd_update_baseline(lint, conc, args.root, baseline)
    return cmd_lint(lint, conc, args.root, baseline, ci=args.ci,
                    as_json=args.json)


if __name__ == "__main__":
    sys.exit(main())
