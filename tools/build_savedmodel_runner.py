#!/usr/bin/env python
"""Build the native C++ SavedModel inference runner against the installed
TensorFlow's C API (the onnx2trt .cpp build-step successor). Prints the
binary path."""
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def build() -> str:
    import tensorflow as tf
    tf_dir = os.path.dirname(tf.__file__)
    src_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "deeplearning_tpu", "native")
    src = os.path.join(src_dir, "savedmodel_runner.cc")
    out = os.path.join(src_dir, "savedmodel_runner")
    if os.path.exists(out) and \
            os.path.getmtime(out) >= os.path.getmtime(src):
        return out
    cmd = ["g++", "-O2", "-std=c++17", src,
           f"-I{os.path.join(tf_dir, 'include')}",
           f"-L{tf_dir}", "-l:libtensorflow_cc.so.2", "-l:libtensorflow_framework.so.2",
           f"-Wl,-rpath,{tf_dir}", "-o", out]
    subprocess.run(cmd, check=True)
    return out


if __name__ == "__main__":
    print(build())
