#!/usr/bin/env python
"""Detection training CLI: RetinaNet end-to-end with COCO evaluation.

  python tools/train_detection.py [--cfg FILE] [key value ...]
  DLTPU_PLATFORM=cpu python tools/train_detection.py train.steps=60

The detection successor of the per-project train entries
(detection/RetinaNet/train.py, fasterRcnn/train_resnet50_fpn.py): builds
the detector, trains on padded fixed-shape box batches (synthetic
colored-box data by default; npz with images/boxes/labels/valid
otherwise), then runs fixed-shape postprocess + the COCO evaluator with
the native C++ matching path and prints the 12-metric summary.
"""

from __future__ import annotations

import dataclasses
import os
import sys
from typing import Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax

if os.environ.get("DLTPU_PLATFORM"):
    jax.config.update("jax_platforms", os.environ["DLTPU_PLATFORM"])

import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class DetModelCfg:
    name: str = "retinanet_resnet18_fpn"
    num_classes: int = 3
    image_size: int = 128


@dataclasses.dataclass(frozen=True)
class DetDataCfg:
    npz: Optional[str] = None
    n_train: int = 32
    max_gt: int = 4
    batch: int = 8


@dataclasses.dataclass(frozen=True)
class DetTrainCfg:
    steps: int = 100
    lr: float = 1e-3
    clip_grad_norm: float = 1.0
    seed: int = 0
    eval_score_thresh: float = 0.3


@dataclasses.dataclass(frozen=True)
class DetConfig:
    model: DetModelCfg = dataclasses.field(default_factory=DetModelCfg)
    data: DetDataCfg = dataclasses.field(default_factory=DetDataCfg)
    train: DetTrainCfg = dataclasses.field(default_factory=DetTrainCfg)


def synthetic_boxes(n: int, size: int, num_classes: int, max_gt: int,
                    seed: int = 0):
    """Images with 1-2 colored squares; the class is the color channel."""
    rng = np.random.default_rng(seed)
    images = rng.normal(0, 0.05, (n, size, size, 3)).astype(np.float32)
    boxes = np.zeros((n, max_gt, 4), np.float32)
    labels = np.zeros((n, max_gt), np.int64)
    valid = np.zeros((n, max_gt), bool)
    for i in range(n):
        for g in range(rng.integers(1, 3)):
            w = rng.integers(size // 5, size // 2)
            h = rng.integers(size // 5, size // 2)
            x0 = rng.integers(0, size - w)
            y0 = rng.integers(0, size - h)
            cls = rng.integers(0, min(num_classes, 3))
            images[i, y0:y0 + h, x0:x0 + w, cls] += 1.5
            boxes[i, g] = (x0, y0, x0 + w, y0 + h)
            labels[i, g] = cls
            valid[i, g] = True
    return images, boxes, labels, valid


def main(argv=None) -> int:
    import optax

    from deeplearning_tpu.core.config import config_cli
    from deeplearning_tpu.core.registry import MODELS
    from deeplearning_tpu.evaluation.coco_eval import CocoEvaluator
    from deeplearning_tpu.models.detection.retinanet import (
        retinanet_anchors, retinanet_loss, retinanet_postprocess)

    cfg = config_cli(DetConfig(), argv, description=__doc__)
    size = cfg.model.image_size
    if cfg.data.npz:
        blob = np.load(cfg.data.npz)
        images, boxes, labels, valid = (blob["images"], blob["boxes"],
                                        blob["labels"], blob["valid"])
    else:
        images, boxes, labels, valid = synthetic_boxes(
            cfg.data.n_train, size, cfg.model.num_classes,
            cfg.data.max_gt, cfg.train.seed)

    model = MODELS.build(cfg.model.name, num_classes=cfg.model.num_classes)
    variables = model.init(jax.random.key(cfg.train.seed),
                           jnp.zeros((1, size, size, 3)), train=False)
    params, stats = variables["params"], variables.get("batch_stats", {})
    anchors = jnp.asarray(retinanet_anchors((size, size)))
    tx = optax.chain(optax.clip_by_global_norm(cfg.train.clip_grad_norm),
                     optax.adam(cfg.train.lr))
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, stats, batch):
        def loss_fn(p):
            out, mut = model.apply(
                {"params": p, "batch_stats": stats}, batch["image"],
                train=True, mutable=["batch_stats"])
            l = retinanet_loss(out, anchors, batch["boxes"],
                               batch["labels"], batch["valid"])
            return l["cls_loss"] + l["reg_loss"], mut
        (total, mut), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return (optax.apply_updates(params, updates), opt_state,
                mut["batch_stats"], total)

    n = len(images)
    rng = np.random.default_rng(cfg.train.seed)
    for it in range(cfg.train.steps):
        idx = rng.choice(n, cfg.data.batch, replace=False)
        batch = {"image": jnp.asarray(images[idx]),
                 "boxes": jnp.asarray(boxes[idx]),
                 "labels": jnp.asarray(labels[idx]),
                 "valid": jnp.asarray(valid[idx])}
        params, opt_state, stats, total = step(params, opt_state, stats,
                                               batch)
        if it % max(cfg.train.steps // 5, 1) == 0:
            print(f"step {it}: loss={float(total):.4f}")

    # ---- evaluate on the training set (smoke metric)
    out = model.apply({"params": params, "batch_stats": stats},
                      jnp.asarray(images), train=False)
    det = retinanet_postprocess(out, anchors, (size, size), max_det=10,
                                score_thresh=cfg.train.eval_score_thresh)
    ev = CocoEvaluator(num_classes=cfg.model.num_classes)
    for i in range(n):
        keep = np.asarray(det["valid"][i])
        ev.add_image(
            i, gt_boxes=boxes[i][valid[i]], gt_labels=labels[i][valid[i]],
            det_boxes=np.asarray(det["boxes"][i])[keep],
            det_scores=np.asarray(det["scores"][i])[keep],
            det_labels=np.asarray(det["labels"][i])[keep])
    summary = ev.summarize()
    print({k: round(v, 4) for k, v in summary.items()})
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
