#!/usr/bin/env python
"""Detection training CLI: RetinaNet / YOLOX / FCOS with COCO evaluation.

  python tools/train_detection.py [--cfg FILE] [key value ...]
  DLTPU_PLATFORM=cpu python tools/train_detection.py train.steps=60
  ... model.name=yolox_s train.multiscale=true   # bucketed random_resize

The detection successor of the per-project train entries
(detection/RetinaNet/train.py, fasterRcnn/train_resnet50_fpn.py,
YOLOX/tools/train.py): builds the detector, dispatches the family's
loss/postprocess (anchor-based focal, SimOTA, or FCOS targets), trains
on padded fixed-shape box batches (synthetic colored-box data by
default; npz with images/boxes/labels/valid otherwise), then runs
fixed-shape postprocess + the COCO evaluator with the native C++
matching path and prints the 12-metric summary. ``train.multiscale``
enables the bucketed-static-shape random_resize schedule
(train/multiscale.py).
"""

from __future__ import annotations

import dataclasses
import os
import sys
from typing import Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax

if os.environ.get("DLTPU_PLATFORM"):
    jax.config.update("jax_platforms", os.environ["DLTPU_PLATFORM"])

import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class DetModelCfg:
    name: str = "retinanet_resnet18_fpn"
    num_classes: int = 3
    image_size: int = 128
    backbone_frozen_bn: bool = False  # FrozenBatchNorm2d backbone stats
                                      # (fasterRcnn resnet50_fpn.py:5);
                                      # pair with train.freeze=backbone
                                      # for reference fine-tune semantics
    rcnn_post_nms_top_n: int = 256    # fasterrcnn proposals kept after
                                      # NMS (rpn_function.py post_nms_top_n)
    rcnn_roi_batch: int = 128         # fasterrcnn sampled rois per image
                                      # (roi_head batch_size_per_image)
    nms_impl: str = "auto"            # NMS path for every postprocess
                                      # (ops/nms.py): auto | blocked |
                                      # pallas | greedy


@dataclasses.dataclass(frozen=True)
class DetDataCfg:
    npz: Optional[str] = None
    coco: Optional[str] = None       # instances.json (real JPEG path)
    coco_images: Optional[str] = None  # default: <json dir>/images
    n_train: int = 32
    max_gt: int = 4
    batch: int = 8
    mosaic: bool = False             # 4-image mosaic per sample
    random_perspective: bool = False  # yolov5 geometric aug inside mosaic
    degrees: float = 0.0             # hyp.scratch.yaml values
    translate: float = 0.1
    scale: float = 0.5
    shear: float = 0.0
    val_rate: float = 0.1            # coco-mode eval split
    num_workers: int = 8             # coco-mode decode threads
    prefetch: int = 2                # device-feed queue depth (0 = off)


@dataclasses.dataclass(frozen=True)
class DetTrainCfg:
    steps: int = 100
    lr: float = 1e-3
    clip_grad_norm: float = 1.0
    freeze: str = ""                  # comma-separated param-path patterns
                                      # (e.g. "backbone"), yolov5 --freeze
    seed: int = 0
    eval_score_thresh: float = 0.3
    eval_tta: bool = False            # ALSO eval with multi-scale+flip
                                      # TTA (YOLOX family only)
    multiscale: bool = False          # bucketed random_resize schedule
    multiscale_min: float = 0.75      # bucket range as ratios of image_size
    multiscale_max: float = 1.25
    multiscale_every: int = 10        # steps between size changes
    no_aug_steps: int = 0             # close mosaic/perspective for the
                                      # LAST N steps and (YOLOX) add the
                                      # L1 loss — the step-based analog of
                                      # the reference's no_aug_epochs
                                      # close-mosaic schedule
                                      # (YOLOX/yolox/core/trainer.py:187-202)


@dataclasses.dataclass(frozen=True)
class DetConfig:
    model: DetModelCfg = dataclasses.field(default_factory=DetModelCfg)
    data: DetDataCfg = dataclasses.field(default_factory=DetDataCfg)
    train: DetTrainCfg = dataclasses.field(default_factory=DetTrainCfg)


def synthetic_boxes(n: int, size: int, num_classes: int, max_gt: int,
                    seed: int = 0):
    """Images with 1-2 colored squares; the class is the color channel."""
    rng = np.random.default_rng(seed)
    images = rng.normal(0, 0.05, (n, size, size, 3)).astype(np.float32)
    boxes = np.zeros((n, max_gt, 4), np.float32)
    labels = np.zeros((n, max_gt), np.int64)
    valid = np.zeros((n, max_gt), bool)
    for i in range(n):
        for g in range(rng.integers(1, 3)):
            w = rng.integers(size // 5, size // 2)
            h = rng.integers(size // 5, size // 2)
            x0 = rng.integers(0, size - w)
            y0 = rng.integers(0, size - h)
            cls = rng.integers(0, min(num_classes, 3))
            images[i, y0:y0 + h, x0:x0 + w, cls] += 1.5
            boxes[i, g] = (x0, y0, x0 + w, y0 + h)
            labels[i, g] = cls
            valid[i, g] = True
    return images, boxes, labels, valid


def build_task(model, name: str, num_classes: int, score_thresh: float,
               max_det: int = 10, rcnn_kw: Optional[dict] = None,
               nms_impl: str = "auto"):
    """Family dispatch. Returns
    (loss_fn(params, stats, batch, rng) -> (total_loss, new_stats),
     predict_fn(params, stats, images) -> padded det dict).
    The image size is read from the traced batch shape, so grids/anchors
    are rebuilt per multi-scale bucket. ``rcnn_kw``: fasterrcnn sizing
    (post_nms_top_n, roi_batch). ``nms_impl`` selects the suppression
    path for every family's postprocess (ops/nms.py).

    The predict half delegates to
    ``models/detection/predict.build_predict_fn`` — the one shared
    definition of each family's postprocessed forward, so training eval
    and the serving engine decode identically."""
    from deeplearning_tpu.models.detection.predict import build_predict_fn
    rcnn_kw = rcnn_kw or {}
    predict_fn = build_predict_fn(
        model, name, num_classes, score_thresh=score_thresh,
        max_det=max_det,
        post_nms_top_n=rcnn_kw.get("post_nms_top_n",
                                   DetModelCfg.rcnn_post_nms_top_n),
        nms_impl=nms_impl)

    def apply_train(params, stats, images, **kw):
        out, mut = model.apply({"params": params, "batch_stats": stats},
                               images, train=True,
                               mutable=["batch_stats"], **kw)
        return out, mut.get("batch_stats", stats)

    if name.startswith("retinanet"):
        from deeplearning_tpu.models.detection.retinanet import (
            retinanet_anchors, retinanet_loss, retinanet_postprocess)

        def loss_fn(params, stats, batch, rng):
            hw = batch["image"].shape[1:3]
            out, new_stats = apply_train(params, stats, batch["image"])
            l = retinanet_loss(out, jnp.asarray(retinanet_anchors(hw)),
                               batch["boxes"], batch["labels"],
                               batch["valid"])
            return l["cls_loss"] + l["reg_loss"], new_stats

        return loss_fn, predict_fn

    if name.startswith("yolox"):
        from deeplearning_tpu.models.detection.yolox import (
            yolox_grid, yolox_loss)

        def loss_fn(params, stats, batch, rng, use_l1=False):
            hw = batch["image"].shape[1:3]
            centers, strides = (jnp.asarray(a) for a in yolox_grid(hw))
            out, new_stats = apply_train(params, stats, batch["image"])
            l = yolox_loss(out, centers, strides, batch["boxes"],
                           batch["labels"], batch["valid"],
                           num_classes=num_classes, use_l1=use_l1)
            return (l["iou_loss"] + l["obj_loss"] + l["cls_loss"]
                    + l["l1_loss"], new_stats)

        return loss_fn, predict_fn

    if name.startswith("yolov5"):
        from deeplearning_tpu.models.detection.yolov5 import (
            yolov5_grid, yolov5_loss)

        def loss_fn(params, stats, batch, rng):
            hw = batch["image"].shape[1:3]
            grid = {k: jnp.asarray(v)
                    for k, v in yolov5_grid(hw).items()}
            out, new_stats = apply_train(params, stats, batch["image"])
            l = yolov5_loss(out, grid, batch["boxes"], batch["labels"],
                            batch["valid"], num_classes=num_classes)
            return (l["box_loss"] + l["obj_loss"] + l["cls_loss"],
                    new_stats)

        return loss_fn, predict_fn

    if name.startswith("fcos"):
        from deeplearning_tpu.models.detection.fcos import (
            fcos_locations, fcos_loss, fcos_targets)

        def loss_fn(params, stats, batch, rng):
            hw = batch["image"].shape[1:3]
            locs, lvl = (jnp.asarray(a) for a in fcos_locations(hw))
            out, new_stats = apply_train(params, stats, batch["image"])
            tgt = fcos_targets(locs, lvl, batch["boxes"], batch["labels"],
                               batch["valid"])
            l = fcos_loss(out, tgt)
            return (l["cls_loss"] + l["reg_loss"] + l["ctr_loss"],
                    new_stats)

        return loss_fn, predict_fn

    if name.startswith("fasterrcnn"):
        # two-stage: RPN loss on the first apply, proposals sampled
        # under stop-gradient semantics, RoI-head loss on a second apply
        # that REUSES the first call's pyramid (one backbone forward per
        # step, train_resnet50_fpn.py flow). The model's class space is
        # num_classes+1 with 0 = background, so gt labels shift +1 here
        # and detections shift -1 back in predict.
        from deeplearning_tpu.models.detection.faster_rcnn import (
            fasterrcnn_anchors, generate_proposals, roi_head_loss,
            rpn_loss, sample_rois)
        # fall back to the DetModelCfg defaults (single source of truth
        # for callers like demo.py that pass no rcnn_kw)
        post_nms = rcnn_kw.get("post_nms_top_n",
                               DetModelCfg.rcnn_post_nms_top_n)
        roi_batch = rcnn_kw.get("roi_batch", DetModelCfg.rcnn_roi_batch)

        def loss_fn(params, stats, batch, rng):
            hw = batch["image"].shape[1:3]
            anchors = jnp.asarray(fasterrcnn_anchors(hw))
            labels1 = jnp.where(batch["valid"], batch["labels"] + 1, 0)
            out, stats1 = apply_train(params, stats, batch["image"])
            r = rpn_loss(out, anchors, batch["boxes"], batch["valid"],
                         rng)
            props, pvalid = generate_proposals(out, anchors, hw,
                                               post_nms_top_n=post_nms,
                                               nms_impl=nms_impl)
            samples = sample_rois(
                jax.lax.stop_gradient(props), pvalid, batch["boxes"],
                labels1, batch["valid"], rng,
                batch_per_image=roi_batch)
            # second stage on the SAME pyramid: no backbone recompute,
            # stats1 stays the step's final batch_stats (the roi pass
            # runs no BN)
            out2, _ = apply_train(params, stats1, batch["image"],
                                  proposals=samples["rois"],
                                  pyramid=out["pyramid"])
            h = roi_head_loss(out2["roi_scores"], out2["roi_deltas"],
                              samples)
            return (r["rpn_obj_loss"] + r["rpn_reg_loss"]
                    + h["roi_cls_loss"] + h["roi_reg_loss"], stats1)

        return loss_fn, predict_fn

    raise ValueError(f"no detection task for model {name!r} "
                     "(expected retinanet*/fasterrcnn*/yolox*/fcos*)")


def main(argv=None) -> int:
    # --exp NAME: seed the config DEFAULTS from a registered DetectionExp
    # (exps/default/* analog). Precedence: defaults < exp < yaml < CLI.
    from deeplearning_tpu.core.compile_cache import enable_compile_cache
    enable_compile_cache()   # step compiles are once-per-machine, not per-run
    from deeplearning_tpu.core.config import config_cli, pop_flag
    argv = list(sys.argv[1:] if argv is None else argv)
    evolve_gens = pop_flag(argv, "--evolve")
    exp_name = pop_flag(argv, "--exp")
    defaults = DetConfig()
    if exp_name:
        from deeplearning_tpu.core.config import load_config
        from deeplearning_tpu.core.experiment import get_exp
        defaults = load_config(
            defaults, None, get_exp(exp_name=exp_name).cli_overrides())
    cfg = config_cli(defaults, argv, description=__doc__)

    if evolve_gens:
        # yolov5 --evolve analog: short training runs as the fitness
        # probe, JSONL records in runs/evolve, best hyp printed at the
        # end. Evolvable genes = the DetTrainCfg fields in the meta.
        from deeplearning_tpu.train.evolve import (DETECTION_META,
                                                   det_fitness, evolve)

        def eval_fn(hyp):
            trial = dataclasses.replace(
                cfg, train=dataclasses.replace(
                    cfg.train, lr=hyp["lr"],
                    clip_grad_norm=hyp["clip_grad_norm"]))
            return det_fitness(run(trial))

        meta = {"lr": DETECTION_META["lr"],
                "clip_grad_norm": (1.0, 0.1, 10.0)}
        best = evolve(eval_fn,
                      {"lr": cfg.train.lr,
                       "clip_grad_norm": cfg.train.clip_grad_norm},
                      meta, int(evolve_gens),
                      records_path="runs/evolve/detection.jsonl",
                      seed=cfg.train.seed)
        print(f"evolve done: best hyp {best}")
        return 0

    run(cfg)
    return 0


def run(cfg) -> dict:
    """Train + evaluate one configuration; returns the COCO summary."""
    import optax

    from deeplearning_tpu.core.registry import MODELS
    from deeplearning_tpu.evaluation.coco_eval import CocoEvaluator
    from deeplearning_tpu.train.multiscale import (MultiScaleSchedule,
                                                   resize_detection_batch)

    size = cfg.model.image_size
    num_classes = cfg.model.num_classes
    if cfg.train.eval_tta and not cfg.model.name.startswith("yolox"):
        raise ValueError("train.eval_tta currently supports the "
                         "YOLOX family")   # fail BEFORE training
    eval_max_det = 10
    train_src = val_src = None
    persp = (dict(degrees=cfg.data.degrees, translate=cfg.data.translate,
                  scale=cfg.data.scale, shear=cfg.data.shear)
             if cfg.data.random_perspective else None)
    if cfg.data.coco:
        from deeplearning_tpu.data.coco import (coco_detection_source,
                                                load_coco_json)
        from deeplearning_tpu.data.loader import MapSource
        records, class_names = load_coco_json(cfg.data.coco)
        images_dir = cfg.data.coco_images or os.path.join(
            os.path.dirname(cfg.data.coco), "images")
        if cfg.model.num_classes != len(class_names):
            raise ValueError(
                f"model.num_classes={cfg.model.num_classes} but "
                f"{cfg.data.coco} has {len(class_names)} categories — "
                "set model.num_classes to match")
        num_classes = len(class_names)
        order = np.random.default_rng(cfg.train.seed).permutation(
            len(records))
        n_val = max(int(len(records) * cfg.data.val_rate), 1)
        val_idx, tr_idx = order[:n_val], order[n_val:]
        aug_src, _ = coco_detection_source(
            images_dir=images_dir, records=records,
            class_names=class_names, image_size=size,
            max_gt=cfg.data.max_gt, augment=True, seed=cfg.train.seed,
            mosaic=cfg.data.mosaic, perspective=persp,
            # extra mosaic tiles must come from the TRAIN split only —
            # drawing from all records would train on held-out val images
            mosaic_pool=tr_idx)
        raw_src, _ = coco_detection_source(
            images_dir=images_dir, records=records,
            class_names=class_names, image_size=size,
            max_gt=cfg.data.max_gt, augment=False)
        train_src = MapSource(len(tr_idx),
                              lambda i: aug_src[int(tr_idx[i])])
        val_src = MapSource(len(val_idx),
                            lambda i: raw_src[int(val_idx[i])])
    elif cfg.data.npz:
        blob = np.load(cfg.data.npz)
        images, boxes, labels, valid = (blob["images"], blob["boxes"],
                                        blob["labels"], blob["valid"])
    else:
        images, boxes, labels, valid = synthetic_boxes(
            cfg.data.n_train, size, cfg.model.num_classes,
            cfg.data.max_gt, cfg.train.seed)
    if cfg.data.mosaic and train_src is None:
        # npz/synthetic arrays: every sample becomes a fresh mosaic
        from deeplearning_tpu.data.mixup import mosaic_array_source
        train_src = mosaic_array_source(
            images, boxes, labels, valid, out_size=size,
            max_boxes=cfg.data.max_gt, seed=cfg.train.seed,
            perspective=persp, fill=float(np.median(images[0])))

    # close-mosaic (trainer.py:187-202 close_mosaic): a geometric-aug-free
    # source for the final no_aug_steps. coco mode keeps the photometric
    # augs and drops mosaic/perspective; array modes fall back to the raw
    # arrays (built below, where the array batch fn lives).
    plain_src = None
    if cfg.train.no_aug_steps > 0 and cfg.data.coco and (
            cfg.data.mosaic or cfg.data.random_perspective):
        plain_aug, _ = coco_detection_source(
            images_dir=images_dir, records=records,
            class_names=class_names, image_size=size,
            max_gt=cfg.data.max_gt, augment=True, seed=cfg.train.seed + 1)
        plain_src = MapSource(len(tr_idx),
                              lambda i: plain_aug[int(tr_idx[i])])

    model_classes = num_classes + (
        1 if cfg.model.name.startswith("fasterrcnn") else 0)  # +background
    model_kw = {}
    if cfg.model.backbone_frozen_bn:
        model_kw["backbone_frozen_bn"] = True
    model = MODELS.build(cfg.model.name, num_classes=model_classes,
                         **model_kw)
    loss_fn_task, predict_fn = build_task(
        model, cfg.model.name, num_classes, cfg.train.eval_score_thresh,
        max_det=eval_max_det,
        rcnn_kw=dict(post_nms_top_n=cfg.model.rcnn_post_nms_top_n,
                     roi_batch=cfg.model.rcnn_roi_batch),
        nms_impl=cfg.model.nms_impl)
    variables = model.init(jax.random.key(cfg.train.seed),
                           jnp.zeros((1, size, size, 3)), train=False)
    params, stats = variables["params"], variables.get("batch_stats", {})
    from deeplearning_tpu.train.optim import build_optimizer
    tx = build_optimizer(
        "adam", cfg.train.lr, clip_grad_norm=cfg.train.clip_grad_norm,
        params=params,
        freeze=tuple(p.strip() for p in cfg.train.freeze.split(",")
                     if p.strip()) or None)
    opt_state = tx.init(params)

    schedule = None
    if cfg.train.multiscale:
        lo = int(size * cfg.train.multiscale_min) // 32 * 32
        hi = int(size * cfg.train.multiscale_max) // 32 * 32
        sizes = tuple(range(max(lo, 32), hi + 1, 32)) or (size,)
        schedule = MultiScaleSchedule(sizes=sizes,
                                      change_every=cfg.train.multiscale_every,
                                      seed=cfg.train.seed)

    import functools

    @functools.partial(jax.jit, static_argnames=("use_l1",))
    def step(params, opt_state, stats, batch, key, use_l1=False):
        def loss_fn(p):
            if use_l1:
                return loss_fn_task(p, stats, batch, key, use_l1=True)
            return loss_fn_task(p, stats, batch, key)
        (total, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return (optax.apply_updates(params, updates), opt_state,
                new_stats, total)

    rng = np.random.default_rng(cfg.train.seed)
    key = jax.random.key(cfg.train.seed)

    def make_loader_fn(src, seed):
        from deeplearning_tpu.data.device_prefetch import DevicePrefetcher
        from deeplearning_tpu.data.loader import DataLoader
        loader = DataLoader(src, cfg.data.batch, shuffle=True, seed=seed,
                            infinite=True,
                            num_workers=cfg.data.num_workers)
        if cfg.data.prefetch:
            # decode + H2D run on the prefetch worker thread, overlapped
            # with the previous step's compute; the old shape blocked on
            # a per-leaf jnp.asarray transfer inside the step loop
            it = iter(DevicePrefetcher(loader, depth=cfg.data.prefetch))
            return lambda: next(it)
        it = iter(loader)
        return lambda: {k: jnp.asarray(v) for k, v in next(it).items()}

    def make_array_fn():
        n = len(images)

        def fn():
            idx = rng.choice(n, cfg.data.batch, replace=False)
            return {"image": jnp.asarray(images[idx]),
                    "boxes": jnp.asarray(boxes[idx]),
                    "labels": jnp.asarray(labels[idx]),
                    "valid": jnp.asarray(valid[idx])}
        return fn

    next_batch = (make_loader_fn(train_src, cfg.train.seed)
                  if train_src is not None else make_array_fn())
    if cfg.train.no_aug_steps >= max(cfg.train.steps, 1):
        raise ValueError(
            f"train.no_aug_steps={cfg.train.no_aug_steps} must be < "
            f"train.steps={cfg.train.steps} (it is the length of the "
            "FINAL aug-free phase)")
    aug_close_at = (cfg.train.steps - cfg.train.no_aug_steps
                    if cfg.train.no_aug_steps > 0 else None)
    next_batch_plain = next_batch
    if aug_close_at is not None:
        if plain_src is not None:
            next_batch_plain = make_loader_fn(plain_src,
                                              cfg.train.seed + 1)
        elif train_src is not None and not cfg.data.coco:
            next_batch_plain = make_array_fn()   # raw npz/synthetic arrays
    is_yolox = cfg.model.name.startswith("yolox")

    for it in range(cfg.train.steps):
        closing = aug_close_at is not None and it >= aug_close_at
        if closing and it == aug_close_at:
            print(f"step {it}: closing mosaic/perspective"
                  + (" + adding L1 loss" if is_yolox else ""))
        batch = (next_batch_plain if closing else next_batch)()
        if schedule is not None:
            batch = resize_detection_batch(batch,
                                           schedule.size_for_step(it))
        params, opt_state, stats, total = step(
            params, opt_state, stats, batch, jax.random.fold_in(key, it),
            use_l1=bool(closing and is_yolox))
        if it % max(cfg.train.steps // 5, 1) == 0:
            print(f"step {it}: loss={float(total):.4f}")

    # ---- evaluate: coco mode on the held-out split, else train set.
    # One jitted batched postprocess per eval step; the whole padded
    # batch lands on the host in one transfer (CocoEvaluator.add_batch),
    # no per-image device slicing.
    def eval_with(pred_fn, tag=""):
        ev = CocoEvaluator(num_classes=num_classes)
        pred_jit = jax.jit(pred_fn)
        if val_src is not None:
            bs = cfg.data.batch
            n_val = len(val_src)
            for start in range(0, n_val, bs):
                # pad the tail chunk to the jitted batch shape, score
                # only the real images
                idx = np.minimum(np.arange(start, start + bs), n_val - 1)
                n_real = min(bs, n_val - start)
                sample = val_src[idx]
                det = pred_jit(params, stats,
                               jnp.asarray(sample["image"]))
                ev.add_batch(
                    np.arange(start, start + bs), det,
                    gt={"boxes": sample["boxes"],
                        "labels": sample["labels"],
                        "valid": sample["valid"]},
                    image_valid=np.arange(bs) < n_real)
        else:
            det = pred_jit(params, stats, jnp.asarray(images))
            ev.add_batch(np.arange(len(images)), det,
                         gt={"boxes": boxes, "labels": labels,
                             "valid": valid})
        summary = ev.summarize()
        print(tag + str({k: round(v, 4) for k, v in summary.items()}))
        return summary

    summary = eval_with(predict_fn)
    if cfg.train.eval_tta:
        from deeplearning_tpu.ops.tta import yolox_tta

        def predict_tta(p, st, imgs):
            raw_fn = lambda x: model.apply(
                {"params": p, "batch_stats": st}, x, train=False)
            return yolox_tta(raw_fn, imgs,
                             score_thresh=cfg.train.eval_score_thresh,
                             max_det=eval_max_det)
        summary_tta = eval_with(predict_tta, tag="TTA ")
        summary = {**summary, "tta": summary_tta}
    return summary


if __name__ == "__main__":
    raise SystemExit(main())
