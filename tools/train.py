#!/usr/bin/env python
"""Unified training CLI — the successor of every per-project train.py.

Usage:
  python tools/train.py --cfg configs/vit_b16.yaml [key value ...]
  python tools/train.py model.name=resnet50 data.synthetic=true train.epochs=2

One entry point drives the whole zoo through the registry + Trainer
(SURVEY.md §1.1: archetypes A/B/C collapse into config + hooks). Data
comes from npz/folder sources or the built-in synthetic generator (for
smoke tests; the reference bundles tiny datasets for the same purpose).
"""

from __future__ import annotations

import dataclasses
import os
import sys
from typing import Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax

# Platform override (e.g. DLTPU_PLATFORM=cpu for smoke tests). Needed
# because this image's sitecustomize imports jax before any user code, so
# the JAX_PLATFORMS env var is already consumed.
if os.environ.get("DLTPU_PLATFORM"):
    jax.config.update("jax_platforms", os.environ["DLTPU_PLATFORM"])

import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class ModelCfg:
    name: str = "mnist_cnn"
    num_classes: int = 10
    precision: str = "bf16"          # bf16 | f32
    exact_gelu: bool = False         # erf GELU (torch parity; −3.8 MFU)


@dataclasses.dataclass(frozen=True)
class DataCfg:
    folder: Optional[str] = None     # ImageFolder root (real JPEG path)
    npz: Optional[str] = None        # npz with images/labels arrays
    synthetic: bool = True
    image_size: int = 28
    channels: int = 1
    n_train: int = 512
    global_batch: int = 64
    val_rate: float = 0.2            # folder-mode train/val split
    num_workers: int = 8             # folder-mode decode threads
    augment: str = "imagenet"        # imagenet | light | none
    prefetch: int = 2                # device-feed queue depth (0 = off)


@dataclasses.dataclass(frozen=True)
class OptimCfg:
    name: str = "sgd"
    lr: float = 0.05
    weight_decay: float = 0.0
    momentum: float = 0.9
    schedule: str = "warmup_cosine"
    warmup_steps: int = 10
    clip_grad_norm: float = 0.0


@dataclasses.dataclass(frozen=True)
class TrainCfg:
    epochs: int = 3
    seed: int = 0
    label_smoothing: float = 0.0
    ema: bool = False
    workdir: Optional[str] = None
    mesh_model_axis: int = 1         # >1 enables tensor parallelism
    mesh_seq_axis: int = 1           # >1 enables sequence parallelism
    seq_parallel: str = "ring"       # ring | ulysses (transformers only)
    accum_steps: int = 1             # gradient accumulation microbatches
    mixup: bool = False              # mixup/cutmix soft targets
    async_checkpoint: bool = False   # overlap Orbax writes with training
    pipeline_stages: int = 1         # >1: GPipe pipeline over 'model' axis
                                     # (ViT family; blocks split S-ways)
    microbatches: int = 0            # pipeline microbatches (0 = stages)
    donate_batch: bool = True        # recycle input HBM buffers per step
    precompile: bool = True          # AOT step compile overlapped w/ feed
    recovery: str = "none"           # none|abort: raise on divergence;
                                     # rollback: anchor + skip + cooldown
    strict: str = ""                 # ""|transfers|nans|all: arm JAX
                                     # sanitizers (see analysis.strict)
    weight_update: str = "replicated"  # replicated | zero1: shard adam
                                     # moments over the data axes (ZeRO-1)
    grad_comm: str = "fp32"          # fp32 | int8: EQuARX block-scaled
                                     # int8 gradient collectives


@dataclasses.dataclass(frozen=True)
class Config:
    model: ModelCfg = dataclasses.field(default_factory=ModelCfg)
    data: DataCfg = dataclasses.field(default_factory=DataCfg)
    optim: OptimCfg = dataclasses.field(default_factory=OptimCfg)
    train: TrainCfg = dataclasses.field(default_factory=TrainCfg)


def load_data(cfg: DataCfg, num_classes: int
              ) -> Tuple[np.ndarray, np.ndarray]:
    if cfg.npz:
        blob = np.load(cfg.npz)
        # raw storage (often uint8 single-channel); conversion to model
        # f32/RGB happens per-sample in the loader source, NOT here — an
        # eager convert would hold a 12x float copy of the whole dataset
        return blob["images"], blob["labels"]
    rng = np.random.default_rng(0)
    n, s, c = cfg.n_train, cfg.image_size, cfg.channels
    labels = rng.integers(0, num_classes, n).astype(np.int32)
    images = rng.normal(0, 0.1, (n, s, s, c)).astype(np.float32)
    block = max(s // num_classes, 1)
    for i, lab in enumerate(labels):
        images[i, :, lab * block:(lab + 1) * block, 0] += 2.0
    return images, labels


def main(argv=None) -> int:
    from deeplearning_tpu.core.compile_cache import enable_compile_cache
    enable_compile_cache()   # step compiles are once-per-machine, not per-run
    from deeplearning_tpu.core.config import config_cli
    from deeplearning_tpu.core.registry import MODELS
    from deeplearning_tpu.data import ArraySource, DataLoader
    from deeplearning_tpu.parallel import MeshConfig, build_mesh
    from deeplearning_tpu.train import (TrainState, make_eval_step,
                                        make_train_step, shard_state)
    from deeplearning_tpu.train.classification import (make_loss_fn,
                                                       make_metric_fn)
    from deeplearning_tpu.train.optim import build_optimizer
    from deeplearning_tpu.train.schedules import build_schedule
    from deeplearning_tpu.train.trainer import Trainer

    cfg = config_cli(Config(), argv, description=__doc__)
    pp_stages = cfg.train.pipeline_stages
    if pp_stages > 1 and (cfg.train.mesh_model_axis > 1
                          or cfg.train.mesh_seq_axis > 1):
        raise ValueError("train.pipeline_stages reuses the 'model' mesh "
                         "axis; unset mesh_model_axis/mesh_seq_axis")
    if pp_stages > 1 and (cfg.train.mixup or cfg.train.ema
                          or cfg.train.accum_steps > 1):
        raise ValueError("pipeline_stages does not compose with "
                         "mixup/ema/accum_steps yet")
    if cfg.train.weight_update not in ("replicated", "zero1"):
        raise ValueError(f"train.weight_update="
                         f"{cfg.train.weight_update!r} (replicated | zero1)")
    if cfg.train.grad_comm not in ("fp32", "int8"):
        raise ValueError(f"train.grad_comm={cfg.train.grad_comm!r} "
                         "(fp32 | int8)")
    zero1 = cfg.train.weight_update == "zero1"
    if (zero1 or cfg.train.grad_comm == "int8") and (
            pp_stages > 1 or cfg.train.mesh_model_axis > 1
            or cfg.train.mesh_seq_axis > 1):
        raise ValueError("train.weight_update=zero1 / train.grad_comm=int8 "
                         "are data-parallel modes; unset pipeline_stages/"
                         "mesh_model_axis/mesh_seq_axis")
    if cfg.train.grad_comm == "int8" and cfg.train.accum_steps > 1:
        raise ValueError("train.grad_comm=int8 requires "
                         "train.accum_steps=1 (quantizing microbatch "
                         "partial sums would stack quantization error)")
    mesh = build_mesh(MeshConfig(
        data=-1,
        model=pp_stages if pp_stages > 1 else cfg.train.mesh_model_axis,
        seq=cfg.train.mesh_seq_axis))
    if pp_stages > 1 and mesh.shape["data"] > 1:
        print(f"WARNING: pipeline_stages={pp_stages} uses only the "
              f"{pp_stages}-device 'model' axis; the {mesh.shape['data']}"
              "-way 'data' axis replicates work (DPxPP composition not "
              "implemented yet) — set pipeline_stages = device count")
    if cfg.data.folder:
        from deeplearning_tpu.data.build import (LoaderConfig,
                                                 build_classification_loaders)
        lcfg = LoaderConfig(global_batch=cfg.data.global_batch,
                            image_size=cfg.data.image_size,
                            val_rate=cfg.data.val_rate,
                            num_workers=cfg.data.num_workers,
                            seed=cfg.train.seed,
                            augment=cfg.data.augment)
        loader, eval_loader, class_to_idx = build_classification_loaders(
            cfg.data.folder, lcfg, mesh=mesh,
            class_indices_path=(os.path.join(cfg.train.workdir,
                                             "class_indices.json")
                                if cfg.train.workdir else None))
        if len(class_to_idx) != cfg.model.num_classes:
            raise ValueError(
                f"model.num_classes={cfg.model.num_classes} but "
                f"{cfg.data.folder} has {len(class_to_idx)} classes")
        sample_shape = (1, cfg.data.image_size, cfg.data.image_size, 3)
        n_train = len(loader) * cfg.data.global_batch
    else:
        images, labels = load_data(cfg.data, cfg.model.num_classes)
        hw = images.shape[1:3]
        sample_shape = (1, hw[0], hw[1], cfg.data.channels)
        tr_images, tr_labels = images, labels
        ev_images, ev_labels = images, labels
        gb = cfg.data.global_batch
        if cfg.data.npz and cfg.data.val_rate > 0 and len(images) >= 2 * gb:
            # held-out split for npz datasets, BEFORE the schedule is
            # sized (total_steps must match the post-split loader) and
            # never smaller than one eval batch (the loader floor-divides,
            # so a sub-batch slice would silently eval nothing)
            order = np.random.default_rng(cfg.train.seed).permutation(
                len(images))
            n_val = min(max(int(len(images) * cfg.data.val_rate), gb),
                        len(images) - gb)
            ev_images, ev_labels = (images[order[:n_val]],
                                    labels[order[:n_val]])
            tr_images, tr_labels = (images[order[n_val:]],
                                    labels[order[n_val:]])
        n_train = len(tr_images)
    dtype = jnp.bfloat16 if cfg.model.precision == "bf16" else jnp.float32
    if cfg.model.exact_gelu:
        from deeplearning_tpu.core import numerics
        numerics.set_exact(True)
    model_kw = {}
    if cfg.train.seq_parallel not in ("ring", "ulysses"):
        raise ValueError(
            f"unknown train.seq_parallel={cfg.train.seq_parallel!r} "
            "(ring | ulysses)")
    if cfg.train.mesh_seq_axis > 1:
        # sequence parallelism INSIDE the model: every attention layer
        # shards its tokens over the 'seq' mesh axis (ring rotation or
        # Ulysses all-to-all) while batch/params stay GSPMD-sharded.
        # Transformers only — the builder must accept attn_fn.
        if cfg.train.seq_parallel == "ring":
            from deeplearning_tpu.parallel.ring_attention import (
                make_ring_attn_fn)
            model_kw["attn_fn"] = make_ring_attn_fn(mesh)
        else:
            from deeplearning_tpu.parallel.ulysses import (
                make_ulysses_attn_fn)
            model_kw["attn_fn"] = make_ulysses_attn_fn(mesh)
    model = MODELS.build(cfg.model.name, num_classes=cfg.model.num_classes,
                         dtype=dtype, **model_kw)
    sample = jnp.zeros(sample_shape)
    variables = model.init(jax.random.key(cfg.train.seed), sample,
                           train=False)
    params = variables["params"]
    k_per_stage = 0
    if pp_stages > 1:
        from deeplearning_tpu.parallel.pipeline_train import \
            split_vit_params
        outer, stages, k_per_stage = split_vit_params(params, pp_stages)
        params = {"outer": outer, "stages": stages}
    steps_per_epoch = n_train // cfg.data.global_batch
    sched = build_schedule(cfg.optim.schedule, base_lr=cfg.optim.lr,
                           total_steps=cfg.train.epochs * steps_per_epoch,
                           warmup_steps=cfg.optim.warmup_steps)
    tx = build_optimizer(cfg.optim.name, sched,
                         clip_grad_norm=cfg.optim.clip_grad_norm or None,
                         weight_decay=cfg.optim.weight_decay,
                         momentum=cfg.optim.momentum, params=params)
    state = TrainState.create(
        apply_fn=model.apply, params=params, tx=tx,
        batch_stats=variables.get("batch_stats", {}),
        use_ema=cfg.train.ema)

    if pp_stages > 1:
        from deeplearning_tpu.parallel.pipeline_train import \
            shard_pipeline_state
        state = shard_pipeline_state(state, mesh)
    else:
        state = shard_state(state, mesh, zero1=zero1)
    has_bn = bool(variables.get("batch_stats"))
    if not cfg.data.folder:
        def _cls_source(imgs, labs):
            """Per-sample uint8→f32 + channel expansion (lazy, so the
            dataset stays in its compact storage dtype in RAM)."""
            needs = (imgs.dtype == np.uint8 or imgs.ndim == 3
                     or imgs.shape[-1] != cfg.data.channels)
            if not needs:
                return ArraySource(image=imgs, label=labs)
            from deeplearning_tpu.data.loader import MapSource

            def fetch(i):
                img = imgs[i]
                if img.dtype == np.uint8:
                    img = img.astype(np.float32) / 255.0
                if img.ndim == 2:
                    img = img[..., None]
                if img.shape[-1] == 1 and cfg.data.channels == 3:
                    img = np.repeat(img, 3, axis=-1)
                return {"image": np.asarray(img, np.float32),
                        "label": labs[i]}
            return MapSource(len(imgs), fetch)

        loader = DataLoader(_cls_source(tr_images, tr_labels),
                            global_batch=cfg.data.global_batch, mesh=mesh,
                            seed=cfg.train.seed)
        eval_loader = DataLoader(_cls_source(ev_images, ev_labels),
                                 global_batch=cfg.data.global_batch,
                                 mesh=mesh, shuffle=False)
    if cfg.data.global_batch % max(cfg.train.accum_steps, 1):
        raise ValueError(
            f"data.global_batch={cfg.data.global_batch} must be divisible "
            f"by train.accum_steps={cfg.train.accum_steps}")
    if pp_stages > 1:
        from deeplearning_tpu.parallel.pipeline_train import \
            make_pipeline_train_step
        micro = cfg.train.microbatches or pp_stages
        if micro % pp_stages:
            raise ValueError(
                f"train.microbatches={micro} must be divisible by "
                f"train.pipeline_stages={pp_stages} (microbatch storage "
                "shards over the pipe axis)")
        if cfg.data.global_batch % micro:
            raise ValueError(
                f"data.global_batch={cfg.data.global_batch} must be "
                f"divisible by train.microbatches={micro}")
        base_step, pp_eval_step = make_pipeline_train_step(
            model, mesh, tx, num_stages=pp_stages,
            k_per_stage=k_per_stage, microbatches=micro,
            label_smoothing=cfg.train.label_smoothing)
    else:
        base_step = make_train_step(
            make_loss_fn(cfg.train.label_smoothing, has_bn), mesh=mesh,
            accum_steps=cfg.train.accum_steps,
            donate_batch=cfg.train.donate_batch,
            weight_update=cfg.train.weight_update,
            grad_comm=cfg.train.grad_comm)
    if cfg.train.mixup:
        from deeplearning_tpu.core import rng as rng_mod
        from deeplearning_tpu.data.mixup import mixup_cutmix

        def train_step(s, batch, rng):
            # fold the step in HERE: the Trainer hands the same run key
            # every iteration (step-folding otherwise happens inside
            # base_step, after augmentation would already have run)
            aug_key = rng_mod.step_key(jax.random.fold_in(rng, 1), s.step)
            batch = mixup_cutmix(batch, aug_key, cfg.model.num_classes,
                                 smoothing=cfg.train.label_smoothing)
            return base_step(s, batch, rng)
        train_step = jax.jit(
            train_step,
            donate_argnums=(0, 1) if cfg.train.donate_batch else (0,))
    else:
        train_step = base_step
    trainer = Trainer(
        state=state,
        train_step=train_step,
        train_loader=loader,
        eval_step=(pp_eval_step if pp_stages > 1
                   else make_eval_step(make_metric_fn())),
        eval_loader=eval_loader,
        epochs=cfg.train.epochs,
        seed=cfg.train.seed,
        workdir=cfg.train.workdir,
        async_checkpoint=cfg.train.async_checkpoint,
        log_every=max(steps_per_epoch // 2, 1),
        prefetch=cfg.data.prefetch,
        recovery=(None if cfg.train.recovery in ("none", "")
                  else cfg.train.recovery),
        strict=cfg.train.strict or None,
        weight_update=cfg.train.weight_update,
        # full config into the flight recorder: a flightrec.json from a
        # crashed run identifies the exact run that produced it
        run_config=dataclasses.asdict(cfg))
    if cfg.train.precompile:
        try:
            # AOT step compile runs while the prefetcher's worker thread
            # decodes + transfers the first batches — neither serializes
            # behind the other
            trainer.precompile()
        except Exception as e:  # noqa: BLE001 - warmup is best-effort
            print(f"precompile skipped: {e}")
    # sharding posture into the flight ring (obs_report renders it):
    # which weight-update mode this run uses and — when the AOT step is
    # available — how many collective bytes one step moves
    try:
        from deeplearning_tpu.obs import flight
        posture = {"weight_update": cfg.train.weight_update,
                   "grad_comm": cfg.train.grad_comm}
        aot = getattr(trainer, "_aot_step", None)
        if aot is not None:
            from deeplearning_tpu.analysis.jaxpr import hlo_collective_bytes
            posture["collective_bytes"] = sum(
                hlo_collective_bytes(aot).values())
        flight.record("sharding", **posture)
    # dltpu: allow(DLT104) posture is observability only, never fail a run
    except Exception:  # noqa: BLE001
        pass
    from deeplearning_tpu.elastic import EXIT_PREEMPTED, Preempted
    try:
        trainer.train()
    except Preempted:
        # checkpoint + flight ring already flushed by the Trainer; 75
        # tells the supervisor "requeue me", not "I crashed"
        return EXIT_PREEMPTED
    results = trainer.evaluate()
    print({k: round(v, 4) for k, v in results.items()})
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
