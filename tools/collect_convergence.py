#!/usr/bin/env python
"""Render the convergence-suite results as a markdown table.

Reads runs/convergence/results.jsonl (+ per-run workdir CSVs for the
classification learning curves) and prints the README table. Run after
tools/convergence_suite.py finishes.
"""

from __future__ import annotations

import json
import os
import re
import sys

OUT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "runs", "convergence")


def curve_from_log(log_path: str):
    """[(epoch, top1), ...] scraped from the Trainer's eval log lines."""
    if not os.path.exists(log_path):
        return []
    rows = []
    pat = re.compile(r"eval @ epoch (\d+):.*top1=([0-9.]+)")
    for line in open(log_path):
        m = pat.search(line)
        if m:
            rows.append((int(m.group(1)), float(m.group(2))))
    # the trainer logs each eval twice (console + file tee); dedupe
    return sorted(set(rows))


def main() -> int:
    results_path = os.path.join(OUT, "results.jsonl")
    if not os.path.exists(results_path):
        print("no results.jsonl yet")
        return 1
    raw = [json.loads(l) for l in open(results_path) if l.strip()]
    latest = {}
    for e in raw:                      # keep the LAST attempt per run
        latest[e["name"]] = e
    entries = list(latest.values())
    print("| run | rc | minutes | final metrics |")
    print("|---|---|---|---|")
    for e in entries:
        final = e["final"]
        m = re.search(r"\{.*\}", final)
        if m:
            final = m.group(0)
        elif e["rc"] != 0:
            final = "(failed)"
        print(f"| {e['name']} | {e['rc']} | {e.get('minutes', '-')} "
              f"| `{final[:160]}` |")
    for e in entries:
        curve = curve_from_log(os.path.join(OUT, f"{e['name']}.log"))
        if curve:
            pts = "  ".join(f"{ep}:{v:.3f}" for ep, v in curve)
            print(f"\n{e['name']} val-top1 curve: {pts}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
