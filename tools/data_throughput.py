#!/usr/bin/env python
"""Host input-pipeline throughput: is data the MFU ceiling?

Measures images/sec of (a) the real JPEG decode+augment folder path with
a worker pool, and (b) the decoded memmap-cache path (the zipreader/
cached-dataset capability, dataLoader/zipreader.py:23 analog) — the
production answer when per-host decode cores are scarce: decode once,
stream batches from a memmapped cache at memory bandwidth.

The ViT-B/16 step rate on one v5e chip is ~960 img/s; the memmap path
must beat that per host core, the JPEG path scales with decode cores
(this build machine has ONE core — real TPU hosts have ~100+).

Usage: python tools/data_throughput.py --folder .data/digits/cls
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def bench_jpeg_folder(root: str, image_size: int, batch: int,
                      num_workers: int, n_batches: int) -> float:
    import jax
    jax.config.update("jax_platforms", "cpu")
    from deeplearning_tpu.data.build import (LoaderConfig,
                                             build_classification_loaders)
    cfg = LoaderConfig(global_batch=batch, image_size=image_size,
                       num_workers=num_workers, val_rate=0.05)
    train, _, _ = build_classification_loaders(root, cfg)
    from deeplearning_tpu.data.build import measure_throughput
    return measure_throughput(train, n_batches=n_batches)


def bench_memmap(image_size: int, batch: int, n_batches: int,
                 n_images: int = 2048) -> float:
    from deeplearning_tpu.data.zip_cache import MemmapCache
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "cache")
        cache = MemmapCache(path, shape=(n_images, image_size,
                                         image_size, 3),
                            dtype=np.uint8)
        rng = np.random.default_rng(0)
        sample = rng.integers(0, 255, (image_size, image_size, 3),
                              dtype=np.uint8)
        for i in range(n_images):
            cache.get(i, lambda _i: sample)
        idx_rng = np.random.default_rng(1)
        _ = np.asarray(cache.arr[np.arange(batch)])   # warm
        t0 = time.perf_counter()
        n = 0
        for _ in range(n_batches):
            idx = np.sort(idx_rng.integers(0, n_images, batch))
            arr = np.asarray(cache.arr[idx])
            arr = arr.astype(np.float32)  # the normalize-cost stand-in
            n += batch
        return n / (time.perf_counter() - t0)


def bench_native_batch(root: str, image_size: int, batch: int,
                       num_workers: int, n_batches: int) -> float:
    """Raw C++ decode_resize_batch rate (native/imagedec.cpp thread pool,
    no augment) — the upper bound of the native input path."""
    from deeplearning_tpu.data.native_decode import (available,
                                                     decode_resize_batch)
    if not available():
        return 0.0
    paths = []
    for dirpath, _, files in os.walk(root):
        paths += [os.path.join(dirpath, f) for f in files
                  if f.lower().endswith((".jpg", ".jpeg"))]
    if not paths:
        return 0.0
    blobs = [open(p, "rb").read() for p in paths[:batch]]
    decode_resize_batch(blobs, image_size, image_size, num_workers)  # warm
    t0 = time.perf_counter()
    n = 0
    for i in range(n_batches):
        sel = [blobs[(i * 7 + j) % len(blobs)] for j in range(batch)]
        decode_resize_batch(sel, image_size, image_size, num_workers)
        n += batch
    return n / (time.perf_counter() - t0)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--folder", default=None)
    ap.add_argument("--image-size", type=int, default=64)
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--workers", type=int, default=8)
    ap.add_argument("--batches", type=int, default=20)
    args = ap.parse_args()

    mm = bench_memmap(args.image_size, args.batch, args.batches)
    print(f"memmap_cache: {mm:,.0f} img/s "
          f"({args.image_size}px, batch {args.batch}, 1 host core)")
    if args.folder:
        nb = bench_native_batch(args.folder, args.image_size, args.batch,
                                args.workers, args.batches)
        if nb:
            print(f"native_decode_resize: {nb:,.0f} img/s "
                  f"(C++ pool, {args.workers} threads)")
        jf = bench_jpeg_folder(args.folder, args.image_size, args.batch,
                               args.workers, args.batches)
        print(f"jpeg_decode+augment: {jf:,.0f} img/s "
              f"({args.workers} workers on {os.cpu_count()} core(s))")


if __name__ == "__main__":
    main()
