#!/usr/bin/env python
"""ViT-B/16 training-step perf sweep on one TPU chip.

Times the real train step (same construction as bench.py) across
variants — batch size, attention softmax dtype, Pallas flash kernel —
and prints a table of step-time / images-per-sec / MFU per variant.
MFU uses XLA's compiled cost analysis like bench.py so numbers are
comparable. Run on the real chip: `python tools/perf_sweep.py`.
"""

import argparse
import contextlib
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

import bench_util  # noqa: F401  (side effect: persistent compile cache)


def peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for key, val in {"v6": 918e12, "v5p": 459e12, "v5": 197e12,
                     "v4": 275e12, "v3": 123e12, "v2": 45e12}.items():
        if key in kind:
            return val
    return 197e12


def bf16_softmax_attention(q, k, v, dropout_rate=0.0, deterministic=True,
                           rng=None):
    """Naive attention with softmax kept in bf16 (row max still exact)."""
    del dropout_rate, deterministic, rng
    scale = q.shape[-1] ** -0.5
    attn = jnp.einsum("bqhd,bkhd->bhqk", q * scale, k)
    attn = jax.nn.softmax(attn, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", attn, v)


class _ConvPatchEmbed(nn.Module):
    """ViT's ORIGINAL strided-conv patch embed.

    Since round 5 `vit.PatchEmbed` lowers the patch conv as reshape+matmul
    (measured +1.2 MFU points); this restores the conv lowering so the
    A/B in ``--set r5`` stays reproducible."""
    patch_size: int = 16
    embed_dim: int = 768
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(self.embed_dim, (self.patch_size, self.patch_size),
                    strides=(self.patch_size, self.patch_size),
                    dtype=self.dtype, name="proj")(x)
        b, h, w, c = x.shape
        return x.reshape(b, h * w, c)


@contextlib.contextmanager
def patch_embed_as_conv():
    """Swap ViT back to the conv patch-embed lowering (the pre-r5 path)."""
    from deeplearning_tpu.models.classification import vit as vit_mod
    orig = vit_mod.PatchEmbed
    vit_mod.PatchEmbed = _ConvPatchEmbed
    try:
        yield
    finally:
        vit_mod.PatchEmbed = orig


def time_variant(name, batch, attn_fn=None, remat=False, n_steps=20,
                 model_name="vit_base_patch16_224", image_size=224,
                 results_path=None):
    from deeplearning_tpu.core.registry import MODELS
    from deeplearning_tpu.train import TrainState, make_train_step
    from deeplearning_tpu.train.classification import make_loss_fn
    from deeplearning_tpu.train.optim import build_optimizer
    from deeplearning_tpu.train.schedules import build_schedule

    kw = {"num_classes": 1000}
    if model_name.startswith("vit"):
        kw.update(attn_fn=attn_fn, remat=remat)
    model = MODELS.build(model_name, **kw)
    rng = jax.random.key(0)
    variables = model.init(rng, jnp.zeros((1, image_size, image_size, 3)),
                           train=False)
    params = variables["params"]
    batch_stats = variables.get("batch_stats")
    sched = build_schedule("warmup_cosine", base_lr=1e-3,
                           total_steps=10_000, warmup_steps=100)
    tx = build_optimizer("adamw", sched, weight_decay=0.05, params=params)
    state = TrainState.create(apply_fn=model.apply, params=params, tx=tx,
                              batch_stats=batch_stats)
    images = jnp.asarray(
        np.random.default_rng(0).normal(
            size=(batch, image_size, image_size, 3)), jnp.float32)
    labels = jnp.asarray(
        np.random.default_rng(1).integers(0, 1000, batch), jnp.int32)
    data = {"image": images, "label": labels}
    step = make_train_step(
        make_loss_fn(label_smoothing=0.1,
                     has_batch_stats=batch_stats is not None),
        donate=True)
    compiled = jax.jit(lambda s, b, r: step(s, b, r),
                       donate_argnums=(0,)).lower(state, data,
                                                  rng).compile()
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):   # older JAX: list of dicts
        cost = cost[0] if cost else {}
    step_flops = float(cost.get("flops", 0.0)) if cost else 0.0

    # drive the ALREADY-compiled executable (re-calling step would pay a
    # second identical XLA compile, minutes on TPU)
    state, metrics = compiled(state, data, rng)
    float(metrics["loss"])  # D2H sync (block_until_ready unreliable here)
    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, metrics = compiled(state, data, rng)
    float(metrics["loss"])
    dt = (time.perf_counter() - t0) / n_steps
    mfu = step_flops / dt / peak_flops(jax.devices()[0]) * 100.0
    # per-step-synced tail stats: the pipelined mean above hides stalls
    # (a wedged iteration, host jitter); p50/p90 make regressions visible
    per_step = []
    for _ in range(min(n_steps, 10)):
        t1 = time.perf_counter()
        state, metrics = compiled(state, data, rng)
        float(metrics["loss"])
        per_step.append(time.perf_counter() - t1)
    p50, p90 = np.percentile(per_step, [50, 90])
    print(f"{name:40s} batch={batch:4d} step={dt * 1e3:8.2f}ms "
          f"img/s={batch / dt:8.1f} mfu={mfu:6.2f}% "
          f"p50={p50 * 1e3:7.2f}ms p90={p90 * 1e3:7.2f}ms", flush=True)
    if results_path:
        from bench_util import append_result
        append_result(results_path, name, batch=batch, step_ms=dt * 1e3,
                      img_per_s=batch / dt, mfu_pct=mfu, model=model_name,
                      step_ms_p50=round(p50 * 1e3, 2),
                      step_ms_p90=round(p90 * 1e3, 2))
    del state, compiled, step
    return dt, mfu


def time_feed_variant(name, batch, n_steps=20, depth=2,
                      model_name="vit_base_patch16_224", image_size=224,
                      results_path=None):
    """End-to-end FEED benchmark: the jitted step driven through the
    Trainer's pipelined throughput pass over REAL loader batches, wrapped
    (depth>0) or not (depth=0) in a DevicePrefetcher. Unlike
    ``time_variant`` (one resident device batch, pure step time), every
    iteration here pays decode + host→HBM transfer — the row's
    ``h2d_wait_frac`` / ``prefetch_occupancy`` columns show how much of
    it the prefetch pipeline hides, so an on-chip A/B of
    feed_prefetch vs feed_serial attributes the MFU delta directly."""
    import numpy as np

    from bench_util import feed_stats
    from deeplearning_tpu.core.registry import MODELS
    from deeplearning_tpu.data import ArraySource, DataLoader
    from deeplearning_tpu.train import TrainState, make_train_step
    from deeplearning_tpu.train.classification import make_loss_fn
    from deeplearning_tpu.train.optim import build_optimizer
    from deeplearning_tpu.train.schedules import build_schedule
    from deeplearning_tpu.train.trainer import Trainer
    from deeplearning_tpu.utils.profiling import cost_analysis_dict

    model = MODELS.build(model_name, num_classes=1000)
    variables = model.init(jax.random.key(0),
                           jnp.zeros((1, image_size, image_size, 3)),
                           train=False)
    params = variables["params"]
    sched = build_schedule("warmup_cosine", base_lr=1e-3,
                           total_steps=10_000, warmup_steps=100)
    tx = build_optimizer("adamw", sched, weight_decay=0.05, params=params)
    state = TrainState.create(apply_fn=model.apply, params=params, tx=tx,
                              batch_stats=variables.get("batch_stats"))
    rng = np.random.default_rng(0)
    n_data = batch * 4          # enough distinct batches to cycle
    images = rng.normal(size=(n_data, image_size, image_size, 3)
                        ).astype(np.float32)
    labels = rng.integers(0, 1000, n_data).astype(np.int32)
    loader = DataLoader(ArraySource(image=images, label=labels),
                        global_batch=batch, shuffle=False)
    step = make_train_step(
        make_loss_fn(label_smoothing=0.1,
                     has_batch_stats=variables.get("batch_stats")
                     is not None),
        donate=True, donate_batch=True)
    trainer = Trainer(state=state, train_step=step, train_loader=loader,
                      retrace_warn=False,
                      prefetch=depth if depth else 0)
    aot = trainer.precompile()   # AOT warmup overlapped with feed start
    flops = 0.0
    if getattr(trainer, "_aot_step", None) is not None:
        flops = float(cost_analysis_dict(trainer._aot_step
                                         ).get("flops", 0.0))
    ips = trainer.throughput(n_iters=n_steps)
    stats = trainer.throughput_stats
    dt = stats["step_ms_mean"] / 1e3
    mfu = flops / dt / peak_flops(jax.devices()[0]) * 100.0 if flops \
        else 0.0
    feed = feed_stats(stats)
    print(f"{name:40s} batch={batch:4d} step={dt * 1e3:8.2f}ms "
          f"img/s={ips:8.1f} mfu={mfu:6.2f}% "
          f"h2d_frac={feed.get('h2d_wait_frac', 0.0):6.3f} "
          f"occ={feed.get('prefetch_occupancy', 0.0):4.1f} "
          f"aot={0.0 if aot is None else aot:6.2f}s", flush=True)
    if results_path:
        from bench_util import append_result
        append_result(results_path, name, batch=batch, step_ms=dt * 1e3,
                      img_per_s=ips, mfu_pct=mfu, model=model_name,
                      step_ms_p50=round(stats["step_ms_p50"], 2),
                      step_ms_p90=round(stats["step_ms_p90"], 2),
                      **feed)
    return dt, mfu


def _detect_nms_case(rng, n):
    ctr = rng.uniform(0, 2000, (n, 2))
    wh = rng.uniform(4, 64, (n, 2))
    boxes = np.concatenate([ctr - wh / 2, ctr + wh / 2],
                           axis=-1).astype(np.float32)
    return jnp.asarray(boxes), jnp.asarray(
        rng.uniform(0, 1, n).astype(np.float32))


def time_detect_set(results_path=None):
    """Detection postprocess sweep (ops/nms.py + ops/roi_align.py).

    Op rows: greedy vs blocked NMS (plus the Pallas tile kernel on TPU)
    at N in {2k, 20k}; one-pass vs masked multiscale RoIAlign at R in
    {256, 1k}. End-to-end row: the jitted RetinaNet eval path (forward +
    decode + blocked NMS), i.e. exactly what one eval step runs."""
    import functools

    from bench_util import append_op_result, append_result, bench
    from deeplearning_tpu.ops import nms as nms_ops
    from deeplearning_tpu.ops import roi_align as roi_ops

    rng = np.random.default_rng(0)
    impls = ["greedy", "blocked"]
    if jax.default_backend() == "tpu":
        impls.append("pallas")
    for n in (2000, 20000):
        boxes, scores = _detect_nms_case(rng, n)
        for impl in impls:
            fn = jax.jit(functools.partial(
                nms_ops.nms, iou_threshold=0.5, max_out=100, impl=impl))
            ms = bench(fn, (boxes, scores), n=10) * 1e3
            print(f"nms_{impl:8s} n={n:6d} {ms:9.3f} ms", flush=True)
            if results_path:
                append_op_result(results_path, f"nms_{impl}", n=n, ms=ms)

    pyr = {f"p{lvl}": jnp.asarray(rng.standard_normal(
        (256 >> (lvl - 2), 256 >> (lvl - 2), 256)).astype(np.float32))
        for lvl in (2, 3, 4, 5)}
    for r in (256, 1000):
        ctr = rng.uniform(20, 1000, (r, 2))
        size = np.exp(rng.uniform(np.log(8), np.log(500), (r, 2)))
        rois = jnp.asarray(np.clip(np.concatenate(
            [ctr - size / 2, ctr + size / 2], -1), 0, 1023
        ).astype(np.float32))
        for impl in ("onepass", "masked"):
            fn = jax.jit(functools.partial(
                roi_ops.multiscale_roi_align, impl=impl))
            ms = bench(fn, (pyr, rois), n=10) * 1e3
            print(f"roi_{impl:9s} r={r:6d} {ms:9.3f} ms", flush=True)
            if results_path:
                append_op_result(results_path, f"roi_align_{impl}",
                                 n=r, ms=ms)

    # Faster R-CNN second stage A/B (ROADMAP PR 3 follow-up): the SAME
    # jitted two-stage predict path, swapping only the model's
    # roi_align_impl knob — the row pair attributes the second-stage
    # cost to the one-pass packed gather vs the masked reference
    from deeplearning_tpu.core.registry import MODELS
    from deeplearning_tpu.models.detection.predict import build_predict_fn
    rcnn_img, rcnn_batch = 256, 2
    rcnn_images = jnp.asarray(rng.normal(
        size=(rcnn_batch, rcnn_img, rcnn_img, 3)).astype(np.float32))
    for impl in ("onepass", "masked"):
        model = MODELS.build("fasterrcnn_resnet18_fpn", num_classes=4,
                             roi_align_impl=impl)
        variables = model.init(jax.random.key(0),
                               jnp.zeros((1, rcnn_img, rcnn_img, 3)),
                               train=False)
        predict = build_predict_fn(model, "fasterrcnn_resnet18_fpn", 3,
                                   score_thresh=0.05, max_det=100)
        fn = jax.jit(functools.partial(
            predict, variables["params"],
            variables.get("batch_stats", {})))
        dt = bench(fn, (rcnn_images,), n=10)
        print(f"fasterrcnn_roi_{impl:8s} batch={rcnn_batch} "
              f"{dt * 1e3:9.2f} ms", flush=True)
        if results_path:
            append_result(results_path, f"fasterrcnn_roi_{impl}",
                          batch=rcnn_batch, step_ms=dt * 1e3,
                          img_per_s=rcnn_batch / dt, mfu_pct=0.0,
                          model="fasterrcnn_resnet18_fpn",
                          image_size=rcnn_img, roi_align_impl=impl)

    # end-to-end eval path: the per-step unit of evaluation/coco_eval —
    # one jitted forward + postprocess over a padded batch
    from deeplearning_tpu.models.detection.retinanet import (
        retinanet_anchors, retinanet_postprocess)
    img, batch = 512, 8
    model = MODELS.build("retinanet_resnet18_fpn", num_classes=80)
    variables = model.init(jax.random.key(0),
                           jnp.zeros((1, img, img, 3)), train=False)
    anchors = jnp.asarray(retinanet_anchors((img, img)))

    @jax.jit
    def eval_step(images):
        out = model.apply(variables, images, train=False)
        return retinanet_postprocess(out, anchors, (img, img),
                                     max_det=100, nms_impl="auto")

    images = jnp.asarray(rng.normal(
        size=(batch, img, img, 3)).astype(np.float32))
    dt = bench(eval_step, (images,), n=10)
    print(f"retinanet_eval_step batch={batch} {dt * 1e3:9.2f} ms "
          f"img/s={batch / dt:8.1f}", flush=True)
    if results_path:
        append_result(results_path, "retinanet_eval_e2e", batch=batch,
                      step_ms=dt * 1e3, img_per_s=batch / dt, mfu_pct=0.0,
                      model="retinanet_resnet18_fpn", image_size=img)


def time_serve_set(results_path=None):
    """Serving-path sweep (serve/ + tools/loadgen.py): the sequential
    per-request baseline vs dynamic micro-batching at several
    concurrencies, on a dispatch-dominated model so the rows isolate the
    batching win rather than raw conv throughput. On TPU, adds a
    ViT-B/16 closed-loop row — the bucket-calibration input for the
    ROADMAP follow-up."""
    from loadgen import (append_serve_row, make_images, run_closed_loop,
                         run_sequential)

    from deeplearning_tpu.serve import InferenceEngine, MicroBatcher

    engine = InferenceEngine("mnist_fcn", num_classes=10, image_size=28,
                             batch_buckets=(1, 8, 64))
    images = make_images(64, 28)
    rec = run_sequential(engine, images, 256)
    print(f"serve_sequential          {rec['req_per_s']:8.1f} req/s "
          f"p99={rec['p99_ms']:7.2f} ms", flush=True)
    if results_path:
        append_serve_row(results_path, rec, model="mnist_fcn")
    base = rec["req_per_s"]
    for conc in (8, 64):
        with MicroBatcher(engine, max_wait_ms=5.0) as mb:
            rec = run_closed_loop(mb, images, conc, 256)
        print(f"serve_closed  conc={conc:4d} {rec['req_per_s']:8.1f} "
              f"req/s p99={rec['p99_ms']:7.2f} ms "
              f"occ={rec['batch_occupancy']:.2f} "
              f"x{rec['req_per_s'] / max(base, 1e-9):.2f}", flush=True)
        if results_path:
            append_serve_row(results_path, rec, model="mnist_fcn",
                             speedup=round(rec["req_per_s"]
                                           / max(base, 1e-9), 2))

    if jax.default_backend() == "tpu":
        # on-chip row: the model the repo actually trains, served at its
        # natural buckets — feeds the v4 bucket-calibration follow-up
        engine = InferenceEngine("vit_base_patch16_224", num_classes=1000,
                                 image_size=224,
                                 batch_buckets=(1, 8, 32))
        images = make_images(32, 224)
        with MicroBatcher(engine, max_wait_ms=5.0) as mb:
            rec = run_closed_loop(mb, images, 32, 128)
        print(f"serve_closed_vit conc=32 {rec['req_per_s']:8.1f} req/s "
              f"p99={rec['p99_ms']:7.2f} ms", flush=True)
        if results_path:
            append_serve_row(results_path, rec,
                             model="vit_base_patch16_224")


def time_zoo_set(results_path=None):
    """Multi-tenant residency sweep (serve/zoo.py): per-model e2e p99
    for a model served SOLO vs as one of THREE residents taking mixed
    traffic, at fp32 vs int8 weight residency. Each variant row carries
    the zoo's resident weight bytes, the backend's ``hbm_snapshot``
    bytes-in-use (0 on CPU — no memory_stats), and the eviction count,
    so the density claim (int8 ≈ 4× more models per chip) and the
    isolation claim (a co-resident's p99 stays near solo) are both read
    off mfu_results.jsonl."""
    from loadgen import append_serve_row, make_images, run_closed_loop

    from deeplearning_tpu.obs.xla import hbm_snapshot
    from deeplearning_tpu.serve import MicroBatcher, ModelZoo

    def hbm_in_use():
        snap = hbm_snapshot()
        return sum(int(d.get("bytes_in_use") or 0)
                   for d in snap.get("devices") or [])

    tenants = {"fcn_a": "mnist_fcn", "fcn_b": "mnist_fcn",
               "cnn": "mnist_cnn"}
    buckets = (1, 8, 32)
    n_req, conc = 192, 16
    images = {a: make_images(buckets[-1], 28) for a in tenants}

    for quant in ("fp32", "int8"):
        for label, aliases in (("solo", ["fcn_a"]),
                               ("resident3", sorted(tenants))):
            zoo = ModelZoo()
            for alias in aliases:
                zoo.register(alias, tenants[alias], weight_quant=quant,
                             num_classes=10, image_size=28,
                             batch_buckets=buckets)
                zoo.load(alias, wait=True)
            mix = {a: 1.0 / len(aliases) for a in aliases}
            with MicroBatcher(zoo=zoo, max_wait_ms=2.0) as mb:
                rec = run_closed_loop(mb, images[aliases[0]], conc,
                                      n_req, mix=mix,
                                      images_by_model=images)
            zs = zoo.stats()
            resident_bytes = sum(m["bytes"]
                                 for m in zs["models"].values())
            row_name = f"zoo_{label}_{quant}"
            print(f"{row_name:22s} req/s={rec['req_per_s']:8.1f} "
                  f"weights={resident_bytes:9d}B "
                  f"hbm={hbm_in_use():11d}B "
                  f"evictions={zs['evictions']}", flush=True)
            for alias, sub in sorted(rec["models"].items()):
                print(f"  {alias:8s} p99={sub['p99_ms']:8.2f} ms "
                      f"completed={sub['completed']}", flush=True)
                if results_path:
                    append_serve_row(
                        results_path, sub, model=alias, variant=row_name,
                        weight_quant=quant, residency=len(aliases),
                        resident_bytes=resident_bytes,
                        hbm_bytes_in_use=hbm_in_use(),
                        evictions=zs["evictions"])


def time_obs_set(results_path=None):
    """Observability-overhead A/B (obs/spans.py): the same jitted train
    step timed with span tracing disabled vs enabled (per-step
    ``step_span`` bracketing, min-of-reps). The rows quantify the README
    "Observability policy" <2% budget on the real step; on CPU a small
    model keeps the run inside the tier-1 window, on TPU the ViT-B/16
    step gives the production number."""
    from bench_util import append_op_result, obs_overhead

    from deeplearning_tpu.core.registry import MODELS
    from deeplearning_tpu.train import TrainState, make_train_step
    from deeplearning_tpu.train.classification import make_loss_fn
    from deeplearning_tpu.train.optim import build_optimizer
    from deeplearning_tpu.train.schedules import build_schedule

    on_tpu = jax.default_backend() == "tpu"
    model_name, size, chans, batch = (
        ("vit_base_patch16_224", 224, 3, 128) if on_tpu
        else ("mnist_fcn", 28, 1, 64))
    model = MODELS.build(model_name, num_classes=1000 if on_tpu else 10)
    rng = jax.random.key(0)
    params = model.init(rng, jnp.zeros((1, size, size, chans)),
                        train=False)["params"]
    tx = build_optimizer("sgd", build_schedule("constant", base_lr=1e-2),
                         params=params)
    state = TrainState.create(apply_fn=model.apply, params=params, tx=tx)
    gen = np.random.default_rng(0)
    data = {"image": jnp.asarray(gen.normal(
                size=(batch, size, size, chans)), jnp.float32),
            "label": jnp.asarray(gen.integers(
                0, 1000 if on_tpu else 10, batch), jnp.int32)}
    step = jax.jit(make_train_step(make_loss_fn()))

    def one_step(s, b, r):
        _, m = step(s, b, r)
        return m["loss"]

    n = 20 if on_tpu else 50
    res = obs_overhead(one_step, (state, data, rng), n=n, reps=3)
    print(f"obs_spans_off {model_name} {res['spans_off_ms']:9.3f} ms/step",
          flush=True)
    print(f"obs_spans_on  {model_name} {res['spans_on_ms']:9.3f} ms/step "
          f"overhead={res['overhead_pct']:+.3f}% "
          f"within_2pct={res['within_budget']}", flush=True)
    if results_path:
        append_op_result(results_path, "obs_spans_off", n=n,
                         ms=res["spans_off_ms"], model=model_name)
        append_op_result(results_path, "obs_spans_on", n=n,
                         ms=res["spans_on_ms"], model=model_name,
                         overhead_pct=res["overhead_pct"],
                         within_2pct=res["within_budget"])
    return res


def time_shard_set(results_path=None):
    """Weight-update sharding A/B (ISSUE 10 tentpole): the same train
    step timed replicated vs zero1 vs zero1+int8 on the full device
    mesh. Each row carries step time, per-device optimizer-state bytes
    (the HBM win ZeRO-1 buys — ~1/dp of replicated), compiled-HLO
    collective bytes, and the compiler's ``memory_analysis`` argument
    bytes when available. On TPU this runs ViT-B/16; on CPU the mnist
    model keeps the sweep inside the tier-1 window."""
    from bench_util import append_op_result

    from deeplearning_tpu.analysis.jaxpr import hlo_collective_bytes
    from deeplearning_tpu.core.registry import MODELS
    from deeplearning_tpu.parallel.mesh import MeshConfig, build_mesh
    from deeplearning_tpu.parallel.sharding import tree_bytes_per_device
    from deeplearning_tpu.train import TrainState, make_train_step
    from deeplearning_tpu.train.classification import make_loss_fn
    from deeplearning_tpu.train.optim import build_optimizer
    from deeplearning_tpu.train.schedules import build_schedule
    from deeplearning_tpu.train.steps import shard_state

    on_tpu = jax.default_backend() == "tpu"
    model_name, size, chans, per_dev = (
        ("vit_base_patch16_224", 224, 3, 16) if on_tpu
        else ("mnist_fcn", 28, 1, 8))
    mesh = build_mesh(MeshConfig(data=-1))
    n_dev = mesh.shape["data"] * mesh.shape["fsdp"]
    batch = per_dev * n_dev
    model = MODELS.build(model_name, num_classes=1000 if on_tpu else 10)
    rng = jax.random.key(0)
    init_params = model.init(rng, jnp.zeros((1, size, size, chans)),
                             train=False)["params"]
    gen = np.random.default_rng(0)
    data = {"image": jnp.asarray(gen.normal(
                size=(batch, size, size, chans)), jnp.float32),
            "label": jnp.asarray(gen.integers(
                0, 1000 if on_tpu else 10, batch), jnp.int32)}

    variants = (("replicated", "replicated", "fp32"),
                ("zero1", "zero1", "fp32"),
                ("zero1_int8", "zero1", "int8"))
    out = {}
    for name, wu, comm in variants:
        tx = build_optimizer("adamw",
                             build_schedule("constant", base_lr=1e-3),
                             params=init_params)
        state = TrainState.create(apply_fn=model.apply,
                                  params=init_params, tx=tx)
        state = shard_state(state, mesh, zero1=(wu == "zero1"))
        opt_bytes = tree_bytes_per_device(state.opt_state)
        step = make_train_step(make_loss_fn(), mesh=mesh, donate=False,
                               weight_update=wu, grad_comm=comm)
        compiled = step.lower(state, data, rng).compile()
        coll = sum(hlo_collective_bytes(compiled).values())
        arg_bytes = None
        try:
            ma = compiled.memory_analysis()
            arg_bytes = int(getattr(ma, "argument_size_in_bytes", 0))
        # dltpu: allow(DLT104) memory_analysis is a backend-optional surface
        except Exception:  # noqa: BLE001
            pass
        state, metrics = compiled(state, data, rng)   # warmup
        float(metrics["loss"])
        n = 20 if on_tpu else 30
        t0 = time.perf_counter()
        for _ in range(n):
            state, metrics = compiled(state, data, rng)
        float(metrics["loss"])
        ms = (time.perf_counter() - t0) / n * 1e3
        print(f"shard_{name:<11s} {model_name} {ms:9.3f} ms/step "
              f"opt_bytes/dev={opt_bytes} collective_bytes={coll}",
              flush=True)
        if results_path:
            append_op_result(results_path, f"shard_{name}", n=batch,
                             ms=ms, model=model_name, devices=n_dev,
                             opt_state_bytes_per_device=opt_bytes,
                             collective_bytes=coll,
                             argument_bytes=arg_bytes)
        out[name] = {"ms": ms, "opt_bytes": opt_bytes,
                     "collective_bytes": coll}
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--set", default="batch",
                    choices=["batch", "attn", "all", "r5", "decomp",
                             "feed", "detect", "serve", "obs", "shard",
                             "zoo"])
    args = ap.parse_args()

    results = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "mfu_results.jsonl")
    if args.set in ("batch", "all"):
        for batch in (128, 160, 192, 256):
            time_variant("naive_f32softmax", batch)
    if args.set in ("attn", "all"):
        from deeplearning_tpu.ops.attention import flash_attn_adapter
        time_variant("bf16_softmax", 128, attn_fn=bf16_softmax_attention)
        time_variant("bf16_softmax", 256, attn_fn=bf16_softmax_attention)
        time_variant("flash_pallas", 128, attn_fn=flash_attn_adapter)
        time_variant("flash_pallas", 256, attn_fn=flash_attn_adapter)
    if args.set == "r5":
        # round-5 single-chip MFU pushes on the ViT-B/16 step. The
        # DEFAULT model is now tanh-GELU + matmul patch embed, so the
        # naive row is the fast path and the context restores the conv
        # for the A/B (first measured 2026-07-31: conv 50.87% vs matmul
        # 52.03%; bf16 softmax REGRESSES to 48.52% — f32 upcast fuses
        # better than bf16 exp)
        time_variant("patch_matmul_b128", 128, results_path=results)
        time_variant("bf16_softmax_b128", 128,
                     attn_fn=bf16_softmax_attention, results_path=results)
        with patch_embed_as_conv():
            time_variant("patch_conv_b128", 128, results_path=results)
    if args.set == "detect":
        time_detect_set(results_path=results)
    if args.set == "serve":
        time_serve_set(results_path=results)
    if args.set == "zoo":
        time_zoo_set(results_path=results)
    if args.set == "obs":
        time_obs_set(results_path=results)
    if args.set == "shard":
        time_shard_set(results_path=results)
    if args.set == "feed":
        # feed-side A/B for the MFU claim: serial blocking H2D vs the
        # threaded prefetch pipeline, same step, real per-iter batches
        time_feed_variant("feed_serial_b128", 128, depth=0,
                          results_path=results)
        time_feed_variant("feed_prefetch_b128", 128, depth=2,
                          results_path=results)
        time_feed_variant("feed_prefetch_deep_b128", 128, depth=4,
                          results_path=results)
    if args.set == "decomp":
        # empirical step-time decomposition (ceiling analysis): replace a
        # subsystem with identity and read the step-time delta vs the
        # full model. FLOPs drop too, so compare step_ms, not mfu_pct.
        time_variant("decomp_full", 128, results_path=results)
        time_variant("decomp_attn_identity", 128,
                     attn_fn=lambda q, k, v, **_: v, results_path=results)

        def scores_only(q, k, v, **_):
            # QK^T + softmax + AV with no f32 upcast and no scaling:
            # isolates the materialized-scores HBM cost vs numerics cost
            attn = jnp.einsum("bqhd,bkhd->bhqk", q, k)
            attn = jax.nn.softmax(attn, axis=-1)
            return jnp.einsum("bhqk,bkhd->bqhd", attn, v)

        time_variant("decomp_attn_bf16_noscale", 128, attn_fn=scores_only,
                     results_path=results)

        def padded_attn(q, k, v, **_):
            # pad N 197→256 inside attention only: aligned MXU tiles at
            # the cost of +69% attention FLOPs (a tiny absolute number)
            n = q.shape[1]
            pad = (-n) % 128
            padw = ((0, 0), (0, pad), (0, 0), (0, 0))
            qp, kp, vp = (jnp.pad(t, padw) for t in (q, k, v))
            scale = q.shape[-1] ** -0.5
            attn = jnp.einsum("bqhd,bkhd->bhqk", qp * scale, kp)
            mask = jnp.arange(kp.shape[1]) < n
            attn = jnp.where(mask[None, None, None, :], attn, -jnp.inf)
            attn = jax.nn.softmax(attn.astype(jnp.float32),
                                  axis=-1).astype(q.dtype)
            return jnp.einsum("bhqk,bkhd->bqhd", attn, vp)[:, :n]

        time_variant("decomp_attn_pad256", 128, attn_fn=padded_attn,
                     results_path=results)


if __name__ == "__main__":
    main()
