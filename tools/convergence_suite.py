#!/usr/bin/env python
"""Offline convergence suite on the HARD digits datasets (VERDICT r3 #5).

Generates the harder datasets if missing (100-class digit pairs with
clutter, 4k-scene detection, 3k-scene segmentation), then runs the
training CLIs sequentially — one per model family — appending one JSON
line per run to runs/convergence/results.jsonl and full stdout to
runs/convergence/<name>.log.

Run it in the background on the build box:
  mkdir -p runs/convergence && \\
    nohup python tools/convergence_suite.py > runs/convergence/suite.log 2>&1 &
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DATA = os.path.join(ROOT, ".data", "digits")
OUT = os.path.join(ROOT, "runs", "convergence")

ENV = dict(os.environ)
ENV.pop("PALLAS_AXON_POOL_IPS", None)   # CPU runs must not touch the
ENV.pop("AXON_LOOPBACK_RELAY", None)    # (possibly wedged) TPU tunnel
ENV["DLTPU_PLATFORM"] = "cpu"
ENV["JAX_PLATFORMS"] = "cpu"

RUNS = [
    # (name, argv) — model families per VERDICT r3 #5 + the MoE curve.
    # ORDER = round-5 evidence priority: the working tree does not survive
    # between rounds, so rows whose numbers the README already cites run
    # first; historical r4 rows re-run last if wall-clock allows.
    # round-5 MoE closure (VERDICT r4 #3): the 56px 100-class run the
    # O(T²d) dense dispatch OOM-killed in r4 (rc=-9), now feasible with
    # the scatter/gather dispatch; dense twin = the equal-size baseline
    ("swin_moe_cls_hard56_v2", [
        "tools/train.py", "model.name=swin_moe_micro_patch2_window7",
        "model.num_classes=100", "model.precision=f32",
        f"data.npz={DATA}/cls_hard56/cls_hard.npz", "data.channels=3",
        "data.val_rate=0.1", "data.global_batch=64", "train.epochs=8",
        "optim.name=adamw", "optim.lr=0.002", "optim.warmup_steps=100",
        f"train.workdir={OUT}/swin_moe56"]),
    ("swin_dense_cls_hard56", [
        "tools/train.py", "model.name=swin_micro_patch2_window7",
        "model.num_classes=100", "model.precision=f32",
        f"data.npz={DATA}/cls_hard56/cls_hard.npz", "data.channels=3",
        "data.val_rate=0.1", "data.global_batch=64", "train.epochs=8",
        "optim.name=adamw", "optim.lr=0.002", "optim.warmup_steps=100",
        f"train.workdir={OUT}/swin_dense56"]),
    # round-5 two-stage plateau (VERDICT r4 #4): shrunk config for the
    # 1-core box — 96px, FrozenBN backbone stats, half-size proposal
    # stage — run to a plateau instead of the r4 80-step loss demo
    ("fasterrcnn_r18_plateau", [
        "tools/train_detection.py", "model.name=fasterrcnn_resnet18_fpn",
        "model.num_classes=10", "model.image_size=96",
        "model.backbone_frozen_bn=true",
        "model.rcnn_post_nms_top_n=128", "model.rcnn_roi_batch=64",
        f"data.coco={DATA}/det_hard/instances.json", "data.batch=8",
        "data.max_gt=8", "train.steps=700", "train.lr=0.0005"]),
    # round-5 matched-budget aug comparison (VERDICT r4 #2): plain vs
    # mosaic+random_perspective with the close-mosaic schedule (last 20%
    # of steps aug-free + YOLOX L1), both 2000 steps
    ("yolox_tiny_det_hard_2k", [
        "tools/train_detection.py", "model.name=yolox_tiny",
        "model.num_classes=10", "model.image_size=128",
        f"data.coco={DATA}/det_hard/instances.json", "data.batch=8",
        "data.max_gt=8", "train.steps=2000", "train.lr=0.001"]),
    ("yolox_tiny_det_hard_mosaic_close", [
        "tools/train_detection.py", "model.name=yolox_tiny",
        "model.num_classes=10", "model.image_size=128",
        f"data.coco={DATA}/det_hard/instances.json", "data.batch=8",
        "data.max_gt=8", "data.mosaic=true",
        "data.random_perspective=true", "data.degrees=5",
        "train.steps=2000", "train.no_aug_steps=400", "train.lr=0.001"]),
    # 28px/batch-16 keeps the dense dispatch einsum (O(T^2 d), an MXU
    # shape, brutal on one CPU core) small enough to converge offline
    ("swin_moe_cls_hard28_e10", [
        "tools/train.py", "model.name=swin_moe_micro_patch2_window7",
        "model.num_classes=100", "model.precision=f32",
        f"data.npz={DATA}/cls_hard28/cls_hard.npz", "data.channels=3",
        "data.val_rate=0.1", "data.global_batch=16", "train.epochs=10",
        "optim.name=adamw", "optim.lr=0.002", "optim.warmup_steps=100",
        f"train.workdir={OUT}/swin_moe"]),
    ("yolox_tiny_det_hard", [
        "tools/train_detection.py", "model.name=yolox_tiny",
        "model.num_classes=10", "model.image_size=128",
        f"data.coco={DATA}/det_hard/instances.json", "data.batch=8",
        "data.max_gt=8", "train.steps=700", "train.lr=0.001"]),
    ("yolox_tiny_det_hard_mosaic", [
        "tools/train_detection.py", "model.name=yolox_tiny",
        "model.num_classes=10", "model.image_size=128",
        f"data.coco={DATA}/det_hard/instances.json", "data.batch=8",
        "data.max_gt=8", "data.mosaic=true",
        "data.random_perspective=true", "data.degrees=5",
        "train.steps=500", "train.lr=0.001"]),
    ("retinanet_r18_det_hard", [
        "tools/train_detection.py", "model.name=retinanet_resnet18_fpn",
        "model.num_classes=10", "model.image_size=128",
        f"data.coco={DATA}/det_hard/instances.json", "data.batch=8",
        "data.max_gt=8", "train.steps=500", "train.lr=0.0005"]),
    ("resnet18_cls_hard", [
        "tools/train.py", "model.name=resnet18",
        "model.num_classes=100", "model.precision=f32",
        f"data.npz={DATA}/cls_hard/cls_hard.npz", "data.channels=3",
        "data.val_rate=0.1", "data.global_batch=32", "train.epochs=3",
        "optim.name=adamw", "optim.lr=0.001", "optim.warmup_steps=100",
        f"train.workdir={OUT}/resnet18"]),
    ("hrnet_w18_seg_hard", [
        "tools/train_task.py", "--task", "segmentation",
        "model.name=hrnet_w18_seg", "model.num_classes=11",
        f"data.npz={DATA}/seg_hard/seg_hard.npz", "data.batch=8",
        "train.steps=500", "train.lr=0.001"]),
    # two-stage demo: ~30 s/step on this box, so a short loss-curve run
    ("fasterrcnn_r18_short", [
        "tools/train_detection.py", "model.name=fasterrcnn_resnet18_fpn",
        "model.num_classes=10", "model.image_size=128",
        f"data.coco={DATA}/det_hard/instances.json", "data.batch=8",
        "data.max_gt=8", "train.steps=80", "train.lr=0.0005"]),
]


def ensure_datasets() -> None:
    from tools.make_digits import (make_cls_hard, make_det_hard,
                                   make_seg_hard)
    def npz_count(path):
        import numpy as np
        return len(np.load(path)["images"])

    def json_count(path):
        with open(path) as f:
            return len(json.load(f)["images"])

    jobs = [
        (f"{DATA}/cls_hard/cls_hard.npz", npz_count, 12000,
         lambda: make_cls_hard(f"{DATA}/cls_hard", n_images=12000)),
        (f"{DATA}/cls_hard28/cls_hard.npz", npz_count, 4000,
         lambda: make_cls_hard(f"{DATA}/cls_hard28", n_images=4000,
                               size=28, seed=2)),
        (f"{DATA}/cls_hard56/cls_hard.npz", npz_count, 8000,
         lambda: make_cls_hard(f"{DATA}/cls_hard56", n_images=8000,
                               size=56, seed=4)),
        (f"{DATA}/det_hard/instances.json", json_count, 4000,
         lambda: make_det_hard(f"{DATA}/det_hard", n_images=4000)),
        (f"{DATA}/seg_hard/seg_hard.npz", npz_count, 3000,
         lambda: make_seg_hard(f"{DATA}/seg_hard", n_images=3000)),
    ]
    for path, count, want, make in jobs:
        # size check, not just existence: a dataset generated earlier
        # with different parameters would silently skew the results
        if os.path.exists(path) and count(path) == want:
            print(f"dataset ok: {path}")
        else:
            t0 = time.time()
            make()
            print(f"generated {path} in {time.time() - t0:.0f}s")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--only", default=None,
                    help="comma-separated run-name substrings")
    ap.add_argument("--timeout", type=float, default=7200,
                    help="per-run wall clock cap (s)")
    args = ap.parse_args(argv)
    os.makedirs(OUT, exist_ok=True)
    sys.path.insert(0, ROOT)
    ensure_datasets()

    results_path = os.path.join(OUT, "results.jsonl")
    done = set()
    if os.path.exists(results_path):
        with open(results_path) as f:
            done = {e["name"] for e in map(json.loads, f)
                    if isinstance(e, dict) and e.get("rc") == 0}
    for name, cmd in RUNS:
        if args.only and not any(tok in name
                                 for tok in args.only.split(",")):
            continue
        if name in done:
            print(f"skip {name} (already in results.jsonl)")
            continue
        log_path = os.path.join(OUT, f"{name}.log")
        print(f"=== {name}: {' '.join(cmd)}")
        t0 = time.time()
        with open(log_path, "w") as log:
            try:
                rc = subprocess.run(
                    [sys.executable] + cmd, cwd=ROOT, env=ENV,
                    stdout=log, stderr=subprocess.STDOUT,
                    timeout=args.timeout).returncode
            except subprocess.TimeoutExpired:
                rc = -9
        tail = ""
        try:
            with open(log_path) as f:
                lines = [l.strip() for l in f.read().splitlines()
                         if l.strip()]
            tail = lines[-1] if lines else ""
        except OSError:
            pass
        entry = {"name": name, "rc": rc,
                 "minutes": round((time.time() - t0) / 60, 1),
                 "final": tail, "cmd": " ".join(cmd)}
        with open(results_path, "a") as f:
            f.write(json.dumps(entry) + "\n")
        print(json.dumps(entry))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
