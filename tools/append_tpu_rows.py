#!/usr/bin/env python
"""Append platform-tagged rows for the round-5 on-chip convergence runs
to runs/convergence/results.jsonl (same schema as convergence_suite.py,
plus a "platform" field; the suite's own rows are implicitly cpu)."""

import json
import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(ROOT, "runs", "convergence")

RUNS = [  # (name, log file) — platform stamped "tpu-v5e" below
    ("resnet18_cls_hard_tpu", "resnet18_cls_hard_tpu.log"),
    ("swin_dense56_tpu", "swin_dense56_tpu.log"),
    ("swin_moe56_tpu", "swin_moe56_tpu.log"),
    ("yolox_tiny_det_hard_2k_tpu", "yolox_tiny_det_hard_2k_tpu.log"),
    ("fasterrcnn_r18_plateau_tpu", "fasterrcnn_r18_plateau_tpu.log"),
    ("swin_diag_lr5e4", "swin_diag_lr5e4.log"),
    ("swin_diag_lr2e3_light", "swin_diag_lr2e3_light.log"),
    ("swin_diag_lr5e4_light", "swin_diag_lr5e4_light.log"),
    ("swin_diag_lr1e3_light_w300", "swin_diag_lr1e3_light_w300.log"),
    ("swin_diag_e40", "swin_diag_e40.log"),
    ("swin_moe_e40", "swin_moe_e40.log"),
]


def main():
    path = os.path.join(OUT, "results.jsonl")
    have = set()
    if os.path.exists(path):
        with open(path) as f:
            have = {json.loads(l)["name"] for l in f if l.strip()}
    added = 0
    with open(path, "a") as out:
        for name, log in RUNS:
            if name in have:
                continue
            lp = os.path.join(OUT, log)
            if not os.path.exists(lp):
                continue
            lines = [l.strip() for l in open(lp, errors="replace")
                     if l.strip() and "WARNING" not in l]
            if not lines:
                continue
            final = lines[-1]
            if not re.match(r"^\{.*\}$", final):
                continue  # run not finished yet
            out.write(json.dumps({
                "name": name, "rc": 0, "platform": "tpu-v5e",
                "final": final, "log": f"runs/convergence/{log}"}) + "\n")
            added += 1
    print(f"appended {added} rows")


if __name__ == "__main__":
    main()
