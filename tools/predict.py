#!/usr/bin/env python
"""Inference CLI — the per-project predict.py successor.

  python tools/predict.py --model mnist_cnn --ckpt runs/x/ckpt/best \\
      --input img.png [--classes class_indices.json] [--topk 5]

Loads a checkpointed TrainState's params, runs one image (or an .npz
batch) through the model, prints top-k classes (swin predict.py:31-130
surface). Detection models print fixed-shape box outputs instead.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax

if os.environ.get("DLTPU_PLATFORM"):
    jax.config.update("jax_platforms", os.environ["DLTPU_PLATFORM"])

import jax.numpy as jnp
import numpy as np


def load_batch(path: str, size: int) -> np.ndarray:
    """Image files go through the eval transform; .npz batches are
    MODEL-READY by convention (tools/train.py feeds npz arrays raw), so
    they bypass normalization — mixing the two would double-normalize."""
    from deeplearning_tpu.data.datasets import load_image
    from deeplearning_tpu.data.transforms import (
        classification_eval_transform)
    if path.endswith(".npz"):
        return np.load(path)["images"]
    imgs = load_image(path)[None]
    fn = classification_eval_transform((size, size))
    return fn({"image": imgs})["image"]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", required=True)
    ap.add_argument("--num-classes", type=int, default=10)
    ap.add_argument("--ckpt", default=None,
                    help="orbax checkpoint dir (step dir or 'best')")
    ap.add_argument("--input", required=True)
    ap.add_argument("--size", type=int, default=224)
    ap.add_argument("--topk", type=int, default=5)
    ap.add_argument("--classes", default=None,
                    help="json mapping class index -> name")
    ap.add_argument("--tta", action="store_true",
                    help="average probabilities over a horizontal-flip "
                         "view (yolov5 --augment analog)")
    args = ap.parse_args(argv)

    from deeplearning_tpu.core.checkpoint import restore_variables
    from deeplearning_tpu.core.registry import MODELS

    model = MODELS.build(args.model, num_classes=args.num_classes)
    images = jnp.asarray(load_batch(args.input, args.size))
    variables = model.init(jax.random.key(0), images[:1], train=False)
    if args.ckpt:
        variables = restore_variables(args.ckpt, variables)
    if args.tta:
        from deeplearning_tpu.ops.tta import classify_tta
        probs = np.asarray(jax.jit(lambda v, x: classify_tta(
            lambda im: model.apply(v, im, train=False), x))(
            variables, images))
    else:
        logits = jax.jit(lambda v, x: model.apply(v, x, train=False))(
            variables, images)
        probs = np.asarray(jax.nn.softmax(logits, -1))
    names = {}
    if args.classes:
        with open(args.classes) as f:
            names = {int(k): v for k, v in json.load(f).items()}
    for bi, p in enumerate(probs):
        order = np.argsort(-p)[: args.topk]
        print(f"image {bi}: " + "  ".join(
            f"{names.get(int(i), int(i))}={p[i]:.4f}" for i in order))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
