#!/usr/bin/env python
"""Inference CLI — the per-project predict.py successor.

  python tools/predict.py --model mnist_cnn --ckpt runs/x/ckpt/best \\
      --input img.png [--classes class_indices.json] [--topk 5]

A thin client of ``deeplearning_tpu.serve.InferenceEngine``: ONE code
path builds the session (params restored once, EMA-preferring), AOT-
compiles exactly the bucket the input needs, and runs the jitted
forward — plain softmax, flip-TTA (``--tta``), or a detection family's
fixed-shape postprocess — with results reported PER IMAGE. Multi-image
``.npz`` batches print one line per image; detection output prints only
the valid rows (the class −1 padding slots of the fixed-shape outputs
are engine-internal and never shown). Serving the same session under
concurrent load is ``tools/serve.py``; this is the one-shot surface.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax

if os.environ.get("DLTPU_PLATFORM"):
    jax.config.update("jax_platforms", os.environ["DLTPU_PLATFORM"])

import numpy as np


def load_batch(path: str, size: int, task: str = "classify") -> np.ndarray:
    """Image files go through the eval transform (resize+/255 for
    detection, demo.py's frame); .npz batches are MODEL-READY by
    convention (tools/train.py feeds npz arrays raw), so they bypass
    normalization — mixing the two would double-normalize."""
    from deeplearning_tpu.data.datasets import load_image
    if path.endswith(".npz"):
        return np.asarray(np.load(path)["images"], np.float32)
    raw = np.asarray(load_image(path), np.float32)
    if task == "detect":
        import jax.numpy as jnp
        if not path.lower().endswith(".npy"):
            raw = raw / 255.0        # .npy is model-ready by convention
        return np.asarray(jax.image.resize(
            jnp.asarray(raw), (size, size, 3), "bilinear"))[None]
    from deeplearning_tpu.data.transforms import (
        classification_eval_transform)
    fn = classification_eval_transform((size, size))
    return fn({"image": raw[None]})["image"]


def report_classification(probs: np.ndarray, names, topk: int) -> None:
    for bi, p in enumerate(probs):
        order = np.argsort(-p)[:topk]
        print(f"image {bi}: " + "  ".join(
            f"{names.get(int(i), int(i))}={p[i]:.4f}" for i in order))


def report_detections(det, names) -> None:
    """Per-image detection lines, VALID rows only — the fixed-shape
    padding rows (class −1 by the PR 3 convention) stay internal."""
    for bi in range(det["boxes"].shape[0]):
        keep = np.asarray(det["valid"][bi], bool)
        rows = [{"box": [round(float(x), 1) for x in b],
                 "score": round(float(s), 4),
                 "label": names.get(int(c), int(c))}
                for b, s, c in zip(np.asarray(det["boxes"][bi])[keep],
                                   np.asarray(det["scores"][bi])[keep],
                                   np.asarray(det["labels"][bi])[keep])]
        print(f"image {bi}: " + json.dumps(rows))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", required=True)
    ap.add_argument("--num-classes", type=int, default=10)
    ap.add_argument("--ckpt", default=None,
                    help="orbax checkpoint dir (step dir or 'best')")
    ap.add_argument("--input", required=True)
    ap.add_argument("--size", type=int, default=224)
    ap.add_argument("--topk", type=int, default=5)
    ap.add_argument("--classes", default=None,
                    help="json mapping class index -> name")
    ap.add_argument("--tta", action="store_true",
                    help="average probabilities over a horizontal-flip "
                         "view (yolov5 --augment analog)")
    ap.add_argument("--score", type=float, default=0.3,
                    help="detection score threshold")
    ap.add_argument("--max-det", type=int, default=100)
    ap.add_argument("--nms-impl", default="auto")
    args = ap.parse_args(argv)

    from deeplearning_tpu.models.detection.predict import (
        is_detection_model)
    from deeplearning_tpu.serve import InferenceEngine

    task = "detect" if is_detection_model(args.model) else "classify"
    images = load_batch(args.input, args.size, task)
    n = images.shape[0]
    if args.input.endswith(".npz"):
        # npz batches are model-ready at THEIR OWN resolution — the
        # engine buckets compile for the actual array shape, not --size
        if images.shape[1] != images.shape[2]:
            raise SystemExit(f"npz images must be square for the "
                             f"bucketed engine, got {images.shape}")
        args.size = images.shape[1]
    # one-shot CLI: compile exactly the bucket this input needs (plus
    # bucket 1 so the engine surface stays uniform), nothing speculative
    engine = InferenceEngine(
        args.model, num_classes=args.num_classes, ckpt=args.ckpt,
        image_size=args.size, batch_buckets=sorted({1, n}),
        tta=args.tta, score_thresh=args.score, max_det=args.max_det,
        nms_impl=args.nms_impl)

    names = {}
    if args.classes:
        with open(args.classes) as f:
            names = {int(k): v for k, v in json.load(f).items()}

    out = engine.infer(images)
    if engine.task == "detect":
        report_detections(out, names)
    else:
        report_classification(out, names, args.topk)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
