#!/usr/bin/env python
"""Supervised training launcher: restart-on-preemption with backoff.

Wraps any training command in the elastic run supervisor (README
"Elastic run policy"): launches it, watches the heartbeat file the
Trainer writes (``DLTPU_HEARTBEAT`` is exported automatically),
distinguishes slow from wedged, kills and requeues on preemption
(exit 75) / crash / wedge under a bounded restart budget, and records
every decision to ``<workdir>/flightrec_supervisor.json``.

Usage:
  python tools/supervise.py [options] -- python tools/train.py \
      train.workdir=runs/vit train.async_checkpoint=true ...

The training command must checkpoint into a stable workdir — resume is
the child's own auto-resume; the supervisor only restarts it.

Fleet mode (``--replicas N``): launch N copies of the command, each
under its own Supervisor thread in ``<workdir>/replica-<i>/``, all
sharing one ``DLTPU_RUN_ID`` and each handed its ``DLTPU_REPLICA``
index + ``DLTPU_ENDPOINT_FILE`` — the identity contract the heartbeat
files, ``/metrics`` exposition, and trace dumps all stamp, and the one
``obs/fleet.py`` discovery + ``tools/trace_merge.py`` join on. The
exit code is CLASSIFIED, not ``max(rcs)``: crash > wedge > preempted >
clean (raw 75 would outrank a crash's 1), with the per-replica
breakdown printed.

Controller mode (``--controller``, README "Fleet controller policy"):
the fleet becomes elastic — a ``FleetController`` scrapes every
replica's ``/metrics``+``/healthz`` on a cadence, scales between
``--min-replicas`` and ``--max-replicas`` on sustained p99 / queue /
error-burn breach vs sustained idle, drains-and-requeues wedged
serving replicas (``POST /admin/drain`` → deadline → supervisor
restart directive), and treats a replica's exit 75 as a capacity
event (immediate replace-or-shed, no backoff). ``--standby N`` keeps N
fully-warmed unroutable spares; losing capacity promotes one (a healthz
flip) instead of paying a cold spawn, and per-tenant SLO breach climbs
a brownout ladder pushed to every replica. Decisions are recorded
to ``<workdir>/flightrec_controller.json``.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading
import uuid

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _print_breakdown(rows, file=sys.stderr) -> None:
    for line in rows:
        print(f"[supervise] {line}", file=file)


def _classified_exit(outcomes, rcs, run_id) -> int:
    """Fleet verdict: per-replica breakdown + one classified exit."""
    from deeplearning_tpu.elastic.supervisor import (exit_for_outcome,
                                                     worst_outcome)
    labels = {}
    for i in sorted(outcomes):
        out = outcomes[i] or ("completed" if not rcs.get(i)
                              else "crashed")
        labels[i] = out
    worst = worst_outcome(list(labels.values()) or ["crashed"])
    rc = exit_for_outcome(worst)
    _print_breakdown(
        [f"replica {i}: {labels[i]} (rc={rcs.get(i)})"
         for i in sorted(labels)]
        + [f"fleet done run_id={run_id} worst={worst} exit={rc}"])
    return rc


def run_controller(args, command) -> int:
    """--controller: replica set + policy + controller, until signaled
    (or every replica ends on its own)."""
    from deeplearning_tpu.fleet import (FleetController, FleetPolicy,
                                        ReplicaSet)
    from deeplearning_tpu.obs.fleet import SLOPolicy

    run_id = args.run_id or f"run-{uuid.uuid4().hex[:8]}"
    workdir = os.path.abspath(args.workdir)
    min_replicas = (args.min_replicas if args.min_replicas is not None
                    else args.replicas)
    max_replicas = (args.max_replicas if args.max_replicas is not None
                    else max(min_replicas * 2, args.replicas, 2))

    def factory(i: int, standby: bool = False):
        from deeplearning_tpu.elastic.supervisor import SupervisorConfig
        return SupervisorConfig(
            command,
            workdir=os.path.join(workdir, f"replica-{i}"),
            max_restarts=args.max_restarts,
            wedge_deadline_s=args.wedge_deadline,
            startup_deadline_s=args.startup_deadline,
            backoff_base_s=args.backoff_base,
            backoff_factor=args.backoff_factor,
            backoff_max_s=args.backoff_max,
            kill_grace_s=args.kill_grace,
            run_id=run_id,
            replica=i,
            env=({"DLTPU_STANDBY": "1"} if standby else None),
        )

    replica_set = ReplicaSet(factory)
    policy = FleetPolicy(
        min_replicas=min_replicas, max_replicas=max_replicas,
        p99_budget_ms=args.p99_budget, queue_high=args.queue_high,
        error_rate_budget=args.error_budget,
        breach_polls=args.breach_polls, idle_polls=args.idle_polls,
        cooldown_s=args.cooldown)
    controller = FleetController(
        replica_set, policy, run_dir=workdir,
        slo=SLOPolicy(p99_budget_ms=args.p99_budget,
                      error_rate_budget=args.error_budget),
        interval_s=args.scale_interval,
        drain_deadline_s=args.drain_deadline,
        standby_target=args.standby)

    print(f"[supervise] controller run_id={run_id} "
          f"replicas={args.replicas} standby={args.standby} "
          f"bounds=[{min_replicas},"
          f"{max_replicas}] workdir={workdir}", file=sys.stderr)
    for _ in range(args.replicas):
        replica_set.spawn()
    # warm spares are the controller's job: its first tick replenishes
    # to --standby and tracks the indices from birth
    controller.start()

    stop_evt = threading.Event()

    def _sig(signum, frame):
        stop_evt.set()
    for signum in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(signum, _sig)
        except ValueError:
            pass           # non-main thread (embedded use)

    try:
        while not stop_evt.wait(0.5):
            if not replica_set.live():
                break      # every replica ended on its own
    except KeyboardInterrupt:
        pass
    controller.stop()
    replica_set.stop_all("controller_shutdown")
    replica_set.join()
    s = controller.summary()
    print(f"[supervise] controller done ticks={s['ticks']} "
          f"scale_ups={s['scale_ups']} scale_downs={s['scale_downs']} "
          f"drains={s['drains']} requeues={s['requeues']} "
          f"preemptions={s['preemptions']} "
          f"promotions={s['promotions']} brownouts={s['brownouts']}",
          file=sys.stderr)
    return _classified_exit(replica_set.outcomes(),
                            replica_set.results(), run_id)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("--workdir", default="runs/supervised",
                        help="supervisor state dir (heartbeat + "
                             "flightrec_supervisor.json)")
    parser.add_argument("--max-restarts", type=int, default=5,
                        help="restart budget across preemptions, "
                             "crashes, and wedge kills")
    parser.add_argument("--wedge-deadline", type=float, default=120.0,
                        help="seconds with neither step nor activity "
                             "progress before the child counts as wedged")
    parser.add_argument("--startup-deadline", type=float, default=600.0,
                        help="seconds to wait for the first heartbeat "
                             "(covers import + first compile)")
    parser.add_argument("--backoff-base", type=float, default=1.0)
    parser.add_argument("--backoff-factor", type=float, default=2.0)
    parser.add_argument("--backoff-max", type=float, default=60.0)
    parser.add_argument("--kill-grace", type=float, default=10.0,
                        help="seconds between SIGTERM and SIGKILL when "
                             "killing a wedged child")
    parser.add_argument("--replicas", type=int, default=1,
                        help="launch N supervised replicas of the "
                             "command under one run id (fleet mode)")
    parser.add_argument("--run-id", default=None,
                        help="fleet run id (default: random); exported "
                             "to children as DLTPU_RUN_ID")
    parser.add_argument("--controller", action="store_true",
                        help="closed-loop fleet controller: autoscale "
                             "between --min/--max-replicas, drain-and-"
                             "requeue wedged replicas, treat exit 75 "
                             "as capacity")
    parser.add_argument("--min-replicas", type=int, default=None,
                        help="controller scale floor (default: "
                             "--replicas)")
    parser.add_argument("--max-replicas", type=int, default=None,
                        help="controller scale ceiling (default: "
                             "max(2*floor, --replicas, 2))")
    parser.add_argument("--scale-interval", type=float, default=2.0,
                        help="controller tick cadence, seconds")
    parser.add_argument("--drain-deadline", type=float, default=10.0,
                        help="seconds a draining replica gets to flush "
                             "before the kill/requeue")
    parser.add_argument("--p99-budget", type=float, default=500.0,
                        help="fleet e2e p99 SLO budget, ms")
    parser.add_argument("--error-budget", type=float, default=0.05,
                        help="fleet error-burn budget (rejected + "
                             "timed-out over submitted, per window)")
    parser.add_argument("--queue-high", type=float, default=16.0,
                        help="queue depth per live replica that counts "
                             "as a scaling breach")
    parser.add_argument("--breach-polls", type=int, default=3,
                        help="consecutive breached ticks before a "
                             "scale-up")
    parser.add_argument("--idle-polls", type=int, default=6,
                        help="consecutive idle ticks before a "
                             "scale-down")
    parser.add_argument("--cooldown", type=float, default=30.0,
                        help="seconds between scale actions")
    parser.add_argument("--standby", type=int, default=0,
                        help="warm spares the controller keeps fully "
                             "warmed but unroutable (DLTPU_STANDBY=1); "
                             "wedges/preemptions/scale-ups promote one "
                             "instead of cold-spawning")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="training command (prefix with --)")
    args = parser.parse_args(argv)

    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        parser.error("no training command given (put it after --)")
    if args.replicas < 1:
        parser.error("--replicas must be >= 1")

    if args.controller:
        return run_controller(args, command)

    from deeplearning_tpu.elastic.supervisor import (Supervisor,
                                                     SupervisorConfig)

    def build_cfg(workdir: str, run_id, replica) -> SupervisorConfig:
        return SupervisorConfig(
            command,
            workdir=workdir,
            max_restarts=args.max_restarts,
            wedge_deadline_s=args.wedge_deadline,
            startup_deadline_s=args.startup_deadline,
            backoff_base_s=args.backoff_base,
            backoff_factor=args.backoff_factor,
            backoff_max_s=args.backoff_max,
            kill_grace_s=args.kill_grace,
            run_id=run_id,
            replica=replica,
        )

    if args.replicas == 1 and args.run_id is None:
        return Supervisor(build_cfg(args.workdir, None, None)).run()

    from deeplearning_tpu.obs import threads as obs_threads

    run_id = args.run_id or f"run-{uuid.uuid4().hex[:8]}"
    print(f"[supervise] fleet run_id={run_id} "
          f"replicas={args.replicas} workdir={args.workdir}",
          file=sys.stderr)
    rcs = {i: 1 for i in range(args.replicas)}
    sups = {}

    def _one(i: int) -> None:
        cfg = build_cfg(os.path.join(args.workdir, f"replica-{i}"),
                        run_id, i)
        sups[i] = Supervisor(cfg)
        try:
            rcs[i] = sups[i].run()
        except Exception as e:  # noqa: BLE001 - one replica's failure
            print(f"[supervise] replica {i} supervisor died: {e!r}",
                  file=sys.stderr)
            rcs[i] = 1

    # non-daemon on purpose: the fleet result is the join below (DLT203)
    threads = [obs_threads.spawn(_one, args=(i,),
                                 name=f"supervise-{i}",
                                 daemon=False, start=False)
               for i in range(args.replicas)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    outcomes = {i: (sups[i].final_outcome if i in sups else None)
                for i in range(args.replicas)}
    return _classified_exit(outcomes, rcs, run_id)


if __name__ == "__main__":
    raise SystemExit(main())
