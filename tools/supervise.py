#!/usr/bin/env python
"""Supervised training launcher: restart-on-preemption with backoff.

Wraps any training command in the elastic run supervisor (README
"Elastic run policy"): launches it, watches the heartbeat file the
Trainer writes (``DLTPU_HEARTBEAT`` is exported automatically),
distinguishes slow from wedged, kills and requeues on preemption
(exit 75) / crash / wedge under a bounded restart budget, and records
every decision to ``<workdir>/flightrec_supervisor.json``.

Usage:
  python tools/supervise.py [options] -- python tools/train.py \
      train.workdir=runs/vit train.async_checkpoint=true ...

The training command must checkpoint into a stable workdir — resume is
the child's own auto-resume; the supervisor only restarts it.

Fleet mode (``--replicas N``): launch N copies of the command, each
under its own Supervisor thread in ``<workdir>/replica-<i>/``, all
sharing one ``DLTPU_RUN_ID`` and each handed its ``DLTPU_REPLICA``
index + ``DLTPU_ENDPOINT_FILE`` — the identity contract the heartbeat
files, ``/metrics`` exposition, and trace dumps all stamp, and the one
``obs/fleet.py`` discovery + ``tools/trace_merge.py`` join on. Exit
code is the worst replica's.
"""

from __future__ import annotations

import argparse
import os
import sys
import uuid

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("--workdir", default="runs/supervised",
                        help="supervisor state dir (heartbeat + "
                             "flightrec_supervisor.json)")
    parser.add_argument("--max-restarts", type=int, default=5,
                        help="restart budget across preemptions, "
                             "crashes, and wedge kills")
    parser.add_argument("--wedge-deadline", type=float, default=120.0,
                        help="seconds with neither step nor activity "
                             "progress before the child counts as wedged")
    parser.add_argument("--startup-deadline", type=float, default=600.0,
                        help="seconds to wait for the first heartbeat "
                             "(covers import + first compile)")
    parser.add_argument("--backoff-base", type=float, default=1.0)
    parser.add_argument("--backoff-factor", type=float, default=2.0)
    parser.add_argument("--backoff-max", type=float, default=60.0)
    parser.add_argument("--kill-grace", type=float, default=10.0,
                        help="seconds between SIGTERM and SIGKILL when "
                             "killing a wedged child")
    parser.add_argument("--replicas", type=int, default=1,
                        help="launch N supervised replicas of the "
                             "command under one run id (fleet mode)")
    parser.add_argument("--run-id", default=None,
                        help="fleet run id (default: random); exported "
                             "to children as DLTPU_RUN_ID")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="training command (prefix with --)")
    args = parser.parse_args(argv)

    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        parser.error("no training command given (put it after --)")
    if args.replicas < 1:
        parser.error("--replicas must be >= 1")

    from deeplearning_tpu.elastic.supervisor import (Supervisor,
                                                     SupervisorConfig)

    def build_cfg(workdir: str, run_id, replica) -> SupervisorConfig:
        return SupervisorConfig(
            command,
            workdir=workdir,
            max_restarts=args.max_restarts,
            wedge_deadline_s=args.wedge_deadline,
            startup_deadline_s=args.startup_deadline,
            backoff_base_s=args.backoff_base,
            backoff_factor=args.backoff_factor,
            backoff_max_s=args.backoff_max,
            kill_grace_s=args.kill_grace,
            run_id=run_id,
            replica=replica,
        )

    if args.replicas == 1 and args.run_id is None:
        return Supervisor(build_cfg(args.workdir, None, None)).run()

    from deeplearning_tpu.obs import threads as obs_threads

    run_id = args.run_id or f"run-{uuid.uuid4().hex[:8]}"
    print(f"[supervise] fleet run_id={run_id} "
          f"replicas={args.replicas} workdir={args.workdir}",
          file=sys.stderr)
    rcs = [1] * args.replicas

    def _one(i: int) -> None:
        cfg = build_cfg(os.path.join(args.workdir, f"replica-{i}"),
                        run_id, i)
        try:
            rcs[i] = Supervisor(cfg).run()
        except Exception as e:  # noqa: BLE001 - one replica's failure
            print(f"[supervise] replica {i} supervisor died: {e!r}",
                  file=sys.stderr)
            rcs[i] = 1

    # non-daemon on purpose: the fleet result is the join below (DLT203)
    threads = [obs_threads.spawn(_one, args=(i,),
                                 name=f"supervise-{i}",
                                 daemon=False, start=False)
               for i in range(args.replicas)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    print(f"[supervise] fleet done run_id={run_id} rcs={rcs}",
          file=sys.stderr)
    return max(rcs)


if __name__ == "__main__":
    raise SystemExit(main())
