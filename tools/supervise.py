#!/usr/bin/env python
"""Supervised training launcher: restart-on-preemption with backoff.

Wraps any training command in the elastic run supervisor (README
"Elastic run policy"): launches it, watches the heartbeat file the
Trainer writes (``DLTPU_HEARTBEAT`` is exported automatically),
distinguishes slow from wedged, kills and requeues on preemption
(exit 75) / crash / wedge under a bounded restart budget, and records
every decision to ``<workdir>/flightrec_supervisor.json``.

Usage:
  python tools/supervise.py [options] -- python tools/train.py \
      train.workdir=runs/vit train.async_checkpoint=true ...

The training command must checkpoint into a stable workdir — resume is
the child's own auto-resume; the supervisor only restarts it.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("--workdir", default="runs/supervised",
                        help="supervisor state dir (heartbeat + "
                             "flightrec_supervisor.json)")
    parser.add_argument("--max-restarts", type=int, default=5,
                        help="restart budget across preemptions, "
                             "crashes, and wedge kills")
    parser.add_argument("--wedge-deadline", type=float, default=120.0,
                        help="seconds with neither step nor activity "
                             "progress before the child counts as wedged")
    parser.add_argument("--startup-deadline", type=float, default=600.0,
                        help="seconds to wait for the first heartbeat "
                             "(covers import + first compile)")
    parser.add_argument("--backoff-base", type=float, default=1.0)
    parser.add_argument("--backoff-factor", type=float, default=2.0)
    parser.add_argument("--backoff-max", type=float, default=60.0)
    parser.add_argument("--kill-grace", type=float, default=10.0,
                        help="seconds between SIGTERM and SIGKILL when "
                             "killing a wedged child")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="training command (prefix with --)")
    args = parser.parse_args(argv)

    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        parser.error("no training command given (put it after --)")

    from deeplearning_tpu.elastic.supervisor import (Supervisor,
                                                     SupervisorConfig)
    cfg = SupervisorConfig(
        command,
        workdir=args.workdir,
        max_restarts=args.max_restarts,
        wedge_deadline_s=args.wedge_deadline,
        startup_deadline_s=args.startup_deadline,
        backoff_base_s=args.backoff_base,
        backoff_factor=args.backoff_factor,
        backoff_max_s=args.backoff_max,
        kill_grace_s=args.kill_grace,
    )
    return Supervisor(cfg).run()


if __name__ == "__main__":
    raise SystemExit(main())
