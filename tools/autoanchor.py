#!/usr/bin/env python
"""Autoanchor CLI: check/recompute YOLOv5 anchors for a COCO-json dataset.

  python tools/autoanchor.py --coco instances.json --img-size 640
  python tools/autoanchor.py --coco instances.json --n 9 --force

The yolov5 autoanchor surface (utils/autoanchor.py: check_anchors BPR
gate + kmean_anchors recompute) as a standalone tool: loads gt boxes,
scales wh to the training image size, prints the current anchors' best
possible recall, and proposes k-means anchors when BPR < 0.98 (or
always with --force).
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def gt_wh_from_coco(path: str, img_size: int) -> np.ndarray:
    """(G, 2) gt widths/heights scaled as training would resize them
    (longest side -> img_size, aspect preserved)."""
    from deeplearning_tpu.data.coco import load_coco_json
    records, _ = load_coco_json(path)
    whs = []
    for rec in records:
        scale = img_size / max(rec["height"], rec["width"])
        for box in rec["boxes"]:
            x0, y0, x1, y1 = box
            whs.append(((x1 - x0) * scale, (y1 - y0) * scale))
    return np.asarray(whs, np.float64)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--coco", required=True, help="instances.json")
    ap.add_argument("--img-size", type=int, default=640)
    ap.add_argument("--n", type=int, default=9, help="anchor count")
    ap.add_argument("--thr", type=float, default=4.0,
                    help="wh ratio threshold (hyp anchor_t)")
    ap.add_argument("--force", action="store_true",
                    help="recompute even when BPR >= 0.98")
    args = ap.parse_args(argv)

    from deeplearning_tpu.models.detection.yolov5 import (DEFAULT_ANCHORS,
                                                          check_anchors,
                                                          kmean_anchors)

    wh = gt_wh_from_coco(args.coco, args.img_size)
    if len(wh) == 0:
        raise SystemExit("no gt boxes in the dataset")
    current = np.asarray(DEFAULT_ANCHORS, np.float64).reshape(-1, 2)
    fit = check_anchors(wh, current, thr=args.thr)
    print(f"current anchors: BPR={fit['bpr']:.4f} "
          f"anchors/target={fit['aat']:.2f} over {len(wh)} gts")
    if fit["bpr"] >= 0.98 and not args.force:
        print("BPR >= 0.98 — current anchors are fine "
              "(yolov5 check_anchors gate)")
        return 0
    proposed = kmean_anchors(wh, n=args.n)
    pfit = check_anchors(wh, proposed, thr=args.thr)
    print(f"k-means anchors: BPR={pfit['bpr']:.4f} "
          f"anchors/target={pfit['aat']:.2f}")
    for row in proposed.round(1):
        print(f"  [{row[0]:.1f}, {row[1]:.1f}]")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
