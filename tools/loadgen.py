#!/usr/bin/env python
"""Load generator for the serving engine (closed- and open-loop).

  # 64 closed-loop clients against a dynamically-batched model
  python tools/loadgen.py --model mnist_fcn --num-classes 10 --size 28 \\
      --buckets 1,8,64 --mode compare --concurrency 64 --n 512

Modes:
- ``closed``: N concurrent clients, each submitting back-to-back
  (throughput under saturation — the MLPerf-server closed loop).
- ``open``: fixed-rate arrivals regardless of completions (latency under
  a target QPS; finds the knee where admission control kicks in).
- ``sequential``: one-at-a-time ``engine.infer`` — the predict.py-style
  baseline dynamic batching is measured against.
- ``compare``: sequential then closed, printing the speedup (the serve
  acceptance gate: batched ≥3× sequential at 64 clients on CPU).

Every run can append a ``--set serve`` row (op schema:
``bench_util.append_op_result``) to tools/mfu_results.jsonl so the
request-path latency trajectory is recorded next to the train-step MFU
rows.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax

if os.environ.get("DLTPU_PLATFORM"):
    jax.config.update("jax_platforms", os.environ["DLTPU_PLATFORM"])

import numpy as np


def _percentiles_ms(lats):
    if not lats:
        return {"p50_ms": 0.0, "p90_ms": 0.0, "p99_ms": 0.0}
    p50, p90, p99 = (float(v) for v in
                     np.percentile(np.asarray(lats), [50, 90, 99]))
    return {"p50_ms": round(p50 * 1e3, 3), "p90_ms": round(p90 * 1e3, 3),
            "p99_ms": round(p99 * 1e3, 3)}


def make_images(n: int, size: int, seed: int = 0) -> np.ndarray:
    return np.random.default_rng(seed).normal(
        size=(n, size, size, 3)).astype(np.float32)


def run_sequential(engine, images, n_requests: int) -> dict:
    """Unbatched baseline: requests served one at a time, each paying a
    full dispatch + materialize round-trip (tools/predict.py's shape)."""
    lats = []
    t0 = time.perf_counter()
    for i in range(n_requests):
        t1 = time.perf_counter()
        engine.infer(images[i % len(images)])
        lats.append(time.perf_counter() - t1)
    wall = time.perf_counter() - t0
    return {"mode": "sequential", "completed": n_requests, "rejected": 0,
            "timed_out": 0, "req_per_s": round(n_requests / wall, 1),
            "wall_s": round(wall, 3), **_percentiles_ms(lats)}


def run_closed_loop(batcher, images, concurrency: int, n_requests: int,
                    timeout_s: float = 30.0) -> dict:
    """``concurrency`` clients, each submit→materialize back-to-back
    until ``n_requests`` total complete. Backpressure rejections honor
    the retry-after hint (bounded, so a saturated queue slows clients
    down instead of losing work)."""
    from deeplearning_tpu.serve import DeadlineExceeded, Rejected

    lock = threading.Lock()
    state = {"launched": 0, "completed": 0, "rejected": 0, "timed_out": 0}
    lats = []

    def worker(wid: int):
        rng = np.random.default_rng(wid)
        while True:
            with lock:
                if state["launched"] >= n_requests:
                    return
                state["launched"] += 1
            img = images[int(rng.integers(len(images)))]
            t0 = time.perf_counter()
            try:
                handle = batcher.submit(img)
                handle.result(timeout=timeout_s)
            except Rejected as r:
                with lock:
                    state["rejected"] += 1
                time.sleep(min(r.retry_after_s, 0.2))
                continue
            except DeadlineExceeded:
                with lock:
                    state["timed_out"] += 1
                continue
            with lock:
                state["completed"] += 1
                lats.append(time.perf_counter() - t0)

    threads = [threading.Thread(target=worker, args=(w,), daemon=True)
               for w in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    snap = batcher.telemetry.snapshot()
    return {"mode": "closed", "concurrency": concurrency, **state,
            "req_per_s": round(state["completed"] / wall, 1),
            "wall_s": round(wall, 3), **_percentiles_ms(lats),
            "batch_occupancy": snap["batch_occupancy"],
            "queue_depth_mean": snap["queue_depth_mean"],
            "shed_batches": snap["shed_batches"]}


def run_open_loop(batcher, images, rate_hz: float, duration_s: float,
                  timeout_s: float = 10.0) -> dict:
    """Fixed-rate arrivals: one submitter paces requests at ``rate_hz``;
    a resolver pool materializes results. Rejections are counted and
    DROPPED (open-loop semantics — the arrival process never waits)."""
    import queue as _queue

    from deeplearning_tpu.serve import DeadlineExceeded, Rejected

    handles: "_queue.Queue" = _queue.Queue()
    lock = threading.Lock()
    state = {"submitted": 0, "completed": 0, "rejected": 0,
             "timed_out": 0}
    lats = []
    done = threading.Event()

    def resolver():
        while True:
            item = handles.get()
            if item is None:
                return
            t0, handle = item
            try:
                handle.result(timeout=timeout_s)
            except (DeadlineExceeded, Exception):  # noqa: BLE001
                with lock:
                    state["timed_out"] += 1
                continue
            with lock:
                state["completed"] += 1
                lats.append(time.perf_counter() - t0)

    pool = [threading.Thread(target=resolver, daemon=True)
            for _ in range(8)]
    for t in pool:
        t.start()
    period = 1.0 / rate_hz
    rng = np.random.default_rng(0)
    t_end = time.perf_counter() + duration_s
    next_t = time.perf_counter()
    while time.perf_counter() < t_end:
        now = time.perf_counter()
        if now < next_t:
            time.sleep(next_t - now)
        next_t += period
        img = images[int(rng.integers(len(images)))]
        t0 = time.perf_counter()
        try:
            handle = batcher.submit(img)
        except Rejected:
            with lock:
                state["rejected"] += 1
            continue
        with lock:
            state["submitted"] += 1
        handles.put((t0, handle))
    for _ in pool:
        handles.put(None)
    for t in pool:
        t.join(timeout=timeout_s)
    done.set()
    snap = batcher.telemetry.snapshot()
    return {"mode": "open", "rate_hz": rate_hz, **state,
            "req_per_s": round(state["completed"] / duration_s, 1),
            **_percentiles_ms(lats),
            "batch_occupancy": snap["batch_occupancy"],
            "queue_depth_mean": snap["queue_depth_mean"],
            "shed_batches": snap["shed_batches"]}


def append_serve_row(results_path: str, rec: dict, **extra) -> None:
    """One serve row in the shared op-row schema (``"op" in rec`` splits
    op rows from step rows for every mfu_results.jsonl consumer)."""
    from bench_util import append_op_result
    tag = rec.get("concurrency", rec.get("rate_hz", 1))
    append_op_result(
        results_path, f"serve_{rec['mode']}", n=int(tag),
        ms=rec.get("p50_ms", 0.0), req_per_s=rec.get("req_per_s", 0.0),
        p99_ms=rec.get("p99_ms", 0.0), completed=rec.get("completed", 0),
        rejected=rec.get("rejected", 0),
        batch_occupancy=rec.get("batch_occupancy", 0.0), **extra)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    # default: a model whose single-request cost is dispatch-dominated,
    # so the compare mode isolates the batching win (a conv model's CPU
    # compute scales linearly with batch and hides the amortization)
    ap.add_argument("--model", default="mnist_fcn")
    ap.add_argument("--num-classes", type=int, default=10)
    ap.add_argument("--ckpt", default=None)
    ap.add_argument("--size", type=int, default=28)
    ap.add_argument("--buckets", default="1,8,64",
                    help="comma-separated batch buckets")
    ap.add_argument("--mode", default="compare",
                    choices=["closed", "open", "sequential", "compare"])
    ap.add_argument("--concurrency", type=int, default=64)
    ap.add_argument("--n", type=int, default=512,
                    help="total requests (closed/sequential)")
    ap.add_argument("--rate", type=float, default=200.0,
                    help="open-loop arrivals per second")
    ap.add_argument("--duration", type=float, default=5.0,
                    help="open-loop duration seconds")
    ap.add_argument("--max-wait-ms", type=float, default=5.0)
    ap.add_argument("--max-queue", type=int, default=256)
    ap.add_argument("--timeout-s", type=float, default=None,
                    help="per-request deadline")
    ap.add_argument("--results", default=None,
                    help="append serve rows to this jsonl "
                         "(default: tools/mfu_results.jsonl; 'none' off)")
    args = ap.parse_args(argv)

    from deeplearning_tpu.serve import InferenceEngine, MicroBatcher

    buckets = tuple(int(b) for b in args.buckets.split(","))
    engine = InferenceEngine(
        args.model, num_classes=args.num_classes, ckpt=args.ckpt,
        image_size=args.size, batch_buckets=buckets)
    images = make_images(max(buckets[-1], 64), args.size)
    results_path = args.results or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "mfu_results.jsonl")
    write_rows = (args.results or "").lower() != "none"

    def report(rec, **extra):
        print(json.dumps(rec), flush=True)
        if write_rows:
            append_serve_row(results_path, rec, model=args.model,
                             **extra)

    recs = []
    if args.mode in ("sequential", "compare"):
        rec = run_sequential(engine, images, args.n)
        report(rec)
        recs.append(rec)
    if args.mode in ("closed", "compare"):
        with MicroBatcher(engine, max_wait_ms=args.max_wait_ms,
                          max_queue=args.max_queue,
                          default_timeout_s=args.timeout_s) as mb:
            rec = run_closed_loop(mb, images, args.concurrency, args.n)
        report(rec)
        recs.append(rec)
    if args.mode == "open":
        with MicroBatcher(engine, max_wait_ms=args.max_wait_ms,
                          max_queue=args.max_queue,
                          default_timeout_s=args.timeout_s) as mb:
            rec = run_open_loop(mb, images, args.rate, args.duration)
        report(rec)
        recs.append(rec)
    if args.mode == "compare" and len(recs) == 2:
        speedup = recs[1]["req_per_s"] / max(recs[0]["req_per_s"], 1e-9)
        print(json.dumps({"mode": "compare",
                          "speedup_vs_sequential": round(speedup, 2)}),
              flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
