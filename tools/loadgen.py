#!/usr/bin/env python
"""Load generator for the serving engine (closed- and open-loop).

  # 64 closed-loop clients against a dynamically-batched model
  python tools/loadgen.py --model mnist_fcn --num-classes 10 --size 28 \\
      --buckets 1,8,64 --mode compare --concurrency 64 --n 512

Modes:
- ``closed``: N concurrent clients, each submitting back-to-back
  (throughput under saturation — the MLPerf-server closed loop).
- ``open``: fixed-rate arrivals regardless of completions (latency under
  a target QPS; finds the knee where admission control kicks in).
- ``sequential``: one-at-a-time ``engine.infer`` — the predict.py-style
  baseline dynamic batching is measured against.
- ``compare``: sequential then closed, printing the speedup (the serve
  acceptance gate: batched ≥3× sequential at 64 clients on CPU).

Mixed multi-tenant traffic (one ``ModelZoo``, weighted per-request
model choice, per-model op rows):

  python tools/loadgen.py --mode closed --concurrency 32 --n 256 \\
      --mix "mnist_fcn=0.7,mnist_cnn=0.3" --size 28 --buckets 1,8,32

Every run can append a ``--set serve`` row (op schema:
``bench_util.append_op_result``) to tools/mfu_results.jsonl so the
request-path latency trajectory is recorded next to the train-step MFU
rows; ``--mix`` runs append one row per tenant.

Fleet HTTP mode (``--mode open --fleet-urls`` / ``--fleet-dir``):
arrivals POST ``/predict`` to a replica fleet through a
``FleetRouter`` (round-robin, drains skipped, failover on 503,
deadline propagation, budgeted retries + tail hedging, per-replica
circuit breakers — README "Resilience policy"). Open-loop records
carry a per-second ``timeline`` (QPS split + p99 + retry/hedge/
deadline-miss counts) so recovery-after-fault can be asserted against
the trajectory, not the run-wide aggregate, and embed the router's
``resilience_stats()``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax

if os.environ.get("DLTPU_PLATFORM"):
    jax.config.update("jax_platforms", os.environ["DLTPU_PLATFORM"])

import numpy as np


def _percentiles_ms(lats):
    if not lats:
        return {"p50_ms": 0.0, "p90_ms": 0.0, "p99_ms": 0.0}
    p50, p90, p99 = (float(v) for v in
                     np.percentile(np.asarray(lats), [50, 90, 99]))
    return {"p50_ms": round(p50 * 1e3, 3), "p90_ms": round(p90 * 1e3, 3),
            "p99_ms": round(p99 * 1e3, 3)}


def make_images(n: int, size: int, seed: int = 0) -> np.ndarray:
    return np.random.default_rng(seed).normal(
        size=(n, size, size, 3)).astype(np.float32)


class Timeline:
    """Per-second QPS/latency buckets for the open-loop modes.

    The aggregate p99 of a 30 s run can look fine while 5 s of it were
    an outage; the recovery assertions ("p99 back in band within N
    seconds of the replacement warming") need the trajectory, not the
    summary. Submissions/rejections bucket at arrival time, completions
    and their latencies at completion time."""

    def __init__(self):
        self.t0 = time.perf_counter()
        self._lock = threading.Lock()
        self._buckets: dict = {}

    _KEYS = ("submitted", "completed", "rejected", "timed_out",
             "no_route", "retries", "hedged", "deadline_miss")

    def note(self, key: str, lat=None, n: int = 1) -> None:
        sec = int(time.perf_counter() - self.t0)
        with self._lock:
            row = self._buckets.setdefault(
                sec, {k: 0 for k in self._KEYS} | {"lats": []})
            row[key] += n
            if lat is not None:
                row["lats"].append(lat)

    def rows(self) -> list:
        with self._lock:
            out = []
            for sec in sorted(self._buckets):
                row = self._buckets[sec]
                out.append(
                    {"t": sec}
                    | {k: row[k] for k in self._KEYS}
                    | {"p99_ms": _percentiles_ms(row["lats"])["p99_ms"]})
            return out


def run_sequential(engine, images, n_requests: int) -> dict:
    """Unbatched baseline: requests served one at a time, each paying a
    full dispatch + materialize round-trip (tools/predict.py's shape)."""
    lats = []
    t0 = time.perf_counter()
    for i in range(n_requests):
        t1 = time.perf_counter()
        engine.infer(images[i % len(images)])
        lats.append(time.perf_counter() - t1)
    wall = time.perf_counter() - t0
    return {"mode": "sequential", "completed": n_requests, "rejected": 0,
            "timed_out": 0, "req_per_s": round(n_requests / wall, 1),
            "wall_s": round(wall, 3), **_percentiles_ms(lats)}


def parse_mix(raw: str) -> dict:
    """``--mix "a=0.7,b=0.3"`` → {alias: normalized weight}. A bare
    alias counts as weight 1 before normalization."""
    out = {}
    for part in raw.split(","):
        alias, _, w = part.partition("=")
        alias = alias.strip()
        if alias:
            out[alias] = float(w) if w else 1.0
    if not out:
        raise ValueError(f"empty --mix {raw!r}")
    total = sum(out.values())
    return {a: w / total for a, w in out.items()}


class _MixSampler:
    """Weighted per-request model choice + per-model tallies for the
    mixed-traffic loops. ``None`` mix degrades to the single-model path
    (model=None submits, one aggregate tally)."""

    def __init__(self, mix, images_by_model, images):
        self.mix = mix
        self.aliases = sorted(mix) if mix else [None]
        self.weights = (np.asarray([mix[a] for a in self.aliases])
                        if mix else None)
        self.images_by_model = images_by_model or {}
        self.images = images
        self.per = {a: {"completed": 0, "rejected": 0, "timed_out": 0,
                        "lats": []} for a in self.aliases}

    def pick(self, rng):
        if self.mix is None:
            return None, self.images
        alias = self.aliases[int(rng.choice(len(self.aliases),
                                            p=self.weights))]
        return alias, self.images_by_model.get(alias, self.images)

    def tally(self, alias, key, lat=None):
        row = self.per[alias]
        row[key] += 1
        if lat is not None:
            row["lats"].append(lat)

    def model_recs(self, mode: str, wall: float) -> dict:
        if self.mix is None:
            return {}
        out = {}
        for alias in self.aliases:
            row = self.per[alias]
            out[alias] = {
                "mode": mode, "model": alias,
                "mix_weight": round(self.mix[alias], 4),
                "completed": row["completed"],
                "rejected": row["rejected"],
                "timed_out": row["timed_out"],
                "req_per_s": round(row["completed"] / max(wall, 1e-9), 1),
                **_percentiles_ms(row["lats"])}
        return out


def run_closed_loop(batcher, images, concurrency: int, n_requests: int,
                    timeout_s: float = 30.0, mix=None,
                    images_by_model=None) -> dict:
    """``concurrency`` clients, each submit→materialize back-to-back
    until ``n_requests`` total complete. Backpressure rejections honor
    the retry-after hint (bounded, so a saturated queue slows clients
    down instead of losing work). With ``mix`` each request samples its
    target model by weight and the record carries per-model splits."""
    from concurrent.futures import TimeoutError as _FutTimeout

    from deeplearning_tpu.serve import DeadlineExceeded, Rejected

    lock = threading.Lock()
    state = {"launched": 0, "completed": 0, "rejected": 0, "timed_out": 0}
    lats = []
    sampler = _MixSampler(mix, images_by_model, images)

    def worker(wid: int):
        rng = np.random.default_rng(wid)
        while True:
            with lock:
                if state["launched"] >= n_requests:
                    return
                state["launched"] += 1
            alias, pool = sampler.pick(rng)
            img = pool[int(rng.integers(len(pool)))]
            t0 = time.perf_counter()
            try:
                handle = batcher.submit(img, model=alias)
                handle.result(timeout=timeout_s)
            except Rejected as r:
                with lock:
                    state["rejected"] += 1
                    if alias is not None:
                        sampler.tally(alias, "rejected")
                time.sleep(min(r.retry_after_s, 0.2))
                continue
            except (DeadlineExceeded, _FutTimeout):
                # a result that outlived timeout_s counts as timed out;
                # the worker keeps driving load instead of dying
                with lock:
                    state["timed_out"] += 1
                    if alias is not None:
                        sampler.tally(alias, "timed_out")
                continue
            lat = time.perf_counter() - t0
            with lock:
                state["completed"] += 1
                lats.append(lat)
                if alias is not None:
                    sampler.tally(alias, "completed", lat)

    from deeplearning_tpu.obs import threads as obs_threads
    threads = [obs_threads.spawn(worker, args=(w,), daemon=True,
                                 name=f"loadgen-closed-{w}", start=False)
               for w in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    snap = batcher.telemetry.snapshot()
    rec = {"mode": "closed", "concurrency": concurrency, **state,
           "req_per_s": round(state["completed"] / wall, 1),
           "wall_s": round(wall, 3), **_percentiles_ms(lats),
           "batch_occupancy": snap["batch_occupancy"],
           "queue_depth_mean": snap["queue_depth_mean"],
           "shed_batches": snap["shed_batches"]}
    models = sampler.model_recs("closed", wall)
    if models:
        rec["models"] = models
    return rec


def run_open_loop(batcher, images, rate_hz: float, duration_s: float,
                  timeout_s: float = 10.0, mix=None,
                  images_by_model=None) -> dict:
    """Fixed-rate arrivals: one submitter paces requests at ``rate_hz``;
    a resolver pool materializes results. Rejections are counted and
    DROPPED (open-loop semantics — the arrival process never waits).
    With ``mix`` each arrival samples its model by weight."""
    import queue as _queue

    from deeplearning_tpu.serve import DeadlineExceeded, Rejected

    handles: "_queue.Queue" = _queue.Queue()
    lock = threading.Lock()
    state = {"submitted": 0, "completed": 0, "rejected": 0,
             "timed_out": 0}
    lats = []
    sampler = _MixSampler(mix, images_by_model, images)
    timeline = Timeline()
    done = threading.Event()

    def resolver():
        while True:
            item = handles.get()
            if item is None:
                return
            t0, alias, handle = item
            try:
                handle.result(timeout=timeout_s)
            except (DeadlineExceeded, Exception):  # noqa: BLE001
                with lock:
                    state["timed_out"] += 1
                    if alias is not None:
                        sampler.tally(alias, "timed_out")
                timeline.note("timed_out")
                continue
            lat = time.perf_counter() - t0
            with lock:
                state["completed"] += 1
                lats.append(lat)
                if alias is not None:
                    sampler.tally(alias, "completed", lat)
            timeline.note("completed", lat)

    from deeplearning_tpu.obs import threads as obs_threads
    pool = [obs_threads.spawn(resolver, daemon=True,
                              name=f"loadgen-resolver-{i}")
            for i in range(8)]
    period = 1.0 / rate_hz
    rng = np.random.default_rng(0)
    t_end = time.perf_counter() + duration_s
    next_t = time.perf_counter()
    while time.perf_counter() < t_end:
        now = time.perf_counter()
        if now < next_t:
            time.sleep(next_t - now)
        next_t += period
        alias, img_pool = sampler.pick(rng)
        img = img_pool[int(rng.integers(len(img_pool)))]
        t0 = time.perf_counter()
        try:
            handle = batcher.submit(img, model=alias)
        except Rejected:
            with lock:
                state["rejected"] += 1
                if alias is not None:
                    sampler.tally(alias, "rejected")
            timeline.note("rejected")
            continue
        with lock:
            state["submitted"] += 1
        timeline.note("submitted")
        handles.put((t0, alias, handle))
    for _ in pool:
        handles.put(None)
    for t in pool:
        t.join(timeout=timeout_s)
    done.set()
    snap = batcher.telemetry.snapshot()
    rec = {"mode": "open", "rate_hz": rate_hz, **state,
           "req_per_s": round(state["completed"] / duration_s, 1),
           **_percentiles_ms(lats),
           "batch_occupancy": snap["batch_occupancy"],
           "queue_depth_mean": snap["queue_depth_mean"],
           "shed_batches": snap["shed_batches"],
           "timeline": timeline.rows()}
    models = sampler.model_recs("open", duration_s)
    if models:
        rec["models"] = models
    return rec


def run_open_loop_http(router, images, rate_hz: float,
                       duration_s: float, timeout_s: float = 10.0,
                       senders: int = 16) -> dict:
    """Open-loop arrivals POSTed to a replica fleet through a
    :class:`~deeplearning_tpu.fleet.FleetRouter` — the drive side of
    the drain-and-requeue choreography. Latency is arrival→response
    (loadgen queueing included: a stalled fleet shows up as p99, not as
    a quietly slower arrival process). 2xx counts as completed, a
    429/503 that survived failover as rejected (an all-shed fleet's
    smallest retry-after hint is surfaced), an empty rotation as
    no_route, connection errors and deadline misses as timed out. Each
    request carries the remaining deadline (``X-Deadline-Ms``); the
    per-second timeline records the router's retry/hedge/deadline-miss
    counts next to the QPS split, and the record embeds
    ``router.resilience_stats()``."""
    import io
    import queue as _queue

    timeline = Timeline()
    jobs: "_queue.Queue" = _queue.Queue()
    lock = threading.Lock()
    state = {"submitted": 0, "completed": 0, "rejected": 0,
             "timed_out": 0, "no_route": 0, "retries": 0, "hedged": 0,
             "deadline_miss": 0}
    hints = []
    lats = []

    def sender():
        while True:
            item = jobs.get()
            if item is None:
                return
            t0, body = item
            code, payload, _url, meta = router.post_ex(
                "/predict", body,
                headers={"Content-Type": "application/octet-stream"},
                deadline_s=timeout_s)
            lat = time.perf_counter() - t0
            retries = int(meta.get("retries", 0))
            with lock:
                state["retries"] += retries
                state["hedged"] += int(bool(meta.get("hedged")))
                state["deadline_miss"] += int(
                    bool(meta.get("deadline_miss")))
                if meta.get("retry_after_s") is not None:
                    hints.append(meta["retry_after_s"])
            if retries:
                timeline.note("retries", n=retries)
            if meta.get("hedged"):
                timeline.note("hedged")
            if meta.get("deadline_miss"):
                timeline.note("deadline_miss")
            if 200 <= code < 300:
                with lock:
                    state["completed"] += 1
                    lats.append(lat)
                timeline.note("completed", lat)
            elif meta.get("no_route"):
                with lock:
                    state["no_route"] += 1
                timeline.note("no_route")
            elif code in (429, 503):
                with lock:
                    state["rejected"] += 1
                timeline.note("rejected")
            else:
                with lock:
                    state["timed_out"] += 1
                timeline.note("timed_out")

    from deeplearning_tpu.obs import threads as obs_threads
    pool = [obs_threads.spawn(sender, daemon=True,
                              name=f"loadgen-http-{i}")
            for i in range(senders)]
    bodies = []
    for img in images[:16]:
        buf = io.BytesIO()
        np.save(buf, img)
        bodies.append(buf.getvalue())
    period = 1.0 / rate_hz
    t_end = time.perf_counter() + duration_s
    next_t = time.perf_counter()
    i = 0
    while time.perf_counter() < t_end:
        now = time.perf_counter()
        if now < next_t:
            time.sleep(next_t - now)
        next_t += period
        with lock:
            state["submitted"] += 1
        timeline.note("submitted")
        jobs.put((time.perf_counter(), bodies[i % len(bodies)]))
        i += 1
    for _ in pool:
        jobs.put(None)
    for t in pool:
        t.join(timeout=timeout_s)
    rec = {"mode": "open_http", "rate_hz": rate_hz, **state,
           "req_per_s": round(state["completed"] / duration_s, 1),
           **_percentiles_ms(lats),
           "failovers": router.failovers,
           "resilience": router.resilience_stats(),
           "timeline": timeline.rows()}
    if hints:
        rec["retry_after_hint_s"] = min(hints)
    return rec


def append_serve_row(results_path: str, rec: dict, **extra) -> None:
    """One serve row in the shared op-row schema (``"op" in rec`` splits
    op rows from step rows for every mfu_results.jsonl consumer)."""
    from bench_util import append_op_result
    tag = rec.get("concurrency", rec.get("rate_hz", 1))
    append_op_result(
        results_path, f"serve_{rec['mode']}", n=int(tag),
        ms=rec.get("p50_ms", 0.0), req_per_s=rec.get("req_per_s", 0.0),
        p99_ms=rec.get("p99_ms", 0.0), completed=rec.get("completed", 0),
        rejected=rec.get("rejected", 0),
        batch_occupancy=rec.get("batch_occupancy", 0.0), **extra)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    # default: a model whose single-request cost is dispatch-dominated,
    # so the compare mode isolates the batching win (a conv model's CPU
    # compute scales linearly with batch and hides the amortization)
    ap.add_argument("--model", default="mnist_fcn")
    ap.add_argument("--num-classes", type=int, default=10)
    ap.add_argument("--ckpt", default=None)
    ap.add_argument("--size", type=int, default=28)
    ap.add_argument("--buckets", default="1,8,64",
                    help="comma-separated batch buckets")
    ap.add_argument("--mode", default="compare",
                    choices=["closed", "open", "sequential", "compare"])
    ap.add_argument("--concurrency", type=int, default=64)
    ap.add_argument("--n", type=int, default=512,
                    help="total requests (closed/sequential)")
    ap.add_argument("--rate", type=float, default=200.0,
                    help="open-loop arrivals per second")
    ap.add_argument("--duration", type=float, default=5.0,
                    help="open-loop duration seconds")
    ap.add_argument("--max-wait-ms", type=float, default=5.0)
    ap.add_argument("--max-queue", type=int, default=256)
    ap.add_argument("--timeout-s", type=float, default=None,
                    help="per-request deadline")
    ap.add_argument("--results", default=None,
                    help="append serve rows to this jsonl "
                         "(default: tools/mfu_results.jsonl; 'none' off)")
    ap.add_argument("--mix", default=None,
                    help='mixed zoo traffic, e.g. "a=0.7,b=0.3": each '
                         "request samples its model by weight "
                         "(closed/open modes; implies a ModelZoo)")
    ap.add_argument("--zoo", default=None,
                    help="tenant specs for --mix aliases: JSON (or "
                         "@file.json) alias -> {model, num_classes, "
                         "image_size, buckets, weight_quant, ...}; "
                         "default: each alias IS its architecture name "
                         "with the CLI's --num-classes/--size")
    ap.add_argument("--fleet-urls", default=None,
                    help="open-loop over HTTP instead of in-process: "
                         "comma-separated replica base URLs routed via "
                         "FleetRouter (round-robin + failover)")
    ap.add_argument("--fleet-dir", default=None,
                    help="like --fleet-urls but discover live replica "
                         "endpoints from this controller run dir on "
                         "every health refresh (scale-ups join, "
                         "drained replicas leave)")
    args = ap.parse_args(argv)
    if args.mix and args.mode not in ("closed", "open"):
        ap.error("--mix needs --mode closed or open")
    if (args.fleet_urls or args.fleet_dir) and args.mode != "open":
        ap.error("--fleet-urls/--fleet-dir need --mode open")

    if args.fleet_urls or args.fleet_dir:
        from deeplearning_tpu.fleet import FleetRouter
        refresh = None
        urls = []
        if args.fleet_dir:
            from deeplearning_tpu.obs.fleet import discover_endpoints

            def refresh(_dir=args.fleet_dir):
                return discover_endpoints(_dir, live_only=True)
            urls = refresh()
        if args.fleet_urls:
            urls = [u.strip() for u in args.fleet_urls.split(",")
                    if u.strip()]
            refresh = None
        router = FleetRouter(urls, refresh_fn=refresh,
                             timeout_s=args.timeout_s or 10.0)
        rec = run_open_loop_http(
            router, make_images(64, args.size), args.rate,
            args.duration, timeout_s=args.timeout_s or 10.0)
        print(json.dumps(rec), flush=True)
        if (args.results or "").lower() != "none":
            append_serve_row(args.results or os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "mfu_results.jsonl"), rec, model=args.model)
        return 0

    from deeplearning_tpu.serve import (InferenceEngine, MicroBatcher,
                                        ModelZoo)

    buckets = tuple(int(b) for b in args.buckets.split(","))
    results_path = args.results or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "mfu_results.jsonl")
    write_rows = (args.results or "").lower() != "none"

    mix = zoo = None
    images_by_model = {}
    if args.mix:
        mix = parse_mix(args.mix)
        if args.zoo:
            raw = args.zoo
            if raw.startswith("@"):
                with open(raw[1:]) as f:
                    spec = json.load(f)
            else:
                spec = json.loads(raw)
        else:
            spec = {alias: {} for alias in mix}
        zoo = ModelZoo()
        for alias in mix:
            row = dict(spec.get(alias, {}))
            model_name = row.pop("model", alias)
            b = row.pop("buckets", None)
            row["batch_buckets"] = (tuple(int(x) for x in b)
                                    if b else buckets)
            row.setdefault("num_classes", args.num_classes)
            row.setdefault("image_size", args.size)
            zoo.register(
                alias, model_name,
                weight_quant=row.pop("weight_quant", "fp32"),
                max_queue=int(row.pop("max_queue", args.max_queue)),
                default_timeout_s=row.pop("timeout_s", args.timeout_s),
                **row)
        for alias in mix:       # measure serving, not cold loads
            if zoo.load(alias, wait=True) != "warm":
                ap.error(
                    f"tenant {alias!r} failed to load: "
                    f"{zoo.load_errors.get(alias, 'unknown')} — with no "
                    "--zoo spec each --mix alias must BE an architecture "
                    'name (or map it: --zoo \'{"%s": {"model": ...}}\')'
                    % alias)
            images_by_model[alias] = make_images(
                max(buckets[-1], 64), zoo.image_size(alias))
        images = next(iter(images_by_model.values()))
        engine = None
    else:
        engine = InferenceEngine(
            args.model, num_classes=args.num_classes, ckpt=args.ckpt,
            image_size=args.size, batch_buckets=buckets)
        images = make_images(max(buckets[-1], 64), args.size)

    def report(rec, **extra):
        print(json.dumps(rec), flush=True)
        if not write_rows:
            return
        models = rec.get("models")
        if models:
            # one op row per tenant, so the per-model latency
            # trajectories land in mfu_results.jsonl individually
            for alias, sub in sorted(models.items()):
                append_serve_row(results_path, sub, model=alias,
                                 mix_weight=sub["mix_weight"], **extra)
        else:
            append_serve_row(results_path, rec, model=args.model,
                             **extra)

    def make_batcher():
        kwargs = dict(max_wait_ms=args.max_wait_ms,
                      max_queue=args.max_queue,
                      default_timeout_s=args.timeout_s)
        if zoo is not None:
            return MicroBatcher(zoo=zoo, **kwargs)
        return MicroBatcher(engine, **kwargs)

    recs = []
    if args.mode in ("sequential", "compare"):
        rec = run_sequential(engine, images, args.n)
        report(rec)
        recs.append(rec)
    if args.mode in ("closed", "compare"):
        with make_batcher() as mb:
            rec = run_closed_loop(mb, images, args.concurrency, args.n,
                                  mix=mix,
                                  images_by_model=images_by_model)
        report(rec)
        recs.append(rec)
    if args.mode == "open":
        with make_batcher() as mb:
            rec = run_open_loop(mb, images, args.rate, args.duration,
                                mix=mix,
                                images_by_model=images_by_model)
        report(rec)
        recs.append(rec)
    if args.mode == "compare" and len(recs) == 2:
        speedup = recs[1]["req_per_s"] / max(recs[0]["req_per_s"], 1e-9)
        print(json.dumps({"mode": "compare",
                          "speedup_vs_sequential": round(speedup, 2)}),
              flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
