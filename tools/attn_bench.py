#!/usr/bin/env python
"""Attention kernel microbenchmark on the real chip.

Times naive XLA attention vs the Pallas flash kernels (per-head and
head-batched) at the zoo's production shapes — ViT-B/16 (N=197), MAE
(N=50 visible? no: encoder N=50, decoder N=197), Swin windows, and
long-context sizes — fwd and fwd+bwd. Prints a markdown table; the
"winner" column drives the model attn_fn defaults.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


from bench_util import bench


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", default="fwd", choices=["fwd", "bwd"])
    ap.add_argument("--shapes", default="vit")
    args = ap.parse_args()

    from deeplearning_tpu.models.classification.vit import (
        dot_product_attention)
    from deeplearning_tpu.ops.pallas.flash_attention import (
        flash_attention, flash_attention_hb)

    SHAPES = {  # (B, H, N, D) at training batch sizes
        "vit":  [(128, 12, 197, 64),    # ViT-B/16 batch 128
                 (64, 16, 197, 64),     # ViT-L/16
                 (128, 16, 50, 80)],    # MAE encoder (25% visible)
        "long": [(8, 12, 1024, 64), (4, 12, 2048, 64), (2, 12, 4096, 64),
                 (1, 12, 8192, 64)],
    }
    shapes = SHAPES[args.shapes]

    def naive_bhnd(q, k, v):
        # (B,H,N,D): reuse the models' naive path via transpose
        t = lambda x: x.transpose(0, 2, 1, 3)
        return t(dot_product_attention(t(q), t(k), t(v)))

    def jax_flash(q, k, v):
        # the JAX-team-tuned TPU kernel (public jax.experimental) — the
        # external reference our kernels are judged against
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention as jf)
        return jf(q, k, v, sm_scale=q.shape[-1] ** -0.5)

    variants = {
        "naive": naive_bhnd,
        "flash": flash_attention,
        "flash_hb": flash_attention_hb,
        "jax_flash": jax_flash,
    }

    print(f"| shape (B,H,N,D) | mode | " + " | ".join(variants) +
          " | winner |")
    print("|---" * (len(variants) + 3) + "|")
    for shape in shapes:
        rng = np.random.default_rng(0)
        q, k, v = (jnp.asarray(rng.normal(size=shape), jnp.bfloat16)
                   for _ in range(3))
        row = {}
        for name, fn in variants.items():
            if args.mode == "fwd":
                f = jax.jit(fn)
            else:
                f = jax.jit(jax.grad(
                    lambda q, k, v: fn(q, k, v).astype(jnp.float32).sum(),
                    argnums=(0, 1, 2)))
            try:
                dt = bench(lambda *a: f(*a), (q, k, v))
                row[name] = dt * 1e3
            except Exception as e:                 # noqa: BLE001
                print(f"  {name} failed on {shape}: {e}", file=sys.stderr)
                row[name] = float("nan")
        ok = [(v, k) for k, v in row.items() if not np.isnan(v)]
        best = min(ok)[1] if ok else "-"
        cells = " | ".join(f"{row[k]:.3f}ms" for k in variants)
        print(f"| {shape} | {args.mode} | {cells} | {best} |", flush=True)


if __name__ == "__main__":
    main()
