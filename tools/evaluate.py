#!/usr/bin/env python
"""Standalone evaluation CLI — the per-project val.py / test.py successor.

  python tools/evaluate.py --model resnet18 --num-classes 10 \\
      --npz data.npz [--ckpt runs/x/ckpt/best] [--batch 64]

Runs the eval step over a dataset and prints top-1/top-5 plus per-class
accuracy from the confusion matrix (the reference's test.py writes a
results txt; here metrics go to stdout and optionally a json file).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax

if os.environ.get("DLTPU_PLATFORM"):
    jax.config.update("jax_platforms", os.environ["DLTPU_PLATFORM"])

import jax.numpy as jnp
import numpy as np


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", required=True)
    ap.add_argument("--num-classes", type=int, default=10)
    ap.add_argument("--ckpt", default=None)
    ap.add_argument("--npz", default=None,
                    help="npz with model-ready 'images' and 'labels'")
    ap.add_argument("--folder", default=None,
                    help="ImageFolder root (real JPEG eval, val split)")
    ap.add_argument("--image-size", type=int, default=64)
    ap.add_argument("--val-rate", type=float, default=0.2)
    ap.add_argument("--seed", type=int, default=0,
                    help="split seed — MUST match train.seed for the "
                         "--folder val split to be truly held out")
    ap.add_argument("--workers", type=int, default=8)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--out-json", default=None)
    ap.add_argument("--tta", action="store_true",
                    help="average probabilities over a horizontal flip "
                         "(yolov5 val --augment analog)")
    args = ap.parse_args(argv)
    if not args.npz and not args.folder:
        ap.error("one of --npz / --folder is required")

    from deeplearning_tpu.core.registry import MODELS
    from deeplearning_tpu.evaluation.metrics import (confusion_matrix,
                                                     miou_from_confusion,
                                                     topk_correct)

    if args.npz:
        blob = np.load(args.npz)
        images, labels = blob["images"], blob["labels"]

        def batches():
            bs = max(min(args.batch, len(images)), 1)
            n = (len(images) // bs) * bs
            for start in range(0, n, bs):
                yield (images[start:start + bs], labels[start:start + bs])
        sample = images[:1]
    else:
        # reuse the training-side loader stack (worker-pool decode,
        # clamped val batch) with the SAME split seed as training
        from deeplearning_tpu.data.build import (LoaderConfig,
                                                 build_classification_loaders)
        lcfg = LoaderConfig(global_batch=args.batch,
                            image_size=args.image_size,
                            val_rate=args.val_rate, seed=args.seed,
                            num_workers=args.workers)
        _, val_loader, class_to_idx = build_classification_loaders(
            args.folder, lcfg)
        if len(class_to_idx) != args.num_classes:
            ap.error(f"--num-classes {args.num_classes} but folder has "
                     f"{len(class_to_idx)} classes")
        if len(val_loader) == 0:
            raise SystemExit(
                "empty val split — raise --val-rate or add images")

        def batches():
            for batch in val_loader:
                yield (batch["image"], batch["label"])
        # init shape is fully determined by --image-size; no need to
        # decode a real batch just for model.init
        sample = np.zeros((1, args.image_size, args.image_size, 3),
                          np.float32)
    model = MODELS.build(args.model, num_classes=args.num_classes)
    variables = model.init(jax.random.key(0),
                           jnp.asarray(sample), train=False)
    if args.ckpt:
        from deeplearning_tpu.core.checkpoint import restore_variables
        variables = restore_variables(args.ckpt, variables)

    @jax.jit
    def eval_batch(imgs, labs):
        if args.tta:
            from deeplearning_tpu.ops.tta import classify_tta
            probs = classify_tta(
                lambda x: model.apply(variables, x, train=False), imgs)
            scores = jnp.log(jnp.maximum(probs, 1e-30))  # rank-equivalent
        else:
            scores = model.apply(variables, imgs, train=False)
        counts = topk_correct(scores, labs)
        cm = confusion_matrix(jnp.argmax(scores, -1), labs,
                              args.num_classes)
        return counts, cm

    totals = {"top1": 0, "top5": 0, "count": 0}
    cm_total = np.zeros((args.num_classes, args.num_classes), np.int64)
    for imgs, labs in batches():
        counts, cm = eval_batch(jnp.asarray(imgs), jnp.asarray(labs))
        for k in totals:
            totals[k] += int(counts[k])
        cm_total += np.asarray(cm)
    if totals["count"] == 0:
        raise SystemExit("no samples evaluated (empty dataset?)")

    count = totals["count"]
    stats = miou_from_confusion(cm_total)
    results = {
        "top1": totals["top1"] / count,
        "top5": totals["top5"] / count,
        "count": count,
        "per_class_acc": [round(float(a), 4)
                          for a in stats["class_acc"]],
    }
    print(json.dumps({k: (round(v, 4) if isinstance(v, float) else v)
                      for k, v in results.items()}))
    if args.out_json:
        with open(args.out_json, "w") as f:
            json.dump(results, f, indent=2)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
