#!/usr/bin/env python
"""Standalone evaluation CLI — the per-project val.py / test.py successor.

  python tools/evaluate.py --model resnet18 --num-classes 10 \\
      --npz data.npz [--ckpt runs/x/ckpt/best] [--batch 64]

Runs the eval step over a dataset and prints top-1/top-5 plus per-class
accuracy from the confusion matrix (the reference's test.py writes a
results txt; here metrics go to stdout and optionally a json file).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax

if os.environ.get("DLTPU_PLATFORM"):
    jax.config.update("jax_platforms", os.environ["DLTPU_PLATFORM"])

import jax.numpy as jnp
import numpy as np


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", required=True)
    ap.add_argument("--num-classes", type=int, default=10)
    ap.add_argument("--ckpt", default=None)
    ap.add_argument("--npz", required=True,
                    help="npz with model-ready 'images' and 'labels'")
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--out-json", default=None)
    args = ap.parse_args(argv)

    from deeplearning_tpu.core.checkpoint import load_pytree
    from deeplearning_tpu.core.registry import MODELS
    from deeplearning_tpu.evaluation.metrics import (confusion_matrix,
                                                     miou_from_confusion,
                                                     topk_correct)

    blob = np.load(args.npz)
    images, labels = blob["images"], blob["labels"]
    model = MODELS.build(args.model, num_classes=args.num_classes)
    variables = model.init(jax.random.key(0),
                           jnp.asarray(images[:1]), train=False)
    if args.ckpt:
        restored = load_pytree(args.ckpt)
        params = restored.get("params", restored) \
            if isinstance(restored, dict) else restored
        variables = {**variables, "params": params}

    @jax.jit
    def eval_batch(imgs, labs):
        logits = model.apply(variables, imgs, train=False)
        counts = topk_correct(logits, labs)
        cm = confusion_matrix(jnp.argmax(logits, -1), labs,
                              args.num_classes)
        return counts, cm

    totals = {"top1": 0, "top5": 0, "count": 0}
    cm_total = np.zeros((args.num_classes, args.num_classes), np.int64)
    n = (len(images) // args.batch) * args.batch
    for start in range(0, n, args.batch):
        counts, cm = eval_batch(
            jnp.asarray(images[start:start + args.batch]),
            jnp.asarray(labels[start:start + args.batch]))
        for k in totals:
            totals[k] += int(counts[k])
        cm_total += np.asarray(cm)

    count = max(totals["count"], 1)
    stats = miou_from_confusion(cm_total)
    results = {
        "top1": totals["top1"] / count,
        "top5": totals["top5"] / count,
        "count": count,
        "per_class_acc": [round(float(a), 4)
                          for a in stats["class_acc"]],
    }
    print(json.dumps({k: (round(v, 4) if isinstance(v, float) else v)
                      for k, v in results.items()}))
    if args.out_json:
        with open(args.out_json, "w") as f:
            json.dump(results, f, indent=2)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
