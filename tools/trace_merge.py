#!/usr/bin/env python
"""Merge per-replica trace.json files into one fleet timeline.

Every replica's span tracer (``obs/spans.py``) dumps Chrome trace-event
JSON with ABSOLUTE wall-clock microsecond timestamps (epoch-anchored on
purpose), so merging is pure bookkeeping: no time re-basing, just a pid
remap so N processes land on N distinct rows. Each input file becomes
one process row (pid 1..N) named ``replica-<id>`` (from the identity
``DLTPU_REPLICA`` stamped into ``otherData``) or the file's parent
directory name, ordered by replica id. The output loads directly in
Perfetto / chrome://tracing — one timeline across the fleet.

Usage:
  # explicit files
  python tools/trace_merge.py --out fleet_trace.json \
      runs/fleet/replica-0/trace.json runs/fleet/replica-1/trace.json

  # or a fleet workdir (finds trace.json + replica-*/trace.json)
  python tools/trace_merge.py --out fleet_trace.json runs/fleet

  python tools/trace_merge.py --check   # jax-free self-test

Stdlib-only: never imports jax or the package, so it runs on a machine
that only has the trace files.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple


def load_trace(path: str) -> Dict[str, Any]:
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        raise ValueError(f"{path}: not a Chrome trace JSON object "
                         "(no traceEvents)")
    return doc


def replica_label(doc: Dict[str, Any], path: str) -> Tuple[int, str]:
    """(sort key, row name) for one input trace: the stamped replica id
    wins; otherwise the parent dir name with an input-order key."""
    other = doc.get("otherData") or {}
    replica = other.get("replica")
    if replica is not None:
        try:
            return int(replica), f"replica-{replica}"
        except (TypeError, ValueError):
            return 1 << 30, f"replica-{replica}"
    parent = os.path.basename(os.path.dirname(os.path.abspath(path)))
    return 1 << 30, parent or os.path.basename(path)


def merge_traces(docs: List[Dict[str, Any]],
                 labels: Optional[List[str]] = None) -> Dict[str, Any]:
    """Pure merge: input doc i becomes process row pid=i+1. Original
    pids (the replicas' real os pids, which can collide across hosts or
    restarts) are discarded; tids pass through untouched since they only
    need to be unique within a process row."""
    if labels is None:
        labels = [f"replica-{i}" for i in range(len(docs))]
    events: List[Dict[str, Any]] = []
    sources = []
    for i, (doc, label) in enumerate(zip(docs, labels)):
        pid = i + 1
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "args": {"name": label}})
        events.append({"ph": "M", "name": "process_sort_index",
                       "pid": pid, "tid": 0, "args": {"sort_index": i}})
        for ev in doc.get("traceEvents", []):
            # the per-replica process_name row metadata is superseded by
            # the merged row name above
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                continue
            out = dict(ev)
            out["pid"] = pid
            events.append(out)
        other = doc.get("otherData") or {}
        sources.append({"pid": pid, "label": label,
                        **{k: other[k] for k in
                           ("run_id", "replica", "recorded", "dropped")
                           if k in other}})
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"merged_from": len(docs), "sources": sources}}


def discover_traces(run_dir: str) -> List[str]:
    """trace.json files under a fleet workdir: the dir itself plus each
    ``replica-*/`` child, sorted by replica index."""
    found: List[str] = []
    direct = os.path.join(run_dir, "trace.json")
    if os.path.isfile(direct):
        found.append(direct)

    def _key(name: str):
        tail = name.rsplit("-", 1)[-1]
        return (0, int(tail)) if tail.isdigit() else (1, 0)

    try:
        children = sorted(os.listdir(run_dir), key=_key)
    except OSError:
        return found
    for name in children:
        p = os.path.join(run_dir, name, "trace.json")
        if os.path.isfile(p):
            found.append(p)
    return found


def merge_files(paths: List[str]) -> Dict[str, Any]:
    loaded = [(load_trace(p), p) for p in paths]
    ordered = sorted(loaded,
                     key=lambda dp: replica_label(dp[0], dp[1])[0])
    docs = [doc for doc, _ in ordered]
    labels = [replica_label(doc, p)[1] for doc, p in ordered]
    return merge_traces(docs, labels)


def _check() -> int:
    """Self-test on synthetic per-replica traces (the shape spans.dump
    writes), asserting the acceptance contract: valid Chrome trace JSON
    with one distinct process row per input."""
    def fake(replica: int, pid: int) -> Dict[str, Any]:
        base = 1_700_000_000_000_000.0 + replica * 10.0
        return {
            "traceEvents": [
                {"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
                 "args": {"name": f"replica-{replica}"}},
                {"ph": "M", "name": "thread_name", "pid": pid, "tid": 7,
                 "args": {"name": "serve-dispatch"}},
                {"ph": "X", "name": "dispatch", "pid": pid, "tid": 7,
                 "ts": base, "dur": 1500.0},
                {"ph": "i", "name": "marker", "pid": pid, "tid": 7,
                 "ts": base + 2000.0, "s": "t"},
            ],
            "displayTimeUnit": "ms",
            "otherData": {"recorded": 2, "dropped": 0,
                          "run_id": "run-check", "replica": str(replica)},
        }

    # colliding original pids on purpose — the remap must not care
    merged = merge_traces([fake(1, 4242), fake(0, 4242)],
                          labels=None)
    # order-by-replica goes through merge_files; here exercise the raw
    # merge plus a round-trip through real files
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        paths = []
        for r in (1, 0):
            d = os.path.join(td, f"replica-{r}")
            os.makedirs(d)
            p = os.path.join(d, "trace.json")
            with open(p, "w") as f:
                json.dump(fake(r, 4242), f)
            paths.append(p)
        disc = discover_traces(td)
        assert [os.path.basename(os.path.dirname(p)) for p in disc] == \
            ["replica-0", "replica-1"], disc
        merged = merge_files(disc)
    out = json.loads(json.dumps(merged))     # valid JSON round-trip
    events = out["traceEvents"]
    rows = {ev["pid"]: ev["args"]["name"] for ev in events
            if ev.get("ph") == "M" and ev.get("name") == "process_name"}
    assert rows == {1: "replica-0", 2: "replica-1"}, rows
    pids = {ev["pid"] for ev in events if ev.get("ph") == "X"}
    assert pids == {1, 2}, pids
    # replica-0 sorted first despite being written second
    sort_idx = {ev["pid"]: ev["args"]["sort_index"] for ev in events
                if ev.get("name") == "process_sort_index"}
    assert sort_idx == {1: 0, 2: 1}, sort_idx
    for ev in events:
        if ev.get("ph") == "X":
            assert "ts" in ev and "dur" in ev, ev
    assert out["otherData"]["merged_from"] == 2
    print("trace_merge --check: OK (2 process rows, valid trace JSON)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("inputs", nargs="*",
                    help="trace.json files, or one fleet workdir")
    ap.add_argument("--out", default="fleet_trace.json",
                    help="merged output path (- for stdout)")
    ap.add_argument("--check", action="store_true",
                    help="run the jax-free self-test and exit")
    args = ap.parse_args(argv)
    if args.check:
        return _check()
    if not args.inputs:
        ap.error("no inputs (trace.json files or a fleet workdir)")

    paths: List[str] = []
    for inp in args.inputs:
        if os.path.isdir(inp):
            found = discover_traces(inp)
            if not found:
                print(f"trace_merge: no trace.json under {inp}",
                      file=sys.stderr)
            paths.extend(found)
        else:
            paths.append(inp)
    if not paths:
        print("trace_merge: nothing to merge", file=sys.stderr)
        return 1
    merged = merge_files(paths)
    n_rows = merged["otherData"]["merged_from"]
    if args.out == "-":
        json.dump(merged, sys.stdout)
        print(file=sys.stdout)
    else:
        with open(args.out, "w") as f:
            json.dump(merged, f)
        print(f"trace_merge: {n_rows} replica rows, "
              f"{len(merged['traceEvents'])} events -> {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
