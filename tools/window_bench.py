#!/usr/bin/env python
"""Swin window-attention microbenchmark on the real chip.

Times the lax reference path vs the fused Pallas window kernel at
Swin-T/B production shapes (the unit_test.py speed-comparison analog for
classification/swin_transformer/kernels/window_process). Also times a
full swin_tiny forward with use_pallas on/off. Appends JSON lines to
tools/window_results.jsonl; run as a single completing script (no
kill-capable timeout — tunnel rule)."""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


from bench_util import bench


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--wpb", type=int, default=8, help="windows per block")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes, kernel-only (CPU interpret check)")
    args = ap.parse_args()

    from deeplearning_tpu.ops.pallas.window_attention import window_attention
    from deeplearning_tpu.ops.window_utils import windowed_attention_reference

    results = []
    # (BW, N, heads, d): Swin-T stage1 (56x56/7 -> 64 win) batch 128;
    # Swin-B stage3 shapes; window 7 -> N=49
    SHAPES = [
        (128 * 64, 49, 3, 32),    # swin-T stage 1, batch 128
        (128 * 16, 49, 6, 32),    # stage 2
        (128 * 4, 49, 12, 32),    # stage 3
        (64 * 64, 49, 4, 32),     # swin-B stage 1, batch 64
        (64 * 4, 49, 16, 32),     # swin-B stage 3
    ]
    if args.smoke:
        SHAPES = [(16, 49, 3, 32)]
    rng = np.random.default_rng(0)
    for bw, n, heads, d in SHAPES:
        qkv = jnp.asarray(rng.normal(size=(bw, n, 3, heads, d)),
                          jnp.bfloat16)
        bias = jnp.asarray(rng.normal(size=(heads, n, n)), jnp.float32)
        f_ref = jax.jit(lambda q, b: windowed_attention_reference(q, b, None))
        f_pal = jax.jit(lambda q, b: window_attention(
            q, b, windows_per_block=args.wpb))
        t_ref = bench(f_ref, (qkv, bias))
        t_pal = bench(f_pal, (qkv, bias))
        rec = {"shape": [bw, n, heads, d], "lax_ms": round(t_ref * 1e3, 3),
               "pallas_ms": round(t_pal * 1e3, 3),
               "speedup": round(t_ref / t_pal, 3), "wpb": args.wpb}
        results.append(rec)
        print(json.dumps(rec), flush=True)

    if args.smoke:
        return
    # full model: swin_tiny forward, pallas on/off
    from deeplearning_tpu.core.registry import MODELS
    x = jnp.asarray(rng.normal(size=(64, 224, 224, 3)), jnp.float32)
    for use_pallas in (False, True):
        model = MODELS.build("swin_tiny_patch4_window7_224",
                             num_classes=1000, use_pallas=use_pallas)
        params = model.init(jax.random.key(0), jnp.zeros((1, 224, 224, 3)),
                            train=False)["params"]
        f = jax.jit(lambda p, x: model.apply({"params": p}, x, train=False))
        t = bench(f, (params, x), n=10)
        rec = {"model": "swin_tiny", "use_pallas": use_pallas,
               "fwd_ms": round(t * 1e3, 2),
               "img_per_s": round(64 / t, 1)}
        results.append(rec)
        print(json.dumps(rec), flush=True)

    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "window_results.jsonl"), "a") as f:
        for rec in results:
            f.write(json.dumps(rec) + "\n")


if __name__ == "__main__":
    main()
