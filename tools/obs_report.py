#!/usr/bin/env python
"""Render one run directory's observability artifacts into a report.

  python tools/obs_report.py runs/exp1             # text report
  python tools/obs_report.py runs/exp1 --json      # machine-readable
  python tools/obs_report.py runs/fleet --fleet    # fleet rollup view
  python tools/obs_report.py --check               # self-test (tier-1)

Consumes what the Trainer writes per run — ``trace.json`` (the span
timeline), ``flightrec.json`` (crash flight record, if the run died),
``metrics.jsonl`` (the jsonl logger backend) — and answers the question
every on-chip calibration item starts from: *where did the wall time
go?* Phases (data_wait / dispatch / metrics_flush / eval / checkpoint)
are summed per span name across threads, compiles get their own table
(seconds, FLOPs, peak HBM, cache verdict from the ``compile/*`` span
args), and a flight record is summarized instead of pasted.

``--check`` builds a synthetic run dir through the REAL SpanTracer +
FlightRecorder APIs, renders it, and asserts on the output — a
dependency-free self-test the tier-1 suite can run.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# the phase spans the Trainer emits on its consumer thread, in hot-loop
# order; everything else in the trace lands under "other spans"
PHASES = ("data_wait", "dispatch", "metrics_flush", "eval", "checkpoint")


def load_trace(run_dir: str) -> List[Dict[str, Any]]:
    path = os.path.join(run_dir, "trace.json")
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return json.load(f).get("traceEvents", [])


def load_flight(run_dir: str) -> Optional[Dict[str, Any]]:
    path = os.path.join(run_dir, "flightrec.json")
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def load_supervisor(run_dir: str) -> Optional[Dict[str, Any]]:
    """The run supervisor's own flight record (launch / backoff /
    wedge-kill decisions) — written by ``tools/supervise.py``."""
    path = os.path.join(run_dir, "flightrec_supervisor.json")
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def load_controller(run_dir: str) -> Optional[Dict[str, Any]]:
    """The fleet controller's decision log (scale / drain / requeue /
    preemption verdicts) — written by ``deeplearning_tpu/fleet`` after
    every actuation."""
    path = os.path.join(run_dir, "flightrec_controller.json")
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            doc = json.load(f)
    except ValueError:
        return None
    return doc if isinstance(doc, dict) else None


def controller_summary(doc: Optional[Dict[str, Any]]
                       ) -> Optional[Dict[str, Any]]:
    """Fleet-controller posture: every actuation class counted, with
    the WHY kept (scale reasons, preemption verdicts) — the section the
    choreography test asserts its decisions showed up in. Pure."""
    if doc is None:
        return None
    ev = doc.get("events", [])

    def of(kind: str) -> List[Dict[str, Any]]:
        return [e for e in ev if e.get("kind") == kind]

    scales = of("fleet_scale")
    out: Dict[str, Any] = {
        "scale_ups": sum(1 for e in scales
                         if e.get("direction") == "up"),
        "scale_downs": sum(1 for e in scales
                           if e.get("direction") == "down"),
        "scale_reasons": [str(e.get("reason")) for e in scales],
        "drains": len(of("fleet_drain")),
        "requeues": len(of("fleet_requeue")),
        "stops": len(of("fleet_stop")),
        "preemptions": len(of("preempt_capacity")),
        "preempt_verdicts": [str(e.get("verdict"))
                             for e in of("preempt_capacity")],
        "tick_errors": len(of("tick_error")),
    }
    stop = of("controller_stop")
    if stop:
        out["ticks"] = stop[-1].get("ticks")
    policy = (doc.get("config") or {}).get("policy") or {}
    if policy:
        out["bounds"] = [policy.get("min_replicas"),
                         policy.get("max_replicas")]
    return out


def load_loadgen(run_dir: str) -> Optional[Dict[str, Any]]:
    """A routed loadgen record (``loadgen.json``) dropped into the run
    dir by the chaos soak / choreography tests — carries the router's
    ``resilience_stats()`` and the per-second timeline."""
    path = os.path.join(run_dir, "loadgen.json")
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            doc = json.load(f)
    except ValueError:
        return None
    return doc if isinstance(doc, dict) else None


def resilience_summary(ctl_doc: Optional[Dict[str, Any]],
                       loadgen: Optional[Dict[str, Any]]
                       ) -> Optional[Dict[str, Any]]:
    """Data-plane resilience posture: standby promotions and brownout
    transitions from the controller's decision log, plus the router's
    retry/hedge/breaker/deadline accounting when a routed loadgen
    record is present. Pure; None when neither source says anything."""
    out: Dict[str, Any] = {}
    ev = (ctl_doc or {}).get("events", [])
    promotes = [e for e in ev if e.get("kind") == "fleet_promote"]
    if promotes:
        out["promotions"] = len(promotes)
        out["promote_reasons"] = [str(e.get("reason"))
                                  for e in promotes]
        secs = [e.get("seconds") for e in promotes
                if isinstance(e.get("seconds"), (int, float))]
        if secs:
            out["promote_max_s"] = round(max(secs), 4)
    standbys = [e for e in ev if e.get("kind") == "fleet_standby"]
    if standbys:
        out["standby_spawns"] = len(standbys)
    brownouts = [e for e in ev if e.get("kind") == "fleet_brownout"]
    if brownouts:
        out["brownout_transitions"] = len(brownouts)
        steps: Dict[str, int] = {}
        for e in brownouts:         # last transition wins per tenant
            if e.get("model") is not None:
                steps[str(e["model"])] = int(e.get("step", 0))
        out["brownout_last_steps"] = steps
    if loadgen:
        res = loadgen.get("resilience") or {}
        for k in ("retries", "hedged", "deadline_miss", "no_route"):
            if loadgen.get(k) is not None:
                out[k] = loadgen[k]
        for k in ("hedges_fired", "hedges_won", "breaker_opens",
                  "breaker_closes", "breaker_skips", "all_shed"):
            if res.get(k) is not None:
                out[k] = res[k]
        budget = res.get("budget") or {}
        if budget:
            out["budget"] = {k: budget[k] for k in
                             ("tokens", "spent", "refunded", "exhausted")
                             if k in budget}
        if loadgen.get("retry_after_hint_s") is not None:
            out["retry_after_hint_s"] = loadgen["retry_after_hint_s"]
    return out or None


def load_registry(run_dir: str) -> Optional[Dict[str, Any]]:
    """The metrics-registry snapshot a Trainer dumps at obs shutdown
    (``metrics_registry.json``) — the same state /metrics exposed live."""
    path = os.path.join(run_dir, "metrics_registry.json")
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            doc = json.load(f)
    except ValueError:
        return None
    return doc if isinstance(doc, dict) else None


def load_fleet(run_dir: str) -> List[Dict[str, Any]]:
    """The ``fleet.jsonl`` rollup timeseries an ``obs/fleet.py`` scraper
    appended while polling this run's replicas."""
    path = os.path.join(run_dir, "fleet.jsonl")
    if not os.path.exists(path):
        return []
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                try:
                    rows.append(json.loads(line))
                except ValueError:
                    continue
    return rows


def registry_summary(reg: Optional[Dict[str, Any]]
                     ) -> Optional[Dict[str, Any]]:
    """Registry section: identity + scalar values of every dltpu_*
    counter/gauge (histograms reduce to count/sum)."""
    if not reg:
        return None
    out: Dict[str, Any] = {
        k: reg[k] for k in ("run_id", "replica") if k in reg}
    out["collect_errors"] = reg.get("collect_errors", 0)
    values: Dict[str, Any] = {}
    for name, sample in sorted((reg.get("metrics") or {}).items()):
        if not isinstance(sample, dict):
            continue
        if sample.get("type") == "histogram":
            values[name] = {"count": sample.get("count"),
                            "sum": sample.get("sum")}
        elif "value" in sample:
            values[name] = sample["value"]
    out["metrics"] = values
    return out if values or len(out) > 1 else None


def fleet_summary(rows: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Fleet section: last rollup + run peaks + SLO burn, from the
    fleet.jsonl timeseries. Pure — tests drive it with synthetic rows."""
    if not rows:
        return None
    last = rows[-1]
    breaches = [r for r in rows if (r.get("slo") or {}).get("breach")]
    out: Dict[str, Any] = {
        "polls": len(rows),
        "replicas": last.get("replicas"),
        "replica_status": last.get("replica_status"),
        "qps_total_last": last.get("qps_total"),
        "qps_total_peak": max((r.get("qps_total", 0.0) for r in rows),
                              default=0.0),
        "e2e_ms_p99_max_last": last.get("e2e_ms_p99_max"),
        "e2e_ms_p99_max_peak": max(
            (r.get("e2e_ms_p99_max", 0.0) for r in rows), default=0.0),
        "queue_depth_total_last": last.get("queue_depth_total"),
        "error_rate_last": last.get("error_rate"),
        "slo_breach_polls": len(breaches),
    }
    slo = last.get("slo")
    if slo:
        out["slo"] = {k: slo.get(k) for k in
                      ("p99_budget_ms", "error_rate_budget", "breach",
                       "p99_breach", "error_breach")}
    return out


_LABELED_KEY = None    # compiled lazily (re import below)


def zoo_summary(reg: Optional[Dict[str, Any]],
                fleet_rows: List[Dict[str, Any]],
                child_flight: Optional[Dict[str, Any]]
                ) -> Optional[Dict[str, Any]]:
    """Multi-tenant posture: per-model warm/bytes/traffic from the
    registry snapshot's ``model``-labeled series, per-model qps/p99/SLO
    from the last fleet rollup's ``models`` fold, and load/evict/reject
    counts from the flight record. None when the run served no zoo."""
    import re
    global _LABELED_KEY
    if _LABELED_KEY is None:
        _LABELED_KEY = re.compile(
            r'^(?P<name>[A-Za-z0-9_:]+)\{model="(?P<model>[^"]+)"\}$')
    models: Dict[str, Dict[str, Any]] = {}
    out: Dict[str, Any] = {}
    per_model_keys = {
        "dltpu_zoo_model_warm": ("warm", bool),
        "dltpu_zoo_model_bytes": ("bytes", int),
        "dltpu_serve_requests_total": ("requests", float),
        "dltpu_serve_rejected_total": ("rejected", float),
        "dltpu_serve_e2e_ms_p99": ("e2e_ms_p99", float),
    }
    metrics = (reg or {}).get("metrics") or {}
    for key, sample in metrics.items():
        m = _LABELED_KEY.match(key)
        if not m or not isinstance(sample, dict) or "value" not in sample:
            continue
        mapped = per_model_keys.get(m["name"])
        if mapped:
            field, cast = mapped
            models.setdefault(m["model"], {})[field] = \
                cast(sample["value"])
    for key, short in (("dltpu_zoo_resident_models", "resident"),
                       ("dltpu_zoo_loads_total", "loads"),
                       ("dltpu_zoo_evictions_total", "evictions"),
                       ("dltpu_zoo_load_rejects_total", "load_rejects")):
        sample = metrics.get(key)
        if isinstance(sample, dict) and "value" in sample:
            out[short] = sample["value"]
    if fleet_rows:
        for alias, frow in (fleet_rows[-1].get("models") or {}).items():
            row = models.setdefault(alias, {})
            row["qps"] = frow.get("qps_total")
            row["p99_ms"] = frow.get("e2e_ms_p99_max")
            slo = frow.get("slo") or {}
            if slo:
                row["slo_breach"] = bool(slo.get("breach"))
    if child_flight is not None:
        for e in child_flight.get("events", []):
            kind = e.get("kind")
            if kind in ("zoo_load", "zoo_evict", "zoo_load_failed",
                        "zoo_load_rejected"):
                out[kind + "_events"] = out.get(kind + "_events", 0) + 1
    if not models and not out:
        return None
    out["models"] = models
    return out


def load_metrics(run_dir: str) -> List[Dict[str, Any]]:
    path = os.path.join(run_dir, "metrics.jsonl")
    if not os.path.exists(path):
        return []
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                try:
                    rows.append(json.loads(line))
                except ValueError:
                    continue
    return rows


def summarize(run_dir: str) -> Dict[str, Any]:
    """One dict per run dir: phase totals, thread lanes, compile table,
    flight/metrics summaries. Pure file reads — never imports jax."""
    events = load_trace(run_dir)
    spans = [e for e in events if e.get("ph") == "X"]
    threads = {e["args"]["name"] for e in events
               if e.get("ph") == "M" and e.get("name") == "thread_name"}

    totals: Dict[str, Dict[str, float]] = {}
    for e in spans:
        name = e["name"]
        agg = totals.setdefault(name, {"count": 0, "total_ms": 0.0})
        agg["count"] += 1
        agg["total_ms"] += e.get("dur", 0.0) / 1e3
    # wall time = extent of the trace (all threads), the denominator
    # every phase percentage is against
    wall_ms = 0.0
    if spans:
        t0 = min(e["ts"] for e in spans)
        t1 = max(e["ts"] + e.get("dur", 0.0) for e in spans)
        wall_ms = (t1 - t0) / 1e3

    phases = {}
    for name in PHASES:
        agg = totals.get(name)
        if agg:
            phases[name] = {
                "count": int(agg["count"]),
                "total_ms": round(agg["total_ms"], 3),
                "pct_wall": round(agg["total_ms"] / wall_ms * 100.0, 2)
                if wall_ms else 0.0,
            }
    other = {name: {"count": int(a["count"]),
                    "total_ms": round(a["total_ms"], 3)}
             for name, a in sorted(totals.items())
             if name not in PHASES and not name.startswith("compile/")}

    compiles = [{"fn": e["name"][len("compile/"):],
                 "ms": round(e.get("dur", 0.0) / 1e3, 1),
                 **{k: e.get("args", {}).get(k) for k in
                    ("flops", "peak_hbm_bytes", "cache_hit")}}
                for e in spans if e["name"].startswith("compile/")]

    out: Dict[str, Any] = {
        "run_dir": run_dir,
        "wall_ms": round(wall_ms, 3),
        "threads": sorted(threads),
        "phases": phases,
        "compiles": compiles,
        "other_spans": other,
    }

    flight = load_flight(run_dir)
    if flight is not None:
        kinds: Dict[str, int] = {}
        for e in flight.get("events", []):
            kinds[e.get("kind", "?")] = kinds.get(e.get("kind", "?"), 0) + 1
        exc = flight.get("exception") or {}
        out["flight"] = {
            "reason": flight.get("reason"),
            "n_events": len(flight.get("events", [])),
            "event_kinds": kinds,
            "exception": (f"{exc.get('type')}: {exc.get('message')}"
                          if exc else None),
        }

    restarts = restart_summary(load_supervisor(run_dir),
                               load_flight(run_dir))
    if restarts:
        out["restarts"] = restarts

    recovery = recovery_summary(flight)
    if recovery:
        out["recovery"] = recovery

    sharding = sharding_summary(flight)
    if sharding:
        out["sharding"] = sharding

    rows = load_metrics(run_dir)
    if rows:
        steps = [r for r in rows if not r.get("summary")]
        out["metrics"] = {"rows": len(rows), "steps": len(steps)}
        if steps:
            last = steps[-1]
            out["metrics"]["last"] = {
                k: v for k, v in last.items()
                if isinstance(v, (int, float)) and k != "time"}

    registry_raw = load_registry(run_dir)
    registry = registry_summary(registry_raw)
    if registry:
        out["registry"] = registry

    fleet_rows = load_fleet(run_dir)
    fleet = fleet_summary(fleet_rows)
    if fleet:
        out["fleet"] = fleet

    ctl_doc = load_controller(run_dir)
    controller = controller_summary(ctl_doc)
    if controller:
        out["controller"] = controller

    resilience = resilience_summary(ctl_doc, load_loadgen(run_dir))
    if resilience:
        out["resilience"] = resilience

    zoo = zoo_summary(registry_raw, fleet_rows, flight)
    if zoo:
        out["zoo"] = zoo

    analysis = analysis_summary()
    if analysis:
        out["analysis"] = analysis
    return out


def analysis_summary() -> Optional[Dict[str, Any]]:
    """dltpu-check posture: rules enabled + the committed baseline's
    size, and (v2) the concurrency surface — registered spawn sites,
    locks in the static order graph, DLT2xx baseline debt. The lint
    half reads ``analysis/baseline.json`` only; the concurrency half
    parses just the thread/lock files (sub-second); run
    ``tools/check.py --ci`` for a verdict."""
    analysis_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "deeplearning_tpu", "analysis")
    lint_py = os.path.join(analysis_dir, "lint.py")
    if not os.path.exists(lint_py):
        return None
    import importlib.util

    def load(alias: str, path: str):
        spec = importlib.util.spec_from_file_location(alias, path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules[spec.name] = mod  # dataclasses resolve via sys.modules
        spec.loader.exec_module(mod)
        return mod

    mod = load("_dltpu_lint_report", lint_py)
    baseline = mod.load_baseline()
    b_counts = baseline.get("counts", {})

    def rule_total(prefix: str) -> int:
        return sum(n for rules in b_counts.values()
                   for rule, n in rules.items()
                   if rule.startswith(prefix))

    out = {
        "rules": len(mod.RULES),
        "baseline_findings": sum(sum(r.values())
                                 for r in b_counts.values()),
        "baseline_files": len(b_counts),
    }
    conc_py = os.path.join(analysis_dir, "concurrency.py")
    if os.path.exists(conc_py):
        try:
            conc = load("_dltpu_concurrency_report", conc_py)
            graph = conc.lock_order_graph()
            out["concurrency"] = {
                "rules": len(conc.RULES),
                "spawn_sites": len(graph["spawn_sites"]),
                "locks": len(graph["locks"]),
                "lock_order_edges": len(graph["edges"]),
                "lock_order_cycles": len(graph["cycles"]),
                "baseline_findings": rule_total("DLT2"),
            }
        except Exception:  # noqa: BLE001 - posture is best-effort
            out["concurrency"] = {"error": "concurrency scan failed"}
    return out


def restart_summary(sup: Optional[Dict[str, Any]],
                    child_flight: Optional[Dict[str, Any]]
                    ) -> Optional[Dict[str, Any]]:
    """Restarts/resume section: supervisor decisions (launches,
    preemptions, wedge kills, backoff waits) joined with the child's
    resume events (which steps it came back at, whether the topology
    changed). None when the run was never supervised and never resumed."""
    out: Dict[str, Any] = {}
    if sup is not None:
        ev = sup.get("events", [])

        def count(kind: str) -> int:
            return sum(1 for e in ev if e.get("kind") == kind)

        exits = [e for e in ev if e.get("kind") == "child_exit"]
        out.update({
            "launches": count("launch"),
            "preemptions": sum(1 for e in exits
                               if e.get("outcome") == "preempted"),
            "crashes": sum(1 for e in exits
                           if e.get("outcome") == "crashed"),
            "wedge_kills": count("wedge_kill"),
            "backoff_waits": count("backoff"),
            "backoff_total_s": round(
                sum(float(e.get("delay_s", 0.0)) for e in ev
                    if e.get("kind") == "backoff"), 3),
            "gave_up": count("gave_up") > 0,
            "final": sup.get("reason"),
        })
    if child_flight is not None:
        resumes = [e for e in child_flight.get("events", [])
                   if e.get("kind") == "resume"]
        if resumes:
            out["resume_steps"] = [int(e.get("step", 0)) for e in resumes]
            out["cross_topology_resumes"] = sum(
                1 for e in resumes if e.get("cross_topology"))
    return out or None


def recovery_summary(child_flight: Optional[Dict[str, Any]]
                     ) -> Optional[Dict[str, Any]]:
    """Self-healing section: what the run survived — divergence
    rollbacks (with the data windows it skipped), quarantined samples,
    checkpoint save retries, and corrupt-checkpoint fallbacks. None
    when the run never needed any of it."""
    if child_flight is None:
        return None
    ev = child_flight.get("events", [])

    def of(kind: str) -> List[Dict[str, Any]]:
        return [e for e in ev if e.get("kind") == kind]

    rollbacks = of("recovery")
    out = {
        "rollbacks": len(rollbacks),
        "rollback_steps": [int(e.get("step", 0)) for e in rollbacks],
        "skipped_windows": [e.get("skipped") for e in rollbacks
                            if e.get("skipped")],
        "quarantined_samples": len(of("quarantine")),
        "ckpt_retries": len(of("ckpt_retry")),
        "ckpt_corrupt": len(of("ckpt_corrupt")),
        "ckpt_fallbacks": [[int(e.get("from_step", 0)),
                            int(e.get("to_step", 0))]
                           for e in of("ckpt_fallback")],
        "exhausted": len(of("recovery_exhausted")) > 0,
    }
    empty = (not rollbacks and not out["quarantined_samples"]
             and not out["ckpt_retries"] and not out["ckpt_corrupt"]
             and not out["ckpt_fallbacks"] and not out["exhausted"])
    return None if empty else out


def sharding_summary(child_flight: Optional[Dict[str, Any]]
                     ) -> Optional[Dict[str, Any]]:
    """Sharding posture: the run's weight-update mode (replicated /
    zero1), gradient-comm dtype, and — when the run recorded its AOT
    step — the collective bytes one step moves. Read from the last
    ``sharding`` flight event (tools/train.py records one after
    precompile) with the flight config as fallback. None when the run
    predates the knobs."""
    if child_flight is None:
        return None
    out: Dict[str, Any] = {}
    cfg = child_flight.get("config") or {}
    train_cfg = cfg.get("train") or {}
    for key in ("weight_update", "grad_comm"):
        if train_cfg.get(key) is not None:
            out[key] = train_cfg[key]
    for e in child_flight.get("events", []):
        if e.get("kind") != "sharding":
            continue
        for key in ("weight_update", "grad_comm", "collective_bytes"):
            if e.get(key) is not None:
                out[key] = e[key]
    return out or None


def render(summary: Dict[str, Any]) -> str:
    lines = [f"run: {summary['run_dir']}",
             f"wall: {summary['wall_ms']:.1f} ms   "
             f"threads: {', '.join(summary['threads']) or '(no trace)'}"]
    if summary["phases"]:
        lines.append("")
        lines.append(f"{'phase':<15s} {'count':>7s} {'total ms':>10s} "
                     f"{'% wall':>7s}")
        for name in PHASES:
            p = summary["phases"].get(name)
            if p:
                lines.append(f"{name:<15s} {p['count']:>7d} "
                             f"{p['total_ms']:>10.1f} "
                             f"{p['pct_wall']:>6.1f}%")
    if summary["compiles"]:
        lines.append("")
        lines.append(f"{'compile':<28s} {'ms':>9s} {'GFLOPs':>9s} "
                     f"{'HBM MB':>8s} {'cache':>6s}")
        for c in summary["compiles"]:
            flops = (c.get("flops") or 0.0) / 1e9
            hbm = (c.get("peak_hbm_bytes") or 0.0) / 1e6
            hit = {True: "hit", False: "miss", None: "n/a"}[
                c.get("cache_hit")]
            lines.append(f"{c['fn']:<28s} {c['ms']:>9.1f} {flops:>9.2f} "
                         f"{hbm:>8.1f} {hit:>6s}")
    if summary.get("other_spans"):
        lines.append("")
        lines.append("other spans: " + ", ".join(
            f"{k}×{v['count']} ({v['total_ms']:.1f} ms)"
            for k, v in summary["other_spans"].items()))
    fl = summary.get("flight")
    if fl:
        lines.append("")
        lines.append(f"flight record: reason={fl['reason']} "
                     f"events={fl['n_events']} "
                     f"kinds={fl['event_kinds']}")
        if fl.get("exception"):
            lines.append(f"  exception: {fl['exception']}")
    r = summary.get("restarts")
    if r:
        lines.append("")
        parts = []
        if "launches" in r:
            parts.append(
                f"launches={r['launches']} "
                f"preemptions={r['preemptions']} crashes={r['crashes']} "
                f"wedge_kills={r['wedge_kills']} "
                f"backoff={r['backoff_total_s']:.1f}s"
                f"×{r['backoff_waits']} final={r['final']}"
                + (" GAVE-UP" if r.get("gave_up") else ""))
        if r.get("resume_steps"):
            parts.append(
                f"resumed at steps {r['resume_steps']} "
                f"({r['cross_topology_resumes']} cross-topology)")
        lines.append("restarts: " + "; ".join(parts))
    rec = summary.get("recovery")
    if rec:
        lines.append("")
        lines.append(
            f"recovery: rollbacks={rec['rollbacks']}"
            + (f" at steps {rec['rollback_steps']}"
               if rec["rollback_steps"] else "")
            + (f" skipped={rec['skipped_windows']}"
               if rec["skipped_windows"] else "")
            + f" quarantined={rec['quarantined_samples']}"
            f" ckpt_retries={rec['ckpt_retries']}"
            + (f" ckpt_fallbacks={rec['ckpt_fallbacks']}"
               if rec["ckpt_fallbacks"] else "")
            + (" EXHAUSTED" if rec.get("exhausted") else ""))
    sh = summary.get("sharding")
    if sh:
        lines.append("")
        line = (f"sharding: weight_update={sh.get('weight_update', '?')} "
                f"grad_comm={sh.get('grad_comm', '?')}")
        if sh.get("collective_bytes") is not None:
            line += f" collective_bytes/step={sh['collective_bytes']}"
        lines.append(line)
    m = summary.get("metrics")
    if m:
        lines.append("")
        lines.append(f"metrics.jsonl: {m['rows']} rows"
                     + (f", last step {m['last']}" if m.get("last")
                        else ""))
    reg = summary.get("registry")
    if reg:
        lines.append("")
        ident = " ".join(
            f"{k}={reg[k]}" for k in ("run_id", "replica")
            if reg.get(k) is not None)
        lines.append(
            f"registry: {len(reg['metrics'])} metric(s)"
            + (f" [{ident}]" if ident else "")
            + (f" collect_errors={reg['collect_errors']}"
               if reg.get("collect_errors") else ""))
        notable = ("dltpu_train_step", "dltpu_compiles_total",
                   "dltpu_serve_requests_total",
                   "dltpu_serve_completed_total",
                   "dltpu_recovery_rollbacks_total",
                   "dltpu_quarantine_total")
        picks = [f"{n}={reg['metrics'][n]}" for n in notable
                 if n in reg["metrics"]]
        if picks:
            lines.append("  " + "  ".join(picks))
    ft = summary.get("fleet")
    if ft:
        lines.append("")
        lines.append(
            f"fleet: {ft['polls']} poll(s), {ft['replicas']} replica(s) "
            f"{ft.get('replica_status') or {}}")
        lines.append(
            f"  qps={ft.get('qps_total_last') or 0.0:.1f} "
            f"(peak {ft.get('qps_total_peak') or 0.0:.1f})  "
            f"p99={ft.get('e2e_ms_p99_max_last') or 0.0:.1f}ms "
            f"(peak {ft.get('e2e_ms_p99_max_peak') or 0.0:.1f}ms)  "
            f"queue={ft.get('queue_depth_total_last') or 0.0:.0f}  "
            f"err={ft.get('error_rate_last') or 0.0:.4f}")
        slo = ft.get("slo")
        if ft.get("slo_breach_polls") or (slo and slo.get("breach")):
            budgets = (f"p99<={slo['p99_budget_ms']}ms "
                       f"err<={slo['error_rate_budget']}" if slo else "?")
            lines.append(
                f"  SLO: {ft['slo_breach_polls']}/{ft['polls']} poll(s) "
                f"in breach (budget {budgets})")
    ct = summary.get("controller")
    if ct:
        lines.append("")
        line = (f"controller: scale_ups={ct['scale_ups']} "
                f"scale_downs={ct['scale_downs']} "
                f"drains={ct['drains']} requeues={ct['requeues']} "
                f"preemptions={ct['preemptions']}")
        if ct.get("ticks") is not None:
            line += f" ticks={ct['ticks']}"
        if ct.get("bounds"):
            line += (f" bounds=[{ct['bounds'][0]},"
                     f"{ct['bounds'][1]}]")
        if ct.get("tick_errors"):
            line += f" TICK-ERRORS={ct['tick_errors']}"
        lines.append(line)
        if ct.get("scale_reasons"):
            lines.append("  scale reasons: "
                         + ", ".join(ct["scale_reasons"]))
        if ct.get("preempt_verdicts"):
            lines.append("  preempt verdicts: "
                         + ", ".join(ct["preempt_verdicts"]))
    rs = summary.get("resilience")
    if rs:
        lines.append("")
        bits = []
        if rs.get("promotions"):
            bit = f"promotions={rs['promotions']}"
            if rs.get("promote_max_s") is not None:
                bit += f" (max {rs['promote_max_s'] * 1e3:.0f}ms)"
            bits.append(bit)
        if rs.get("standby_spawns"):
            bits.append(f"standby_spawns={rs['standby_spawns']}")
        if rs.get("brownout_transitions"):
            bits.append(
                f"brownouts={rs['brownout_transitions']}")
        for k in ("retries", "hedged", "deadline_miss", "no_route",
                  "hedges_won", "breaker_opens", "breaker_closes"):
            if rs.get(k) is not None:
                bits.append(f"{k}={rs[k]}")
        lines.append("resilience: " + (" ".join(bits) or "(quiet)"))
        if rs.get("promote_reasons"):
            lines.append("  promote reasons: "
                         + ", ".join(rs["promote_reasons"]))
        if rs.get("brownout_last_steps"):
            lines.append("  brownout steps: " + ", ".join(
                f"{m}={s}" for m, s in
                sorted(rs["brownout_last_steps"].items())))
        budget = rs.get("budget")
        if budget:
            lines.append(
                f"  retry budget: tokens={budget.get('tokens')} "
                f"spent={budget.get('spent')} "
                f"refunded={budget.get('refunded')} "
                f"exhausted={budget.get('exhausted')}")
    z = summary.get("zoo")
    if z:
        lines.append("")
        head = (f"zoo: {len(z['models'])} model(s)"
                f" resident={z.get('resident', '?')}"
                f" loads={z.get('loads', 0)}"
                f" evictions={z.get('evictions', 0)}"
                f" load_rejects={z.get('load_rejects', 0)}")
        evs = [f"{k[:-len('_events')]}×{v}" for k, v in sorted(z.items())
               if k.endswith("_events")]
        if evs:
            head += "  [" + " ".join(evs) + "]"
        lines.append(head)
        for alias, row in sorted(z["models"].items()):
            bits = []
            if "warm" in row:
                bits.append("warm" if row["warm"] else "cold")
            if row.get("bytes"):
                bits.append(f"{row['bytes']}B")
            if row.get("requests") is not None:
                bits.append(f"req={row['requests']:.0f}")
            if row.get("qps") is not None:
                bits.append(f"qps={row['qps']:.1f}")
            p99 = row.get("p99_ms", row.get("e2e_ms_p99"))
            if p99 is not None:
                bits.append(f"p99={p99:.1f}ms")
            if row.get("slo_breach"):
                bits.append("SLO-BREACH")
            lines.append(f"  {alias}: " + " ".join(bits))
    a = summary.get("analysis")
    if a:
        lines.append("")
        lines.append(
            f"analysis: {a['rules']} DLT rules enabled, baseline "
            f"{a['baseline_findings']} finding(s) in "
            f"{a['baseline_files']} file(s) (tools/check.py --ci)")
        c = a.get("concurrency")
        if c and "error" not in c:
            lines.append(
                f"concurrency: {c['rules']} DLT2xx rules, "
                f"{c['spawn_sites']} spawn site(s) registered, "
                f"{c['locks']} lock(s) in the static order graph "
                f"({c['lock_order_edges']} edge(s), "
                f"{c['lock_order_cycles']} cycle(s)), baseline "
                f"{c['baseline_findings']} finding(s) "
                f"(DLTPU_STRICT=threads arms the runtime sanitizer)")
    return "\n".join(lines)


def render_fleet(run_dir: str) -> str:
    """``--fleet`` view: the rollup timeseries a scraper appended to
    ``fleet.jsonl`` in a fleet workdir, one line per poll, plus the
    summary footer. Pure file reads."""
    rows = load_fleet(run_dir)
    lines = [f"fleet: {run_dir}"]
    if not rows:
        lines.append("  no fleet.jsonl (run obs/fleet.FleetScraper or "
                     "tools/supervise.py --replicas N first)")
        return "\n".join(lines)
    t0 = rows[0].get("time") or 0.0
    lines.append("")
    lines.append(f"{'t(s)':>7s} {'rep':>4s} {'qps':>8s} {'rej/s':>7s} "
                 f"{'p99 ms':>8s} {'queue':>6s} {'err':>7s}  slo")
    for r in rows:
        slo = r.get("slo") or {}
        verdict = "BREACH" if slo.get("breach") else (
            "ok" if slo else "-")
        if slo.get("breach"):
            which = [k for k in ("p99_breach", "error_breach")
                     if slo.get(k)]
            verdict += f" ({', '.join(w.split('_')[0] for w in which)})"
        lines.append(
            f"{(r.get('time') or 0.0) - t0:>7.1f} "
            f"{r.get('replicas', 0):>4d} "
            f"{r.get('qps_total', 0.0):>8.1f} "
            f"{r.get('rejects_per_s_total', 0.0):>7.1f} "
            f"{r.get('e2e_ms_p99_max', 0.0):>8.1f} "
            f"{r.get('queue_depth_total', 0.0):>6.0f} "
            f"{r.get('error_rate', 0.0):>7.4f}  {verdict}")
    ft = fleet_summary(rows) or {}
    lines.append("")
    lines.append(
        f"{ft.get('polls', 0)} poll(s); peak qps "
        f"{ft.get('qps_total_peak') or 0.0:.1f}, peak p99 "
        f"{ft.get('e2e_ms_p99_max_peak') or 0.0:.1f} ms; "
        f"{ft.get('slo_breach_polls', 0)} poll(s) in SLO breach; "
        f"last status {ft.get('replica_status') or {}}")
    return "\n".join(lines)


def _check() -> int:
    """Self-test: synthesize a run dir through the real obs APIs, render
    it, assert the report carries every section. No jax import, no
    device — safe in the tier-1 window."""
    import tempfile
    import time

    from deeplearning_tpu.obs.flight import FlightRecorder
    from deeplearning_tpu.obs.spans import SpanTracer

    with tempfile.TemporaryDirectory() as run_dir:
        tracer = SpanTracer(capacity=64)
        t0 = time.perf_counter()
        for i in range(3):
            tracer.record("data_wait", t0 + i * 0.01, 0.002)
            tracer.record("dispatch", t0 + i * 0.01 + 0.002, 0.007)
            tracer.record("metrics_flush", t0 + i * 0.01 + 0.009, 0.001)
        tracer.record("eval", t0 + 0.03, 0.005)
        tracer.record("compile/train_step", t0, 0.25,
                      {"seconds": 0.25, "flops": 2.5e9,
                       "peak_hbm_bytes": 1.5e6, "cache_hit": False})
        tracer.dump(os.path.join(run_dir, "trace.json"))

        rec = FlightRecorder(capacity=16)
        rec.record("step", step=1, loss=0.9)
        rec.record("resume", step=1, cross_topology=True,
                   saved_topology="data=8", current_topology="data=4")
        rec.record("step", step=2, loss=float("nan"))
        rec.record("divergence", step=2)
        # self-healing telemetry (PR 7): one survived rollback, one
        # quarantined sample, one save retry, one corrupt-ckpt fallback
        rec.record("recovery", step=2, anchor_step=1, loss=float("nan"),
                   skipped=[1, 2], rollbacks=1)
        rec.record("quarantine", index=37,
                   error="ValueError('truncated jpeg')")
        rec.record("ckpt_retry", step=2, attempt=1,
                   error="OSError(28, 'No space left')")
        rec.record("ckpt_fallback", from_step=2, to_step=1)
        # sharding posture event (tools/train.py records it post-compile)
        rec.record("sharding", weight_update="zero1", grad_comm="int8",
                   collective_bytes=1252352)
        rec.configure(os.path.join(run_dir, "flightrec.json"),
                      {"model": "mnist_fcn", "batch": 64,
                       "train": {"weight_update": "zero1",
                                 "grad_comm": "int8"}})
        assert rec.dump("divergence",
                        exception=FloatingPointError("loss=nan"))

        # supervisor decision log, through the same real recorder API
        sup = FlightRecorder(capacity=16)
        sup.record("launch", attempt=0, argv=["python", "train.py"])
        sup.record("child_exit", attempt=0, returncode=75,
                   outcome="preempted")
        sup.record("backoff", attempt=1, outcome="preempted", delay_s=1.2)
        sup.record("launch", attempt=1, argv=["python", "train.py"])
        sup.record("wedge_kill", attempt=1, pid=123, deadline_s=2.0)
        sup.record("backoff", attempt=2, outcome="wedged", delay_s=2.4)
        sup.record("launch", attempt=2, argv=["python", "train.py"])
        sup.record("child_exit", attempt=2, returncode=0,
                   outcome="completed")
        sup.record("completed", attempt=2)
        assert sup.configure(
            os.path.join(run_dir, "flightrec_supervisor.json")
        ).dump("completed", include_hbm=False)

        with open(os.path.join(run_dir, "metrics.jsonl"), "w") as f:
            f.write(json.dumps({"step": 1, "time": 0.0,
                                "train/loss": 0.9}) + "\n")
            f.write(json.dumps({"step": 2, "time": 0.1,
                                "train/loss": 1e9}) + "\n")

        # fleet-controller decision log, through the same recorder API
        # (the file deeplearning_tpu/fleet dumps after every actuation)
        ctl = FlightRecorder(capacity=16)
        ctl.record("fleet_drain", replica=1, reason="wedged",
                   then="restart", deadline_s=2.0)
        ctl.record("fleet_requeue", replica=1, reason="wedged",
                   drained=False, waited_s=2.0)
        ctl.record("preempt_capacity", replica=2, attempt=0,
                   verdict="replace", live_after=2)
        ctl.record("fleet_scale", direction="up", replica=3,
                   reason="p99_breach", live=2)
        ctl.record("fleet_scale", direction="down", replica=3,
                   reason="sustained_idle", live=3)
        # resilience actuations (PR 15): a warm-standby spawn + promote,
        # one tenant brownout transition
        ctl.record("fleet_standby", replica=4, target=1)
        ctl.record("fleet_promote", replica=4,
                   url="http://127.0.0.1:9004", reason="wedged",
                   seconds=0.012)
        ctl.record("fleet_brownout", model="alpha", step=1,
                   replicas=2, breach=True)
        ctl.record("controller_stop", ticks=9, scale_ups=1,
                   scale_downs=1, drains=1, requeues=1, preemptions=1,
                   promotions=1, brownouts=1)
        assert ctl.configure(
            os.path.join(run_dir, "flightrec_controller.json"),
            {"policy": {"min_replicas": 2, "max_replicas": 4}}
        ).dump("controller_stop", include_hbm=False)

        # routed loadgen record with the router's resilience stats —
        # built through the REAL RetryBudget/CircuitBreaker snapshots
        # so the section exercises the actual schema
        from deeplearning_tpu.fleet.resilience import (CircuitBreaker,
                                                       RetryBudget)
        rb = RetryBudget(fraction=0.2, cap=10.0, initial=2.0)
        for _ in range(5):
            rb.note_success()
        assert rb.try_spend()
        cb = CircuitBreaker(min_samples=2, failure_threshold=0.5,
                            reset_timeout_s=0.0)
        cb.record(False)
        cb.record(False)          # trips open
        assert cb.allow()         # past cooldown: half-open probe
        cb.record(True)           # probe ok: closed again
        with open(os.path.join(run_dir, "loadgen.json"), "w") as f:
            json.dump({"mode": "open_http", "retries": 3, "hedged": 2,
                       "deadline_miss": 1, "no_route": 0,
                       "retry_after_hint_s": 0.25,
                       "resilience": {
                           "hedges_fired": 2, "hedges_won": 1,
                           "breaker_opens": cb.snapshot()["opens"],
                           "breaker_closes": cb.snapshot()["closes"],
                           "breaker_skips": 4, "all_shed": 1,
                           "budget": rb.snapshot()}}, f)

        # metrics-registry snapshot through the real registry API (the
        # file a Trainer dumps at obs shutdown)
        from deeplearning_tpu.obs import fleet as fleet_mod
        from deeplearning_tpu.obs.metrics import MetricsRegistry
        regy = MetricsRegistry()
        regy.counter("dltpu_serve_requests_total").inc(42)
        regy.counter("dltpu_recovery_rollbacks_total").inc()
        regy.gauge("dltpu_train_step").set(17)
        regy.histogram("dltpu_step_ms", buckets=(1.0, 10.0)).observe(3.0)
        # zoo posture: per-model labeled series + residency counters,
        # exactly what the serve collector mirrors in zoo mode
        regy.gauge("dltpu_zoo_resident_models").set(2)
        regy.counter("dltpu_zoo_loads_total").inc(3)
        regy.counter("dltpu_zoo_evictions_total").inc()
        for alias, warm, nbytes, reqs in (("alpha", 1.0, 5354536, 30.0),
                                          ("beta", 0.0, 1361872, 12.0)):
            labels = {"model": alias}
            regy.gauge("dltpu_zoo_model_warm", labels=labels).set(warm)
            regy.gauge("dltpu_zoo_model_bytes",
                       labels=labels).set(nbytes)
            regy.counter("dltpu_serve_requests_total",
                         labels=labels).inc(reqs)
        regy.dump(os.path.join(run_dir, "metrics_registry.json"))

        # fleet.jsonl through the real rollup/SLO fold: one healthy
        # poll, one p99 breach
        def _fsample(i, qps, p99):
            return {"url": f"http://127.0.0.1:900{i}", "ok": True,
                    "status": "ready", "replica": str(i),
                    "metrics": {"dltpu_serve_requests_per_s": qps,
                                "dltpu_serve_e2e_ms_p99": p99,
                                "dltpu_serve_queue_depth": 1.0,
                                "dltpu_serve_requests_total": 100.0,
                                "dltpu_serve_completed_total": 99.0,
                                "dltpu_serve_rejected_total": 1.0,
                                "dltpu_serve_timed_out_total": 0.0},
                    # per-tenant fold input (zoo replicas label their
                    # serve series; scrape_replica groups them here)
                    "by_model": {"alpha": {
                        "dltpu_serve_requests_per_s": qps / 2,
                        "dltpu_serve_e2e_ms_p99": p99,
                        "dltpu_serve_requests_total": 50.0,
                        "dltpu_serve_rejected_total": 1.0}}}
        slo = fleet_mod.SLOPolicy(p99_budget_ms=10.0,
                                  error_rate_budget=0.5)
        with open(os.path.join(run_dir, "fleet.jsonl"), "w") as f:
            for samples in ([_fsample(0, 5.0, 4.0), _fsample(1, 7.0, 6.0)],
                            [_fsample(0, 9.0, 40.0), _fsample(1, 7.0, 6.0)]):
                f.write(json.dumps(
                    fleet_mod.compute_rollup(samples, slo)) + "\n")

        summary = summarize(run_dir)
        report = render(summary)

        assert summary["phases"]["data_wait"]["count"] == 3, summary
        assert summary["phases"]["dispatch"]["pct_wall"] > 0, summary
        assert summary["compiles"][0]["fn"] == "train_step", summary
        assert summary["compiles"][0]["cache_hit"] is False, summary
        assert summary["flight"]["reason"] == "divergence", summary
        assert summary["flight"]["event_kinds"]["step"] == 2, summary
        assert "FloatingPointError" in summary["flight"]["exception"]
        assert summary["metrics"]["rows"] == 2, summary
        r = summary["restarts"]
        assert r["launches"] == 3 and r["preemptions"] == 1, r
        assert r["wedge_kills"] == 1 and r["crashes"] == 0, r
        assert r["backoff_waits"] == 2, r
        assert abs(r["backoff_total_s"] - 3.6) < 1e-6, r
        assert r["final"] == "completed" and not r["gave_up"], r
        assert r["resume_steps"] == [1], r
        assert r["cross_topology_resumes"] == 1, r
        rc = summary["recovery"]
        assert rc["rollbacks"] == 1 and rc["rollback_steps"] == [2], rc
        assert rc["skipped_windows"] == [[1, 2]], rc
        assert rc["quarantined_samples"] == 1, rc
        assert rc["ckpt_retries"] == 1, rc
        assert rc["ckpt_fallbacks"] == [[2, 1]], rc
        assert not rc["exhausted"], rc
        sh = summary["sharding"]
        assert sh["weight_update"] == "zero1", sh
        assert sh["grad_comm"] == "int8", sh
        assert sh["collective_bytes"] == 1252352, sh
        for token in ("data_wait", "train_step", "divergence",
                      "restarts:", "cross-topology", "recovery:",
                      "quarantined=1", "sharding: weight_update=zero1",
                      "collective_bytes/step=1252352"):
            assert token in report, report
        # registry + fleet sections (the new telemetry plane files)
        rg = summary["registry"]
        assert rg["metrics"]["dltpu_serve_requests_total"] == 42.0, rg
        assert rg["metrics"]["dltpu_train_step"] == 17.0, rg
        assert rg["metrics"]["dltpu_step_ms"] == \
            {"count": 1, "sum": 3.0}, rg
        assert rg["collect_errors"] == 0, rg
        ftl = summary["fleet"]
        assert ftl["polls"] == 2 and ftl["replicas"] == 2, ftl
        assert ftl["replica_status"] == {"ready": 2}, ftl
        assert abs(ftl["qps_total_last"] - 16.0) < 1e-9, ftl
        assert abs(ftl["e2e_ms_p99_max_peak"] - 40.0) < 1e-9, ftl
        assert ftl["slo_breach_polls"] == 1, ftl
        assert ftl["slo"]["p99_breach"] and ftl["slo"]["breach"], ftl
        for token in ("registry: 13 metric(s)",
                      "dltpu_serve_requests_total=42.0",
                      "fleet: 2 poll(s), 2 replica(s)",
                      "SLO: 1/2 poll(s) in breach"):
            assert token in report, report
        fleet_view = render_fleet(run_dir)
        assert "BREACH (p99)" in fleet_view, fleet_view
        assert fleet_view.count("\n") >= 5, fleet_view
        # fleet-controller posture: every actuation class counted, the
        # whys preserved, policy bounds read from the flight config
        ct = summary["controller"]
        assert ct["scale_ups"] == 1 and ct["scale_downs"] == 1, ct
        assert ct["drains"] == 1 and ct["requeues"] == 1, ct
        assert ct["preemptions"] == 1, ct
        assert ct["preempt_verdicts"] == ["replace"], ct
        assert ct["scale_reasons"] == ["p99_breach",
                                       "sustained_idle"], ct
        assert ct["ticks"] == 9 and ct["bounds"] == [2, 4], ct
        assert ct["tick_errors"] == 0, ct
        for token in ("controller: scale_ups=1", "requeues=1",
                      "scale reasons: p99_breach, sustained_idle",
                      "preempt verdicts: replace"):
            assert token in report, report
        # resilience posture: controller promote/brownout events joined
        # with the routed loadgen's retry/hedge/breaker accounting
        rs = summary["resilience"]
        assert rs["promotions"] == 1, rs
        assert rs["promote_reasons"] == ["wedged"], rs
        assert rs["promote_max_s"] == 0.012, rs
        assert rs["standby_spawns"] == 1, rs
        assert rs["brownout_transitions"] == 1, rs
        assert rs["brownout_last_steps"] == {"alpha": 1}, rs
        assert rs["retries"] == 3 and rs["hedged"] == 2, rs
        assert rs["deadline_miss"] == 1, rs
        assert rs["hedges_won"] == 1, rs
        assert rs["breaker_opens"] == 1, rs
        assert rs["breaker_closes"] == 1, rs
        assert rs["retry_after_hint_s"] == 0.25, rs
        assert rs["budget"]["spent"] == 1, rs
        for token in ("resilience: promotions=1 (max 12ms)",
                      "standby_spawns=1", "brownouts=1",
                      "promote reasons: wedged",
                      "brownout steps: alpha=1",
                      "retry budget: tokens="):
            assert token in report, report
        # zoo posture section: registry labels + fleet per-model fold
        zz = summary["zoo"]
        assert zz["resident"] == 2.0 and zz["loads"] == 3.0, zz
        assert zz["evictions"] == 1.0, zz
        assert zz["models"]["alpha"]["warm"] is True, zz
        assert zz["models"]["beta"]["warm"] is False, zz
        assert zz["models"]["alpha"]["bytes"] == 5354536, zz
        assert zz["models"]["alpha"]["requests"] == 30.0, zz
        assert zz["models"]["alpha"]["qps"] == 8.0, zz
        assert zz["models"]["alpha"]["p99_ms"] == 40.0, zz
        assert zz["models"]["alpha"]["slo_breach"] is True, zz
        for token in ("zoo: 2 model(s)", "evictions=1",
                      "alpha: warm", "SLO-BREACH", "beta: cold"):
            assert token in report, report
        # dltpu-check posture line: rules enabled + committed baseline
        ana = summary["analysis"]
        assert ana["rules"] >= 6, ana
        assert ana["baseline_findings"] >= 0, ana
        assert "analysis: " in report and "DLT rules enabled" in report, \
            report
        # dltpu-check v2 concurrency posture: the thread fleet is
        # visible (spawn sites registered, locks graphed, no cycles)
        con = ana["concurrency"]
        assert con["rules"] == 6, con
        assert con["spawn_sites"] > 0, con
        assert con["locks"] > 0, con
        assert con["lock_order_cycles"] == 0, con
        assert "concurrency: " in report and \
            "spawn site(s) registered" in report, report
    print("obs_report --check: ok")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("run_dir", nargs="?", default=None,
                    help="run directory (runs/<name>)")
    ap.add_argument("--json", action="store_true",
                    help="emit the summary as JSON")
    ap.add_argument("--check", action="store_true",
                    help="self-test on a synthetic run dir")
    ap.add_argument("--fleet", action="store_true",
                    help="render the fleet.jsonl rollup timeseries")
    args = ap.parse_args(argv)
    if args.check:
        return _check()
    if not args.run_dir:
        ap.error("run_dir required (or --check)")
    if not os.path.isdir(args.run_dir):
        print(f"not a directory: {args.run_dir}", file=sys.stderr)
        return 2
    if args.fleet:
        rows = load_fleet(args.run_dir)
        print(json.dumps({"rows": rows,
                          "summary": fleet_summary(rows)}, indent=1)
              if args.json else render_fleet(args.run_dir))
        return 0
    summary = summarize(args.run_dir)
    print(json.dumps(summary, indent=1) if args.json
          else render(summary))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
