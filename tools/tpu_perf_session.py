#!/usr/bin/env python
"""One-shot TPU perf session: run EVERYTHING in a single completing
process (the axon tunnel wedges if a TPU process is killed mid-compile,
so no stage may be timeout-killed; results print incrementally with
flush so partial progress survives a tunnel death).

Stages (ordered so the most important number lands first if the tunnel
wedges mid-session; every result also appends to tools/mfu_results.jsonl):
  1. health probe (fails fast if the tunnel is wedged)
  2. ViT-B/16 train-step MFU: naive vs XLA-SDPA vs flash_hb attention
  2b. round-4 numerics-delta isolation: erf-vs-tanh GELU on the ViT
      step, torch_pad-vs-SAME on a ResNet-50 step (VERDICT r4 #1 asked
      for the "asserted ~0" parity-fix cost to be measured)
  3. attention kernel microbench fwd+bwd at ViT + long-context shapes
  4. Swin-B window-attention: fused kernel vs lax path

Run: python tools/tpu_perf_session.py [--skip-train-steps]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


from bench_util import bench


def stage1_probe():
    t0 = time.perf_counter()
    x = jnp.ones((256, 256), jnp.bfloat16)
    val = float(jnp.asarray(x @ x, jnp.float32)[0, 0])
    assert val == 256.0, val
    print(f"[probe] ok in {time.perf_counter() - t0:.1f}s "
          f"device={jax.devices()[0].device_kind}", flush=True)


RESULTS = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "mfu_results.jsonl")


def stage2_train_steps():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from perf_sweep import time_variant
    from deeplearning_tpu.ops.attention import flash_hb_adapter

    from deeplearning_tpu.ops.attention import sdpa_adapter

    results = {}
    for name, fn in [("naive", None),
                     ("sdpa", sdpa_adapter),
                     ("flash_hb", flash_hb_adapter)]:
        try:
            dt, mfu = time_variant(f"vit_train_{name}", 128, attn_fn=fn,
                                   results_path=RESULTS)
            results[name] = mfu
        except Exception as e:                       # noqa: BLE001
            print(f"[train:{name}] FAILED: {e}", flush=True)
    if results:
        best = max(results, key=results.get)
        print(f"[train] best attention for ViT-B/16 step: {best} "
              f"({results[best]:.2f}% MFU)", flush=True)
    return results


def stage2b_numerics_deltas():
    """Isolate the MFU cost of the round-4 parity fixes.

    erf-GELU: measure one ViT-B/16 train step under
    ``numerics.exact_numerics()`` (erf, the torch-parity flavor). Since
    round 5 the DEFAULT is the tanh approximation, so stage2's
    vit_train_naive row is the tanh baseline and this is the erf variant.
    First measured 2026-07-31: erf 47.94% vs tanh 51.71% MFU (−3.8 pts),
    which is why the default flipped.
    torch_pad: rebind the resnet module's torch_pad to XLA "SAME" for one
    ResNet-50 measurement (round 4 switched stride-2 convs to explicit
    torch-symmetric padding across resnet/yolox/hrnet/mobile/fpn).
    """
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from perf_sweep import time_variant
    from deeplearning_tpu.core import numerics
    from deeplearning_tpu.models.classification import resnet as resnet_mod

    try:
        with numerics.exact_numerics():
            time_variant("vit_train_gelu_erf", 128, results_path=RESULTS)
    except Exception as e:                           # noqa: BLE001
        print(f"[delta:gelu] FAILED: {e}", flush=True)

    orig_pad = resnet_mod.torch_pad
    try:
        time_variant("resnet50_train_torch_pad", 128,
                     model_name="resnet50", results_path=RESULTS)
        resnet_mod.torch_pad = lambda k, dilation=1: "SAME"
        time_variant("resnet50_train_same_pad", 128,
                     model_name="resnet50", results_path=RESULTS)
    except Exception as e:                           # noqa: BLE001
        print(f"[delta:pad] FAILED: {e}", flush=True)
    finally:
        resnet_mod.torch_pad = orig_pad


def stage3_attn_micro():
    from deeplearning_tpu.models.classification.vit import (
        dot_product_attention)
    from deeplearning_tpu.ops.pallas.flash_attention import (
        flash_attention, flash_attention_hb)

    def naive_bhnd(q, k, v):
        t = lambda x: x.transpose(0, 2, 1, 3)
        return t(dot_product_attention(t(q), t(k), t(v)))

    def jax_flash(q, k, v):
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention as jf)
        return jf(q, k, v, sm_scale=q.shape[-1] ** -0.5)

    shapes = [(128, 12, 197, 64), (128, 16, 50, 80),
              (8, 12, 1024, 64), (2, 12, 4096, 64), (1, 12, 8192, 64)]
    variants = {"naive": naive_bhnd, "flash": flash_attention,
                "flash_hb": flash_attention_hb, "jax_flash": jax_flash}
    for shape in shapes:
        rng = np.random.default_rng(0)
        q, k, v = (jnp.asarray(rng.normal(size=shape), jnp.bfloat16)
                   for _ in range(3))
        for mode in ("fwd", "bwd"):
            row = {}
            for name, fn in variants.items():
                f = (jax.jit(fn) if mode == "fwd" else jax.jit(jax.grad(
                    lambda q, k, v, _fn=fn: _fn(q, k, v)
                    .astype(jnp.float32).sum(), argnums=(0, 1, 2))))
                try:
                    row[name] = bench(f, (q, k, v)) * 1e3
                except Exception as e:               # noqa: BLE001
                    print(f"[attn {shape} {mode} {name}] FAILED: {e}",
                          flush=True)
                    row[name] = float("nan")
            ok = {k: v for k, v in row.items() if not np.isnan(v)}
            best = min(ok, key=ok.get) if ok else "-"
            cells = " ".join(f"{k}={v:.3f}ms" for k, v in row.items())
            print(f"[attn {shape} {mode}] {cells} winner={best}",
                  flush=True)


def stage4_window():
    from deeplearning_tpu.ops.pallas.window_attention import (
        window_attention, window_attention_checkpointed)

    # Swin-B stage-1 training shape: 224/4=56 → 64 windows of 7²=49
    # tokens, 4 heads d=32 (dim 128), batch 64 → BW=4096
    bw, n, heads, d = 64 * 64, 49, 4, 32
    rng = np.random.default_rng(0)
    qkv = jnp.asarray(rng.normal(size=(bw, n, 3, heads, d)), jnp.bfloat16)
    bias = jnp.asarray(rng.normal(size=(heads, n, n)), jnp.float32)

    def lax_path(qkv, bias):
        q = jnp.moveaxis(qkv[:, :, 0], 1, 2)
        k = jnp.moveaxis(qkv[:, :, 1], 1, 2)
        v = jnp.moveaxis(qkv[:, :, 2], 1, 2)
        s = jnp.einsum("bhnd,bhmd->bhnm", q * (d ** -0.5), k)
        s = s + bias[None].astype(s.dtype)
        p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
        o = jnp.einsum("bhnm,bhmd->bhnd", p, v)
        return jnp.moveaxis(o, 1, 2).reshape(bw, n, heads * d)

    variants = [("lax", lax_path), ("pallas", window_attention),
                ("pallas_ckpt", window_attention_checkpointed)]
    for name, fn in variants:
        try:
            dt = bench(jax.jit(fn), (qkv, bias)) * 1e3
            print(f"[window fwd {name}] {dt:.3f}ms", flush=True)
        except Exception as e:                       # noqa: BLE001
            print(f"[window fwd {name}] FAILED: {e}", flush=True)
    # training path: fwd+bwd through each variant
    for name, fn in [("lax", lax_path),
                     ("pallas_ckpt", window_attention_checkpointed)]:
        try:
            # grad w.r.t. qkv AND the trainable relative-position bias
            g = jax.jit(jax.grad(
                lambda qkv, bias, _f=fn: _f(qkv, bias)
                .astype(jnp.float32).sum(), argnums=(0, 1)))
            dt = bench(g, (qkv, bias)) * 1e3
            print(f"[window bwd {name}] {dt:.3f}ms", flush=True)
        except Exception as e:                       # noqa: BLE001
            print(f"[window bwd {name}] FAILED: {e}", flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--skip-train-steps", action="store_true")
    ap.add_argument("--skip-micro", action="store_true")
    args = ap.parse_args()
    stage1_probe()
    # train-step MFU first: it is the headline number, so it must land
    # before a mid-session tunnel wedge can take the rest
    if not args.skip_train_steps:
        stage2_train_steps()
        stage2b_numerics_deltas()
    if not args.skip_micro:
        stage3_attn_micro()
        stage4_window()


if __name__ == "__main__":
    main()
