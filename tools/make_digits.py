#!/usr/bin/env python
"""Materialize real-image datasets for offline end-to-end training.

The image has no bundled ImageNet/COCO, so the real-data pipeline is
proven on sklearn's bundled *digits* dataset (1797 real handwritten-digit
scans, the classic UCI test set):

- ``cls``: upscaled digit scans written as an ImageFolder of real JPEGs
  (root/<class>/*.jpg) — exercises the same scan/decode/augment path an
  ImageNet folder would (dataLoader/build.py capability).
- ``det``: digits composited onto textured canvases with recorded boxes,
  written as images/ + COCO-format instances.json — exercises the COCO
  json + JPEG decode detection path (YOLOX datasets/coco.py capability).

Usage: python tools/make_digits.py --root .data/digits --which both
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def load_digits_images():
    from sklearn.datasets import load_digits
    d = load_digits()
    # (N, 8, 8) float 0..16 → uint8 grayscale 0..255
    imgs = (d.images / 16.0 * 255.0).astype(np.uint8)
    return imgs, d.target.astype(np.int32)


def make_cls(root: str, size: int = 64, quality: int = 90) -> int:
    from PIL import Image
    imgs, labels = load_digits_images()
    for c in range(10):
        os.makedirs(os.path.join(root, str(c)), exist_ok=True)
    for i, (im, lab) in enumerate(zip(imgs, labels)):
        pil = Image.fromarray(im, "L").resize((size, size), Image.BICUBIC)
        pil.convert("RGB").save(
            os.path.join(root, str(lab), f"digit_{i:04d}.jpg"),
            quality=quality)
    return len(imgs)


def _paste_digit(bg, imgs, labels, rng, side_range):
    """Composite one random digit onto ``bg`` (max blend, textured bg)
    and return (x0, y0, side, class_idx, won): ``won`` is the boolean
    patch of pixels where the digit ACTUALLY shows after the max — the
    ground truth for masks must follow the composite, not the ink."""
    from PIL import Image
    j = int(rng.integers(0, len(imgs)))
    side = int(rng.integers(*side_range))
    canvas = bg.shape[0]
    digit = np.asarray(
        Image.fromarray(imgs[j], "L").resize((side, side), Image.BICUBIC),
        np.float32)
    x0 = int(rng.integers(0, canvas - side))
    y0 = int(rng.integers(0, canvas - side))
    patch = bg[y0:y0 + side, x0:x0 + side]
    won = (digit > patch) & (digit > 80)   # visible ink only
    bg[y0:y0 + side, x0:x0 + side] = np.maximum(patch, digit)
    return x0, y0, side, int(labels[j]), won


def make_det(root: str, n_images: int = 800, canvas: int = 256,
             max_obj: int = 5, seed: int = 0) -> int:
    from PIL import Image
    imgs, labels = load_digits_images()
    rng = np.random.default_rng(seed)
    img_dir = os.path.join(root, "images")
    os.makedirs(img_dir, exist_ok=True)
    coco = {"images": [], "annotations": [],
            "categories": [{"id": c + 1, "name": str(c)} for c in range(10)]}
    ann_id = 1
    for img_id in range(n_images):
        # textured background so detection isn't trivially thresholdable
        bg = rng.normal(96, 24, (canvas, canvas)).clip(0, 255)
        n_obj = int(rng.integers(1, max_obj + 1))
        for _ in range(n_obj):
            x0, y0, side, cls, _ = _paste_digit(bg, imgs, labels, rng,
                                                (28, 72))
            coco["annotations"].append({
                "id": ann_id, "image_id": img_id,
                "category_id": cls + 1,
                "bbox": [x0, y0, side, side],   # COCO xywh
                "area": side * side, "iscrowd": 0})
            ann_id += 1
        fname = f"det_{img_id:05d}.jpg"
        Image.fromarray(bg.astype(np.uint8), "L").convert("RGB").save(
            os.path.join(img_dir, fname), quality=90)
        coco["images"].append({"id": img_id, "file_name": fname,
                               "width": canvas, "height": canvas})
    with open(os.path.join(root, "instances.json"), "w") as f:
        json.dump(coco, f)
    return n_images


def make_seg(root: str, n_images: int = 400, canvas: int = 128,
             max_obj: int = 4, seed: int = 0) -> int:
    """Semantic-segmentation variant: composited digit scenes + per-pixel
    class masks (0 = background, 1..10 = digit class + 1) in ONE npz —
    the real-data path for tools/train_task.py --task segmentation."""
    imgs, labels = load_digits_images()
    rng = np.random.default_rng(seed)
    os.makedirs(root, exist_ok=True)
    # uint8 grayscale storage (12x smaller than f32 RGB); the loader
    # expands to model-ready float RGB
    xs = np.zeros((n_images, canvas, canvas), np.uint8)
    ys = np.zeros((n_images, canvas, canvas), np.uint8)
    for img_id in range(n_images):
        bg = rng.normal(96, 24, (canvas, canvas)).clip(0, 255)
        mask = np.zeros((canvas, canvas), np.uint8)
        for _ in range(int(rng.integers(1, max_obj + 1))):
            x0, y0, side, cls, won = _paste_digit(bg, imgs, labels, rng,
                                                  (20, 56))
            # label exactly the pixels the composite shows (won): no
            # hidden-ink labels, later digits only claim where they win
            mask[y0:y0 + side, x0:x0 + side][won] = cls + 1
        xs[img_id] = bg.astype(np.uint8)
        ys[img_id] = mask
    out = os.path.join(root, "seg.npz")
    np.savez_compressed(out, images=xs, masks=ys)
    return n_images


def make_kp(root: str, n_images: int = 300, canvas: int = 128,
            n_kp: int = 4, seed: int = 0) -> int:
    """Keypoint variant: digit centers as keypoints (x, y, vis), padded
    to ``n_kp`` slots — the real-data path for --task keypoints."""
    imgs, labels = load_digits_images()
    rng = np.random.default_rng(seed)
    os.makedirs(root, exist_ok=True)
    xs = np.zeros((n_images, canvas, canvas), np.uint8)
    kps = np.zeros((n_images, n_kp, 3), np.float32)
    for img_id in range(n_images):
        bg = rng.normal(96, 24, (canvas, canvas)).clip(0, 255)
        for slot in range(int(rng.integers(1, n_kp + 1))):
            x0, y0, side, _, _ = _paste_digit(bg, imgs, labels, rng,
                                              (20, 56))
            kps[img_id, slot] = (x0 + side / 2, y0 + side / 2, 1.0)
        xs[img_id] = bg.astype(np.uint8)
    out = os.path.join(root, "kp.npz")
    np.savez_compressed(out, images=xs, keypoints=kps)
    return n_images


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--root", default=".data/digits")
    ap.add_argument("--which", default="both",
                    choices=["cls", "det", "seg", "kp", "both", "all"])
    ap.add_argument("--det-images", type=int, default=800)
    ap.add_argument("--seg-images", type=int, default=400)
    ap.add_argument("--kp-images", type=int, default=300)
    args = ap.parse_args()
    if args.which in ("cls", "both", "all"):
        n = make_cls(os.path.join(args.root, "cls"))
        print(f"cls: wrote {n} JPEGs under {args.root}/cls")
    if args.which in ("det", "both", "all"):
        n = make_det(os.path.join(args.root, "det"),
                     n_images=args.det_images)
        print(f"det: wrote {n} composited scenes under {args.root}/det")
    if args.which in ("seg", "all"):
        n = make_seg(os.path.join(args.root, "seg"),
                     n_images=args.seg_images)
        print(f"seg: wrote {n} scenes+masks to {args.root}/seg/seg.npz")
    if args.which in ("kp", "all"):
        n = make_kp(os.path.join(args.root, "kp"),
                    n_images=args.kp_images)
        print(f"kp: wrote {n} scenes+keypoints to {args.root}/kp/kp.npz")


if __name__ == "__main__":
    main()
