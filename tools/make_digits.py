#!/usr/bin/env python
"""Materialize real-image datasets for offline end-to-end training.

The image has no bundled ImageNet/COCO, so the real-data pipeline is
proven on sklearn's bundled *digits* dataset (1797 real handwritten-digit
scans, the classic UCI test set):

- ``cls``: upscaled digit scans written as an ImageFolder of real JPEGs
  (root/<class>/*.jpg) — exercises the same scan/decode/augment path an
  ImageNet folder would (dataLoader/build.py capability).
- ``det``: digits composited onto textured canvases with recorded boxes,
  written as images/ + COCO-format instances.json — exercises the COCO
  json + JPEG decode detection path (YOLOX datasets/coco.py capability).

Usage: python tools/make_digits.py --root .data/digits --which both
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def load_digits_images():
    from sklearn.datasets import load_digits
    d = load_digits()
    # (N, 8, 8) float 0..16 → uint8 grayscale 0..255
    imgs = (d.images / 16.0 * 255.0).astype(np.uint8)
    return imgs, d.target.astype(np.int32)


def make_cls(root: str, size: int = 64, quality: int = 90) -> int:
    from PIL import Image
    imgs, labels = load_digits_images()
    for c in range(10):
        os.makedirs(os.path.join(root, str(c)), exist_ok=True)
    for i, (im, lab) in enumerate(zip(imgs, labels)):
        pil = Image.fromarray(im, "L").resize((size, size), Image.BICUBIC)
        pil.convert("RGB").save(
            os.path.join(root, str(lab), f"digit_{i:04d}.jpg"),
            quality=quality)
    return len(imgs)


def _paste_digit(bg, imgs, labels, rng, side_range):
    """Composite one random digit onto ``bg`` (max blend, textured bg)
    and return (x0, y0, side, class_idx, won): ``won`` is the boolean
    patch of pixels where the digit ACTUALLY shows after the max — the
    ground truth for masks must follow the composite, not the ink."""
    from PIL import Image
    j = int(rng.integers(0, len(imgs)))
    side = int(rng.integers(*side_range))
    canvas = bg.shape[0]
    digit = np.asarray(
        Image.fromarray(imgs[j], "L").resize((side, side), Image.BICUBIC),
        np.float32)
    x0 = int(rng.integers(0, canvas - side))
    y0 = int(rng.integers(0, canvas - side))
    patch = bg[y0:y0 + side, x0:x0 + side]
    won = (digit > patch) & (digit > 80)   # visible ink only
    bg[y0:y0 + side, x0:x0 + side] = np.maximum(patch, digit)
    return x0, y0, side, int(labels[j]), won


def make_det(root: str, n_images: int = 800, canvas: int = 256,
             max_obj: int = 5, seed: int = 0) -> int:
    from PIL import Image
    imgs, labels = load_digits_images()
    rng = np.random.default_rng(seed)
    img_dir = os.path.join(root, "images")
    os.makedirs(img_dir, exist_ok=True)
    coco = {"images": [], "annotations": [],
            "categories": [{"id": c + 1, "name": str(c)} for c in range(10)]}
    ann_id = 1
    for img_id in range(n_images):
        # textured background so detection isn't trivially thresholdable
        bg = rng.normal(96, 24, (canvas, canvas)).clip(0, 255)
        n_obj = int(rng.integers(1, max_obj + 1))
        for _ in range(n_obj):
            x0, y0, side, cls, _ = _paste_digit(bg, imgs, labels, rng,
                                                (28, 72))
            coco["annotations"].append({
                "id": ann_id, "image_id": img_id,
                "category_id": cls + 1,
                "bbox": [x0, y0, side, side],   # COCO xywh
                "area": side * side, "iscrowd": 0})
            ann_id += 1
        fname = f"det_{img_id:05d}.jpg"
        Image.fromarray(bg.astype(np.uint8), "L").convert("RGB").save(
            os.path.join(img_dir, fname), quality=90)
        coco["images"].append({"id": img_id, "file_name": fname,
                               "width": canvas, "height": canvas})
    with open(os.path.join(root, "instances.json"), "w") as f:
        json.dump(coco, f)
    return n_images


def make_seg(root: str, n_images: int = 400, canvas: int = 128,
             max_obj: int = 4, seed: int = 0) -> int:
    """Semantic-segmentation variant: composited digit scenes + per-pixel
    class masks (0 = background, 1..10 = digit class + 1) in ONE npz —
    the real-data path for tools/train_task.py --task segmentation."""
    imgs, labels = load_digits_images()
    rng = np.random.default_rng(seed)
    os.makedirs(root, exist_ok=True)
    # uint8 grayscale storage (12x smaller than f32 RGB); the loader
    # expands to model-ready float RGB
    xs = np.zeros((n_images, canvas, canvas), np.uint8)
    ys = np.zeros((n_images, canvas, canvas), np.uint8)
    for img_id in range(n_images):
        bg = rng.normal(96, 24, (canvas, canvas)).clip(0, 255)
        mask = np.zeros((canvas, canvas), np.uint8)
        for _ in range(int(rng.integers(1, max_obj + 1))):
            x0, y0, side, cls, won = _paste_digit(bg, imgs, labels, rng,
                                                  (20, 56))
            # label exactly the pixels the composite shows (won): no
            # hidden-ink labels, later digits only claim where they win
            mask[y0:y0 + side, x0:x0 + side][won] = cls + 1
        xs[img_id] = bg.astype(np.uint8)
        ys[img_id] = mask
    out = os.path.join(root, "seg.npz")
    np.savez_compressed(out, images=xs, masks=ys)
    return n_images


def make_kp(root: str, n_images: int = 300, canvas: int = 128,
            n_kp: int = 4, seed: int = 0) -> int:
    """Keypoint variant: digit centers as keypoints (x, y, vis), padded
    to ``n_kp`` slots — the real-data path for --task keypoints."""
    imgs, labels = load_digits_images()
    rng = np.random.default_rng(seed)
    os.makedirs(root, exist_ok=True)
    xs = np.zeros((n_images, canvas, canvas), np.uint8)
    kps = np.zeros((n_images, n_kp, 3), np.float32)
    for img_id in range(n_images):
        bg = rng.normal(96, 24, (canvas, canvas)).clip(0, 255)
        for slot in range(int(rng.integers(1, n_kp + 1))):
            x0, y0, side, _, _ = _paste_digit(bg, imgs, labels, rng,
                                              (20, 56))
            kps[img_id, slot] = (x0 + side / 2, y0 + side / 2, 1.0)
        xs[img_id] = bg.astype(np.uint8)
    out = os.path.join(root, "kp.npz")
    np.savez_compressed(out, images=xs, keypoints=kps)
    return n_images


def _clutter(bg: np.ndarray, rng, n: int) -> None:
    """Unlabeled distractors: bright strokes/blobs/ring fragments that a
    lazy detector confuses with digit ink (occlusion + hard negatives)."""
    canvas = bg.shape[0]
    for _ in range(n):
        kind = rng.integers(0, 3)
        if kind == 0:        # line stroke
            x0, y0 = rng.integers(0, canvas, 2)
            ang = rng.uniform(0, 2 * np.pi)
            length = int(rng.integers(canvas // 8, canvas // 2))
            ts = np.arange(length)
            xs = (x0 + ts * np.cos(ang)).astype(int) % canvas
            ys = (y0 + ts * np.sin(ang)).astype(int) % canvas
            val = rng.uniform(120, 230)
            for d in (-1, 0, 1):
                bg[np.clip(ys + d, 0, canvas - 1), xs] = np.maximum(
                    bg[np.clip(ys + d, 0, canvas - 1), xs], val)
        elif kind == 1:      # gaussian blob
            cx, cy = rng.integers(8, canvas - 8, 2)
            sig = rng.uniform(2, 6)
            yy, xx = np.mgrid[0:canvas, 0:canvas]
            blob = np.exp(-((xx - cx) ** 2 + (yy - cy) ** 2)
                          / (2 * sig ** 2)) * rng.uniform(90, 200)
            np.maximum(bg, blob, out=bg)
        else:                # ring fragment
            cx, cy = rng.integers(10, canvas - 10, 2)
            r = rng.uniform(canvas // 16, canvas // 5)
            a0 = rng.uniform(0, 2 * np.pi)
            ts = np.linspace(a0, a0 + rng.uniform(1, 5), 80)
            xs = np.clip((cx + r * np.cos(ts)).astype(int), 0, canvas - 1)
            ys = np.clip((cy + r * np.sin(ts)).astype(int), 0, canvas - 1)
            bg[ys, xs] = np.maximum(bg[ys, xs], rng.uniform(120, 220))


def _affine_digit(imgs, j, side, rng):
    """Digit scan -> side x side patch with random rotation/shear."""
    from PIL import Image
    pil = Image.fromarray(imgs[j], "L").resize((side, side), Image.BICUBIC)
    pil = pil.rotate(float(rng.uniform(-25, 25)), resample=Image.BICUBIC,
                     fillcolor=0)
    return np.asarray(pil, np.float32)


def make_cls_hard(root: str, n_images: int = 12000, size: int = 64,
                  seed: int = 0) -> int:
    """100-class hard classification: ordered digit PAIRS (class =
    10*left + right) composited with rotation, scale jitter, textured
    background and clutter — the offline proxy for many-class
    classification (VERDICT r3 #5: >=50 classes, clutter, 10-20k
    images). One npz: images (N, size, size, 1) uint8 + labels."""
    imgs, labels = load_digits_images()
    by_class = [np.flatnonzero(labels == c) for c in range(10)]
    rng = np.random.default_rng(seed)
    os.makedirs(root, exist_ok=True)
    xs = np.zeros((n_images, size, size, 1), np.uint8)
    ys = np.zeros((n_images,), np.int64)
    for i in range(n_images):
        cls = int(rng.integers(0, 100))
        left, right = cls // 10, cls % 10
        bg = rng.normal(80, 26, (size, size)).clip(0, 255)
        _clutter(bg, rng, int(rng.integers(1, 4)))
        for k, digit_cls in enumerate((left, right)):
            side = int(rng.integers(size // 3, size // 2))
            j = int(by_class[digit_cls][rng.integers(
                0, len(by_class[digit_cls]))])
            patch = _affine_digit(imgs, j, side, rng)
            cx = int(rng.integers(0, size // 2 - side // 2)) if k == 0                 else int(rng.integers(size // 2, size - side))
            cy = int(rng.integers(0, size - side))
            region = bg[cy:cy + side, cx:cx + side]
            np.maximum(region, patch, out=region)
        xs[i, :, :, 0] = bg.astype(np.uint8)
        ys[i] = cls
    np.savez_compressed(os.path.join(root, "cls_hard.npz"),
                        images=xs, labels=ys)
    return n_images


def make_det_hard(root: str, n_images: int = 4000, canvas: int = 128,
                  max_obj: int = 8, seed: int = 0) -> int:
    """Harder detection: up to ``max_obj`` digits per 128px scene, wide
    scale range, rotations, heavy clutter, overlaps allowed."""
    from PIL import Image
    imgs, labels = load_digits_images()
    rng = np.random.default_rng(seed)
    img_dir = os.path.join(root, "images")
    os.makedirs(img_dir, exist_ok=True)
    coco = {"images": [], "annotations": [],
            "categories": [{"id": c + 1, "name": str(c)}
                           for c in range(10)]}
    ann_id = 1
    for img_id in range(n_images):
        bg = rng.normal(84, 26, (canvas, canvas)).clip(0, 255)
        _clutter(bg, rng, int(rng.integers(2, 6)))
        for _ in range(int(rng.integers(1, max_obj + 1))):
            side = int(rng.integers(14, 52))
            j = int(rng.integers(0, len(imgs)))
            patch = _affine_digit(imgs, j, side, rng)
            x0 = int(rng.integers(0, canvas - side))
            y0 = int(rng.integers(0, canvas - side))
            region = bg[y0:y0 + side, x0:x0 + side]
            np.maximum(region, patch, out=region)
            coco["annotations"].append({
                "id": ann_id, "image_id": img_id,
                "category_id": int(labels[j]) + 1,
                "bbox": [x0, y0, side, side],
                "area": side * side, "iscrowd": 0})
            ann_id += 1
        fname = f"det_{img_id:05d}.jpg"
        Image.fromarray(bg.astype(np.uint8), "L").convert("RGB").save(
            os.path.join(img_dir, fname), quality=90)
        coco["images"].append({"id": img_id, "file_name": fname,
                               "width": canvas, "height": canvas})
    with open(os.path.join(root, "instances.json"), "w") as f:
        json.dump(coco, f)
    return n_images


def make_seg_hard(root: str, n_images: int = 3000, canvas: int = 128,
                  max_obj: int = 6, seed: int = 0) -> int:
    """Harder 11-class segmentation: more objects + clutter distractors
    that stay background-labeled."""
    imgs, labels = load_digits_images()
    rng = np.random.default_rng(seed)
    os.makedirs(root, exist_ok=True)
    xs = np.zeros((n_images, canvas, canvas), np.uint8)
    ys = np.zeros((n_images, canvas, canvas), np.uint8)
    for img_id in range(n_images):
        bg = rng.normal(84, 26, (canvas, canvas)).clip(0, 255)
        _clutter(bg, rng, int(rng.integers(2, 5)))
        mask = np.zeros((canvas, canvas), np.uint8)
        for _ in range(int(rng.integers(1, max_obj + 1))):
            x0, y0, side, cls, won = _paste_digit(bg, imgs, labels, rng,
                                                  (16, 52))
            mask[y0:y0 + side, x0:x0 + side][won] = cls + 1
        xs[img_id] = bg.astype(np.uint8)
        ys[img_id] = mask
    np.savez_compressed(os.path.join(root, "seg_hard.npz"),
                        images=xs, masks=ys)
    return n_images


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--root", default=".data/digits")
    ap.add_argument("--which", default="both",
                    choices=["cls", "det", "seg", "kp", "both", "all",
                             "hard"])
    ap.add_argument("--det-images", type=int, default=800)
    ap.add_argument("--seg-images", type=int, default=400)
    ap.add_argument("--kp-images", type=int, default=300)
    args = ap.parse_args()
    if args.which in ("cls", "both", "all"):
        n = make_cls(os.path.join(args.root, "cls"))
        print(f"cls: wrote {n} JPEGs under {args.root}/cls")
    if args.which in ("det", "both", "all"):
        n = make_det(os.path.join(args.root, "det"),
                     n_images=args.det_images)
        print(f"det: wrote {n} composited scenes under {args.root}/det")
    if args.which in ("seg", "all"):
        n = make_seg(os.path.join(args.root, "seg"),
                     n_images=args.seg_images)
        print(f"seg: wrote {n} scenes+masks to {args.root}/seg/seg.npz")
    if args.which == "hard":
        n = make_cls_hard(os.path.join(args.root, "cls_hard"))
        print(f"cls_hard: {n} images -> {args.root}/cls_hard/cls_hard.npz")
        n = make_det_hard(os.path.join(args.root, "det_hard"),
                          n_images=args.det_images)
        print(f"det_hard: {n} scenes -> {args.root}/det_hard/")
        n = make_seg_hard(os.path.join(args.root, "seg_hard"),
                          n_images=args.seg_images)
        print(f"seg_hard: {n} scenes -> {args.root}/seg_hard/seg_hard.npz")
    if args.which in ("kp", "all"):
        n = make_kp(os.path.join(args.root, "kp"),
                    n_images=args.kp_images)
        print(f"kp: wrote {n} scenes+keypoints to {args.root}/kp/kp.npz")


if __name__ == "__main__":
    main()
