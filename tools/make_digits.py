#!/usr/bin/env python
"""Materialize real-image datasets for offline end-to-end training.

The image has no bundled ImageNet/COCO, so the real-data pipeline is
proven on sklearn's bundled *digits* dataset (1797 real handwritten-digit
scans, the classic UCI test set):

- ``cls``: upscaled digit scans written as an ImageFolder of real JPEGs
  (root/<class>/*.jpg) — exercises the same scan/decode/augment path an
  ImageNet folder would (dataLoader/build.py capability).
- ``det``: digits composited onto textured canvases with recorded boxes,
  written as images/ + COCO-format instances.json — exercises the COCO
  json + JPEG decode detection path (YOLOX datasets/coco.py capability).

Usage: python tools/make_digits.py --root .data/digits --which both
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def load_digits_images():
    from sklearn.datasets import load_digits
    d = load_digits()
    # (N, 8, 8) float 0..16 → uint8 grayscale 0..255
    imgs = (d.images / 16.0 * 255.0).astype(np.uint8)
    return imgs, d.target.astype(np.int32)


def make_cls(root: str, size: int = 64, quality: int = 90) -> int:
    from PIL import Image
    imgs, labels = load_digits_images()
    for c in range(10):
        os.makedirs(os.path.join(root, str(c)), exist_ok=True)
    for i, (im, lab) in enumerate(zip(imgs, labels)):
        pil = Image.fromarray(im, "L").resize((size, size), Image.BICUBIC)
        pil.convert("RGB").save(
            os.path.join(root, str(lab), f"digit_{i:04d}.jpg"),
            quality=quality)
    return len(imgs)


def make_det(root: str, n_images: int = 800, canvas: int = 256,
             max_obj: int = 5, seed: int = 0) -> int:
    from PIL import Image
    imgs, labels = load_digits_images()
    rng = np.random.default_rng(seed)
    img_dir = os.path.join(root, "images")
    os.makedirs(img_dir, exist_ok=True)
    coco = {"images": [], "annotations": [],
            "categories": [{"id": c + 1, "name": str(c)} for c in range(10)]}
    ann_id = 1
    for img_id in range(n_images):
        # textured background so detection isn't trivially thresholdable
        bg = rng.normal(96, 24, (canvas, canvas)).clip(0, 255)
        n_obj = int(rng.integers(1, max_obj + 1))
        for _ in range(n_obj):
            j = int(rng.integers(0, len(imgs)))
            side = int(rng.integers(28, 72))
            digit = np.asarray(
                Image.fromarray(imgs[j], "L").resize((side, side),
                                                     Image.BICUBIC),
                np.float32)
            x0 = int(rng.integers(0, canvas - side))
            y0 = int(rng.integers(0, canvas - side))
            patch = bg[y0:y0 + side, x0:x0 + side]
            bg[y0:y0 + side, x0:x0 + side] = np.maximum(patch, digit)
            coco["annotations"].append({
                "id": ann_id, "image_id": img_id,
                "category_id": int(labels[j]) + 1,
                "bbox": [x0, y0, side, side],   # COCO xywh
                "area": side * side, "iscrowd": 0})
            ann_id += 1
        fname = f"det_{img_id:05d}.jpg"
        Image.fromarray(bg.astype(np.uint8), "L").convert("RGB").save(
            os.path.join(img_dir, fname), quality=90)
        coco["images"].append({"id": img_id, "file_name": fname,
                               "width": canvas, "height": canvas})
    with open(os.path.join(root, "instances.json"), "w") as f:
        json.dump(coco, f)
    return n_images


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--root", default=".data/digits")
    ap.add_argument("--which", default="both",
                    choices=["cls", "det", "both"])
    ap.add_argument("--det-images", type=int, default=800)
    args = ap.parse_args()
    if args.which in ("cls", "both"):
        n = make_cls(os.path.join(args.root, "cls"))
        print(f"cls: wrote {n} JPEGs under {args.root}/cls")
    if args.which in ("det", "both"):
        n = make_det(os.path.join(args.root, "det"),
                     n_images=args.det_images)
        print(f"det: wrote {n} composited scenes under {args.root}/det")


if __name__ == "__main__":
    main()
