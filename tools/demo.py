#!/usr/bin/env python
"""Detection demo CLI: image in → annotated image out.

  python tools/demo.py --model yolox_tiny --num-classes 80 \\
      --input street.jpg --out street_det.jpg [--ckpt DIR] [--tta]

The YOLOX ``tools/demo.py`` / yolov5 ``detect.py`` successor: builds any
registry detector, restores a checkpoint, runs the family's fixed-shape
postprocess (optionally multi-scale+flip TTA for the YOLOX family),
draws the surviving boxes with ``utils/visualize.draw_boxes`` and writes
the annotated image. Detections also print as JSON lines for scripting.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax

if os.environ.get("DLTPU_PLATFORM"):
    jax.config.update("jax_platforms", os.environ["DLTPU_PLATFORM"])

import jax.numpy as jnp
import numpy as np


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", required=True,
                    help="registry name (yolox_*, yolov5*, retinanet_*, "
                         "fcos_*, fasterrcnn_*)")
    ap.add_argument("--num-classes", type=int, default=80)
    ap.add_argument("--ckpt", default=None,
                    help="orbax checkpoint dir (TrainState or params)")
    ap.add_argument("--input", required=True, help="image file")
    ap.add_argument("--out", default=None,
                    help="annotated image path (default <input>_det.png)")
    ap.add_argument("--size", type=int, default=640)
    ap.add_argument("--score", type=float, default=0.3)
    ap.add_argument("--tta", action="store_true",
                    help="multi-scale+flip TTA (YOLOX family only)")
    ap.add_argument("--classes", default=None,
                    help="json mapping class index -> name")
    args = ap.parse_args(argv)

    from deeplearning_tpu.core.checkpoint import restore_variables
    from deeplearning_tpu.core.registry import MODELS
    from deeplearning_tpu.data.datasets import load_image
    from deeplearning_tpu.utils.visualize import draw_boxes
    from train_detection import build_task

    # fasterrcnn heads train with class 0 = background (train_detection
    # builds them with num_classes+1 and the postprocess shifts labels)
    model_classes = args.num_classes + (
        1 if args.model.startswith("fasterrcnn") else 0)
    model = MODELS.build(args.model, num_classes=model_classes)
    is_npy = args.input.lower().endswith(".npy")
    raw = np.asarray(load_image(args.input), np.float32)  # (H, W, 3)
    h0, w0 = raw.shape[:2]
    if not is_npy:               # image files decode to 0-255
        raw = raw / 255.0        # .npy is model-ready by convention
    elif raw.max() > 4.0:
        # mean/std-normalized arrays top out near ~3; values beyond
        # that mean raw 0-255 pixels were saved un-normalized
        print(f"warning: .npy input has max {raw.max():.1f} — looks "
              "like raw 0-255 pixels; .npy must be model-ready "
              "(normalized) or detections will be garbage",
              file=sys.stderr)
    images = jax.image.resize(jnp.asarray(raw),
                              (args.size, args.size, 3), "bilinear")[None]

    variables = model.init(jax.random.key(0), images, train=False)
    if args.ckpt:
        variables = restore_variables(args.ckpt, variables)
    params = variables["params"]
    stats = variables.get("batch_stats", {})

    if args.tta:
        if not args.model.startswith("yolox"):
            raise SystemExit("--tta currently supports the YOLOX family")
        from deeplearning_tpu.ops.tta import yolox_tta
        raw_fn = lambda x: model.apply(
            {"params": params, "batch_stats": stats}, x, train=False)
        det = jax.jit(lambda im: yolox_tta(
            raw_fn, im, score_thresh=args.score, max_det=100))(images)
    else:
        _, predict_fn = build_task(model, args.model, args.num_classes,
                                   score_thresh=args.score, max_det=100)
        det = jax.jit(predict_fn)(params, stats, images)

    keep = np.asarray(det["valid"][0])
    boxes = np.asarray(det["boxes"][0])[keep]
    scores = np.asarray(det["scores"][0])[keep]
    labels = np.asarray(det["labels"][0])[keep]
    # back to the original frame
    boxes = boxes * np.array([w0 / args.size, h0 / args.size] * 2)

    names = {}
    if args.classes:
        with open(args.classes) as f:
            names = {int(k): v for k, v in json.load(f).items()}
    for b, s, c in zip(boxes, scores, labels):
        print(json.dumps({
            "box": [round(float(x), 1) for x in b],
            "score": round(float(s), 4),
            "label": names.get(int(c), int(c))}))

    # render: image files are 0-1 here; arbitrary-range .npy is min-max
    # normalized for display only
    disp = raw if not is_npy else \
        (raw - raw.min()) / max(raw.max() - raw.min(), 1e-6)
    annotated = draw_boxes(
        np.clip(disp * 255.0, 0, 255).astype(np.uint8), boxes,
        labels=[names.get(int(c), str(int(c))) for c in labels],
        scores=scores)
    out_path = args.out or os.path.splitext(args.input)[0] + "_det.png"
    from PIL import Image
    Image.fromarray(annotated).save(out_path)
    print(f"wrote {out_path} ({keep.sum()} detections)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
