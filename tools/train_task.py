#!/usr/bin/env python
"""Unified task CLI for the non-classification families — the successor
of the reference's per-project train.py entries: Image_segmentation/*/
train.py, self-supervised/MAE/train.py, self-supervised/SupCon (trainer/
trainer.py), metric_learning/BDB/main.py, pose_estimation/Insulator/
train.py, deep_stereo Stereo_Online_Adaptation.py.

Usage:
  python tools/train_task.py --task segmentation model.name=unet
  python tools/train_task.py --task mae train.steps=20
  python tools/train_task.py --task supcon
  python tools/train_task.py --task metric
  python tools/train_task.py --task keypoints
  python tools/train_task.py --task stereo

Each task trains on synthetic (or npz) data with the family's loss and
prints a task metric at the end — the smoke-train surface the reference
covers with its bundled mini-datasets.
"""

from __future__ import annotations

import dataclasses
import os
import sys
from typing import Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax

if os.environ.get("DLTPU_PLATFORM"):
    jax.config.update("jax_platforms", os.environ["DLTPU_PLATFORM"])

import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class ModelCfg:
    name: str = ""                   # per-task default if empty
    num_classes: int = 4
    image_size: int = 32


@dataclasses.dataclass(frozen=True)
class DataCfg:
    npz: Optional[str] = None
    n_train: int = 32
    batch: int = 8


@dataclasses.dataclass(frozen=True)
class TrainCfg:
    steps: int = 30
    lr: float = 1e-3
    seed: int = 0


@dataclasses.dataclass(frozen=True)
class TaskConfig:
    model: ModelCfg = dataclasses.field(default_factory=ModelCfg)
    data: DataCfg = dataclasses.field(default_factory=DataCfg)
    train: TrainCfg = dataclasses.field(default_factory=TrainCfg)


DEFAULT_MODEL = {
    "segmentation": "unet",
    "mae": "mae_vit_small_patch16",
    "supcon": "supcon_resnet18",
    "metric": "arcface_resnet18",
    "keypoints": "hrnet_w18_keypoints",
    "stereo": "madnet",
}


def _loop(loss_fn, params, steps, lr):
    """Shared Adam loop: loss_fn(params, step) -> scalar loss."""
    import optax
    tx = optax.adam(lr)
    opt = tx.init(params)

    @jax.jit
    def step(params, opt, i):
        loss, g = jax.value_and_grad(lambda p: loss_fn(p, i))(params)
        up, opt = tx.update(g, opt, params)
        return optax.apply_updates(params, up), opt, loss

    first = last = None
    for i in range(steps):
        params, opt, loss = step(params, opt, jnp.asarray(i))
        last = float(loss)
        if first is None:
            first = last
        if i % max(steps // 5, 1) == 0:
            print(f"step {i}: loss={last:.4f}", flush=True)
    if last is None:
        print("no steps run")
        return params, float("nan"), float("nan")
    print(f"loss {first:.4f} -> {last:.4f}")
    return params, first, last


def _load_npz_images(blob):
    """images from an npz: uint8 -> [0,1] float, grayscale -> RGB."""
    images = blob["images"]
    if images.dtype == np.uint8:
        images = images.astype(np.float32) / 255.0
    if images.ndim == 3:
        images = np.repeat(images[..., None], 3, axis=-1)
    return images


def _make_batcher(batch, *arrays):
    """Deterministic wraparound minibatcher over equally-indexed arrays
    (jit-safe: dynamic_slice with the traced step index)."""
    b = min(batch, arrays[0].shape[0])

    def batch_at(i):
        start = (i * b) % (arrays[0].shape[0] - b + 1)
        return tuple(jax.lax.dynamic_slice_in_dim(a, start, b)
                     for a in arrays)
    return batch_at


def _chunked_apply(n_total, batch):
    """Yield (idx, n_real) chunks covering [0, n_total) at a fixed jit
    batch shape: tail chunks pad by clamping to the last index and the
    caller counts only the first n_real rows."""
    eb = min(batch, n_total)
    for start in range(0, n_total, eb):
        idx = np.minimum(np.arange(start, start + eb), n_total - 1)
        yield idx, min(eb, n_total - start)


def _pk_order(labels_all):
    """K=2 same-id instances adjacent, ids cycling — every wraparound
    batch then has both positives AND negatives (a label-sorted order
    degenerates contrastive/triplet objectives: no negatives)."""
    by_id = np.argsort(labels_all, kind="stable")
    within = np.zeros(len(labels_all), np.int64)
    counts = {}
    for pos, idx in enumerate(by_id):
        c = int(labels_all[idx])
        within[pos] = counts.get(c, 0)
        counts[c] = counts.get(c, 0) + 1
    return by_id[np.lexsort((within % 2, labels_all[by_id],
                             within // 2))]


def run_segmentation(cfg: TaskConfig) -> int:
    from deeplearning_tpu.core.registry import MODELS
    from deeplearning_tpu.evaluation.metrics import (confusion_matrix,
                                                     miou_from_confusion)
    from deeplearning_tpu.ops import losses as L

    if cfg.data.npz:
        # real-data path: npz with images (N,H,W,3) f32 and masks
        # (N,H,W) int; first 10% held out for the mIoU report
        blob = np.load(cfg.data.npz)
        images = _load_npz_images(blob)
        masks = blob["masks"].astype(np.int32)
        num_classes = int(masks.max()) + 1
        n_val = max(len(images) // 10, 1)
        val_x, val_y = images[:n_val], masks[:n_val]
        tr_x = jnp.asarray(images[n_val:])
        tr_y = jnp.asarray(masks[n_val:])
        batch_at = _make_batcher(cfg.data.batch, tr_x, tr_y)
        init_x = tr_x[:1]
    else:
        s = cfg.model.image_size
        rng = np.random.default_rng(cfg.train.seed)
        x = rng.normal(0, 0.1, (cfg.data.batch, s, s, 3)).astype(
            np.float32)
        y = np.zeros((cfg.data.batch, s, s), np.int32)
        for i in range(cfg.data.batch):
            cx, cy, r = rng.integers(8, s - 8), rng.integers(8, s - 8), 6
            yy, xx = np.mgrid[:s, :s]
            m = (yy - cy) ** 2 + (xx - cx) ** 2 < r * r
            y[i][m] = 1
            x[i][m] += 1.0
        tr_x, tr_y = jnp.asarray(x), jnp.asarray(y)
        val_x, val_y = x, y
        num_classes = 2
        batch_at = lambda i: (tr_x, tr_y)
        init_x = tr_x[:1]

    model = MODELS.build(cfg.model.name or "unet",
                         num_classes=num_classes, dtype=jnp.float32)
    variables = model.init(jax.random.key(0), init_x, train=False)
    params, stats = variables["params"], variables.get("batch_stats", {})

    def loss_fn(p, i):
        bx, by = batch_at(i)
        out = model.apply({"params": p, "batch_stats": stats}, bx,
                          train=False)
        logits = out[0] if isinstance(out, tuple) else out
        return L.cross_entropy(logits, by) + L.dice_loss(logits, by)

    params, first, last = _loop(loss_fn, params, cfg.train.steps,
                                cfg.train.lr)

    @jax.jit
    def predict(p, bx):
        out = model.apply({"params": p, "batch_stats": stats}, bx,
                          train=False)
        return jnp.argmax(out[0] if isinstance(out, tuple) else out, -1)

    mat = np.zeros((num_classes, num_classes), np.int64)
    for idx, n_real in _chunked_apply(len(val_x), cfg.data.batch):
        pred = predict(params, jnp.asarray(val_x[idx]))
        mat += np.asarray(confusion_matrix(
            pred[:n_real], jnp.asarray(val_y[idx][:n_real]),
            num_classes))
    miou = miou_from_confusion(mat)["miou"]
    print(f"task_metric miou={float(miou):.4f}")
    return 0 if np.isfinite(last) else 1


def run_mae(cfg: TaskConfig) -> int:
    from deeplearning_tpu.core.registry import MODELS

    if cfg.data.npz:
        # real-data pretraining: npz images, wraparound minibatches
        images = _load_npz_images(np.load(cfg.data.npz))
        tr_x = jnp.asarray(images)
        batch_at = _make_batcher(cfg.data.batch, tr_x)
        init_x = tr_x[:1]
    else:
        s = max(cfg.model.image_size, 32)
        tr_x = jnp.asarray(np.random.default_rng(cfg.train.seed).normal(
            size=(cfg.data.batch, s, s, 3)), jnp.float32)
        batch_at = lambda i: (tr_x,)
        init_x = tr_x
    model = MODELS.build(cfg.model.name or "mae_vit_small_patch16",
                         dtype=jnp.float32, depth=2, decoder_depth=2)
    variables = model.init(
        {"params": jax.random.key(0), "masking": jax.random.key(1)},
        init_x, train=False)

    def loss_fn(p, i):
        (bx,) = batch_at(i)
        loss, _, _ = model.apply(
            {"params": p}, bx, train=True,
            rngs={"masking": jax.random.fold_in(jax.random.key(5), i),
                  "dropout": jax.random.fold_in(jax.random.key(6), i)})
        return loss

    _, first, last = _loop(loss_fn, variables["params"], cfg.train.steps,
                           cfg.train.lr)
    print(f"task_metric mae_recon_loss={last:.4f}")
    return 0 if np.isfinite(last) else 1


def run_supcon(cfg: TaskConfig) -> int:
    from deeplearning_tpu.core.registry import MODELS
    from deeplearning_tpu.ops import losses as L

    rng = np.random.default_rng(cfg.train.seed)
    if cfg.data.npz:
        # real-data path: npz {images, labels}; the second view is a
        # horizontal flip (two-view supervised-contrastive batches)
        blob = np.load(cfg.data.npz)
        images = _load_npz_images(blob)
        labels_all = blob["labels"].astype(np.int32)
        order = _pk_order(labels_all)   # mixed-class batches (negatives)
        images, labels_all = images[order], labels_all[order]
        tr_x = jnp.asarray(images)
        tr_y = jnp.asarray(labels_all)
        batch_at = _make_batcher(cfg.data.batch, tr_x, tr_y)
        init_x = tr_x[:1]
        two_views = lambda bx: (bx, bx[:, :, ::-1, :])
    else:
        s = cfg.model.image_size
        labels = np.repeat(np.arange(max(cfg.data.batch // 2, 1)), 2)
        base = rng.normal(0, 0.2,
                          (len(labels), s, s, 3)).astype(np.float32)
        base[np.arange(len(labels)), labels * 3 % s,
             labels * 3 % s, :] += 2.0
        tr_x, tr_y = jnp.asarray(base), jnp.asarray(labels)
        batch_at = lambda i: (tr_x, tr_y)
        init_x = tr_x[:1]
        two_views = lambda bx: (bx, bx)     # two-view stand-in

    model = MODELS.build(cfg.model.name or "supcon_resnet18",
                         num_classes=cfg.model.num_classes,
                         dtype=jnp.float32)
    variables = model.init(jax.random.key(0), init_x, train=False)
    params, stats = variables["params"], variables.get("batch_stats", {})

    def loss_fn(p, i):
        bx, by = batch_at(i)
        va, vb = two_views(bx)
        za = model.apply({"params": p, "batch_stats": stats}, va,
                         train=False)
        zb = model.apply({"params": p, "batch_stats": stats}, vb,
                         train=False)
        feats = jnp.stack([za, zb], axis=1)
        return L.supcon_loss(feats, by)

    _, first, last = _loop(loss_fn, params, cfg.train.steps, cfg.train.lr)
    print(f"task_metric supcon_loss={last:.4f}")
    return 0 if np.isfinite(last) else 1


def run_metric(cfg: TaskConfig) -> int:
    from deeplearning_tpu.core.registry import MODELS
    from deeplearning_tpu.evaluation.retrieval import (cmc_map,
                                                       pairwise_distances)
    from deeplearning_tpu.ops import losses as L

    s = cfg.model.image_size
    rng = np.random.default_rng(cfg.train.seed)
    if cfg.data.npz:
        # real-data path: npz with images (N,H,W[,3]) and labels (N,)
        # identity labels; PK-style batches come from the wraparound
        # batcher over a label-sorted order (ids stay adjacent)
        blob = np.load(cfg.data.npz)
        images = _load_npz_images(blob)
        labels_all = blob["labels"].astype(np.int32)
        order = _pk_order(labels_all)
        images, labels_all = images[order], labels_all[order]
        n_id = int(labels_all.max()) + 1
        tr_x = jnp.asarray(images)
        tr_y = jnp.asarray(labels_all)
        batch_at = _make_batcher(cfg.data.batch, tr_x, tr_y)
        x, y = tr_x, tr_y          # eval embeds the whole set below
        init_x = tr_x[:1]
    else:
        n_id = cfg.model.num_classes
        labels = np.repeat(np.arange(n_id),
                           max(cfg.data.batch // n_id, 2))
        xx = rng.normal(0, 0.2, (len(labels), s, s, 3)).astype(
            np.float32)
        for i, lab in enumerate(labels):
            xx[i, :, lab * 4 % s:(lab * 4 % s) + 3, :] += 1.5
        x, y = jnp.asarray(xx), jnp.asarray(labels)
        batch_at = lambda i: (x, y)
        init_x = x[:1]

    model = MODELS.build(cfg.model.name or "arcface_resnet18",
                         num_classes=n_id, dtype=jnp.float32)
    variables = model.init(jax.random.key(0), init_x, train=False)
    params, stats = variables["params"], variables.get("batch_stats", {})

    def loss_fn(p, i):
        bx, by = batch_at(i)
        out = model.apply({"params": p, "batch_stats": stats}, bx,
                          train=False)
        emb, centers = out["embedding"], out["centers"]
        logits = L.arcface_logits(emb, centers, by)
        return L.cross_entropy(logits, by) + L.triplet_loss(emb, by,
                                                            margin=0.3)

    params, first, last = _loop(loss_fn, params, cfg.train.steps,
                                cfg.train.lr)

    @jax.jit
    def embed(p, bx):
        return model.apply({"params": p, "batch_stats": stats}, bx,
                           train=False)["embedding"]

    chunks = []
    for idx, n_real in _chunked_apply(x.shape[0], cfg.data.batch):
        chunks.append(np.asarray(embed(params,
                                       jnp.asarray(x[idx])))[:n_real])
    emb = np.concatenate(chunks)
    # interleave query/gallery so every query id appears in the gallery
    # (a contiguous split would separate the id sets -> vacuous metric)
    q, g = emb[0::2], emb[1::2]
    yq, yg = np.asarray(y)[0::2], np.asarray(y)[1::2]
    dist = pairwise_distances(q, g)
    res = cmc_map(dist, yq, yg)
    print(f"task_metric rank1={res['rank1']:.4f} mAP={res['mAP']:.4f}")
    return 0 if np.isfinite(last) else 1


def run_keypoints(cfg: TaskConfig) -> int:
    from deeplearning_tpu.core.registry import MODELS
    from deeplearning_tpu.evaluation.keypoints import (decode_heatmaps,
                                                       make_heatmap_targets,
                                                       pck)
    from deeplearning_tpu.ops import losses as L

    if cfg.data.npz:
        # real-data path: npz with images (N,H,W[,3]) and keypoints
        # (N,K,3) = (x, y, vis); heatmap targets precomputed host-side
        blob = np.load(cfg.data.npz)
        images = _load_npz_images(blob)
        kps_all = blob["keypoints"].astype(np.float32)     # (N, K, 3)
        h, w = images.shape[1:3]
        s = max(h, w)                       # pck threshold scale
        k = kps_all.shape[1]
        vis_all = kps_all[..., 2]
        n_val = max(len(images) // 10, 1)
        val = (images[:n_val], kps_all[:n_val], vis_all[:n_val])
        # targets only for the TRAINING slice (val scores via pck)
        targets = np.stack([
            make_heatmap_targets(kps_all[i, :, :2], vis_all[i],
                                 (h // 4, w // 4), stride=4)
            for i in range(n_val, len(images))])
        tr_x = jnp.asarray(images[n_val:])
        tr_t = jnp.asarray(targets)
        tr_v = jnp.asarray(vis_all[n_val:])
        batch_at = _make_batcher(cfg.data.batch, tr_x, tr_t, tr_v)
        init_x = tr_x[:1]
    else:
        s = max(cfg.model.image_size, 64)
        k = 4
        rng = np.random.default_rng(cfg.train.seed)
        kps = rng.uniform(8, s - 8,
                          (cfg.data.batch, k, 2)).astype(np.float32)
        vis = np.ones((cfg.data.batch, k), np.float32)
        x = np.zeros((cfg.data.batch, s, s, 3), np.float32)
        for i in range(cfg.data.batch):
            for j in range(k):
                xx, yy = int(kps[i, j, 0]), int(kps[i, j, 1])
                x[i, max(yy - 1, 0):yy + 2,
                  max(xx - 1, 0):xx + 2, j % 3] = 2.0
        target = jnp.asarray(np.stack([
            make_heatmap_targets(kps[i], vis[i], (s // 4, s // 4),
                                 stride=4)
            for i in range(cfg.data.batch)]))
        tr_x = jnp.asarray(x)
        vis_j = jnp.asarray(vis)
        batch_at = lambda i: (tr_x, target, vis_j)
        val = (x, np.concatenate([kps, vis[..., None]], -1), vis)
        init_x = tr_x[:1]

    model = MODELS.build(cfg.model.name or "hrnet_w18_keypoints",
                         num_classes=k, dtype=jnp.float32)
    variables = model.init(jax.random.key(0), init_x, train=False)
    params, stats = variables["params"], variables.get("batch_stats", {})

    def loss_fn(p, i):
        bx, bt, bv = batch_at(i)
        heat = model.apply({"params": p, "batch_stats": stats}, bx,
                           train=False)
        return L.heatmap_mse_loss(heat, bt, bv)

    params, first, last = _loop(loss_fn, params, cfg.train.steps,
                                cfg.train.lr)

    val_x, val_kp, val_vis = val

    @jax.jit
    def predict(p, bx):
        heat = model.apply({"params": p, "batch_stats": stats}, bx,
                           train=False)
        return decode_heatmaps(heat, stride=4)[0]

    scores = []
    for idx, n_real in _chunked_apply(len(val_x), cfg.data.batch):
        pred = np.asarray(predict(params, jnp.asarray(val_x[idx])))
        scores.extend(pck(pred[i], val_kp[idx[i], :, :2],
                          val_vis[idx[i]], threshold_px=s * 0.2)
                      for i in range(n_real))
    score = float(np.mean(scores))
    print(f"task_metric pck@0.2={float(score):.4f}")
    return 0 if np.isfinite(last) else 1


def run_stereo(cfg: TaskConfig) -> int:
    from deeplearning_tpu.core.registry import MODELS
    from deeplearning_tpu.models.stereo.madnet import photometric_loss

    rng = np.random.default_rng(cfg.train.seed)
    if cfg.data.npz:
        # real-data path: npz with left/right (N,H,W[,3]) rectified pairs
        blob = np.load(cfg.data.npz)
        left = _load_npz_images({"images": blob["left"]})
        right = _load_npz_images({"images": blob["right"]})
        left, right = jnp.asarray(left), jnp.asarray(right)
    else:
        s = max(cfg.model.image_size, 64)
        b = max(cfg.data.batch, 1)
        left = rng.normal(0, 1, (b, s, s, 3)).astype(np.float32)
        right = np.roll(left, -3, axis=2)
        left, right = jnp.asarray(left), jnp.asarray(right)

    model = MODELS.build(cfg.model.name or "madnet", dtype=jnp.float32)
    params = model.init(jax.random.key(0), left, right)["params"]

    def loss_fn(p, i):
        out = model.apply({"params": p}, left, right)
        return photometric_loss(left, right, out["disparity"])

    _, first, last = _loop(loss_fn, params, cfg.train.steps, cfg.train.lr)
    print(f"task_metric photometric={last:.4f}")
    return 0 if np.isfinite(last) else 1


def run_stereo_online(cfg: TaskConfig) -> int:
    """MAD online adaptation (Stereo_Online_Adaptation.py modes): per
    'frame', sample a subset of blocks with the reward-softmax sampler
    and backprop only through them (grad mask)."""
    import optax
    from deeplearning_tpu.core.registry import MODELS
    from deeplearning_tpu.models.stereo.madnet import (MADSampler,
                                                       photometric_loss)

    rng = np.random.default_rng(cfg.train.seed)
    if cfg.data.npz:
        # real-data path: npz {left, right} frame sequences; online
        # adaptation consumes frame i%N at step i (the video-stream
        # semantics of Stereo_Online_Adaptation)
        blob = np.load(cfg.data.npz)
        lefts = jnp.asarray(_load_npz_images({"images": blob["left"]}))
        rights = jnp.asarray(_load_npz_images({"images": blob["right"]}))
        frame_at = lambda i: (lefts[i % lefts.shape[0]][None],
                              rights[i % rights.shape[0]][None])
        left0, right0 = frame_at(0)
    else:
        s = max(cfg.model.image_size, 64)
        base = rng.normal(0, 1, (max(cfg.data.batch, 1), s, s, 3)).astype(
            np.float32)
        left0 = jnp.asarray(base)
        right0 = jnp.asarray(np.roll(base, -3, axis=2))
        frame_at = lambda i: (left0, right0)

    model = MODELS.build(cfg.model.name or "madnet", dtype=jnp.float32)
    params = model.init(jax.random.key(0), left0, right0)["params"]
    tx = optax.adam(cfg.train.lr)
    opt = tx.init(params)

    @jax.jit
    def step(params, opt, mask, left, right):
        def lf(p):
            out = model.apply({"params": p}, left, right)
            return photometric_loss(left, right, out["disparity"])
        loss, g = jax.value_and_grad(lf)(params)
        g = jax.tree.map(lambda gg, m: gg * m, g, mask)
        up, opt = tx.update(g, opt, params)
        # mask the UPDATE too: Adam's momentum would otherwise keep
        # moving deselected blocks for many frames after selection
        up = jax.tree.map(lambda u, m: u * m, up, mask)
        return optax.apply_updates(params, up), opt, loss

    sampler = MADSampler(list(params), sample_n=2, mode="probabilistic",
                         seed=cfg.train.seed)
    first = last = None
    for i in range(cfg.train.steps):
        selected = sampler.sample()
        mask = sampler.grad_mask(params, selected)
        fl, fr = frame_at(i)
        params, opt, loss = step(params, opt, mask, fl, fr)
        last = float(loss)
        sampler.update(selected, last)
        if first is None:
            first = last
        if i % max(cfg.train.steps // 5, 1) == 0:
            print(f"frame {i}: loss={last:.4f} blocks={selected}",
                  flush=True)
    if last is None:
        print("no steps run")
        return 1
    print(f"loss {first:.4f} -> {last:.4f}")
    print(f"task_metric photometric_online={last:.4f}")
    return 0 if np.isfinite(last) else 1


RUNNERS = {
    "segmentation": run_segmentation,
    "mae": run_mae,
    "supcon": run_supcon,
    "metric": run_metric,
    "keypoints": run_keypoints,
    "stereo": run_stereo,
    "stereo_online": run_stereo_online,
}


def main(argv=None) -> int:
    from deeplearning_tpu.core.compile_cache import enable_compile_cache
    enable_compile_cache()   # step compiles are once-per-machine, not per-run
    from deeplearning_tpu.core.config import config_cli, pop_flag

    argv = list(sys.argv[1:] if argv is None else argv)
    task = pop_flag(argv, "--task")
    if task not in RUNNERS:
        raise SystemExit(f"--task must be one of {list(RUNNERS)}")
    cfg = config_cli(TaskConfig(), argv, description=__doc__)
    return RUNNERS[task](cfg)


if __name__ == "__main__":
    raise SystemExit(main())
