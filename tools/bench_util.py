"""Shared timing helpers for the TPU microbenchmarks."""

import time

import jax.numpy as jnp


def sync(x):
    # D2H scalar fetch — block_until_ready is unreliable on this
    # remote-tunnel backend; a host fetch always syncs
    jnp.asarray(x).ravel()[0].item()


def bench(fn, args, n=30, warmup=3):
    for _ in range(warmup):
        out = fn(*args)
    sync(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    sync(out)
    return (time.perf_counter() - t0) / n
