"""Shared timing helpers for the TPU microbenchmarks."""

import time

import jax
import jax.numpy as jnp


def sync(x):
    # D2H scalar fetch — block_until_ready is unreliable on this
    # remote-tunnel backend; a host fetch always syncs. Accepts any
    # pytree: syncs on its first leaf.
    jnp.asarray(jax.tree.leaves(x)[0]).ravel()[0].astype(
        jnp.float32).item()


def bench(fn, args, n=30, warmup=3):
    for _ in range(warmup):
        out = fn(*args)
    sync(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    sync(out)
    return (time.perf_counter() - t0) / n
