"""Shared timing helpers for the TPU microbenchmarks."""

import os
import time

import jax
import jax.numpy as jnp

# Persistent XLA compile cache shared by every perf tool: a wedge-prone
# tunnel means each completed compile should only ever be paid once per
# round. (Mirror of the block in bench.py, which stays import-free of
# tools/ — keep the two in sync.)
try:
    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
except Exception:  # noqa: BLE001 - cache is an optimization, never fatal
    pass


def sync(x):
    # D2H scalar fetch — block_until_ready is unreliable on this
    # remote-tunnel backend; a host fetch always syncs. Accepts any
    # pytree: syncs on its first leaf.
    jnp.asarray(jax.tree.leaves(x)[0]).ravel()[0].astype(
        jnp.float32).item()


def bench(fn, args, n=30, warmup=3):
    for _ in range(warmup):
        out = fn(*args)
    sync(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    sync(out)
    return (time.perf_counter() - t0) / n
