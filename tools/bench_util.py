"""Shared timing helpers for the TPU microbenchmarks."""

import json
import os
import sys
import time

import jax
import jax.numpy as jnp

# Persistent XLA compile cache shared by every perf tool: a wedge-prone
# tunnel means each completed compile should only ever be paid once per
# round. Canonical wiring lives in deeplearning_tpu.core.compile_cache
# (same repo-root .jax_cache dir bench.py uses).
try:
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from deeplearning_tpu.core.compile_cache import enable_compile_cache
    enable_compile_cache()
except Exception:  # noqa: BLE001 - cache is an optimization, never fatal
    pass


def append_result(path, variant, *, batch, step_ms, img_per_s, mfu_pct,
                  **extra):
    """Append one measurement to mfu_results.jsonl (single shared schema
    for perf_sweep.py and mfu_push.py rows).

    Stamps the fields every consumer needs to interpret a row — device,
    UTC time, and the GELU numerics mode (rows before/after the round-5
    tanh-default switch differ by ~3.8 MFU points on ViT). Returns the
    record so callers can print exactly what was written."""
    from deeplearning_tpu.core import numerics
    rec = {
        "variant": variant,
        "batch": batch,
        "step_ms": round(step_ms, 2),
        "img_per_s": round(img_per_s, 1),
        "mfu_pct": round(mfu_pct, 2),
        "gelu": "erf" if numerics.exact_enabled() else "tanh",
        "device": jax.devices()[0].device_kind,
        "utc": time.strftime("%Y-%m-%d %H:%M:%S", time.gmtime()),
    }
    rec.update(extra)
    with open(path, "a") as f:
        f.write(json.dumps(rec) + "\n")
    return rec


def append_op_result(path, op, *, n, ms, **extra):
    """Append one OP-level microbench row (the ``--set detect`` sweep and
    bench.py's CPU fallback section) to the same jsonl as the step-level
    rows. Op rows carry {op, n, ms} instead of batch/step_ms/img_per_s so
    consumers can split the two schemas with ``"op" in rec``."""
    rec = {
        "op": op,
        "n": int(n),
        "ms": round(float(ms), 3),
        "device": jax.devices()[0].device_kind,
        "utc": time.strftime("%Y-%m-%d %H:%M:%S", time.gmtime()),
    }
    rec.update(extra)
    with open(path, "a") as f:
        f.write(json.dumps(rec) + "\n")
    return rec


def feed_stats(source):
    """Device-feed telemetry columns for bench rows.

    Accepts a ``DevicePrefetcher`` (calls its ``stats()``) or an
    already-built stats dict (e.g. ``Trainer.throughput_stats``) and
    returns the input-feed subset every perf row should carry —
    ``h2d_wait_frac`` + ``prefetch_occupancy`` are what let the next
    on-chip run attribute an MFU delta to feed overlap vs step compute."""
    stats = source.stats() if callable(getattr(source, "stats", None)) \
        else dict(source)
    keys = ("h2d_wait_frac", "prefetch_occupancy", "prefetch_depth",
            "data_wait_frac")
    return {k: round(float(stats[k]), 4) for k in keys if k in stats}


def sync(x):
    # D2H scalar fetch — block_until_ready is unreliable on this
    # remote-tunnel backend; a host fetch always syncs. Accepts any
    # pytree: syncs on its first leaf.
    jnp.asarray(jax.tree.leaves(x)[0]).ravel()[0].astype(
        jnp.float32).item()


def bench(fn, args, n=30, warmup=3):
    for _ in range(warmup):
        out = fn(*args)
    sync(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    sync(out)
    return (time.perf_counter() - t0) / n


def obs_overhead(step_fn, args, n=30, reps=3, budget_pct=2.0):
    """A/B the span-instrumented hot loop: the same ``step_fn(*args)``
    loop timed with tracing disabled vs enabled (each step bracketed in
    a ``step_span``, the Trainer's per-step instrumentation). Min-of-reps
    per arm absorbs host jitter — this measures the instrumentation
    floor, not scheduler noise. Returns the README "Observability
    policy" contract numbers: ``within_budget`` is the <=``budget_pct``%
    overhead assertion the bench smoke rides on."""
    from deeplearning_tpu.obs import spans

    def loop(instrument):
        out = None
        t0 = time.perf_counter()
        for i in range(n):
            if instrument:
                with spans.step_span("dispatch", i):
                    out = step_fn(*args)
            else:
                out = step_fn(*args)
        sync(out)
        return time.perf_counter() - t0

    # warmup: compile + touch both code paths once
    sync(step_fn(*args))
    was_enabled = spans.enabled()
    off = ms_on = float("inf")
    try:
        for _ in range(reps):
            spans.disable()
            off = min(off, loop(False))
            spans.enable()
            ms_on = min(ms_on, loop(True))
    finally:
        spans.enable() if was_enabled else spans.disable()
    overhead_pct = (ms_on - off) / off * 100.0 if off > 0 else 0.0
    return {
        "spans_off_ms": round(off / n * 1e3, 4),
        "spans_on_ms": round(ms_on / n * 1e3, 4),
        "overhead_pct": round(overhead_pct, 3),
        "within_budget": overhead_pct <= budget_pct,
        "budget_pct": budget_pct,
    }


def metrics_overhead(step_fn, args, n=30, reps=3, budget_pct=2.0):
    """A/B the metrics-instrumented hot loop: the same ``step_fn(*args)``
    loop with the registry disabled vs enabled, each step paying the
    per-step push a real instrumented loop pays (one counter ``inc`` +
    one histogram ``observe``). Min-of-reps per arm, same <=2% contract
    shape as ``obs_overhead`` — the fleet scrape surface must cost no
    more than the span tracer it sits next to."""
    from deeplearning_tpu.obs import metrics

    def loop():
        out = None
        t0 = time.perf_counter()
        for i in range(n):
            metrics.inc("dltpu_bench_steps_total")
            metrics.observe("dltpu_bench_step_ms", float(i))
            out = step_fn(*args)
        sync(out)
        return time.perf_counter() - t0

    sync(step_fn(*args))           # warmup: compile once
    was_enabled = metrics.enabled()
    off = on = float("inf")
    try:
        for _ in range(reps):
            metrics.disable()
            off = min(off, loop())
            metrics.enable()
            on = min(on, loop())
    finally:
        metrics.enable() if was_enabled else metrics.disable()
    overhead_pct = (on - off) / off * 100.0 if off > 0 else 0.0
    return {
        "metrics_off_ms": round(off / n * 1e3, 4),
        "metrics_on_ms": round(on / n * 1e3, 4),
        "overhead_pct": round(overhead_pct, 3),
        "within_budget": overhead_pct <= budget_pct,
        "budget_pct": budget_pct,
    }


def recovery_overhead(step_fn, args, state, n=30, reps=3, budget_pct=2.0):
    """A/B the self-healing hooks' IDLE cost: the same ``step_fn(*args)``
    loop bare vs with the Trainer's per-step recovery hooks — the
    ``maybe_snapshot`` cadence check and the ``cooldown_scale`` compare
    — at a cadence that never actually snapshots (anchor_every far past
    n), which is the steady-state cost every healthy step pays. Same
    min-of-reps discipline and <=``budget_pct``% contract shape as
    ``obs_overhead``."""
    from deeplearning_tpu.train.recovery import (RecoveryManager,
                                                 RecoveryPolicy)

    mgr = RecoveryManager(RecoveryPolicy(anchor_every=10 ** 9))

    def loop(with_hooks):
        out = None
        t0 = time.perf_counter()
        for i in range(n):
            if with_hooks:
                mgr.maybe_snapshot(i, state)
                mgr.cooldown_scale(i)
                out = step_fn(*args)
            else:
                out = step_fn(*args)
        sync(out)
        return time.perf_counter() - t0

    sync(step_fn(*args))           # warmup: compile once
    off = on = float("inf")
    for _ in range(reps):
        off = min(off, loop(False))
        on = min(on, loop(True))
    overhead_pct = (on - off) / off * 100.0 if off > 0 else 0.0
    return {
        "recovery_off_ms": round(off / n * 1e3, 4),
        "recovery_on_ms": round(on / n * 1e3, 4),
        "overhead_pct": round(overhead_pct, 3),
        "within_budget": overhead_pct <= budget_pct,
        "budget_pct": budget_pct,
    }
