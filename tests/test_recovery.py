"""Self-healing runs (PR 7): divergence rollback-and-skip, bad-batch
quarantine, hardened checkpoint I/O, and the serving wedge surface."""

import json
import os
import sys
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning_tpu.core.checkpoint import CheckpointManager
from deeplearning_tpu.core.registry import MODELS
from deeplearning_tpu.data import (ArraySource, DataLoader, PoisonedData,
                                   QuarantineLog, quarantinable)
from deeplearning_tpu.elastic import faults
from deeplearning_tpu.elastic.preempt import agree_preempt_step
from deeplearning_tpu.train import (RecoveryExhausted, RecoveryManager,
                                    RecoveryPolicy, TrainState,
                                    make_eval_step, make_train_step)
from deeplearning_tpu.train import recovery as recovery_mod
from deeplearning_tpu.train.classification import make_loss_fn, make_metric_fn
from deeplearning_tpu.train.optim import build_optimizer
from deeplearning_tpu.train.schedules import build_schedule
from deeplearning_tpu.train.trainer import Trainer

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))


def synthetic_cls(n=96, seed=0):
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 4, n).astype(np.int32)
    images = rng.normal(0, 0.1, (n, 16, 16, 1)).astype(np.float32)
    for i, l in enumerate(labels):
        images[i, :, l * 4:(l + 1) * 4, 0] += 2.0
    return images, labels


def make_state(seed=0):
    model = MODELS.build("mnist_fcn", num_classes=4, dtype=jnp.float32)
    params = model.init(jax.random.key(seed),
                        jnp.zeros((1, 16, 16, 1)))["params"]
    tx = build_optimizer(
        "sgd", build_schedule("constant", base_lr=0.1), params=params)
    return TrainState.create(apply_fn=model.apply, params=params, tx=tx)


def make_trainer(train_step=None, *, epochs=1, log_every=100, n=96,
                 metrics_lag=None, batch=32, **trainer_kw):
    images, labels = synthetic_cls(n)
    loader = DataLoader(ArraySource(image=images, label=labels),
                        global_batch=batch, seed=0)
    eval_loader = DataLoader(ArraySource(image=images, label=labels),
                             global_batch=batch, shuffle=False)
    return Trainer(
        state=make_state(),
        train_step=train_step or make_train_step(make_loss_fn(),
                                                 donate=False),
        train_loader=loader,
        eval_step=make_eval_step(make_metric_fn(ks=(1,))),
        eval_loader=eval_loader,
        epochs=epochs, log_every=log_every, metrics_lag=metrics_lag,
        **trainer_kw)


class _FlakySource:
    """ArraySource-alike whose __getitem__ raises on chosen indices —
    the corrupt-JPEG stand-in the quarantine path must survive."""

    def __init__(self, n=64, bad=(), exc=ValueError):
        self.images, self.labels = synthetic_cls(n)
        self.bad = set(bad)
        self.exc = exc

    def __len__(self):
        return len(self.labels)

    def __getitem__(self, idx):
        idx_arr = np.atleast_1d(np.asarray(idx))
        hit = self.bad.intersection(int(i) for i in idx_arr)
        if hit:
            raise self.exc(f"decode failed for sample {sorted(hit)}")
        return {"image": self.images[idx], "label": self.labels[idx]}


# --------------------------------------------------------- RecoveryManager
class TestRecoveryManager:
    def tree(self, v=0.0):
        return {"w": jnp.full((3,), float(v))}

    def test_promotion_requires_strictly_newer_finite_entry(self):
        mgr = RecoveryManager(RecoveryPolicy(anchor_every=2))
        mgr.seed(0, self.tree(0))
        mgr.maybe_snapshot(2, self.tree(2))
        assert mgr.anchor_step == 0           # pending, not promoted
        mgr.mark_verified(2)                  # entry AT 2 vouches for 1,
        assert mgr.anchor_step == 0           # not for state 2 itself
        mgr.mark_verified(3)
        assert mgr.anchor_step == 2

    def test_snapshot_cadence_is_anchor_every(self):
        mgr = RecoveryManager(RecoveryPolicy(anchor_every=5))
        mgr.seed(0, self.tree())
        for step in range(1, 12):
            mgr.maybe_snapshot(step, self.tree(step))
        assert [s for s, _ in mgr._pending] == [5, 10]

    def test_rollback_returns_anchor_copy_and_skips_window(self):
        mgr = RecoveryManager(RecoveryPolicy(anchor_every=2,
                                             cooldown_steps=3,
                                             lr_decay=0.25))
        mgr.seed(0, self.tree(0))
        mgr.maybe_snapshot(2, self.tree(2))
        mgr.mark_verified(3)
        step, state = mgr.on_divergence(4)
        assert step == 2
        assert float(state["w"][0]) == 2.0
        assert mgr.skipped == [(2, 4)]
        # cooldown covers [anchor, anchor + cooldown_steps)
        assert mgr.cooldown_scale(3) == 0.25
        assert mgr.cooldown_scale(5) is None
        # the anchor survives: a second divergence in the same window
        # rolls back to the SAME state even if the first copy was mutated
        state["w"] = state["w"] * 0 - 1
        _, again = mgr.on_divergence(4)
        assert float(again["w"][0]) == 2.0
        assert mgr.rollbacks == 2

    def test_budget_exhaustion_raises(self):
        mgr = RecoveryManager(RecoveryPolicy(anchor_every=1,
                                             max_recoveries=2))
        mgr.seed(0, self.tree())
        mgr.on_divergence(1)
        mgr.on_divergence(2)
        with pytest.raises(RecoveryExhausted, match="already spent"):
            mgr.on_divergence(3)

    def test_windowed_budget_forgets_old_rollbacks(self):
        mgr = RecoveryManager(RecoveryPolicy(anchor_every=1,
                                             max_recoveries=1,
                                             budget_steps=10))
        mgr.seed(0, self.tree())
        mgr.on_divergence(1)
        with pytest.raises(RecoveryExhausted):
            mgr.on_divergence(5)              # inside the window
        assert mgr.on_divergence(20)[0] == 0  # step 1 aged out

    def test_no_anchor_raises(self):
        mgr = RecoveryManager(RecoveryPolicy())
        with pytest.raises(RecoveryExhausted, match="no verified anchor"):
            mgr.on_divergence(3)

    def test_abort_mode_policy_rejected_values(self):
        with pytest.raises(ValueError, match="rollback|abort"):
            RecoveryPolicy(mode="retry")

    def test_damp_update_is_leafwise_lerp(self):
        old = {"w": jnp.zeros((4,))}
        new = {"w": jnp.full((4,), 8.0)}
        out = recovery_mod.damp_update(old, new, 0.25)
        np.testing.assert_allclose(np.asarray(out["w"]), 2.0)


# ----------------------------------------------------------- fault grammar
class TestSelfHealingFaults:
    def test_parse_new_kinds(self):
        specs = faults.parse_faults(
            "nan@step:4;bad_sample@step:9;ckpt_corrupt@checkpoint:2")
        assert [(s.kind, s.site, s.at_step) for s in specs] == [
            ("nan", "step", 4), ("bad_sample", "step", 9),
            ("ckpt_corrupt", "checkpoint", 2)]

    def test_consumed_kinds_never_fire_but_consume_once(self, monkeypatch):
        monkeypatch.setenv(faults.ENV_VAR, "nan@step:3")
        monkeypatch.delenv(faults.ATTEMPT_VAR, raising=False)
        faults.reset()
        try:
            faults.maybe_fire("step", step=10)         # no delivery
            assert not faults.consume("nan", "step", step=2)  # below floor
            assert faults.consume("nan", "step", step=3)
            assert not faults.consume("nan", "step", step=4)  # once only
        finally:
            faults.reset()


# -------------------------------------------------------------- quarantine
class TestQuarantine:
    def test_serial_loader_quarantines_and_fills_batch(self, tmp_path):
        qlog = QuarantineLog(str(tmp_path / "quarantine.jsonl"))
        src = _FlakySource(n=64, bad=(3, 17))
        loader = DataLoader(src, global_batch=8, shuffle=False,
                            quarantine=qlog)
        batches = list(loader)
        assert len(batches) == 8
        for b in batches:                      # batches stay full-shape
            assert b["image"].shape[0] == 8
        assert qlog.quarantined == 2
        rows = [json.loads(line) for line in
                open(tmp_path / "quarantine.jsonl")]
        assert sorted(r["index"] for r in rows) == [3, 17]
        assert all("decode failed" in r["error"] for r in rows)

    def test_parallel_loader_quarantines(self, tmp_path):
        qlog = QuarantineLog(str(tmp_path / "q.jsonl"))
        src = _FlakySource(n=64, bad=(5,))
        loader = DataLoader(src, global_batch=8, shuffle=False,
                            num_workers=2, quarantine=qlog)
        batches = list(loader)
        assert len(batches) == 8
        assert qlog.quarantined == 1

    def test_escalation_raises_poisoned_data(self, tmp_path):
        qlog = QuarantineLog(str(tmp_path / "q.jsonl"),
                             max_poisoned_frac=0.05, min_samples=16)
        src = _FlakySource(n=64, bad=set(range(0, 64, 4)))   # 25% bad
        loader = DataLoader(src, global_batch=8, shuffle=False,
                            quarantine=qlog)
        with pytest.raises(PoisonedData, match="poisoned"):
            list(loader)

    def test_bad_sample_fault_routes_through_quarantine(self, tmp_path,
                                                        monkeypatch):
        monkeypatch.setenv(faults.ENV_VAR, "bad_sample@step:5")
        faults.reset()
        try:
            qlog = QuarantineLog(str(tmp_path / "q.jsonl"))
            src = _FlakySource(n=32, bad=())
            loader = DataLoader(src, global_batch=8, shuffle=False,
                                quarantine=qlog)
            batches = list(loader)
        finally:
            faults.reset()
        assert len(batches) == 4
        assert qlog.quarantined == 1
        row = json.loads(open(tmp_path / "q.jsonl").readline())
        assert "InjectedBadSample" in row["error"]

    def test_parallel_nonquarantinable_reraises_with_traceback(self):
        src = _FlakySource(n=32, bad=(9,), exc=MemoryError)
        loader = DataLoader(src, global_batch=8, shuffle=False,
                            num_workers=2,
                            quarantine=QuarantineLog(os.devnull))
        with pytest.raises(MemoryError) as ei:
            list(loader)
        # original worker traceback survives the thread hop
        assert any("_fetch_one" in str(f) for f in ei.traceback)

    def test_serial_no_quarantine_keeps_seed_behavior(self):
        src = _FlakySource(n=32, bad=(9,))
        loader = DataLoader(src, global_batch=8, shuffle=False)
        with pytest.raises(ValueError, match="decode failed"):
            list(loader)

    def test_quarantinable_predicate(self):
        assert quarantinable(ValueError("x"))
        assert not quarantinable(MemoryError())
        assert not quarantinable(PoisonedData("x"))
        assert not quarantinable(KeyboardInterrupt())

    def test_reseed_changes_order(self):
        images, labels = synthetic_cls(32)
        loader = DataLoader(ArraySource(image=images, label=labels),
                            global_batch=8, seed=0)
        first = np.concatenate([b["label"] for b in loader])
        loader.reseed(1)
        second = np.concatenate([b["label"] for b in loader])
        assert sorted(first.tolist()) == sorted(second.tolist())
        assert first.tolist() != second.tolist()


# ------------------------------------------------------ checkpoint hardening
class TestCheckpointHardening:
    def save_steps(self, tmp_path, steps=(1, 2, 3)):
        state = make_state()
        ckpt = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=8)
        for s in steps:
            state = state.replace(step=jnp.asarray(s, jnp.int32))
            ckpt.save(s, state)
        ckpt.wait_until_finished()
        return ckpt, state

    def test_checksum_sidecar_and_verify(self, tmp_path):
        ckpt, _ = self.save_steps(tmp_path)
        sidecar = tmp_path / "ckpt" / "checksums.json"
        assert sidecar.exists()
        table = json.loads(sidecar.read_text())
        assert set(table) == {"1", "2", "3"}
        assert all(ckpt.verify_step(s) for s in (1, 2, 3))
        faults.corrupt_checkpoint(str(tmp_path / "ckpt"), 3)
        assert not ckpt.verify_step(3)
        assert ckpt.verify_step(2)

    def test_restore_falls_back_to_newest_intact_step(self, tmp_path):
        ckpt, state = self.save_steps(tmp_path)
        faults.corrupt_checkpoint(str(tmp_path / "ckpt"), 3)
        restored, step = ckpt.restore_verified(make_state(seed=1))
        assert step == 2
        assert int(restored.step) == 2
        # bitwise parity with a direct restore of the intact step
        direct = ckpt.restore(make_state(seed=1), step=2)
        for a, b in zip(jax.tree.leaves(restored), jax.tree.leaves(direct)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # the corrupt dir is quarantined out of Orbax's step scan
        assert (tmp_path / "ckpt" / "corrupt-3").exists()
        assert not (tmp_path / "ckpt" / "3").exists()
        assert ckpt.latest_step() == 2

    def test_unverifiable_step_is_trusted(self, tmp_path):
        # steps without a sidecar entry (pre-PR-7 checkpoints) restore
        ckpt, _ = self.save_steps(tmp_path, steps=(1,))
        os.remove(tmp_path / "ckpt" / "checksums.json")
        assert ckpt.verify_step(1)
        restored, step = ckpt.restore_verified(make_state(seed=1))
        assert step == 1 and int(restored.step) == 1

    def test_all_steps_corrupt_returns_none(self, tmp_path):
        ckpt, _ = self.save_steps(tmp_path, steps=(1, 2))
        faults.corrupt_checkpoint(str(tmp_path / "ckpt"), 1)
        faults.corrupt_checkpoint(str(tmp_path / "ckpt"), 2)
        restored, step = ckpt.restore_verified(make_state(seed=1))
        assert restored is None and step == 0

    def test_auto_resume_routes_through_verification(self, tmp_path):
        ckpt, _ = self.save_steps(tmp_path)
        faults.corrupt_checkpoint(str(tmp_path / "ckpt"), 3)
        _, step = ckpt.auto_resume(make_state(seed=1))
        assert step == 2


# --------------------------------------------------------- trainer e2e
class TestTrainerSelfHealing:
    def test_nan_fault_rolls_back_and_completes(self, tmp_path,
                                                monkeypatch):
        monkeypatch.setenv(faults.ENV_VAR, "nan@step:3")
        monkeypatch.delenv(faults.ATTEMPT_VAR, raising=False)
        faults.reset()
        try:
            trainer = make_trainer(
                epochs=2, log_every=2, metrics_lag=1, n=96, batch=32,
                workdir=str(tmp_path), obs=True,
                recovery=RecoveryPolicy(anchor_every=2, cooldown_steps=2))
            trainer.train()                    # must NOT raise
        finally:
            faults.reset()
        assert trainer._recovery.rollbacks >= 1
        rec = json.loads((tmp_path / "flightrec.json").read_text())
        kinds = {e["kind"] for e in rec["events"]}
        assert {"fault_injected", "divergence", "recovery",
                "recovery_summary"} <= kinds
        assert rec["reason"] == "recovered"
        recov = next(e for e in rec["events"] if e["kind"] == "recovery")
        assert recov["anchor_step"] < recov["step"]

    def test_abort_mode_keeps_seed_behavior(self, monkeypatch):
        monkeypatch.setenv(faults.ENV_VAR, "nan@step:3")
        faults.reset()
        try:
            trainer = make_trainer(epochs=2, log_every=2, metrics_lag=1,
                                   n=96, batch=32, recovery=None)
            with pytest.raises(FloatingPointError, match="non-finite"):
                trainer.train()
        finally:
            faults.reset()

    def test_exhausted_budget_falls_through_to_abort(self, monkeypatch):
        # every step poisons -> rollback budget spends out -> seed abort
        monkeypatch.setenv(faults.ENV_VAR,
                           "nan@step:2;nan@step:2;nan@step:2")
        faults.reset()
        try:
            trainer = make_trainer(
                epochs=4, log_every=1, metrics_lag=1, n=32, batch=32,
                recovery=RecoveryPolicy(anchor_every=1, max_recoveries=1,
                                        cooldown_steps=0))
            with pytest.raises(FloatingPointError, match="non-finite"):
                trainer.train()
        finally:
            faults.reset()
        assert trainer._recovery.rollbacks == 1

    def test_ckpt_corrupt_fault_resumes_from_intact_step(self, tmp_path,
                                                         monkeypatch):
        monkeypatch.setenv(faults.ENV_VAR, "ckpt_corrupt@checkpoint:4")
        faults.reset()
        try:
            trainer = make_trainer(epochs=2, n=96, batch=32,
                                   workdir=str(tmp_path))
            trainer.train()                    # saves at steps 3 and 6;
        finally:                               # the step-6 dir is garbled
            faults.reset()
        ckpt = CheckpointManager(str(tmp_path / "ckpt"))
        assert not ckpt.verify_step(6)
        restored, step = ckpt.auto_resume(make_state(seed=1))
        assert step == 3
        direct = ckpt.restore(make_state(seed=1), step=3)
        for a, b in zip(jax.tree.leaves(restored), jax.tree.leaves(direct)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ----------------------------------------------------- multi-host agreement
class TestPreemptAgreement:
    def test_single_host_is_identity(self):
        assert agree_preempt_step(7) == 7


# --------------------------------------------------------- serve wedge/beat
class TestServeSupervision:
    @pytest.fixture(scope="class")
    def engine(self):
        from deeplearning_tpu.serve import InferenceEngine
        return InferenceEngine("mnist_fcn", num_classes=10,
                               image_size=28, batch_buckets=(1, 4))

    def test_dispatch_touches_heartbeat(self, engine):
        from deeplearning_tpu.elastic import heartbeat as hb
        from deeplearning_tpu.serve import MicroBatcher
        beat = hb.Heartbeat()
        with MicroBatcher(engine, heartbeat=beat) as mb:
            h = mb.submit(np.zeros((28, 28, 3), np.float32))
            h.result(timeout=10.0)
            assert mb.dispatched >= 1
        assert beat.phase == "dispatch"
        assert beat.step >= 1 and beat.activity >= 1

    def test_idle_server_never_wedges(self, engine):
        from deeplearning_tpu.serve import MicroBatcher
        from deeplearning_tpu.serve.health import DispatchWatch, health
        with MicroBatcher(engine) as mb:
            watch = DispatchWatch(mb, deadline_s=0.0)
            for _ in range(3):
                assert watch.verdict() != "wedged"
            code, payload = health(engine, mb, wedge=watch)
            assert code == 200 and payload["wedged"] is False

    def test_frozen_dispatch_reports_wedged_over_http(self, engine):
        import urllib.request
        import urllib.error
        from serve import serve_http

        from deeplearning_tpu.serve import MicroBatcher
        mb = MicroBatcher(engine)
        server = None
        try:
            # freeze the dispatch thread, then queue work: the classic
            # wedge signature (connections answered, counter frozen)
            mb._stop.set()
            mb._thread.join(5.0)
            assert not mb._thread.is_alive()
            mb.submit(np.zeros((28, 28, 3), np.float32))
            server = serve_http(mb, "classify", 28, {}, 5, 5.0, 0, 0.0)
            threading.Thread(target=server.serve_forever,
                             daemon=True).start()
            base = f"http://127.0.0.1:{server.server_port}"
            payload = None
            for _ in range(4):      # detector needs repeat observations
                try:
                    with urllib.request.urlopen(base + "/healthz",
                                                timeout=5) as r:
                        payload = json.loads(r.read())
                except urllib.error.HTTPError as e:
                    payload = json.loads(e.read())
                    if payload["status"] == "wedged":
                        break
            assert payload["status"] == "wedged"
            assert payload["wedged"] is True
            assert payload["stalled_s"] >= 0.0
            assert payload["queue_depth"] >= 1
        finally:
            if server is not None:
                server.shutdown()
                server.server_close()
            mb._q.queue.clear()
            mb.close()
