"""Faster R-CNN: proposal generation, two-stage losses, postprocess."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning_tpu.core.registry import MODELS
from deeplearning_tpu.models.detection.faster_rcnn import (
    fasterrcnn_anchors, fasterrcnn_postprocess, generate_proposals,
    roi_head_loss, rpn_loss, sample_rois)

IMG = 64
NC = 4   # incl background


@pytest.fixture(scope="module")
def setup():
    model = MODELS.build("fasterrcnn_resnet18_fpn", num_classes=NC,
                         dtype=jnp.float32)
    x = jnp.zeros((1, IMG, IMG, 3))
    variables = model.init(jax.random.key(0), x, train=False)
    anchors = jnp.asarray(fasterrcnn_anchors((IMG, IMG)))
    return model, variables, anchors


class TestFasterRCNN:
    def test_rpn_outputs_and_anchor_count(self, setup):
        model, variables, anchors = setup
        out = model.apply(variables, jnp.zeros((2, IMG, IMG, 3)),
                          train=False)
        a = anchors.shape[0]
        assert out["rpn_obj"].shape == (2, a)
        assert out["rpn_deltas"].shape == (2, a, 4)
        assert sum(out["level_counts"]) == a

    def test_proposals_fixed_shape(self, setup):
        model, variables, anchors = setup
        out = model.apply(variables, jnp.zeros((2, IMG, IMG, 3)),
                          train=False)
        props, valid = generate_proposals(out, anchors, (IMG, IMG),
                                          pre_nms_top_n=200,
                                          post_nms_top_n=64)
        assert props.shape == (2, 64, 4)
        assert valid.shape == (2, 64)
        b = np.asarray(props)
        assert (b >= 0).all() and (b <= IMG).all()

    def test_second_stage_and_losses(self, setup):
        model, variables, anchors = setup
        images = jnp.zeros((1, IMG, IMG, 3))
        out = model.apply(variables, images, train=False)
        props, pvalid = generate_proposals(out, anchors, (IMG, IMG),
                                           pre_nms_top_n=200,
                                           post_nms_top_n=32)
        gt_boxes = jnp.asarray([[[10.0, 10.0, 40.0, 40.0],
                                 [0.0, 0.0, 0.0, 0.0]]])
        gt_labels = jnp.asarray([[2, 0]])
        gt_valid = jnp.asarray([[True, False]])
        rl = rpn_loss(out, anchors, gt_boxes, gt_valid, jax.random.key(0))
        assert np.isfinite(float(rl["rpn_obj_loss"]))
        assert np.isfinite(float(rl["rpn_reg_loss"]))

        samples = sample_rois(props, pvalid, gt_boxes, gt_labels, gt_valid,
                              jax.random.key(1), batch_per_image=32)
        assert samples["rois"].shape == (1, 32 + 2, 4)
        out2 = model.apply(variables, images, proposals=samples["rois"],
                           train=False)
        assert out2["roi_scores"].shape == (1, 34, NC)
        assert out2["roi_deltas"].shape == (1, 34, NC, 4)
        hl = roi_head_loss(out2["roi_scores"], out2["roi_deltas"], samples)
        assert np.isfinite(float(hl["roi_cls_loss"]))
        assert np.isfinite(float(hl["roi_reg_loss"]))
        # gt box was appended to rois -> at least one positive sample
        assert int(samples["pos"].sum()) >= 1

    def test_postprocess_fixed_shapes(self, setup):
        model, variables, anchors = setup
        images = jnp.zeros((2, IMG, IMG, 3))
        out = model.apply(variables, images, train=False)
        props, pvalid = generate_proposals(out, anchors, (IMG, IMG),
                                           pre_nms_top_n=200,
                                           post_nms_top_n=32)
        out2 = model.apply(variables, images, proposals=props, train=False)
        det = fasterrcnn_postprocess(out2["roi_scores"],
                                     out2["roi_deltas"], props,
                                     (IMG, IMG), max_det=20,
                                     score_thresh=0.0)
        assert det["boxes"].shape == (2, 20, 4)
        assert det["labels"].shape == (2, 20)
        lab = np.asarray(det["labels"])[np.asarray(det["valid"])]
        assert (lab >= 1).all()          # background never emitted

    def test_end_to_end_jit(self, setup):
        """The whole two-stage train-mode computation jits as one graph."""
        model, variables, anchors = setup
        gt_boxes = jnp.asarray([[[10.0, 10.0, 40.0, 40.0]]])
        gt_labels = jnp.asarray([[1]])
        gt_valid = jnp.asarray([[True]])

        @jax.jit
        def full_loss(params, images, rng):
            out = model.apply({"params": params,
                               "batch_stats": variables["batch_stats"]},
                              images, train=False)
            props, pvalid = generate_proposals(out, anchors, (IMG, IMG),
                                               pre_nms_top_n=100,
                                               post_nms_top_n=16)
            r = rpn_loss(out, anchors, gt_boxes, gt_valid, rng)
            samples = sample_rois(props, pvalid, gt_boxes, gt_labels,
                                  gt_valid, rng, batch_per_image=16)
            out2 = model.apply({"params": params,
                                "batch_stats": variables["batch_stats"]},
                               images, proposals=samples["rois"],
                               train=False)
            h = roi_head_loss(out2["roi_scores"], out2["roi_deltas"],
                              samples)
            return (r["rpn_obj_loss"] + r["rpn_reg_loss"]
                    + h["roi_cls_loss"] + h["roi_reg_loss"])

        loss = full_loss(variables["params"], jnp.zeros((1, IMG, IMG, 3)),
                         jax.random.key(0))
        assert np.isfinite(float(loss))
        g = jax.grad(lambda p: full_loss(p, jnp.zeros((1, IMG, IMG, 3)),
                                         jax.random.key(0)))(
            variables["params"])
        gn = np.sqrt(sum(float(jnp.sum(x ** 2))
                         for x in jax.tree.leaves(g)))
        assert np.isfinite(gn) and gn > 0


def test_pyramid_reuse_matches_recompute():
    """The pyramid= fast path (one backbone forward per train step) must
    produce identical RoI outputs to the full recompute path."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from deeplearning_tpu.core.registry import MODELS

    model = MODELS.build("fasterrcnn_resnet18_fpn", num_classes=4,
                         dtype=jnp.float32)
    x = jnp.asarray(np.random.default_rng(0).normal(
        size=(1, 64, 64, 3)), jnp.float32)
    variables = model.init(jax.random.key(0), x, train=False)
    props = jnp.asarray(
        np.random.default_rng(1).uniform(4, 60, (1, 8, 4)).astype("f4"))
    props = jnp.concatenate([jnp.minimum(props[..., :2], props[..., 2:]),
                             jnp.maximum(props[..., :2], props[..., 2:])],
                            axis=-1)
    full = model.apply(variables, x, proposals=props, train=False)
    fast = model.apply(variables, x, proposals=props, train=False,
                       pyramid=full["pyramid"])
    np.testing.assert_array_equal(np.asarray(full["roi_scores"]),
                                  np.asarray(fast["roi_scores"]))
    np.testing.assert_array_equal(np.asarray(full["roi_deltas"]),
                                  np.asarray(fast["roi_deltas"]))
