"""Swin absolute position embedding (reference swin_transformer.py:516-533).

Motivated by the r5 convergence diagnosis: the ordered digit-pair hard
set is position-dependent, and Swin's window-relative bias alone cannot
express absolute layout (runs/convergence/swin_diag_* all flatline while
ResNet-18 learns the same npz to 0.54+).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning_tpu.core.registry import MODELS


def test_ape_param_created_and_used():
    m = MODELS.build("swin_mini_patch2_window7_ape", num_classes=10,
                     dtype=jnp.float32)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 56, 56, 3)),
                    jnp.float32)
    v = m.init(jax.random.key(0), x, train=False)
    assert "absolute_pos_embed" in v["params"]
    assert v["params"]["absolute_pos_embed"].shape == (1, 28 * 28, 64)
    base = m.apply(v, x, train=False)
    # random (not constant!) perturbation — a constant offset would be
    # erased by the first LayerNorm downstream
    noise = np.random.default_rng(1).normal(
        0, 1.0, v["params"]["absolute_pos_embed"].shape).astype(np.float32)
    shifted = dict(v["params"])
    shifted["absolute_pos_embed"] = shifted["absolute_pos_embed"] + noise
    moved = m.apply({"params": shifted}, x, train=False)
    assert not np.allclose(np.asarray(base), np.asarray(moved))


def test_ape_off_by_default():
    m = MODELS.build("swin_mini_patch2_window7", num_classes=10,
                     dtype=jnp.float32)
    v = m.init(jax.random.key(0), jnp.zeros((1, 56, 56, 3)), train=False)
    assert "absolute_pos_embed" not in v["params"]


@pytest.mark.parametrize("name", ["swin_mini_patch2_window7",
                                  "swin_moe_mini_patch2_window7_ape"])
def test_mini_configs_forward(name):
    m = MODELS.build(name, num_classes=100, dtype=jnp.float32)
    x = jnp.zeros((2, 56, 56, 3))
    v = m.init(jax.random.key(0), x, train=False)
    out = m.apply(v, x, train=False)  # moe aux losses are sow'n, not returned
    assert out.shape == (2, 100)
