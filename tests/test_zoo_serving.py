"""PR 12 multi-tenant zoo serving: ModelZoo residency states, the
3-model CPU e2e (mixed traffic -> per-model compile-once + bitwise
parity vs solo engines), HBM-pressure LRU eviction with a stubbed
snapshot, reload-after-evict freshness, per-tenant admission isolation
(the per-model EWMA drain bugfix), zoo health states, and the labeled
metrics -> fleet rollup path.

Fake engines (no device work) drive the policy tests so they run in
milliseconds; the e2e uses real ``InferenceEngine`` sessions because
bitwise parity and trace counters are the acceptance contract."""

import os
import sys
import threading
import time

import numpy as np
import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "tools"))

from deeplearning_tpu.obs import flight, metrics, spans
from deeplearning_tpu.obs.fleet import (SLOPolicy, compute_rollup,
                                        scrape_replica)
from deeplearning_tpu.obs.metrics import MetricsServer
from deeplearning_tpu.serve import (InferenceEngine, MicroBatcher,
                                    ModelZoo, Rejected, TenantAdmission,
                                    zoo_health)


@pytest.fixture(autouse=True)
def _clean_obs_globals():
    """Zoo internals bump the process-wide registry when one is
    installed; keep every test hermetic (same discipline as
    test_metrics_fleet)."""
    def reset():
        metrics.disable()
        spans.disable()
        rec = flight.get_recorder()
        rec.clear()
        rec.path = None
        rec.config = None
    reset()
    yield
    reset()


class FakeEngine:
    """Engine-shaped stand-in: everything the batcher/zoo touch, no jax.
    ``scale`` makes outputs model-distinguishable; ``delay_s`` simulates
    a slow executable so one tenant's queue can be saturated."""

    def __init__(self, buckets=(1, 4), image_size=8, nbytes=400,
                 scale=1.0, delay_s=0.0):
        self.buckets = tuple(sorted(buckets))
        self.image_size = image_size
        self.trace_count = len(self.buckets)
        self.compile_count = len(self.buckets)
        self.scale = scale
        self.delay_s = delay_s
        self._nbytes = nbytes
        self.calls = []

    def variables_nbytes(self):
        return self._nbytes

    def bucket_for(self, n):
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    def pad_to_bucket(self, images, bucket):
        if images.shape[0] == bucket:
            return images
        pad = np.zeros((bucket - images.shape[0],) + images.shape[1:],
                       images.dtype)
        return np.concatenate([images, pad], axis=0)

    def run(self, bucket, padded):
        if self.delay_s:
            time.sleep(self.delay_s)
        self.calls.append(bucket)
        return self.scale * padded.sum(axis=(1, 2, 3))


def img(size, seed=0):
    return np.random.default_rng(seed).normal(
        size=(size, size, 3)).astype(np.float32)


# ------------------------------------------------------- registry/states
class TestZooRegistry:
    def test_states_and_prebuilt_engine(self):
        zoo = ModelZoo()
        zoo.register("a", engine=FakeEngine())
        assert zoo.state("a") == "warm"
        assert zoo.engine("a") is not None
        zoo.register("b", engine_factory=FakeEngine,
                     batch_buckets=(1, 4), image_size=8)
        assert zoo.state("b") == "registered"
        assert zoo.engine("b") is None       # cold: dispatcher skips it
        with pytest.raises(ValueError):
            zoo.register("a", engine=FakeEngine())
        with pytest.raises(KeyError):
            zoo.state("nope")
        assert zoo.models() == ["a", "b"]
        st = zoo.stats()
        assert st["registered"] == 2 and st["resident"] == 1
        assert st["models"]["b"]["state"] == "registered"

    def test_load_failure_is_held_not_raised(self):
        zoo = ModelZoo()

        def boom():
            raise RuntimeError("no such checkpoint")

        zoo.register("bad", engine_factory=boom,
                     batch_buckets=(1,), image_size=8)
        assert zoo.load("bad", wait=True) == "failed"
        assert "no such checkpoint" in zoo.load_errors["bad"]
        # a later request restarts the load (state machine, not a latch)
        assert zoo.request("bad") == "loading"


# ------------------------------------------------------------- eviction
def pressure_zoo(limit=1000, alert=0.9, **zoo_kwargs):
    """Zoo whose stubbed HBM snapshot tracks ACTUAL residency: usage is
    the sum of resident engine bytes, so the freed-bytes projection in
    ``_ensure_capacity`` sees evictions land."""
    holder = {}

    def snap():
        zoo = holder["zoo"]
        in_use = sum(zoo._resident_bytes.get(a, 0)
                     for a in zoo._engines)
        return {"devices": [{"bytes_limit": limit,
                             "bytes_in_use": in_use,
                             "usage_frac": in_use / limit}]}

    zoo = ModelZoo(alert_frac=alert, hbm_snapshot_fn=snap, **zoo_kwargs)
    holder["zoo"] = zoo
    for alias in ("a", "b", "c"):
        zoo.register(alias, engine_factory=lambda: FakeEngine(nbytes=400),
                     est_bytes=400, batch_buckets=(1, 4), image_size=8)
    return zoo


class TestEvictionUnderPressure:
    def test_lru_evicted_when_projection_crosses_alert(self):
        zoo = pressure_zoo()
        assert zoo.load("a", wait=True) == "warm"    # 400/1000 = 0.40
        assert zoo.load("b", wait=True) == "warm"    # 800/1000 = 0.80
        # c projects 0.8 + 0.4 = 1.2 >= 0.9: the LRU idle model (a,
        # loaded first, untouched since) must go first
        assert zoo.load("c", wait=True) == "warm"
        assert zoo.state("a") == "evicted"
        assert zoo.state("b") == "warm" and zoo.state("c") == "warm"
        assert zoo.evictions == 1
        assert zoo.engine("a") is None

    def test_recent_touch_redirects_the_victim(self):
        zoo = pressure_zoo()
        zoo.load("a", wait=True)
        zoo.load("b", wait=True)
        zoo.touch("a")                       # now b is the LRU
        zoo.load("c", wait=True)
        assert zoo.state("b") == "evicted"
        assert zoo.state("a") == "warm"

    def test_nothing_evictable_rejects_with_429_semantics(self):
        zoo = pressure_zoo()
        zoo.load("b", wait=True)
        zoo.load("c", wait=True)
        zoo.mark_dispatch("b", +1)           # batches in flight: both
        zoo.mark_dispatch("c", +1)           # residents are untouchable
        with pytest.raises(Rejected) as ei:
            zoo.request("a")
        assert ei.value.reason == "hbm_pressure"
        assert ei.value.model == "a"
        assert ei.value.retry_after_s > 0
        assert zoo.rejected_loads == 1
        assert zoo.state("a") == "registered"   # not failed: retryable
        # batches drain -> the same request now admits (evicting LRU)
        zoo.mark_dispatch("b", -1)
        zoo.mark_dispatch("c", -1)
        assert zoo.load("a", wait=True) == "warm"

    def test_max_resident_cap(self):
        zoo = pressure_zoo(limit=10**9, max_resident=1)
        zoo.load("a", wait=True)
        zoo.load("b", wait=True)             # evicts a (cap, not HBM)
        assert zoo.state("a") == "evicted"
        assert zoo.state("b") == "warm"
        zoo.mark_dispatch("b", +1)
        with pytest.raises(Rejected) as ei:
            zoo.request("c")
        assert ei.value.reason == "zoo_capacity"

    def test_enforce_pressure_sweeps_back_under_alert(self):
        zoo = pressure_zoo(alert=0.5)
        # bypass the load-time gate to create standing over-pressure
        zoo._alert_frac = 2.0
        zoo.load("a", wait=True)
        zoo.load("b", wait=True)
        zoo._alert_frac = 0.5                # 0.8 in use vs 0.5 alert
        assert zoo.enforce_pressure() == 1
        assert zoo.stats()["resident"] == 1


# --------------------------------------------------- reload after evict
def test_reload_after_evict_is_fresh():
    built = []

    def make():
        eng = FakeEngine(nbytes=100 + 10 * len(built))
        built.append(eng)
        return eng

    zoo = ModelZoo()
    zoo.register("m", engine_factory=make,
                 batch_buckets=(1, 4), image_size=8)
    zoo.load("m", wait=True)
    first = zoo.engine("m")
    assert zoo.evict("m") is True
    assert zoo.state("m") == "evicted" and zoo.engine("m") is None
    assert zoo.evict("m") is False           # idempotent: already gone
    # the next request hot-reloads a NEW engine — never the stale one
    assert zoo.request("m") == "loading"
    zoo.load("m", wait=True)
    second = zoo.engine("m")
    assert second is not first and len(built) == 2
    assert zoo.stats()["models"]["m"]["bytes"] == 110
    assert zoo.loads == 2 and zoo.evictions == 1


# ------------------------------------------------- per-tenant admission
class TestTenantIsolation:
    def test_retry_after_quotes_the_target_models_own_drain(self):
        ta = TenantAdmission()
        slow = ta.configure("slow", (1, 4), max_queue=8)
        fast = ta.configure("fast", (1, 4), max_queue=8)
        slow.note_drained(10, 1.0)           # 10 req/s
        fast.note_drained(1000, 1.0)         # 1000 req/s
        # the bugfix: one global EWMA would give both tenants the same
        # hint; per-model controllers quote their OWN backlog drain
        assert slow.retry_after_s(20) == pytest.approx(2.0)
        assert fast.retry_after_s(20) == pytest.approx(0.02)
        assert ta.for_model("slow") is slow

    def test_saturating_one_tenant_does_not_starve_the_other(self):
        zoo = ModelZoo()
        zoo.register("slow", engine=FakeEngine(delay_s=0.02),
                     max_queue=2)
        zoo.register("fast", engine=FakeEngine(scale=2.0))
        frame = img(8)
        # solo baseline for the fast tenant
        with MicroBatcher(zoo=zoo, max_wait_ms=1.0) as mb:
            for _ in range(16):
                mb.submit(frame, model="fast").result(timeout=10.0)
            solo_p99 = mb.lane_telemetry("fast").snapshot()["e2e_ms_p99"]
        with MicroBatcher(zoo=zoo, max_wait_ms=1.0) as mb:
            rejected = None
            for _ in range(64):              # saturate slow's queue of 2
                try:
                    mb.submit(frame, model="slow", timeout_s=30.0)
                except Rejected as r:
                    rejected = r
                    break
            assert rejected is not None
            assert rejected.model == "slow"
            assert rejected.reason == "queue_full"
            assert rejected.retry_after_s > 0
            # the fast tenant keeps its latency while slow is saturated
            for _ in range(16):
                out = mb.submit(frame, model="fast").result(timeout=10.0)
                assert np.isclose(out, 2.0 * frame.sum(), rtol=1e-5)
            mixed = mb.lane_telemetry("fast").snapshot()
            assert mixed["rejected"] == 0
            # a fast request can at worst sit behind ONE slow 20ms
            # batch (round-robin); the floor absorbs that + CI noise
            budget = max(2.0 * solo_p99, 80.0)
            assert mixed["e2e_ms_p99"] <= budget, \
                f"fast p99 {mixed['e2e_ms_p99']}ms vs budget {budget}ms"

    def test_unknown_model_is_keyerror_not_silent_lane(self):
        zoo = ModelZoo()
        zoo.register("a", engine=FakeEngine())
        with MicroBatcher(zoo=zoo) as mb:
            with pytest.raises(KeyError):
                mb.submit(img(8), model="ghost")


# --------------------------------------------------------------- health
def test_zoo_health_states():
    zoo = ModelZoo()
    zoo.register("warmed", engine=FakeEngine())
    zoo.register("cold", engine_factory=FakeEngine,
                 batch_buckets=(1,), image_size=8)
    code, payload = zoo_health(zoo)
    # cold (registered/evicted) tenants do NOT block readiness: a
    # request for one hot-loads instead of erroring
    assert code == 200 and payload["status"] == "ready"
    assert payload["models"]["cold"]["state"] == "registered"
    zoo._state["cold"] = "loading"
    code, payload = zoo_health(zoo)
    assert code == 503 and payload["status"] == "warming"
    zoo._state["cold"] = "registered"


# ------------------------------------------- labeled metrics -> rollup
def test_zoo_labeled_metrics_scrape_and_fleet_rollup():
    from serve import make_metrics_collector   # tools/serve.py

    zoo = ModelZoo()
    zoo.register("a", engine=FakeEngine())
    zoo.register("b", engine=FakeEngine(scale=2.0))
    reg = metrics.enable()
    frame = img(8)
    with MicroBatcher(zoo=zoo, max_wait_ms=1.0) as mb:
        reg.register_collector(make_metrics_collector(mb))
        for _ in range(3):
            mb.submit(frame, model="a").result(timeout=10.0)
        mb.submit(frame, model="b").result(timeout=10.0)
        text = reg.prometheus_text()
        assert 'dltpu_serve_requests_total{model="a"} 3.0' in text
        assert 'dltpu_serve_requests_total{model="b"} 1.0' in text
        assert 'dltpu_zoo_model_warm{model="a"} 1.0' in text
        assert "dltpu_zoo_resident 2.0" in text
        with MetricsServer(reg, port=0,
                           healthz_fn=lambda: (200, {"status": "ready"})
                           ) as srv:
            sample = scrape_replica(srv.url, timeout_s=5.0)
    assert sample["ok"]
    assert sample["by_model"]["a"]["dltpu_serve_requests_total"] == 3.0
    assert sample["by_model"]["b"]["dltpu_serve_requests_total"] == 1.0
    rollup = compute_rollup([sample],
                            slo=SLOPolicy(p99_budget_ms=1e-6))
    assert rollup["models"]["a"]["requests_total"] == 3.0
    assert rollup["models"]["b"]["requests_total"] == 1.0
    # any observed latency breaches a 1ns p99 budget: the per-model SLO
    # verdict is evaluated per tenant, not just fleet-wide
    assert rollup["models"]["a"]["slo"]["breach"]


# ----------------------------------------------------------- 3-model e2e
@pytest.mark.e2e
def test_zoo_three_model_e2e_compile_once_parity_evict_reload():
    """The PR acceptance run: three registered models, mixed traffic,
    per-model at-most-one-compile-per-bucket, bitwise parity vs solo
    engines, then forced HBM pressure evicts the LRU model and the next
    request hot-reloads it."""
    buckets = (1, 4)
    tenants = {
        "fcn_a": dict(model_name="mnist_fcn", num_classes=10),
        "fcn_b": dict(model_name="mnist_fcn", num_classes=10,
                      weight_quant="int8"),
        "cnn": dict(model_name="mnist_cnn", num_classes=10),
    }
    # stubbed snapshot: usage tracks residency (0.2/model) on top of a
    # dialable base, over a limit that dwarfs real weight bytes — so
    # load projections ~= current frac and one eviction relieves one
    # model's worth of pressure (enforce_pressure stops at the LRU)
    pressure = {"base": 0.0}
    holder = {}

    def snap():
        frac = pressure["base"] + 0.2 * len(holder["zoo"]._engines)
        return {"devices": [{"bytes_limit": int(1e12),
                             "bytes_in_use": int(frac * 1e12),
                             "usage_frac": frac}]}

    zoo = ModelZoo(alert_frac=0.9, hbm_snapshot_fn=snap)
    holder["zoo"] = zoo
    for alias, kw in tenants.items():
        kw = dict(kw, image_size=28, batch_buckets=buckets,
                  est_bytes=100)
        zoo.register(alias, kw.pop("model_name"), **kw)
        assert zoo.load(alias, wait=True) == "warm"
    # engines are seeded (seed=0 default): a solo engine with the same
    # config is bit-identical, which is what makes parity testable
    solo = {alias: InferenceEngine(
        kw["model_name"], num_classes=10, image_size=28,
        batch_buckets=buckets,
        weight_quant=kw.get("weight_quant", "fp32"))
        for alias, kw in tenants.items()}

    rng = np.random.default_rng(12)
    images = rng.normal(size=(18, 28, 28, 3)).astype(np.float32)
    order = [list(tenants)[i % 3] for i in range(len(images))]
    warm = {a: (zoo.engine(a).trace_count, zoo.engine(a).compile_count)
            for a in tenants}
    with MicroBatcher(zoo=zoo, max_wait_ms=2.0) as mb:
        handles = [(alias, im, mb.submit(im, model=alias))
                   for alias, im in zip(order, images)]
        for alias, im, h in handles:
            got = h.result(timeout=60.0)
            want = solo[alias].infer(im)[0]
            assert np.array_equal(got, want), f"parity broke for {alias}"
        # interleaved traffic retraced nothing: per-model compile-once
        for a in tenants:
            eng = zoo.engine(a)
            assert (eng.trace_count, eng.compile_count) == warm[a], \
                f"{a} retraced under interleaved dispatch"
            assert eng.compile_count == len(buckets)
        # int8 residency is denser than fp32 for the same architecture
        st = zoo.stats()["models"]
        assert 0 < st["fcn_b"]["bytes"] < st["fcn_a"]["bytes"]

        # force pressure: the LRU tenant goes, traffic to it reloads it
        for alias in ("fcn_b", "cnn"):
            zoo.touch(alias)
        pressure["base"] = 0.35              # 0.35 + 3*0.2 = 0.95 > 0.9
        assert zoo.enforce_pressure() == 1   # one evict: 0.75 < alert
        assert zoo.state("fcn_a") == "evicted"
        pressure["base"] = 0.0
        h = mb.submit(images[0], model="fcn_a")       # kicks hot reload
        assert np.array_equal(h.result(timeout=120.0),
                              solo["fcn_a"].infer(images[0])[0])
    assert zoo.state("fcn_a") == "warm"
    assert zoo.loads == 4 and zoo.evictions == 1


# --------------------------------------- load-vs-evict under threadsan
class TestZooRaceUnderThreadSanitizer:
    """ISSUE 13 satellite: hammer admin load against pressure eviction
    over the same alias with ``DLTPU_STRICT=threads`` armed — any
    lock-order inversion or discipline break between the zoo lock and
    the spawn registry raises ``LockOrderError`` and fails here."""

    def test_load_vs_evict_race_is_lock_clean(self, monkeypatch):
        from deeplearning_tpu.analysis import strict as strict_mod
        from deeplearning_tpu.analysis import threadsan
        monkeypatch.setenv("DLTPU_STRICT", "threads")
        threadsan.reset()
        assert strict_mod.maybe_enable_threads(strict_mod.resolve())
        try:
            zoo = pressure_zoo(limit=1000, alert=0.9)
            errors = []
            deadline = time.monotonic() + 1.0

            def hammer(step):
                while time.monotonic() < deadline:
                    try:
                        step()
                    except threadsan.LockOrderError as exc:
                        errors.append(exc)
                        return

            def admin():
                zoo.load("a", wait=True, timeout_s=5.0)
                zoo.touch("a")
                zoo.engine("a")

            def pressure():
                zoo.load("b", wait=True, timeout_s=5.0)
                zoo.evict("a")
                zoo.enforce_pressure()

            workers = [threading.Thread(target=hammer, args=(fn,),
                                        daemon=True)
                       for fn in (admin, pressure)]
            for t in workers:
                t.start()
            for t in workers:
                t.join(15.0)
            assert not any(t.is_alive() for t in workers), \
                "race workers wedged (deadlock?)"
            assert errors == [], errors[0].report
            st = threadsan.status()
            assert st["violations"] == 0
            assert st["locks_instrumented"] > 0   # zoo lock WAS watched
            # with the hammering done, no entry is half-flipped: every
            # warm alias serves a real engine, everything else serves
            # none
            for alias in ("a", "b"):
                if zoo.state(alias) == "warm":
                    assert zoo.engine(alias) is not None
                else:
                    assert zoo.engine(alias) is None
        finally:
            threadsan.disable()
            threadsan.reset()
