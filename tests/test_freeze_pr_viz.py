"""Parameter freezing, PR curves, t-SNE projection.

References: fasterRcnn change_backbone_with*.py (backbone freezing),
yolov5 utils/metrics.py (ap_per_class / plot_pr_curve),
self-supervised/SupCon t-SNE.py."""

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning_tpu.evaluation.metrics import precision_recall_curve
from deeplearning_tpu.train.optim import build_optimizer, freeze_mask
from deeplearning_tpu.train.schedules import build_schedule
from deeplearning_tpu.utils.visualize import (embedding_projection_figure,
                                              pr_curve_figure)


class TestFreeze:
    def _params(self):
        return {
            "backbone": {"conv1": {"kernel": jnp.ones((3, 3, 4, 8))}},
            "head": {"fc": {"kernel": jnp.ones((8, 2)),
                            "bias": jnp.zeros((2,))}},
        }

    def test_freeze_mask_matches_patterns(self):
        mask = freeze_mask(self._params(), ("backbone",))
        assert mask["backbone"]["conv1"]["kernel"] is True
        assert mask["head"]["fc"]["kernel"] is False

    def test_freeze_mask_component_boundaries(self):
        params = {f"blocks_{i}": {"kernel": jnp.ones((2, 2))}
                  for i in (1, 10, 11)}
        mask = freeze_mask(params, ("blocks_1",))
        assert mask["blocks_1"]["kernel"] is True
        assert mask["blocks_10"]["kernel"] is False
        assert mask["blocks_11"]["kernel"] is False
        # multi-segment patterns still work
        mask2 = freeze_mask(self._params(), ("backbone/conv1",))
        assert mask2["backbone"]["conv1"]["kernel"] is True

    def test_frozen_params_do_not_move_under_adamw_decay(self):
        params = self._params()
        tx = build_optimizer("adamw", build_schedule("constant",
                                                     base_lr=0.1),
                             params=params, weight_decay=0.5,
                             freeze=("backbone",))
        state = tx.init(params)
        grads = jax.tree.map(jnp.ones_like, params)
        updates, _ = tx.update(grads, state, params)
        assert float(jnp.abs(updates["backbone"]["conv1"]["kernel"]).max()) \
            == 0.0
        # unfrozen params do get updates (incl. decoupled decay)
        assert float(jnp.abs(updates["head"]["fc"]["kernel"]).max()) > 0.0

    def test_frozen_grads_excluded_from_clip_norm(self):
        # requires_grad=False semantics: a huge gradient on a frozen param
        # must not eat the trainable params' global-norm clip budget.
        params = self._params()
        sched = build_schedule("constant", base_lr=1.0)
        tx = build_optimizer("sgd", sched, momentum=0.0, clip_grad_norm=1.0,
                             params=params, freeze=("backbone",))
        grads = jax.tree.map(jnp.zeros_like, params)
        grads["backbone"]["conv1"]["kernel"] = \
            jnp.full((3, 3, 4, 8), 1e6)  # enormous frozen grad
        grads["head"]["fc"]["kernel"] = jnp.full((8, 2), 0.1)
        updates, _ = tx.update(grads, tx.init(params), params)
        # trainable grad norm (0.4) is under the clip=1.0 → unscaled step
        np.testing.assert_allclose(
            np.asarray(updates["head"]["fc"]["kernel"]), -0.1, rtol=1e-5)
        assert float(jnp.abs(updates["backbone"]["conv1"]["kernel"]).max()) \
            == 0.0


class TestPRCurve:
    def test_perfect_detector_ap_one(self):
        out = precision_recall_curve(
            np.array([0.9, 0.8, 0.7]), np.array([True, True, True]), n_gt=3)
        assert out["ap"] > 0.99
        assert np.all(out["precision"] == 1.0)
        assert out["recall"][-1] == 1.0

    def test_mixed_detections(self):
        # conf-ordered: TP FP TP FP; 3 gts (one missed)
        out = precision_recall_curve(
            np.array([0.9, 0.8, 0.7, 0.6]),
            np.array([True, False, True, False]), n_gt=3)
        np.testing.assert_allclose(out["recall"],
                                   [1 / 3, 1 / 3, 2 / 3, 2 / 3])
        np.testing.assert_allclose(out["precision"],
                                   [1.0, 0.5, 2 / 3, 0.5])
        # AP: envelope is 1.0 until r=1/3, 2/3 until r=2/3, 0 beyond
        assert 0.5 < out["ap"] < 0.62

    def test_empty_detections(self):
        out = precision_recall_curve(np.zeros((0,)), np.zeros((0,), bool),
                                     n_gt=5)
        assert out["ap"] == 0.0

    def test_figure(self):
        out = precision_recall_curve(
            np.array([0.9, 0.8]), np.array([True, False]), n_gt=2)
        fig = pr_curve_figure({"cls0": out})
        assert fig is not None


class TestEmbeddingProjection:
    def test_tsne_and_pca(self):
        rng = np.random.default_rng(0)
        emb = np.concatenate([rng.normal(0, 0.1, (20, 8)),
                              rng.normal(3, 0.1, (20, 8))])
        labels = [0] * 20 + [1] * 20
        assert embedding_projection_figure(emb, labels, "pca") is not None
        assert embedding_projection_figure(emb, labels, "tsne") is not None


class TestFrozenBN:
    """FrozenBatchNorm2d semantics (fasterRcnn/models/backbone/
    resnet50_fpn.py:5): batch statistics stay fixed in train mode, so the
    train-mode forward equals the eval-mode forward and batch_stats never
    update. Pairs with the optimizer freeze mask for full requires_grad
    =False parity."""

    def _model_and_vars(self, frozen):
        from deeplearning_tpu.core.registry import MODELS
        model = MODELS.build("retinanet_resnet18_fpn", num_classes=3,
                             backbone_frozen_bn=frozen)
        x = jnp.asarray(np.random.default_rng(0).normal(
            size=(1, 64, 64, 3)), jnp.float32)
        variables = model.init(jax.random.key(0), x, train=False)
        # non-trivial running stats so frozen vs live actually differs
        keys = iter(jax.random.split(jax.random.key(1), 10_000))
        stats = jax.tree.map(
            lambda s: s + 0.3 * jax.random.uniform(next(keys), s.shape),
            variables["batch_stats"])
        return model, {"params": variables["params"],
                       "batch_stats": stats}, x

    def test_frozen_stats_do_not_update(self):
        model, variables, x = self._model_and_vars(frozen=True)
        _, mutated = model.apply(variables, x, train=True,
                                 mutable=["batch_stats"])
        before = jax.tree.leaves(variables["batch_stats"])
        after = jax.tree.leaves(mutated["batch_stats"])
        assert all(bool(jnp.array_equal(b, a))
                   for b, a in zip(before, after))

    def test_frozen_train_forward_equals_eval(self):
        model, variables, x = self._model_and_vars(frozen=True)
        train_out, _ = model.apply(variables, x, train=True,
                                   mutable=["batch_stats"])
        eval_out = model.apply(variables, x, train=False)
        np.testing.assert_allclose(
            np.asarray(train_out["cls_logits"]),
            np.asarray(eval_out["cls_logits"]),
            rtol=1e-6, atol=1e-6)

    def test_live_bn_still_updates(self):
        model, variables, x = self._model_and_vars(frozen=False)
        _, mutated = model.apply(variables, x, train=True,
                                 mutable=["batch_stats"])
        before = jax.tree.leaves(variables["batch_stats"])
        after = jax.tree.leaves(mutated["batch_stats"])
        assert any(not bool(jnp.array_equal(b, a))
                   for b, a in zip(before, after))
