"""PR 11 fleet telemetry plane: metrics registry units + Prometheus
line-format conformance, the MetricsServer scrape surface, serve
telemetry windowed rates, replica identity stamping (heartbeat, spans,
endpoint files), fleet rollup/SLO folds, trace_merge, the bench
overhead helper, and the multi-replica CPU e2e acceptance run
(supervise --replicas 2 -> serve replicas -> FleetScraper -> SLO
breach -> graceful drain -> merged fleet trace)."""

import json
import io
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "tools"))

from deeplearning_tpu.elastic import heartbeat as hb
from deeplearning_tpu.obs import flight, metrics, spans
from deeplearning_tpu.obs.fleet import (FleetScraper, SLOPolicy,
                                        compute_rollup,
                                        discover_endpoints,
                                        parse_prometheus_text,
                                        scrape_replica)
from deeplearning_tpu.obs.metrics import MetricsRegistry, MetricsServer
from deeplearning_tpu.serve.telemetry import ServeTelemetry


@pytest.fixture(autouse=True)
def _clean_obs_globals():
    """Every test starts and ends with the process-wide registry and
    tracer disabled and the default flight recorder disarmed."""
    def reset():
        metrics.disable()
        spans.disable()
        rec = flight.get_recorder()
        rec.clear()
        rec.path = None
        rec.config = None
    reset()
    yield
    reset()


# -------------------------------------------------------------- registry
class TestMetricsRegistry:
    def test_counter_gauge_histogram(self):
        reg = MetricsRegistry()
        c = reg.counter("dltpu_x_total")
        c.inc()
        c.inc(2.5)
        assert c.value == 3.5
        assert reg.counter("dltpu_x_total") is c      # get-or-create
        g = reg.gauge("dltpu_depth")
        g.set(7.0)
        g.inc(-2.0)
        assert g.value == 5.0
        h = reg.histogram("dltpu_lat_ms", buckets=(1.0, 10.0, 100.0))
        for v in (0.5, 5.0, 50.0, 500.0):
            h.observe(v)
        assert h.count == 4 and h.sum == 555.5
        cum = dict(h._cumulative())
        assert cum["+Inf"] == 4
        assert cum[repr(10.0)] == 2                   # cumulative, sorted

    def test_set_total_is_monotonic(self):
        c = MetricsRegistry().counter("dltpu_mirror_total")
        c.set_total(5.0)
        c.set_total(3.0)                              # source reset: hold
        assert c.value == 5.0
        c.set_total(9.0)
        assert c.value == 9.0

    def test_kind_mismatch_raises(self):
        reg = MetricsRegistry()
        reg.counter("dltpu_x_total")
        with pytest.raises(TypeError):
            reg.gauge("dltpu_x_total")

    def test_bad_metric_name_rejected(self):
        with pytest.raises(ValueError):
            MetricsRegistry().counter("bad name!")

    def test_disabled_helpers_are_inert(self):
        assert not metrics.enabled()
        metrics.inc("dltpu_never_total")
        metrics.set_gauge("dltpu_never", 1.0)
        metrics.observe("dltpu_never_ms", 1.0)
        assert metrics.get_registry() is None

    def test_enabled_helpers_write_one_registry(self):
        reg = metrics.enable()
        assert metrics.enable() is reg                # idempotent
        metrics.inc("dltpu_steps_total", 3)
        metrics.set_gauge("dltpu_step", 17.0)
        metrics.observe("dltpu_step_ms", 2.0, buckets=(1.0, 4.0))
        snap = reg.snapshot()["metrics"]
        assert snap["dltpu_steps_total"]["value"] == 3.0
        assert snap["dltpu_step"]["value"] == 17.0
        assert snap["dltpu_step_ms"]["count"] == 1

    def test_collector_errors_counted_not_raised(self):
        reg = MetricsRegistry()

        def bad(_reg):
            raise RuntimeError("boom")
        reg.register_collector(bad)
        reg.register_collector(bad)                   # identity dedup
        reg.register_collector(
            lambda r: r.gauge("dltpu_ok").set(1.0))
        snap = reg.snapshot()
        assert snap["collect_errors"] == 1
        assert snap["metrics"]["dltpu_ok"]["value"] == 1.0

    def test_dump_writes_snapshot(self, tmp_path):
        reg = MetricsRegistry()
        reg.counter("dltpu_x_total").inc()
        path = reg.dump(str(tmp_path / "metrics_registry.json"))
        doc = json.load(open(path))
        assert doc["metrics"]["dltpu_x_total"]["value"] == 1.0


# ----------------------------------------- prometheus format conformance
class TestPrometheusConformance:
    def test_text_round_trips_through_strict_parser(self, monkeypatch):
        monkeypatch.setenv(metrics.RUN_ID_VAR, "run-x")
        monkeypatch.setenv(metrics.REPLICA_VAR, "3")
        reg = MetricsRegistry()
        reg.counter("dltpu_req_total", "requests").inc(42)
        reg.gauge("dltpu_depth").set(2.5)
        h = reg.histogram("dltpu_lat_ms", "latency", buckets=(1.0, 10.0))
        h.observe(0.5)
        h.observe(99.0)
        text = reg.prometheus_text()
        assert "# TYPE dltpu_req_total counter" in text
        assert "# HELP dltpu_req_total requests" in text
        assert "# TYPE dltpu_lat_ms histogram" in text
        samples = parse_prometheus_text(text)   # strict: raises on bad
        flat = {(n, tuple(sorted(lab.items()))): v
                for n, lab, v in samples}
        assert flat[("dltpu_req_total", ())] == 42.0
        assert flat[("dltpu_depth", ())] == 2.5
        assert flat[("dltpu_lat_ms_bucket", (("le", "1.0"),))] == 1.0
        assert flat[("dltpu_lat_ms_bucket", (("le", "+Inf"),))] == 2.0
        assert flat[("dltpu_lat_ms_count", ())] == 2.0
        assert flat[("dltpu_lat_ms_sum", ())] == 99.5
        info = [lab for n, lab, v in samples
                if n == "dltpu_replica_info"]
        assert info == [{"run_id": "run-x", "replica": "3"}]

    def test_parser_rejects_malformed_lines(self):
        for bad in ("dltpu_x not_a_number",
                    "dltpu x 1",
                    'dltpu_x{le="1.0" 2',
                    "# TYPE dltpu_x nonsense"):
            with pytest.raises(ValueError):
                parse_prometheus_text(bad + "\n")

    def test_special_values(self):
        samples = parse_prometheus_text(
            "dltpu_a +Inf\ndltpu_b -Inf\ndltpu_c 1e3\n")
        vals = {n: v for n, _, v in samples}
        assert vals["dltpu_a"] == float("inf")
        assert vals["dltpu_b"] == float("-inf")
        assert vals["dltpu_c"] == 1000.0


# --------------------------------------------------------- scrape server
class TestMetricsServer:
    def test_routes(self):
        reg = MetricsRegistry()
        reg.counter("dltpu_x_total").inc(3)
        calls = []

        def healthz():
            calls.append(1)
            return 200, {"status": "ready", "step": 7}
        with MetricsServer(reg, port=0, healthz_fn=healthz) as srv:
            with urllib.request.urlopen(srv.url + "/metrics",
                                        timeout=5) as r:
                assert "version=0.0.4" in r.headers["Content-Type"]
                text = r.read().decode()
            assert ("dltpu_x_total", {}, 3.0) in \
                parse_prometheus_text(text)
            with urllib.request.urlopen(srv.url + "/metrics.json",
                                        timeout=5) as r:
                snap = json.loads(r.read())
            assert snap["metrics"]["dltpu_x_total"]["value"] == 3.0
            with urllib.request.urlopen(srv.url + "/healthz",
                                        timeout=5) as r:
                hz = json.loads(r.read())
            assert hz == {"status": "ready", "step": 7} and calls
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(srv.url + "/nope", timeout=5)
            assert ei.value.code == 404

    def test_scrape_replica_reads_identity(self, monkeypatch):
        monkeypatch.setenv(metrics.RUN_ID_VAR, "run-y")
        monkeypatch.setenv(metrics.REPLICA_VAR, "1")
        reg = MetricsRegistry()
        reg.gauge("dltpu_serve_queue_depth").set(4.0)
        with MetricsServer(reg, port=0,
                           healthz_fn=lambda: (200, {"status": "ready"})
                           ) as srv:
            sample = scrape_replica(srv.url, timeout_s=5.0)
        assert sample["ok"] and sample["status"] == "ready"
        assert sample["run_id"] == "run-y" and sample["replica"] == "1"
        assert sample["metrics"]["dltpu_serve_queue_depth"] == 4.0

    def test_unreachable_replica_is_a_sample_not_a_crash(self):
        sample = scrape_replica("http://127.0.0.1:9", timeout_s=0.2)
        assert sample["ok"] is False
        assert sample["status"] == "unreachable"


# ------------------------------------------------------- telemetry rates
class TestTelemetryRates:
    def test_windowed_rates(self):
        t = ServeTelemetry()
        for _ in range(10):
            t.record_submit()
        t.record_reject()
        t.record_dispatch_latency(0.001, n=4)
        r = t.rates(window_s=10.0)
        # effective window = age of the telemetry (just born), so a
        # startup burst reads as a real rate, not one diluted by the
        # full window
        assert r["requests_per_s"] > 10.0
        assert r["rejects_per_s"] > 0.0
        assert r["completions_per_s"] > 0.0
        assert 0.0 <= r["window_s"] <= 10.0   # rounded to 3 decimals
        snap = t.snapshot()
        assert snap["submitted"] == 10.0
        assert "requests_per_s" in snap and "window_s" in snap

    def test_rates_empty(self):
        r = ServeTelemetry().rates()
        assert r["requests_per_s"] == 0.0


# ----------------------------------------------------- identity stamping
class TestIdentityStamping:
    def test_heartbeat_carries_run_and_replica(self, tmp_path,
                                               monkeypatch):
        monkeypatch.setenv(hb.RUN_ID_VAR, "run-z")
        monkeypatch.setenv(hb.REPLICA_VAR, "2")
        path = str(tmp_path / "heartbeat.json")
        w = hb.HeartbeatWriter(path, hb.Heartbeat(),
                               interval_s=0.05).start()
        try:
            deadline = time.time() + 5.0
            doc = None
            while time.time() < deadline:
                if os.path.exists(path):
                    doc = json.load(open(path))
                    break
                time.sleep(0.02)
        finally:
            w.stop()
        assert doc and doc["run_id"] == "run-z" and doc["replica"] == "2"

    def test_trace_dump_carries_replica_process_row(self, tmp_path,
                                                    monkeypatch):
        monkeypatch.setenv(metrics.RUN_ID_VAR, "run-z")
        monkeypatch.setenv(metrics.REPLICA_VAR, "5")
        tracer = spans.enable()
        with spans.span("dispatch"):
            pass
        path = tracer.dump(str(tmp_path / "trace.json"))
        doc = json.load(open(path))
        assert doc["otherData"]["run_id"] == "run-z"
        assert doc["otherData"]["replica"] == "5"
        procs = [e for e in doc["traceEvents"]
                 if e.get("ph") == "M" and e["name"] == "process_name"]
        assert procs and procs[0]["args"]["name"] == "replica-5"

    def test_endpoint_files_and_discovery(self, tmp_path, monkeypatch):
        for i in range(2):
            d = tmp_path / f"replica-{i}"
            d.mkdir()
            monkeypatch.setenv(metrics.REPLICA_VAR, str(i))
            p = metrics.write_endpoint(f"http://127.0.0.1:900{i}",
                                       role="serve",
                                       path=str(d / "endpoint.json"))
            assert p and metrics.read_endpoint(p)["replica"] == str(i)
        # written in reverse-looking dir order still sorts by replica id
        assert discover_endpoints(str(tmp_path)) == [
            "http://127.0.0.1:9000", "http://127.0.0.1:9001"]

    def test_write_endpoint_unadvertised_is_noop(self, monkeypatch):
        monkeypatch.delenv(metrics.ENDPOINT_FILE_VAR, raising=False)
        assert metrics.write_endpoint("http://x", role="serve") is None


# ------------------------------------------------------- rollup and SLO
class TestRollupSLO:
    @staticmethod
    def _sample(i, qps=5.0, p99=4.0, status="ready", rejected=0.0):
        return {"url": f"http://r{i}", "ok": True, "status": status,
                "replica": str(i),
                "metrics": {"dltpu_serve_requests_per_s": qps,
                            "dltpu_serve_rejects_per_s": 0.0,
                            "dltpu_serve_e2e_ms_p99": p99,
                            "dltpu_serve_queue_depth": 2.0,
                            "dltpu_serve_requests_total": 100.0,
                            "dltpu_serve_completed_total": 98.0,
                            "dltpu_serve_rejected_total": rejected,
                            "dltpu_serve_timed_out_total": 0.0}}

    def test_rollup_folds(self):
        r = compute_rollup([self._sample(0, qps=5.0, p99=4.0),
                            self._sample(1, qps=7.0, p99=10.0),
                            {"url": "http://r2", "ok": False,
                             "status": "unreachable"}])
        assert r["replicas"] == 3
        assert r["replica_status"] == {"ready": 2, "unreachable": 1}
        assert r["qps_total"] == 12.0
        assert r["e2e_ms_p99_max"] == 10.0
        assert r["e2e_ms_p99_mean"] == 7.0
        assert r["queue_depth_total"] == 4.0
        assert r["requests_total"] == 200.0
        assert "slo" not in r

    def test_slo_p99_and_error_breach(self):
        slo = SLOPolicy(p99_budget_ms=5.0, error_rate_budget=0.1)
        ok = compute_rollup([self._sample(0, p99=4.0)], slo)
        assert ok["slo"]["breach"] is False
        bad = compute_rollup([self._sample(0, p99=50.0,
                                           rejected=90.0)], slo)
        assert bad["slo"]["p99_breach"] and bad["slo"]["error_breach"]
        assert bad["slo"]["breach"] is True
        assert bad["error_rate"] > 0.1

    def test_scraper_appends_and_records_breach(self, tmp_path):
        # a dead endpoint: rollup still lands, status unreachable;
        # error-rate SLO cannot breach on an empty fleet
        fleet_path = str(tmp_path / "fleet.jsonl")
        s = FleetScraper(["http://127.0.0.1:9"],
                         slo=SLOPolicy(p99_budget_ms=1.0),
                         fleet_path=fleet_path, timeout_s=0.2)
        rollup = s.scrape_once()
        assert rollup["replica_status"] == {"unreachable": 1}
        assert s.polls == 1 and s.breaches == 0
        rows = [json.loads(x) for x in open(fleet_path)]
        assert len(rows) == 1 and rows[0]["replicas"] == 1


# ------------------------------------------------------- tool self-tests
class TestToolChecks:
    def test_trace_merge_check(self):
        proc = subprocess.run(
            [sys.executable, os.path.join(ROOT, "tools",
                                          "trace_merge.py"), "--check"],
            capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, proc.stderr
        assert "OK" in proc.stdout

    def test_metrics_overhead_shape(self):
        import jax.numpy as jnp

        import bench_util
        res = bench_util.metrics_overhead(
            lambda x: x + 1, (jnp.ones((8,), jnp.float32),), n=3, reps=1)
        assert set(res) == {"metrics_off_ms", "metrics_on_ms",
                            "overhead_pct", "within_budget", "budget_pct"}
        assert res["metrics_on_ms"] > 0
        assert not metrics.enabled()     # A/B restored the disabled state


# ------------------------------------------------- multi-replica CPU e2e
@pytest.mark.e2e
class TestFleetE2E:
    def test_supervised_fleet_scrape_breach_drain_merge(self, tmp_path):
        """The ISSUE 11 acceptance run: supervise.py launches 2 serve
        replicas under one run id, load lands on both, the fleet
        scraper's rollup agrees with the per-replica /stats counters, a
        deliberately tiny p99 budget records an slo_breach flight
        event, SIGTERM drains the replicas gracefully, and trace_merge
        joins the per-replica traces into one timeline with 2 process
        rows."""
        wd = str(tmp_path / "fleet")
        env = dict(os.environ)
        env["DLTPU_TRACE"] = "1"
        env.pop("DLTPU_HEARTBEAT", None)
        cmd = [sys.executable, os.path.join(ROOT, "tools",
                                            "supervise.py"),
               "--replicas", "2", "--run-id", "fleet-test",
               "--workdir", wd,
               "--max-restarts", "0",
               # an idle serve replica only advances its activity
               # watermark per dispatched batch — a tight deadline
               # would read "idle" as "wedged"
               "--wedge-deadline", "600",
               "--startup-deadline", "600",
               "--",
               sys.executable, os.path.join(ROOT, "tools", "serve.py"),
               "--model", "mnist_fcn", "--num-classes", "10",
               "--size", "28", "--buckets", "1,4", "--max-wait-ms", "2",
               "--http", "0", "--wedge-deadline-s", "600"]
        log = open(os.path.join(str(tmp_path), "supervise.log"), "w")
        proc = subprocess.Popen(cmd, env=env, stdout=log,
                                stderr=subprocess.STDOUT)
        pids = []
        try:
            # both replicas advertise their scrape endpoint once warm
            deadline = time.time() + 240.0
            endpoints = []
            while time.time() < deadline:
                endpoints = discover_endpoints(wd)
                if len(endpoints) >= 2:
                    break
                assert proc.poll() is None, \
                    f"supervise died rc={proc.returncode}; see " \
                    f"{log.name}"
                time.sleep(0.25)
            assert len(endpoints) == 2, endpoints
            for i in range(2):
                doc = metrics.read_endpoint(
                    os.path.join(wd, f"replica-{i}", "endpoint.json"))
                assert doc["role"] == "serve"
                assert doc["run_id"] == "fleet-test"
                assert doc["replica"] == str(i)
                pids.append(doc["pid"])

            # load on both replicas: one 4-image batch x 3 posts each
            body = io.BytesIO()
            np.save(body, np.zeros((4, 28, 28, 3), np.float32))
            for url in endpoints:
                for _ in range(3):
                    req = urllib.request.Request(
                        url + "/predict", data=body.getvalue(),
                        method="POST")
                    with urllib.request.urlopen(req, timeout=60) as r:
                        assert len(json.loads(r.read())["results"]) == 4

            # scrape: rollup must agree with the per-replica /stats
            # counters; the absurd 1e-4 ms p99 budget injects a breach
            scraper = FleetScraper(
                endpoints, slo=SLOPolicy(p99_budget_ms=1e-4),
                fleet_path=os.path.join(wd, "fleet.jsonl"),
                timeout_s=10.0)
            rollup = scraper.scrape_once()
            stats = []
            for url in endpoints:
                with urllib.request.urlopen(url + "/stats",
                                            timeout=10) as r:
                    stats.append(json.loads(r.read()))
            assert rollup["replicas"] == 2
            assert rollup["replica_status"] == {"ready": 2}
            assert rollup["requests_total"] == \
                sum(s["submitted"] for s in stats) == 24.0
            assert rollup["completed_total"] == \
                sum(s["completed"] for s in stats) == 24.0
            assert rollup["e2e_ms_p99_max"] == \
                pytest.approx(max(s["e2e_ms_p99"] for s in stats))
            assert {(p["replica"], p["run_id"])
                    for p in rollup["per_replica"]} == \
                {("0", "fleet-test"), ("1", "fleet-test")}
            # SLO breach -> flight event in the scraping process
            assert rollup["slo"]["p99_breach"] and scraper.breaches == 1
            breaches = flight.get_recorder().events("slo_breach")
            assert breaches and breaches[0]["signal"] == "p99"
            assert breaches[0]["replicas"] == 2

            # the fleet view renders the breach from fleet.jsonl alone
            view = subprocess.run(
                [sys.executable, os.path.join(ROOT, "tools",
                                              "obs_report.py"),
                 wd, "--fleet"],
                capture_output=True, text=True, timeout=120)
            assert view.returncode == 0, view.stderr
            assert "BREACH" in view.stdout, view.stdout

            # graceful drain: SIGTERM each replica -> trace dumped,
            # supervisor records completion, fleet exits 0
            for pid in pids:
                os.kill(pid, signal.SIGTERM)
            assert proc.wait(timeout=120) == 0
        finally:
            for pid in pids:
                try:
                    os.kill(pid, signal.SIGKILL)
                except (OSError, ProcessLookupError):
                    pass
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)
            log.close()

        # one merged Perfetto timeline, one process row per replica
        out = os.path.join(str(tmp_path), "fleet_trace.json")
        merge = subprocess.run(
            [sys.executable, os.path.join(ROOT, "tools",
                                          "trace_merge.py"),
             "--out", out, wd],
            capture_output=True, text=True, timeout=60)
        assert merge.returncode == 0, merge.stderr
        doc = json.load(open(out))
        rows = {e["pid"]: e["args"]["name"] for e in doc["traceEvents"]
                if e.get("ph") == "M" and e["name"] == "process_name"}
        assert rows == {1: "replica-0", 2: "replica-1"}, rows
        assert doc["otherData"]["merged_from"] == 2
        spans_x = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        assert {e["pid"] for e in spans_x} == {1, 2}
        assert any(e["name"] == "serve/dispatch" for e in spans_x)
        labels = {s["label"]: s.get("run_id")
                  for s in doc["otherData"]["sources"]}
        assert labels == {"replica-0": "fleet-test",
                          "replica-1": "fleet-test"}
