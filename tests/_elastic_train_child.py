"""Training child launched by the supervisor e2e test (test_elastic.py).

Runs a tiny mnist_fcn classification job under the full elastic stack:
the mesh is chosen by the restart attempt (attempt 0 -> pure data
parallel, attempt >= 1 -> DP x TP, so any resume after the first launch
is a cross-topology resume), faults come from ``DLTPU_FAULTS``, the
heartbeat path from ``DLTPU_HEARTBEAT``, and a preemption signal turns
into exit code 75 exactly as in tools/train.py. One record per attempt
is appended to ``<workdir>/progress.jsonl`` so the test can assert step
continuity across restarts.

Usage: python tests/_elastic_train_child.py <workdir> [epochs]
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# Same forcing as tests/conftest.py: XLA_FLAGS is read at backend init
# (which has not happened yet), the platform must go through jax.config.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def main() -> int:
    workdir = sys.argv[1]
    epochs = int(sys.argv[2]) if len(sys.argv) > 2 else 3

    from deeplearning_tpu.core.registry import MODELS
    from deeplearning_tpu.data import ArraySource, DataLoader
    from deeplearning_tpu.elastic import EXIT_PREEMPTED, Preempted
    from deeplearning_tpu.elastic.faults import current_attempt
    from deeplearning_tpu.parallel import MeshConfig, build_mesh
    from deeplearning_tpu.parallel.mesh import mesh_shape_str
    from deeplearning_tpu.parallel.sharding import TRANSFORMER_TP_RULES
    from deeplearning_tpu.train import TrainState, make_train_step
    from deeplearning_tpu.train.classification import (make_loss_fn,
                                                       make_metric_fn)
    from deeplearning_tpu.train.optim import build_optimizer
    from deeplearning_tpu.train.schedules import build_schedule
    from deeplearning_tpu.train.steps import make_eval_step, shard_state
    from deeplearning_tpu.train.trainer import Trainer

    attempt = current_attempt()
    if attempt == 0:
        mesh = build_mesh(MeshConfig(data=-1))
        rules = None
    else:
        mesh = build_mesh(MeshConfig(data=-1, model=2))
        rules = TRANSFORMER_TP_RULES

    rng = np.random.default_rng(0)
    n, batch = 96, 16
    labels = rng.integers(0, 4, n).astype(np.int32)
    images = rng.normal(0, 0.1, (n, 16, 16, 1)).astype(np.float32)
    for i, lab in enumerate(labels):
        images[i, :, lab * 4:(lab + 1) * 4, 0] += 2.0

    model = MODELS.build("mnist_fcn", num_classes=4, dtype=jnp.float32)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 16, 16, 1)))["params"]
    tx = build_optimizer(
        "sgd", build_schedule("constant", base_lr=0.1), params=params)
    state = TrainState.create(apply_fn=model.apply, params=params, tx=tx)
    state = shard_state(state, mesh, rules)

    trainer = Trainer(
        state=state,
        train_step=make_train_step(make_loss_fn(), donate=False, mesh=mesh),
        train_loader=DataLoader(ArraySource(image=images, label=labels),
                                global_batch=batch, seed=0),
        eval_step=make_eval_step(make_metric_fn(ks=(1,)), mesh=mesh),
        eval_loader=DataLoader(ArraySource(image=images, label=labels),
                               global_batch=batch, shuffle=False),
        epochs=epochs, log_every=100, workdir=workdir,
        async_checkpoint=True, save_every_epochs=1,
        log_backends=("jsonl",), obs=True,
    )

    start_step = trainer.ckpt.latest_step() or 0

    def progress(outcome: str) -> None:
        rec = {"attempt": attempt, "start_step": int(start_step),
               "final_step": int(trainer.state.step),
               "mesh": mesh_shape_str(mesh), "outcome": outcome}
        with open(os.path.join(workdir, "progress.jsonl"), "a") as f:
            f.write(json.dumps(rec) + "\n")

    try:
        trainer.train()
    except Preempted:
        progress("preempted")
        return EXIT_PREEMPTED
    progress("completed")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
