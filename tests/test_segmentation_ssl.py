"""Segmentation + SSL model tests: shapes, losses, learning checks."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deeplearning_tpu.core.registry import MODELS
from deeplearning_tpu.evaluation.metrics import (confusion_matrix,
                                                 miou_from_confusion)
from deeplearning_tpu.ops import losses as L


class TestSegmentationModels:
    @pytest.mark.parametrize("name", ["unet", "fcn_resnet50",
                                      "deeplabv3_resnet50",
                                      "deeplabv3plus_resnet50",
                                      "hrnet_w18_seg"])
    def test_forward_shape(self, name):
        model = MODELS.build(name, num_classes=5, dtype=jnp.float32)
        x = jnp.zeros((1, 64, 64, 3))
        variables = model.init(jax.random.key(0), x, train=False)
        out = model.apply(variables, x, train=False)
        assert out.shape == (1, 64, 64, 5)
        assert np.all(np.isfinite(np.asarray(out)))

    def test_fcn_aux_tuple_in_train(self):
        model = MODELS.build("fcn_resnet50", num_classes=3,
                             dtype=jnp.float32)
        x = jnp.zeros((1, 64, 64, 3))
        variables = model.init(jax.random.key(0), x, train=False)
        out = model.apply(variables, x, train=True,
                          rngs={"dropout": jax.random.key(1)},
                          mutable=["batch_stats"])[0]
        logits, aux = out
        assert logits.shape == aux.shape == (1, 64, 64, 3)

    def test_unet_overfits_binary_mask(self):
        model = MODELS.build("unet", num_classes=2, base_features=8,
                             dtype=jnp.float32)
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(0, 0.1, (2, 32, 32, 3)), jnp.float32)
        y = np.zeros((2, 32, 32), np.int32)
        y[:, 8:24, 8:24] = 1
        x = x.at[:, 8:24, 8:24, :].add(1.5)
        y = jnp.asarray(y)
        variables = model.init(jax.random.key(0), x, train=False)
        params, stats = variables["params"], variables["batch_stats"]
        tx = optax.adam(3e-3)
        opt = tx.init(params)

        @jax.jit
        def step(params, opt, stats):
            def loss_fn(p):
                logits, mut = model.apply(
                    {"params": p, "batch_stats": stats}, x, train=True,
                    mutable=["batch_stats"])
                loss = L.cross_entropy(logits, y) + L.dice_loss(logits, y)
                return loss, mut["batch_stats"]
            (loss, stats2), g = jax.value_and_grad(loss_fn, has_aux=True)(
                params)
            up, opt = tx.update(g, opt, params)
            return optax.apply_updates(params, up), opt, stats2, loss

        first = None
        for _ in range(30):
            params, opt, stats, loss = step(params, opt, stats)
            first = first or float(loss)
        assert float(loss) < first * 0.3
        # mIoU on the training image should be high
        logits = model.apply({"params": params, "batch_stats": stats}, x,
                             train=False)
        pred = jnp.argmax(logits, -1)
        cm = confusion_matrix(pred, y, 2)
        m = miou_from_confusion(np.asarray(cm))
        assert m["miou"] > 0.8

    def test_hrnet_keypoint_head_stride4(self):
        model = MODELS.build("hrnet_w18_keypoints", num_classes=7,
                             dtype=jnp.float32)
        x = jnp.zeros((1, 64, 64, 3))
        variables = model.init(jax.random.key(0), x, train=False)
        out = model.apply(variables, x, train=False)
        assert out.shape == (1, 16, 16, 7)


class TestMAE:
    def test_loss_and_shapes(self):
        model = MODELS.build("mae_vit_small_patch16", dtype=jnp.float32)
        x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 64, 64, 3)),
                        jnp.float32)
        variables = model.init(
            {"params": jax.random.key(0), "masking": jax.random.key(1)},
            x, train=False)
        loss, pred, mask = model.apply(
            variables, x, train=False, rngs={"masking": jax.random.key(2)})
        n = (64 // 16) ** 2
        assert pred.shape == (2, n, 16 * 16 * 3)
        assert mask.shape == (2, n)
        # exactly 75% masked
        assert int(mask.sum()) == int(2 * n * 0.75)
        assert np.isfinite(float(loss))

    def test_mask_ratio_token_saving(self):
        # encoder must only process kept tokens: check intermediate shape
        from deeplearning_tpu.models.ssl.mae import random_masking
        x = jnp.arange(2 * 16 * 4, dtype=jnp.float32).reshape(2, 16, 4)
        kept, mask, restore = random_masking(x, 0.75, jax.random.key(0))
        assert kept.shape == (2, 4, 4)
        # restore permutation is the inverse of the shuffle: gathering the
        # kept+masked concat by restore puts kept rows where mask==0
        assert np.all(np.asarray(mask.sum(1)) == 12)

    def test_loss_decreases(self):
        model = MODELS.build("mae_vit_small_patch16", dtype=jnp.float32,
                             decoder_depth=2, depth=2)
        x = jnp.asarray(np.random.default_rng(0).normal(size=(4, 32, 32, 3)),
                        jnp.float32)
        variables = model.init(
            {"params": jax.random.key(0), "masking": jax.random.key(1)},
            x, train=False)
        params = variables["params"]
        tx = optax.adam(1e-3)
        opt = tx.init(params)

        @jax.jit
        def step(params, opt, i):
            def loss_fn(p):
                loss, _, _ = model.apply(
                    {"params": p}, x, train=True,
                    rngs={"masking": jax.random.key(5),
                          "dropout": jax.random.fold_in(jax.random.key(6), i)})
                return loss
            loss, g = jax.value_and_grad(loss_fn)(params)
            up, opt = tx.update(g, opt, params)
            return optax.apply_updates(params, up), opt, loss

        first = None
        for i in range(20):
            params, opt, loss = step(params, opt, i)
            first = first or float(loss)
        assert float(loss) < first * 0.8


class TestSupCon:
    def test_projection_normalized_and_loss(self):
        model = MODELS.build("supcon_resnet18", num_classes=4,
                             dtype=jnp.float32)
        x = jnp.asarray(np.random.default_rng(0).normal(size=(8, 32, 32, 3)),
                        jnp.float32)
        variables = model.init(jax.random.key(0), x, train=False)
        z = model.apply(variables, x, train=False)
        norms = np.linalg.norm(np.asarray(z), axis=-1)
        np.testing.assert_allclose(norms, 1.0, atol=1e-5)
        # two views: (B, V, D)
        feats = jnp.stack([z, z], axis=1)
        labels = jnp.asarray([0, 0, 1, 1, 2, 2, 3, 3])
        loss = L.supcon_loss(feats, labels)
        assert np.isfinite(float(loss)) and float(loss) > 0
        # classify mode
        logits = model.apply(variables, x, train=False, mode="classify")
        assert logits.shape == (8, 4)

    def test_swa_average(self):
        from deeplearning_tpu.models.ssl.supcon import swa_update
        p1 = {"w": jnp.ones(3)}
        p2 = {"w": jnp.ones(3) * 3}
        swa, n = swa_update(None, p1, 0)
        swa, n = swa_update(swa, p2, n)
        np.testing.assert_allclose(np.asarray(swa["w"]), 2.0)
        assert n == 2
