"""Test env: 8 virtual CPU devices so mesh/pjit/collective paths run in CI
without a pod (SURVEY.md §4 rebuild strategy (b)).

Note: this image's sitecustomize imports jax at interpreter start (axon TPU
tunnel), so JAX_PLATFORMS in os.environ is read too early to help — the
platform must be forced via jax.config, and the host-device-count flag via
XLA_FLAGS before backend initialization (which register() does not do).
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")


def pytest_collection_modifyitems(config, items):
    """Run subprocess-spawning e2e tests after everything else: each
    child process re-imports jax and recompiles its step from scratch,
    making them the priciest items in the suite — fast unit feedback
    should not queue behind them under a tight CI time budget."""
    tail = [it for it in items if it.get_closest_marker("e2e")]
    if tail:
        tail_set = set(tail)
        items[:] = [it for it in items if it not in tail_set] + tail
