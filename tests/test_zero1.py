"""ZeRO-1 weight-update sharding + quantized collectives (ISSUE 10).

The compiler-driven ZeRO-1 contract: ``shard_state(zero1=True)`` splits
optimizer moments 1/dp per device, ``make_train_step(weight_update=
"zero1")`` keeps them there across steps with loss parity against the
replicated baseline, and the EQuARX-style int8 collectives reduce
gradients bitwise-exactly on small-integer payloads with a documented
error bound on general values. The HLO-level proof that the lowering is
reduce-scatter -> shard-update -> all-gather lives in the jaxpr audits
(tests/test_analysis.py); here we test semantics and memory."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deeplearning_tpu.core.registry import MODELS
from deeplearning_tpu.parallel import MeshConfig, build_mesh
from deeplearning_tpu.parallel._compat import shard_map
from deeplearning_tpu.parallel.collectives import (
    quantized_psum, quantized_psum_tree, quantized_reduce_scatter)
from deeplearning_tpu.parallel.sharding import (
    DATA_AXIS, FSDP_AXIS, P, batch_sharding, shard_layout_summary,
    tree_bytes_per_device, zero1_partition_spec)
from deeplearning_tpu.train import TrainState, make_train_step, shard_state
from deeplearning_tpu.train.classification import make_loss_fn

AXES = (DATA_AXIS, FSDP_AXIS)

needs_devices = pytest.mark.skipif(len(jax.devices()) < 8,
                                   reason="needs 8 (virtual) devices")


def _mnist_state(seed: int = 0, tx=None) -> TrainState:
    model = MODELS.build("mnist_fcn", num_classes=4, dtype=jnp.float32)
    params = model.init(jax.random.key(seed),
                        jnp.zeros((1, 16, 16, 1)))["params"]
    return TrainState.create(apply_fn=model.apply, params=params,
                             tx=tx if tx is not None else optax.adamw(1e-3))


def _mnist_batch(rng: np.random.Generator, n: int):
    return {"image": jnp.asarray(rng.normal(size=(n, 16, 16, 1)),
                                 jnp.float32),
            "label": jnp.asarray(rng.integers(0, 4, n), jnp.int32)}


class TestZero1PartitionSpec:
    def test_first_divisible_dim_wins(self):
        assert zero1_partition_spec((16, 24), 8) == P(AXES, None)
        # dim 0 indivisible, dim 1 divides -> dim 1 carries the shard
        assert zero1_partition_spec((10, 16), 8) == P(None, AXES)

    def test_indivisible_leaf_replicates(self):
        assert zero1_partition_spec((10,), 8) == P()
        assert zero1_partition_spec((4,), 8) == P()      # smaller than dp
        assert zero1_partition_spec((), 8) == P()

    def test_dp1_is_noop(self):
        assert zero1_partition_spec((512, 512), 1) == P()


@needs_devices
class TestZero1Memory:
    def test_opt_bytes_shrink_by_data_extent(self):
        """The headline claim: per-device optimizer bytes under zero1 are
        <= 1/dp of replicated, plus only the non-divisible tail that
        legitimately stays replicated."""
        mesh = build_mesh(MeshConfig(data=-1))
        dp = mesh.shape[DATA_AXIS] * mesh.shape[FSDP_AXIS]

        rep = shard_state(_mnist_state(0), mesh, zero1=False)
        z = shard_state(_mnist_state(0), mesh, zero1=True)
        rep_bytes = tree_bytes_per_device(rep.opt_state)
        z_bytes = tree_bytes_per_device(z.opt_state)

        # slack = whatever zero1 left replicated (odd-width biases,
        # scalar counters) — everything else must be a true 1/dp shard
        slack = sum(
            int(np.prod(leaf.shape, dtype=np.int64)) * leaf.dtype.itemsize
            for leaf in jax.tree.leaves(z.opt_state)
            if leaf.sharding.is_fully_replicated)
        assert z_bytes <= rep_bytes // dp + slack
        # and the shrink is real, not vacuous
        assert z_bytes < rep_bytes // 2

    def test_non_divisible_tail_stays_replicated_and_visible(self):
        """mnist_fcn's 4-class head bias (4,) cannot split 8 ways: it
        must stay replicated and shard_layout_summary must show the
        mixed layout rather than papering over it."""
        mesh = build_mesh(MeshConfig(data=-1))
        z = shard_state(_mnist_state(0), mesh, zero1=True)

        summary = shard_layout_summary(z.opt_state)
        assert summary["sharded"] > 0
        assert summary["replicated"] > 0
        # the (4,) head bias moments are in the replicated bucket...
        head_bias = [path for path in summary["specs"]
                     if path.endswith("Dense_2/bias")]
        assert not head_bias
        # ...while the matching (256, 4) head kernel moments sharded
        assert any(path.endswith("Dense_2/kernel")
                   for path in summary["specs"])
        # params are untouched by zero1 — pure DP stays fully replicated
        assert shard_layout_summary(z.params)["sharded"] == 0


@needs_devices
class TestZero1Parity:
    def test_50_step_loss_parity_and_stable_layout(self):
        """50 optimizer steps under zero1 track the replicated baseline
        at float-roundoff level (the math is the same Adam, only
        sharded), and the moment layout is a fixed point of the step —
        no per-step reshuffling creeping in."""
        mesh = build_mesh(MeshConfig(data=-1))
        loss_fn = make_loss_fn()
        step_rep = make_train_step(loss_fn, mesh=mesh)
        step_z = make_train_step(loss_fn, mesh=mesh, weight_update="zero1")

        st_rep = shard_state(_mnist_state(0), mesh, zero1=False)
        st_z = shard_state(_mnist_state(0), mesh, zero1=True)

        layout0 = None
        losses_rep, losses_z = [], []
        g = np.random.default_rng(0)
        for i in range(50):
            batch = jax.device_put(_mnist_batch(g, 64),
                                   batch_sharding(mesh))
            rng = jax.random.key(i)
            st_rep, m_rep = step_rep(st_rep, batch, rng)
            st_z, m_z = step_z(st_z, batch, rng)
            losses_rep.append(float(m_rep["loss"]))
            losses_z.append(float(m_z["loss"]))
            if i == 0:
                layout0 = shard_layout_summary(st_z.opt_state)
                bytes0 = tree_bytes_per_device(st_z.opt_state)

        np.testing.assert_allclose(losses_z, losses_rep,
                                   rtol=1e-5, atol=1e-5)
        # final params agree leaf-by-leaf at accumulated-roundoff scale
        # (per-step diff is ~1e-7; 50 Adam steps compound to ~1e-5)
        for lz, lr in zip(jax.tree.leaves(st_z.params),
                          jax.tree.leaves(st_rep.params)):
            np.testing.assert_allclose(np.asarray(lz), np.asarray(lr),
                                       rtol=1e-3, atol=1e-4)
        # layout and per-device footprint are step-invariant
        assert shard_layout_summary(st_z.opt_state) == layout0
        assert tree_bytes_per_device(st_z.opt_state) == bytes0
        assert shard_layout_summary(st_z.params)["sharded"] == 0


class TestGradDtypePolicy:
    """The fp32-gradient unification satellite: with bf16 params the
    optimizer must see fp32 gradients on BOTH the single-step and the
    accumulation paths (before ISSUE 10 the accum path upcast and the
    accum_steps=1 path handed optax raw bf16)."""

    @pytest.mark.parametrize("accum_steps", [1, 2])
    def test_optimizer_sees_fp32_grads(self, accum_steps):
        seen = set()
        base = optax.sgd(1e-2)

        def update(grads, opt_state, params=None):
            seen.update(str(l.dtype) for l in jax.tree.leaves(grads))
            return base.update(grads, opt_state, params)

        params = {"w": jnp.full((8, 4), 0.5, jnp.bfloat16)}
        state = TrainState.create(
            apply_fn=lambda *a, **k: None, params=params,
            tx=optax.GradientTransformation(base.init, update))

        def loss_fn(params, state, batch, rng):
            pred = batch["x"].astype(jnp.bfloat16) @ params["w"]
            loss = jnp.mean((pred.astype(jnp.float32) - batch["y"]) ** 2)
            return loss, {}

        step = make_train_step(loss_fn, accum_steps=accum_steps,
                               donate=False)
        batch = {"x": jnp.ones((4, 8)), "y": jnp.zeros((4, 4))}
        state, metrics = step(state, batch, jax.random.key(0))
        assert np.isfinite(float(metrics["loss"]))
        assert seen == {"float32"}, (
            f"optimizer saw {seen} grads at accum_steps={accum_steps}")


@needs_devices
class TestQuantizedCollectives:
    def _mesh(self):
        return build_mesh(MeshConfig(data=-1))

    def test_psum_bitwise_exact_on_small_ints(self):
        """Power-of-two block scales shift integer payloads losslessly:
        on small-int values (and sums) the quantized all-reduce is
        BITWISE equal to jax.lax.psum."""
        mesh = self._mesh()
        n = mesh.shape[DATA_AXIS] * mesh.shape[FSDP_AXIS]
        g = np.random.default_rng(0)
        vals = jnp.asarray(g.integers(-7, 8, (n, 96)), jnp.float32)

        f = jax.jit(shard_map(
            lambda x: (quantized_psum(x[0], AXES, block=16),
                       jax.lax.psum(x[0], AXES)),
            mesh=mesh, in_specs=(P(AXES),), out_specs=(P(), P()),
            check_vma=False))
        q, exact = f(vals)
        np.testing.assert_array_equal(np.asarray(q), np.asarray(exact))

    def test_psum_tree_gaussian_error_bound(self):
        """General-case accuracy: two quantization stages bound the
        error at ~2/127 of the block max — assert the documented 5%
        relative bound with plenty of margin (measured ~1%)."""
        mesh = self._mesh()
        n = mesh.shape[DATA_AXIS] * mesh.shape[FSDP_AXIS]
        g = np.random.default_rng(1)
        tree = {"a": jnp.asarray(g.normal(size=(n, 4096)), jnp.float32),
                "b": jnp.asarray(g.normal(size=(n, 33, 7)), jnp.float32)}

        f = jax.jit(shard_map(
            lambda t: (quantized_psum_tree(
                           jax.tree.map(lambda x: x[0], t), AXES),
                       jax.tree.map(lambda x: jax.lax.psum(x[0], AXES), t)),
            mesh=mesh,
            in_specs=(jax.tree.map(lambda _: P(AXES), tree),),
            out_specs=(jax.tree.map(lambda _: P(), tree),) * 2,
            check_vma=False))
        q, exact = f(tree)
        for key in tree:
            qe = np.asarray(q[key]), np.asarray(exact[key])
            rel = np.abs(qe[0] - qe[1]).max() / np.abs(qe[1]).max()
            assert rel < 0.05, f"{key}: rel err {rel:.4f} exceeds bound"

    def test_reduce_scatter_matches_psum_slice(self):
        """Each replica's reduce-scatter shard is its leading-dim slice
        of the full sum — gathering the shards reconstructs psum, and
        the single-stage path is exact on integer payloads."""
        mesh = self._mesh()
        n = mesh.shape[DATA_AXIS] * mesh.shape[FSDP_AXIS]
        g = np.random.default_rng(2)
        vals = jnp.asarray(g.integers(-5, 6, (n, 2 * n, 5)), jnp.float32)

        f = jax.jit(shard_map(
            lambda x: (quantized_reduce_scatter(x[0], AXES, block=16),
                       jax.lax.psum(x[0], AXES)),
            mesh=mesh, in_specs=(P(AXES),),
            out_specs=(P(AXES), P()), check_vma=False))
        scattered, full = f(vals)       # shards gather back to (2n, 5)
        np.testing.assert_array_equal(np.asarray(scattered),
                                      np.asarray(full))

    def test_reduce_scatter_rejects_indivisible_dim0(self):
        mesh = self._mesh()
        n = mesh.shape[DATA_AXIS] * mesh.shape[FSDP_AXIS]
        vals = jnp.ones((n, n + 1, 3), jnp.float32)
        f = shard_map(
            lambda x: quantized_reduce_scatter(x[0], AXES),
            mesh=mesh, in_specs=(P(AXES),), out_specs=P(AXES),
            check_vma=False)
        with pytest.raises(ValueError, match="dim0"):
            jax.jit(f)(vals)


@needs_devices
class TestInt8TrainStep:
    def test_step_parity_against_fp32_rng_free(self):
        """One SGD step on an RNG-free linear MSE model: the int8-reduced
        update differs from the fp32 baseline by at most 5% of the max
        update magnitude (the per-leaf quantization bound), and the
        reported loss — which rides an fp32 pmean, never the int8 wire —
        matches tightly."""
        mesh = build_mesh(MeshConfig(data=-1))

        def loss_fn(params, state, batch, rng):
            pred = batch["image"] @ params["w"]
            return jnp.mean((pred - batch["label"]) ** 2), {}

        def fresh():
            params = {"w": jnp.zeros((16, 4), jnp.float32)}
            return shard_state(
                TrainState.create(apply_fn=lambda *a, **k: None,
                                  params=params, tx=optax.sgd(0.1)),
                mesh)

        g = np.random.default_rng(0)
        batch = {"image": jnp.asarray(g.normal(size=(32, 16)),
                                      jnp.float32),
                 "label": jnp.asarray(g.normal(size=(32, 4)),
                                      jnp.float32)}
        batch = jax.device_put(batch, batch_sharding(mesh))
        rng = jax.random.key(0)

        base = fresh()
        st32, m32 = make_train_step(loss_fn, mesh=mesh,
                                    donate=False)(fresh(), batch, rng)
        st8, m8 = make_train_step(loss_fn, mesh=mesh, donate=False,
                                  grad_comm="int8")(fresh(), batch, rng)

        w32 = np.asarray(st32.params["w"])
        w8 = np.asarray(st8.params["w"])
        update_scale = np.abs(w32 - np.asarray(base.params["w"])).max()
        assert update_scale > 0          # the step actually moved
        assert np.abs(w8 - w32).max() <= 0.05 * update_scale
        np.testing.assert_allclose(float(m8["loss"]), float(m32["loss"]),
                                   rtol=1e-5)

    def test_zero1_int8_mnist_smoke(self):
        """The combined mode — moment-sharded update fed by int8
        reduce-scatter gradients — trains mnist_fcn to finite decreasing
        loss with the moment layout intact."""
        mesh = build_mesh(MeshConfig(data=-1))
        state = shard_state(_mnist_state(0), mesh, zero1=True)
        step = make_train_step(make_loss_fn(), mesh=mesh,
                               weight_update="zero1", grad_comm="int8")
        g = np.random.default_rng(0)
        batch = jax.device_put(_mnist_batch(g, 64), batch_sharding(mesh))
        losses = []
        for i in range(10):
            state, metrics = step(state, batch, jax.random.key(i))
            losses.append(float(metrics["loss"]))
        assert all(np.isfinite(l) for l in losses)
        assert losses[-1] < losses[0]    # same batch: loss must drop
        assert shard_layout_summary(state.opt_state)["sharded"] > 0


class TestMakeTrainStepValidation:
    def test_zero1_requires_mesh(self):
        with pytest.raises(ValueError, match="mesh"):
            make_train_step(make_loss_fn(), weight_update="zero1")

    def test_int8_requires_mesh(self):
        with pytest.raises(ValueError, match="mesh"):
            make_train_step(make_loss_fn(), grad_comm="int8")

    @needs_devices
    def test_int8_rejects_accum_and_rules(self):
        mesh = build_mesh(MeshConfig(data=-1))
        with pytest.raises(ValueError, match="accum_steps"):
            make_train_step(make_loss_fn(), mesh=mesh,
                            grad_comm="int8", accum_steps=4)
        from deeplearning_tpu.parallel.sharding import TRANSFORMER_TP_RULES
        with pytest.raises(ValueError, match="data-parallel only"):
            make_train_step(make_loss_fn(), mesh=mesh,
                            grad_comm="int8", rules=TRANSFORMER_TP_RULES)

    def test_unknown_modes_rejected(self):
        with pytest.raises(ValueError, match="weight_update"):
            make_train_step(make_loss_fn(), weight_update="zero3")
        with pytest.raises(ValueError, match="grad_comm"):
            make_train_step(make_loss_fn(), grad_comm="fp8")
