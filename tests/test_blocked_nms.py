"""Blocked bitmask NMS + one-pass RoIAlign: equivalence, memory and
wall-clock acceptance.

The contract under test (ISSUE 3 tentpole): the blocked lax sweep and
the Pallas tile kernel produce the *same keep set in the same order* as
the greedy reference across randomized cases, never materialize an N×N
IoU buffer, and beat the reference by >= 3x wall-clock at N=20k on CPU;
one-pass multiscale RoIAlign matches the masked reference bitwise-close
while doing a single bilinear sampling pass.
"""

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning_tpu.analysis import jaxpr as ana_jaxpr
from deeplearning_tpu.ops import nms as nms_ops
from deeplearning_tpu.ops import roi_align as roi_ops
from deeplearning_tpu.ops.pallas import nms as pallas_nms


def make_cases(rng, cases, n, span=64.0, wh_max=24.0, nan_frac=0.0):
    """Overlap-heavy random boxes: (cases, n, 4) + scores (cases, n)."""
    ctr = rng.uniform(0, span, (cases, n, 2))
    wh = rng.uniform(2.0, wh_max, (cases, n, 2))
    boxes = np.concatenate([ctr - wh / 2, ctr + wh / 2],
                           axis=-1).astype(np.float32)
    scores = rng.uniform(0.0, 1.0, (cases, n)).astype(np.float32)
    if nan_frac:
        mask = rng.uniform(size=scores.shape) < nan_frac
        scores[mask] = np.nan
    return jnp.asarray(boxes), jnp.asarray(scores)


def assert_same_keeps(ref, got, context=""):
    """(idx, valid) pairs agree: same valid mask, same indices on valid
    slots (both paths emit keeps in descending-score order)."""
    i1, v1 = map(np.asarray, ref)
    i2, v2 = map(np.asarray, got)
    assert np.array_equal(v1, v2), f"valid mask mismatch {context}"
    assert np.all((i1 == i2) | ~v1), f"keep indices mismatch {context}"


# The four (iou_thresh, score_thresh, max_out) regimes the property
# tests sweep; with 256 random cases each, every path sees >= 1024
# randomized cases total.
CONFIGS = [
    (0.5, float("-inf"), 64),
    (0.3, 0.25, 32),
    (0.7, 0.5, 16),
    (0.45, 0.05, 100),
]


class TestBlockedEquivalence:
    """Lax blocked sweep == greedy reference, 1024 randomized cases."""

    def test_keep_set_equivalence_1024_cases(self):
        rng = np.random.default_rng(0)
        n = 200                       # pads to 256 at block 64 (nb=4)
        for ci, (th, st, mo) in enumerate(CONFIGS):
            ref = jax.jit(jax.vmap(functools.partial(
                nms_ops.nms_reference, iou_threshold=th, max_out=mo,
                score_threshold=st)))
            blk = jax.jit(jax.vmap(functools.partial(
                nms_ops.nms_blocked, iou_threshold=th, max_out=mo,
                score_threshold=st, block_size=64)))
            boxes, scores = make_cases(rng, 256, n,
                                       nan_frac=0.02 if ci == 0 else 0.0)
            assert_same_keeps(ref(boxes, scores), blk(boxes, scores),
                              context=f"config {ci}")

    def test_class_aware_equivalence(self):
        rng = np.random.default_rng(1)
        boxes, scores = make_cases(rng, 128, 150)
        classes = jnp.asarray(
            rng.integers(0, 5, (128, 150)).astype(np.int32))
        ref = jax.jit(jax.vmap(functools.partial(
            nms_ops.batched_nms, iou_threshold=0.5, max_out=40,
            score_threshold=0.1, impl="greedy")))
        blk = jax.jit(jax.vmap(functools.partial(
            nms_ops.batched_nms, iou_threshold=0.5, max_out=40,
            score_threshold=0.1, impl="blocked", block_size=32)))
        assert_same_keeps(ref(boxes, scores, classes),
                          blk(boxes, scores, classes), "class-aware")

    def test_all_suppressed_single_keep(self):
        # identical boxes: exactly the top-scoring one survives
        boxes = jnp.tile(jnp.asarray([[10., 10., 20., 20.]]), (64, 1))
        scores = jnp.linspace(0.1, 0.9, 64)
        idx, valid = nms_ops.nms_blocked(boxes, scores, 0.5, 10,
                                         block_size=16)
        assert int(valid.sum()) == 1
        assert int(idx[0]) == 63      # highest score
        assert_same_keeps(nms_ops.nms_reference(boxes, scores, 0.5, 10),
                          (idx, valid), "all-suppressed")

    def test_empty_below_threshold(self):
        rng = np.random.default_rng(2)
        boxes, scores = make_cases(rng, 1, 80)
        for fn in (nms_ops.nms_reference, nms_ops.nms_blocked):
            idx, valid = fn(boxes[0], scores[0], 0.5, 20,
                            score_threshold=2.0)   # nothing passes
            assert int(np.asarray(valid).sum()) == 0
            assert np.all(np.asarray(idx) == 0)

    def test_max_out_exceeds_n(self):
        rng = np.random.default_rng(3)
        boxes, scores = make_cases(rng, 1, 7, span=500.0, wh_max=4.0)
        assert_same_keeps(
            nms_ops.nms_reference(boxes[0], scores[0], 0.5, 32),
            nms_ops.nms_blocked(boxes[0], scores[0], 0.5, 32),
            "max_out > n")

    def test_dispatcher_and_default(self):
        rng = np.random.default_rng(4)
        boxes, scores = make_cases(rng, 1, 300)
        ref = nms_ops.nms(boxes[0], scores[0], 0.5, 30, impl="greedy")
        for impl in ("blocked", "pallas", "auto", "reference"):
            assert_same_keeps(ref,
                              nms_ops.nms(boxes[0], scores[0], 0.5, 30,
                                          impl=impl), impl)
        prev = nms_ops.set_default_nms_impl("greedy")
        try:
            assert nms_ops.get_default_nms_impl() == "greedy"
            assert_same_keeps(ref, nms_ops.nms(boxes[0], scores[0],
                                               0.5, 30), "default")
        finally:
            nms_ops.set_default_nms_impl(prev)
        with pytest.raises(ValueError):
            nms_ops.set_default_nms_impl("cuda")


class TestPallasEquivalence:
    """Pallas tile kernel (interpret mode on CPU) == greedy reference,
    1024 randomized cases."""

    def test_keep_set_equivalence_1024_cases(self):
        rng = np.random.default_rng(10)
        n = 200                       # pads to 256 at block 64
        for ci, (th, st, mo) in enumerate(CONFIGS):
            ref = jax.jit(jax.vmap(functools.partial(
                nms_ops.nms_reference, iou_threshold=th, max_out=mo,
                score_threshold=st)))
            pal = jax.jit(jax.vmap(functools.partial(
                pallas_nms.nms_pallas, iou_threshold=th, max_out=mo,
                score_threshold=st, block_size=64)))
            boxes, scores = make_cases(rng, 256, n,
                                       nan_frac=0.02 if ci == 0 else 0.0)
            assert_same_keeps(ref(boxes, scores), pal(boxes, scores),
                              context=f"config {ci}")

    def test_single_block_and_padding(self):
        rng = np.random.default_rng(11)
        for n, block in ((40, 64), (64, 64), (65, 64), (500, 128)):
            boxes, scores = make_cases(rng, 1, n)
            assert_same_keeps(
                nms_ops.nms_reference(boxes[0], scores[0], 0.5, 25),
                pallas_nms.nms_pallas(boxes[0], scores[0], 0.5, 25,
                                      block_size=block),
                f"n={n} block={block}")

    def test_all_suppressed(self):
        boxes = jnp.tile(jnp.asarray([[5., 5., 30., 30.]]), (100, 1))
        scores = jnp.linspace(0.0, 1.0, 100)
        idx, valid = pallas_nms.nms_pallas(boxes, scores, 0.5, 10,
                                           block_size=32)
        assert int(valid.sum()) == 1 and int(idx[0]) == 99


class TestMemory:
    """The inline jaxpr walk these tests used to carry now lives in
    ``analysis.jaxpr`` (one implementation, same bounds) — the linter's
    sibling auditor, also run by ``tools/check.py --jaxpr``."""

    def test_no_nxn_intermediate(self):
        """The blocked path's biggest intermediate is O(N*B), never N^2."""
        n, block = 4096, 256
        boxes = jnp.zeros((n, 4))
        scores = jnp.zeros((n,))
        biggest = ana_jaxpr.assert_peak_intermediate_below(
            functools.partial(nms_ops.nms_blocked, iou_threshold=0.5,
                              max_out=100, block_size=block),
            (boxes, scores), 4 * n * block, msg="O(N*B) budget")
        assert biggest < n * n // 2, \
            f"blocked NMS materializes a near-N^2 buffer ({biggest})"
        # sanity: the checker DOES see the reference's N x N buffer
        biggest_ref = ana_jaxpr.peak_intermediate(
            functools.partial(nms_ops.nms_reference, iou_threshold=0.5,
                              max_out=100), boxes, scores)
        assert biggest_ref >= n * n

    def test_pallas_wrapper_no_nxn(self):
        n = 2048
        biggest = ana_jaxpr.peak_intermediate(
            functools.partial(pallas_nms.nms_pallas, iou_threshold=0.5,
                              max_out=100, block_size=256),
            jnp.zeros((n, 4)), jnp.zeros((n,)))
        assert biggest < n * n // 2


def _bench(fn, *args, reps=3):
    out = fn(*args)
    jax.tree.map(lambda x: x.block_until_ready(), out)
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.tree.map(lambda x: x.block_until_ready(), out)
        times.append(time.perf_counter() - t0)
    return min(times)


class TestWallClock:
    """CPU wall-clock acceptance (style of the prefetcher's 1.15x test
    in test_device_prefetch.py): the asymptotics must show up as real
    time even on the CPU backend."""

    def test_blocked_3x_faster_at_20k(self):
        rng = np.random.default_rng(20)
        n, mo = 20000, 100
        boxes, scores = make_cases(rng, 1, n, span=2000.0, wh_max=64.0)
        boxes, scores = boxes[0], scores[0]
        ref = jax.jit(functools.partial(nms_ops.nms_reference,
                                        iou_threshold=0.5, max_out=mo))
        blk = jax.jit(functools.partial(nms_ops.nms_blocked,
                                        iou_threshold=0.5, max_out=mo))
        assert_same_keeps(ref(boxes, scores), blk(boxes, scores),
                          "20k pre-timing")
        t_ref = _bench(ref, boxes, scores)
        t_blk = _bench(blk, boxes, scores)
        assert t_blk * 3 <= t_ref, \
            f"blocked {t_blk*1e3:.1f}ms not 3x faster than greedy " \
            f"{t_ref*1e3:.1f}ms at N={n}"

    def test_onepass_roi_align_beats_masked(self):
        rng = np.random.default_rng(21)
        pyr = {f"p{l}": jnp.asarray(rng.standard_normal(
            (256 >> (l - 2), 256 >> (l - 2), 64)).astype(np.float32))
            for l in (2, 3, 4, 5)}
        r = 1000
        ctr = rng.uniform(20, 480, (r, 2))
        size = np.exp(rng.uniform(np.log(8), np.log(400), (r, 2)))
        rois = jnp.asarray(np.clip(np.concatenate(
            [ctr - size / 2, ctr + size / 2], -1), 0, 511).astype(
                np.float32))
        one = jax.jit(lambda q: roi_ops.multiscale_roi_align(pyr, q))
        msk = jax.jit(lambda q: roi_ops.multiscale_roi_align(
            pyr, q, impl="masked"))
        np.testing.assert_allclose(np.asarray(one(rois)),
                                   np.asarray(msk(rois)), atol=1e-5)
        t_one = _bench(one, rois)
        t_msk = _bench(msk, rois)
        assert t_one < t_msk, \
            f"one-pass {t_one*1e3:.1f}ms not faster than masked " \
            f"{t_msk*1e3:.1f}ms at R={r}"


class TestRoIAlignOnePass:
    def _pyramid_and_rois(self, seed=30, r=200, c=16):
        rng = np.random.default_rng(seed)
        pyr = {f"p{l}": jnp.asarray(rng.standard_normal(
            (128 >> (l - 2), 160 >> (l - 2), c)).astype(np.float32))
            for l in (2, 3, 4, 5)}
        ctr = rng.uniform(5, 250, (r, 2))
        size = np.exp(rng.uniform(np.log(6), np.log(240), (r, 2)))
        rois = np.clip(np.concatenate([ctr - size / 2, ctr + size / 2],
                                      -1), 0, 255).astype(np.float32)
        return pyr, jnp.asarray(rois)

    def test_parity_with_masked(self):
        pyr, rois = self._pyramid_and_rois()
        a = roi_ops.multiscale_roi_align(pyr, rois)
        b = roi_ops.multiscale_roi_align_masked(pyr, rois)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5)

    def test_single_sampling_pass(self):
        """One-pass means ONE set of 4 corner gathers against the packed
        buffer — not 4 per level. The masked reference costs 4x."""
        pyr, rois = self._pyramid_and_rois(r=50)

        def count_gathers(fn):
            return ana_jaxpr.count_primitive(fn, "gather", rois)

        n_one = count_gathers(
            lambda q: roi_ops.multiscale_roi_align(pyr, q))
        n_msk = count_gathers(
            lambda q: roi_ops.multiscale_roi_align_masked(pyr, q))
        # 4 corner gathers + 4 tiny per-level table lookups
        assert n_one <= 8, f"one-pass does {n_one} gathers"
        assert n_msk >= 4 * len(pyr), \
            f"masked reference unexpectedly cheap ({n_msk} gathers)"

    def test_invalid_impl_raises(self):
        pyr, rois = self._pyramid_and_rois(r=4)
        with pytest.raises(ValueError):
            roi_ops.multiscale_roi_align(pyr, rois, impl="twopass")

    def test_torchvision_parity(self):
        torch = pytest.importorskip("torch")
        tv_ops = pytest.importorskip("torchvision.ops")
        rng = np.random.default_rng(31)
        feat = rng.standard_normal((32, 40, 8)).astype(np.float32)
        rois = np.asarray([[2.0, 3.0, 20.0, 18.0],
                           [0.0, 0.0, 39.0, 31.0],
                           [10.5, 7.25, 30.0, 28.5]], np.float32)
        ours = roi_ops.roi_align(jnp.asarray(feat), jnp.asarray(rois),
                                 output_size=7, spatial_scale=0.5,
                                 sampling_ratio=2)
        t_feat = torch.from_numpy(feat.transpose(2, 0, 1))[None]
        t_rois = torch.cat([torch.zeros(3, 1),
                            torch.from_numpy(rois)], dim=1)
        theirs = tv_ops.roi_align(t_feat, t_rois, output_size=7,
                                  spatial_scale=0.5, sampling_ratio=2)
        np.testing.assert_allclose(
            np.asarray(ours).transpose(0, 3, 1, 2),
            theirs.numpy(), atol=1e-4)


class TestSatellites:
    def test_gather_fill_padded_classes(self):
        """Regression: padded slots must not alias class-0/score-0."""
        idx = jnp.asarray([2, 0, 0])
        valid = jnp.asarray([True, False, False])
        boxes = jnp.arange(12.0).reshape(3, 4)
        scores = jnp.asarray([0.9, 0.8, 0.7])
        classes = jnp.asarray([0, 1, 2], jnp.int32)
        b, s, c = nms_ops.gather_nms_outputs(idx, valid, boxes, scores,
                                             classes, fill=(0, 0, -1))
        assert np.asarray(c).tolist() == [2, -1, -1]
        assert float(s[1]) == 0.0
        # scalar fill still applies everywhere (back-compat default)
        _, _, c0 = nms_ops.gather_nms_outputs(idx, valid, boxes, scores,
                                              classes)
        assert np.asarray(c0).tolist() == [2, 0, 0]
        with pytest.raises(ValueError):
            nms_ops.gather_nms_outputs(idx, valid, boxes, fill=(0, 1))

    def test_batched_nms_nan_box_does_not_poison(self):
        """Regression: one NaN/inf box must not poison every class
        offset (old max_coord = max(boxes) + 1)."""
        boxes = np.asarray([[0., 0., 10., 10.],
                            [100., 100., 110., 110.],
                            [np.nan, 0., 10., np.inf],
                            [50., 50., 60., 60.]], np.float32)
        scores = jnp.asarray([0.9, 0.8, 0.95, 0.7])
        classes = jnp.asarray([0, 1, 0, 1], jnp.int32)
        for impl in ("greedy", "blocked"):
            idx, valid = nms_ops.batched_nms(
                jnp.asarray(boxes), scores, classes, 0.5, 4,
                score_threshold=0.0, impl=impl)
            kept = set(np.asarray(idx)[np.asarray(valid)].tolist())
            # the three finite boxes are far apart -> all survive
            assert {0, 1, 3} <= kept, f"{impl}: finite boxes lost {kept}"

    def test_add_batch_matches_add_image(self):
        from deeplearning_tpu.evaluation.coco_eval import CocoEvaluator
        rng = np.random.default_rng(40)
        b, d, g, nc = 3, 6, 4, 3
        det = {
            "boxes": rng.uniform(0, 80, (b, d, 4)).astype(np.float32),
            "scores": rng.uniform(0, 1, (b, d)).astype(np.float32),
            "labels": rng.integers(0, nc, (b, d)),
            "valid": rng.uniform(size=(b, d)) < 0.7,
        }
        gt = {
            "boxes": rng.uniform(0, 80, (b, g, 4)).astype(np.float32),
            "labels": rng.integers(0, nc, (b, g)),
            "valid": rng.uniform(size=(b, g)) < 0.8,
        }
        det["boxes"][..., 2:] += det["boxes"][..., :2]
        gt["boxes"][..., 2:] += gt["boxes"][..., :2]
        # padded det slots carry the -1 class fill
        det["labels"][~det["valid"]] = -1

        ev1 = CocoEvaluator(nc, use_cpp=False)
        ev1.add_batch(np.arange(b), det, gt)
        ev2 = CocoEvaluator(nc, use_cpp=False)
        for j in range(b):
            dv = det["valid"][j]
            gv = gt["valid"][j]
            ev2.add_image(j, gt_boxes=gt["boxes"][j][gv],
                          gt_labels=gt["labels"][j][gv],
                          det_boxes=det["boxes"][j][dv],
                          det_scores=det["scores"][j][dv],
                          det_labels=det["labels"][j][dv])
        s1, s2 = ev1.summarize(), ev2.summarize()
        assert s1 == s2

    def test_add_batch_image_valid_mask(self):
        from deeplearning_tpu.evaluation.coco_eval import CocoEvaluator
        ev = CocoEvaluator(2, use_cpp=False)
        z4 = np.zeros((2, 1, 4))
        ev.add_batch([7, 8],
                     det={"boxes": z4, "scores": np.zeros((2, 1)),
                          "labels": -np.ones((2, 1), np.int64),
                          "valid": np.zeros((2, 1), bool)},
                     gt={"boxes": z4, "labels": np.zeros((2, 1)),
                         "valid": np.zeros((2, 1), bool)},
                     image_valid=[True, False])
        assert 7 in ev._gts and 8 not in ev._gts

    def test_postprocess_knob_greedy_vs_blocked(self):
        """The shared nms_impl knob: same detections either way (here on
        the yolox decoded surface every family shares)."""
        from deeplearning_tpu.models.detection.yolox import \
            postprocess_decoded
        rng = np.random.default_rng(41)
        dec = np.zeros((2, 400, 10), np.float32)
        ctr = rng.uniform(10, 100, (2, 400, 2))
        wh = rng.uniform(4, 30, (2, 400, 2))
        dec[..., 0:2] = ctr - wh / 2
        dec[..., 2:4] = ctr + wh / 2
        dec[..., 4:] = rng.normal(0, 2, (2, 400, 6))
        out_g = postprocess_decoded(jnp.asarray(dec), max_det=20,
                                    nms_impl="greedy")
        out_b = postprocess_decoded(jnp.asarray(dec), max_det=20,
                                    nms_impl="blocked")
        for k in ("boxes", "scores", "labels", "valid"):
            np.testing.assert_array_equal(np.asarray(out_g[k]),
                                          np.asarray(out_b[k]), k)
