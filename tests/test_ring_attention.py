"""Ring attention over the seq mesh axis vs single-device reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from deeplearning_tpu.parallel import MeshConfig, build_mesh
from deeplearning_tpu.parallel.ring_attention import make_ring_attention


def reference(q, k, v):
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(q.dtype), v)


class TestRingAttention:
    @pytest.mark.parametrize("seq_devices", [4, 8])
    def test_matches_reference(self, seq_devices):
        mesh = build_mesh(MeshConfig(data=-1, seq=seq_devices))
        rng = np.random.default_rng(0)
        b, h, n, d = 2, 4, 64 * seq_devices, 32
        q = jnp.asarray(rng.normal(0, 1, (b, h, n, d)), jnp.float32)
        k = jnp.asarray(rng.normal(0, 1, (b, h, n, d)), jnp.float32)
        v = jnp.asarray(rng.normal(0, 1, (b, h, n, d)), jnp.float32)

        ref = reference(q, k, v)

        sharding = NamedSharding(mesh, P(None, None, "seq", None))
        qs, ks, vs = (jax.device_put(t, sharding) for t in (q, k, v))
        ring = jax.jit(make_ring_attention(mesh))
        out = ring(qs, ks, vs)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_flash_kernel_chunks_match_reference(self):
        # VERDICT item 7: the Pallas kernel runs INSIDE the ring —
        # per-chunk (out, lse) merge must reproduce full attention
        mesh = build_mesh(MeshConfig(data=-1, seq=4))
        rng = np.random.default_rng(2)
        b, h, n, d = 1, 2, 64 * 4, 32
        q = jnp.asarray(rng.normal(0, 1, (b, h, n, d)), jnp.float32)
        k = jnp.asarray(rng.normal(0, 1, (b, h, n, d)), jnp.float32)
        v = jnp.asarray(rng.normal(0, 1, (b, h, n, d)), jnp.float32)
        ref = reference(q, k, v)
        sharding = NamedSharding(mesh, P(None, None, "seq", None))
        qs, ks, vs = (jax.device_put(t, sharding) for t in (q, k, v))
        ring = jax.jit(make_ring_attention(mesh, use_flash=True))
        out = ring(qs, ks, vs)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_flash_with_lse_matches_naive(self):
        from deeplearning_tpu.ops.pallas.flash_attention import (
            flash_attention_with_lse)
        rng = np.random.default_rng(3)
        b, h, n, d = 1, 2, 80, 16        # n not a block multiple → padded
        q = jnp.asarray(rng.normal(0, 1, (b, h, n, d)), jnp.float32)
        k = jnp.asarray(rng.normal(0, 1, (b, h, n, d)), jnp.float32)
        v = jnp.asarray(rng.normal(0, 1, (b, h, n, d)), jnp.float32)
        out, lse = flash_attention_with_lse(q, k, v)
        ref = reference(q, k, v)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * (d ** -0.5)
        ref_lse = jax.scipy.special.logsumexp(s, axis=-1)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)
        np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse),
                                   atol=2e-5, rtol=2e-5)

    def test_gradients_flow(self):
        mesh = build_mesh(MeshConfig(data=-1, seq=4))
        rng = np.random.default_rng(1)
        b, h, n, d = 1, 2, 128, 16
        q = jnp.asarray(rng.normal(0, 1, (b, h, n, d)), jnp.float32)
        k = jnp.asarray(rng.normal(0, 1, (b, h, n, d)), jnp.float32)
        v = jnp.asarray(rng.normal(0, 1, (b, h, n, d)), jnp.float32)
        sharding = NamedSharding(mesh, P(None, None, "seq", None))
        qs, ks, vs = (jax.device_put(t, sharding) for t in (q, k, v))
        ring = make_ring_attention(mesh)

        g_ring = jax.jit(jax.grad(
            lambda q, k, v: jnp.sum(ring(q, k, v).astype(jnp.float32) ** 2),
            argnums=(0, 1, 2)))(qs, ks, vs)
        g_ref = jax.grad(
            lambda q, k, v: jnp.sum(reference(q, k, v).astype(jnp.float32) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(g_ring, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       atol=5e-5, rtol=5e-5)

    def test_flash_ring_gradients_match_reference(self):
        # the TRAINABLE kernel-backed ring: custom-VJP backward ring with
        # per-chunk flash gradients against the global LSE
        mesh = build_mesh(MeshConfig(data=-1, seq=4))
        rng = np.random.default_rng(5)
        b, h, n, d = 1, 2, 64 * 4, 32
        q = jnp.asarray(rng.normal(0, 1, (b, h, n, d)), jnp.float32)
        k = jnp.asarray(rng.normal(0, 1, (b, h, n, d)), jnp.float32)
        v = jnp.asarray(rng.normal(0, 1, (b, h, n, d)), jnp.float32)
        sharding = NamedSharding(mesh, P(None, None, "seq", None))
        qs, ks, vs = (jax.device_put(t, sharding) for t in (q, k, v))
        ring = make_ring_attention(mesh, use_flash=True)

        loss = lambda fn: (lambda q, k, v: jnp.sum(
            fn(q, k, v).astype(jnp.float32) ** 2))
        g_ring = jax.jit(jax.grad(loss(ring), argnums=(0, 1, 2)))(
            qs, ks, vs)
        g_ref = jax.grad(loss(reference), argnums=(0, 1, 2))(q, k, v)
        for got, want in zip(g_ring, g_ref):
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       atol=1e-4, rtol=1e-4)


class TestRingAttnFnInModel:
    """Sequence parallelism dropped INTO a model: a ViT built with
    attn_fn=make_ring_attn_fn(mesh) — N=17 tokens (16+cls) padded and
    masked over a 4-device seq axis."""

    def _tiny_vit(self, attn_fn=None):
        from deeplearning_tpu.models.classification.vit import (
            VisionTransformer)
        return VisionTransformer(
            img_size=32, patch_size=8, num_classes=3, embed_dim=32,
            depth=2, num_heads=4, dtype=jnp.float32, attn_fn=attn_fn)

    def test_forward_and_grads_match_naive_attention(self):
        from deeplearning_tpu.parallel.ring_attention import (
            make_ring_attn_fn)
        mesh = build_mesh(MeshConfig(data=-1, seq=4))
        x = jnp.asarray(np.random.default_rng(0).normal(
            size=(2, 32, 32, 3)), jnp.float32)
        naive = self._tiny_vit()
        variables = naive.init(jax.random.key(0), x, train=False)
        ring_model = self._tiny_vit(attn_fn=make_ring_attn_fn(mesh))

        want = naive.apply(variables, x, train=False)
        got = jax.jit(
            lambda v, x: ring_model.apply(v, x, train=False))(variables, x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-4, rtol=1e-4)

        def loss(model):
            return lambda v: jnp.sum(
                model.apply(v, x, train=False).astype(jnp.float32) ** 2)

        g_ring = jax.jit(jax.grad(loss(ring_model)))(variables)
        g_naive = jax.grad(loss(naive))(variables)
        flat_r = jax.tree.leaves(g_ring)
        flat_n = jax.tree.leaves(g_naive)
        for a, b in zip(flat_r, flat_n):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-4, rtol=2e-4)
