"""ONNX export demo (SURVEY §7 Phase 6; reference
detection/yolov5/export.py:43 torch.onnx.export and
others/deploy/pytorch2onnx/support_new_ops.py symbolic registration).

No onnx/onnxruntime packages exist in this image, so export/onnx.py
implements the protobuf wire format itself; these tests assert the
SERIALIZED ARTIFACT (bytes → parse → evaluate) matches the jax forward."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning_tpu.core.registry import MODELS
from deeplearning_tpu.export.onnx import (ONNX_LOWERINGS, export_onnx,
                                          load_onnx, run_onnx,
                                          register_onnx_lowering)


def _roundtrip(fn, *args):
    blob = export_onnx(fn, list(args))
    graph = load_onnx(blob)
    outs = run_onnx(graph, *[np.asarray(a) for a in args])
    return blob, graph, outs


class TestOnnxExport:
    def test_mnist_cnn_roundtrip(self, tmp_path):
        model = MODELS.build("mnist_cnn", num_classes=10,
                             dtype=jnp.float32)
        x = jnp.asarray(np.random.default_rng(0).normal(
            size=(2, 28, 28, 1)), jnp.float32)
        variables = model.init(jax.random.key(0), x, train=False)
        fn = lambda xx: model.apply(variables, xx, train=False)
        path = tmp_path / "m.onnx"
        blob = export_onnx(fn, [x], path=str(path))
        assert path.read_bytes() == blob
        got = run_onnx(load_onnx(blob), np.asarray(x))[0]
        np.testing.assert_allclose(got, np.asarray(fn(x)),
                                   rtol=1e-4, atol=1e-4)

    def test_resnet18_roundtrip(self):
        model = MODELS.build("resnet18", num_classes=4, dtype=jnp.float32)
        x = jnp.asarray(np.random.default_rng(1).normal(
            size=(1, 32, 32, 3)), jnp.float32)
        variables = model.init(jax.random.key(0), x, train=False)
        # non-trivial running stats so BN folding is exercised
        keys = iter(jax.random.split(jax.random.key(1), 10_000))
        stats = jax.tree.map(
            lambda s: s + 0.2 * jax.random.uniform(next(keys), s.shape),
            variables["batch_stats"])
        variables = {"params": variables["params"], "batch_stats": stats}
        fn = lambda xx: model.apply(variables, xx, train=False)
        _, graph, outs = _roundtrip(fn, x)
        np.testing.assert_allclose(outs[0], np.asarray(fn(x)),
                                   rtol=1e-4, atol=1e-4)
        ops = {n["op"] for n in graph["nodes"]}
        assert {"Conv", "MaxPool", "MatMul"} <= ops

    def test_attention_block_roundtrip(self):
        """Transformer math (dot_general with batch dims, softmax,
        layernorm) through the generic MatMul normalization path."""
        from deeplearning_tpu.models.classification.vit import Block
        block = Block(num_heads=2, dtype=jnp.float32)
        x = jnp.asarray(np.random.default_rng(2).normal(
            size=(2, 5, 16)), jnp.float32)
        variables = block.init(jax.random.key(0), x)
        fn = lambda xx: block.apply(variables, xx)
        _, _, outs = _roundtrip(fn, x)
        np.testing.assert_allclose(outs[0], np.asarray(fn(x)),
                                   rtol=1e-4, atol=1e-4)

    def test_unsupported_primitive_error_names_hook(self):
        fn = lambda a: jnp.arctan2(a, a + 1.0)
        x = jnp.ones((3,), jnp.float32)
        with pytest.raises(NotImplementedError,
                           match="register_onnx_lowering"):
            export_onnx(fn, [x])

    def test_custom_op_registration(self):
        """The support_new_ops.py flow: a primitive the exporter doesn't
        know gets a registered lowering (g.op analog) and exports."""
        assert "atan" not in ONNX_LOWERINGS

        @register_onnx_lowering("atan")
        def _atan(g, eqn, ins, outs):
            g.node("Atan", ins, outs)

        try:
            fn = lambda a: jnp.arctan(a) * 2.0
            x = jnp.asarray(np.linspace(-2, 2, 7), jnp.float32)
            blob = export_onnx(fn, [x])
            graph = load_onnx(blob)
            assert any(n["op"] == "Atan" for n in graph["nodes"])
            # evaluator hook for the custom op
            import deeplearning_tpu.export.onnx as onnx_mod
            orig = onnx_mod._eval_node

            def patched(node, vals):
                if node["op"] == "Atan":
                    return np.arctan(
                        np.asarray(vals[node["inputs"][0]]))
                return orig(node, vals)
            onnx_mod._eval_node = patched
            try:
                got = run_onnx(graph, np.asarray(x))[0]
            finally:
                onnx_mod._eval_node = orig
            np.testing.assert_allclose(got, np.asarray(fn(x)),
                                       rtol=1e-5, atol=1e-6)
        finally:
            ONNX_LOWERINGS.pop("atan", None)

    def test_export_cli(self, tmp_path):
        from tools.export import main
        out = tmp_path / "lenet.onnx"
        rc = main(["--model", "mnist_cnn", "--channels", "1", "--size",
                   "28", "--num-classes", "10", "--format", "onnx",
                   "--out", str(out)])
        assert rc == 0 and out.stat().st_size > 1000


class TestDetectionOnnx:
    """Detection-model export (VERDICT r4 #6): gather/iota/top-k/argsort
    lowerings + the pre-NMS decoded graph the reference exports for TRT
    (yolov5 export.py:29-159, YOLOX tools/export_onnx.py)."""

    def test_yolox_decoded_roundtrip(self):
        from deeplearning_tpu.models.detection.yolox import (decode_outputs,
                                                             yolox_grid)
        model = MODELS.build("yolox_nano", num_classes=3,
                             dtype=jnp.float32)
        x = jnp.asarray(np.random.default_rng(2).normal(
            size=(1, 32, 32, 3)), jnp.float32)
        variables = model.init(jax.random.key(0), x, train=False)
        centers, strides = (jnp.asarray(a) for a in yolox_grid((32, 32)))

        def fn(xx):
            return decode_outputs(
                model.apply(variables, xx, train=False), centers, strides)

        _, graph, outs = _roundtrip(fn, x)
        np.testing.assert_allclose(outs[0], np.asarray(fn(x)),
                                   rtol=1e-4, atol=1e-4)
        ops = {n["op"] for n in graph["nodes"]}
        assert "GatherND" in ops          # the Focus strided-slice gather

    def test_topk_argsort_iota_lowerings(self):
        def fn(x):
            vals, idx = jax.lax.top_k(x, 3)
            order = jnp.argsort(x, axis=-1)
            return vals, idx, order, jnp.arange(5, dtype=jnp.float32) + x[0]

        x = jnp.asarray(np.random.default_rng(3).normal(size=(4, 5)),
                        jnp.float32)
        _, graph, outs = _roundtrip(fn, x)
        want = fn(x)
        for got, w in zip(outs, want):
            np.testing.assert_allclose(got, np.asarray(w),
                                       rtol=1e-5, atol=1e-5)
        ops = {n["op"] for n in graph["nodes"]}
        assert "TopK" in ops and "GatherElements" in ops

    def test_take_gather_lowering(self):
        tbl = jnp.asarray(np.random.default_rng(4).normal(size=(7, 3)),
                          jnp.float32)
        idx = jnp.asarray([[0, 2], [6, 1]], jnp.int32)

        def fn(x):
            return tbl[idx] + x

        x = jnp.asarray(np.ones((2, 2, 3)), jnp.float32)
        _, graph, outs = _roundtrip(fn, x)
        np.testing.assert_allclose(outs[0], np.asarray(fn(x)),
                                   rtol=1e-5, atol=1e-5)
