"""Data pipeline (transforms/mixup/mosaic/converters) + Trainer + LR finder."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning_tpu.core.registry import MODELS
from deeplearning_tpu.data import ArraySource, DataLoader
from deeplearning_tpu.data import label_convert as LC
from deeplearning_tpu.data import mixup as MX
from deeplearning_tpu.data import transforms as T
from deeplearning_tpu.train import TrainState, make_eval_step, make_train_step
from deeplearning_tpu.train.classification import make_loss_fn, make_metric_fn
from deeplearning_tpu.train.lr_finder import lr_range_test
from deeplearning_tpu.train.optim import build_optimizer
from deeplearning_tpu.train.schedules import build_schedule
from deeplearning_tpu.train.trainer import Callbacks, Trainer


class TestTransforms:
    def test_resize_with_pad_scales_boxes(self):
        img = np.ones((100, 200, 3), np.float32) * 255
        boxes = np.asarray([[0, 0, 200, 100]], np.float32)
        out, scale, newb = T.resize_with_pad(img, (64, 64), boxes)
        assert out.shape == (64, 64, 3)
        assert scale == pytest.approx(64 / 200)
        np.testing.assert_allclose(newb, [[0, 0, 64, 32]], atol=0.5)
        # bottom is padding
        assert (out[40:] == 114.0).all()

    def test_normalize_and_eval_transform(self):
        imgs = np.full((2, 50, 50, 3), 128, np.float32)
        fn = T.classification_eval_transform((32, 32))
        out = fn({"image": imgs})["image"]
        assert out.shape == (2, 32, 32, 3)
        assert abs(out.mean()) < 1.0          # roughly standardized

    def test_random_flip_boxes(self):
        rng = np.random.default_rng(0)
        img = np.zeros((10, 20, 3))
        boxes = np.asarray([[2.0, 1, 6, 5]])
        img2, b2 = T.random_flip_lr(img, rng, boxes, p=1.0)
        np.testing.assert_allclose(b2, [[14, 1, 18, 5]])


class TestMixupMosaic:
    def test_mixup_soft_targets_sum_to_one(self):
        batch = {"image": jnp.ones((4, 8, 8, 3)),
                 "label": jnp.asarray([0, 1, 2, 3])}
        out = MX.mixup_cutmix(batch, jax.random.key(0), num_classes=5,
                              smoothing=0.1)
        s = np.asarray(out["label"]).sum(-1)
        np.testing.assert_allclose(s, 1.0, atol=1e-5)
        assert out["image"].shape == batch["image"].shape

    def test_mosaic4_boxes_within_canvas(self):
        rng = np.random.default_rng(0)
        imgs = [np.full((40 + i * 10, 50, 3), i * 60.0) for i in range(4)]
        boxes = [np.asarray([[5.0, 5, 30, 30]]) for _ in range(4)]
        labels = [np.asarray([i]) for i in range(4)]
        canvas, b, l, v = MX.mosaic4(imgs, boxes, labels, out_size=64,
                                     rng=rng, max_boxes=16)
        assert canvas.shape == (64, 64, 3)
        assert b.shape == (16, 4) and v.sum() >= 1
        bb = b[v]
        assert (bb >= 0).all() and (bb <= 64).all()


class TestLabelConvert:
    def _rec(self):
        return {"filename": "a.jpg", "width": 100, "height": 80,
                "boxes": np.asarray([[10.0, 10, 50, 40],
                                     [60, 20, 90, 70]], np.float32),
                "names": ["cat", "dog"],
                "difficult": np.asarray([False, False])}

    def test_voc_xml_roundtrip(self, tmp_path):
        p = str(tmp_path / "a.xml")
        LC.write_voc_xml(self._rec(), p)
        back = LC.parse_voc_xml(p)
        np.testing.assert_allclose(back["boxes"], self._rec()["boxes"])
        assert back["names"] == ["cat", "dog"]

    def test_coco_roundtrip(self):
        coco = LC.records_to_coco([self._rec()], ["cat", "dog"])
        assert len(coco["annotations"]) == 2
        assert coco["annotations"][0]["bbox"] == [10.0, 10, 40, 30]
        back = LC.coco_to_records(coco)[0]
        np.testing.assert_allclose(back["boxes"], self._rec()["boxes"])

    def test_yolo_roundtrip(self):
        txt = LC.record_to_yolo(self._rec(), ["cat", "dog"])
        assert txt.splitlines()[0].startswith("0 ")
        back = LC.yolo_to_record(txt, 100, 80, ["cat", "dog"])
        np.testing.assert_allclose(back["boxes"], self._rec()["boxes"],
                                   atol=0.01)

    def test_records_to_arrays_padding(self):
        arrs = LC.records_to_arrays([self._rec()], ["cat", "dog"],
                                    max_boxes=5)
        assert arrs["boxes"].shape == (1, 5, 4)
        assert arrs["valid"][0].sum() == 2
        assert list(arrs["labels"][0][:2]) == [0, 1]


def synthetic_cls(n=96, seed=0):
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 4, n).astype(np.int32)
    images = rng.normal(0, 0.1, (n, 16, 16, 1)).astype(np.float32)
    for i, l in enumerate(labels):
        images[i, :, l * 4:(l + 1) * 4, 0] += 2.0
    return images, labels


class TestTrainer:
    def _make(self, workdir=None, epochs=2):
        images, labels = synthetic_cls()
        model = MODELS.build("mnist_fcn", num_classes=4, dtype=jnp.float32)
        params = model.init(jax.random.key(0),
                            jnp.zeros((1, 16, 16, 1)))["params"]
        tx = build_optimizer(
            "sgd", build_schedule("constant", base_lr=0.1), params=params)
        state = TrainState.create(apply_fn=model.apply, params=params,
                                  tx=tx)
        loader = DataLoader(ArraySource(image=images, label=labels),
                            global_batch=32, seed=0)
        eval_loader = DataLoader(ArraySource(image=images, label=labels),
                                 global_batch=32, shuffle=False)
        return Trainer(
            state=state,
            train_step=make_train_step(make_loss_fn(), donate=False),
            train_loader=loader,
            eval_step=make_eval_step(make_metric_fn(ks=(1,))),
            eval_loader=eval_loader,
            epochs=epochs, workdir=workdir, best_metric="top1",
            log_every=100)

    def test_trains_and_evaluates_with_hooks(self, tmp_path):
        trainer = self._make(str(tmp_path / "run"))
        events = []
        for ev in ("before_train", "before_epoch", "after_epoch",
                   "on_evaluate", "after_train"):
            trainer.callbacks.register(
                ev, lambda t, _e=ev, **kw: events.append(_e))
        trainer.train()
        assert events[0] == "before_train" and events[-1] == "after_train"
        assert events.count("before_epoch") == 2
        res = trainer.evaluate()
        assert res["top1"] > 0.9
        # checkpoint + best written
        assert os.path.isdir(str(tmp_path / "run" / "ckpt" / "best"))
        trainer.ckpt.close()

    def test_auto_resume_continues(self, tmp_path):
        wd = str(tmp_path / "run")
        t1 = self._make(wd, epochs=1)
        t1.train()
        step_after = int(t1.state.step)
        t1.ckpt.close()
        t2 = self._make(wd, epochs=2)
        t2.train()                      # resumes from epoch 1
        assert int(t2.state.step) == step_after * 2
        t2.ckpt.close()

    def test_throughput_mode(self):
        trainer = self._make(None, epochs=1)
        ips = trainer.throughput(n_iters=3)
        assert ips > 0


class TestLrFinder:
    def test_suggests_reasonable_lr(self):
        images, labels = synthetic_cls(128)
        model = MODELS.build("mnist_fcn", num_classes=4, dtype=jnp.float32)
        params0 = model.init(jax.random.key(0),
                             jnp.zeros((1, 16, 16, 1)))["params"]

        def make_state(schedule):
            import optax
            return TrainState.create(
                apply_fn=model.apply, params=params0,
                tx=optax.sgd(schedule))

        batches = [{"image": jnp.asarray(images[i:i + 16]),
                    "label": jnp.asarray(labels[i:i + 16])}
                   for i in range(0, 128, 16)]
        res = lr_range_test(
            make_state, lambda s: make_train_step(make_loss_fn(),
                                                  donate=False),
            batches * 3, min_lr=1e-5, max_lr=10.0)
        assert 1e-5 < res["suggestion"] < 10.0
        assert len(res["lrs"]) == len(res["losses"])


def test_parallel_loader_matches_serial():
    """num_workers>0 must yield the same batches in the same order as
    the serial path (decode runs on a pool, assembly stays ordered)."""
    from deeplearning_tpu.data.loader import DataLoader, MapSource

    def fetch(i):
        return {"x": np.full((3,), i, np.float32),
                "label": np.asarray(i, np.int32)}

    src = MapSource(37, fetch)
    serial = DataLoader(src, 8, shuffle=True, seed=3)
    pooled = DataLoader(src, 8, shuffle=True, seed=3, num_workers=4,
                        lookahead=3)
    for a, b in zip(serial, pooled):
        np.testing.assert_array_equal(a["x"], b["x"])
        np.testing.assert_array_equal(a["label"], b["label"])
    assert len(list(iter(pooled))) == len(serial)


class TestLoggerHub:
    """Pluggable logger backends (yolov5 utils/loggers/__init__.py:17-27
    csv/TensorBoard/W&B trio; the W&B slot is the offline JSONL sink)."""

    def test_backends_write(self, tmp_path):
        import json

        from deeplearning_tpu.core.logging import LoggerHub
        hub = LoggerHub(str(tmp_path), ("csv", "jsonl"))
        hub.scalars({"train/loss": 1.5, "train/acc": 0.5}, step=1)
        hub.scalars({"train/loss": 1.0, "train/acc": 0.7}, step=2)
        hub.summary({"top1": 0.9})
        hub.close()
        csv_lines = (tmp_path / "results.csv").read_text().splitlines()
        assert csv_lines[0].startswith("step,")
        assert len(csv_lines) == 3
        recs = [json.loads(l) for l in
                (tmp_path / "metrics.jsonl").read_text().splitlines()]
        assert recs[0]["step"] == 1 and recs[1]["train/acc"] == 0.7
        assert recs[-1]["summary"] is True and recs[-1]["top1"] == 0.9

    def test_unknown_backend_fails_loudly(self, tmp_path):
        import pytest

        from deeplearning_tpu.core.logging import LoggerHub
        with pytest.raises(KeyError, match="wandb_online"):
            LoggerHub(str(tmp_path), ("csv", "wandb_online"))
