"""Serving engine (deeplearning_tpu/serve): bucket selection, AOT
compile counters, batched-vs-unbatched bitwise parity (classification
AND detection), micro-batcher demux, backpressure/deadline semantics,
overload shedding, and the loadgen speedup gate.

The parity tests are the PR's core contract: a request must get the
SAME bits whether it rode a padded batch or ran alone, with zero XLA
compiles after warmup (trace_count/compile_count are the test surface —
the traced forward bumps trace_count exactly when XLA retraces)."""

import os
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

from deeplearning_tpu.serve import (AdmissionController, DeadlineExceeded,
                                    InferenceEngine, MicroBatcher,
                                    Rejected, ServeTelemetry)


def tree_equal(a, b):
    import jax
    leaves_a = jax.tree.leaves(a)
    leaves_b = jax.tree.leaves(b)
    assert len(leaves_a) == len(leaves_b)
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(leaves_a, leaves_b))


@pytest.fixture(scope="module")
def fcn_engine():
    """One warmed classification session shared by the module (warmup
    compiles exactly len(buckets) executables — reused so the suite
    pays it once)."""
    return InferenceEngine("mnist_fcn", num_classes=10, image_size=28,
                           batch_buckets=(1, 4, 8))


# --------------------------------------------------------------- buckets
def test_bucket_selection():
    eng = InferenceEngine("mnist_fcn", num_classes=10, image_size=28,
                          batch_buckets=(8, 1, 32), precompile=False)
    assert eng.buckets == (1, 8, 32)       # sorted, deduped
    assert eng.bucket_for(1) == 1
    assert eng.bucket_for(2) == 8
    assert eng.bucket_for(8) == 8
    assert eng.bucket_for(9) == 32
    assert eng.bucket_for(33) == 32        # oversize: callers chunk
    spec = eng.bucket_spec(8)
    assert spec.shape == (8, 28, 28, 3)
    with pytest.raises(ValueError):
        InferenceEngine("mnist_fcn", num_classes=10,
                        batch_buckets=(0, 4), precompile=False)


def test_pad_to_bucket(fcn_engine):
    imgs = np.ones((3, 28, 28, 3), np.float32)
    padded = fcn_engine.pad_to_bucket(imgs, 8)
    assert padded.shape == (8, 28, 28, 3)
    assert np.array_equal(padded[:3], imgs)
    assert not padded[3:].any()
    assert fcn_engine.pad_to_bucket(imgs, 3) is imgs   # exact fit: no copy


# ------------------------------------------------- compile-once contract
def test_at_most_one_compile_per_bucket(fcn_engine):
    eng = fcn_engine
    assert eng.compile_count == len(eng.buckets)
    assert eng.trace_count == len(eng.buckets)
    eng.warmup()                            # idempotent
    # concurrent callers race the compile lock: still one per bucket
    threads = [threading.Thread(target=eng._compile_bucket, args=(b,))
               for b in eng.buckets for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for n in (1, 3, 4, 7, 8):
        eng.infer(np.zeros((n, 28, 28, 3), np.float32))
    assert eng.compile_count == len(eng.buckets)
    assert eng.trace_count == len(eng.buckets)
    with pytest.raises(ValueError):
        eng.run(5, np.zeros((5, 28, 28, 3), np.float32))  # not a bucket


# ------------------------------------------------------- bitwise parity
def test_classification_batch_parity_bitwise(fcn_engine):
    eng = fcn_engine
    rng = np.random.default_rng(0)
    images = rng.normal(size=(7, 28, 28, 3)).astype(np.float32)
    batched = eng.infer(images)             # pads 7 -> bucket 8
    singles = np.stack([eng.infer(images[i])[0] for i in range(7)])
    assert batched.shape == (7, 10)
    assert np.array_equal(batched, singles)
    assert eng.trace_count == len(eng.buckets)


def test_detection_batch_parity_bitwise():
    eng = InferenceEngine("retinanet_resnet18_fpn", num_classes=3,
                          image_size=64, batch_buckets=(1, 4),
                          score_thresh=0.05, max_det=10)
    rng = np.random.default_rng(1)
    images = rng.normal(size=(3, 64, 64, 3)).astype(np.float32)
    batched = eng.infer(images)             # pads 3 -> bucket 4
    for k in ("boxes", "scores", "labels", "valid"):
        assert k in batched
    assert batched["boxes"].shape == (3, 10, 4)
    for i in range(3):
        single = eng.infer(images[i])
        assert tree_equal(
            {k: v[i] for k, v in batched.items()},
            {k: v[0] for k, v in single.items()})
    # padded slots carry the class -1 convention, real rows never do
    assert (np.asarray(batched["labels"])[
        ~np.asarray(batched["valid"], bool)] == -1).all()
    assert eng.trace_count == len(eng.buckets)
    assert eng.compile_count == len(eng.buckets)


def test_microbatcher_demux_parity(fcn_engine):
    eng = fcn_engine
    rng = np.random.default_rng(2)
    images = rng.normal(size=(6, 28, 28, 3)).astype(np.float32)
    direct = eng.infer(images)
    with MicroBatcher(eng, max_wait_ms=20.0) as mb:
        handles = [mb.submit(img) for img in images]
        rows = [h.result(timeout=10.0) for h in handles]
    assert np.array_equal(np.stack(rows), direct)
    assert eng.trace_count == len(eng.buckets)
    snap = mb.telemetry.snapshot()
    assert snap["submitted"] == 6 and snap["completed"] == 6
    assert snap["batches"] >= 1


# ----------------------------------------- admission policy (pure logic)
def test_admission_backpressure_and_bucket_policy():
    adm = AdmissionController((1, 4, 16), max_queue=3)
    adm.admit(2)                            # has room
    with pytest.raises(Rejected) as ei:
        adm.admit(3)
    assert ei.value.retry_after_s > 0
    adm.note_drained(16, 0.1)               # 160 req/s observed
    assert 1e-3 <= adm.retry_after_s(8) <= 30.0
    assert adm.target_bucket(0) == 1
    assert adm.target_bucket(3) == 4
    assert adm.target_bucket(100) == 16     # overload: largest only
    assert adm.overloaded(16) and not adm.overloaded(15)
    assert adm.expired(None) is False
    now = time.perf_counter()
    assert adm.expired(now - 1.0)
    assert not adm.expired(now + 60.0)
    assert adm.deadline_for(None) is None   # no default timeout
    assert adm.deadline_for(1.0, now=10.0) == 11.0


class _SlowFakeEngine:
    """Controllable engine stub: the batcher contract is just buckets /
    bucket_for / pad_to_bucket / run / image_size, so saturation tests
    need no XLA (run blocks until released, deterministically)."""

    def __init__(self, buckets=(1, 2, 8), size=4):
        self.buckets = tuple(sorted(buckets))
        self.image_size = size
        self.release = threading.Event()
        self.ran_buckets = []

    def bucket_for(self, n):
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    def pad_to_bucket(self, images, bucket):
        n = images.shape[0]
        if n == bucket:
            return images
        pad = np.zeros((bucket - n, *images.shape[1:]), images.dtype)
        return np.concatenate([images, pad], axis=0)

    def run(self, bucket, images):
        self.release.wait(timeout=10.0)
        self.ran_buckets.append(bucket)
        return images.sum(axis=(1, 2, 3))   # one scalar per row


def test_backpressure_on_saturated_queue():
    eng = _SlowFakeEngine()
    img = np.ones((4, 4, 3), np.float32)
    with MicroBatcher(eng, max_wait_ms=1.0, max_queue=2) as mb:
        first = mb.submit(img)              # dispatcher blocks in run()
        time.sleep(0.1)                     # let it pop the first request
        held = [mb.submit(img), mb.submit(img)]   # fills max_queue=2
        with pytest.raises(Rejected) as ei:
            mb.submit(img)
        assert ei.value.retry_after_s > 0
        eng.release.set()                   # drain
        assert first.result(timeout=10.0) == pytest.approx(48.0)
        for h in held:
            h.result(timeout=10.0)
    assert mb.telemetry.snapshot()["rejected"] == 1


def test_deadline_cancels_before_dispatch():
    eng = _SlowFakeEngine()
    img = np.ones((4, 4, 3), np.float32)
    with MicroBatcher(eng, max_wait_ms=1.0) as mb:
        blocker = mb.submit(img)            # occupies the dispatcher
        time.sleep(0.1)
        doomed = mb.submit(img, timeout_s=0.01)   # expires in queue
        time.sleep(0.1)
        eng.release.set()
        with pytest.raises(DeadlineExceeded):
            doomed.result(timeout=10.0)
        blocker.result(timeout=10.0)
    snap = mb.telemetry.snapshot()
    assert snap["timed_out"] == 1
    # the expired request never reached the engine: only real dispatches
    assert sum(eng.ran_buckets) == sum(
        eng.bucket_for(1) for _ in range(len(eng.ran_buckets)))


def test_overload_sheds_to_largest_bucket():
    eng = _SlowFakeEngine(buckets=(1, 2, 8))
    eng.release.set()                       # run() returns immediately
    img = np.ones((4, 4, 3), np.float32)
    adm = AdmissionController(eng.buckets, max_queue=64, shed_threshold=1)
    mb = MicroBatcher(eng, max_wait_ms=0.0, admission=adm, start=False)
    handles = [mb.submit(img) for _ in range(4)]   # queue builds unstarted
    mb.start()
    for h in handles:
        h.result(timeout=10.0)
    mb.close()
    # max_wait 0 pops single requests, but the deep queue trips the shed
    # policy: at least one dispatch ran in the LARGEST bucket
    assert 8 in eng.ran_buckets
    assert mb.telemetry.snapshot()["shed_batches"] >= 1


def test_telemetry_percentiles():
    t = ServeTelemetry()
    for ms in range(1, 101):
        t.record_e2e_latency(ms / 1e3)
    lat = t.latency_ms("e2e")
    assert lat["p50"] == pytest.approx(51.0)   # nearest-rank: xs[50]
    assert lat["p99"] == pytest.approx(100.0)  # xs[99]
    t.record_batch(8, 6, queue_depth=2, shed=False)
    assert t.batch_occupancy == pytest.approx(0.75)
    snap = t.snapshot()
    assert snap["batches"] == 1 and snap["queue_depth_mean"] == 2.0


# ------------------------------------------------------- loadgen gate
def test_loadgen_dynamic_batching_speedup(fcn_engine):
    """The PR acceptance gate: closed-loop dynamic batching beats the
    sequential per-request baseline >=3x at 64 concurrent clients on
    CPU (measured ~25x for the dispatch-dominated mnist_fcn; 3x leaves
    an 8x margin for machine noise)."""
    from loadgen import make_images, run_closed_loop, run_sequential
    eng = fcn_engine
    images = make_images(8, 28)
    seq = run_sequential(eng, images, 192)
    with MicroBatcher(eng, max_wait_ms=5.0) as mb:
        closed = run_closed_loop(mb, images, concurrency=64,
                                 n_requests=192)
    assert closed["completed"] == 192
    speedup = closed["req_per_s"] / max(seq["req_per_s"], 1e-9)
    assert speedup >= 3.0, f"dynamic batching only {speedup:.2f}x"
    assert closed["batch_occupancy"] > 0.2
    assert eng.trace_count == len(eng.buckets)


def test_hub_serve_entry_point():
    from deeplearning_tpu import hub
    eng = hub.serve("mnist_fcn", num_classes=10, image_size=28,
                    batch_buckets=(1, 2))
    assert isinstance(eng, InferenceEngine)
    assert eng.compile_count == 2           # warmed at construction
    out = eng.infer(np.zeros((1, 28, 28, 3), np.float32))
    assert out.shape == (1, 10)
    assert eng.compile_count == 2


# --------------------------------------------------- predict.py client
def test_predict_npz_multi_image(tmp_path, capsys):
    import predict
    rng = np.random.default_rng(3)
    npz = tmp_path / "batch.npz"
    np.savez(npz, images=rng.normal(size=(3, 28, 28, 3)).astype(np.float32))
    rc = predict.main(["--model", "mnist_fcn", "--num-classes", "4",
                       "--input", str(npz), "--topk", "2"])
    assert rc == 0
    lines = [l for l in capsys.readouterr().out.splitlines() if l]
    assert len(lines) == 3                  # one line PER image
    for i, line in enumerate(lines):
        assert line.startswith(f"image {i}: ")
        assert len(line.split("=")) == 3    # topk=2 -> two probabilities
