"""Folder dataset discovery + spec-driven YOLO builder + wnfc."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning_tpu.core.registry import MODELS
from deeplearning_tpu.data.datasets import (folder_source, read_split_data,
                                            write_class_indices)
from deeplearning_tpu.models.detection.yolo_builder import (SpecModel,
                                                            YOLOV5_SPEC,
                                                            load_spec_yaml)


@pytest.fixture
def image_root(tmp_path):
    for c in ("ant", "bee", "cat"):
        d = tmp_path / c
        d.mkdir()
        for i in range(4):
            np.save(d / f"{i}.npy",
                    np.full((8, 8, 3), hash(c) % 7 + i, np.float32))
    return str(tmp_path)


class TestFolderDataset:
    def test_split_and_classes(self, image_root):
        split = read_split_data(image_root, val_rate=0.25, seed=0)
        assert split["class_to_idx"] == {"ant": 0, "bee": 1, "cat": 2}
        assert len(split["train_paths"]) + len(split["val_paths"]) == 12
        assert len(split["val_paths"]) == 3
        # deterministic given seed
        split2 = read_split_data(image_root, val_rate=0.25, seed=0)
        assert split["val_paths"] == split2["val_paths"]

    def test_folder_source_and_loader(self, image_root):
        from deeplearning_tpu.data import DataLoader
        split = read_split_data(image_root, val_rate=0.25, seed=0)
        src = folder_source(split["train_paths"], split["train_labels"])
        loader = DataLoader(src, global_batch=4, seed=0)
        batch = next(iter(loader))
        assert batch["image"].shape == (4, 8, 8, 3)
        assert batch["label"].shape == (4,)

    def test_class_indices_json(self, image_root, tmp_path):
        split = read_split_data(image_root, val_rate=0.25)
        p = str(tmp_path / "ci.json")
        write_class_indices(split["class_to_idx"], p)
        import json
        with open(p) as f:
            inv = json.load(f)
        assert inv["0"] == "ant" and inv["2"] == "cat"


class TestSpecBuilder:
    def test_matches_grid_count(self):
        from deeplearning_tpu.models.detection.yolov5 import yolov5_grid
        m = MODELS.build("yolov5_from_spec", num_classes=2,
                         width_mult=0.25, dtype=jnp.float32)
        x = jnp.zeros((1, 64, 64, 3))
        v = m.init(jax.random.key(0), x, train=False)
        raw = m.apply(v, x, train=False)
        grid = yolov5_grid((64, 64))
        assert raw.shape == (1, len(grid["cell"]), 7)

    def test_yaml_spec_loading(self, tmp_path):
        yaml_text = """
nc: 4
depth_multiple: 0.33
width_multiple: 0.25
backbone:
  - [-1, 1, Focus, [16]]
  - [-1, 1, Conv, [32, 3, 2]]
  - [-1, 1, C3, [32]]
head:
  - [[-1], 1, Detect, []]
"""
        p = tmp_path / "tiny.yaml"
        p.write_text(yaml_text)
        kwargs = load_spec_yaml(str(p))
        assert kwargs["num_classes"] == 4
        model = SpecModel(spec=tuple(map(tuple, kwargs["spec"])),
                          num_classes=4, width_mult=kwargs["width_mult"],
                          depth_mult=kwargs["depth_mult"],
                          dtype=jnp.float32)
        x = jnp.zeros((1, 32, 32, 3))
        v = model.init(jax.random.key(0), x, train=False)
        out = model.apply(v, x, train=False)
        assert out.shape == (1, (32 // 4) ** 2 * 3, 9)

    def test_unknown_module_raises(self):
        model = SpecModel(spec=((-1, 1, "Bogus", []),), dtype=jnp.float32)
        with pytest.raises(ValueError):
            model.init(jax.random.key(0), jnp.zeros((1, 8, 8, 3)))


class TestWnfc:
    def test_cosine_classifier(self):
        from deeplearning_tpu.ops.losses import wnfc_logits
        emb = jnp.asarray([[1.0, 0.0]])
        w = jnp.asarray([[1.0, 0.0], [0.0, 1.0]]).T
        logits = wnfc_logits(emb, w, s=10.0)
        np.testing.assert_allclose(np.asarray(logits), [[10.0, 0.0]],
                                   atol=1e-5)
