"""Elastic runs: chained signals, fault injection, heartbeat, wedge
detection, backoff, the HBM usage alert, and the supervisor's
kill-and-resume invariant (ISSUE 6 acceptance) end-to-end over a real
subprocess child."""

import importlib.util
import json
import os
import random
import signal
import subprocess
import sys
import time
import types

import pytest

from deeplearning_tpu.elastic import (EXIT_PREEMPTED, Preempted,
                                      PreemptionGuard, Supervisor,
                                      SupervisorConfig, WedgeDetector,
                                      faults, signals)
from deeplearning_tpu.elastic.heartbeat import (Heartbeat, HeartbeatWriter,
                                                read_heartbeat)
from deeplearning_tpu.elastic.supervisor import backoff_delay
from deeplearning_tpu.obs import flight

# Deferred to the tail of the run (conftest e2e reordering): this file
# spawns full training subprocesses — each child re-imports jax and
# recompiles — making it the priciest module in the suite.
pytestmark = pytest.mark.e2e

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHILD = os.path.join(ROOT, "tests", "_elastic_train_child.py")


def _deliver(signum):
    os.kill(os.getpid(), signum)
    time.sleep(0.01)               # let a (rare) deferred delivery land


# --------------------------------------------------------------- signals
class TestSignalChaining:
    """SIGUSR1 stands in for SIGTERM: same registry code path, no risk
    of killing the test process on a chained default."""

    def test_chain_then_graceful_owner(self):
        calls = []
        prev = signal.signal(signal.SIGUSR1,
                             lambda s, f: calls.append("prev"))
        sub_a = lambda s, f: calls.append("a")          # noqa: E731
        sub_g = lambda s, f: calls.append("graceful")   # noqa: E731
        try:
            assert signals.subscribe(signal.SIGUSR1, sub_a)
            assert signals.installed(signal.SIGUSR1)
            _deliver(signal.SIGUSR1)
            # non-graceful subscriber runs, then chains the pre-registry
            # handler — the flight-recorder-only process dies as before
            assert calls == ["a", "prev"]

            calls.clear()
            assert signals.subscribe(signal.SIGUSR1, sub_g, graceful=True)
            _deliver(signal.SIGUSR1)
            # a graceful owner suppresses the chain: everyone still runs,
            # the terminating previous handler does not
            assert calls == ["a", "graceful"]
        finally:
            # leave the dispatcher installed (removing it races with
            # delivery — signals.py's own rule); just drop subscribers
            signals.unsubscribe(signal.SIGUSR1, sub_a)
            signals.unsubscribe(signal.SIGUSR1, sub_g)
        assert signals.subscribers(signal.SIGUSR1) == []

    def test_failing_subscriber_never_starves_the_rest(self):
        calls = []

        def bad(s, f):
            raise RuntimeError("boom")

        ok = lambda s, f: calls.append("ok")            # noqa: E731
        graceful = lambda s, f: None                    # noqa: E731
        assert signals.subscribe(signal.SIGUSR1, bad)
        assert signals.subscribe(signal.SIGUSR1, ok)
        assert signals.subscribe(signal.SIGUSR1, graceful, graceful=True)
        try:
            _deliver(signal.SIGUSR1)
            assert calls == ["ok"]
        finally:
            signals.unsubscribe(signal.SIGUSR1, bad)
            signals.unsubscribe(signal.SIGUSR1, ok)
            signals.unsubscribe(signal.SIGUSR1, graceful)


class TestPreemptionGuard:
    def test_signal_flushes_and_flags(self):
        flushed = []
        guard = PreemptionGuard(signums=(signal.SIGUSR2,))
        guard.add_flush(lambda: flushed.append(1))
        assert guard.install()
        try:
            before = len(flight.get_recorder().events("preempt_signal"))
            _deliver(signal.SIGUSR2)
            assert guard.requested()
            assert guard.signum == signal.SIGUSR2
            assert flushed == [1]
            after = flight.get_recorder().events("preempt_signal")
            assert len(after) == before + 1
            # double delivery: already landing, flush not re-run
            _deliver(signal.SIGUSR2)
            assert flushed == [1]
        finally:
            guard.uninstall()
        assert signals.subscribers(signal.SIGUSR2) == []

    def test_programmatic_request(self):
        guard = PreemptionGuard(signums=())
        assert not guard.requested()
        guard.request()
        assert guard.requested()


# ---------------------------------------------------------------- faults
class TestFaultGrammar:
    def test_parse(self):
        specs = faults.parse_faults(
            "sigterm@step:5@attempt:0; crash@checkpoint ;wedge@step:3;"
            "bogus@step;crash@nonsense:2;sigint;;crash@step:xyz")
        assert [(s.kind, s.site, s.at_step, s.attempt) for s in specs] == [
            ("sigterm", "step", 5, 0),
            ("crash", "checkpoint", None, None),
            ("wedge", "step", 3, None),
            ("sigint", "step", None, None),
        ]

    def test_matches_step_attempt_and_once(self):
        spec = faults.parse_faults("crash@step:5@attempt:1")[0]
        assert not spec.matches("step", 4, 1)      # before threshold
        assert not spec.matches("step", 5, 0)      # wrong attempt
        assert not spec.matches("checkpoint", 5, 1)  # wrong site
        assert spec.matches("step", 7, 1)          # at_step is a floor
        spec.fired = True
        assert not spec.matches("step", 7, 1)      # at most once

    def test_maybe_fire_crash(self, monkeypatch):
        monkeypatch.setenv(faults.ENV_VAR, "crash@checkpoint:2")
        monkeypatch.delenv(faults.ATTEMPT_VAR, raising=False)
        faults.reset()
        try:
            faults.maybe_fire("step", step=10)         # wrong site
            faults.maybe_fire("checkpoint", step=1)    # below floor
            with pytest.raises(faults.InjectedCrash):
                faults.maybe_fire("checkpoint", step=2)
            faults.maybe_fire("checkpoint", step=3)    # fired once only
        finally:
            faults.reset()                 # forget the patched env

    def test_empty_env_is_free(self, monkeypatch):
        monkeypatch.delenv(faults.ENV_VAR, raising=False)
        faults.reset()
        try:
            faults.maybe_fire("step", step=0)
        finally:
            faults.reset()


# ------------------------------------------------------------- heartbeat
class TestHeartbeat:
    def test_touch_semantics(self):
        beat = Heartbeat(step=3)
        beat.touch("eval")
        assert (beat.step, beat.activity, beat.phase) == (3, 1, "eval")
        beat.touch("step", step=4)
        assert (beat.step, beat.activity, beat.phase) == (4, 2, "step")

    def test_writer_roundtrip(self, tmp_path):
        path = str(tmp_path / "hb.json")
        beat = Heartbeat()
        writer = HeartbeatWriter(path, beat, interval_s=0.05).start()
        deadline = time.monotonic() + 5.0
        doc = None
        while time.monotonic() < deadline:
            doc = read_heartbeat(path)
            if doc is not None:
                break
            time.sleep(0.01)
        assert doc is not None and doc["pid"] == os.getpid()
        beat.touch("step", step=9)
        writer.stop()                      # final write = exit watermark
        doc = read_heartbeat(path)
        assert doc["step"] == 9 and doc["activity"] == 1

    def test_read_absent_and_torn(self, tmp_path):
        assert read_heartbeat(str(tmp_path / "missing.json")) is None
        torn = tmp_path / "torn.json"
        torn.write_text('{"step": 3, "activ')
        assert read_heartbeat(str(torn)) is None


# -------------------------------------------------------- wedge detector
class TestWedgeDetector:
    def test_slow_vs_wedged_classification(self):
        det = WedgeDetector(10.0)
        assert det.observe(0, 0, now=1000.0) == "ok"
        # activity ticking, step frozen: a long compile is SLOW, not dead
        assert det.observe(0, 1, now=1005.0) == "slow"
        assert det.observe(0, 2, now=1012.0) == "slow"
        assert det.observe(0, 2, now=1021.9) == "slow"   # 9.9s < deadline
        assert det.observe(0, 2, now=1022.0) == "wedged"
        assert det.stalled_for(now=1022.0) == pytest.approx(10.0)
        # any movement re-arms
        assert det.observe(1, 3, now=1023.0) == "ok"
        assert det.stalled_for(now=1023.0) == 0.0

    def test_watch_fires_once_after_freeze(self):
        det = WedgeDetector(0.2)
        fired = []
        act = [0]
        thread = det.watch(lambda: act[0], fired.append, poll_s=0.03)
        for _ in range(5):                 # healthy: activity advances
            act[0] += 1
            time.sleep(0.05)
        assert fired == []
        deadline = time.monotonic() + 5.0  # now freeze it
        while not fired and time.monotonic() < deadline:
            time.sleep(0.02)
        assert len(fired) == 1 and fired[0] >= 0.2
        thread.join(2.0)
        assert not thread.is_alive()       # one-shot: thread exits

    def test_watch_stop_never_fires(self):
        det = WedgeDetector(0.1)
        fired = []
        thread = det.watch(lambda: 0, fired.append, poll_s=0.02)
        thread.stop.set()
        thread.join(2.0)
        assert fired == [] and not thread.is_alive()


def test_backoff_bounds():
    cfg = SupervisorConfig(["x"], backoff_base_s=0.5, backoff_factor=2.0,
                           backoff_max_s=4.0, backoff_jitter=0.25)
    rng = random.Random(0)
    for attempt, lo in [(1, 0.5), (2, 1.0), (3, 2.0), (4, 4.0), (9, 4.0)]:
        for _ in range(25):
            d = backoff_delay(attempt, cfg, rng)
            assert lo <= d <= lo * 1.25 + 1e-9


# --------------------------------------------------------- HBM alerting
class TestHbmAlert:
    def test_edge_triggered_alert_and_field_guards(self):
        from deeplearning_tpu.obs import xla
        dev = types.SimpleNamespace(id=7777, device_kind="fake")
        prev = xla.set_hbm_alert_frac(0.8)
        try:
            hot = {"bytes_in_use": 90, "bytes_limit": 100,
                   "peak_bytes_in_use": "not-a-number"}
            n0 = len(flight.get_recorder().events("hbm_alert"))
            entry = xla._mem_entry(dev, hot, 0.8)
            assert entry["usage_frac"] == 0.9
            assert entry["alert"]["threshold_frac"] == 0.8
            assert "peak_bytes_in_use" not in entry   # bad field dropped
            events = flight.get_recorder().events("hbm_alert")
            assert len(events) == n0 + 1
            # still hot: alert annotation persists, no second event
            assert "alert" in xla._mem_entry(dev, hot, 0.8)
            assert len(flight.get_recorder().events("hbm_alert")) == n0 + 1
            # recede below threshold: re-arms
            cool = {"bytes_in_use": 10, "bytes_limit": 100}
            assert "alert" not in xla._mem_entry(dev, cool, 0.8)
            xla._mem_entry(dev, hot, 0.8)
            assert len(flight.get_recorder().events("hbm_alert")) == n0 + 2
        finally:
            xla.set_hbm_alert_frac(prev)

    def test_missing_fields_are_guarded(self):
        from deeplearning_tpu.obs import xla
        dev = types.SimpleNamespace(id=7778, device_kind="fake")
        assert "usage_frac" not in xla._mem_entry(
            dev, {"bytes_in_use": 5}, 0.8)             # no limit
        assert xla._mem_entry(dev, {}, 0.8) == {"id": 7778, "kind": "fake"}
        snap = xla.hbm_snapshot()          # CPU backend: must not raise
        assert "time" in snap


# ---------------------------------------------------- obs_report section
def test_obs_report_restart_summary():
    spec = importlib.util.spec_from_file_location(
        "obs_report", os.path.join(ROOT, "tools", "obs_report.py"))
    obs_report = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(obs_report)
    sup = {"reason": "completed", "events": [
        {"kind": "launch"}, {"kind": "child_exit", "outcome": "preempted",
                             "returncode": 75},
        {"kind": "backoff", "delay_s": 1.5},
        {"kind": "launch"}, {"kind": "completed"}]}
    child = {"events": [{"kind": "resume", "step": 7,
                         "cross_topology": True}]}
    rs = obs_report.restart_summary(sup, child)
    assert rs["launches"] == 2 and rs["preemptions"] == 1
    assert rs["wedge_kills"] == 0 and rs["crashes"] == 0
    assert rs["backoff_waits"] == 1
    assert rs["backoff_total_s"] == pytest.approx(1.5)
    assert rs["final"] == "completed" and not rs["gave_up"]
    assert rs["resume_steps"] == [7] and rs["cross_topology_resumes"] == 1
    assert obs_report.restart_summary(None, None) is None


# ------------------------------------------- trainer preemption (in-proc)
class TestTrainerPreemption:
    def test_request_checkpoints_and_raises(self, tmp_path, monkeypatch):
        monkeypatch.delenv(faults.ENV_VAR, raising=False)
        faults.reset()
        from test_async_hotpath import make_trainer
        cell = {}

        def hook(trainer, **kw):
            if trainer.host_step >= 2 and trainer.preempt_guard:
                trainer.preempt_guard.request()

        from deeplearning_tpu.train.trainer import Callbacks
        callbacks = Callbacks()
        callbacks.register("after_iter", hook)
        trainer = make_trainer(epochs=2, n=6 * 16, batch=16,
                               workdir=str(tmp_path),
                               async_checkpoint=True, callbacks=callbacks)
        cell["t"] = trainer
        with pytest.raises(Preempted) as exc:
            trainer.train()
        # the guard flushed + the trainer saved the interrupted step:
        # nothing past the last checkpoint is lost on requeue
        step = int(trainer.state.step)
        assert exc.value.step == step and step >= 2
        assert trainer.ckpt.latest_step() == step
        # guard uninstalled on the way out: no graceful owner remains
        # (the flight recorder's non-graceful subscriber may stay)
        assert trainer.preempt_guard is None
        assert not any(g for _, g in signals.subscribers(signal.SIGTERM))


# --------------------------------------------------- supervisor e2e runs
class TestSupervisorE2E:
    def test_crash_exhausts_budget(self, tmp_path):
        cfg = SupervisorConfig(
            [sys.executable, "-c", "import sys; sys.exit(7)"],
            workdir=str(tmp_path), max_restarts=1,
            backoff_base_s=0.05, backoff_max_s=0.1, poll_s=0.05,
            startup_deadline_s=60.0, seed=0)
        sup = Supervisor(cfg)
        assert sup.run() == 7
        assert sup.outcomes == ["crashed", "crashed"]
        rec = json.load(open(tmp_path / "flightrec_supervisor.json"))
        assert rec["reason"] == "gave_up"
        kinds = [e["kind"] for e in rec["events"]]
        assert kinds.count("launch") == 2
        assert kinds.count("backoff") == 1
        assert kinds[-1] == "gave_up"

    def test_kill_resume_wedge_cycle(self, tmp_path):
        """The acceptance invariant, full stack: attempt 0 (data=8 mesh)
        is preempted mid-epoch and exits 75 with its checkpoint flushed;
        attempt 1 resumes cross-topology (data=4 x model=2), then wedges
        and must be detected and killed within the deadline; attempt 2
        resumes again and trains to completion. Step continuity: every
        resume starts exactly at the preempted checkpoint."""
        env = dict(os.environ)
        env["DLTPU_FAULTS"] = "sigterm@step:7@attempt:0;wedge@step:9@attempt:1"
        cfg = SupervisorConfig(
            [sys.executable, CHILD, str(tmp_path), "3"],
            workdir=str(tmp_path), max_restarts=4,
            wedge_deadline_s=8.0, startup_deadline_s=180.0,
            poll_s=0.05, kill_grace_s=0.5,
            backoff_base_s=0.1, backoff_factor=2.0, backoff_max_s=0.3,
            backoff_jitter=0.25, env=env, seed=0)
        sup = Supervisor(cfg)
        rc = sup.run()
        assert rc == 0
        assert sup.outcomes == ["preempted", "wedged", "completed"]
        assert sup.launches == 3
        assert sup.backoff_total_s > 0

        # supervisor decision log
        rec = json.load(open(tmp_path / "flightrec_supervisor.json"))
        assert rec["reason"] == "completed"
        events = rec["events"]
        kinds = [e["kind"] for e in events]
        assert kinds.count("launch") == 3
        assert kinds.count("wedge_kill") == 1
        assert kinds.count("backoff") == 2
        exits = [e for e in events if e["kind"] == "child_exit"]
        assert exits[0]["returncode"] == EXIT_PREEMPTED
        assert exits[0]["outcome"] == "preempted"
        assert exits[-1]["outcome"] == "completed"
        # wedge detection fired in bounded time: kill decision landed
        # within (child startup + a few steps + deadline), far below the
        # injected 600s sleep it interrupted
        launch_1 = [e for e in events
                    if e["kind"] == "launch" and e["attempt"] == 1][0]
        wedge = [e for e in events if e["kind"] == "wedge_kill"][0]
        assert wedge["attempt"] == 1
        assert wedge["time"] - launch_1["time"] < 60.0

        # child-side continuity: the wedged attempt is SIGKILLed and
        # leaves no record; attempts 0 and 2 bracket the run
        recs = [json.loads(line) for line in
                open(tmp_path / "progress.jsonl")]
        assert [r["outcome"] for r in recs] == ["preempted", "completed"]
        assert recs[0]["attempt"] == 0 and recs[0]["mesh"] == "data=8"
        assert recs[1]["attempt"] == 2 and "model=2" in recs[1]["mesh"]
        # no checkpointed step is ever lost: the resume starts exactly
        # where the preempted attempt flushed
        assert recs[1]["start_step"] == recs[0]["final_step"]
        assert recs[0]["final_step"] >= 7
        assert recs[1]["final_step"] >= 18

        # the wedged attempt's SIGTERM dump captured its cross-topology
        # resume — obs_report's restarts section joins on exactly this
        child_rec = json.load(open(tmp_path / "flightrec.json"))
        resumes = [e for e in child_rec["events"]
                   if e["kind"] == "resume"]
        assert resumes and resumes[0]["cross_topology"] is True
        assert resumes[0]["step"] == recs[0]["final_step"]
        wedge_faults = [e for e in child_rec["events"]
                        if e["kind"] == "fault_injected"
                        and "wedge" in e["fault"]]
        assert wedge_faults
