"""Round-2 gap closures: Swin-MLP, yolov3 variant, keypoint data path,
pose registry, non-finite-loss abort, and the ADVICE.md semantic fixes
(SimOTA both-gate preference, matcher low-quality restore, MoE top-k
gate normalization, PatchMerging channel order, accumulation metrics)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning_tpu.core.registry import MODELS


class TestSwinMLP:
    def test_forward_finite_with_shift(self):
        # 64px/patch4 → 16×16 stage-0 grid with window 8 → shifted blocks
        # exercise the zero-pad+crop path
        model = MODELS.build("swin_mlp_tiny_c24_patch4_window8_256",
                             num_classes=5, dtype=jnp.float32,
                             drop_path_rate=0.0)
        x = jnp.asarray(np.random.default_rng(0).normal(
            size=(2, 64, 64, 3)), jnp.float32)
        variables = model.init(jax.random.key(0), x, train=False)
        out = model.apply(variables, x, train=False)
        assert out.shape == (2, 5)
        assert np.all(np.isfinite(np.asarray(out)))
        # spatial-MLP params present, attention params absent
        flat = jax.tree_util.tree_flatten_with_path(variables["params"])[0]
        names = ["/".join(str(k) for k in path) for path, _ in flat]
        assert any("spatial_mlp_kernel" in n for n in names)
        assert not any("qkv" in n for n in names)

    def test_registry_base_variant(self):
        model = MODELS.build("swin_mlp_base_patch4_window7_224",
                             num_classes=3, dtype=jnp.float32)
        assert model.spatial_mlp and model.embed_dim == 128


class TestPatchMergingOrder:
    def test_channel_order_matches_reference_concat(self):
        # the module's reshape/transpose must equal the reference's
        # [x0;x1;x2;x3] = [(0,0),(1,0),(0,1),(1,1)] slicing over
        # (h-sub, w-sub) (swin_transformer.py:308)
        h = w = 4
        c = 3
        x = jnp.arange(h * w * c, dtype=jnp.float32).reshape(1, h * w, c)
        merged = x.reshape(1, h // 2, 2, w // 2, 2, c).transpose(
            0, 1, 3, 4, 2, 5).reshape(1, (h // 2) * (w // 2), 4 * c)
        g = x.reshape(1, h, w, c)
        expected = jnp.concatenate(
            [g[:, 0::2, 0::2], g[:, 1::2, 0::2],
             g[:, 0::2, 1::2], g[:, 1::2, 1::2]],
            axis=-1).reshape(1, (h // 2) * (w // 2), 4 * c)
        np.testing.assert_array_equal(np.asarray(merged),
                                      np.asarray(expected))


class TestYolov3Variant:
    def test_forward_shapes(self):
        model = MODELS.build("yolox_yolov3", num_classes=4,
                             dtype=jnp.float32)
        x = jnp.asarray(np.random.default_rng(0).normal(
            size=(1, 64, 64, 3)), jnp.float32)
        variables = model.init(jax.random.key(0), x, train=False)
        out = model.apply(variables, x, train=False)
        # anchors = 8² + 4² + 2² = 84 at strides 8/16/32
        assert out.shape == (1, 84, 9)
        assert np.all(np.isfinite(np.asarray(out)))
        flat = jax.tree_util.tree_flatten_with_path(variables["params"])[0]
        names = ["/".join(str(k) for k in path) for path, _ in flat]
        assert any("spp_out" in n for n in names)        # Darknet53 SPP
        assert any("out1_cbl" in n for n in names)       # YOLOFPN branch


class TestSimOTABothGatePreference:
    def test_prefers_anchor_in_box_and_center(self):
        from deeplearning_tpu.models.detection.yolox import simota_assign
        # two anchors with IDENTICAL predictions: anchor0 in-box only,
        # anchor1 in-box AND in-center → with dynamic_k=1 the reference
        # cost prefers anchor1 (extra 1e5 for single-gate candidates)
        centers = jnp.asarray([[0.0, 0.0], [5.0, 0.0]])   # cx = 0.5, 5.5
        strides = jnp.asarray([1.0, 1.0])
        pred_box = [0.0, 0.0, 10.0, 0.8]                  # iou 0.4 vs gt
        decoded = jnp.asarray([pred_box + [0.0] * 3] * 2, jnp.float32)
        gt_boxes = jnp.asarray([[0.0, 0.0, 10.0, 2.0]])   # center (5, 1)
        out = simota_assign(decoded, centers, strides, gt_boxes,
                            jnp.asarray([0]), jnp.asarray([True]),
                            num_classes=2)
        fg = np.asarray(out["fg"])
        assert fg[1] and not fg[0]


class TestMatcherLowQualityRestore:
    def test_restores_anchor_own_best_gt(self):
        from deeplearning_tpu.ops.matcher import match_anchors
        # anchor0 is gt0's best anchor (0.3) but itself overlaps gt1 more
        # (0.4): torchvision restores anchor0's own argmax (gt1)
        iou = jnp.asarray([[0.3, 0.1],
                           [0.4, 0.45]])
        matches = match_anchors(iou, jnp.asarray([True, True]),
                                high_threshold=0.5, low_threshold=0.45,
                                allow_low_quality=True)
        assert int(matches[0]) == 1
        assert int(matches[1]) == 1


class TestMoETopKGateNormalization:
    def test_identical_experts_reduce_to_plain_mlp(self):
        from deeplearning_tpu.parallel.moe import MoEMlp
        # with all experts sharing weights and nothing dropped, a
        # properly-normalized top-2 combine must equal the single MLP
        # output exactly (gates sum to 1)
        moe = MoEMlp(num_experts=2, top_k=2, capacity_factor=8.0,
                     aux_weight=0.0, dtype=jnp.float32)
        x = jnp.asarray(np.random.default_rng(0).normal(
            size=(1, 6, 8)), jnp.float32)
        params = moe.init(jax.random.key(0), x)["params"]
        for leaf in ("fc1_kernel", "fc1_bias", "fc2_kernel", "fc2_bias"):
            arr = params["experts"][leaf]
            params["experts"][leaf] = jnp.broadcast_to(
                arr[0][None], arr.shape)
        out, _ = moe.apply({"params": params}, x)

        def ref_mlp(tokens):
            k1 = params["experts"]["fc1_kernel"][0]
            b1 = params["experts"]["fc1_bias"][0]
            k2 = params["experts"]["fc2_kernel"][0]
            b2 = params["experts"]["fc2_bias"][0]
            y = jax.nn.gelu(tokens @ k1 + b1, approximate=True)
            return y @ k2 + b2

        expected = ref_mlp(x.reshape(-1, 8)).reshape(x.shape)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                                   rtol=1e-5, atol=1e-5)


class TestAccumulationAux:
    def test_metrics_averaged_and_stats_advanced(self):
        from deeplearning_tpu.train import TrainState, make_train_step
        from deeplearning_tpu.train.classification import make_loss_fn
        from deeplearning_tpu.train.optim import build_optimizer
        from deeplearning_tpu.train.schedules import build_schedule
        import flax.linen as nn

        class BnNet(nn.Module):
            @nn.compact
            def __call__(self, x, train=False):
                x = x.reshape(x.shape[0], -1)
                x = nn.BatchNorm(use_running_average=not train,
                                 momentum=0.5, name="bn")(x)
                return nn.Dense(2)(x)

        model = BnNet()
        x = np.random.default_rng(0).normal(
            size=(8, 4, 4, 1)).astype(np.float32)
        variables = model.init(jax.random.key(0), jnp.zeros((1, 4, 4, 1)))
        tx = build_optimizer("sgd", build_schedule("constant",
                                                   base_lr=0.0),
                             params=variables["params"])
        state = TrainState.create(
            apply_fn=model.apply, params=variables["params"], tx=tx,
            batch_stats=variables["batch_stats"])
        batch = {"image": jnp.asarray(x),
                 "label": jnp.asarray([0, 1] * 4, jnp.int32)}
        loss_fn = make_loss_fn(has_batch_stats=True)
        step2 = make_train_step(loss_fn, accum_steps=2, donate=False)
        new_state, metrics = step2(state, batch, jax.random.key(1))

        # batch_stats advance by BOTH microbatches: replaying the two
        # half-batch BN updates sequentially must give the same mean
        stats = state.batch_stats
        for lo, hi in ((0, 4), (4, 8)):
            _, mut = model.apply(
                {"params": state.params, "batch_stats": stats},
                batch["image"][lo:hi], train=True,
                mutable=["batch_stats"],
                rngs={"dropout": jax.random.key(0)})
            stats = mut["batch_stats"]
        np.testing.assert_allclose(
            np.asarray(new_state.batch_stats["bn"]["mean"]),
            np.asarray(stats["bn"]["mean"]), rtol=1e-5)

        # metrics are averaged over microbatches: accuracy equals the
        # mean of the two microbatch accuracies → within [0, 1]
        assert 0.0 <= float(metrics["accuracy"]) <= 1.0


class TestNonFiniteAbort:
    def test_trainer_raises_on_nan_loss(self):
        from deeplearning_tpu.train.trainer import Trainer

        class FakeLoader:
            def __len__(self):
                return 2

            def set_epoch(self, e):
                pass

            def __iter__(self):
                return iter([{"x": np.zeros((2,))}] * 2)

        class FakeState:
            step = 0

        def bad_step(state, batch, rng):
            return state, {"loss": jnp.asarray(float("nan"))}

        trainer = Trainer(state=FakeState(), train_step=bad_step,
                          train_loader=FakeLoader(), epochs=1)
        with pytest.raises(FloatingPointError):
            trainer.train()


class TestKeypointDataPath:
    def test_affine_identity_and_rotation(self):
        from deeplearning_tpu.data import keypoint_transforms as K
        img = np.random.default_rng(0).normal(
            size=(32, 24, 3)).astype(np.float32)
        m = K.get_affine_matrix((0, 0, 24, 32), (32, 24), 0.0)
        out = K.warp_affine(img, m, (32, 24))
        np.testing.assert_allclose(out, img, atol=1e-4)
        # 180° rotation maps a point center-symmetrically
        m180 = K.get_affine_matrix((0, 0, 24, 32), (32, 24), 180.0)
        pt = K.affine_points(np.asarray([[2.0, 3.0]]), m180)
        np.testing.assert_allclose(pt, [[22.0, 29.0]], atol=1e-4)

    def test_invert_affine_roundtrip(self):
        from deeplearning_tpu.data import keypoint_transforms as K
        m = K.get_affine_matrix((5, 7, 20, 40), (64, 48), 30.0)
        inv = K.invert_affine(m)
        pts = np.asarray([[8.0, 20.0], [15.0, 30.0]])
        back = K.affine_points(K.affine_points(pts, m), inv)
        np.testing.assert_allclose(back, pts, atol=1e-3)

    def test_flip_back_and_pairs(self):
        from deeplearning_tpu.data import keypoint_transforms as K
        heat = np.zeros((4, 6, 17), np.float32)
        heat[1, 2, 1] = 1.0          # left joint 1
        out = K.flip_back(heat)
        assert out[1, 3, 2] == 1.0   # mirrored column, right joint 2

    def test_train_transform_deterministic_heatmap_peak(self):
        from deeplearning_tpu.data import keypoint_transforms as K
        fn = K.keypoint_train_transform(
            fixed_size=(64, 48), scale_range=(1.0, 1.0),
            rotation_range=(0.0, 0.0), half_body_prob=0.0, flip_prob=0.0)
        img = np.zeros((128, 96, 3), np.float32)
        kps = np.asarray([[48.0, 64.0]] + [[0.0, 0.0]] * 16, np.float32)
        vis = np.asarray([2.0] + [0.0] * 16, np.float32)
        out = fn(img, (0, 0, 96, 128), kps, vis)
        assert out["image"].shape == (64, 48, 3)
        assert out["heatmaps"].shape == (16, 12, 17)
        # kp at image center → crop center (24, 32) → heatmap (6, 8)
        peak = np.unravel_index(np.argmax(out["heatmaps"][..., 0]),
                                (16, 12))
        assert peak == (8, 6)
        assert out["kp_weights"][0] == 1.0 and out["kp_weights"][1] == 0.0


class TestPoseRegistry:
    def test_hrnet_keypoints_moved_to_pose(self):
        from deeplearning_tpu.models.pose.hrnet_pose import (  # noqa: F401
            hrnet_w18_keypoints)
        model = MODELS.build("hrnet_w18_keypoints", num_classes=5,
                             dtype=jnp.float32, blocks_per_stage=1)
        x = jnp.zeros((1, 64, 64, 3))
        variables = model.init(jax.random.key(0), x, train=False)
        out = model.apply(variables, x, train=False)
        assert out.shape == (1, 16, 16, 5)       # stride-4 heatmaps
