"""Detection ops vs hand-rolled numpy golden references.

The references implement torchvision's documented semantics (the ops the
reference repo consumes: nms, roi_align, box coder), so parity here means
parity with the reference's native ops (SURVEY.md §2.10.5)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning_tpu.ops import anchors as anc
from deeplearning_tpu.ops import boxes as B
from deeplearning_tpu.ops import matcher as M
from deeplearning_tpu.ops import nms as N
from deeplearning_tpu.ops import roi_align as R


# ---------------------------------------------------------------- golden
def np_nms(boxes, scores, thresh):
    order = np.argsort(-scores)
    keep = []
    while order.size:
        i = order[0]
        keep.append(i)
        if order.size == 1:
            break
        rest = order[1:]
        xx1 = np.maximum(boxes[i, 0], boxes[rest, 0])
        yy1 = np.maximum(boxes[i, 1], boxes[rest, 1])
        xx2 = np.minimum(boxes[i, 2], boxes[rest, 2])
        yy2 = np.minimum(boxes[i, 3], boxes[rest, 3])
        inter = np.clip(xx2 - xx1, 0, None) * np.clip(yy2 - yy1, 0, None)
        a1 = (boxes[i, 2] - boxes[i, 0]) * (boxes[i, 3] - boxes[i, 1])
        a2 = (boxes[rest, 2] - boxes[rest, 0]) * \
            (boxes[rest, 3] - boxes[rest, 1])
        iou = inter / (a1 + a2 - inter + 1e-9)
        order = rest[iou <= thresh]
    return np.asarray(keep)


def np_bilinear(feat, y, x):
    h, w, _ = feat.shape
    if y < -1 or y > h or x < -1 or x > w:
        return np.zeros(feat.shape[-1])
    y = min(max(y, 0), h - 1)
    x = min(max(x, 0), w - 1)
    y0, x0 = int(np.floor(y)), int(np.floor(x))
    y1, x1 = min(y0 + 1, h - 1), min(x0 + 1, w - 1)
    ly, lx = y - y0, x - x0
    return (feat[y0, x0] * (1 - ly) * (1 - lx) + feat[y0, x1] * (1 - ly) * lx
            + feat[y1, x0] * ly * (1 - lx) + feat[y1, x1] * ly * lx)


def np_roi_align(feat, roi, out_size, scale, sr):
    x1, y1, x2, y2 = roi * scale
    rw = max(x2 - x1, 1.0)
    rh = max(y2 - y1, 1.0)
    bw, bh = rw / out_size, rh / out_size
    out = np.zeros((out_size, out_size, feat.shape[-1]))
    for i in range(out_size):
        for j in range(out_size):
            acc = np.zeros(feat.shape[-1])
            for si in range(sr):
                for sj in range(sr):
                    yy = y1 + (i + (si + 0.5) / sr) * bh
                    xx = x1 + (j + (sj + 0.5) / sr) * bw
                    acc += np_bilinear(feat, yy, xx)
            out[i, j] = acc / (sr * sr)
    return out


# ----------------------------------------------------------------- tests
class TestBoxOps:
    def test_iou_matrix(self):
        b1 = jnp.asarray([[0, 0, 10, 10], [5, 5, 15, 15]], jnp.float32)
        b2 = jnp.asarray([[0, 0, 10, 10], [100, 100, 110, 110]], jnp.float32)
        iou = B.box_iou(b1, b2)
        np.testing.assert_allclose(np.asarray(iou),
                                   [[1.0, 0.0], [25 / 175, 0.0]], atol=1e-6)

    def test_encode_decode_roundtrip(self):
        rng = np.random.default_rng(0)
        anchors = np.abs(rng.normal(50, 20, (32, 2)))
        anchors = np.concatenate([anchors, anchors + np.abs(
            rng.normal(30, 10, (32, 2))) + 1], axis=1).astype(np.float32)
        gt = anchors + rng.normal(0, 3, anchors.shape).astype(np.float32)
        gt[:, 2:] = np.maximum(gt[:, 2:], gt[:, :2] + 1)
        deltas = B.encode_boxes(jnp.asarray(gt), jnp.asarray(anchors),
                                weights=(10, 10, 5, 5))
        back = B.decode_boxes(deltas, jnp.asarray(anchors),
                              weights=(10, 10, 5, 5))
        np.testing.assert_allclose(np.asarray(back), gt, atol=1e-3)

    def test_elementwise_iou_kinds(self):
        b1 = jnp.asarray([[0, 0, 10, 10]], jnp.float32)
        b2 = jnp.asarray([[5, 5, 15, 15]], jnp.float32)
        iou = float(B.elementwise_box_iou(b1, b2, "iou")[0])
        giou = float(B.elementwise_box_iou(b1, b2, "giou")[0])
        ciou = float(B.elementwise_box_iou(b1, b2, "ciou")[0])
        assert iou == pytest.approx(25 / 175, abs=1e-6)
        assert giou < iou          # hull penalty
        assert ciou < iou          # distance penalty
        # identical boxes: all kinds == 1
        same = float(B.elementwise_box_iou(b1, b1, "ciou")[0])
        assert same == pytest.approx(1.0, abs=1e-6)

    def test_clip_and_small_mask(self):
        boxes = jnp.asarray([[-5, -5, 20, 20], [0, 0, 0.5, 8]], jnp.float32)
        clipped = B.clip_boxes(boxes, (10, 12))
        np.testing.assert_allclose(np.asarray(clipped),
                                   [[0, 0, 12, 10], [0, 0, 0.5, 8]])
        mask = B.remove_small_boxes_mask(clipped, 1.0)
        np.testing.assert_array_equal(np.asarray(mask), [True, False])


class TestNMS:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_matches_numpy_greedy(self, seed):
        rng = np.random.default_rng(seed)
        n = 60
        ctr = rng.uniform(10, 90, (n, 2))
        wh = rng.uniform(5, 30, (n, 2))
        boxes = np.concatenate([ctr - wh / 2, ctr + wh / 2],
                               axis=1).astype(np.float32)
        scores = rng.uniform(0, 1, n).astype(np.float32)
        ref = np_nms(boxes, scores, 0.5)
        idx, valid = jax.jit(
            lambda b, s: N.nms(b, s, 0.5, max_out=n))(
            jnp.asarray(boxes), jnp.asarray(scores))
        got = np.asarray(idx)[np.asarray(valid)]
        np.testing.assert_array_equal(got, ref)

    def test_max_out_truncates(self):
        boxes = jnp.asarray([[i * 20, 0, i * 20 + 10, 10] for i in range(8)],
                            jnp.float32)
        scores = jnp.asarray(np.linspace(0.9, 0.2, 8), jnp.float32)
        idx, valid = N.nms(boxes, scores, 0.5, max_out=3)
        assert int(valid.sum()) == 3
        np.testing.assert_array_equal(np.asarray(idx), [0, 1, 2])

    def test_batched_nms_classes_dont_suppress(self):
        boxes = jnp.asarray([[0, 0, 10, 10], [1, 1, 11, 11]], jnp.float32)
        scores = jnp.asarray([0.9, 0.8])
        classes = jnp.asarray([0, 1])
        _, valid = N.batched_nms(boxes, scores, classes, 0.3, max_out=2)
        assert int(valid.sum()) == 2          # same box, different class
        _, valid_same = N.nms(boxes, scores, 0.3, max_out=2)
        assert int(valid_same.sum()) == 1

    def test_score_threshold(self):
        boxes = jnp.asarray([[0, 0, 10, 10], [20, 20, 30, 30]], jnp.float32)
        scores = jnp.asarray([0.9, 0.01])
        _, valid = N.nms(boxes, scores, 0.5, max_out=2, score_threshold=0.1)
        assert int(valid.sum()) == 1


class TestRoIAlign:
    @pytest.mark.parametrize("aligned", [False])
    def test_matches_numpy(self, aligned):
        rng = np.random.default_rng(0)
        feat = rng.normal(0, 1, (16, 16, 3)).astype(np.float32)
        rois = np.asarray([[2.0, 2.0, 10.0, 12.0], [0.0, 0.0, 32.0, 32.0]],
                          np.float32)
        out = R.roi_align(jnp.asarray(feat), jnp.asarray(rois),
                          output_size=5, spatial_scale=0.5,
                          sampling_ratio=2)
        for r in range(2):
            ref = np_roi_align(feat, rois[r], 5, 0.5, 2)
            np.testing.assert_allclose(np.asarray(out[r]), ref, atol=1e-4)

    def test_multiscale_level_assignment(self):
        rng = np.random.default_rng(0)
        pyramid = {f"p{l}": jnp.asarray(
            rng.normal(0, 1, (64 // 2 ** (l - 2), 64 // 2 ** (l - 2), 4)),
            jnp.float32) for l in (2, 3, 4, 5)}
        rois = jnp.asarray([
            [0, 0, 32, 32],          # small → p2
            [0, 0, 224, 224],        # canonical → p4
            [0, 0, 500, 500],        # large → p5
        ], jnp.float32)
        out = R.multiscale_roi_align(pyramid, rois, output_size=7)
        assert out.shape == (3, 7, 7, 4)
        # small roi must equal direct p2 align
        direct = R.roi_align(pyramid["p2"], rois[:1], 7, 1 / 4, 2)
        np.testing.assert_allclose(np.asarray(out[0]), np.asarray(direct[0]),
                                   atol=1e-5)


class TestMatcherSampler:
    def test_matcher_categories(self):
        gt = jnp.asarray([[0, 0, 10, 10], [50, 50, 60, 60], [0, 0, 0, 0]],
                         jnp.float32)
        valid = jnp.asarray([True, True, False])
        anchors = jnp.asarray([
            [0, 0, 10, 10],        # IoU 1.0 with gt0 -> match 0
            [0, 0, 14, 10],        # IoU ~0.71 -> match 0
            [2, 2, 12, 12],        # IoU ~0.47 with gt0 -> between
            [48, 48, 54, 54],      # IoU ~0.1 with gt1 -> below, but best
        ], jnp.float32)
        iou = B.box_iou(gt, anchors)
        m = M.match_anchors(iou, valid, 0.7, 0.3, allow_low_quality=False)
        assert int(m[0]) == 0 and int(m[1]) == 0
        assert int(m[2]) == M.BETWEEN
        assert int(m[3]) == M.BELOW_LOW
        forced = M.match_anchors(iou, valid, 0.7, 0.3,
                                 allow_low_quality=True)
        assert int(forced[3]) == 1           # gt1's best anchor forced in

    def test_balanced_sampler_counts(self):
        matches = jnp.asarray([0] * 10 + [M.BELOW_LOW] * 100
                              + [M.BETWEEN] * 5)
        pos, neg = M.balanced_sample(matches, jax.random.key(0),
                                     batch_size_per_image=64,
                                     positive_fraction=0.25)
        assert int(pos.sum()) == 10            # only 10 available (<16)
        assert int(neg.sum()) == 54            # fills to 64
        assert not bool((pos & neg).any())
        # between-category anchors never sampled
        assert not bool(pos[110:].any()) and not bool(neg[110:].any())


class TestAnchors:
    def test_grid_counts_and_coverage(self):
        shapes = {"p3": (8, 8), "p4": (4, 4)}
        strides = {"p3": 8, "p4": 16}
        sizes = {"p3": (32,), "p4": (64,)}
        all_anchors, counts = anc.pyramid_anchors(shapes, strides, sizes,
                                                  ratios=(1.0,))
        assert counts == [64, 16]
        assert all_anchors.shape == (80, 4)
        # first p3 anchor centered at (0,0) with size 32
        np.testing.assert_allclose(all_anchors[0], [-16, -16, 16, 16])
        # retinanet sizes helper
        s = anc.retinanet_sizes()
        assert set(s) == {"p3", "p4", "p5", "p6", "p7"}
        assert s["p3"][0] == pytest.approx(32)
