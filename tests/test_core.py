"""Core runtime tests: config, registry, precision, rng, checkpoint."""

import dataclasses
import os
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning_tpu.core import config as cfg_mod
from deeplearning_tpu.core import precision, rng
from deeplearning_tpu.core.checkpoint import (CheckpointManager, load_pytree,
                                              save_pytree, surgical_load)
from deeplearning_tpu.core.registry import Registry


@dataclasses.dataclass(frozen=True)
class Train:
    lr: float = 0.1
    epochs: int = 10
    sizes: Tuple[int, ...] = (1, 2)


@dataclasses.dataclass(frozen=True)
class Cfg:
    name: str = "m"
    train: Train = dataclasses.field(default_factory=Train)


class TestConfig:
    def test_defaults_yaml_cli_precedence(self, tmp_path):
        base = tmp_path / "base.yaml"
        base.write_text("train:\n  lr: 0.5\n  epochs: 3\n")
        child = tmp_path / "child.yaml"
        child.write_text(f"_base_: base.yaml\nname: x\ntrain:\n  lr: 0.7\n")
        out = cfg_mod.load_config(Cfg(), str(child),
                                  opts=["train.epochs", "99"])
        assert out.name == "x"
        assert out.train.lr == 0.7          # yaml beats base
        assert out.train.epochs == 99       # cli beats yaml

    def test_equals_style_opts_and_coercion(self):
        out = cfg_mod.load_config(Cfg(), opts=["train.lr=1e-3",
                                               "train.sizes=[4,5,6]"])
        assert out.train.lr == pytest.approx(1e-3)
        assert out.train.sizes == (4, 5, 6)

    def test_strict_unknown_key(self):
        with pytest.raises(KeyError):
            cfg_mod.load_config(Cfg(), opts=["nope", "1"])

    def test_save_roundtrip(self, tmp_path):
        p = str(tmp_path / "c.yaml")
        cfg_mod.save_config(Cfg(), p)
        out = cfg_mod.load_config(Cfg(), p)
        assert out == Cfg()


class TestRegistry:
    def test_register_get_build(self):
        reg = Registry("t")

        @reg.register()
        def thing(x):
            return x * 2

        assert reg.build("thing", 3) == 6
        with pytest.raises(KeyError):
            reg.get("missing")
        with pytest.raises(KeyError):
            reg.register("thing")(lambda: None)


class TestPrecision:
    def test_policy_cast(self):
        pol = precision.get_policy("bf16")
        tree = {"w": jnp.ones((2, 2), jnp.float32), "i": jnp.ones((2,), jnp.int32)}
        out = pol.cast_to_compute(tree)
        assert out["w"].dtype == jnp.bfloat16
        assert out["i"].dtype == jnp.int32

    def test_clip_by_global_norm(self):
        tree = {"a": jnp.ones((3,)) * 2.0}
        clipped, norm = precision.clip_by_global_norm(tree, 1.0)
        assert norm == pytest.approx(np.sqrt(12), rel=1e-5)
        got = precision.global_norm(clipped)
        assert float(got) == pytest.approx(1.0, rel=1e-4)

    def test_no_clip_reports_norm(self):
        tree = {"a": jnp.ones((4,))}
        same, norm = precision.clip_by_global_norm(tree, None)
        assert float(norm) == pytest.approx(2.0)
        np.testing.assert_array_equal(same["a"], tree["a"])


class TestRng:
    def test_step_key_deterministic(self):
        k = rng.root_key(0)
        a = jax.random.normal(rng.step_key(k, 5), (3,))
        b = jax.random.normal(rng.step_key(k, 5), (3,))
        c = jax.random.normal(rng.step_key(k, 6), (3,))
        np.testing.assert_array_equal(a, b)
        assert not np.allclose(a, c)


class TestCheckpoint:
    def test_manager_save_restore_auto_resume(self, tmp_path):
        state = {"params": {"w": jnp.arange(4.0)}, "step": jnp.asarray(0)}
        mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=2)
        mgr.save(1, state)
        state2 = {"params": {"w": jnp.arange(4.0) * 2},
                  "step": jnp.asarray(1)}
        mgr.save(2, state2, is_best=True)
        restored, step = mgr.auto_resume(jax.tree.map(np.zeros_like, state))
        assert step == 2
        np.testing.assert_array_equal(restored["params"]["w"],
                                      np.arange(4.0) * 2)
        assert os.path.isdir(str(tmp_path / "ckpt" / "best"))
        mgr.close()

    def test_pytree_roundtrip(self, tmp_path):
        tree = {"a": np.ones((2, 3)), "b": {"c": np.arange(5)}}
        save_pytree(str(tmp_path / "tree"), tree)
        out = load_pytree(str(tmp_path / "tree"))
        np.testing.assert_array_equal(out["a"], tree["a"])
        np.testing.assert_array_equal(out["b"]["c"], tree["b"]["c"])

    def test_surgical_load(self):
        params = {"backbone": {"w": np.zeros((3, 3))},
                  "head": {"w": np.zeros((3, 10))}}
        pretrained = {"backbone": {"w": np.ones((3, 3))},
                      "head": {"w": np.ones((3, 5))}}   # mismatched head
        out = surgical_load(params, pretrained, drop=[r"^head"])
        np.testing.assert_array_equal(out["backbone"]["w"], np.ones((3, 3)))
        np.testing.assert_array_equal(out["head"]["w"], np.zeros((3, 10)))

    def test_surgical_load_resize_hook(self):
        params = {"pos": np.zeros((4,))}
        pretrained = {"pos": np.ones((2,))}

        def resize(path, value, shape):
            return np.resize(value, shape)

        out = surgical_load(params, pretrained, resize_fn=resize)
        np.testing.assert_array_equal(out["pos"], np.ones((4,)))


class TestRestoreVariables:
    """One shared interpretation of inference checkpoints for every CLI
    (predict/evaluate/demo) — EMA preferred, batch_stats merged."""

    def test_trainstate_dict_prefers_ema_and_merges_stats(self, tmp_path):
        import jax.numpy as jnp
        from deeplearning_tpu.core.checkpoint import (restore_variables,
                                                      save_pytree)
        ckpt = {"params": {"w": jnp.ones(2)},
                "ema_params": {"w": jnp.full(2, 3.0)},
                "batch_stats": {"bn": {"mean": jnp.full(1, 7.0)}},
                "step": 5}
        path = str(tmp_path / "ck")
        save_pytree(path, ckpt)
        init = {"params": {"w": jnp.zeros(2)},
                "batch_stats": {"bn": {"mean": jnp.zeros(1)}}}
        v = restore_variables(path, init)
        assert float(v["params"]["w"][0]) == 3.0
        assert float(v["batch_stats"]["bn"]["mean"][0]) == 7.0
        v2 = restore_variables(path, init, prefer_ema=False)
        assert float(v2["params"]["w"][0]) == 1.0

    def test_bare_param_tree(self, tmp_path):
        import jax.numpy as jnp
        from deeplearning_tpu.core.checkpoint import (restore_variables,
                                                      save_pytree)
        path = str(tmp_path / "ck")
        save_pytree(path, {"w": jnp.full(2, 4.0)})
        v = restore_variables(path, {"params": {"w": jnp.zeros(2)}})
        assert float(v["params"]["w"][0]) == 4.0


class TestHub:
    def test_load_and_forward(self, tmp_path):
        import jax.numpy as jnp
        from deeplearning_tpu import hub
        assert "resnet18" in hub.list_models("resnet")
        model, variables, forward = hub.load(
            "mnist_cnn", num_classes=4, input_shape=(1, 28, 28, 1))
        out = forward(jnp.zeros((2, 28, 28, 1)))
        assert out.shape == (2, 4)

    def test_load_with_ckpt(self, tmp_path):
        import jax.numpy as jnp
        import numpy as np
        from deeplearning_tpu import hub
        from deeplearning_tpu.core.checkpoint import save_pytree
        _, variables, _ = hub.load("mnist_cnn", num_classes=4,
                                   input_shape=(1, 28, 28, 1))
        mutated = {"params": jax.tree.map(lambda x: x + 1.0,
                                          variables["params"])}
        path = str(tmp_path / "ck")
        save_pytree(path, mutated)
        _, v2, fwd = hub.load("mnist_cnn", num_classes=4,
                              input_shape=(1, 28, 28, 1), ckpt=path)
        a = jax.tree.leaves(v2["params"])[0]
        b = jax.tree.leaves(variables["params"])[0]
        np.testing.assert_allclose(np.asarray(a), np.asarray(b) + 1.0)


class TestAsyncCheckpoint:
    def test_async_best_survives_gc_and_holds_best_data(self, tmp_path):
        import os
        import jax.numpy as jnp
        import numpy as np
        from deeplearning_tpu.core.checkpoint import (CheckpointManager,
                                                      load_pytree)
        mgr = CheckpointManager(str(tmp_path / "ck"), max_to_keep=2,
                                async_save=True)
        mgr.save(1, {"w": jnp.arange(4.0)}, is_best=True)
        # enough later saves that max_to_keep GC deletes step 1
        for step in (2, 3, 4):
            mgr.save(step, {"w": jnp.arange(4.0) + step})
        mgr.wait_until_finished()
        got = mgr.restore({"w": jnp.zeros(4)}, step=4)
        np.testing.assert_allclose(np.asarray(got["w"]),
                                   np.arange(4.0) + 4)
        # the best dir exists AND holds step 1's data, even though
        # step 1's own dir was garbage-collected
        best = str(tmp_path / "ck" / "best")
        assert os.path.isdir(best)
        assert not os.path.isdir(str(tmp_path / "ck" / "1"))
        restored = load_pytree(best)
        np.testing.assert_allclose(np.asarray(restored["w"]),
                                   np.arange(4.0))
        mgr.close()
