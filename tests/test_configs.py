"""Every shipped config must parse against its CLI schema and name a
buildable registry model (the reference's configs/*.yaml zoo breadth,
VERDICT item 10)."""

import glob
import os
import sys

import jax.numpy as jnp
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

CONFIGS = sorted(glob.glob(os.path.join(REPO, "configs", "*.yaml")))
DETECTION_PREFIXES = ("retinanet", "fasterrcnn", "yolox", "fcos",
                      "yolov5")


def _schema_for(path):
    import yaml
    from deeplearning_tpu.core.config import load_config
    raw = {}
    p = path
    while p:      # follow _base_ chain to find the model name
        with open(p) as f:
            doc = yaml.safe_load(f) or {}
        raw = {**doc, **raw}
        base = doc.get("_base_")
        p = os.path.join(os.path.dirname(p), base) if base else None
    name = (raw.get("model") or {}).get("name", "")
    if name.startswith(DETECTION_PREFIXES):
        from train_detection import DetConfig
        return load_config(DetConfig(), path), name
    from train import Config
    return load_config(Config(), path), name


def test_at_least_fifteen_configs():
    assert len(CONFIGS) >= 15


@pytest.mark.parametrize("path", CONFIGS,
                         ids=[os.path.basename(p) for p in CONFIGS])
def test_config_parses_and_model_builds(path):
    from deeplearning_tpu.core.registry import MODELS
    cfg, name = _schema_for(path)
    assert cfg.model.name == name
    model = MODELS.build(name, num_classes=cfg.model.num_classes,
                         dtype=jnp.float32)
    assert model is not None
