"""Sequence parallelism as a USER-FACING training option:
train.mesh_seq_axis + train.seq_parallel build the ring/Ulysses attn_fn
into the model through tools/train.py (long-context training is
first-class, not a library-only capability)."""

import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

COMMON = ["model.name=vit_base_patch16_224", "model.num_classes=4",
          "data.synthetic=true", "data.image_size=32", "data.channels=3",
          "data.n_train=16", "data.global_batch=8", "train.epochs=1"]


@pytest.mark.parametrize("flavor", ["ring", "ulysses"])
def test_sp_training_through_cli(flavor, capsys):
    from train import main
    rc = main(COMMON + ["train.mesh_seq_axis=2",
                        f"train.seq_parallel={flavor}"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "loss_sum" in out


def test_unknown_flavor_rejected():
    from train import main
    with pytest.raises(ValueError, match="seq_parallel"):
        main(COMMON + ["train.mesh_seq_axis=2",
                       "train.seq_parallel=nope"])


def test_mae_with_ring_attn_matches_plain():
    """MAE pretraining composes with SP: same loss with and without the
    ring attn_fn (the ring is exact attention)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from deeplearning_tpu.core.registry import MODELS
    from deeplearning_tpu.parallel import MeshConfig, build_mesh
    from deeplearning_tpu.parallel.ring_attention import make_ring_attn_fn

    mesh = build_mesh(MeshConfig(data=-1, seq=2))
    imgs = jnp.asarray(np.random.default_rng(0).normal(
        size=(2, 32, 32, 3)), jnp.float32)
    rng = jax.random.key(1)
    plain = MODELS.build("mae_vit_small_patch16", patch_size=8,
                         dtype=jnp.float32)
    variables = plain.init(jax.random.key(0), imgs, train=False, rng=rng)
    ringed = MODELS.build("mae_vit_small_patch16", patch_size=8,
                          dtype=jnp.float32,
                          attn_fn=make_ring_attn_fn(mesh))
    loss_p, _, _ = plain.apply(variables, imgs, train=False, rng=rng)
    loss_r, _, _ = jax.jit(
        lambda v, x: ringed.apply(v, x, train=False, rng=rng))(
        variables, imgs)
    np.testing.assert_allclose(float(loss_r), float(loss_p), rtol=1e-4)


def test_3d_parallel_train_step():
    """DP x TP x SP composed in ONE train step: batch over data, params
    over model (TRANSFORMER_TP_RULES), attention tokens over seq (ring
    adapter). Loss must be finite and match the plain DP run."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from deeplearning_tpu.core.registry import MODELS
    from deeplearning_tpu.parallel import MeshConfig, build_mesh
    from deeplearning_tpu.parallel.ring_attention import make_ring_attn_fn
    from deeplearning_tpu.parallel.sharding import (TRANSFORMER_TP_RULES,
                                                    batch_sharding)
    from deeplearning_tpu.train import (TrainState, make_train_step,
                                        shard_state)
    from deeplearning_tpu.train.classification import make_loss_fn
    import optax

    mesh = build_mesh(MeshConfig(data=2, model=2, seq=2))
    g = np.random.default_rng(0)
    batch = {"image": jnp.asarray(g.normal(size=(8, 32, 32, 3)),
                                  jnp.float32),
             "label": jnp.asarray(g.integers(0, 4, 8), jnp.int32)}

    def build(attn_fn, msh, rules):
        model = MODELS.build("vit_base_patch16_224", num_classes=4,
                             img_size=32, patch_size=8, embed_dim=32,
                             depth=2, num_heads=4, dtype=jnp.float32,
                             attn_fn=attn_fn)
        params = model.init(jax.random.key(0),
                            jnp.zeros((1, 32, 32, 3)),
                            train=False)["params"]
        state = shard_state(
            TrainState.create(apply_fn=model.apply, params=params,
                              tx=optax.sgd(0.01)), msh, rules)
        step = make_train_step(make_loss_fn(), mesh=msh)
        data = jax.device_put(batch, batch_sharding(msh))
        return step(state, data, jax.random.key(1))

    state3, m3 = build(make_ring_attn_fn(mesh), mesh,
                       TRANSFORMER_TP_RULES)
    mesh_dp = build_mesh(MeshConfig(data=-1))
    state1, m1 = build(None, mesh_dp, None)
    assert np.isfinite(float(m3["loss"]))
    np.testing.assert_allclose(float(m3["loss"]), float(m1["loss"]),
                               rtol=1e-4)
    for a, b in zip(jax.tree.leaves(state3.params),
                    jax.tree.leaves(state1.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-3)
