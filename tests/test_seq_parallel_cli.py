"""Sequence parallelism as a USER-FACING training option:
train.mesh_seq_axis + train.seq_parallel build the ring/Ulysses attn_fn
into the model through tools/train.py (long-context training is
first-class, not a library-only capability)."""

import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

COMMON = ["model.name=vit_base_patch16_224", "model.num_classes=4",
          "data.synthetic=true", "data.image_size=32", "data.channels=3",
          "data.n_train=16", "data.global_batch=8", "train.epochs=1"]


@pytest.mark.parametrize("flavor", ["ring", "ulysses"])
def test_sp_training_through_cli(flavor, capsys):
    from train import main
    rc = main(COMMON + ["train.mesh_seq_axis=2",
                        f"train.seq_parallel={flavor}"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "loss_sum" in out


def test_unknown_flavor_rejected():
    from train import main
    with pytest.raises(ValueError, match="seq_parallel"):
        main(COMMON + ["train.mesh_seq_axis=2",
                       "train.seq_parallel=nope"])
