"""Ulysses (all-to-all) sequence parallelism vs single-device reference.

The second SP flavor next to ring attention; heads redistribute over the
seq axis so each device runs full-sequence attention for H/P heads."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from deeplearning_tpu.parallel import MeshConfig, build_mesh
from deeplearning_tpu.parallel.ulysses import make_ulysses_attention


def reference(q, k, v):
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(q.dtype), v)


def _qkv(seq_devices, b=2, h=8, d=16, n_per=32, seed=0):
    rng = np.random.default_rng(seed)
    n = n_per * seq_devices
    return tuple(jnp.asarray(rng.normal(0, 1, (b, h, n, d)), jnp.float32)
                 for _ in range(3))


class TestUlysses:
    @pytest.mark.parametrize("seq_devices", [4, 8])
    def test_matches_reference(self, seq_devices):
        mesh = build_mesh(MeshConfig(data=-1, seq=seq_devices))
        q, k, v = _qkv(seq_devices)
        ref = reference(q, k, v)
        sharding = NamedSharding(mesh, P(None, None, "seq", None))
        qs, ks, vs = (jax.device_put(t, sharding) for t in (q, k, v))
        fn = jax.jit(make_ulysses_attention(mesh))
        out = fn(qs, ks, vs)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_gradients_match_reference(self):
        # unlike ring+flash, Ulysses composes with ANY inner attention
        # differentiably — the all_to_alls transpose cleanly
        mesh = build_mesh(MeshConfig(data=-1, seq=4))
        q, k, v = _qkv(4, seed=1)
        sharding = NamedSharding(mesh, P(None, None, "seq", None))
        qs, ks, vs = (jax.device_put(t, sharding) for t in (q, k, v))
        fn = make_ulysses_attention(mesh)
        g_sp = jax.jit(jax.grad(
            lambda q, k, v: jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2),
            argnums=(0, 1, 2)))(qs, ks, vs)
        g_ref = jax.grad(
            lambda q, k, v: jnp.sum(
                reference(q, k, v).astype(jnp.float32) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_sp, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-5, rtol=5e-5)

    def test_flash_inner_attention(self):
        from deeplearning_tpu.ops.pallas.flash_attention import (
            flash_attention)
        mesh = build_mesh(MeshConfig(data=-1, seq=4))
        q, k, v = _qkv(4, seed=2)
        ref = reference(q, k, v)
        sharding = NamedSharding(mesh, P(None, None, "seq", None))
        qs, ks, vs = (jax.device_put(t, sharding) for t in (q, k, v))
        fn = jax.jit(make_ulysses_attention(
            mesh, attn_fn=flash_attention, check_vma=False))
        out = fn(qs, ks, vs)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_rejects_indivisible_heads(self):
        mesh = build_mesh(MeshConfig(data=-1, seq=4))
        q, k, v = _qkv(4, h=6)   # 6 heads over 4 devices
        sharding = NamedSharding(mesh, P(None, None, "seq", None))
        qs, ks, vs = (jax.device_put(t, sharding) for t in (q, k, v))
        with pytest.raises(ValueError, match="divide"):
            jax.jit(make_ulysses_attention(mesh))(qs, ks, vs)
