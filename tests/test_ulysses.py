"""Ulysses (all-to-all) sequence parallelism vs single-device reference.

The second SP flavor next to ring attention; heads redistribute over the
seq axis so each device runs full-sequence attention for H/P heads."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from deeplearning_tpu.parallel import MeshConfig, build_mesh
from deeplearning_tpu.parallel.ulysses import make_ulysses_attention


def reference(q, k, v):
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(q.dtype), v)


def _qkv(seq_devices, b=2, h=8, d=16, n_per=32, seed=0):
    rng = np.random.default_rng(seed)
    n = n_per * seq_devices
    return tuple(jnp.asarray(rng.normal(0, 1, (b, h, n, d)), jnp.float32)
                 for _ in range(3))


class TestUlysses:
    @pytest.mark.parametrize("seq_devices", [4, 8])
    def test_matches_reference(self, seq_devices):
        mesh = build_mesh(MeshConfig(data=-1, seq=seq_devices))
        q, k, v = _qkv(seq_devices)
        ref = reference(q, k, v)
        sharding = NamedSharding(mesh, P(None, None, "seq", None))
        qs, ks, vs = (jax.device_put(t, sharding) for t in (q, k, v))
        fn = jax.jit(make_ulysses_attention(mesh))
        out = fn(qs, ks, vs)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_gradients_match_reference(self):
        # unlike ring+flash, Ulysses composes with ANY inner attention
        # differentiably — the all_to_alls transpose cleanly
        mesh = build_mesh(MeshConfig(data=-1, seq=4))
        q, k, v = _qkv(4, seed=1)
        sharding = NamedSharding(mesh, P(None, None, "seq", None))
        qs, ks, vs = (jax.device_put(t, sharding) for t in (q, k, v))
        fn = make_ulysses_attention(mesh)
        g_sp = jax.jit(jax.grad(
            lambda q, k, v: jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2),
            argnums=(0, 1, 2)))(qs, ks, vs)
        g_ref = jax.grad(
            lambda q, k, v: jnp.sum(
                reference(q, k, v).astype(jnp.float32) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_sp, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-5, rtol=5e-5)

    def test_flash_inner_attention(self):
        from deeplearning_tpu.ops.pallas.flash_attention import (
            flash_attention)
        mesh = build_mesh(MeshConfig(data=-1, seq=4))
        q, k, v = _qkv(4, seed=2)
        ref = reference(q, k, v)
        sharding = NamedSharding(mesh, P(None, None, "seq", None))
        qs, ks, vs = (jax.device_put(t, sharding) for t in (q, k, v))
        fn = jax.jit(make_ulysses_attention(
            mesh, attn_fn=flash_attention, check_vma=False))
        out = fn(qs, ks, vs)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_rejects_indivisible_heads(self):
        mesh = build_mesh(MeshConfig(data=-1, seq=4))
        q, k, v = _qkv(4, h=6)   # 6 heads over 4 devices
        sharding = NamedSharding(mesh, P(None, None, "seq", None))
        qs, ks, vs = (jax.device_put(t, sharding) for t in (q, k, v))
        with pytest.raises(ValueError, match="divide"):
            jax.jit(make_ulysses_attention(mesh))(qs, ks, vs)


class TestUlyssesAttnFnInModel:
    def test_vit_forward_matches_naive(self):
        """Ulysses dropped INTO a ViT via attn_fn — N=17 (16+cls) padded
        over a 4-device seq axis, 4 heads redistributed."""
        from deeplearning_tpu.models.classification.vit import (
            VisionTransformer)
        from deeplearning_tpu.parallel.ulysses import make_ulysses_attn_fn
        mesh = build_mesh(MeshConfig(data=-1, seq=4))
        x = jnp.asarray(np.random.default_rng(0).normal(
            size=(2, 32, 32, 3)), jnp.float32)

        def tiny(attn_fn=None):
            return VisionTransformer(
                img_size=32, patch_size=8, num_classes=3, embed_dim=32,
                depth=2, num_heads=4, dtype=jnp.float32, attn_fn=attn_fn)

        naive = tiny()
        variables = naive.init(jax.random.key(0), x, train=False)
        uly = tiny(attn_fn=make_ulysses_attn_fn(mesh))
        want = naive.apply(variables, x, train=False)
        got = jax.jit(lambda v, xx: uly.apply(v, xx, train=False))(
            variables, x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-4, rtol=1e-4)

        g_u = jax.jit(jax.grad(lambda v: jnp.sum(
            uly.apply(v, x, train=False).astype(jnp.float32) ** 2)))(
            variables)
        g_n = jax.grad(lambda v: jnp.sum(
            naive.apply(v, x, train=False).astype(jnp.float32) ** 2))(
            variables)
        for a, b in zip(jax.tree.leaves(g_u), jax.tree.leaves(g_n)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-4, rtol=2e-4)
