"""Real-image input pipelines: ImageFolder builder + COCO-json source
(data/build.py, data/coco.py — dataLoader/build.py and YOLOX
datasets/coco.py surfaces), decoding actual JPEGs from disk."""

import json
import os

import numpy as np
import pytest


@pytest.fixture(scope="module")
def image_folder(tmp_path_factory):
    """Tiny 2-class ImageFolder of real JPEGs."""
    from PIL import Image
    root = tmp_path_factory.mktemp("folder")
    rng = np.random.default_rng(0)
    for c in range(2):
        d = root / f"class{c}"
        d.mkdir()
        for i in range(12):
            arr = rng.integers(0, 255, (40, 40, 3), dtype=np.uint8)
            arr[:, :, c] = 255  # class-colored channel
            Image.fromarray(arr).save(d / f"im{i}.jpg")
    return str(root)


@pytest.fixture(scope="module")
def coco_folder(tmp_path_factory):
    """Tiny COCO-format detection set of real JPEGs."""
    from PIL import Image
    root = tmp_path_factory.mktemp("coco")
    (root / "images").mkdir()
    rng = np.random.default_rng(1)
    coco = {"images": [], "annotations": [],
            "categories": [{"id": 1, "name": "thing"}]}
    ann = 1
    for i in range(6):
        arr = rng.integers(0, 120, (48, 64, 3), dtype=np.uint8)
        arr[10:30, 20:50] = 255
        Image.fromarray(arr).save(root / "images" / f"i{i}.jpg")
        coco["images"].append({"id": i, "file_name": f"i{i}.jpg",
                               "width": 64, "height": 48})
        coco["annotations"].append({
            "id": ann, "image_id": i, "category_id": 1,
            "bbox": [20, 10, 30, 20], "area": 600, "iscrowd": 0})
        ann += 1
    with open(root / "instances.json", "w") as f:
        json.dump(coco, f)
    return str(root)


class TestFolderBuilder:
    def test_loaders_and_shapes(self, image_folder):
        from deeplearning_tpu.data.build import (LoaderConfig,
                                                 build_classification_loaders)
        cfg = LoaderConfig(global_batch=8, image_size=32, val_rate=0.25,
                           num_workers=2, augment="light")
        train, val, c2i = build_classification_loaders(image_folder, cfg)
        assert sorted(c2i) == ["class0", "class1"]
        batch = next(iter(train))
        assert batch["image"].shape == (8, 32, 32, 3)
        assert batch["label"].shape == (8,)
        # val split smaller than global_batch must still yield batches
        vb = next(iter(val))
        assert vb["image"].shape[0] >= 1

    def test_augment_presets_differ(self, image_folder):
        from deeplearning_tpu.data.transforms import (
            eval_image_transform, get_train_transform)
        from deeplearning_tpu.data.datasets import load_image
        img = load_image(os.path.join(image_folder, "class0", "im0.jpg"))
        out_none = get_train_transform("none", (32, 32))(img)
        out_eval = eval_image_transform((32, 32), crop_frac=1.0)(img)
        np.testing.assert_allclose(out_none, out_eval)
        with pytest.raises(ValueError):
            get_train_transform("nope")

    def test_throughput_meter_runs(self, image_folder):
        from deeplearning_tpu.data.build import (LoaderConfig,
                                                 build_classification_loaders,
                                                 measure_throughput)
        cfg = LoaderConfig(global_batch=4, image_size=32, val_rate=0.25,
                           num_workers=2)
        train, _, _ = build_classification_loaders(image_folder, cfg)
        rate = measure_throughput(train, n_batches=2, warmup=1)
        assert rate > 0


class TestCocoSource:
    def test_fixed_shapes_and_box_scaling(self, coco_folder):
        from deeplearning_tpu.data.coco import coco_detection_source
        src, names = coco_detection_source(
            os.path.join(coco_folder, "instances.json"),
            image_size=32, max_gt=4)
        assert names == ["thing"]
        s = src[0]
        assert s["image"].shape == (32, 32, 3)
        assert s["boxes"].shape == (4, 4)
        assert s["valid"].sum() == 1
        # 64-wide image → scale 0.5; box [20,10,50,30] → [10,5,25,15]
        np.testing.assert_allclose(s["boxes"][0], [10, 5, 25, 15],
                                   atol=0.5)
        assert s["image"].max() <= 1.0

    def test_preparsed_records_shared(self, coco_folder):
        from deeplearning_tpu.data.coco import (coco_detection_source,
                                                load_coco_json)
        records, names = load_coco_json(
            os.path.join(coco_folder, "instances.json"))
        src, _ = coco_detection_source(
            images_dir=os.path.join(coco_folder, "images"),
            records=records, class_names=names, image_size=32, max_gt=2)
        assert len(src) == 6

    def test_augment_flip_keeps_box_inside(self, coco_folder):
        from deeplearning_tpu.data.coco import coco_detection_source
        src, _ = coco_detection_source(
            os.path.join(coco_folder, "instances.json"),
            image_size=32, max_gt=4, augment=True, seed=0)
        for i in range(len(src)):
            s = src[i]
            b = s["boxes"][s["valid"]]
            assert (b[:, 0] < b[:, 2]).all() and (b[:, 1] < b[:, 3]).all()
            assert b.min() >= 0 and b.max() <= 32
