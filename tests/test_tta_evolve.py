"""TTA inference and genetic hyperparameter evolution.

References: yolov5 models/yolo.py:183-244 (forward_augment/_descale_pred),
train.py:637-716 (--evolve loop), utils/metrics.py:15 (fitness)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning_tpu.ops.tta import (classify_tta, descale_boxes,
                                      flip_lr_boxes, yolox_tta)
from deeplearning_tpu.train.evolve import (DETECTION_META, best_hyp,
                                           det_fitness, evolve, mutate)


class TestDescale:
    def test_flip_roundtrip(self):
        boxes = jnp.asarray([[10.0, 5.0, 30.0, 25.0]])
        flipped = flip_lr_boxes(boxes, 100.0)
        np.testing.assert_allclose(np.asarray(flipped),
                                   [[70.0, 5.0, 90.0, 25.0]])
        back = flip_lr_boxes(flipped, 100.0)
        np.testing.assert_allclose(np.asarray(back), np.asarray(boxes))

    def test_descale_inverts_scale_and_flip(self):
        base = np.array([[40.0, 16.0, 80.0, 48.0]], np.float32)
        # forward transform: scale by 0.5 into a 64-wide frame, then flip
        scaled = base * 0.5
        aug = np.asarray(flip_lr_boxes(jnp.asarray(scaled), 64.0))
        out = descale_boxes(jnp.asarray(aug), 0.5, True, 64.0)
        np.testing.assert_allclose(np.asarray(out), base, rtol=1e-6)

    def test_descale_anisotropic(self):
        base = np.array([[10.0, 20.0, 30.0, 60.0]], np.float32)
        aug = base * np.array([0.5, 0.25, 0.5, 0.25])
        out = descale_boxes(jnp.asarray(aug), (0.5, 0.25), False, 0.0)
        np.testing.assert_allclose(np.asarray(out), base, rtol=1e-6)


class TestClassifyTTA:
    def test_flip_average_changes_asymmetric_logits(self):
        # logits_fn keyed on image content: mean over W-halves
        def logits_fn(x):
            left = x[:, :, : x.shape[2] // 2].mean((1, 2, 3))
            right = x[:, :, x.shape[2] // 2:].mean((1, 2, 3))
            return jnp.stack([left, right], -1)

        img = jnp.zeros((1, 4, 4, 1)).at[:, :, :2].set(1.0)
        p = np.asarray(classify_tta(logits_fn, img, flip=True))
        assert p.shape == (1, 2)
        # flip symmetrizes: both classes get identical probability
        np.testing.assert_allclose(p[0, 0], p[0, 1], rtol=1e-5)
        np.testing.assert_allclose(p.sum(), 1.0, rtol=1e-5)

    def test_no_flip_is_plain_softmax(self):
        logits_fn = lambda x: jnp.asarray([[2.0, 0.0]])
        out = classify_tta(logits_fn, jnp.zeros((1, 2, 2, 1)), flip=False)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(jax.nn.softmax(
                jnp.asarray([[2.0, 0.0]]))), rtol=1e-5)


class TestYoloxTTA:
    def _model(self):
        from deeplearning_tpu.core.registry import MODELS
        model = MODELS.build("yolox_nano", num_classes=3,
                             dtype=jnp.float32)
        variables = model.init(jax.random.key(0),
                               jnp.zeros((1, 64, 64, 3)), train=False)
        return model, variables

    def test_identity_tta_matches_plain_postprocess(self):
        from deeplearning_tpu.models.detection.yolox import (
            decode_outputs, yolox_grid, yolox_postprocess)
        model, variables = self._model()
        img = jnp.asarray(np.random.default_rng(0).normal(
            size=(1, 64, 64, 3)), jnp.float32)
        raw_fn = lambda x: model.apply(variables, x, train=False)
        tta = yolox_tta(raw_fn, img, scales=(1.0,), flips=(False,),
                        max_det=10)
        centers, strides = yolox_grid((64, 64))
        plain = yolox_postprocess(raw_fn(img), jnp.asarray(centers),
                                  jnp.asarray(strides), max_det=10)
        np.testing.assert_allclose(np.asarray(tta["boxes"]),
                                   np.asarray(plain["boxes"]), rtol=1e-5,
                                   atol=1e-4)
        np.testing.assert_array_equal(np.asarray(tta["valid"]),
                                      np.asarray(plain["valid"]))

    def test_multiscale_flip_tta_shapes_and_jit(self):
        model, variables = self._model()
        img = jnp.asarray(np.random.default_rng(1).normal(
            size=(2, 64, 64, 3)), jnp.float32)
        raw_fn = lambda x: model.apply(variables, x, train=False)
        out = jax.jit(lambda im: yolox_tta(
            raw_fn, im, scales=(1.0, 0.83, 0.67),
            flips=(False, True, False), max_det=20))(img)
        assert out["boxes"].shape == (2, 20, 4)
        assert out["scores"].shape == (2, 20)
        # de-scaled boxes stay in the base 64x64 frame
        kept = np.asarray(out["boxes"])[np.asarray(out["valid"])]
        if kept.size:
            assert kept.min() > -64 and kept.max() < 128


class TestEvolve:
    def test_mutate_respects_bounds_and_changes(self):
        rng = np.random.default_rng(0)
        hyp = {"lr": 0.01, "mosaic": 1.0, "fliplr": 0.5, "extra": 7.0}
        out = mutate(hyp, DETECTION_META, rng)
        assert out != hyp
        assert out["extra"] == 7.0          # not in meta: untouched
        assert out["fliplr"] == 0.5         # gain 0 gene: never mutates
        for k in ("lr", "mosaic"):
            lo, hi = DETECTION_META[k][1], DETECTION_META[k][2]
            assert lo <= out[k] <= hi

    def test_mutate_no_mutable_genes_returns_unchanged(self):
        # all-gain-0 (or meta-disjoint) hyps must not hang the retry loop
        rng = np.random.default_rng(0)
        assert mutate({"fliplr": 0.5}, DETECTION_META, rng) \
            == {"fliplr": 0.5}
        assert mutate({"unknown": 1.0}, DETECTION_META, rng) \
            == {"unknown": 1.0}

    def test_evolution_improves_toy_fitness(self, tmp_path):
        # fitness peaks at lr=0.03, mosaic=0.5 — evolution should climb
        target = {"lr": 0.03, "mosaic": 0.5}

        def eval_fn(hyp):
            return -sum((hyp[k] - target[k]) ** 2 for k in target)

        path = str(tmp_path / "evolve.jsonl")
        hyp0 = {"lr": 0.001, "mosaic": 1.0}
        best = evolve(eval_fn, hyp0, DETECTION_META, generations=40,
                      records_path=path, seed=0)
        assert eval_fn(best) > eval_fn(hyp0) + 1e-4
        assert best == best_hyp(path)
        # resumable: one more generation appends, doesn't reset
        best2 = evolve(eval_fn, hyp0, DETECTION_META, generations=1,
                       records_path=path, seed=1)
        assert eval_fn(best2) >= eval_fn(best)

    def test_det_fitness_weights(self):
        assert det_fitness({"ap": 1.0, "ap50": 0.0}) == pytest.approx(0.9)
        assert det_fitness({"ap": 0.0, "ap50": 1.0}) == pytest.approx(0.1)
