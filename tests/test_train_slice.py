"""Phase-1 end-to-end slice: the mnist-equivalent smoke test.

Mirrors BASELINE.md "mnist LeNet train.py runs end-to-end, single device":
synthetic separable data, MnistCNN, SGD+cosine, jitted train step with and
without grad accumulation, eval step, loss decreases, checkpoint roundtrip.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning_tpu.core import rng as rng_mod
from deeplearning_tpu.core.registry import MODELS
from deeplearning_tpu.data import ArraySource, DataLoader
from deeplearning_tpu.parallel import data_parallel_mesh
from deeplearning_tpu.train import (TrainState, make_eval_step,
                                    make_train_step, shard_state)
from deeplearning_tpu.train.classification import make_loss_fn, make_metric_fn
from deeplearning_tpu.train.optim import build_optimizer
from deeplearning_tpu.train.schedules import build_schedule


def synthetic_mnist(n=256, seed=0):
    """Linearly-separable 28x28 'digits': class k lights up column block k."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, n)
    images = rng.normal(0, 0.1, (n, 28, 28, 1)).astype(np.float32)
    for i, lab in enumerate(labels):
        images[i, :, lab * 2:lab * 2 + 2, 0] += 2.0
    return images, labels.astype(np.int32)


def make_state(model_name="mnist_cnn", lr=0.1, total_steps=100, **opt_kw):
    model = MODELS.build(model_name, num_classes=10)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 28, 28, 1)), train=False)["params"]
    sched = build_schedule("warmup_cosine", base_lr=lr,
                           total_steps=total_steps, warmup_steps=5)
    tx = build_optimizer("sgd", sched, momentum=0.9, params=params)
    return TrainState.create(apply_fn=model.apply, params=params, tx=tx)


class TestEndToEndSlice:
    def test_loss_decreases_and_accuracy_rises(self):
        images, labels = synthetic_mnist()
        state = make_state(lr=0.05, total_steps=32)
        step = make_train_step(make_loss_fn())
        key = rng_mod.root_key(0)
        loader = DataLoader(ArraySource(image=images, label=labels),
                            global_batch=64, seed=0)
        first_loss = None
        for epoch in range(8):
            loader.set_epoch(epoch)
            for batch in loader:
                batch = {k: jnp.asarray(v) for k, v in batch.items()}
                state, metrics = step(state, batch, key)
                if first_loss is None:
                    first_loss = float(metrics["loss"])
        assert float(metrics["loss"]) < first_loss * 0.5
        assert float(metrics["accuracy"]) > 0.8
        assert int(state.step) == 8 * len(loader)

    def test_grad_accumulation_matches_full_batch(self):
        images, labels = synthetic_mnist(64)
        batch = {"image": jnp.asarray(images), "label": jnp.asarray(labels)}
        key = rng_mod.root_key(1)
        # dropout must be off for exact equality -> use fcn with no dropout
        # by running in a single step and comparing grads via param delta.
        s1 = make_state("mnist_fcn", lr=0.5)
        s2 = make_state("mnist_fcn", lr=0.5)
        # identical init?
        chex_equal = jax.tree.map(lambda a, b: np.allclose(a, b),
                                  s1.params, s2.params)
        assert all(jax.tree.leaves(chex_equal))

        step1 = make_train_step(make_loss_fn(), accum_steps=1, donate=False)
        step4 = make_train_step(make_loss_fn(), accum_steps=4, donate=False)
        out1, m1 = step1(s1, batch, key)
        out4, m4 = step4(s2, batch, key)
        # dropout streams differ between the two paths; mnist_fcn has
        # dropout, so compare loss only loosely and param delta direction.
        assert float(m4["loss"]) == pytest.approx(float(m1["loss"]), rel=0.2)

    def test_eval_step_counts(self):
        images, labels = synthetic_mnist(64)
        state = make_state()
        eval_step = make_eval_step(make_metric_fn())
        out = eval_step(state, {"image": jnp.asarray(images),
                                "label": jnp.asarray(labels)})
        assert int(out["count"]) == 64
        assert 0 <= int(out["top1"]) <= int(out["top5"]) <= 64

    def test_sharded_training_on_mesh(self):
        """Phase-2 DDP successor: same slice, batch sharded over 8 devices."""
        mesh = data_parallel_mesh()
        images, labels = synthetic_mnist(128)
        state = shard_state(make_state(), mesh)
        step = make_train_step(make_loss_fn(), mesh=mesh)
        key = rng_mod.root_key(0)
        loader = DataLoader(ArraySource(image=images, label=labels),
                            global_batch=64, mesh=mesh, seed=0)
        for epoch in range(2):
            loader.set_epoch(epoch)
            for batch in loader:
                state, metrics = step(state, batch, key)
        assert np.isfinite(float(metrics["loss"]))
        # params stay replicated across the mesh
        leaf = jax.tree.leaves(state.params)[0]
        assert leaf.sharding.is_fully_replicated

    def test_ema_tracks_params(self):
        images, labels = synthetic_mnist(64)
        model = MODELS.build("mnist_fcn", num_classes=10)
        params = model.init(jax.random.key(0),
                            jnp.zeros((1, 28, 28, 1)))["params"]
        tx = build_optimizer("sgd", build_schedule("constant", base_lr=0.5),
                             params=params)
        state = TrainState.create(apply_fn=model.apply, params=params, tx=tx,
                                  use_ema=True, ema_decay=0.5)
        step = make_train_step(make_loss_fn(), donate=False)
        batch = {"image": jnp.asarray(images), "label": jnp.asarray(labels)}
        new_state, _ = step(state, batch, rng_mod.root_key(0))
        # EMA moved toward new params but not equal to them
        p0 = jax.tree.leaves(state.params)[0]
        p1 = jax.tree.leaves(new_state.params)[0]
        e1 = jax.tree.leaves(new_state.ema_params)[0]
        assert not np.allclose(p0, p1)
        assert not np.allclose(e1, p1)
