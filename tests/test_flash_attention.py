"""Pallas flash attention vs lax reference — the TPU-era analog of the Swin
CUDA kernel unit test (swin kernels/window_process/unit_test.py): fused
kernel forward AND backward compared numerically against the naive path.
Runs in Pallas interpret mode on CPU."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning_tpu.ops.pallas import flash_attention as fa


def reference_attention(q, k, v, causal=False, kv_len=None):
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q * scale, k).astype(jnp.float32)
    n = q.shape[2]
    if kv_len is not None:
        mask = jnp.arange(n)[None, :] < kv_len
        s = jnp.where(mask[None, None], s, -jnp.inf)
    if causal:
        cm = jnp.tril(jnp.ones((n, n), bool))
        s = jnp.where(cm[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(q.dtype), v)


@pytest.fixture(autouse=True)
def _interpret(monkeypatch):
    """Force pallas interpret mode on CPU."""
    import jax.experimental.pallas as pl
    orig = pl.pallas_call
    monkeypatch.setattr(pl, "pallas_call",
                        functools.partial(orig, interpret=True))
    yield


def rand_qkv(b=2, h=3, n=197, d=64, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(0, 1, (b, h, n, d)), dtype)
    return mk(), mk(), mk()


class TestFlashForward:
    def test_matches_reference_f32(self):
        q, k, v = rand_qkv(n=197)
        out = fa.flash_attention(q, k, v)
        ref = reference_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_matches_reference_small_n(self):
        q, k, v = rand_qkv(n=49, d=32)   # swin window size
        out = fa.flash_attention(q, k, v)
        ref = reference_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_causal(self):
        q, k, v = rand_qkv(n=128, d=32)
        out = fa.flash_attention(q, k, v, causal=True)
        ref = reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_bf16(self):
        q, k, v = rand_qkv(n=256, dtype=jnp.bfloat16)
        out = fa.flash_attention(q, k, v)
        ref = reference_attention(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32),
            atol=2e-2, rtol=2e-2)


class TestFlashBackward:
    def test_grads_match_reference(self):
        q, k, v = rand_qkv(b=1, h=2, n=197, d=64)

        def loss_flash(q, k, v):
            return jnp.sum(jnp.square(fa.flash_attention(q, k, v)))

        def loss_ref(q, k, v):
            return jnp.sum(jnp.square(reference_attention(q, k, v)))

        g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for gf, gr, name in zip(g_flash, g_ref, "qkv"):
            np.testing.assert_allclose(
                np.asarray(gf), np.asarray(gr), atol=5e-4, rtol=5e-4,
                err_msg=f"grad mismatch for {name}")

    def test_causal_grads(self):
        q, k, v = rand_qkv(b=1, h=1, n=128, d=32)

        def loss_flash(q, k, v):
            return jnp.sum(fa.flash_attention(q, k, v, causal=True) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

        g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for gf, gr in zip(g_flash, g_ref):
            np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                       atol=5e-4, rtol=5e-4)


class TestLayoutWrapper:
    def test_bnhd_wrapper(self):
        q, k, v = rand_qkv(n=64, d=32)
        out1 = fa.flash_attention(q, k, v)
        out2 = fa.flash_attention_bnhd(q.transpose(0, 2, 1, 3),
                                       k.transpose(0, 2, 1, 3),
                                       v.transpose(0, 2, 1, 3))
        np.testing.assert_allclose(np.asarray(out1),
                                   np.asarray(out2.transpose(0, 2, 1, 3)),
                                   atol=1e-6)


class TestHeadBatchedForward:
    def test_matches_reference(self):
        from deeplearning_tpu.ops.pallas.flash_attention import (
            flash_attention_hb)
        q, k, v = rand_qkv(b=2, h=4, n=197, d=32)
        out = flash_attention_hb(q, k, v, head_block=4)
        ref = reference_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=3e-5, rtol=3e-5)

    def test_head_block_not_dividing_heads(self):
        from deeplearning_tpu.ops.pallas.flash_attention import (
            flash_attention_hb)
        q, k, v = rand_qkv(b=1, h=3, n=64, d=32)   # 3 heads, hb falls to 1
        out = flash_attention_hb(q, k, v, head_block=4)
        ref = reference_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=3e-5, rtol=3e-5)


class TestHeadBatchedBackward:
    def test_grads_match_reference(self):
        from deeplearning_tpu.ops.pallas.flash_attention import (
            flash_attention_hb)
        q, k, v = rand_qkv(b=2, h=4, n=197, d=32)

        def loss_hb(q, k, v):
            return jnp.sum(
                flash_attention_hb(q, k, v, head_block=4) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(reference_attention(q, k, v) ** 2)

        g_hb = jax.grad(loss_hb, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for gf, gr in zip(g_hb, g_ref):
            np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                       atol=5e-4, rtol=5e-4)

    def test_causal_grads_match(self):
        from deeplearning_tpu.ops.pallas.flash_attention import (
            flash_attention_hb)
        q, k, v = rand_qkv(b=1, h=4, n=128, d=32)

        def loss_hb(q, k, v):
            return jnp.sum(flash_attention_hb(
                q, k, v, head_block=2, causal=True) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(
                reference_attention(q, k, v, causal=True) ** 2)

        g_hb = jax.grad(loss_hb, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for gf, gr in zip(g_hb, g_ref):
            np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                       atol=5e-4, rtol=5e-4)


class TestChunkGrads:
    def test_single_chunk_equals_full_gradient(self):
        # flash_chunk_grads with the GLOBAL lse/delta over one chunk that
        # IS the whole sequence must equal the full attention gradient
        from deeplearning_tpu.ops.pallas.flash_attention import (
            flash_attention_with_lse, flash_chunk_grads)
        rng = np.random.default_rng(7)
        b, h, n, d = 1, 2, 96, 16      # not a block multiple → padded
        q, k, v, do = (jnp.asarray(rng.normal(0, 1, (b, h, n, d)),
                                   jnp.float32) for _ in range(4))
        out, lse = flash_attention_with_lse(q, k, v)
        delta = jnp.sum(do * out, axis=-1)
        dq, dk, dv = flash_chunk_grads(q, k, v, do, lse, delta)

        def ref_loss(q, k, v):
            s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * (d ** -0.5)
            p = jax.nn.softmax(s, axis=-1)
            return jnp.sum(jnp.einsum("bhqk,bhkd->bhqd", p, v) * do)

        rq, rk, rv = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        np.testing.assert_allclose(np.asarray(dq), np.asarray(rq),
                                   atol=1e-4, rtol=1e-4)
        np.testing.assert_allclose(np.asarray(dk), np.asarray(rk),
                                   atol=1e-4, rtol=1e-4)
        np.testing.assert_allclose(np.asarray(dv), np.asarray(rv),
                                   atol=1e-4, rtol=1e-4)
