"""Sync-free hot path: DeferredMetrics staleness, device-side divergence
guard, zero-sync eval, retrace guard, and the persistent compile cache."""

import time
import warnings
from collections import defaultdict

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning_tpu.core.registry import MODELS
from deeplearning_tpu.data import ArraySource, DataLoader
from deeplearning_tpu.train import TrainState, make_eval_step, make_train_step
from deeplearning_tpu.train.async_metrics import DeferredMetrics
from deeplearning_tpu.train.classification import make_loss_fn, make_metric_fn
from deeplearning_tpu.train.optim import build_optimizer
from deeplearning_tpu.train.schedules import build_schedule
from deeplearning_tpu.train.trainer import Trainer
from deeplearning_tpu.utils.profiling import RetraceGuard


class TestDeferredMetrics:
    def test_staleness_and_ordering(self):
        ring = DeferredMetrics(lag=3)
        for i in range(10):
            ring.push({"loss": jnp.asarray(float(i))}, it=i)
        # entries with >= 3 newer entries behind them are ready: 0..6
        ready = ring.poll()
        assert [m["it"] for m, _ in ready] == list(range(7))
        assert [h["loss"] for _, h in ready] == [float(i) for i in range(7)]
        assert ring.pending == 3
        assert ring.fetch_count == 1          # one sync event for 7 entries
        assert ring.fetched_entries == 7
        # nothing new became ready -> no extra sync event
        assert ring.poll() == []
        assert ring.fetch_count == 1
        rest = ring.drain()
        assert [m["it"] for m, _ in rest] == [7, 8, 9]
        assert ring.fetch_count == 2 and ring.pending == 0

    def test_zero_lag_materializes_immediately(self):
        ring = DeferredMetrics(lag=0)
        ring.push({"x": jnp.asarray(1.0)})
        ready = ring.poll()
        assert len(ready) == 1 and ready[0][1]["x"] == 1.0

    def test_meta_is_passed_through_host_side(self):
        ring = DeferredMetrics(lag=0)
        ring.push({"x": jnp.asarray(2.0)}, epoch=3, data_time=0.5)
        (meta, host), = ring.poll()
        assert meta["epoch"] == 3 and meta["data_time"] == 0.5


class TestWindowedMetrics:
    def test_window_means_and_meta(self):
        ring = DeferredMetrics(lag=0, window=4)
        for i in range(8):
            ring.push({"loss": jnp.asarray(float(i))}, it=i)
        ready = ring.poll()
        assert [h["loss"] for _, h in ready] == [1.5, 5.5]   # window means
        assert [m["it"] for m, _ in ready] == [3, 7]   # last step's meta
        assert ring.fetch_count == 1 and ring.fetched_entries == 2

    def test_host_state_is_o1_per_step(self):
        """100 pushes at window=10 hold 10 closed windows + one device
        accumulator — never 100 per-step dicts."""
        ring = DeferredMetrics(lag=0, window=10)
        for i in range(105):
            ring.push({"loss": jnp.asarray(1.0)}, it=i)
        assert len(ring._buf) == 10
        assert ring._open_n == 5
        assert ring.pending == 11

    def test_bad_step_is_summed_not_averaged(self):
        ring = DeferredMetrics(lag=0, window=4)
        for i in range(4):
            ring.push({"loss": jnp.asarray(1.0),
                       "bad_step": jnp.int32(1 if i == 2 else 0)})
        (_, host), = ring.poll()
        assert host["bad_step"] == 1.0        # any bad step survives
        assert host["loss"] == 1.0

    def test_nan_poisons_window_mean(self):
        ring = DeferredMetrics(lag=0, window=3)
        for v in (1.0, float("nan"), 2.0):
            ring.push({"loss": jnp.asarray(v)})
        (_, host), = ring.poll()
        assert not np.isfinite(host["loss"])

    def test_lag_counts_pushes_since_close(self):
        ring = DeferredMetrics(lag=3, window=2)
        for i in range(4):                    # windows close at push 2, 4
            ring.push({"x": jnp.asarray(float(i))})
        assert ring.poll() == []              # newest close only 0 old
        for i in range(2):                    # pushes 5, 6
            ring.push({"x": jnp.asarray(float(i))})
        ready = ring.poll()                   # first window now 4 old
        assert len(ready) == 1 and ready[0][1]["x"] == 0.5
        assert ring.pending == 2              # windows closed at 4 and 6

    def test_drain_closes_partial_window(self):
        ring = DeferredMetrics(lag=5, window=4)
        for i in range(3):
            ring.push({"x": jnp.asarray(float(i))})
        entries = ring.drain()
        assert len(entries) == 1 and entries[0][1]["x"] == 1.0
        assert ring.pending == 0

    def test_trainer_auto_window_and_divergence(self):
        """log_every > 100 turns the windowed reduction on; the NaN
        abort still fires through the window-mean path."""
        trainer = make_trainer(epochs=1, log_every=150, n=5 * 16, batch=16)
        assert trainer.metrics_window == 150
        trainer.train()
        # 5 steps fold into ONE partial window drained at epoch end
        assert trainer.deferred.fetched_entries == 1
        assert trainer.deferred.fetch_count <= 1

        base = make_train_step(make_loss_fn(), donate=False)

        def nan_step(state, batch, rng):
            state, metrics = base(state, batch, rng)
            bad = jnp.float32(float("nan"))
            return state, {**metrics, "loss": bad, "bad_step": jnp.int32(1)}

        trainer = make_trainer(nan_step, epochs=1, log_every=150,
                               n=5 * 16, batch=16)
        with pytest.raises(FloatingPointError, match="non-finite"):
            trainer.train()


def synthetic_cls(n=96, seed=0):
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 4, n).astype(np.int32)
    images = rng.normal(0, 0.1, (n, 16, 16, 1)).astype(np.float32)
    for i, l in enumerate(labels):
        images[i, :, l * 4:(l + 1) * 4, 0] += 2.0
    return images, labels


def make_trainer(train_step=None, *, epochs=1, log_every=100, n=96,
                 metrics_lag=None, batch=32, **trainer_kw):
    images, labels = synthetic_cls(n)
    model = MODELS.build("mnist_fcn", num_classes=4, dtype=jnp.float32)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 16, 16, 1)))["params"]
    tx = build_optimizer(
        "sgd", build_schedule("constant", base_lr=0.1), params=params)
    state = TrainState.create(apply_fn=model.apply, params=params, tx=tx)
    loader = DataLoader(ArraySource(image=images, label=labels),
                        global_batch=batch, seed=0)
    eval_loader = DataLoader(ArraySource(image=images, label=labels),
                             global_batch=batch, shuffle=False)
    return Trainer(
        state=state,
        train_step=train_step or make_train_step(make_loss_fn(),
                                                 donate=False),
        train_loader=loader,
        eval_step=make_eval_step(make_metric_fn(ks=(1,))),
        eval_loader=eval_loader,
        epochs=epochs, log_every=log_every, metrics_lag=metrics_lag,
        **trainer_kw)


class TestZeroSyncHotLoop:
    def test_smoke_five_steps_at_most_one_sync(self):
        """5 Trainer steps with the async pipeline: the mid-epoch polls
        find nothing ready (lag = log_every > 5) and the epoch-end drain
        is the single bulk fetch -> exactly one metrics sync event."""
        trainer = make_trainer(epochs=1, log_every=100, n=5 * 16, batch=16)
        assert len(trainer.train_loader) == 5
        trainer.train()
        assert trainer.deferred.fetched_entries == 5   # every step checked
        assert trainer.deferred.fetch_count <= 1
        assert trainer.deferred.pending == 0

    def test_wrapped_loader_keeps_sync_bound(self):
        """Same ≤1-sync bound with the hot loop fed through a
        DevicePrefetcher: the overlapped feed must not reintroduce any
        D2H fetch between log points."""
        from deeplearning_tpu.data import DevicePrefetcher
        trainer = make_trainer(epochs=1, log_every=100, n=5 * 16, batch=16,
                               prefetch=2)
        assert isinstance(trainer.train_loader, DevicePrefetcher)
        assert len(trainer.train_loader) == 5
        trainer.train()
        assert trainer.deferred.fetched_entries == 5
        assert trainer.deferred.fetch_count <= 1
        assert trainer.deferred.pending == 0
        # feed telemetry flowed through the epoch-end reset
        assert trainer.train_loader.batches_fed == 0   # reset after epoch

    def test_device_side_guard_aborts_within_lag_window(self):
        """Injected NaN loss at step N aborts within metrics_lag +
        log_every steps (K*log_every with K=2 at the default lag), via
        the jitted bad_step flag on the stale snapshot."""
        base = make_train_step(make_loss_fn(), donate=False)
        calls = {"n": 0}

        def nan_after_3(state, batch, rng):
            calls["n"] += 1
            state, metrics = base(state, batch, rng)
            if calls["n"] >= 3:
                bad = jnp.float32(float("nan"))
                metrics = {**metrics, "loss": bad,
                           "bad_step": jnp.int32(1)}
            return state, metrics

        log_every, lag = 2, 2
        trainer = make_trainer(nan_after_3, epochs=4, log_every=log_every,
                               metrics_lag=lag, n=320, batch=32)
        with pytest.raises(FloatingPointError, match="non-finite"):
            trainer.train()
        # abort within K*log_every of the bad step (K=2 here)
        assert calls["n"] - 3 <= lag + log_every

    def test_bad_step_flag_from_jitted_step(self):
        """make_train_step computes isfinite(loss) on device."""
        images, labels = synthetic_cls(8)
        model = MODELS.build("mnist_fcn", num_classes=4, dtype=jnp.float32)
        params = model.init(jax.random.key(0),
                            jnp.zeros((1, 16, 16, 1)))["params"]
        tx = build_optimizer(
            "sgd", build_schedule("constant", base_lr=0.1), params=params)
        state = TrainState.create(apply_fn=model.apply, params=params,
                                  tx=tx)
        step = make_train_step(make_loss_fn(), donate=False)
        batch = {"image": jnp.asarray(images), "label": jnp.asarray(labels)}
        _, metrics = step(state, batch, jax.random.key(0))
        assert int(metrics["bad_step"]) == 0
        bad_batch = {"image": jnp.full_like(batch["image"], jnp.nan),
                     "label": batch["label"]}
        _, metrics = step(state, bad_batch, jax.random.key(0))
        assert int(metrics["bad_step"]) == 1


class TestZeroSyncEval:
    def test_single_materialization_and_bitwise_totals(self):
        trainer = make_trainer(epochs=1)
        trainer.train()
        fetches_before = trainer.eval_fetches

        # reference: the old per-batch float() accumulation
        ref = defaultdict(float)
        for b in trainer.eval_loader:
            counts = trainer.eval_step(trainer.state, b)
            for k, v in counts.items():
                ref[k] += float(v)
        if "count" in ref and ref["count"] > 0:
            ref = {k: v / ref["count"] for k, v in ref.items()
                   if k != "count"}

        results = trainer.evaluate()
        assert trainer.eval_fetches == fetches_before + 1
        assert set(results) == set(ref)
        for k in ref:       # bitwise: same values, same summation order
            assert results[k] == ref[k], k


class TestThroughputStats:
    def test_percentiles_and_data_wait(self):
        trainer = make_trainer(epochs=1)
        ips = trainer.throughput(n_iters=3)
        assert ips > 0
        stats = trainer.throughput_stats
        for key in ("step_ms_mean", "step_ms_p50", "step_ms_p90",
                    "data_wait_frac", "images_per_sec", "batch"):
            assert key in stats, key
        assert stats["step_ms_p90"] >= stats["step_ms_p50"] > 0
        assert 0.0 <= stats["data_wait_frac"] <= 1.0


class TestLoaderDataWait:
    def test_parallel_loader_reports_wait(self):
        from deeplearning_tpu.data.loader import MapSource

        def slow_fetch(i):
            time.sleep(0.002)
            return {"x": np.full((3,), i, np.float32)}

        src = MapSource(24, slow_fetch)
        loader = DataLoader(src, 8, shuffle=False, num_workers=2,
                            lookahead=1)
        waits = []
        for _ in loader:
            assert loader.last_data_wait is not None
            waits.append(loader.last_data_wait)
        assert len(waits) == 3
        assert loader.data_wait_total == pytest.approx(sum(waits))
        # cold queue + slow decode: starvation must actually register
        assert max(waits) > 0

    def test_serial_loader_has_no_estimate(self):
        images, labels = synthetic_cls(32)
        loader = DataLoader(ArraySource(image=images, label=labels),
                            global_batch=16)
        next(iter(loader))
        assert loader.last_data_wait is None


class TestRetraceGuard:
    def test_warns_on_shape_churn(self):
        guard = RetraceGuard(jax.jit(lambda x: x * 2), name="churn_step")
        with warnings.catch_warnings():
            warnings.simplefilter("error")    # first call must NOT warn
            guard(jnp.ones((4, 4)))
        with pytest.warns(RuntimeWarning, match="retrace"):
            guard(jnp.ones((5, 4)))           # new shape -> warn
        assert guard.retraces == 1 and guard.n_signatures == 2
        with warnings.catch_warnings():
            warnings.simplefilter("error")    # known shape stays quiet
            guard(jnp.ones((4, 4)))

    def test_dtype_flip_warns_and_scalars_hash_by_type(self):
        guard = RetraceGuard(lambda x, n: x, name="s")
        guard(jnp.ones((2,), jnp.float32), 1)
        with warnings.catch_warnings():
            warnings.simplefilter("error")    # int value change: no warn
            guard(jnp.ones((2,), jnp.float32), 7)
        with pytest.warns(RuntimeWarning):
            guard(jnp.ones((2,), jnp.int32), 1)


class TestCompileCache:
    def test_enable_points_jax_at_dir(self, tmp_path, monkeypatch):
        import deeplearning_tpu.core.compile_cache as cc
        monkeypatch.setattr(cc, "_enabled_dir", None)
        target = str(tmp_path / "cache")
        assert cc.enable_compile_cache(target) == target
        assert jax.config.jax_compilation_cache_dir == target
        assert cc.active_cache_dir() == target
        # idempotent
        assert cc.enable_compile_cache(target) == target

    def test_env_disable(self, monkeypatch):
        import deeplearning_tpu.core.compile_cache as cc
        monkeypatch.setenv("DLTPU_COMPILE_CACHE", "off")
        monkeypatch.setattr(cc, "_enabled_dir", None)
        assert cc.enable_compile_cache() is None


class TestStrictHotLoop:
    """Runtime proof of the sync-free claim (ISSUE 8): the counter-based
    tests above show ≤1 fetch per window; these run the same 5-step loop
    with ``analysis.strict``'s transfer-guard armed, so ANY stray D2H
    between log points would raise at the offending line."""

    def test_five_steps_under_dltpu_strict(self, monkeypatch):
        """Acceptance: 5-step CPU smoke under DLTPU_STRICT=1 passes with
        zero disallowed transfers between log points — every step region
        ran inside a guard section and the one designed sync (the lagged
        epoch-end drain) stayed outside them."""
        monkeypatch.setenv("DLTPU_STRICT", "1")
        trainer = make_trainer(epochs=1, log_every=100, n=5 * 16, batch=16)
        assert trainer.strict_modes == frozenset({"transfers"})
        trainer.train()
        assert trainer.strict_sections == 5   # guard wrapped every step
        assert trainer.deferred.fetched_entries == 5
        assert trainer.deferred.fetch_count <= 1

    def test_arg_overrides_env(self, monkeypatch):
        monkeypatch.setenv("DLTPU_STRICT", "1")
        trainer = make_trainer(epochs=1, log_every=100, n=16, batch=16,
                               strict=False)
        assert trainer.strict_modes == frozenset()
        trainer.train()
        assert trainer.strict_sections == 0

    def test_stray_sync_raises_when_enforced(self):
        """Negative case: a callback that materializes the in-flight
        metrics inside the guard region must raise. Only runnable where
        the backend enforces the d2h guard (CPU's zero-copy D2H is
        exempt from it, so this is a TPU/GPU-only teeth check)."""
        from deeplearning_tpu.analysis import strict
        from deeplearning_tpu.train.trainer import Callbacks
        if not strict.guard_enforced("device_to_host"):
            pytest.skip("backend does not enforce the d2h transfer "
                        "guard (CPU zero-copy)")
        cb = Callbacks()
        cb.register("after_iter",
                    lambda tr, metrics=None: float(metrics["loss"]))
        trainer = make_trainer(epochs=1, log_every=100, n=16, batch=16,
                               strict="transfers", callbacks=cb)
        with pytest.raises(Exception):
            trainer.train()
