"""Config system: dataclass tree + YAML + dotted CLI overrides
(core/config.py — the cfg/flag-system surface, SURVEY §5)."""

import dataclasses
from typing import Optional, Tuple

import pytest

from deeplearning_tpu.core.config import config_cli, merge_dict


@dataclasses.dataclass(frozen=True)
class Inner:
    lr: float = 0.1
    steps: int = 10
    name: str = "sgd"
    sizes: Tuple[int, ...] = (1, 2)
    npz: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class Cfg:
    inner: Inner = dataclasses.field(default_factory=Inner)
    flag: bool = False


def test_cli_scientific_notation_becomes_float():
    # regression: yaml reads "1e-4" as a STRING (needs "1.0e-4" for
    # float), and `from __future__ import annotations` makes the field
    # type a string too, so coercion must resolve real type hints —
    # otherwise the string reaches optax and `'1e-4' * param` raises.
    cfg = config_cli(Cfg(), ["inner.lr=1e-4"])
    assert isinstance(cfg.inner.lr, float) and cfg.inner.lr == 1e-4


def test_cli_int_bool_tuple_coercion():
    cfg = config_cli(Cfg(), ["inner.steps=5", "flag=true",
                             "inner.sizes=[3,4,5]"])
    assert cfg.inner.steps == 5 and cfg.flag is True
    assert cfg.inner.sizes == (3, 4, 5)


def test_merge_dict_strict_unknown_key():
    with pytest.raises(KeyError):
        merge_dict(Cfg(), {"inner": {"nope": 1}})
    out = merge_dict(Cfg(), {"inner": {"nope": 1}}, strict=False)
    assert out == Cfg()


def test_yaml_file_merge(tmp_path):
    p = tmp_path / "c.yaml"
    p.write_text("inner:\n  lr: 0.5\n  name: adamw\n")
    cfg = config_cli(Cfg(), ["--cfg", str(p), "inner.steps", "7"])
    assert cfg.inner.lr == 0.5
    assert cfg.inner.name == "adamw"
    assert cfg.inner.steps == 7


def test_pop_flag_basic_and_separator():
    from deeplearning_tpu.core.config import pop_flag

    argv = ["--task", "cls", "lr", "3e-4"]
    assert pop_flag(argv, "--task") == "cls"
    assert argv == ["lr", "3e-4"]

    argv = ["--exp=yolox_s", "x"]
    assert pop_flag(argv, "--exp") == "yolox_s"
    assert argv == ["x"]

    # tokens after a literal `--` are values, never selector flags
    argv = ["--name", "--", "--task", "literal"]
    assert pop_flag(argv, "--task") is None
    assert argv == ["--name", "--", "--task", "literal"]
